"""gang_drain: the whole queue as one device program (models/gang.py).

The reference's sequential loop gives later pods visibility of earlier
placements for free; the drain must reproduce that across batch boundaries —
capacity (requested carries) AND relational state (committed pods become
valid epods for later batches' spread/affinity/anti-affinity terms).
"""

import numpy as np

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.gang import gang_drain, gang_schedule
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _encode(nodes, pods_all, batch):
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods_all)
    batches = [pods_all[i:i + batch] for i in range(0, len(pods_all), batch)]
    pbs = [enc.encode_pods(b, meta) for b in batches]
    return ct, pbs, batches, meta


def _zone_nodes(n, per_zone=3, cpu="4"):
    return [make_node(f"n{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "20"})
            .label("kubernetes.io/hostname", f"n{i}")
            .label("topology.kubernetes.io/zone", f"z{i // per_zone}")
            .obj() for i in range(n)]


def test_single_batch_matches_gang_schedule():
    nodes = _zone_nodes(8)
    pods = [make_pod(f"p{i}").req({"cpu": "500m"}).obj() for i in range(6)]
    ct, pbs, batches, meta = _encode(nodes, pods, batch=8)
    want, _ = gang_schedule(ct, pbs[0], topo_keys=meta.topo_keys)
    got, rounds, _ = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    np.testing.assert_array_equal(want, got[0])


def test_cross_batch_anti_affinity():
    """8 pods with required hostname anti-affinity in 2 batches of 4 must land
    on 8 distinct nodes — batch 2 must see batch 1's placements."""
    nodes = _zone_nodes(8)
    pods = [make_pod(f"p{i}").label("grp", "g").req({"cpu": "500m"})
            .pod_anti_affinity("kubernetes.io/hostname", {"grp": "g"}).obj()
            for i in range(8)]
    ct, pbs, batches, meta = _encode(nodes, pods, batch=4)
    a, rounds, _ = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    placed = [int(a[b][i]) for b in range(len(batches))
              for i in range(len(batches[b]))]
    assert all(x >= 0 for x in placed)
    assert len(set(placed)) == 8, f"cross-batch anti-affinity violated: {placed}"


def test_cross_batch_capacity_carry():
    """2-cpu nodes, 1-cpu pods: at most 2 per node even across batches."""
    nodes = _zone_nodes(4, cpu="2")
    pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(8)]
    ct, pbs, batches, meta = _encode(nodes, pods, batch=3)
    a, _, requested = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    placed = [int(a[b][i]) for b in range(len(batches))
              for i in range(len(batches[b]))]
    assert all(x >= 0 for x in placed)
    counts = np.bincount(placed, minlength=4)
    assert counts.max() <= 2, counts


def test_cross_batch_hard_spread():
    """Hard zone spread (maxSkew=1) over 4 zones, 2 batches of 4: every zone
    must end with exactly 2 — requires batch 2 to count batch 1's pods."""
    nodes = _zone_nodes(8, per_zone=2)
    pods = [make_pod(f"p{i}").label("app", "a").req({"cpu": "250m"})
            .spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                    {"app": "a"}).obj() for i in range(8)]
    ct, pbs, batches, meta = _encode(nodes, pods, batch=4)
    a, _, _ = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    placed = [int(a[b][i]) for b in range(len(batches))
              for i in range(len(batches[b]))]
    assert all(x >= 0 for x in placed)
    zones = [placed[i] // 2 for i in range(8)]
    counts = np.bincount(zones, minlength=4)
    assert counts.max() - counts.min() <= 1, counts


def test_drain_validity_vs_oracle():
    """Every drain placement, checked one pod at a time against the oracle
    with all other placed pods bound, must be feasible."""
    import copy
    import random
    rng = random.Random(7)
    nodes = _zone_nodes(9)
    pods = []
    for i in range(18):
        b = make_pod(f"p{i}").req({"cpu": f"{rng.choice([250, 500, 750])}m"})
        b = b.label("app", f"g{i % 3}")
        if i % 4 == 0:
            b = b.spread(2, "topology.kubernetes.io/zone", "DoNotSchedule",
                         {"app": f"g{i % 3}"})
        if i % 5 == 0:
            b = b.pod_anti_affinity("kubernetes.io/hostname",
                                    {"app": f"g{i % 3}"})
        pods.append(b.obj())
    ct, pbs, batches, meta = _encode(nodes, pods, batch=5)
    a, _, _ = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    placed = []
    flat = [(p, int(a[b][i])) for b, chunk in enumerate(batches)
            for i, p in enumerate(chunk)]
    for p, ni in flat:
        if ni >= 0:
            q = copy.deepcopy(p)
            q.spec.node_name = nodes[ni].metadata.name
            placed.append((q, ni))
    for i, (q, ni) in enumerate(placed):
        others = [x for j, (x, _) in enumerate(placed) if j != i]
        orc = OracleScheduler(nodes, others)
        unbound = copy.deepcopy(q)
        unbound.spec.node_name = ""
        mask, reasons = orc.feasible(unbound)
        assert mask[ni], (f"{q.key} invalid on node {ni}: "
                          f"{reasons.get(nodes[ni].metadata.name)}")
