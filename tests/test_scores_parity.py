"""Score parity: jitted schedule step (models/schedule_step.py) vs oracle.

Strategy: for a batch of pods evaluated against the SAME cluster state (no
intra-batch folding), total weighted scores must match the oracle to float32
tolerance and the selected host must be score-equivalent (same best score;
identical node when the seeded tie-break applies)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import Requirement
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.schedule_step import schedule_step
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

from test_filters_parity import random_node, random_pod


def run_both(nodes, pods, bound=None, seed=0):
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound or [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    res = schedule_step(ct, pb, seed=seed, topo_keys=meta.topo_keys)
    orc = OracleScheduler(nodes, bound or [], seed=seed)
    return res, orc, len(pods), len(nodes)


def assert_score_parity(nodes, pods, bound=None, seed=0):
    res, orc, P, N = run_both(nodes, pods, bound, seed)
    scores = np.asarray(res.scores)[:P, :N]
    choices = np.asarray(res.choice)[:P]
    assigned = np.asarray(res.assigned)[:P]
    for i, pod in enumerate(pods):
        mask, _ = orc.feasible(pod)
        oscores = orc.score(pod, mask)
        np.testing.assert_allclose(
            np.where(np.isfinite(scores[i]), scores[i], -1e30),
            np.where(np.isfinite(oscores), oscores, -1e30),
            rtol=1e-4, atol=1e-3, err_msg=f"pod {pod.key}")
        osel = orc.select_host(oscores)
        if osel is None:
            assert not assigned[i], f"pod {pod.key}: oracle unschedulable, tpu assigned"
        else:
            assert assigned[i], f"pod {pod.key}: tpu unschedulable, oracle chose {osel}"
            # score-equivalence: the chosen node's score equals the oracle best
            np.testing.assert_allclose(scores[i, choices[i]], oscores[osel],
                                       rtol=1e-4, atol=1e-3)


def test_least_allocated_prefers_empty_node():
    nodes = [make_node("full").capacity({"cpu": "4", "memory": "8Gi"}).obj(),
             make_node("empty").capacity({"cpu": "4", "memory": "8Gi"}).obj()]
    bound = [make_pod("b").req({"cpu": "3", "memory": "6Gi"}).node("full").obj()]
    pods = [make_pod("p").req({"cpu": "1", "memory": "1Gi"}).obj()]
    res, orc, P, N = run_both(nodes, pods, bound)
    assert np.asarray(res.choice)[0] == 1  # empty node wins LeastAllocated
    assert_score_parity(nodes, pods, bound)


def test_balanced_allocation_prefers_even_usage():
    # node0 would end up cpu-heavy; node1 balanced
    nodes = [make_node("skew").capacity({"cpu": "4", "memory": "32Gi"}).obj(),
             make_node("even").capacity({"cpu": "4", "memory": "4Gi"}).obj()]
    pods = [make_pod("p").req({"cpu": "2", "memory": "2Gi"}).obj()]
    assert_score_parity(nodes, pods)


def test_preferred_node_affinity_weights():
    nodes = [make_node("a").capacity({"cpu": "4"}).label("zone", "us-a").obj(),
             make_node("b").capacity({"cpu": "4"}).label("zone", "us-b").obj(),
             make_node("c").capacity({"cpu": "4"}).label("zone", "us-c").obj()]
    pods = [make_pod("p")
            .preferred_node_affinity(80, Requirement("zone", "In", ["us-b"]))
            .preferred_node_affinity(20, Requirement("zone", "In", ["us-c"]))
            .obj()]
    res, orc, P, N = run_both(nodes, pods)
    assert np.asarray(res.choice)[0] == 1
    assert_score_parity(nodes, pods)


def test_prefer_no_schedule_taint_scores_lower():
    nodes = [make_node("clean").capacity({"cpu": "4"}).obj(),
             make_node("soft").capacity({"cpu": "4"})
             .taint("slow", "", "PreferNoSchedule").obj()]
    pods = [make_pod("p").obj()]
    res, orc, P, N = run_both(nodes, pods)
    assert np.asarray(res.choice)[0] == 0
    assert_score_parity(nodes, pods)


def test_image_locality():
    gb = 1024 * 1024 * 1024
    nodes = [make_node("has").capacity({"cpu": "4"}).image("big:latest", gb).obj(),
             make_node("not").capacity({"cpu": "4"}).obj()]
    pods = [make_pod("p").image("big:latest").obj()]
    res, orc, P, N = run_both(nodes, pods)
    assert np.asarray(res.choice)[0] == 0
    assert_score_parity(nodes, pods)


def test_tie_break_determinism():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(5)]
    pods = [make_pod("p").obj()]
    for seed in (0, 1, 7, 12345):
        res, orc, P, N = run_both(nodes, pods, seed=seed)
        oscores = orc.score(pods[0], orc.feasible(pods[0])[0])
        orc.seed = seed
        assert np.asarray(res.choice)[0] == orc.select_host(oscores)


def test_all_infeasible():
    nodes = [make_node("tiny").capacity({"cpu": "1"}).obj()]
    pods = [make_pod("p").req({"cpu": "4"}).obj()]
    res, orc, P, N = run_both(nodes, pods)
    assert not np.asarray(res.assigned)[0]


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_score_parity(seed):
    rng = random.Random(1000 + seed)
    n_nodes, n_bound, n_pods = rng.randint(2, 10), rng.randint(0, 6), rng.randint(1, 8)
    nodes = [random_node(rng, i) for i in range(n_nodes)]
    names = [n.metadata.name for n in nodes]
    bound = []
    for i in range(n_bound):
        p = random_pod(rng, 100 + i, names)
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    pods = [random_pod(rng, i, names) for i in range(n_pods)]
    # add preferred affinity + images to exercise score paths
    for i, p in enumerate(pods):
        if rng.random() < 0.4:
            w = make_pod("tmp")
            w.pod = p
            w.preferred_node_affinity(rng.randint(1, 100),
                                      Requirement("zone", "In", [rng.choice(["us-a", "us-b"])]))
    assert_score_parity(nodes, pods, bound, seed=seed)
