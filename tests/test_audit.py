"""Continuous invariant auditor + device-parity sentinel.

Contracts pinned here:

1. DETECTION — forced state corruption (overcommit, double-bind, partial
   gangs, stale nominations, cache/ctx divergence) is caught by the named
   invariant, counted, and repro-bundled; a healthy cluster confirms
   NOTHING (anti-flap).
2. PARITY — a device program that silently returns wrong winners (the
   GSPMD-miscompile class) is refuted by the oracle cross-check and trips
   the circuit breaker with reason "parity"; a device program that RAISES
   trips as "device". After a parity trip the scheduler converges via the
   oracle fallback without losing pods.
3. HYGIENE — stale nominations are garbage-collected by the runner sweep,
   the bench refuses a summary without the invariant_violations field,
   and non-daemon thread leaks are detectable.
"""

import io
import json
import os
import threading
import time
from types import SimpleNamespace

import pytest

from kubernetes_tpu.audit.auditor import (
    InvariantAuditor,
    InvariantViolationError,
)
from kubernetes_tpu.audit.invariants import (
    AuditSnapshot,
    check_ctx_parity,
    run_invariants,
)
from kubernetes_tpu.audit.sentinel import (
    verify_drain_winners,
    verify_wave_results,
)
from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.config.types import SchedulerConfiguration, validate
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.audit


def wait_for(pred, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _nodes(n, cpu="4", pods="16"):
    return [make_node(f"n{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": pods})
            .label("kubernetes.io/hostname", f"n{i}")
            .obj() for i in range(n)]


def _auditor(store, cache=None, scheduler=None, tmp_path=None, **kw):
    return InvariantAuditor(client=DirectClient(store), cache=cache,
                            scheduler=scheduler,
                            audit_dir=str(tmp_path) if tmp_path else None,
                            **kw)


# ---- 1. invariant detection ----------------------------------------------

def test_overcommit_detected_and_bundled(tmp_path):
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(make_node("n0").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj().to_dict())
    for i in range(2):
        client.pods().create(make_pod(f"p{i}").req({"cpu": "1500m"})
                             .node("n0").obj().to_dict())
    auditor = _auditor(store, tmp_path=tmp_path)
    fresh = auditor.run_once()
    assert [v.invariant for v in fresh] == ["node_overcommit"]
    assert "cpu" in fresh[0].detail
    # repro bundle on disk, replayable fields present
    bundles = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert len(bundles) == 1
    payload = json.loads((tmp_path / bundles[0]).read_text())
    assert payload["invariant"] == "node_overcommit"
    assert "chaosSeed" in payload and "podBatch" in payload
    assert payload["objects"][0]["node"] == "n0"
    # same corruption is not re-counted every sweep
    assert auditor.run_once() == []
    assert auditor.total_violations == 1
    assert auditor.status()["byInvariant"] == {"node_overcommit": 1}


def test_overcommit_counts_assumed_pods(tmp_path):
    """A wrong ASSUME overbooks a node before any binding exists in the
    API — the auditor must see scheduler-side optimism too."""
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(make_node("n0").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj().to_dict())
    cache = SchedulerCache()
    cache.add_node(make_node("n0").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj())
    for i in range(2):
        pod = make_pod(f"a{i}").req({"cpu": "1500m"}).obj()
        client.pods().create(pod.to_dict())  # pending in the API
        cache.assume(pod, "n0")              # but double-booked by assume
    auditor = _auditor(store, cache=cache, tmp_path=tmp_path)
    fresh = auditor.run_once()
    assert [v.invariant for v in fresh] == ["node_overcommit"]


def test_double_bind_confirms_across_sweeps(tmp_path):
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_nodes(2)[0].to_dict())
    pod = make_pod("p0").req({"cpu": "100m"}).obj()
    client.pods().create(make_pod("p0").req({"cpu": "100m"})
                         .node("n1").obj().to_dict())
    cache = SchedulerCache()
    cache.assume(pod, "n0")  # scheduler thinks n0; apiserver says n1
    auditor = _auditor(store, cache=cache, tmp_path=tmp_path)
    assert auditor.run_once() == []  # first sighting: could be a race
    fresh = auditor.run_once()       # persisted: corruption
    assert [v.invariant for v in fresh] == ["double_bind"]
    assert "n0" in fresh[0].detail and "n1" in fresh[0].detail


def test_gang_atomicity_partial_flagged_full_clean(tmp_path):
    store = ObjectStore()
    client = DirectClient(store)
    for n in _nodes(2):
        client.nodes().create(n.to_dict())
    # gang g1: half bound (older than one sweep -> violation)
    client.pods().create(make_pod("g1a").label(
        "kubernetes-tpu.io/gang", "g1").node("n0").obj().to_dict())
    client.pods().create(make_pod("g1b").label(
        "kubernetes-tpu.io/gang", "g1").obj().to_dict())
    # gang g2: fully bound (clean)
    for m in ("a", "b"):
        client.pods().create(make_pod(f"g2{m}").label(
            "kubernetes-tpu.io/gang", "g2").node("n1").obj().to_dict())
    auditor = _auditor(store, tmp_path=tmp_path)
    assert auditor.run_once() == []
    fresh = auditor.run_once()
    assert [(v.invariant, v.fingerprint[1]) for v in fresh] \
        == [("gang_atomicity", "g1")]


def test_cache_parity_phantom_pod(tmp_path):
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_nodes(1)[0].to_dict())
    cache = SchedulerCache()
    cache.add_pod(make_pod("ghost").node("n0").obj())  # not in the API
    auditor = _auditor(store, cache=cache, tmp_path=tmp_path)
    assert auditor.run_once() == []
    assert auditor.run_once() == []
    fresh = auditor.run_once()  # confirm=3 for phantom pods
    assert [v.invariant for v in fresh] == ["cache_parity"]
    assert "ghost" in fresh[0].detail


def test_ctx_parity_unit():
    base = dict(ts=0.0, rv=None, api_pods=[], api_nodes=[],
                cache={"bound": {"default/p1": "n0"}, "assumed": {},
                       "nodes": {"n0"}, "generation": 1})
    ctx = {"profile": "default-scheduler", "tainted": False, "seq": 0,
           "fill_bound": 1, "fill_host": 1, "top": 8,
           "folded": {"default/p1": "n0", "default/p2": "n1"},
           "mesh_epoch": 0, "pending": 0}
    # p2 folded but unknown to the cache and no pending delta explains it
    snap = AuditSnapshot(**base, ctx=ctx, ctx_pending_keys=set())
    out = check_ctx_parity(snap)
    assert [v.fingerprint[1] for v in out] == ["default/p2"]
    # a pending delta for p2 exempts it (the ctx just hasn't consumed it)
    snap = AuditSnapshot(**base, ctx=ctx, ctx_pending_keys={"default/p2"})
    assert check_ctx_parity(snap) == []
    # tainted ctx is declared unaccountable: no judgment
    snap = AuditSnapshot(**base, ctx=dict(ctx, tainted=True),
                         ctx_pending_keys=set())
    assert check_ctx_parity(snap) == []
    # fold accounting gone negative (top is a downward cursor and NOT
    # comparable to the watermark — only negativity is judgeable)
    snap = AuditSnapshot(**base, ctx=dict(ctx, fill_bound=-1,
                                          folded={"default/p1": "n0"}),
                         ctx_pending_keys=set())
    assert any(v.fingerprint[1] == "fill" for v in check_ctx_parity(snap))


def test_fail_fast_raises(tmp_path):
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(make_node("n0").capacity(
        {"cpu": "1", "pods": "10"}).obj().to_dict())
    client.pods().create(make_pod("p0").req({"cpu": "2"})
                         .node("n0").obj().to_dict())
    auditor = _auditor(store, tmp_path=tmp_path, fail_fast=True)
    with pytest.raises(InvariantViolationError) as ei:
        auditor.run_once()
    assert ei.value.violations[0].invariant == "node_overcommit"
    assert auditor.failed


def test_clean_connected_runner_confirms_nothing(tmp_path):
    """Anti-flap acceptance: a healthy live runner — binds in flight,
    assumed pods, resident drain ctx — must audit clean sweep after
    sweep."""
    store = ObjectStore()
    truth = DirectClient(store)
    for n in _nodes(4, cpu="8", pods="32"):
        truth.nodes().create(n.to_dict())
    runner = SchedulerRunner(DirectClient(store), SchedulerConfiguration(
        batch_size=8, backoff_initial_s=0.02, backoff_max_s=0.1))
    runner.auditor.audit_dir = str(tmp_path)
    try:
        runner.start()
        for i in range(24):
            truth.pods().create(make_pod(f"cp{i}")
                                .req({"cpu": "200m"}).obj().to_dict())
        assert wait_for(lambda: sum(
            1 for p in truth.pods().list()
            if p["spec"].get("nodeName")) == 24)
        for _ in range(4):
            assert runner.auditor.run_once() == []
        assert runner.auditor.total_violations == 0
    finally:
        runner.stop()


# ---- 2. stale-nomination GC ----------------------------------------------

def test_stale_nomination_gc_clears_bound_and_terminal_only():
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_nodes(1)[0].to_dict())
    # bound pod with a leftover nomination (preemption churn shape)
    bound = client.pods().create(make_pod("b0").node("n0").obj().to_dict())
    bound.setdefault("status", {})["nominatedNodeName"] = "n0"
    client.pods().update_status(bound)
    # terminal pod with a leftover nomination
    term = client.pods().create(make_pod("t0").obj().to_dict())
    term.setdefault("status", {}).update(
        {"phase": "Succeeded", "nominatedNodeName": "n0"})
    client.pods().update_status(term)
    # PENDING nominee: its reservation is live and must survive the sweep
    pend = client.pods().create(make_pod("p0").obj().to_dict())
    pend.setdefault("status", {})["nominatedNodeName"] = "n0"
    client.pods().update_status(pend)

    runner = SchedulerRunner(DirectClient(store))
    try:
        assert runner.sweep_stale_nominations() == 2
        pods = {p["metadata"]["name"]: p for p in client.pods().list()}
        assert "nominatedNodeName" not in pods["b0"]["status"]
        assert "nominatedNodeName" not in pods["t0"]["status"]
        assert pods["p0"]["status"]["nominatedNodeName"] == "n0"
        assert runner.sweep_stale_nominations() == 0  # idempotent
    finally:
        runner.scheduler.close()


def test_nomination_invariant_flags_what_gc_missed(tmp_path):
    """The auditor's nomination_consistency invariant is the check that
    the GC converged; with the GC as pre-sweep hook, the sweep judges the
    post-GC state and stays clean."""
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_nodes(1)[0].to_dict())
    bound = client.pods().create(make_pod("b0").node("n0").obj().to_dict())
    bound.setdefault("status", {})["nominatedNodeName"] = "n0"
    client.pods().update_status(bound)
    # without the GC hook: flagged once confirmed
    auditor = _auditor(store, tmp_path=tmp_path)
    assert auditor.run_once() == []
    fresh = auditor.run_once()
    assert [v.invariant for v in fresh] == ["nomination_consistency"]
    # with the GC riding as pre-sweep (the runner wiring): never flagged
    bound2 = client.pods().get("b0")
    bound2.setdefault("status", {})["nominatedNodeName"] = "n0"
    client.pods().update_status(bound2)
    runner = SchedulerRunner(DirectClient(store))
    runner.auditor.audit_dir = str(tmp_path)
    try:
        for _ in range(3):
            assert runner.auditor.run_once() == []
    finally:
        runner.scheduler.close()


# ---- 3. parity sentinel ---------------------------------------------------

def _drain_sched(nodes, batch_size=4, **cfg_kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.01, backoff_max=0.05)
    cfg = SchedulerConfiguration(batch_size=batch_size,
                                 max_drain_batches=2,
                                 backoff_initial_s=0.01,
                                 backoff_max_s=0.05, **cfg_kw)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, cache, queue, log


def test_parity_sentinel_clean_drain_no_divergence(tmp_path, monkeypatch):
    monkeypatch.setenv("KTPU_AUDIT_DIR", str(tmp_path))
    sched, cache, queue, log = _drain_sched(_nodes(8),
                                            parity_sample_every=1)
    assert sched.sentinel is not None
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    try:
        for i in range(16):
            queue.add(make_pod(f"d{i}").req({"cpu": "100m"}).obj())
        bound = 0
        for _ in range(20):
            bound += sched.run_once(wait=0.01)
            if bound >= 16:
                break
        bound += sched._resolve_pending()
        assert bound == 16
        sched.sentinel.drain()
        assert sched.sentinel.samples["drain"] >= 1
        assert sched.sentinel.divergences == 0
        assert sched.breaker.mode == "single"
        assert not os.listdir(tmp_path)  # no bundles from a clean run
    finally:
        sched.close()


def test_wrong_winners_trip_parity_and_converge_via_oracle(tmp_path,
                                                           monkeypatch):
    """The acceptance gate: a miscompile simulation (drain returns every
    winner on node 0 — overcommitted, no exception raised) must be
    refuted by the sentinel, trip the breaker with reason 'parity', write
    a repro bundle, and the scheduler must keep binding pods through the
    oracle fallback."""
    from kubernetes_tpu.metrics.registry import PARITY_DIVERGENCES
    import kubernetes_tpu.models.gang as gang_mod
    monkeypatch.setenv("KTPU_AUDIT_DIR", str(tmp_path))
    sched, cache, queue, log = _drain_sched(_nodes(4),
                                            parity_sample_every=1)
    # ground-truth store mirroring the workload: the auditor judges the
    # corrupted assumes against it
    store = ObjectStore()
    truth = DirectClient(store)
    for n in _nodes(4):
        truth.nodes().create(n.to_dict())
    warm = [make_pod(f"__warm{i}").req({"cpu": "1"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    before = PARITY_DIVERGENCES.get({"site": "drain"})
    orig = gang_mod.drain_step

    def wrong_winners(ct, pb, fill, patch=None, **kw):
        import jax.numpy as jnp
        a, rounds, ct2, fill2 = orig(ct, pb, fill, patch, **kw)
        return jnp.where(a >= 0, 0, a), rounds, ct2, fill2
    monkeypatch.setattr(gang_mod, "drain_step", wrong_winners)
    try:
        # 8 x 1cpu onto 4cpu nodes: all-on-n0 is a 2x overcommit
        for i in range(8):
            pod = make_pod(f"w{i}").req({"cpu": "1"}).obj()
            truth.pods().create(pod.to_dict())
            queue.add(pod)
        bound = 0
        for _ in range(10):
            bound += sched.run_once(wait=0.01)
            bound += sched._resolve_pending()
            if bound >= 8:
                break
        sched.sentinel.drain()
        assert wait_for(lambda: sched.breaker.mode == "oracle", timeout=5)
        assert sched.breaker.last_trip_reason == "parity"
        assert sched.breaker.trip_reasons.get("parity", 0) >= 1
        assert PARITY_DIVERGENCES.get({"site": "drain"}) > before
        last = sched.sentinel.last_divergence
        assert last is not None and last["site"] == "drain"
        bundles = [f for f in os.listdir(tmp_path) if "parity" in f]
        assert bundles, "divergence must write a repro bundle"
        payload = json.loads((tmp_path / bundles[0]).read_text())
        assert payload["problems"]
        # the AUDITOR catches the same corruption by name: the wrong
        # assumes overbook n0 against the apiserver's view
        auditor = _auditor(store, cache=cache, tmp_path=tmp_path)
        caught = auditor.run_once()
        assert "node_overcommit" in [v.invariant for v in caught]
        assert any("node_overcommit" in f for f in os.listdir(tmp_path))
        # convergence: with the device still lying, the oracle floor keeps
        # binding — a fresh batch schedules to 100%
        for i in range(8):
            queue.add(make_pod(f"o{i}").req({"cpu": "1"}).obj())
        bound2 = 0
        for _ in range(30):
            bound2 += sched.run_once(wait=0.01)
            if bound2 >= 8:
                break
        assert bound2 == 8
        sched.wait_for_bindings()
        assert len(log) >= 16
    finally:
        sched.close()


def test_device_fault_trips_as_device_not_parity():
    """Attribution: a drain_step that RAISES (chaos device fault) must
    trip via the consecutive-failure path with reason 'device' — never
    'parity' (no answer was produced to refute)."""
    from kubernetes_tpu.chaos import DeviceChaos, Fault, FaultSchedule
    sched, cache, queue, log = _drain_sched(_nodes(4),
                                            parity_sample_every=1,
                                            breaker_threshold=1)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    schedule = FaultSchedule([Fault("device.drain", "runtime", 0, 1)])
    chaos = DeviceChaos(schedule).install()
    try:
        for i in range(8):
            queue.add(make_pod(f"f{i}").req({"cpu": "100m"}).obj())
        bound = 0
        for _ in range(20):
            bound += sched.run_once(wait=0.01)
            if bound >= 8:
                break
        bound += sched._resolve_pending()
        assert bound == 8
        assert sched.breaker.trips >= 1
        assert sched.breaker.last_trip_reason == "device"
        assert "parity" not in sched.breaker.trip_reasons
    finally:
        chaos.uninstall()
        sched.close()


def test_trip_now_stale_level_ignored():
    """A parity verdict attributed to a level that is no longer active
    (the breaker restored past it while the verdict was in flight) must
    not degrade the level nobody refuted."""
    from kubernetes_tpu.sched.resilience import DeviceCircuitBreaker
    from kubernetes_tpu.utils.clock import FakeClock
    clock = FakeClock(0.0)
    br = DeviceCircuitBreaker(levels=("mesh", "single", "oracle"),
                              threshold=1, cooldown_s=10.0, clock=clock)
    br.fail("mesh")
    assert br.mode == "single"
    clock.advance(11.0)
    assert br.attempt_level() == "mesh"  # half-open probe
    br.succeed("mesh")
    assert br.mode == "mesh"
    # stale verdict for the since-restored-past level: ignored
    assert br.trip_now("single", "parity") == "mesh"
    assert "parity" not in br.trip_reasons
    # active-level verdict: immediate one-step degrade, reason recorded
    assert br.trip_now("mesh", "parity") == "single"
    assert br.last_trip_reason == "parity"
    assert br.trip_reasons == {"device": 1, "parity": 1}


def test_auditor_post_sweep_hook_fires(tmp_path):
    """Every background sweep runs the post-sweep hook (the runner hooks
    publish_status here so `ktpu audit status` reads LIVE state, not the
    start-time snapshot)."""
    store = ObjectStore()
    DirectClient(store).nodes().create(_nodes(1)[0].to_dict())
    published = []
    auditor = InvariantAuditor(client=DirectClient(store),
                               audit_dir=str(tmp_path), interval_s=0.05,
                               post_sweep=lambda: published.append(1))
    auditor.start()
    try:
        assert wait_for(lambda: auditor.sweeps >= 2 and len(published) >= 2,
                        timeout=10)
    finally:
        auditor.stop()


def test_verify_drain_winners_unit():
    nodes = _nodes(2, cpu="2")
    p0 = make_pod("p0").req({"cpu": "1500m"}).obj()
    p1 = make_pod("p1").req({"cpu": "1500m"}).obj()
    # sound: one per node
    assert verify_drain_winners(nodes, [], [(p0, "n0"), (p1, "n1")],
                                []) == []
    # overcommit: both on n0
    problems = verify_drain_winners(nodes, [], [(p0, "n0"), (p1, "n0")],
                                    [])
    assert any("overcommitted" in s for s in problems)
    # bound state counts; an EXEMPT bound pod does not (the device
    # provably had not seen it)
    b = make_pod("b0").req({"cpu": "1500m"}).node("n0").obj()
    assert verify_drain_winners(nodes, [b], [(p0, "n0")], [])
    assert verify_drain_winners(nodes, [b], [(p0, "n0")], [],
                                exempt=frozenset({b.key})) == []
    # prior in-flight drains' winners count like bound state
    assert verify_drain_winners(nodes, [], [(p0, "n0")], [(p1, "n0")])


def test_verify_wave_results_unit():
    from kubernetes_tpu.sched.preemption import PreemptionResult
    nodes = _nodes(1, cpu="2")
    victim = make_pod("v0").req({"cpu": "1500m"}).priority(1) \
        .node("n0").obj()
    pre = make_pod("hi").req({"cpu": "1500m"}).priority(100).obj()
    sound = PreemptionResult(node_name="n0", victims=[victim])
    assert verify_wave_results(nodes, [victim], [pre], [sound]) == []
    # equal-priority victim is never evictable
    peer = make_pod("peer").req({"cpu": "1500m"}).priority(100) \
        .node("n0").obj()
    bad = PreemptionResult(node_name="n0", victims=[peer])
    assert any("equal/higher-priority" in s for s in
               verify_wave_results(nodes, [peer], [pre], [bad]))
    # victim not on the named node
    stray = make_pod("stray").req({"cpu": "1"}).priority(1).node("nX").obj()
    ghost = PreemptionResult(node_name="n0", victims=[stray])
    assert any("not a bound pod" in s for s in
               verify_wave_results(nodes, [victim, stray], [pre], [ghost]))
    # evictions that still leave the preemptor infeasible
    small = make_pod("small").req({"cpu": "100m"}).priority(1) \
        .node("n0").obj()
    weak = PreemptionResult(node_name="n0", victims=[small])
    assert any("still infeasible" in s for s in verify_wave_results(
        nodes, [victim, small], [pre], [weak]))


# ---- 4. surfaces: CLI, config, bench gate, thread-leak detector ----------

def test_ktpu_audit_status():
    from kubernetes_tpu.cli.ktpu import cmd_audit
    store = ObjectStore()
    runner = SchedulerRunner(DirectClient(store))
    try:
        runner.publish_status()
        out = io.StringIO()
        rc = cmd_audit(runner.client,
                       SimpleNamespace(namespace="default", output="json"),
                       out)
        assert rc == 0
        audit = json.loads(out.getvalue())
        assert audit["violations"] == 0 and "parity" in audit
        assert audit["parity"]["every"] == runner.cfg.parity_sample_every
        out = io.StringIO()
        rc = cmd_audit(runner.client,
                       SimpleNamespace(namespace="default", output=None),
                       out)
        assert rc == 0
        text = out.getvalue()
        assert "Violations:    0" in text and "Parity:" in text
    finally:
        runner.scheduler.close()


def test_audit_config_knobs():
    cfg = SchedulerConfiguration.from_dict({
        "auditIntervalSeconds": 5, "auditFailFast": True,
        "paritySampleEvery": 3})
    assert cfg.audit_interval_s == 5.0
    assert cfg.audit_fail_fast is True
    assert cfg.parity_sample_every == 3
    validate(cfg)
    from kubernetes_tpu.config.types import ValidationError
    import dataclasses
    with pytest.raises(ValidationError):
        validate(dataclasses.replace(cfg, audit_interval_s=0))
    with pytest.raises(ValidationError):
        validate(dataclasses.replace(cfg, parity_sample_every=-1))
    # paritySampleEvery: 0 disables the sentinel
    sched, *_ = _drain_sched(_nodes(1), parity_sample_every=0)
    try:
        assert sched.sentinel is None
    finally:
        sched.close()


def test_bench_summary_refuses_missing_invariant_field():
    import bench
    with pytest.raises(SystemExit):
        bench._require_invariant_field({"metric": "x"}, "test summary")
    bench._require_invariant_field({"invariant_violations": 0}, "ok")
    assert bench._sum_violations(None, {"invariant_violations": 2},
                                 {"invariant_violations": 1}, {}) == 3


def test_thread_leak_detector_helper():
    import conftest
    baseline = {t.ident for t in threading.enumerate() if not t.daemon}
    ev = threading.Event()
    t = threading.Thread(target=ev.wait, name="leaky", daemon=False)
    t.start()
    try:
        leaked = conftest._leaked_nondaemon(baseline, grace_s=0.1)
        assert any(x.name == "leaky" for x in leaked)
    finally:
        ev.set()
        t.join()
    assert conftest._leaked_nondaemon(baseline, grace_s=0.5) == []
