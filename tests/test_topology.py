"""Topology-aware slice carving (topology/).

Contracts pinned here:

1. PARITY — the device carver (topology/carve.carve_step) and the numpy
   oracle carver (sched/oracle.oracle_carve over numpy_grids) produce
   BIT-EQUAL score planes and identical assignments/evictions across
   randomized fragmented/wrap-around/rotated clusters, and the
   ParitySentinel's carve site confirms it live (0 divergences).
2. INTEGRATION — slice gangs ride the normal group path: carve pins via
   ext_mask, the gang binds one CONTIGUOUS torus box, slice preemption
   evicts the cheapest contiguous victim set, and a failed carve explains
   itself ("0/N origins can host a 2x2x4 slice: ...") through the event,
   the explanations surface (ktpu why), and FailReason.SLICE_UNAVAILABLE.
3. PERIPHERY — the slice_contiguity audit invariant, the SliceDefrag
   descheduler strategy, the DRA claims bridge (sliceShape requests,
   ResourceSlice topology attributes, allocation coordinates), and the
   ktpu status Topology line.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.encode.snapshot import TENANT_KEY_ID, SnapshotEncoder
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.dra import DraCatalog, allocation_patch
from kubernetes_tpu.sched.oracle import FailReason, OracleScheduler
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.topology import (
    GANG_LABEL,
    SLICE_SHAPE_LABEL,
    carve_device,
    coords_of_labels,
    covered_nodes,
    coverage_stats,
    grid_dims,
    is_contiguous_slice,
    numpy_grids,
    parse_shape,
    rotations,
    select_assignment,
    select_eviction,
    shape_str,
    topology_labels,
)

pytestmark = pytest.mark.topology


def _grid_node(name, x, y, z, cpu="4"):
    nb = make_node(name).capacity({"cpu": cpu, "memory": "8Gi",
                                   "pods": "16"})
    for k, v in topology_labels(x, y, z).items():
        nb = nb.label(k, v)
    return nb


def _grid_nodes(X, Y, Z, cpu="4"):
    return [_grid_node(f"n{x}{y}{z}", x, y, z, cpu=cpu).obj()
            for x in range(X) for y in range(Y) for z in range(Z)]


def _slice_gang(gang, shape, cpu="2", prio=0):
    want = shape[0] * shape[1] * shape[2]
    out = []
    for m in range(want):
        pb = (make_pod(f"{gang}-{m}").req({"cpu": cpu})
              .labels({GANG_LABEL: gang,
                       SLICE_SHAPE_LABEL: shape_str(shape)}))
        if prio:
            pb = pb.priority(prio)
        out.append(pb.obj())
    return out


def _sched(nodes, bound=(), batch_size=8):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in bound:
        cache.add_pod(p)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    cfg = SchedulerConfiguration(batch_size=batch_size)
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, cache, queue, log


def _drive(sched, queue, pods, rounds=4):
    for p in pods:
        queue.add(p)
    for _ in range(rounds):
        sched.run_once(wait=0.01)
    sched.wait_for_bindings()


class _Recorder:
    def __init__(self):
        self.events = []

    def event(self, obj, type_, reason, message):
        self.events.append((obj.key, type_, reason, message))

    def flush(self):
        pass


# ---- 1. slicing primitives -----------------------------------------------

def test_parse_shape_and_rotations():
    assert parse_shape("2x2x4") == (2, 2, 4)
    assert parse_shape(" 1X2x3 ") == (1, 2, 3)
    for bad in (None, "", "2x2", "2x2x0", "ax2x2", "2x2x2x2", "-1x2x2"):
        assert parse_shape(bad) is None
    # rotations: sorted unique axis permutations, filtered to the grid
    assert rotations((2, 2, 4), (4, 4, 4)) == ((2, 2, 4), (2, 4, 2),
                                               (4, 2, 2))
    # an extent can't exceed its axis — wrap-around would double-count
    assert rotations((2, 1, 1), (2, 1, 1)) == ((2, 1, 1),)
    assert rotations((2, 2, 2), (2, 2, 1)) == ()


def test_is_contiguous_slice_wraparound():
    dims = (4, 1, 1)
    # wrap-around box {3, 0} is contiguous on the torus
    assert is_contiguous_slice([(3, 0, 0), (0, 0, 0)], (2, 1, 1), dims)
    assert not is_contiguous_slice([(0, 0, 0), (2, 0, 0)], (2, 1, 1), dims)
    # duplicates are never a slice
    assert not is_contiguous_slice([(0, 0, 0), (0, 0, 0)], (2, 1, 1), dims)


def test_carve_wraparound_and_rotation():
    # 4x1x1 torus, cells 1 and 2 occupied: ONLY the wrap-around origin
    # x=3 hosts a 2x1x1 slice
    coords = [(0, 0, 0), (1, 0, 0), (2, 0, 0), (3, 0, 0)]
    free = [True, False, False, True]
    res = numpy_grids(coords, free, [True] * 4, [0, 1, 1, 0],
                      (4, 1, 1), (2, 1, 1))
    assert select_assignment(res) == [3, 0]
    # rotation: a 2x2x1 request fits a 1x2x2 grid only rotated
    coords = [(0, y, z) for y in range(2) for z in range(2)]
    res = numpy_grids(coords, [True] * 4, [True] * 4, [0] * 4,
                      (1, 2, 2), (2, 2, 1))
    assert res.rots == ((1, 2, 2),)
    assert sorted(select_assignment(res)) == [0, 1, 2, 3]


def test_select_eviction_prefers_cheapest_box():
    # 4x1x1: boxes {0,1} cost 5, {1,2} cost 4, {2,3} cost 1, {3,0} cost 2
    coords = [(x, 0, 0) for x in range(4)]
    free = [False, False, False, True]
    res = numpy_grids(coords, free, [True] * 4, [2, 3, 1, 0],
                      (4, 1, 1), (2, 1, 1))
    nodes, cells, cost = select_eviction(res)
    assert (nodes, cost) == ([2, 3], 1.0)
    assert cells == [(2, 0, 0), (3, 0, 0)]


def test_coverage_and_covered_nodes():
    coords = [(x, 0, 0) for x in range(4)]
    free = [True, True, False, False]
    res = numpy_grids(coords, free, [True] * 4, [0, 0, 1, 1],
                      (4, 1, 1), (2, 1, 1))
    # only origin 0 fits; its box covers nodes 0 and 1
    assert covered_nodes(res, 4) == [True, True, False, False]
    stats = coverage_stats(res)
    assert stats["origins"] == 1
    assert stats["fragmentationPct"] == 0.0  # every free cell is covered
    assert coverage_stats(None) == {"origins": 0, "fragmentationPct": None}


# ---- 2. device <-> oracle carve bit-parity --------------------------------

@pytest.mark.parametrize("seed", range(5))
def test_carve_parity_fuzz(seed):
    """Randomized fragmented/wrap-around/rotated clusters: the device
    carve's score planes, node grid, and host-side selections are
    BIT-EQUAL to the numpy oracle carver's."""
    rng = random.Random(4000 + seed)
    X, Y, Z = rng.randint(2, 4), rng.randint(1, 3), rng.randint(1, 2)
    nodes, k = [], 0
    for x in range(X):
        for y in range(Y):
            for z in range(Z):
                if rng.random() < 0.15:
                    continue  # hole in the torus
                nb = _grid_node(f"n{k}", x, y, z,
                                cpu=rng.choice(["2", "4", "8"]))
                if rng.random() < 0.1:
                    nb = nb.unschedulable()
                nodes.append(nb.obj())
                k += 1
    if rng.random() < 0.5:  # a node with no coordinates at all
        nodes.append(make_node(f"n{k}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj())
    if not any(coords_of_labels(n.metadata.labels) for n in nodes):
        pytest.skip("degenerate sample: no labeled nodes")
    names = [n.metadata.name for n in nodes]
    bound = []
    for i in range(rng.randint(0, 2 * len(nodes))):
        p = make_pod(f"b{i}").req(
            {"cpu": rng.choice(["500m", "1", "2", "3"])}).obj()
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    shape = rng.choice([(1, 1, 1), (2, 1, 1), (1, 2, 1), (2, 2, 1),
                        (1, 2, 2), (3, 1, 1)])
    gang = _slice_gang("g", shape, cpu=rng.choice(["500m", "1", "2"]))
    gang.sort(key=lambda p: p.key)

    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound, pending_pods=gang)
    pb = enc.encode_pods(gang, meta)
    member_req = np.asarray(pb.requests)[:len(gang)].max(axis=0)
    tenant = int(np.asarray(pb.pod_labels)[0, TENANT_KEY_ID])
    dims = grid_dims([c for c in (coords_of_labels(n.metadata.labels)
                                  for n in nodes) if c is not None])
    claimed = np.zeros(ct.node_valid.shape[0], bool)
    dev = carve_device(ct, member_req, tenant, claimed, dims, shape)

    orc = OracleScheduler(nodes, bound)
    ora = orc.oracle_carve(gang, shape, set())

    assert (dev is None) == (ora is None)
    if dev is None:
        return
    np.testing.assert_array_equal(dev.node_grid, ora.node_grid)
    np.testing.assert_array_equal(dev.free_grid, ora.free_grid)
    np.testing.assert_array_equal(dev.fits, ora.fits)
    np.testing.assert_array_equal(dev.cost, ora.cost)
    assert dev.rots == ora.rots and dev.dims == ora.dims
    assert select_assignment(dev) == select_assignment(ora)
    assert select_eviction(dev) == select_eviction(ora)


# ---- 3. scheduler integration --------------------------------------------

def _fragmented_cluster():
    """4x4x1 grid with 5 nodes nearly full: carving must route around
    them."""
    nodes = _grid_nodes(4, 4, 1)
    fillers = []
    for i, nn in enumerate(["n000", "n010", "n110", "n220", "n330"]):
        p = make_pod(f"filler{i}").req({"cpu": "3"}).obj()
        p.spec.node_name = nn
        fillers.append(p)
    return nodes, fillers


def test_gang_binds_contiguous_slice():
    nodes, fillers = _fragmented_cluster()
    sched, cache, queue, log = _sched(nodes, bound=fillers)
    sched.sentinel.every = 1  # judge every carve
    gang = _slice_gang("g1", (2, 2, 1))
    _drive(sched, queue, gang)
    assert len(log) == 4, log
    by_name = {n.metadata.name: n for n in nodes}
    placed = [coords_of_labels(by_name[nn].metadata.labels)
              for _p, nn in log]
    assert is_contiguous_slice(placed, (2, 2, 1), (4, 4, 1)), placed
    full = {f.spec.node_name for f in fillers}
    assert all(nn not in full for _p, nn in log)
    # the sentinel's carve site replayed the oracle carver: no divergence
    sched.sentinel.drain()
    assert sched.sentinel.samples["carve"] >= 1
    assert sched.sentinel.divergences == 0
    # status surface
    topo = sched.topology_status()
    assert topo["grid"] == "4x4x1" and topo["nodes"] == 16
    assert topo["carves"]["carved"] == 1
    assert "2x2x1" in topo["shapes"]
    from kubernetes_tpu.cli.ktpu import _topology_line
    line = _topology_line(topo)
    assert line.startswith("Topology:      4x4x1 grid (16 nodes")
    assert "carves 1 ok / 0 failed / 0 slice-preempts" in line


def test_failed_carve_emits_origin_breakdown_and_explanation():
    # every node hosts a filler: 4-3=1 CPU free, members need 2 — no
    # origin can be carved free, but evicting any box's fillers frees one
    nodes = _grid_nodes(2, 1, 1)
    fillers = []
    for i, n in enumerate(nodes):
        p = make_pod(f"filler{i}").req({"cpu": "3"}).obj()
        p.spec.node_name = n.metadata.name
        fillers.append(p)
    sched, cache, queue, log = _sched(nodes, bound=fillers)
    rec = _Recorder()
    sched.recorder = rec
    gang = _slice_gang("g1", (2, 1, 1))  # prio 0: no preemption
    _drive(sched, queue, gang, rounds=1)
    assert log == []
    want = ("0/2 origins can host a 2x1x1 slice: 0 free cell(s) on the "
            "2x1x1 torus are too fragmented; freeing the cheapest origin "
            "costs 2 eviction(s)")
    msgs = [m for _k, t, r, m in rec.events
            if (t, r) == ("Warning", "FailedScheduling")]
    assert msgs and all(m == want for m in msgs), rec.events
    # the verdict also lands in the explanations surface (ktpu why)
    sched.explainer.drain()
    exp = sched.explainer.explain_of(gang[0].key)
    assert exp is not None and exp["mode"] == "carve"
    assert exp["message"] == want
    assert exp["filters"] == {"SliceCarve": 2}
    with sched._carve_lock:
        assert sched._carve_stats["failed"] >= 1


def test_slice_preemption_evicts_contiguous_victim_set():
    nodes = _grid_nodes(2, 2, 1)
    victims = []
    for i, n in enumerate(nodes):
        p = make_pod(f"victim{i}").req({"cpu": "3"}).obj()
        p.spec.node_name = n.metadata.name
        victims.append(p)
    sched, cache, queue, log = _sched(nodes, bound=victims)
    gang = _slice_gang("hi", (2, 2, 1), prio=100)
    _drive(sched, queue, gang, rounds=4)
    # all four victims evicted (the whole box), the gang bound contiguous
    assert all(not cache.is_bound(v.key) for v in victims)
    assert len(log) == 4, log
    by_name = {n.metadata.name: n for n in nodes}
    placed = [coords_of_labels(by_name[nn].metadata.labels)
              for _p, nn in log]
    assert is_contiguous_slice(placed, (2, 2, 1), (2, 2, 1)), placed
    with sched._carve_lock:
        assert sched._carve_stats["slicePreempts"] == 1


def test_slice_fail_message_short_circuit_order():
    pod = make_pod("p").obj()
    msg = Scheduler._slice_fail_message(
        {"res": None, "dims": (4, 4, 1), "shape": (2, 2, 1),
         "nodes": [], "members": [pod]})
    assert msg == ("0/0 origins can host a 2x2x1 slice: gang has 1 "
                   "member(s), the shape needs 4")
    msg = Scheduler._slice_fail_message(
        {"res": None, "dims": None, "shape": (2, 2, 1), "nodes": [],
         "members": [pod] * 4})
    assert "no node carries kubernetes-tpu.io/topology-{x,y,z}" in msg
    msg = Scheduler._slice_fail_message(
        {"res": None, "dims": (1, 1, 1), "shape": (2, 2, 1), "nodes": [],
         "members": [pod] * 4})
    assert "no rotation of the shape fits the 1x1x1 grid" in msg


def test_slice_chunks_keep_gangs_whole():
    nodes = _grid_nodes(2, 2, 1)
    sched, _cache, _queue, _log = _sched(nodes, batch_size=4)
    g1 = [(p, 0) for p in _slice_gang("a", (2, 1, 1))]
    g2 = [(p, 0) for p in _slice_gang("b", (2, 1, 1))]
    big = [(p, 0) for p in _slice_gang("c", (2, 2, 2))]  # 8 > batch_size
    chunks = sched._slice_chunks(g1 + g2 + big)
    assert [len(c) for c in chunks] == [4, 8]
    assert {p.metadata.labels[GANG_LABEL] for p, _ in chunks[0]} == {"a",
                                                                    "b"}
    assert {p.metadata.labels[GANG_LABEL] for p, _ in chunks[1]} == {"c"}


# ---- 4. oracle path + explain vocabulary ---------------------------------

def test_oracle_schedule_all_places_slice_first():
    nodes = _grid_nodes(2, 2, 1)
    gang = sorted(_slice_gang("g", (2, 2, 1)), key=lambda p: p.key)
    plain = make_pod("plain").req({"cpu": "1"}).obj()
    orc = OracleScheduler(nodes, [])
    out = orc.schedule_all(gang + [plain])
    assert all(ni is not None for ni in out)
    placed = [coords_of_labels(nodes[ni].metadata.labels)
              for ni in out[:4]]
    assert is_contiguous_slice(placed, (2, 2, 1), (2, 2, 1)), placed
    # and matches the standalone plan
    plans = OracleScheduler(nodes, []).plan_slices(gang)
    assert plans["g"] == {p.key: nodes[ni].metadata.name
                          for p, ni in zip(gang, out[:4])}


def test_oracle_slice_unavailable_reason_through_explainer():
    """Degraded/oracle explains: nodes outside every carveable placement
    report FailReason.SLICE_UNAVAILABLE (the SliceCarve pseudo-filter),
    not a misleading per-node pass."""
    from kubernetes_tpu.models.explain import (FILTER_MESSAGES,
                                               REASON_TO_FILTER)
    assert REASON_TO_FILTER[FailReason.SLICE_UNAVAILABLE] == "SliceCarve"
    assert FILTER_MESSAGES["SliceCarve"] == FailReason.SLICE_UNAVAILABLE
    # 3x1x1 grid with the middle cell missing and x=0 full: no 2x1x1 box
    nodes = [_grid_node("a", 0, 0, 0).obj(), _grid_node("b", 2, 0, 0).obj()]
    filler = make_pod("filler").req({"cpu": "4"}).obj()
    filler.spec.node_name = "a"
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    cache.add_pod(filler)
    from kubernetes_tpu.sched.explainer import SchedulingExplainer
    rec = _Recorder()
    cfg = SchedulerConfiguration()
    ex = SchedulingExplainer(cfg, lambda: rec)
    pod = _slice_gang("g", (2, 1, 1))[0]
    assert ex.submit(cache, cfg.profiles[0], "single", [pod])
    ex.drain()
    exp = ex.explain_of(pod.key)
    assert exp is not None and exp["mode"] == "oracle"
    assert exp["filters"] == {"SliceCarve": 2}
    assert exp["message"] == (
        "0/2 nodes are available: 2 node(s) were outside every carveable "
        "slice of the requested shape.")
    ex.close()


# ---- 5. sentinel carve site ----------------------------------------------

def test_verify_carve_assignments_refutes_tampering():
    from kubernetes_tpu.audit.sentinel import verify_carve_assignments
    nodes = _grid_nodes(2, 2, 1)
    gang = sorted(_slice_gang("g", (2, 1, 1)), key=lambda p: p.key)
    plans = OracleScheduler(nodes, []).plan_slices(gang, validate=False)
    good = {"g": plans["g"]}
    assert verify_carve_assignments(nodes, [], good, gang) == []
    bad = {"g": {k: ("n110" if v != "n110" else "n000")
                 for k, v in plans["g"].items()}}
    problems = verify_carve_assignments(nodes, [], bad, gang)
    assert problems and "diverged" in problems[0]


# ---- 6. audit invariant ---------------------------------------------------

def _store_with(nodes, pods):
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.store.store import ObjectStore
    store = ObjectStore()
    client = DirectClient(store)
    for n in nodes:
        client.nodes().create(n.to_dict())
    for p in pods:
        client.pods().create(p.to_dict())
    return store


def test_slice_contiguity_invariant(tmp_path):
    from kubernetes_tpu.audit.auditor import InvariantAuditor
    from kubernetes_tpu.client.clientset import DirectClient
    nodes = _grid_nodes(4, 1, 1)

    def bound_gang(xs):
        pods = _slice_gang("g", (2, 1, 1), cpu="1")
        for p, x in zip(pods, xs):
            p.spec.node_name = f"n{x}00"
        return pods

    ok = _store_with(nodes, bound_gang([0, 1]))
    auditor = InvariantAuditor(client=DirectClient(ok),
                               audit_dir=str(tmp_path))
    assert [v for v in auditor.run_once()
            if v.invariant == "slice_contiguity"] == []

    broken = _store_with(nodes, bound_gang([0, 2]))
    auditor = InvariantAuditor(client=DirectClient(broken),
                               audit_dir=str(tmp_path))
    fresh = [v for v in auditor.run_once()
             if v.invariant == "slice_contiguity"]
    assert len(fresh) == 1 and "g" in fresh[0].detail


# ---- 7. descheduler SliceDefrag ------------------------------------------

def test_slice_defrag_names_cheapest_contiguous_box():
    from kubernetes_tpu.descheduler import slice_defrag_candidates
    nodes = _grid_nodes(2, 2, 1)
    residents = []
    for i, n in enumerate(nodes):
        p = make_pod(f"r{i}").req({"cpu": "1"}).obj()
        p.spec.node_name = n.metadata.name
        residents.append(p)
    pending = _slice_gang("g", (2, 2, 1), prio=10)
    cands = slice_defrag_candidates(nodes, residents, pending=pending)
    assert len(cands) == 1
    c = cands[0]
    assert c.strategy == "SliceDefrag" and c.name == "slicedefrag/g"
    assert sorted(v.metadata.name for v in c.victims) == [
        f"r{i}" for i in range(4)]
    assert c.exclude_targets == {n.metadata.name for n in nodes}
    # a GANG_LABEL resident poisons its box: no candidate on a 1x1 grid
    # of protected pods
    protected = [p for p in residents]
    for p in protected:
        p.metadata.labels[GANG_LABEL] = "other"
    assert slice_defrag_candidates(nodes, protected, pending=pending) == []


# ---- 8. DRA claims bridge -------------------------------------------------

def _slice_claim(name, shape="2x2x1"):
    return {"apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"devices": {"requests": [
                {"name": "r0", "deviceClassName": "tpu", "count": 1,
                 "sliceShape": shape}]}}}


def _topo_slice(name, node, x, y, z):
    return {"apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
            "metadata": {"name": name},
            "spec": {"nodeName": node,
                     "devices": [{"name": "d0", "deviceClassName": "tpu",
                                  "count": 1,
                                  "attributes": {
                                      "topology-x": {"int": x},
                                      "topology-y": {"int": y},
                                      "topology-z": {"int": z}}}]}}


def test_dra_slice_claims_bridge():
    cat = DraCatalog.from_lists(
        claims=[_slice_claim("c1")],
        slices=[_topo_slice("s1", "n0", 1, 2, 0)])
    assert DraCatalog.claim_slice_shape(_slice_claim("x")) == (2, 2, 1)
    assert DraCatalog.claim_slice_shape(
        {"spec": {"devices": {"requests": [{"count": 2}]}}}) is None
    pod = make_pod("p").req({"cpu": "100m"}).obj()
    pod.spec.resource_claims = [{"name": "dev", "resourceClaimName": "c1"}]
    assert cat.pod_slice_shape(pod) == (2, 2, 1)
    assert cat.node_topology("n0") == (1, 2, 0)
    assert cat.node_topology("n-missing") is None
    # allocation provenance: carved coordinates + shape recorded
    out = allocation_patch(_slice_claim("c1"), "n0", pod,
                           coords=(1, 2, 0), shape=(2, 2, 1))
    alloc = out["status"]["allocation"]
    assert alloc["nodeName"] == "n0"
    assert alloc["topology"] == {"coordinates": [1, 2, 0],
                                 "sliceShape": "2x2x1"}
    # no coords -> no topology key (ordinary device claims unchanged)
    out = allocation_patch(_slice_claim("c1"), "n0", pod)
    assert "topology" not in out["status"]["allocation"]


def test_dra_claim_routes_pod_into_carver():
    """A slice-shaped ResourceClaim routes the pod into the carver with
    no slice-shape label at all."""
    nodes = _grid_nodes(2, 1, 1)
    cat = DraCatalog.from_lists(claims=[_slice_claim("c1", "2x1x1")],
                                slices=[])
    orc = OracleScheduler(nodes, [], dra=cat)
    pod = make_pod("claimed").req({"cpu": "1"}).obj()
    pod.spec.resource_claims = [{"name": "dev", "resourceClaimName": "c1"}]
    assert orc._slice_shape_of(pod) == (2, 1, 1)
    sched, _cache, _queue, _log = _sched(nodes)
    sched.cache._dra = cat
    assert sched._slice_shape_of(pod) == (2, 1, 1)


# ---- 9. status line -------------------------------------------------------

def test_topology_line_renders_shapes_and_counters():
    from kubernetes_tpu.cli.ktpu import _topology_line
    line = _topology_line(
        {"grid": "4x4x4", "nodes": 64, "freeCells": 12,
         "shapes": {"2x2x4": {"origins": 3, "fragmentationPct": 25.0}},
         "carves": {"carved": 7, "failed": 1, "slicePreempts": 2}})
    assert line == ("Topology:      4x4x4 grid (64 nodes, 12 free cells) "
                    "— 2x2x4: 3 carveable, 25.0% fragmented — carves "
                    "7 ok / 1 failed / 2 slice-preempts\n")
    # no coordinates published -> scheduler reports None -> no line
    nodes = [make_node("plain").capacity({"cpu": "4", "pods": "8"}).obj()]
    sched, _c, _q, _l = _sched(nodes)
    assert sched.topology_status() is None
