"""ktpu CLI against a live apiserver: get/apply/delete/describe/scale/cordon."""

import io
import json
import time

import pytest

from kubernetes_tpu.cli.ktpu import main
from kubernetes_tpu.client.clientset import HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture()
def server():
    s = APIServer().start()
    yield s
    s.stop()


def run(server, *argv):
    out = io.StringIO()
    rc = main(["--server", server.url, *argv], out=out)
    return rc, out.getvalue()


def test_apply_get_describe_delete(server, tmp_path):
    manifest = tmp_path / "app.yaml"
    manifest.write_text("""
apiVersion: v1
kind: Pod
metadata: {name: cli-pod, namespace: default, labels: {app: cli}}
spec:
  containers:
  - name: c
    image: img:v1
    resources: {requests: {cpu: 250m}}
---
apiVersion: v1
kind: Service
metadata: {name: cli-svc, namespace: default}
spec:
  selector: {app: cli}
  ports: [{port: 80}]
""")
    rc, out = run(server, "apply", "-f", str(manifest))
    assert rc == 0 and "pod/cli-pod created" in out and "service/cli-svc created" in out

    rc, out = run(server, "get", "pods")
    assert rc == 0 and "cli-pod" in out and "NAME" in out

    rc, out = run(server, "get", "pod", "cli-pod", "-o", "json")
    assert rc == 0
    assert json.loads(out)["metadata"]["name"] == "cli-pod"

    rc, out = run(server, "get", "svc")
    assert "10.96." in out  # allocated clusterIP rendered

    rc, out = run(server, "describe", "pod", "cli-pod")
    assert "Name:         cli-pod" in out and "Image: img:v1" in out

    # re-apply with a change -> configured
    manifest.write_text(manifest.read_text().replace("img:v1", "img:v2"))
    rc, out = run(server, "apply", "-f", str(manifest))
    assert rc == 0 and "pod/cli-pod configured" in out
    rc, out = run(server, "get", "pod", "cli-pod", "-o", "json")
    assert json.loads(out)["spec"]["containers"][0]["image"] == "img:v2"

    rc, out = run(server, "delete", "pod", "cli-pod")
    assert rc == 0 and "deleted" in out
    rc, out = run(server, "get", "pods")
    assert "cli-pod" not in out


def test_scale_and_selector(server):
    client = HTTPClient(server.url)
    client.resource("deployments").create({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"replicas": 2, "selector": {"matchLabels": {"app": "web"}},
                 "template": {"metadata": {"labels": {"app": "web"}},
                              "spec": {"containers": [{"name": "c"}]}}},
    })
    rc, out = run(server, "scale", "deployment", "web", "--replicas", "5")
    assert rc == 0
    assert client.resource("deployments").get("web")["spec"]["replicas"] == 5

    client.pods().create(make_pod("a").label("app", "web").obj().to_dict())
    client.pods().create(make_pod("b").label("app", "other").obj().to_dict())
    rc, out = run(server, "get", "pods", "-l", "app=web")
    assert "a" in out.split() and "b" not in out.split()


def test_cordon_drain_uncordon(server):
    client = HTTPClient(server.url)
    client.nodes().create(make_node("n1").obj().to_dict())
    pod = make_pod("on-n1").node("n1").obj().to_dict()
    client.pods().create(pod)
    ds_pod = make_pod("daemon-on-n1").node("n1").obj().to_dict()
    ds_pod["metadata"]["ownerReferences"] = [{"kind": "DaemonSet", "name": "ds",
                                              "uid": "u-ds", "controller": True}]
    client.pods().create(ds_pod)

    rc, out = run(server, "drain", "n1")
    assert rc == 0 and "pod/on-n1 evicted" in out
    assert client.nodes().get("n1")["spec"]["unschedulable"] is True
    names = [p["metadata"]["name"] for p in client.pods().list()]
    assert names == ["daemon-on-n1"]  # daemon pod survives the drain

    rc, out = run(server, "uncordon", "n1")
    assert rc == 0
    assert not client.nodes().get("n1")["spec"].get("unschedulable")


def test_error_surface(server):
    rc, out = run(server, "get", "pod", "nope")
    assert rc == 1 and "Error from server" in out
    with pytest.raises(SystemExit):
        run(server, "get", "flurble")
