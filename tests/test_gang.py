"""Gang batcher: serial-mode parity vs oracle; rounds-mode validity + speed.

Serial mode must reproduce the oracle's ScheduleOne loop exactly (same
assignments). Rounds mode must produce a *sequentially valid* assignment
(capacity + relational constraints hold) in far fewer rounds than pods."""

import random

import numpy as np
import pytest

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.gang import gang_schedule
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

from test_filters_parity import random_node, random_pod


def encode(nodes, pods, bound=None):
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound or [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    return ct, pb, meta


def names_of(assignment, nodes, pods):
    return {p.key: (nodes[a].metadata.name if a >= 0 else None)
            for p, a in zip(pods, assignment[:len(pods)])}


def check_validity(nodes, bound, pods, assignment):
    """Final-state validity: each assigned pod, removed from the final state,
    must still find its node feasible."""
    placed = []
    for p, a in zip(pods, assignment[:len(pods)]):
        if a >= 0:
            import copy
            q = copy.deepcopy(p)
            q.spec.node_name = nodes[a].metadata.name
            placed.append((q, int(a)))
    for i, (q, a) in enumerate(placed):
        others = [x for j, (x, _) in enumerate(placed) if j != i]
        orc = OracleScheduler(nodes, (bound or []) + others)
        mask, reasons = orc.feasible(_unbound(q))
        assert mask[a], (f"{q.key} invalid on {nodes[a].metadata.name}: "
                         f"{reasons.get(nodes[a].metadata.name)}")


def _unbound(pod):
    import copy
    q = copy.deepcopy(pod)
    q.spec.node_name = ""
    return q


def test_serial_matches_oracle_basic():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj()
             for i in range(6)]
    pods = [make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj()
            for i in range(10)]
    ct, pb, meta = encode(nodes, pods)
    assignment, rounds = gang_schedule(ct, pb, topo_keys=meta.topo_keys, serial=True)
    oracle = OracleScheduler(nodes, []).schedule_all([_unbound(p) for p in pods])
    assert [int(a) for a in assignment[:len(pods)]] == [o if o is not None else -1
                                                        for o in oracle]


def test_serial_capacity_chain():
    # 3 pods want the same tiny node; serial order decides who wins
    nodes = [make_node("small").capacity({"cpu": "2"}).obj(),
             make_node("big").capacity({"cpu": "16"}).obj()]
    pods = [make_pod(f"p{i}").req({"cpu": "1500m"}).obj() for i in range(3)]
    ct, pb, meta = encode(nodes, pods)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys, serial=True)
    oracle = OracleScheduler(nodes, []).schedule_all([_unbound(p) for p in pods])
    assert [int(a) for a in assignment[:3]] == oracle


def test_rounds_capacity_exact_fill():
    nodes = [make_node(f"n{i}").capacity({"cpu": "2", "pods": "100"}).obj() for i in range(4)]
    pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(12)]
    ct, pb, meta = encode(nodes, pods)
    assignment, rounds = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a = assignment[:12]
    assert (a >= 0).sum() == 8  # 4 nodes x 2 cpu
    counts = np.bincount(a[a >= 0], minlength=4)
    assert (counts <= 2).all()
    check_validity(nodes, [], pods, assignment)


def test_rounds_anti_affinity_spreads_fast():
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "50"}).obj() for i in range(8)]
    pods = [make_pod(f"p{i}").label("app", "web")
            .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"}).obj()
            for i in range(6)]
    ct, pb, meta = encode(nodes, pods)
    assignment, rounds = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a = assignment[:6]
    assert (a >= 0).sum() == 6
    assert len(set(a.tolist())) == 6, "anti-affinity pods must land on distinct hosts"
    assert rounds <= 4, f"expected near-parallel acceptance, took {rounds} rounds"
    check_validity(nodes, [], pods, assignment)


def test_rounds_anti_affinity_exhausts_hosts():
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "50"}).obj() for i in range(3)]
    pods = [make_pod(f"p{i}").label("app", "web")
            .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"}).obj()
            for i in range(5)]
    ct, pb, meta = encode(nodes, pods)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a = assignment[:5]
    assert (a >= 0).sum() == 3  # only 3 hosts available
    assert len(set(a[a >= 0].tolist())) == 3
    check_validity(nodes, [], pods, assignment)


def test_rounds_required_affinity_colocates():
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "50"})
             .label("zone", f"z{i % 2}").obj() for i in range(4)]
    pods = [make_pod(f"p{i}").label("app", "db")
            .pod_affinity("zone", {"app": "db"}).obj() for i in range(4)]
    ct, pb, meta = encode(nodes, pods)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a = assignment[:4]
    assert (a >= 0).all()
    zones = {nodes[i].metadata.labels["zone"] for i in a}
    assert len(zones) == 1, f"affine gang split across zones {zones}"
    check_validity(nodes, [], pods, assignment)


def test_rounds_hard_spread():
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "50"})
             .label("zone", f"z{i % 3}").obj() for i in range(6)]
    pods = [make_pod(f"p{i}").label("app", "api")
            .spread(1, "zone", "DoNotSchedule", {"app": "api"}).obj()
            for i in range(9)]
    ct, pb, meta = encode(nodes, pods)
    assignment, rounds = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a = assignment[:9]
    assert (a >= 0).all()
    zone_counts = {}
    for i in a:
        z = nodes[i].metadata.labels["zone"]
        zone_counts[z] = zone_counts.get(z, 0) + 1
    assert max(zone_counts.values()) - min(zone_counts.values()) <= 1, zone_counts
    check_validity(nodes, [], pods, assignment)


def test_priority_order_respected_under_scarcity():
    nodes = [make_node("only").capacity({"cpu": "2"}).obj()]
    pods = [make_pod("low").req({"cpu": "1500m"}).priority(1).obj(),
            make_pod("high").req({"cpu": "1500m"}).priority(100).obj()]
    ct, pb, meta = encode(nodes, pods)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    assert assignment[1] == 0 and assignment[0] == -1


def test_serial_does_not_retry_failed_pods():
    """ScheduleOne semantics: a pod that fails is not retried within the batch,
    even if a later commit would have made it feasible."""
    nodes = [make_node("n0").capacity({"cpu": "8"}).label("zone", "z1").obj()]
    A = make_pod("a").pod_affinity("zone", {"app": "web"}).obj()  # needs app=web
    B = make_pod("b").label("app", "web").obj()
    ct, pb, meta = encode(nodes, [A, B])
    a, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys, serial=True)
    oracle = OracleScheduler(nodes, []).schedule_all([_unbound(A), _unbound(B)])
    assert [int(x) for x in a[:2]] == [-1, 0]
    assert oracle == [None, 0]


def test_profile_weights_and_fit_strategy_wiring():
    nodes = [make_node("fuller").capacity({"cpu": "4", "pods": "10"}).obj(),
             make_node("empty").capacity({"cpu": "4", "pods": "10"}).obj()]
    bound = [make_pod("seed").req({"cpu": "2"}).node("fuller").obj()]
    p = make_pod("p").req({"cpu": "1"}).obj()
    ct, pb, meta = encode(nodes, [p], bound)
    a_least, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    a_most, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys,
                              fit_strategy="MostAllocated")
    assert int(a_least[0]) == 1 and int(a_most[0]) == 0


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_serial_parity(seed):
    rng = random.Random(2000 + seed)
    nodes = [random_node(rng, i) for i in range(rng.randint(2, 8))]
    names = [n.metadata.name for n in nodes]
    bound = []
    for i in range(rng.randint(0, 4)):
        p = random_pod(rng, 100 + i, names)
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    pods = [random_pod(rng, i, names) for i in range(rng.randint(2, 8))]
    for p in pods:
        p.spec.priority = 0  # equal priority -> list order == rank order
        p.spec.node_name = ""
    ct, pb, meta = encode(nodes, pods, bound)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys, serial=True)
    oracle = OracleScheduler(nodes, bound).schedule_all([_unbound(p) for p in pods])
    assert [int(a) for a in assignment[:len(pods)]] == [o if o is not None else -1
                                                        for o in oracle], \
        f"seed={seed}"


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_rounds_validity(seed):
    rng = random.Random(3000 + seed)
    nodes = [random_node(rng, i) for i in range(rng.randint(3, 8))]
    pods = []
    for i in range(rng.randint(3, 10)):
        w = make_pod(f"p{i}").req({"cpu": rng.choice(["250m", "1"])}).label("app", rng.choice("ab"))
        if rng.random() < 0.4:
            w.pod_anti_affinity("kubernetes.io/hostname", {"app": rng.choice("ab")})
        if rng.random() < 0.3:
            w.spread(1, "zone", "DoNotSchedule", {"app": rng.choice("ab")})
        pods.append(w.obj())
    ct, pb, meta = encode(nodes, pods)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    check_validity(nodes, [], pods, assignment)
