"""Tier-1 static-analysis gate: the REAL analyzer over the REAL package.

Zero-NEW enforcement: every finding in ``kubernetes_tpu/`` must be covered
by the committed baseline (``analysis/ktpu_lint_baseline.json``). A new
unlocked ``+=`` on annotated shared state, a fresh silent swallow, a
donate-without-pin — any of the seven rule classes — fails tier-1 here
instead of waiting for the next review pass to re-find it by hand.

Budget: the acceptance bar is a full-package run in < 10s (it measures the
analyzer, not test-collection overhead, so a loaded CI box still clears
it with margin — the run takes ~3s on the bench box).
"""

from __future__ import annotations

import os
import time

from kubernetes_tpu.analysis import (
    DEFAULT_BASELINE,
    diff,
    load_baseline,
    run_analysis,
)

PACKAGE_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "kubernetes_tpu")


def _run():
    t0 = time.time()
    findings = run_analysis(PACKAGE_ROOT)
    return findings, time.time() - t0


def test_baseline_is_committed():
    assert os.path.isfile(DEFAULT_BASELINE), (
        "analysis/ktpu_lint_baseline.json must be committed — regenerate "
        "with `python -m kubernetes_tpu.analysis --write-baseline`")
    assert load_baseline(), "committed baseline is empty/unreadable"


def test_no_new_findings_and_under_budget():
    findings, elapsed = _run()
    new, _fixed = diff(findings, load_baseline())
    assert not new, (
        "ktpu-lint found NEW violations (fix them, add a reasoned "
        "'# ktpu-lint: disable=KTL00N -- why', or deliberately accept "
        "via --write-baseline):\n" + "\n".join(f.render() for f in new))
    assert elapsed < 10.0, (
        f"ktpu-lint took {elapsed:.1f}s over the package "
        "(acceptance budget is < 10s)")


def test_burned_down_rules_stay_at_zero():
    """KTL001/KTL002/KTL005/KTL006/KTL007 were burned to zero in this PR
    (annotation sweep + reasoned exemptions); the baseline must never
    quietly re-grow them — only KTL003/KTL004 carry accepted debt."""
    findings, _ = _run()
    base = load_baseline()
    debt = {f.rule for f in findings if f.fingerprint in base}
    assert debt <= {"KTL003", "KTL004"}, sorted(debt)


def test_guarded_by_annotations_are_live():
    """The KTL001 seed annotations exist where ISSUE 15 demanded coverage
    (hot shared state): losing one silently disables the rule there."""
    expect = {
        "kubernetes_tpu/sched/cache.py",
        "kubernetes_tpu/sched/staging.py",
        "kubernetes_tpu/kubelet/kubemark.py",
        "kubernetes_tpu/audit/auditor.py",
        "kubernetes_tpu/sched/queue.py",
    }
    for rel in sorted(expect):
        path = os.path.join(os.path.dirname(PACKAGE_ROOT), rel)
        with open(path, encoding="utf-8") as f:
            assert "# guarded by: self._" in f.read(), (
                f"{rel} lost its 'guarded by:' annotations")
