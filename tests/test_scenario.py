"""Cluster time machine: trace format, generators, recorders, driver.

Format tests pin the canonical-bytes contract (save -> load -> save is
bit-equal, generators are pure in (params, seed), the committed golden
fixture never drifts); recorder tests turn a real WAL and a synthetic
audit bundle into traces; the e2e tests replay small traces through a
REAL in-process apiserver + connected scheduler and hold the same gates
the ScenarioReplay bench case holds (all resident pods bound, per-phase
p99 present, dispatch order == plan, status ConfigMap published).
"""

import io
import json
import os
import time

import pytest

from kubernetes_tpu.scenario import (Trace, TraceEvent, TraceFormatError,
                                     TraceManifest, builtin_trace,
                                     trace_from_bundle, trace_from_wal)
from kubernetes_tpu.scenario.driver import (SCENARIO_CONFIGMAP,
                                            ScenarioDriver)
from kubernetes_tpu.scenario.generate import (BUILTINS, diurnal_burst,
                                              job_waves, rolling_update,
                                              smoke, tenant_onboarding)
from kubernetes_tpu.scenario.trace import TENANT_LABEL

pytestmark = pytest.mark.scenario

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "config",
    "scenario-smoke.trace.jsonl")


# ---- format ---------------------------------------------------------------

def test_round_trip_is_bit_equal(tmp_path):
    t = smoke(seed=7)
    p1 = str(tmp_path / "a.trace.jsonl")
    p2 = str(tmp_path / "b.trace.jsonl")
    t.save(p1)
    loaded = Trace.load(p1)
    loaded.save(p2)
    assert open(p1).read() == open(p2).read()
    assert loaded == t


def test_unknown_version_refused():
    t = smoke(seed=0)
    lines = t.to_lines()
    head = json.loads(lines[0])
    head["version"] = 99
    with pytest.raises(TraceFormatError, match="unknown trace version"):
        Trace.loads("\n".join([json.dumps(head)] + lines[1:]))


def test_wrong_kind_refused():
    with pytest.raises(TraceFormatError, match="not a ktpu-trace"):
        Trace.loads(json.dumps({"kind": "ConfigMap", "version": 1}))


def test_bad_verb_refused():
    t = smoke(seed=0)
    lines = t.to_lines()
    ev = json.loads(lines[1])
    ev["verb"] = "explode"
    with pytest.raises(TraceFormatError, match="unknown event verb"):
        Trace.loads("\n".join([lines[0], json.dumps(ev)]))


def test_malformed_event_line_names_the_line():
    t = smoke(seed=0)
    with pytest.raises(TraceFormatError, match="line 2"):
        Trace.loads("\n".join([t.to_lines()[0], "{not json"]))


def test_manifest_slo_gates_and_chaos_round_trip():
    t = diurnal_burst({"pods": 6, "nodes": 2, "p99_slo_s": 2.5}, seed=1)
    t.manifest.chaos = {"seed": 42, "profile": "churn"}
    rt = Trace.loads("\n".join(t.to_lines()))
    assert rt.manifest.slo_gates == {"p99AttemptLatencySeconds": 2.5}
    assert rt.manifest.chaos == {"seed": 42, "profile": "churn"}
    assert rt.manifest.seed == 1
    assert rt.describe()["sloGates"] == {"p99AttemptLatencySeconds": 2.5}


@pytest.mark.parametrize("name", sorted(BUILTINS))
def test_generator_determinism_across_seeds(name):
    for seed in (0, 1, 2):
        a = builtin_trace(name, seed=seed).to_lines()
        b = builtin_trace(name, seed=seed).to_lines()
        assert a == b, f"{name} seed={seed} is not pure"
    assert (builtin_trace(name, seed=0).to_lines()
            != builtin_trace(name, seed=1).to_lines()), \
        f"{name} ignores its seed"


def test_golden_fixture_pinned():
    # the committed fixture IS smoke(seed=0): tests and
    # BENCH_SCENARIO=builtin:smoke replay the same bytes, and toolchain
    # drift in the generators gets caught here, not in a bench round
    assert open(FIXTURE).read().splitlines() == smoke(seed=0).to_lines()


def test_unknown_builtin_lists_catalog():
    with pytest.raises(KeyError, match="diurnal-burst"):
        builtin_trace("nope")


def test_materialize_stamps_identity_and_tenant():
    t = tenant_onboarding({"tenants": 1, "pods_per_tenant": 2,
                           "background_pods": 0, "nodes": 2}, seed=0)
    ev = next(e for e in t.events if e.tenant)
    obj = t.materialize(ev)
    assert obj["metadata"]["name"] == ev.name
    assert obj["metadata"]["namespace"] == ev.ns
    assert obj["metadata"]["labels"][TENANT_LABEL] == ev.tenant
    node = t.fleet_nodes()[0]
    assert node["metadata"]["labels"]["kubernetes.io/hostname"] == \
        node["metadata"]["name"]


def test_unknown_template_ref_refused():
    t = Trace(TraceManifest(name="x"),
              [TraceEvent(at_s=0.0, verb="create", kind="Pod",
                          ns="default", name="p0", template="ghost")])
    with pytest.raises(TraceFormatError, match="unknown template"):
        t.materialize(t.events[0])


def test_resident_pods_tracks_deletes():
    t = rolling_update({"replicas": 6, "nodes": 3}, seed=0)
    resident = t.resident_pods()
    # every old-generation pod is deleted by the rollout; the new
    # generation stays
    assert len(resident) == 6
    assert all(name.startswith("new-") for _, name in resident)
    jw = job_waves({"waves": 2, "jobs_per_wave": 3}, seed=0)
    assert len(jw.resident_pods()) == 3  # only the final wave survives


# ---- recorders ------------------------------------------------------------

def test_trace_from_wal(tmp_path):
    from kubernetes_tpu.store.store import ObjectStore
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    store = ObjectStore(data_dir=str(tmp_path))
    for i in range(2):
        store.create("Node", make_node(f"wn{i}").capacity(
            {"cpu": "4", "pods": "10"}).obj().to_dict())
    for i in range(3):
        store.create("Pod", make_pod(f"wp{i}").req(
            {"cpu": "100m"}).obj().to_dict())
    store.delete("Pod", "default", "wp2")
    store.close()

    t = trace_from_wal(str(tmp_path / "wal.jsonl"), chaos_seed=99)
    # nodes journaled before the first pod op became the manifest fleet
    assert len(t.manifest.fleet) == 2
    assert {e.verb for e in t.events} == {"create", "delete"}
    assert len(t.resident_pods()) == 2
    assert t.manifest.chaos == {"seed": 99, "profile": "churn"}
    # recorded events carry inline objects stripped of server-minted
    # metadata, and replay in rv order
    ev = t.events[0]
    assert ev.obj is not None
    assert "resourceVersion" not in ev.obj["metadata"]
    assert [e.at_s for e in t.events] == sorted(e.at_s for e in t.events)
    # and the capture round-trips through the canonical format
    assert Trace.loads("\n".join(t.to_lines())) == t


def test_trace_from_wal_refuses_empty(tmp_path):
    p = tmp_path / "wal.jsonl"
    p.write_text(json.dumps({"op": "set", "kind": "ConfigMap",
                             "ns": "default", "name": "c", "rv": "1",
                             "obj": {}}) + "\n")
    with pytest.raises(TraceFormatError, match="no replayable"):
        trace_from_wal(str(p))


def test_trace_from_bundle():
    bundle = {"invariant": "phantom_binding", "chaosSeed": 1234,
              "resourceVersion": "567",
              "podBatch": [f"default/ip{i}" for i in range(4)]}
    t = trace_from_bundle(bundle, nodes=3)
    assert t.manifest.name == "bundle-phantom_binding"
    assert t.manifest.chaos == {"seed": 1234, "profile": "churn"}
    assert len(t.events) == 4
    assert all(e.template == "incident-pod" for e in t.events)
    assert len(t.fleet_nodes()) == 3
    with pytest.raises(TraceFormatError, match="no podBatch"):
        trace_from_bundle({"podBatch": []})


# ---- driver e2e -----------------------------------------------------------

def _wait_for(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _replay_against_live_stack(trace, speed=0.0):
    """Seed the trace's fleet into a real in-process apiserver with a
    connected scheduler, replay, and return (result, server url)."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    client = HTTPClient(server.url)
    runner = SchedulerRunner(client, SchedulerConfiguration(
        backoff_initial_s=0.05, backoff_max_s=0.2))
    runner.start()
    try:
        for n in trace.fleet_nodes():
            client.nodes().create(n)
        driver = ScenarioDriver(HTTPClient(server.url), trace,
                                speed=speed, bind_timeout_s=30.0)
        result = driver.run()
        assert result["dispatch_order"] == driver.plan()
        try:  # snapshot the published status CM before teardown
            cm = client.resource("configmaps", "default").get(
                SCENARIO_CONFIGMAP)
            status_cm = json.loads(cm["data"]["scenario"])
        except Exception:
            status_cm = None
        return result, status_cm
    finally:
        runner.stop()
        server.stop()


def test_driver_replay_e2e_diurnal():
    from kubernetes_tpu.metrics.registry import SCENARIO_ATTEMPT
    trace = diurnal_burst({"pods": 8, "nodes": 4, "cycles": 1,
                           "period_s": 0.5, "bursts": 1,
                           "burst_pods": 4}, seed=0)
    result, status_cm = _replay_against_live_stack(trace)
    assert result["completed"], result
    assert result["bound"] == result["resident"] == 12
    assert result["error_count"] == 0
    # per-phase p99 attempt latency present for every phase with pods —
    # the bench gate treats a missing number as failure
    assert result["phases"]
    for ph, st in result["phases"].items():
        assert st["bound"] == st["pods"], (ph, st)
        assert isinstance(st["p99_attempt_latency_s"], (int, float)), ph
        assert SCENARIO_ATTEMPT.count({"phase": ph}) == st["pods"]
    # the driver published its status ConfigMap (KTL006 upsert path)
    assert status_cm is not None
    assert status_cm["state"] == "done"
    assert status_cm["podsBound"] == 12
    assert status_cm["trace"] == "diurnal-burst"


def test_driver_status_line_renders():
    """ktpu status renders the Scenario: line from the published CM."""
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.store.apiserver import APIServer
    from kubernetes_tpu.utils.configmap import upsert_configmap
    server = APIServer().start()
    try:
        upsert_configmap(
            HTTPClient(server.url), "default", SCENARIO_CONFIGMAP,
            {"scenario": json.dumps(
                {"trace": "smoke", "state": "dispatching",
                 "phase": "wave-1", "eventsDispatched": 20,
                 "eventsTotal": 32, "skewMaxMs": 3.1, "podsBound": 9,
                 "podsResident": 32, "speed": 4.0})},
            site="test_scenario")
        out = io.StringIO()
        assert ktpu_main(["-s", server.url, "status"], out=out) == 0
        text = out.getvalue()
        assert "Scenario:" in text
        assert "smoke dispatching (phase wave-1)" in text
        assert "20/32 events" in text
    finally:
        server.stop()


def test_bundle_to_trace_to_replay_e2e(tmp_path):
    """The acceptance e2e: an audit repro bundle becomes a trace file
    that replays through the driver against the live stack."""
    from kubernetes_tpu.audit.auditor import write_bundle
    bundle_path = write_bundle(
        str(tmp_path), "incident",
        {"invariant": "test_incident", "resourceVersion": "42",
         "podBatch": [f"default/bp{i}" for i in range(6)]})
    trace = trace_from_bundle(bundle_path, nodes=4)
    path = str(tmp_path / "incident.trace.jsonl")
    trace.save(path)
    replayed = Trace.load(path)
    assert replayed == trace
    result, _ = _replay_against_live_stack(replayed)
    assert result["completed"], result
    assert result["bound"] == result["resident"] == 6
    assert result["phases"]["incident"]["bound"] == 6


class _NullRes:
    def create(self, obj):
        pass


class _NullClient:
    def pods(self, ns):
        return _NullRes()

    def nodes(self):
        return _NullRes()


def _warp_trace():
    return Trace(
        TraceManifest(name="warp", templates={"pod": {
            "kind": "Pod", "metadata": {}, "spec": {}}}),
        [TraceEvent(at_s=i * 0.2, verb="create", kind="Pod",
                    ns="default", name=f"w{i}", template="pod")
         for i in range(3)])


def test_driver_time_warp_paces_dispatch():
    """speed warps dispatch pacing: the 0.4s trace dispatches in >= 0.2s
    at speed 2, and near-instantly at speed 0 (as fast as possible)."""
    fast = ScenarioDriver(_NullClient(), _warp_trace(), speed=0.0,
                          publish=False, bind_timeout_s=0.0).run()
    assert fast["dispatched"] == 3
    assert fast["dispatch_s"] < 0.2  # no pacing at speed 0
    paced = ScenarioDriver(_NullClient(), _warp_trace(), speed=2.0,
                           publish=False, bind_timeout_s=0.0).run()
    assert paced["dispatched"] == 3
    assert paced["dispatch_s"] >= 0.2  # 0.4s of trace time at 2x
    assert paced["resident"] == 3  # nothing binds (null client)
    assert paced["bound"] == 0 and not paced["completed"]


def test_driver_counts_dispatch_errors():
    """API errors during dispatch are counted and listed, never raised —
    a replayed incident is expected to hit conflicts."""

    class _Boom:
        def create(self, obj):
            raise RuntimeError("conflict")

    class _BoomClient:
        def pods(self, ns):
            return _Boom()

        def nodes(self):
            return _Boom()

    res = ScenarioDriver(_BoomClient(), _warp_trace(), speed=0.0,
                         publish=False, bind_timeout_s=0.0).run()
    assert res["dispatched"] == 3
    assert res["error_count"] == 3
    assert "RuntimeError" in res["errors"][0]


# ---- workloads seed audit -------------------------------------------------

def _seeded_content(objs):
    """Object dicts minus the wrapper's bookkeeping fields (uid counter,
    wall-clock creationTimestamp) — the seed governs everything else."""
    out = []
    for o in objs:
        d = o.to_dict()
        for k in ("uid", "creationTimestamp", "resourceVersion"):
            d.get("metadata", {}).pop(k, None)
        out.append(d)
    return out


def test_workloads_same_seed_twice_identical():
    """The seed-threading contract the scenario generators rely on:
    mixed_heterogeneous/huge_cluster derive ALL randomness from the
    passed seed — same seed, same objects; different seed, different."""
    from benchmarks.workloads import huge_cluster, mixed_heterogeneous
    for fn, kw in ((mixed_heterogeneous, {"pods": 40, "nodes": 20}),
                   (huge_cluster, {"pods": 12, "nodes": 16})):
        n1, p1 = fn(seed=3, **kw)
        n2, p2 = fn(seed=3, **kw)
        assert _seeded_content(n1) == _seeded_content(n2), fn.__name__
        assert _seeded_content(p1) == _seeded_content(p2), fn.__name__
        _, p3 = fn(seed=4, **kw)
        assert _seeded_content(p1) != _seeded_content(p3), fn.__name__
