"""Round-4 breadth controllers: ResourceQuota status, node TTL annotation,
ClusterRole aggregation.

Reference: pkg/controller/{resourcequota,ttl,clusterroleaggregation}/.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.clusterroleaggregation import (
    ClusterRoleAggregationController,
)
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.ttl import TTL_ANNOTATION, TTLController
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def run_controller(client, ctrl):
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def stop(ctrl, factory):
    ctrl.stop()
    factory.stop_all()


# -------------------------------------------------------------- resourcequota

def test_quota_status_tracks_usage(client):
    client.resource("resourcequotas", "default").create({
        "kind": "ResourceQuota", "metadata": {"name": "rq"},
        "spec": {"hard": {"pods": "10", "requests.cpu": "4",
                          "count/configmaps": "5"}}})
    ctrl, factory = run_controller(client, ResourceQuotaController(client))
    try:
        client.pods("default").create(
            make_pod("a").req({"cpu": "500m"}).obj().to_dict())
        client.pods("default").create(
            make_pod("b").req({"cpu": "250m"}).obj().to_dict())
        client.resource("configmaps", "default").create(
            {"kind": "ConfigMap", "metadata": {"name": "cm"}})

        def used():
            q = client.resource("resourcequotas", "default").get("rq")
            return (q.get("status") or {}).get("used") or {}
        assert wait_until(lambda: used().get("pods") == "2"), used()
        assert used()["requests.cpu"] == "750m"
        assert used()["count/configmaps"] == "1"
        # terminal pods stop counting
        a = client.pods("default").get("a")
        a.setdefault("status", {})["phase"] = "Succeeded"
        client.pods("default").update_status(a)
        assert wait_until(lambda: used().get("pods") == "1"), used()
        assert used()["requests.cpu"] == "250m"
    finally:
        stop(ctrl, factory)


# ------------------------------------------------------------------------ ttl

def test_ttl_annotation_by_cluster_size(client):
    ctrl, factory = run_controller(client, TTLController(client))
    try:
        client.nodes().create(make_node("n0").obj().to_dict())

        def ttl(name):
            n = client.nodes().get(name)
            return ((n.get("metadata") or {}).get("annotations") or {}) \
                .get(TTL_ANNOTATION)
        assert wait_until(lambda: ttl("n0") == "0"), ttl("n0")
    finally:
        stop(ctrl, factory)


def test_ttl_scales_with_boundaries():
    from kubernetes_tpu.controllers.ttl import _BOUNDARIES, _MAX_TTL
    assert _BOUNDARIES[0] == (100, 0)
    assert _MAX_TTL == 300


# --------------------------------------------------------- role aggregation

def test_clusterrole_aggregation(client):
    roles = client.resource("clusterroles", None)
    roles.create({"kind": "ClusterRole", "metadata": {"name": "admin"},
                  "aggregationRule": {"clusterRoleSelectors": [
                      {"matchLabels": {"aggregate-to-admin": "true"}}]},
                  "rules": []})
    ctrl, factory = run_controller(
        client, ClusterRoleAggregationController(client))
    try:
        roles.create({"kind": "ClusterRole",
                      "metadata": {"name": "crd-edit",
                                   "labels": {"aggregate-to-admin": "true"}},
                      "rules": [{"apiGroups": ["example.com"],
                                 "resources": ["widgets"],
                                 "verbs": ["*"]}]})

        def rules():
            return roles.get("admin").get("rules") or []
        assert wait_until(lambda: len(rules()) == 1), rules()
        assert rules()[0]["resources"] == ["widgets"]
        # removing the labeled role empties the aggregate again
        roles.delete("crd-edit")
        assert wait_until(lambda: rules() == []), rules()
        # unlabeled roles never aggregate
        roles.create({"kind": "ClusterRole",
                      "metadata": {"name": "loner"},
                      "rules": [{"apiGroups": [""], "resources": ["pods"],
                                 "verbs": ["get"]}]})
        time.sleep(0.3)
        assert rules() == []
    finally:
        stop(ctrl, factory)


def test_ttl_fleet_enqueue_only_on_tier_change():
    """ADVICE r4: per-event fleet fan-out is O(N^2) at scale — the fleet is
    re-enqueued only when the cluster-size TIER changes (upstream enqueues
    everything on ttlBoundaries crossings, not every membership event)."""
    ctrl = TTLController.__new__(TTLController)
    enqueued = []
    ctrl.enqueue = lambda obj: enqueued.append(
        (obj.get("metadata") or {}).get("name"))

    class _Store:
        def __init__(self):
            self.nodes = []

        def list(self):
            return self.nodes

        def __len__(self):
            return len(self.nodes)

    class _Informer:
        def __init__(self):
            self.store = _Store()

        def add_event_handler(self, fn):
            pass

    class _Factory:
        def informer(self, *a):
            return _Informer()

    ctrl.register(_Factory())
    store = ctrl.node_informer.store
    # first ADDED establishes the tier -> one fleet pass of size 1
    store.nodes = [{"metadata": {"name": "n0"}}]
    ctrl._on_node("ADDED", store.nodes[0], None)
    n_after_first = len(enqueued)
    # 50 more ADDs inside the same tier (<100 -> ttl 0): one enqueue each,
    # no fleet fan-out
    for i in range(1, 51):
        store.nodes = [{"metadata": {"name": f"n{j}"}}
                       for j in range(i + 1)]
        ctrl._on_node("ADDED", store.nodes[-1], None)
    assert len(enqueued) == n_after_first + 50
    # crossing the 100-node boundary re-enqueues the fleet once
    store.nodes = [{"metadata": {"name": f"n{j}"}} for j in range(101)]
    before = len(enqueued)
    ctrl._on_node("ADDED", store.nodes[-1], None)
    assert len(enqueued) == before + 101  # whole fleet, tier changed
