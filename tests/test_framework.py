"""Scheduler framework: queue, cache, config, metrics, preemption, main loop."""

import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.config.features import FeatureGate
from kubernetes_tpu.config.types import (
    Profile,
    SchedulerConfiguration,
    ValidationError,
    validate,
)
from kubernetes_tpu.metrics.registry import Registry
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.preemption import find_candidate
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


# ------------------------------------------------------------------- queue

def test_queue_priority_and_fifo_order():
    q = SchedulingQueue()
    q.add(make_pod("low").priority(1).obj())
    q.add(make_pod("high").priority(10).obj())
    q.add(make_pod("mid").priority(5).obj())
    batch = q.pop_batch(10, wait=0.1)
    assert [p.metadata.name for p, _ in batch] == ["high", "mid", "low"]


def test_queue_backoff_requeues():
    q = SchedulingQueue(backoff_initial=0.05, backoff_max=0.1)
    pod = make_pod("p").obj()
    q.add_unschedulable(pod, attempts=1)
    assert q.pop_batch(10, wait=0.01) == []      # still backing off
    time.sleep(0.07)
    batch = q.pop_batch(10, wait=0.2)
    assert len(batch) == 1 and batch[0][1] == 1


def test_queue_scheduling_gates_hold_until_cleared():
    q = SchedulingQueue()
    pod = make_pod("gated").scheduling_gate("wait-for-quota").obj()
    q.add(pod)
    assert q.pop_batch(10, wait=0.05) == []
    q.move_all_to_active_or_backoff("NodeAdd")
    assert q.pop_batch(10, wait=0.05) == []      # still gated
    pod.spec.scheduling_gates = []
    q.activate_gated(pod)
    assert len(q.pop_batch(10, wait=0.1)) == 1


def test_queue_event_moves_unschedulable():
    q = SchedulingQueue(unschedulable_timeout=999)
    q.park_unschedulable(make_pod("stuck").obj(), attempts=3)
    assert q.pop_batch(10, wait=0.05) == []
    q.move_all_to_active_or_backoff("NodeAdd")
    batch = q.pop_batch(10, wait=0.1)
    assert len(batch) == 1 and batch[0][1] == 3


def test_queue_dedup():
    q = SchedulingQueue()
    pod = make_pod("p").obj()
    q.add(pod)
    q.add(pod)
    assert len(q.pop_batch(10, wait=0.1)) == 1


# ------------------------------------------------------------------- cache

def test_cache_snapshot_caching_and_invalidation():
    c = SchedulerCache()
    c.add_node(make_node("n0").capacity({"cpu": "4"}).obj())
    nodes1, ct1, meta1 = c.snapshot()
    nodes2, ct2, meta2 = c.snapshot()
    assert ct1 is ct2, "unchanged cluster must reuse the encoded snapshot"
    c.add_node(make_node("n1").capacity({"cpu": "8"}).obj())
    nodes3, ct3, _ = c.snapshot()
    assert ct3 is not ct1 and len(nodes3) == 2


def test_cache_assume_expire_and_confirm():
    c = SchedulerCache(assume_ttl=0.05)
    c.add_node(make_node("n0").capacity({"cpu": "4", "pods": "10"}).obj())
    pod = make_pod("p").req({"cpu": "2"}).obj()
    c.assume(pod, "n0")
    _, ct, _ = c.snapshot()
    assert np.asarray(ct.requested)[0, 0] == 2000  # assumed folded in
    time.sleep(0.07)
    _, ct2, _ = c.snapshot()
    assert np.asarray(ct2.requested)[0, 0] == 0    # expired, forgotten
    # confirm path: assume then add_pod confirms, no expiry
    c.assume(pod, "n0")
    bound = make_pod("p").req({"cpu": "2"}).node("n0").obj()
    bound.metadata.uid = pod.metadata.uid
    c.add_pod(bound)
    time.sleep(0.07)
    _, ct3, _ = c.snapshot()
    assert np.asarray(ct3.requested)[0, 0] == 2000


def test_cache_forget_rolls_back():
    c = SchedulerCache()
    c.add_node(make_node("n0").capacity({"cpu": "4"}).obj())
    pod = make_pod("p").req({"cpu": "2"}).obj()
    c.assume(pod, "n0")
    c.forget(pod.key)
    _, ct, _ = c.snapshot()
    assert np.asarray(ct.requested)[0, 0] == 0


# ------------------------------------------------------------------- config

def test_config_defaults_and_yaml(tmp_path):
    cfg = SchedulerConfiguration()
    validate(cfg)
    f = tmp_path / "cfg.yaml"
    f.write_text("""
batchSize: 128
profiles:
- schedulerName: default-scheduler
  fitStrategy: MostAllocated
  scoreWeights: {ImageLocality: 0}
- schedulerName: binpack
  disabledFilters: [NodePorts]
""")
    cfg = SchedulerConfiguration.from_yaml(str(f))
    validate(cfg)
    assert cfg.batch_size == 128
    assert cfg.profile_for("binpack").enabled_filters is not None
    assert "NodePorts" not in cfg.profile_for("binpack").enabled_filters
    assert cfg.profile_for("default-scheduler").weights()["ImageLocality"] == 0


@pytest.mark.parametrize("mutate,err", [
    (lambda c: c.profiles.clear(), "at least one"),
    (lambda c: setattr(c.profiles[0], "fit_strategy", "Bogus"), "fitStrategy"),
    (lambda c: c.profiles[0].disabled_filters.append("Nope"), "unknown filter"),
    (lambda c: c.profiles[0].score_weights.update({"NodeAffinity": -1}), "negative"),
    (lambda c: setattr(c, "batch_size", 0), "batchSize"),
    (lambda c: c.profiles.append(Profile()), "duplicate"),
])
def test_config_validation_rejects(mutate, err):
    cfg = SchedulerConfiguration()
    mutate(cfg)
    with pytest.raises(ValidationError, match=err):
        validate(cfg)


def test_feature_gates():
    fg = FeatureGate()
    assert fg.enabled("TPUBatchScheduling")
    fg.set("TPUBatchScheduling", False)
    assert not fg.enabled("TPUBatchScheduling")
    with pytest.raises(ValueError):
        fg.set("SchedulingGates", False)  # GA locked
    with pytest.raises(KeyError):
        fg.enabled("NoSuchGate")


# ------------------------------------------------------------------- metrics

def test_metrics_exposition():
    r = Registry()
    c = r.counter("test_total", "help text")
    c.inc({"result": "ok"})
    c.inc({"result": "ok"})
    h = r.histogram("test_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = r.expose_text()
    assert 'test_total{result="ok"} 2.0' in text
    assert 'test_seconds_bucket{le="0.1"} 1' in text
    assert 'test_seconds_count 2' in text
    assert h.percentile(0.5) == 0.1


# ---------------------------------------------------------------- preemption

def test_preemption_evicts_minimal_lowest_priority():
    nodes = [make_node("n0").capacity({"cpu": "4", "pods": "10"}).obj()]
    bound = [make_pod("v-low").req({"cpu": "2"}).priority(1).node("n0").obj(),
             make_pod("v-mid").req({"cpu": "2"}).priority(5).node("n0").obj()]
    pod = make_pod("hi").req({"cpu": "2"}).priority(100).obj()
    res = find_candidate(nodes, bound, pod)
    assert res is not None and res.node_name == "n0"
    assert [v.metadata.name for v in res.victims] == ["v-low"]


def test_preemption_no_candidate_when_priorities_equal():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("same").req({"cpu": "4"}).priority(10).node("n0").obj()]
    pod = make_pod("p").req({"cpu": "2"}).priority(10).obj()
    assert find_candidate(nodes, bound, pod) is None


# ---------------------------------------------------------- scheduler loop

def make_sched(nodes, binder=None, cfg=None):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    bound_log = []

    def default_binder(pod, node_name):
        bound_log.append((pod.metadata.name, node_name))
        return True

    sched = Scheduler(cfg or SchedulerConfiguration(), cache, queue,
                      binder or default_binder)
    return sched, queue, cache, bound_log


def test_scheduler_end_to_end_batch():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4", "pods": "10"}).obj()
             for i in range(4)]
    sched, queue, cache, log = make_sched(nodes)
    for i in range(8):
        queue.add(make_pod(f"p{i}").req({"cpu": "1"}).obj())
    n = sched.run_once()
    sched.wait_for_bindings()
    assert n == 8 and len(log) == 8
    assert cache.stats()["assumed"] == 8


def test_scheduler_unschedulable_goes_to_backoff():
    nodes = [make_node("tiny").capacity({"cpu": "1"}).obj()]
    sched, queue, cache, log = make_sched(nodes)
    queue.add(make_pod("big").req({"cpu": "8"}).obj())
    assert sched.run_once() == 0
    assert queue.stats()["backoff"] == 1


def test_scheduler_failed_binding_rolls_back():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    sched, queue, cache, log = make_sched(nodes, binder=lambda p, n: False)
    queue.add(make_pod("p").req({"cpu": "1"}).obj())
    sched.run_once()
    sched.wait_for_bindings()
    assert cache.stats()["assumed"] == 0      # forgotten
    assert queue.stats()["backoff"] == 1      # requeued


def test_scheduler_ignores_foreign_scheduler_name():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    sched, queue, cache, log = make_sched(nodes)
    queue.add(make_pod("alien").scheduler_name("other-scheduler").obj())
    assert sched.run_once() == 0
    assert log == []


def test_scheduler_profile_fit_strategy():
    # MostAllocated profile should bin-pack onto the fuller node
    nodes = [make_node("fuller").capacity({"cpu": "4", "pods": "10"}).obj(),
             make_node("empty").capacity({"cpu": "4", "pods": "10"}).obj()]
    cfg = SchedulerConfiguration(profiles=[Profile(fit_strategy="MostAllocated")])
    sched, queue, cache, log = make_sched(nodes, cfg=cfg)
    cache.add_pod(make_pod("seed").req({"cpu": "2"}).node("fuller").obj())
    queue.add(make_pod("p").req({"cpu": "1"}).obj())
    sched.run_once()
    sched.wait_for_bindings()
    assert log == [("p", "fuller")]
