"""Connected-path pipeline: incremental encode + multi-deep dispatch.

Three properties hold the north star together and are pinned here:

1. ENCODE PARITY — pod rows compiled at informer-event time
   (``SnapshotEncoder.precompile_pod``) must yield byte-identical batch
   tensors to hot-path compilation, and the cache's patch-encoded
   snapshots must stay semantically equal to a full re-encode under
   randomized add/update/delete churn (Cache.UpdateSnapshot contract).
2. PIPELINE PARITY — with N drains in flight (cfg.pipeline_depth > 1)
   the placements must equal the one-deep pipeline's, every placement
   must be feasible per the serial oracle, and the fold-region /
   fill-bound reservation arithmetic must reconcile once the pipeline
   drains.
3. FAILURE BOOKKEEPING — kubelet pod workers record + retry sync errors
   with backoff instead of swallowing them, and the static-pod mirror
   resync backstop recreates mirrors even when the DELETED watch event
   is lost.
"""

import json
import random
import time

import numpy as np
import pytest

from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n, cpu="4", prefix="n"):
    return [make_node(f"{prefix}{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "32"})
            .label("kubernetes.io/hostname", f"{prefix}{i}")
            .label("topology.kubernetes.io/zone", f"z{i % 3}")
            .obj() for i in range(n)]


def _rich_pod(i, anti=False, spread=False):
    b = (make_pod(f"p{i}").req({"cpu": "250m", "memory": "128Mi"})
         .label("app", f"g{i % 3}"))
    if anti:
        b = b.pod_anti_affinity("kubernetes.io/hostname",
                                {"app": f"g{i % 3}"})
    if spread:
        b = b.spread(2, "topology.kubernetes.io/zone", "DoNotSchedule",
                     {"app": f"g{i % 3}"})
    return b.obj()


# ---- 1a. precompiled rows == hot-path rows (exact array parity) ----------

def test_precompiled_encode_pods_matches_inline():
    import jax
    nodes = _nodes(6)
    pods = [_rich_pod(i, anti=(i % 2 == 0), spread=(i % 3 == 0))
            for i in range(8)]
    enc_a, enc_b = SnapshotEncoder(), SnapshotEncoder()
    _, meta_a = enc_a.encode_cluster(nodes, [], pending_pods=pods)
    _, meta_b = enc_b.encode_cluster(nodes, [], pending_pods=pods)
    # A precompiles at "informer-event time" (same intern order as the
    # inline path would produce), B compiles on the hot path
    for p in pods:
        assert enc_a.precompile_pod(p)
    pb_a = enc_a.encode_pods(pods, meta_a, min_p=8)
    pb_b = enc_b.encode_pods(pods, meta_b, min_p=8)
    assert enc_a.pod_cache_hits == len(pods)
    assert enc_b.pod_cache_hits == 0
    for la, lb in zip(jax.tree_util.tree_leaves(pb_a),
                      jax.tree_util.tree_leaves(pb_b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_precompile_cache_invalidated_by_catalog_epoch():
    nodes = _nodes(2)
    pods = [_rich_pod(0)]
    enc = SnapshotEncoder()
    _, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    assert enc.precompile_pod(pods[0])
    enc.set_namespaces({"default": {"team": "a"}})  # bumps the epoch
    enc.encode_pods(pods, meta)
    assert enc.pod_cache_hits == 0  # stale record must NOT be served
    assert enc.pod_cache_misses == 1


def test_precompile_cache_requires_object_identity():
    """A fresh watch object (new Pod instance, same key) must miss."""
    nodes = _nodes(2)
    p1 = _rich_pod(0)
    p2 = _rich_pod(0)  # same key, different object
    enc = SnapshotEncoder()
    _, meta = enc.encode_cluster(nodes, [], pending_pods=[p1])
    assert enc.precompile_pod(p1)
    enc.encode_pods([p2], meta)
    assert enc.pod_cache_hits == 0


# ---- 1b. randomized delta churn: patch path == full re-encode ------------

def test_randomized_incremental_encode_parity():
    """Property-style: after random pod assume/update/delete and node
    add/remove sequences, the cache's (possibly patch-encoded) snapshot is
    semantically identical to a fresh full encode of the same state —
    same per-node requested vectors, same existing-pod placement
    multiset."""
    rng = random.Random(1234)
    cache = SchedulerCache()
    for n in _nodes(6):
        cache.add_node(n)
    live: dict[str, object] = {}   # key -> bound pod
    node_names = [f"n{i}" for i in range(6)]
    extra_nodes = 0
    cache.snapshot()  # seed the cached encoding

    for step in range(40):
        op = rng.random()
        if op < 0.55 or not live:
            i = rng.randrange(10_000)
            p = _rich_pod(i, anti=rng.random() < 0.3,
                          spread=rng.random() < 0.2)
            cache.assume(p, rng.choice(node_names))
            live[p.key] = p
        elif op < 0.80:
            key = rng.choice(list(live))
            cache.remove_pod(key)
            del live[key]
        elif op < 0.92 and live:
            # rebind an existing pod elsewhere (update path)
            key = rng.choice(list(live))
            p = live[key]
            cache.remove_pod(key)
            cache.assume(p, rng.choice(node_names))
        else:
            extra_nodes += 1
            name = f"x{extra_nodes}"
            cache.add_node(make_node(name)
                           .capacity({"cpu": "2", "memory": "4Gi",
                                      "pods": "8"})
                           .label("kubernetes.io/hostname", name)
                           .obj())
            node_names.append(name)

        if step % 5 != 4:
            continue
        nodes_now, ct, meta = cache.snapshot()
        bound = cache.bound_pods()
        fresh = SnapshotEncoder()
        ct_ref, meta_ref = fresh.encode_cluster(nodes_now, bound)
        # per-node requested parity, matched BY NAME (row order may differ
        # only if node sets diverged — they must not)
        assert meta.node_names == meta_ref.node_names
        n_live = len(nodes_now)
        req = np.asarray(ct.requested)
        req_ref = np.asarray(ct_ref.requested)
        shared = min(req.shape[1], req_ref.shape[1])
        np.testing.assert_array_equal(req[:n_live, :shared],
                                      req_ref[:n_live, :shared])
        if req.shape[1] > shared:
            assert not np.asarray(req[:n_live, shared:]).any()
        # existing-pod placement multiset parity
        def placements(ct_x):
            v = np.asarray(ct_x.epod_valid)
            return sorted(np.asarray(ct_x.epod_node)[v].tolist())
        assert placements(ct) == placements(ct_ref), f"step {step}"


# ---- 2. pipeline depth > 1: parity + invariants --------------------------

def _pipelined_sched(nodes, depth, batch_size=4, drain_batches=2):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    cfg = SchedulerConfiguration(batch_size=batch_size,
                                 max_drain_batches=drain_batches,
                                 pipeline_depth=depth)
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(batch_size)]
    assert sched.warm_drain(warm, slot_headroom=64), "context failed to arm"
    return sched, cache, queue, log


def _run_to_empty(sched, queue, pods, rounds=24):
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if not sched._pending and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()
    return bound


def test_pipeline_depth_parity_invariants_and_oracle_feasibility():
    """Identical workload through depth=1 and depth=3 pipelines must place
    every pod identically — overlap changes WHEN host work happens, never
    WHAT the device computes. On the pipelined leg, the fold-region /
    fill-bound reservation arithmetic must reconcile once the pipeline
    drains, and every placement must be feasible per the serial oracle."""
    import copy
    from kubernetes_tpu.sched.oracle import OracleScheduler
    placements = {}
    nodes = _nodes(8)
    for depth in (1, 3):
        pods = [_rich_pod(i, anti=(i % 5 == 0), spread=(i % 4 == 0))
                for i in range(32)]
        sched, cache, queue, log = _pipelined_sched(nodes, depth)
        bound = _run_to_empty(sched, queue, pods)
        assert bound == 32, f"depth {depth} lost pods: {bound}"
        placements[depth] = dict(log)
        if depth == 3:
            # fold-region invariants after the pipeline drained: every
            # dispatch-side reservation either folded (fill_host) or was
            # released, and the fold never crossed the patch cursor
            ctx = sched._drain_ctx
            assert ctx is not None and not sched._pending
            cs = ctx["cs"]
            assert ctx["fill_bound"] == cs.fill_host
            assert cs.fill_host <= cs.top
            assert cs.fill_host == bound
        sched.close()
    assert placements[1] == placements[3]
    # serial-oracle feasibility of each pipelined placement, given all the
    # other placements bound
    pods = [_rich_pod(i, anti=(i % 5 == 0), spread=(i % 4 == 0))
            for i in range(32)]
    placed = []
    for p in pods:
        q = copy.deepcopy(p)
        q.spec.node_name = placements[3][p.metadata.name]
        placed.append(q)
    name_to_idx = {n.metadata.name: i for i, n in enumerate(nodes)}
    for i, q in enumerate(placed):
        others = [x for j, x in enumerate(placed) if j != i]
        orc = OracleScheduler(nodes, others)
        unbound = copy.deepcopy(q)
        ni = name_to_idx[q.spec.node_name]
        unbound.spec.node_name = ""
        mask, reasons = orc.feasible(unbound)
        assert mask[ni], (f"{q.key} infeasible on {q.spec.node_name}: "
                          f"{reasons.get(q.spec.node_name)}")


def test_pipeline_overlap_really_happens():
    """With depth=3 and a full backlog, dispatches must stack to the
    configured bound: suppress early-resolution (as if the device were
    still busy — deterministic on a fast CPU) and require the pipeline to
    reach 3 in flight while still binding every pod."""
    nodes = _nodes(8)
    pods = [_rich_pod(i) for i in range(32)]
    sched, cache, queue, log = _pipelined_sched(nodes, 3)
    sched._drain_ready = lambda pend: False  # never resolve early
    max_depth = 0
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(24):
        bound += sched.run_once(wait=0.01)
        max_depth = max(max_depth, len(sched._pending))
        if not queue.stats()["active"] and len(sched._pending) == 0:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()
    sched.close()
    assert bound == 32
    assert max_depth == 3, max_depth  # the bound was reached, not exceeded


# ---- 3a. pod workers: sync errors recorded + retried with backoff --------

def test_pod_workers_record_and_retry_sync_errors():
    from kubernetes_tpu.kubelet.pod_workers import PodWorkers
    from kubernetes_tpu.metrics.registry import KUBELET_SYNC_ERRORS
    calls = []

    def sync(uid, pod):
        calls.append(pod)
        if len(calls) <= 2:
            raise RuntimeError("transient sync failure")

    pw = PodWorkers(sync, backoff_initial=0.02, backoff_max=0.1)
    before = KUBELET_SYNC_ERRORS.get()
    pw.update_pod("u1", {"metadata": {"name": "p1"}})
    deadline = time.time() + 5.0
    while time.time() < deadline and len(calls) < 3:
        time.sleep(0.01)
    assert len(calls) >= 3, "failed sync was not retried"
    time.sleep(0.05)
    assert pw.sync_errors("u1") == 0, "success did not clear the counter"
    assert KUBELET_SYNC_ERRORS.get() - before >= 2
    pw.stop()


def test_pod_workers_latest_update_wins_during_backoff():
    from kubernetes_tpu.kubelet.pod_workers import PodWorkers
    seen = []

    def sync(uid, pod):
        seen.append(pod["v"])
        if pod["v"] == 1:
            raise RuntimeError("v1 always fails")

    pw = PodWorkers(sync, backoff_initial=0.05, backoff_max=0.1)
    pw.update_pod("u1", {"v": 1})
    time.sleep(0.02)          # v1 fails, worker parks in backoff
    pw.update_pod("u1", {"v": 2})  # newer update supersedes the retry
    deadline = time.time() + 5.0
    while time.time() < deadline and 2 not in seen:
        time.sleep(0.01)
    assert 2 in seen
    pw.stop()


# ---- 3b. static-pod mirror resync backstop -------------------------------

def test_static_pod_mirror_resync_survives_lost_delete_event(tmp_path):
    """Kill the kubelet's informer (simulating a swallowed DELETED event —
    the starvation mode behind the old flake), delete the mirror via the
    API, and require the periodic resync backstop to recreate it."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.kubelet.kubelet import HollowNode
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    node = None
    try:
        client = HTTPClient(server.url)
        manifest_dir = tmp_path / "m"
        manifest_dir.mkdir()
        node = HollowNode(client, "rsb-1")
        node.kubelet.start(static_pod_path=str(manifest_dir),
                           static_poll_s=0.05)
        (manifest_dir / "kapi.json").write_text(json.dumps({
            "kind": "Pod", "metadata": {"name": "kapi"},
            "spec": {"containers": [{"name": "c", "image": "api:v1"}]}}))

        def mirror():
            try:
                return client.pods("default").get("kapi-rsb-1")
            except Exception:
                return None

        deadline = time.time() + 20
        while time.time() < deadline and mirror() is None:
            time.sleep(0.05)
        assert mirror() is not None
        # no informer => the DELETED event below is never delivered
        node.kubelet._informer.stop()
        time.sleep(0.2)
        client.pods("default").delete("kapi-rsb-1")
        deadline = time.time() + 20
        while time.time() < deadline and mirror() is None:
            time.sleep(0.05)
        assert mirror() is not None, \
            "resync backstop did not recreate the mirror"
    finally:
        if node is not None:
            node.stop()
        server.stop()


# ---- bulk status transport (kubemark batcher's storage half) -------------

def test_bulk_pod_status_roundtrip():
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        client.pods("default").create_many(
            [make_pod(f"s{i}", "default").obj().to_dict() for i in range(4)])
        errs = client.pods("default").update_status_many(
            [("default", f"s{i}", {"phase": "Running"}) for i in range(3)]
            + [("default", "missing", {"phase": "Running"})])
        assert errs[:3] == [None, None, None]
        assert "not found" in errs[3]
        for i in range(3):
            assert client.pods("default").get(
                f"s{i}")["status"]["phase"] == "Running"
    finally:
        server.stop()
