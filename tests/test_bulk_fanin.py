"""Bulk control-plane fan-in: ``nodes/-/status`` + ``leases/-/renew``
parity with the singleton paths, the kubemark fleet batchers, and the
scheduler's liveness-only node-event skip.

The sublinear-control-plane contract: batched heartbeats/lease renewals/
status writes must be INDISTINGUISHABLE from N singleton requests to
every consumer (watchers, resourceVersion discipline, node-lifecycle),
while per-item failures report without failing siblings."""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.kubemark import (
    HollowCluster,
    _HeartbeatBatcher,
    _LeaseBatcher,
    _StatusBatcher,
)
from kubernetes_tpu.store.store import MODIFIED, ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def wait_until(fn, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def _node_dict(name, extra_conditions=()):
    n = make_node(name).allocatable({"cpu": "4", "pods": "10"}) \
        .obj().to_dict()
    n["status"]["conditions"] = [
        {"type": "Ready", "status": "Unknown", "lastHeartbeatTime": 0.0},
        *extra_conditions]
    return n


def _lease(name, renew=1.0):
    return {"kind": "Lease",
            "metadata": {"name": name, "namespace": "kube-node-lease"},
            "spec": {"holderIdentity": name, "leaseDurationSeconds": 40,
                     "renewTime": renew}}


# ---- 1. bulk-vs-singleton parity ------------------------------------------

def test_bulk_heartbeat_matches_singleton_merge_and_watch():
    """One nodes/-/status batch produces exactly what N singleton
    heartbeats produced: conditions merged BY TYPE (foreign conditions
    survive, Ready replaced), one MODIFIED + one fresh resourceVersion
    per item, in request order."""
    store = ObjectStore()
    client = DirectClient(store)
    other = {"type": "NetworkUnavailable", "status": "False"}
    client.nodes().create_many([_node_dict("b0", [other]),
                                _node_dict("b1"), _node_dict("s0", [other])])

    # singleton reference: the kubelet's read-modify-write heartbeat
    k = Kubelet(client, "s0", register_node=False)
    k.heartbeat_once()
    single = client.nodes().get("s0")["status"]["conditions"]

    rv0 = store.resource_version
    w = store.watch("Node", since_rv=rv0)
    errs = client.nodes().heartbeat_many([
        ("b0", {"conditions": [
            {"type": "Ready", "status": "True",
             "lastHeartbeatTime": time.time()}]}),
        ("b1", {"conditions": [
            {"type": "Ready", "status": "True",
             "lastHeartbeatTime": time.time()}]}),
    ])
    assert errs == [None, None]
    evs = [w.get(timeout=1.0), w.get(timeout=1.0)]
    assert [e.type for e in evs] == [MODIFIED, MODIFIED]
    # rv discipline: one bump per item, in order, stamped on the object
    assert [e.resource_version for e in evs] == [rv0 + 1, rv0 + 2]
    assert [e.object["metadata"]["resourceVersion"] for e in evs] == \
        [str(rv0 + 1), str(rv0 + 2)]
    assert [e.object["metadata"]["name"] for e in evs] == ["b0", "b1"]
    w.stop()

    # merge parity with the singleton path: Ready replaced, foreign
    # condition preserved, nothing else about the object touched
    bulk = client.nodes().get("b0")["status"]["conditions"]
    assert {(c["type"], c["status"]) for c in bulk} == \
        {(c["type"], c["status"]) for c in single}
    assert client.nodes().get("b0")["status"]["allocatable"] == \
        client.nodes().get("s0")["status"]["allocatable"]


def test_bulk_heartbeat_per_item_404_does_not_fail_batch():
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_node_dict("n0"))
    fresh = {"conditions": [{"type": "Ready", "status": "True",
                             "lastHeartbeatTime": 9.0}]}
    errs = client.nodes().heartbeat_many(
        [("ghost", fresh), ("n0", fresh), ("ghost2", fresh)])
    assert errs[0] and "not found" in errs[0]
    assert errs[1] is None
    assert errs[2] and "not found" in errs[2]
    # the sibling committed
    conds = client.nodes().get("n0")["status"]["conditions"]
    assert any(c["status"] == "True" for c in conds
               if c["type"] == "Ready")


def test_bulk_lease_renew_parity_and_missing():
    store = ObjectStore()
    client = DirectClient(store)
    leases = client.leases("kube-node-lease")
    leases.create_many([_lease("n0"), _lease("n1")])
    rv0 = store.resource_version
    w = store.watch("Lease", since_rv=rv0)
    errs = leases.renew_many([("n0", 50.0), ("missing", 1.0),
                              ("n1", 60.0)])
    assert errs[0] is None and errs[2] is None
    assert errs[1] and "not found" in errs[1]
    assert leases.get("n0")["spec"]["renewTime"] == 50.0
    assert leases.get("n1")["spec"]["renewTime"] == 60.0
    evs = [w.get(timeout=1.0), w.get(timeout=1.0)]
    assert [e.type for e in evs] == [MODIFIED, MODIFIED]
    assert [e.resource_version for e in evs] == [rv0 + 1, rv0 + 2]
    # holderIdentity untouched (renew bumps renewTime only)
    assert leases.get("n0")["spec"]["holderIdentity"] == "n0"
    w.stop()


def test_bulk_endpoints_over_http():
    """The HTTP transport speaks the same bulk protocol: per-item status
    arrays in request order from both endpoints."""
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        client.nodes().create_many([_node_dict("h0"), _node_dict("h1")])
        errs = client.nodes().heartbeat_many([
            ("h0", {"conditions": [{"type": "Ready", "status": "True",
                                    "lastHeartbeatTime": 3.0}]}),
            ("nope", {}),
        ])
        assert errs[0] is None and errs[1] and "not found" in errs[1]
        assert any(c["status"] == "True"
                   for c in client.nodes().get("h0")["status"]["conditions"]
                   if c["type"] == "Ready")
        leases = client.leases("kube-node-lease")
        leases.create_many([_lease("h0")])
        errs = leases.renew_many([("h0", 77.0), ("nope", 1.0)])
        assert errs[0] is None and errs[1] and "not found" in errs[1]
        assert leases.get("h0")["spec"]["renewTime"] == 77.0
    finally:
        server.stop()


# ---- 2. fleet batchers -----------------------------------------------------

class _StubKubelet:
    """The slice of Kubelet the batchers consume."""

    def __init__(self, name):
        self.node_name = name
        self.dead = False

    def heartbeat_payload(self):
        return {"conditions": [{"type": "Ready", "status": "True",
                                "lastHeartbeatTime": time.time()}]}

    def _node_object(self):
        return {"kind": "Node", "metadata": {"name": self.node_name},
                "spec": {}, "status": self.heartbeat_payload()}


def test_heartbeat_batcher_shards_flush_and_heal():
    store = ObjectStore()
    client = DirectClient(store)
    stubs = [_StubKubelet(f"hb-{i}") for i in range(8)]
    client.nodes().create_many([s._node_object() for s in stubs])
    b = _HeartbeatBatcher(client, period_s=999.0, shards=3)
    try:
        for s in stubs:
            b.add(s)
        # membership spread over the shards (stable hash, no empty fleet
        # concentration)
        sizes = [len(m) for m in b._members]
        assert sum(sizes) == 8 and max(sizes) < 8
        # a node deleted out from under the fleet heals via bulk
        # re-register; a DEAD member must not resurrect
        client.nodes().delete("hb-0")
        client.nodes().delete("hb-1")
        dead = next(s for s in stubs if s.node_name == "hb-1")
        dead.dead = True
        b.flush_all()
        assert b.items > 0 and b.flushes >= 1
        names = {n["metadata"]["name"] for n in client.nodes().list()}
        assert "hb-0" in names        # healed
        assert "hb-1" not in names    # dead stays dead
        # conditions actually refreshed
        conds = client.nodes().get("hb-2")["status"]["conditions"]
        assert any(c["status"] == "True" for c in conds
                   if c["type"] == "Ready")
    finally:
        b.stop()


def test_heartbeat_heal_retries_after_failed_reregister():
    """A per-item 404 invalidates the member's fingerprint even when the
    re-register itself fails: the NEXT period's heartbeat (not the
    30-sweep refresh backstop) re-encounters the 404 and retries the
    heal."""
    store = ObjectStore()
    client = DirectClient(store)
    s = _StubKubelet("heal-0")
    client.nodes().create(s._node_object())
    b = _HeartbeatBatcher(client, period_s=999.0, shards=1)
    try:
        b.add(s)
        b.flush_all()
        client.nodes().delete("heal-0")
        b._fps.pop("heal-0")  # the node's refresh-backstop slot comes due
        calls = {"n": 0}
        orig = b._reregister

        def flaky(names):
            calls["n"] += 1
            if calls["n"] == 1:
                return  # transient transport failure, swallowed
            orig(names)

        b._reregister = flaky
        b.flush_all()  # 404 seen, fp popped, re-register attempt fails
        assert "heal-0" not in {n["metadata"]["name"]
                                for n in client.nodes().list()}
        b.flush_all()  # fp invalidated -> heartbeat resent -> heal retries
        assert calls["n"] == 2
        assert "heal-0" in {n["metadata"]["name"]
                            for n in client.nodes().list()}
    finally:
        b.stop()


def test_batcher_phase_jitter_spreads_shards():
    store = ObjectStore()
    client = DirectClient(store)
    hb = _HeartbeatBatcher(client, period_s=10.0, shards=4)
    le = _LeaseBatcher(client, period_s=10.0, shards=4, phase=0.5)
    try:
        delays = [hb._phase_delay(i) for i in range(4)]
        assert delays == sorted(delays) and len(set(delays)) == 4
        assert delays[-1] < 10.0  # all within one period
        # sibling batcher interleaves between the heartbeat shards
        assert set(le._phase_delay(i) for i in range(4)) \
            .isdisjoint(set(delays))
    finally:
        hb.stop()
        le.stop()


def test_lease_batcher_creates_missing_then_renews():
    store = ObjectStore()
    client = DirectClient(store)
    stubs = [_StubKubelet(f"lb-{i}") for i in range(4)]
    b = _LeaseBatcher(client, period_s=999.0, shards=2)
    try:
        for s in stubs:
            b.add(s)
        b.flush_all()  # no leases yet: per-item 404s -> bulk create
        leases = client.leases("kube-node-lease")
        created = {ls["metadata"]["name"] for ls in leases.list()}
        assert created == {s.node_name for s in stubs}
        t0 = leases.get("lb-0")["spec"]["renewTime"]
        time.sleep(0.01)
        b.flush_all()  # now they exist: renewed in bulk
        assert leases.get("lb-0")["spec"]["renewTime"] > t0
    finally:
        b.stop()


def test_status_batcher_sharded_newest_wins():
    store = ObjectStore()
    client = DirectClient(store)
    client.pods("default").create_many(
        [make_pod(f"sp-{i}").obj().to_dict() for i in range(6)])
    b = _StatusBatcher(client, flush_s=999.0, shards=3)
    try:
        for i in range(6):
            b.push("default", f"sp-{i}", {"phase": "Pending"})
        # newest status for one pod wins within the un-flushed window
        b.push("default", "sp-0", {"phase": "Running"})
        b.flush()
        phases = {p["metadata"]["name"]: p["status"]["phase"]
                  for p in client.pods("default").list()}
        assert phases["sp-0"] == "Running"
        assert all(phases[f"sp-{i}"] == "Pending" for i in range(1, 6))
        assert b.items == 6  # dedup: 7 pushes, 6 writes
    finally:
        b.stop()


def test_hollow_cluster_batches_heartbeats_and_leases():
    """Fleet integration: HollowCluster routes every liveness path
    through the batchers — nodes turn Ready via nodes/-/status, leases
    appear and renew via leases/-/renew, and a removed node cannot be
    resurrected by an in-flight flush."""
    store = ObjectStore()
    client = DirectClient(store)
    cluster = HollowCluster(client, 6, prefix="fx", heartbeat_period=0.2,
                            publish_status=False).start(wait_sync=5.0)
    try:
        assert len(client.nodes().list()) == 6
        assert wait_until(lambda: (cluster._heartbeats.items > 0
                                   and cluster._leases.items > 0), 5.0)
        assert wait_until(lambda: len(
            client.leases("kube-node-lease").list()) == 6, 5.0)
        rt0 = client.leases("kube-node-lease").get("fx-0")["spec"].get(
            "renewTime")
        assert wait_until(lambda: client.leases("kube-node-lease")
                          .get("fx-0")["spec"].get("renewTime", 0)
                          > (rt0 or 0), 5.0)
        cluster.remove_node("fx-5")
        cluster._heartbeats.flush_all()
        names = {n["metadata"]["name"] for n in client.nodes().list()}
        assert "fx-5" not in names
    finally:
        cluster.stop()


# ---- 2b. batcher outage discipline (apiserver dies mid-flush) -------------

class _OutageDirect(DirectClient):
    """DirectClient whose bulk verbs fail while ``down`` — a scripted
    apiserver outage window. Successful bulk sends are recorded so tests
    can assert EXACTLY what reached the server after the heal."""

    def __init__(self, store):
        super().__init__(store)
        self.down = False
        self.sent: list = []

    def heartbeat_many(self, items):
        if self.down:
            raise ConnectionError("connection refused (outage)")
        self.sent.append(("heartbeat", [n for n, _ in items]))
        return super().heartbeat_many(items)

    def renew_many(self, ns, items):
        if self.down:
            raise ConnectionError("connection refused (outage)")
        self.sent.append(("lease", [n for n, _ in items]))
        return super().renew_many(ns, items)

    def update_status_many(self, items):
        if self.down:
            raise ConnectionError("connection refused (outage)")
        self.sent.append(("status", [f"{ns}/{n}" for ns, n, _ in items]))
        return super().update_status_many(items)


def test_heartbeat_outage_no_duplicates_no_resurrection():
    """The reflush dedup contract: flushes failing across an outage
    window must not duplicate members into the post-heal flush, a member
    removed MID-outage must not reappear, and the reconnect heal clears
    fingerprints so every survivor's status re-asserts promptly."""
    store = ObjectStore()
    client = _OutageDirect(store)
    stubs = [_StubKubelet(f"ob-{i}") for i in range(6)]
    client.nodes().create_many([s._node_object() for s in stubs])
    # refresh_every=1: every sweep is due, so the outage window provably
    # exercises the failed-flush path (thinned fps would skip it)
    b = _HeartbeatBatcher(client, period_s=999.0, shards=1,
                          refresh_every=1)
    try:
        for s in stubs:
            b.add(s)
        b.flush_all()  # healthy baseline
        client.sent.clear()
        client.down = True
        b.flush_all()  # outage sweep 1: fails
        b.flush_all()  # outage sweep 2
        assert b.errors >= 2
        assert b._errs[0] >= 2
        b.remove("ob-3")  # scale-down lands while the server is dead
        client.down = False
        b.flush_all()  # heal
        sent_names = [n for verb, names in client.sent
                      if verb == "heartbeat" for n in names]
        # every LIVE member exactly once — no duplicates from the failed
        # sweeps, no resurrection of the removed member
        assert sorted(sent_names) == sorted(
            s.node_name for s in stubs if s.node_name != "ob-3"), sent_names
        assert b.reconnects >= 1
        assert b._errs[0] == 0
        # the reconnect heal dropped every member's fingerprint, so the
        # next sweeps re-assert full status even under thinning
        assert not any(s.node_name in b._fps for s in stubs)
    finally:
        b.stop()


def test_status_batcher_requeues_newest_wins_bounded_drops():
    store = ObjectStore()
    client = _OutageDirect(store)
    for name in ("p0", "p1"):
        client.pods().create(make_pod(name).obj().to_dict())
    b = _StatusBatcher(client, flush_s=999.0, shards=1)
    try:
        client.down = True
        b.push("default", "p0", {"phase": "Pending"})
        b.flush_all()  # fails -> re-coalesces p0
        assert b.requeued == 1 and b.errors == 1
        # a NEWER status pushed during the outage wins over the requeue
        b.push("default", "p0", {"phase": "Running"})
        b.push("default", "p1", {"phase": "Running"})
        b.flush_all()  # fails again; both re-coalesce
        client.down = False
        b.flush_all()
        assert store.get("Pod", "default", "p0")["status"]["phase"] \
            == "Running"
        assert store.get("Pod", "default", "p1")["status"]["phase"] \
            == "Running"
        # each pod's status landed exactly once post-heal
        sent = [k for verb, keys in client.sent if verb == "status"
                for k in keys]
        assert sorted(sent) == ["default/p0", "default/p1"]
        # the requeue never clobbers a fresher queued payload
        b._queued[0]["default/p2"] = {"phase": "Running"}
        b._requeue(0, [("default/p2", {"phase": "Pending"})])
        assert b._queued[0]["default/p2"] == {"phase": "Running"}
        # bounded: past max_queued the oldest failure is dropped + counted
        b.max_queued = 3
        b._queued[0].clear()
        b._requeue(0, [(f"default/q{i}", {"phase": "Pending"})
                       for i in range(5)])
        assert len(b._queued[0]) == 3 and b.drops == 2
        from kubernetes_tpu.metrics.registry import BATCHER_DROPS
        assert BATCHER_DROPS.get({"batcher": "status"}) >= 2
    finally:
        b.stop()


def test_shard_backoff_grows_jittered_and_capped():
    store = ObjectStore()
    client = DirectClient(store)
    b = _StatusBatcher(client, flush_s=0.1, shards=1)
    try:
        assert b._next_wait(0) == pytest.approx(0.1)
        for errs, ceiling in ((1, 0.2), (3, 0.8), (20, b.backoff_cap_s)):
            b._errs[0] = errs
            waits = [b._next_wait(0) for _ in range(20)]
            assert all(0.5 * ceiling - 1e-9 <= w <= ceiling + 1e-9
                       for w in waits), (errs, waits)
            assert len(set(waits)) > 1  # jittered, not synchronized
    finally:
        b.stop()


def test_lease_flush_never_creates_removed_members_lease():
    """A lease 404 answered after the member was removed must NOT
    re-create the lease: a zombie renewTime would keep node-lifecycle
    treating the deleted node as alive for a whole grace period."""
    store = ObjectStore()
    client = DirectClient(store)
    live, gone = _StubKubelet("lz-live"), _StubKubelet("lz-gone")
    b = _LeaseBatcher(client, period_s=999.0, shards=1)
    try:
        b.add(live)
        b.add(gone)
        b.remove("lz-gone")  # removed while a flush carrying it in flight
        now = time.time()
        assert b._flush([("lz-live", now), ("lz-gone", now)])
        names = {le["metadata"]["name"]
                 for le in client.leases("kube-node-lease").list()}
        assert names == {"lz-live"}
    finally:
        b.stop()


# ---- 3. node lifecycle: leases keep nodes alive ---------------------------

def test_batched_lease_renewal_keeps_node_ready_while_status_lags():
    """node-lifecycle treats a fresh lease renewTime as liveness even
    when the status heartbeat is STALE (upstream: status 5-minutely,
    leases every 10s) — so the fleet's cheap bulk-renew path alone keeps
    nodes untainted; stopping it surfaces unreachable."""
    from kubernetes_tpu.controllers.nodelifecycle import (
        TAINT_UNREACHABLE, NodeLifecycleController)
    store = ObjectStore()
    client = DirectClient(store)
    node = _node_dict("lagging")
    # status heartbeat far in the past, condition still True
    node["status"]["conditions"] = [
        {"type": "Ready", "status": "True",
         "lastHeartbeatTime": time.time() - 3600.0}]
    client.nodes().create(node)
    # the lease starts FRESH (a stale lease + stale status would taint
    # before the first batched renewal could land)
    client.leases("kube-node-lease").create_many(
        [_lease("lagging", renew=time.time())])
    ctrl = NodeLifecycleController(client, grace_period=0.6,
                                   monitor_period=0.1)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        # batched renewals every ~0.2s: the node must stay untainted for
        # well past the grace period
        deadline = time.time() + 1.5
        while time.time() < deadline:
            client.leases("kube-node-lease").renew_many(
                [("lagging", time.time())])
            time.sleep(0.1)
            taints = (client.nodes().get("lagging")["spec"]
                      .get("taints") or [])
            assert not any(t["key"] == TAINT_UNREACHABLE for t in taints)
        # renewals stop -> unreachable within grace + monitor slack
        assert wait_until(lambda: any(
            t["key"] == TAINT_UNREACHABLE
            for t in (client.nodes().get("lagging")["spec"]
                      .get("taints") or [])), 10.0)
    finally:
        ctrl.stop()
        factory.stop_all()


# ---- 4. scheduler informer hygiene ----------------------------------------

def test_scheduler_skips_liveness_only_node_modifieds():
    """A heartbeat/lease-driven node MODIFIED must not wake the
    scheduling loop or append deltas; a real change (allocatable, taints,
    labels, condition STATUS) still processes."""
    from kubernetes_tpu.sched.runner import SchedulerRunner
    store = ObjectStore()
    runner = SchedulerRunner(DirectClient(store))
    try:
        v1 = _node_dict("sk0")
        runner._on_node("ADDED", v1, None)
        gen0 = runner.cache._generation
        skips0 = runner._node_skips

        # heartbeat-only refresh: timestamp + endpoint re-assertion
        v2 = {**v1, "status": {**v1["status"], "conditions": [
            {"type": "Ready", "status": "Unknown",
             "lastHeartbeatTime": time.time()}],
            "daemonEndpoints": {"kubeletEndpoint": {"Port": 12345}}}}
        runner._on_node("MODIFIED", v2, v1)
        assert runner._node_skips == skips0 + 1
        assert runner.cache._generation == gen0

        # condition STATUS transition: NOT liveness-only
        v3 = {**v2, "status": {**v2["status"], "conditions": [
            {"type": "Ready", "status": "False",
             "lastHeartbeatTime": time.time()}]}}
        runner._on_node("MODIFIED", v3, v2)
        assert runner._node_skips == skips0 + 1  # processed, not skipped

        # allocatable change: processed, generation bumps
        v4 = {**v3, "status": {**v3["status"],
                               "allocatable": {"cpu": "8", "pods": "10"}}}
        runner._on_node("MODIFIED", v4, v3)
        assert runner.cache._generation > gen0
        assert runner._node_skips == skips0 + 1
    finally:
        runner.scheduler.close()


@pytest.mark.parametrize("mutate,expect_skip", [
    (lambda n: n["status"]["conditions"].__setitem__(
        0, {"type": "Ready", "status": "Unknown",
            "lastHeartbeatTime": 999.0}), True),
    (lambda n: n["metadata"].setdefault("labels", {})
     .__setitem__("zone", "b"), False),
    (lambda n: n["spec"].__setitem__(
        "taints", [{"key": "k", "effect": "NoSchedule"}]), False),
    (lambda n: n["status"].__setitem__("capacity", {"cpu": "16"}), False),
])
def test_node_liveness_fingerprint_matrix(mutate, expect_skip):
    from kubernetes_tpu.sched.runner import SchedulerRunner
    import json
    old = _node_dict("fp0")
    new = json.loads(json.dumps(old))
    mutate(new)
    assert SchedulerRunner._node_liveness_only(new, old) is expect_skip
