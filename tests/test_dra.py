"""Dynamic Resource Allocation: device classes as synthetic resources on the
shared axis (sched/dra.py), claim lifecycle (controllers/resourceclaim.py),
and allocation-on-bind (sched/runner.py).

Reference: pkg/scheduler/framework/plugins/dynamicresources/ +
pkg/controller/resourceclaim/.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import ResourceClaimController
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.gang import gang_schedule
from kubernetes_tpu.models.schedule_step import evaluate
from kubernetes_tpu.sched.dra import DraCatalog
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def claim(name, cls_name="gpu", count=1, ns="default", alloc_node=None):
    c = {"apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
         "metadata": {"name": name, "namespace": ns},
         "spec": {"devices": {"requests": [
             {"name": "r0", "deviceClassName": cls_name, "count": count}]}}}
    if alloc_node:
        c["status"] = {"allocation": {"nodeName": alloc_node},
                       "reservedFor": []}
    return c


def dev_slice(name, node, cls_name="gpu", count=1):
    return {"apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
            "metadata": {"name": name},
            "spec": {"nodeName": node,
                     "devices": [{"name": "d0", "deviceClassName": cls_name,
                                  "count": count}]}}


def pod_with_claim(name, claim_name):
    p = make_pod(name).req({"cpu": "100m"}).obj()
    p.spec.resource_claims = [{"name": "dev", "resourceClaimName": claim_name}]
    return p


def both_masks(nodes, pods, bound, catalog):
    enc = SnapshotEncoder()
    enc.set_dra(catalog)
    ct, meta = enc.encode_cluster(nodes, bound, pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    res = evaluate(ct, pb, topo_keys=meta.topo_keys)
    tm = np.asarray(res.feasible)[:len(pods), :len(nodes)]
    orc = OracleScheduler(nodes, bound, dra=catalog)
    om = np.asarray([orc.feasible(p)[0] for p in pods])
    np.testing.assert_array_equal(tm, om)
    return tm


def test_catalog_resolution():
    cat = DraCatalog.from_lists(
        claims=[claim("c1", count=2)],
        slices=[dev_slice("s1", "n0", count=4)])
    p = pod_with_claim("p", "c1")
    assert cat.pod_demands(p) == {"dra:gpu": 2}
    assert cat.node_capacity("n0") == {"dra:gpu": 4}
    assert cat.node_capacity("n1") == {}
    assert cat.class_names() == {"gpu"}
    assert cat.pod_allocated_node(p) is None


def test_claim_filters_to_device_nodes():
    nodes = [make_node("gpu-node").capacity({"cpu": "8", "pods": "10"}).obj(),
             make_node("cpu-node").capacity({"cpu": "8", "pods": "10"}).obj()]
    cat = DraCatalog.from_lists(claims=[claim("c1")],
                                slices=[dev_slice("s1", "gpu-node")])
    tm = both_masks(nodes, [pod_with_claim("p", "c1"),
                            make_pod("plain").req({"cpu": "1"}).obj()],
                    [], cat)
    np.testing.assert_array_equal(tm, [[True, False], [True, True]])


def test_devices_in_use_by_bound_pods_count():
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj()]
    bound = pod_with_claim("holder", "c0")
    bound.spec.node_name = "n0"
    cat = DraCatalog.from_lists(
        claims=[claim("c0"), claim("c1")],
        slices=[dev_slice("s1", "n0", count=1)])
    tm = both_masks(nodes, [pod_with_claim("p", "c1")], [bound], cat)
    np.testing.assert_array_equal(tm, [[False]])  # only device already held


def test_allocated_claim_pins_pod():
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj(),
             make_node("n1").capacity({"cpu": "8", "pods": "10"}).obj()]
    cat = DraCatalog.from_lists(
        claims=[claim("c1", alloc_node="n1")],
        slices=[dev_slice("s0", "n0"), dev_slice("s1", "n1")])
    tm = both_masks(nodes, [pod_with_claim("p", "c1")], [], cat)
    np.testing.assert_array_equal(tm, [[False, True]])


def test_gang_contends_for_devices():
    """Two pods, one device: the gang batcher's capacity acceptance must
    serialize them like any other scarce resource."""
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj()]
    cat = DraCatalog.from_lists(
        claims=[claim("c1"), claim("c2")],
        slices=[dev_slice("s1", "n0", count=1)])
    pods = [pod_with_claim("p1", "c1"), pod_with_claim("p2", "c2")]
    enc = SnapshotEncoder()
    enc.set_dra(cat)
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    assignment, _ = gang_schedule(ct, pb, topo_keys=meta.topo_keys)
    placed = [a for a in assignment[:2] if a >= 0]
    assert len(placed) == 1, assignment[:2]


# ------------------------------------------------------------- controller

def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def test_claim_template_instantiation_and_release(client):
    ctrl = ResourceClaimController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.resource("resourceclaimtemplates").create({
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaimTemplate",
            "metadata": {"name": "gpu-tpl", "namespace": "default"},
            "spec": {"spec": {"devices": {"requests": [
                {"name": "r0", "deviceClassName": "gpu", "count": 1}]}}}})
        p = make_pod("worker").obj().to_dict()
        p["spec"]["resourceClaims"] = [
            {"name": "dev", "resourceClaimTemplateName": "gpu-tpl"}]
        client.pods().create(p)
        assert wait_until(lambda: client.resource("resourceclaims")
                          .list())
        got = client.resource("resourceclaims").get("worker-dev")
        assert got["spec"]["devices"]["requests"][0]["deviceClassName"] == "gpu"
        assert got["metadata"]["ownerReferences"][0]["kind"] == "Pod"

        # simulate the scheduler's allocation, then finish the pod: the
        # controller must release the devices
        got["status"] = {"allocation": {"nodeName": "n0"},
                         "reservedFor": [{"resource": "pods",
                                          "name": "worker", "uid": ""}]}
        client.resource("resourceclaims").update_status(got)
        pod = client.pods().get("worker")
        pod["status"] = {"phase": "Succeeded"}
        client.pods().update(pod)
        assert wait_until(lambda: not (client.resource("resourceclaims")
                                       .get("worker-dev").get("status") or {})
                          .get("allocation"))
    finally:
        ctrl.stop()
        factory.stop_all()
