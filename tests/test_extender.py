"""Scheduler extender: HTTP client in the cycle (extender.go HTTPExtender
analog) and the tensor-backed extender server, including a round trip —
our scheduler calling our own extender server.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.sched.extender import (
    ExtenderConfig,
    HTTPExtender,
    extender_binder,
    run_extenders,
)
from kubernetes_tpu.sched.extender_server import TPUExtenderServer
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


class FakeExtender:
    """Scriptable extender endpoint: bans nodes by name, boosts one node,
    records bind calls."""

    def __init__(self, banned=(), boost=None, fail=False):
        self.banned = set(banned)
        self.boost = boost
        self.fail = fail
        self.bound = []
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(n) or b"{}")
                if srv.fail:
                    self.send_error(500)
                    return
                if self.path.endswith("/filter"):
                    names = payload.get("nodenames") or [
                        (x.get("metadata") or {}).get("name", "")
                        for x in ((payload.get("nodes") or {}).get("items") or [])]
                    body = {"nodenames": [x for x in names
                                          if x not in srv.banned]}
                elif self.path.endswith("/prioritize"):
                    names = payload.get("nodenames") or [
                        (x.get("metadata") or {}).get("name", "")
                        for x in ((payload.get("nodes") or {}).get("items") or [])]
                    body = [{"host": x,
                             "score": 10 if x == srv.boost else 0}
                            for x in names]
                elif self.path.endswith("/bind"):
                    srv.bound.append((payload.get("podName"),
                                      payload.get("node")))
                    body = {}
                else:
                    self.send_error(404)
                    return
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _ext(url, **kw):
    return HTTPExtender(ExtenderConfig(
        url_prefix=url, filter_verb=kw.pop("filter_verb", "filter"),
        prioritize_verb=kw.pop("prioritize_verb", ""),
        node_cache_capable=True, **kw))


def test_extender_filter_and_prioritize():
    fake = FakeExtender(banned=["n1"], boost="n2")
    try:
        pods = [make_pod("p0").obj()]
        names = ["n0", "n1", "n2"]
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, filter_verb="filter",
            prioritize_verb="prioritize", weight=2.0,
            node_cache_capable=True))
        mask, scores, errs = run_extenders([ext], pods, names)
        assert not errs
        np.testing.assert_array_equal(mask, [[True, False, True]])
        np.testing.assert_array_equal(scores, [[0.0, 0.0, 20.0]])
    finally:
        fake.stop()


def test_extender_managed_resources_gating():
    fake = FakeExtender(banned=["n0"])
    try:
        cfg = ExtenderConfig(url_prefix=fake.url, filter_verb="filter",
                             node_cache_capable=True,
                             managed_resources=["example.com/tpu"])
        ext = HTTPExtender(cfg)
        plain = make_pod("plain").req({"cpu": "1"}).obj()
        managed = make_pod("managed").req({"example.com/tpu": "1"}).obj()
        mask, _, errs = run_extenders([ext], [plain, managed], ["n0", "n1"])
        assert not errs
        # plain skips the extender; managed loses n0
        np.testing.assert_array_equal(mask, [[True, True], [False, True]])
    finally:
        fake.stop()


def test_extender_error_policies():
    fake = FakeExtender(fail=True)
    try:
        pods = [make_pod("p0").obj()]
        ignorable = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, filter_verb="filter", ignorable=True,
            node_cache_capable=True, timeout_s=2.0))
        mask, _, errs = run_extenders([ignorable], pods, ["n0"])
        assert not errs
        assert mask is None  # skipped entirely, no mask produced
        strict = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, filter_verb="filter",
            node_cache_capable=True, timeout_s=2.0))
        mask, _, errs = run_extenders([strict], pods, ["n0"])
        assert mask is None and errs == {0}  # attempt ERROR, not unschedulable
    finally:
        fake.stop()


def test_extender_duplicate_names_still_filter():
    """A misbehaving extender returning duplicate node names must not defeat
    the veto of the nodes it dropped."""
    pods = [make_pod("p0").obj()]

    class DupExtender(HTTPExtender):
        def filter(self, pod, node_names):
            return ["n0", "n0"]  # drops n1, padded with a duplicate
    ext = DupExtender(ExtenderConfig(url_prefix="http://unused",
                                     filter_verb="filter"))
    mask, _, _errs = run_extenders([ext], pods, ["n0", "n1"])
    np.testing.assert_array_equal(mask, [[True, False]])


def test_prioritize_errors_are_ignored():
    """prioritizeNodesWithExtenders semantics: a failing prioritize never
    fails the pod, even for non-ignorable extenders."""
    fake = FakeExtender(fail=True)
    try:
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, prioritize_verb="prioritize",
            node_cache_capable=True, timeout_s=2.0))
        mask, scores, errs = run_extenders([ext], [make_pod("p0").obj()], ["n0"])
        assert not errs
        assert mask is None and scores is None
    finally:
        fake.stop()


def test_extender_bind_delegation():
    fake = FakeExtender()
    try:
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, bind_verb="bind", node_cache_capable=True))
        bind = extender_binder([ext])
        pod = make_pod("p0").obj()
        assert bind(pod, "n3") is True
        assert fake.bound == [("p0", "n3")]
        # no interested extender -> None (default binder path)
        gated = HTTPExtender(ExtenderConfig(
            url_prefix=fake.url, bind_verb="bind",
            managed_resources=["example.com/tpu"]))
        assert extender_binder([gated])(pod, "n3") is None
    finally:
        fake.stop()


# ------------------------------------------------- scheduler-in-the-loop

def test_scheduler_respects_extender():
    """End-to-end DirectClient scheduler run: the extender bans the only
    otherwise-best node and boosts another; binding goes through the gang
    path with the extender mask."""
    from kubernetes_tpu.sched.cache import SchedulerCache
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler

    fake = FakeExtender(banned=["n0"], boost="n2")
    try:
        cfg = SchedulerConfiguration(extenders=[ExtenderConfig(
            url_prefix=fake.url, filter_verb="filter",
            prioritize_verb="prioritize", weight=100.0,
            node_cache_capable=True)])
        cache = SchedulerCache()
        for i in range(3):
            cache.add_node(make_node(f"n{i}")
                           .capacity({"cpu": "8", "pods": "10"}).obj())
        queue = SchedulingQueue()
        bound = {}
        sched = Scheduler(cfg, cache, queue,
                          binder=lambda p, n: bound.setdefault(p.key, n) or True)
        pod = make_pod("p0").req({"cpu": "1"}).obj()
        queue.add(pod)
        sched.run_once(wait=0.1)
        sched.wait_for_bindings()
        assert bound.get("default/p0") == "n2", bound
    finally:
        fake.stop()


# ------------------------------------------------- tensor-backed server

def test_extender_server_filter_and_prioritize():
    server = TPUExtenderServer().start()
    try:
        nodes = [make_node("big").capacity({"cpu": "8", "pods": "10"}).obj(),
                 make_node("small").capacity({"cpu": "1", "pods": "10"}).obj()]
        server.set_cluster(nodes, [])
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=server.url, filter_verb="filter",
            prioritize_verb="prioritize", node_cache_capable=True,
            timeout_s=60.0))
        pod = make_pod("p0").req({"cpu": "4"}).obj()
        assert ext.filter(pod, ["big", "small"]) == ["big"]
        scores = ext.prioritize(pod, ["big", "small"])
        assert scores["big"] > 0
    finally:
        server.stop()


def test_extender_server_full_node_objects_mode():
    """nodeCacheCapable=False (the default, what a stock kube-scheduler
    sends): full Node objects go out, and the response mirrors the request
    shape with nodes.items."""
    server = TPUExtenderServer().start()
    try:
        nodes = [make_node("big").capacity({"cpu": "8", "pods": "10"}).obj(),
                 make_node("small").capacity({"cpu": "1", "pods": "10"}).obj()]
        server.set_cluster(nodes, [])
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=server.url, filter_verb="filter",
            prioritize_verb="prioritize", timeout_s=60.0))  # NOT cache capable
        pod = make_pod("p0").req({"cpu": "4"}).obj()
        assert ext.filter(pod, nodes) == ["big"]
        scores = ext.prioritize(pod, nodes)
        assert scores["big"] > 0
        # the wire really carried full node objects (allocatable visible)
        raw = ext._args(pod, nodes)
        assert raw["nodes"]["items"][0]["status"]["allocatable"]["cpu"] == "8"
    finally:
        server.stop()


def test_extender_server_round_trip_through_scheduler():
    """Our scheduler consuming our own extender server: the server's filter
    (backed by the tensor pipeline over ITS view of the cluster) vetoes the
    node that is full in the server's cluster state."""
    from kubernetes_tpu.sched.cache import SchedulerCache
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler

    server = TPUExtenderServer().start()
    try:
        nodes = [make_node("n0").capacity({"cpu": "2", "pods": "10"}).obj(),
                 make_node("n1").capacity({"cpu": "2", "pods": "10"}).obj()]
        # in the SERVER's view, n0 is already full
        hog = make_pod("hog").req({"cpu": "2"}).node("n0").obj()
        server.set_cluster(nodes, [hog])

        cfg = SchedulerConfiguration(extenders=[ExtenderConfig(
            url_prefix=server.url, filter_verb="filter",
            node_cache_capable=True, timeout_s=60.0)])
        cache = SchedulerCache()
        for n in nodes:
            cache.add_node(n)  # the local view has BOTH nodes free
        queue = SchedulingQueue()
        bound = {}
        sched = Scheduler(cfg, cache, queue,
                          binder=lambda p, n: bound.setdefault(p.key, n) or True)
        pod = make_pod("p0").req({"cpu": "1"}).obj()
        queue.add(pod)
        sched.run_once(wait=0.1)
        sched.wait_for_bindings()
        assert bound.get("default/p0") == "n1", bound
    finally:
        server.stop()
