"""Store, apiserver, clientset, informers, workqueue, leader election."""

import threading
import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, DirectClient, HTTPClient
from kubernetes_tpu.client.informer import InformerFactory, SharedInformer
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.client.workqueue import RateLimitingQueue, WorkQueue
from kubernetes_tpu.store.apiserver import AdmissionError, APIServer
from kubernetes_tpu.store.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    TooOld,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


# ------------------------------------------------------------------- store

def test_store_crud_and_rv():
    s = ObjectStore()
    pod = make_pod("p1", "ns1").obj().to_dict()
    created = s.create("Pod", pod)
    assert created["metadata"]["resourceVersion"] == "1"
    with pytest.raises(AlreadyExists):
        s.create("Pod", pod)
    got = s.get("Pod", "ns1", "p1")
    got["spec"]["priority"] = 5
    updated = s.update("Pod", got, expect_rv="1")
    assert updated["metadata"]["resourceVersion"] == "2"
    with pytest.raises(Conflict):
        s.update("Pod", got, expect_rv="1")
    items, rv = s.list("Pod")
    assert len(items) == 1 and rv == 2
    s.delete("Pod", "ns1", "p1")
    with pytest.raises(NotFound):
        s.get("Pod", "ns1", "p1")


def test_store_watch_replay_and_live():
    s = ObjectStore()
    s.create("Pod", make_pod("a").obj().to_dict())
    w = s.watch("Pod", since_rv=0)
    s.create("Pod", make_pod("b").obj().to_dict())
    evs = [w.get(0.2), w.get(0.2)]
    assert [e.type for e in evs] == [ADDED, ADDED]
    assert [e.object["metadata"]["name"] for e in evs] == ["a", "b"]
    s.delete("Pod", "default", "a")
    ev = w.get(0.5)
    assert ev.type == DELETED
    w.stop()


def test_store_checkpoint_roundtrip(tmp_path):
    s = ObjectStore()
    s.create("Node", make_node("n1").obj().to_dict())
    s.create("Pod", make_pod("p1").obj().to_dict())
    path = str(tmp_path / "ckpt.json")
    s.save(path)
    s2 = ObjectStore()
    s2.load(path)
    assert s2.get("Node", "", "n1")["metadata"]["name"] == "n1"
    assert s2.resource_version == s.resource_version


# ------------------------------------------------- apiserver + http client

@pytest.fixture(scope="module")
def api():
    server = APIServer().start()
    yield server
    server.stop()


def test_apiserver_pod_lifecycle(api):
    c = HTTPClient(api.url)
    pods = c.pods("prod")
    created = pods.create(make_pod("web", "prod").req({"cpu": "1"}).obj().to_dict())
    assert created["metadata"]["namespace"] == "prod"
    assert pods.get("web")["spec"]["containers"]
    # bind subresource
    pods.bind("web", "node-1")
    assert pods.get("web")["spec"]["nodeName"] == "node-1"
    with pytest.raises(ApiError) as ei:
        pods.bind("web", "node-2")
    assert ei.value.code == 409
    # status subresource
    obj = pods.get("web")
    obj["status"]["phase"] = "Running"
    pods.update_status(obj)
    assert pods.get("web")["status"]["phase"] == "Running"
    # list with selectors
    pods.create(make_pod("db", "prod").label("app", "db").obj().to_dict())
    assert len(pods.list(label_selector="app=db")) == 1
    assert len(pods.list(field_selector="spec.nodeName=node-1")) == 1
    pods.delete("db")
    with pytest.raises(ApiError):
        pods.get("db")


def test_apiserver_watch_stream(api):
    c = HTTPClient(api.url)
    nodes = c.nodes()
    _, rv = nodes.list_rv()
    w = nodes.watch(since_rv=rv)
    nodes.create(make_node("w1").obj().to_dict())
    ev = None
    for _ in range(20):
        ev = w.get(timeout=0.5)
        if ev:
            break
    w.stop()
    assert ev is not None and ev.type == ADDED
    assert ev.object["metadata"]["name"] == "w1"


def test_apiserver_admission(api):
    def deny_privileged(verb, kind, obj):
        if kind == "Pod" and (obj.get("metadata", {}).get("labels") or {}).get("privileged"):
            raise AdmissionError("privileged pods denied")
        return obj

    api.admission.append(deny_privileged)
    try:
        c = HTTPClient(api.url)
        with pytest.raises(ApiError) as ei:
            c.pods().create(make_pod("bad").label("privileged", "true").obj().to_dict())
        assert ei.value.code == 400
    finally:
        api.admission.clear()


def test_apiserver_404_and_healthz(api):
    import urllib.request
    assert urllib.request.urlopen(api.url + "/healthz").read() == b"ok"
    body = urllib.request.urlopen(api.url + "/metrics").read()
    assert b"# TYPE" in body
    c = HTTPClient(api.url)
    with pytest.raises(ApiError) as ei:
        c.pods().get("nope")
    assert ei.value.code == 404


# -------------------------------------------------------------- informers

def test_informer_sync_and_events():
    store = ObjectStore()
    client = DirectClient(store)
    store.create("Pod", make_pod("pre").obj().to_dict())
    events = []
    inf = SharedInformer(client.resource("pods", None),
                         indexers={"node": lambda o: [o.get("spec", {}).get("nodeName", "")]})
    inf.add_event_handler(lambda t, o, old: events.append((t, o["metadata"]["name"])))
    inf.start()
    assert inf.wait_for_cache_sync(5)
    store.create("Pod", make_pod("live").node("n1").obj().to_dict())
    for _ in range(50):
        if ("ADDED", "live") in events:
            break
        time.sleep(0.02)
    assert ("ADDED", "pre") in events and ("ADDED", "live") in events
    assert [o["metadata"]["name"] for o in inf.store.by_index("node", "n1")] == ["live"]
    store.delete("Pod", "default", "live")
    for _ in range(50):
        if any(t == "DELETED" for t, _ in events):
            break
        time.sleep(0.02)
    assert any(t == "DELETED" for t, _ in events)
    inf.stop()


def test_informer_field_selector():
    store = ObjectStore()
    client = DirectClient(store)
    inf = SharedInformer(client.resource("pods", None),
                         field_selector="spec.nodeName=n1")
    inf.start()
    assert inf.wait_for_cache_sync(5)
    store.create("Pod", make_pod("on-n1").node("n1").obj().to_dict())
    store.create("Pod", make_pod("on-n2").node("n2").obj().to_dict())
    time.sleep(0.3)
    names = {o["metadata"]["name"] for o in inf.store.list()}
    assert names == {"on-n1"}
    inf.stop()


# -------------------------------------------------------------- workqueue

def test_workqueue_dedup_and_reprocess():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    assert q.get(0.1) == "a"
    q.add("a")            # re-added while processing
    assert q.get(0.05) is None
    q.done("a")
    assert q.get(0.1) == "a"
    q.done("a")
    q.close()


def test_rate_limited_backoff():
    # FakeClock owns delay expiry: the item CANNOT become due until the
    # test advances time, so "still delayed" is a fact, not a race against
    # the wall clock (the 20ms-delay version flaked whenever the runner
    # stalled longer than the delay between add and get).
    from kubernetes_tpu.utils.clock import FakeClock
    clock = FakeClock(100.0)
    q = RateLimitingQueue(base_delay=5.0, max_delay=30.0, clock=clock)
    q.add_rate_limited("x")
    assert q.get(0.05) is None       # delayed: fake time has not moved
    clock.advance(5.0)
    item = q.get(2.0)                # pump notices fake expiry within ~10ms
    assert item == "x"
    q.done("x")
    assert q.num_requeues("x") == 1
    q.add_rate_limited("x")          # second failure: exponential delay
    clock.advance(5.0)
    assert q.get(0.05) is None       # 10s due now, only 5 elapsed
    clock.advance(5.0)
    assert q.get(2.0) == "x"
    q.done("x")
    q.forget("x")
    assert q.num_requeues("x") == 0
    q.close()


# -------------------------------------------------------- leader election

def test_leader_election_failover():
    store = ObjectStore()
    client = DirectClient(store)
    leases = client.leases()
    a_started = threading.Event()
    b_started = threading.Event()
    cfg_a = LeaderElectionConfig("sched", "A", lease_duration=0.3,
                                 renew_deadline=0.2, retry_period=0.05,
                                 on_started_leading=a_started.set)
    cfg_b = LeaderElectionConfig("sched", "B", lease_duration=0.3,
                                 renew_deadline=0.2, retry_period=0.05,
                                 on_started_leading=b_started.set)
    ea, eb = LeaderElector(leases, cfg_a), LeaderElector(leases, cfg_b)
    stop_a, stop_b = threading.Event(), threading.Event()
    ta = threading.Thread(target=ea.run, args=(stop_a,), daemon=True)
    tb = threading.Thread(target=eb.run, args=(stop_b,), daemon=True)
    ta.start()
    assert a_started.wait(2)
    tb.start()
    time.sleep(0.2)
    assert not eb.is_leader          # A holds the lease
    stop_a.set()                     # A dies; lease expires; B takes over
    assert b_started.wait(3)
    stop_b.set()


def test_store_tooold_is_per_kind(monkeypatch):
    """Global rv churn on one kind must not compact another kind's replay
    window (regression: TooOld used the global rv, so any watcher more than
    a full replay window of total writes behind got a spurious 410 even with
    its kind's history intact)."""
    import kubernetes_tpu.store.store as store_mod
    monkeypatch.setattr(store_mod, "REPLAY_WINDOW", 64)
    s = ObjectStore()
    s.create("Pod", make_pod("p").obj().to_dict())
    for i in range(100):  # > REPLAY_WINDOW writes on an unrelated kind
        s.create("Lease", {"metadata": {"name": f"l{i}", "namespace": "ns"}})
    w = s.watch("Pod", since_rv=0)
    ev = w.get(timeout=1.0)
    w.stop()
    assert ev is not None and ev.type == ADDED
    with pytest.raises(TooOld):
        s.watch("Lease", since_rv=0)


def test_http_watch_namespace_scoped(api):
    """A namespaced HTTP watch must not stream other namespaces' events
    (regression: do_GET discarded the route's namespace for watches)."""
    c = HTTPClient(api.url)
    pods_a = c.pods("ns-a")
    _, rv = pods_a.list_rv()
    w = pods_a.watch(since_rv=rv)
    c.pods("ns-b").create(make_pod("other", "ns-b").obj().to_dict())
    pods_a.create(make_pod("mine", "ns-a").obj().to_dict())
    seen = []
    deadline = time.time() + 5
    while time.time() < deadline and "mine" not in seen:
        ev = w.get(timeout=0.5)
        if ev is not None:
            seen.append(ev.object["metadata"]["name"])
    w.stop()
    assert seen == ["mine"]


def test_leader_elector_survives_transport_errors():
    """Non-ApiError transport failures (URLError/OSError) must count as missed
    renewals, not kill the elector thread (regression: zombie leader)."""
    client = DirectClient(ObjectStore())
    fail = {"on": False}

    def maybe_fail(obj):
        if fail["on"]:
            raise OSError("connection refused")
        return obj

    client.prepend_reactor("*", "leases", maybe_fail)
    started, stopped = [], threading.Event()
    cfg = LeaderElectionConfig(
        "sched-err", "A", lease_duration=0.3, renew_deadline=0.2,
        retry_period=0.05, on_started_leading=lambda: started.append(1),
        on_stopped_leading=stopped.set)
    e = LeaderElector(client.leases(), cfg)
    stop = threading.Event()
    t = threading.Thread(target=e.run, args=(stop,), daemon=True)
    t.start()
    deadline = time.time() + 3
    while time.time() < deadline and not started:
        time.sleep(0.02)
    assert started
    fail["on"] = True                 # transport down: renewals now raise
    assert stopped.wait(3)            # leadership lost, callback fired
    assert t.is_alive()               # ...and the elector thread survived
    assert not e.is_leader
    fail["on"] = False                # transport back: re-acquire
    deadline = time.time() + 3
    while time.time() < deadline and len(started) < 2:
        time.sleep(0.02)
    stop.set()
    assert len(started) == 2


def test_checkpoint_restore_invalidates_live_watchers(tmp_path):
    """load() must close live watch streams: a connected watcher would
    otherwise miss the restore delta (objects absent from the blob never emit
    DELETED) and retain phantoms forever."""
    s = ObjectStore()
    s.create("Pod", make_pod("keep").obj().to_dict())
    path = str(tmp_path / "ckpt.json")
    s.save(path)
    s.create("Pod", make_pod("phantom").obj().to_dict())  # post-checkpoint
    w = s.watch("Pod", since_rv=s.resource_version)
    s.load(path)  # restore: 'phantom' no longer exists
    deadline = time.time() + 2
    while time.time() < deadline and not w.closed:
        w.get(timeout=0.1)
    assert w.closed  # stream invalidated -> consumer relists
    with pytest.raises(TooOld):  # and pre-restore rvs force a relist
        s.watch("Pod", since_rv=0)
