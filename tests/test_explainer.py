"""Decision provenance: the per-filter-output program (models/explain.py)
against the numpy oracle's per-filter verdicts, and the background
explainer's event/ConfigMap/metric surfaces (sched/explainer.py)."""

import random
import time

import numpy as np
import pytest

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.explain import (
    EXPLAIN_FILTERS,
    REASON_TO_FILTER,
    explain_step,
    failed_scheduling_message,
    first_fail,
    reject_histogram,
)
from kubernetes_tpu.sched.oracle import FailReason, OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

from test_filters_parity import random_node, random_pod


def explain_verdicts(nodes, pods, bound=None, enabled=None):
    import jax
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound or [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    verdicts, valid = jax.device_get(
        explain_step(ct, pb, topo_keys=meta.topo_keys, enabled=enabled))
    return first_fail(np.asarray(verdicts),
                      np.asarray(valid))[:len(pods), :len(nodes)]


def assert_explain_parity(nodes, pods, bound=None):
    """Per-(pod, node) first-fail reasons from the batched per-filter
    program must match the oracle's short-circuit verdict EXACTLY."""
    ff = explain_verdicts(nodes, pods, bound)
    orc = OracleScheduler(nodes, bound or [])
    for pi, pod in enumerate(pods):
        mask, reasons = orc.feasible(pod)
        for ni, node in enumerate(nodes):
            got = ff[pi, ni]
            if mask[ni]:
                assert got == -1, (
                    f"{pod.key} on {node.metadata.name}: oracle feasible, "
                    f"tensor rejected by {EXPLAIN_FILTERS[got]}")
            else:
                want = REASON_TO_FILTER[reasons[node.metadata.name]]
                assert got >= 0 and EXPLAIN_FILTERS[got] == want, (
                    f"{pod.key} on {node.metadata.name}: oracle said "
                    f"{want!r}, tensor said "
                    f"{EXPLAIN_FILTERS[got] if got >= 0 else 'feasible'!r}")
    return ff


# ------------------------------------------------------------ golden cases

def test_first_fail_order_matches_oracle_short_circuit():
    # one node failing MANY filters at once: the verdict must be the
    # FIRST in the oracle's check order (unschedulable beats taint beats
    # resources)
    nodes = [make_node("bad").capacity({"cpu": "1", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").unschedulable().obj()]
    pods = [make_pod("p0").req({"cpu": "4"}).obj()]
    ff = assert_explain_parity(nodes, pods)
    assert EXPLAIN_FILTERS[ff[0, 0]] == "NodeUnschedulable"


def test_taint_and_resources_histogram():
    nodes = [make_node("t0").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj(),
             make_node("t1").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj(),
             make_node("small").capacity({"cpu": "1", "pods": "10"}).obj()]
    pods = [make_pod("p0").req({"cpu": "4"}).obj()]
    ff = assert_explain_parity(nodes, pods)
    hist = reject_histogram(ff[0])
    assert hist == {"TaintToleration": 2, "NodeResourcesFit": 1}
    msg = failed_scheduling_message(len(nodes), hist)
    assert msg == ("0/3 nodes are available: 2 node(s) had untolerated "
                   "taint, 1 Insufficient resources.")


def test_message_counts_and_tiebreak_order():
    hist = {"NodeResourcesFit": 2, "TaintToleration": 2, "NodeAffinity": 5}
    msg = failed_scheduling_message(9, hist)
    # counts descending; equal counts in filter-stack order
    assert msg == ("0/9 nodes are available: 5 " + FailReason.AFFINITY
                   + ", 2 " + FailReason.RESOURCES
                   + ", 2 " + FailReason.TAINT + ".")
    assert "became feasible" in failed_scheduling_message(
        3, {"NodeName": 2}, feasible_now=1)
    assert failed_scheduling_message(0, {}) \
        == "0/0 nodes are available: no nodes in the cluster."


def test_relational_filters_explained():
    # spread: 2 replicas already in zone a -> skew violation there
    nodes = [make_node("za").capacity({"cpu": "8", "pods": "10"})
             .label("zone", "a").obj(),
             make_node("zb").capacity({"cpu": "8", "pods": "10"})
             .label("zone", "b").obj()]
    bound = [make_pod(f"b{i}").label("app", "web").node("za").obj()
             for i in range(2)]
    pod = (make_pod("p0").label("app", "web")
           .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj())
    ff = assert_explain_parity(nodes, [pod], bound)
    assert EXPLAIN_FILTERS[ff[0, 0]] == "PodTopologySpread"
    assert ff[0, 1] == -1
    # required anti-affinity to itself via an existing pod
    anti = (make_pod("anti").label("app", "db")
            .pod_affinity("zone", {"app": "db"}, anti=True).obj())
    bound2 = [make_pod("b-db").label("app", "db").node("za").obj()]
    ff2 = assert_explain_parity(nodes, [anti], bound2)
    assert EXPLAIN_FILTERS[ff2[0, 0]] == "InterPodAffinity"


def test_disabled_filters_pass_everywhere():
    nodes = [make_node("t0").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj()]
    pods = [make_pod("p0").req({"cpu": "1"}).obj()]
    enabled = tuple(sorted(set(EXPLAIN_FILTERS) - {"TaintToleration"}))
    ff = explain_verdicts(nodes, pods, enabled=enabled)
    assert ff[0, 0] == -1  # taint filter disabled -> feasible


# ------------------------------------------------------------- fuzz parity

@pytest.mark.parametrize("seed", range(8))
def test_fuzz_explain_parity(seed):
    """Randomized clusters: per-(pod, node) reject reasons from the
    batched per-filter-output program match the numpy oracle exactly."""
    rng = random.Random(1000 + seed)
    n_nodes = rng.randint(1, 12)
    n_bound = rng.randint(0, 8)
    n_pods = rng.randint(1, 10)
    nodes = [random_node(rng, i) for i in range(n_nodes)]
    names = [n.metadata.name for n in nodes]
    bound = []
    for i in range(n_bound):
        p = random_pod(rng, 100 + i, names)
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    pods = [random_pod(rng, i, names) for i in range(n_pods)]
    assert_explain_parity(nodes, pods, bound)


# --------------------------------------------------- explainer (threaded)

class _Recorder:
    def __init__(self):
        self.events = []

    def event(self, obj, type_, reason, message):
        self.events.append((obj.key, type_, reason, message))


def _cluster_cache(nodes):
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    for n in nodes:
        cache.update_node(n)
    return cache


def _mk_explainer(recorder):
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.explainer import SchedulingExplainer
    cfg = SchedulerConfiguration()
    return cfg, SchedulingExplainer(cfg, lambda: recorder)


@pytest.mark.parametrize("level,mode", [("single", "tensor"),
                                        ("oracle", "oracle")])
def test_explainer_thread_verdict(level, mode):
    from kubernetes_tpu.metrics.registry import UNSCHEDULABLE_REASONS
    nodes = [make_node("t0").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj()]
    cache = _cluster_cache(nodes)
    rec = _Recorder()
    cfg, ex = _mk_explainer(rec)
    published = []
    ex.publisher = published.append
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    base = UNSCHEDULABLE_REASONS.get({"filter": "TaintToleration"})
    assert ex.submit(cache, cfg.profiles[0], level, [pod])
    ex.drain()
    exp = ex.explain_of(pod.key)
    assert exp is not None and exp["mode"] == mode
    assert exp["filters"] == {"TaintToleration": 1}
    assert exp["message"] == ("0/1 nodes are available: 1 node(s) had "
                              "untolerated taint.")
    (key, type_, reason, msg) = rec.events[0]
    assert (key, type_, reason) == (pod.key, "Warning", "FailedScheduling")
    assert msg == exp["message"]
    assert UNSCHEDULABLE_REASONS.get({"filter": "TaintToleration"}) \
        == base + 1
    assert published and pod.key in published[-1]
    ex.close()


def test_explainer_throttles_reexplanation():
    nodes = [make_node("n0").capacity({"cpu": "1", "pods": "10"}).obj()]
    cache = _cluster_cache(nodes)
    rec = _Recorder()
    cfg, ex = _mk_explainer(rec)
    pod = make_pod("p0").req({"cpu": "4"}).obj()
    assert ex.submit(cache, cfg.profiles[0], "single", [pod])
    # immediately re-failing the same pod: the explainer still OWNS the
    # event (True) but takes no second sample
    assert ex.submit(cache, cfg.profiles[0], "single", [pod])
    assert ex.samples == 1
    ex.drain()
    ex.close()


def test_explainer_backlog_full_falls_back():
    nodes = [make_node("n0").capacity({"cpu": "1", "pods": "10"}).obj()]
    cache = _cluster_cache(nodes)
    rec = _Recorder()
    cfg, ex = _mk_explainer(rec)
    ex._max_backlog = 0  # every submit sees a full backlog
    pod = make_pod("p0").req({"cpu": "4"}).obj()
    assert not ex.submit(cache, cfg.profiles[0], "single", [pod])
    assert ex.skipped == 1
    ex.close()


def test_explainer_feasible_now_raced_cluster():
    """The cluster moved between the failed cycle and the explanation:
    the re-run finds a feasible node, and says so instead of lying."""
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj()]
    cache = _cluster_cache(nodes)
    rec = _Recorder()
    cfg, ex = _mk_explainer(rec)
    pod = make_pod("p0").req({"cpu": "1"}).obj()  # fits fine NOW
    assert ex.submit(cache, cfg.profiles[0], "single", [pod])
    ex.drain()
    exp = ex.explain_of(pod.key)
    assert exp["feasibleNow"] == 1 and exp["filters"] == {}
    assert "became feasible" in exp["message"]
    ex.close()


def test_oracle_mode_disabled_filter_rejections_become_unjudged():
    """Degraded (oracle) mode cannot skip a profile's disabled filters;
    nodes it rejected ONLY via a disabled filter must come out 'not
    judged' — never blamed on a filter the profile disabled, never an
    empty-cluster message."""
    from kubernetes_tpu.config.types import Profile, SchedulerConfiguration
    from kubernetes_tpu.sched.explainer import SchedulingExplainer
    nodes = [make_node("t0").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj()]
    cache = _cluster_cache(nodes)
    rec = _Recorder()
    cfg = SchedulerConfiguration(profiles=[
        Profile(disabled_filters=["TaintToleration"])])
    ex = SchedulingExplainer(cfg, lambda: rec)
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    assert ex.submit(cache, cfg.profiles[0], "oracle", [pod])
    ex.drain()
    exp = ex.explain_of(pod.key)
    assert exp["mode"] == "oracle"
    assert exp["filters"] == {} and exp["unjudged"] == 1
    assert "TaintToleration" not in exp["message"]
    assert "not judged" in exp["message"]
    assert "no nodes in the cluster" not in exp["message"]
    ex.close()


def test_scheduler_failure_path_routes_through_explainer():
    """Scheduler-level: an unschedulable batch gets the explainer's
    upstream-style event, not the generic one."""
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler
    nodes = [make_node("t0").capacity({"cpu": "8", "pods": "10"})
             .taint("dedicated", "ml", "NoSchedule").obj()]
    cache = _cluster_cache(nodes)
    queue = SchedulingQueue()
    sched = Scheduler(SchedulerConfiguration(), cache, queue,
                      binder=lambda p, n: True)
    rec = _Recorder()
    sched.recorder = rec
    assert sched.explainer is not None
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    queue.add(pod)
    try:
        sched.run_once(wait=0.5)
        sched.explainer.drain()
        assert any(r == "FailedScheduling"
                   and "untolerated taint" in m
                   for _k, _t, r, m in rec.events), rec.events
    finally:
        queue.close()
        sched.close()


def test_score_breakdown_for_scheduled_pod():
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj(),
             make_node("n1").capacity({"cpu": "2", "pods": "10"}).obj()]
    bound = [make_pod("busy").req({"cpu": "1"}).node("n1").obj()]
    rec = _Recorder()
    _cfg, ex = _mk_explainer(rec)
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    pod.spec.node_name = "n0"
    bd = ex.score_breakdown(nodes, bound, pod)
    assert bd["feasible"] == 2 and bd["chosen"] == "n0"
    names = [n for n, _s in bd["top"]]
    assert names[0] == "n0"  # LeastAllocated prefers the empty node
    ex.close()
