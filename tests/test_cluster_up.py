"""Cluster bootstrap (kubeadm analog) + tensor sanity checking.

Reference: cmd/kubeadm (init/join); the sanity module is the tensor path's
race-detector/sanitizer analog (utils/sanity.py).
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.cli.cluster import LocalCluster, join
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.schedule_step import evaluate
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.utils.sanity import (
    check_assignment,
    check_step_result,
    checked_evaluate,
)


def wait_until(fn, timeout=30.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def test_cluster_init_schedules_and_runs_a_deployment():
    with LocalCluster(nodes=2) as cluster:
        client = cluster.client
        client.resource("deployments").create({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c",
                                      "resources": {"requests":
                                                    {"cpu": "100m"}}}]}}}})

        def running():
            pods = [p for p in client.pods().list()
                    if (p["metadata"].get("labels") or {}).get("app") == "web"]
            return (len(pods) == 3
                    and all(p["spec"].get("nodeName") for p in pods)
                    and all((p.get("status") or {}).get("phase") == "Running"
                            for p in pods))
        assert wait_until(running), [
            (p["metadata"]["name"], p["spec"].get("nodeName"),
             (p.get("status") or {}).get("phase"))
            for p in client.pods().list()]


def test_cluster_with_auth_mints_admin_credential():
    """auth=True: the control plane runs with a minted system:masters token
    (kubeadm admin.conf analog) — writes succeed, anonymous writes don't."""
    from kubernetes_tpu.client.clientset import ApiError, HTTPClient
    with LocalCluster(nodes=1, auth=True) as cluster:
        assert cluster.admin_token
        assert wait_until(lambda: cluster.client.nodes().list())
        anonymous = HTTPClient(cluster.server.url)
        with pytest.raises(ApiError) as e:
            anonymous.pods().create({"apiVersion": "v1", "kind": "Pod",
                                     "metadata": {"name": "x",
                                                  "namespace": "default"},
                                     "spec": {"containers": [{"name": "c"}]}})
        assert e.value.code == 403
        # the admin client CAN write
        cluster.client.pods().create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "ok", "namespace": "default"},
            "spec": {"containers": [{"name": "c",
                "resources": {"requests": {"cpu": "100m"}}}]}})
        assert wait_until(lambda: cluster.client.pods().get("ok")
                          ["spec"].get("nodeName"))


def test_join_adds_schedulable_nodes():
    with LocalCluster(nodes=1) as cluster:
        workers = join(cluster.server.url, n=2, name_prefix="joined")
        try:
            assert wait_until(lambda: len(cluster.client.nodes().list()) == 3)
            names = {n["metadata"]["name"]
                     for n in cluster.client.nodes().list()}
            assert {"joined-0", "joined-1"} <= names
        finally:
            for w in workers:
                w.stop()


# ------------------------------------------------------------- sanity gate

def _small_cluster():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4", "pods": "10"})
             .label("zone", f"z{i % 2}").obj() for i in range(4)]
    pods = [make_pod(f"p{i}").req({"cpu": "1"})
            .label("app", "web")
            .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
            for i in range(3)]
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    return ct, pb, meta, len(nodes)


def test_step_result_invariants_clean():
    ct, pb, meta, n = _small_cluster()
    res = evaluate(ct, pb, topo_keys=meta.topo_keys)
    assert check_step_result(res, n) == []
    assert check_assignment(np.asarray(res.choice), n + 10) == []
    assert check_assignment(np.asarray([n + 10]), n) != []


def test_checked_evaluate_passes_on_healthy_input():
    ct, pb, meta, _ = _small_cluster()
    res = checked_evaluate(ct, pb, topo_keys=meta.topo_keys)
    assert bool(np.asarray(res.assigned).any())


def test_check_step_result_flags_nan():
    ct, pb, meta, n = _small_cluster()
    res = evaluate(ct, pb, topo_keys=meta.topo_keys)
    poisoned = res.replace(scores=np.asarray(res.scores) * np.nan)
    assert any("NaN" in p for p in check_step_result(poisoned, n))
