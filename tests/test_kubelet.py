"""Kubelet: fake runtime, PLEG, pod workers, hollow node, and the full
cluster slice (controllers + scheduler + kubelet all reconciling)."""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubelet import FakeRuntime, GenericPLEG, HollowNode, PodWorkers
from kubernetes_tpu.kubelet.pleg import CONTAINER_DIED, CONTAINER_STARTED
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_pod


def wait_until(fn, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


# ------------------------------------------------------------ fake runtime

def test_fake_runtime_lifecycle():
    rt = FakeRuntime(exit_after=0.05)
    rt.run_pod_sandbox("u1", "p", "default")
    rt.create_container("u1", "c", "img")
    rt.start_container("u1", "c")
    sb = rt.get_sandbox("u1")
    assert sb.containers["c"].state == "RUNNING"
    assert wait_until(lambda: rt.get_sandbox("u1").containers["c"].state == "EXITED")
    assert rt.get_sandbox("u1").containers["c"].exit_code == 0
    rt.stop_pod_sandbox("u1")
    assert rt.get_sandbox("u1") is None


def test_pleg_emits_start_and_die():
    rt = FakeRuntime(exit_after=0.05)
    pleg = GenericPLEG(rt, relist_period=0.02)
    rt.run_pod_sandbox("u1", "p", "default")
    rt.create_container("u1", "c", "img")
    rt.start_container("u1", "c")
    pleg.start()
    try:
        evs = []

        def saw_both():
            while not pleg.events.empty():
                evs.append(pleg.events.get_nowait())
            types = [e.type for e in evs]
            return CONTAINER_STARTED in types and CONTAINER_DIED in types
        assert wait_until(saw_both)
    finally:
        pleg.stop()


def test_pod_workers_serialize_per_pod():
    import threading
    seen = []
    lock = threading.Lock()
    active = {"u1": 0}
    overlap = []

    def sync(uid, pod):
        with lock:
            active[uid] = active.get(uid, 0) + 1
            if active[uid] > 1:
                overlap.append(uid)
        time.sleep(0.01)
        with lock:
            active[uid] -= 1
            seen.append((uid, pod and pod.get("v")))

    w = PodWorkers(sync)
    for v in range(5):
        w.update_pod("u1", {"v": v})
    w.update_pod("u2", {"v": 99})
    assert wait_until(lambda: ("u2", 99) in seen and any(u == "u1" for u, _ in seen))
    time.sleep(0.05)
    assert not overlap  # same-pod syncs never overlapped
    # latest-wins coalescing: not all 5 u1 updates ran, but the last did
    assert ("u1", 4) in seen
    w.stop()


# ------------------------------------------------------------- hollow node

def test_kubelet_runs_bound_pod_and_reports_status(client):
    node = HollowNode(client, "knode-a").start()
    try:
        assert wait_until(lambda: any(n["metadata"]["name"] == "knode-a"
                                      for n in client.nodes().list()))
        pod = make_pod("p1").node("knode-a").obj().to_dict()
        created = client.pods().create(pod)

        def running():
            p = client.pods().get("p1")
            st = p.get("status") or {}
            return (st.get("phase") == "Running" and st.get("podIP")
                    and any(c.get("type") == "Ready" and c.get("status") == "True"
                            for c in st.get("conditions") or []))
        assert wait_until(running)
        # deletion tears the sandbox down
        uid = created["metadata"]["uid"]
        client.pods().delete("p1")
        assert wait_until(lambda: node.kubelet.runtime.get_sandbox(uid) is None)
    finally:
        node.stop()


def test_kubelet_heartbeat_updates_ready_condition(client):
    node = HollowNode(client, "knode-hb", heartbeat_period=0.1).start()
    try:
        def hb():
            ns = [n for n in client.nodes().list()
                  if n["metadata"]["name"] == "knode-hb"]
            if not ns:
                return 0
            for c in ns[0]["status"].get("conditions") or []:
                if c.get("type") == "Ready":
                    return float(c.get("lastHeartbeatTime", 0))
            return 0
        t1 = 0

        def beat_advanced():
            nonlocal t1
            cur = hb()
            if not t1:
                t1 = cur
                return False
            return cur > t1
        assert wait_until(beat_advanced)
    finally:
        node.stop()


def test_kubelet_completes_job_pod(client):
    node = HollowNode(client, "knode-job", exit_after=0.1).start()
    try:
        pod = make_pod("once").node("knode-job").obj().to_dict()
        pod["spec"]["restartPolicy"] = "Never"
        client.pods().create(pod)
        assert wait_until(lambda: client.pods().get("once")
                          .get("status", {}).get("phase") == "Succeeded")
    finally:
        node.stop()


def test_kubelet_restarts_on_always_policy(client):
    node = HollowNode(client, "knode-rs", exit_after=0.05).start()
    try:
        pod = make_pod("daemonish").node("knode-rs").obj().to_dict()
        created = client.pods().create(pod)
        uid = created["metadata"]["uid"]

        def restarted():
            sb = node.kubelet.runtime.get_sandbox(uid)
            return sb is not None and any(c.restart_count >= 1
                                          for c in sb.containers.values())
        assert wait_until(restarted)
        # phase must never settle on Succeeded under Always
        assert client.pods().get("daemonish")["status"]["phase"] != "Succeeded"
    finally:
        node.stop()


# ------------------------------------------------- full cluster end-to-end

def test_full_cluster_deployment_to_running(client):
    """deployment -> replicaset -> pods -> scheduler binds -> kubelets run ->
    status flows back up to deployment.readyReplicas, no hand-faking."""
    nodes = [HollowNode(client, f"w{i}").start() for i in range(2)]
    mgr = ControllerManager(client, resync_period=0.3).start()
    sched = SchedulerRunner(client).start()
    try:
        client.resource("deployments").create({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "app", "namespace": "default"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "app"}},
                     "template": {"metadata": {"labels": {"app": "app"}},
                                  "spec": {"containers": [
                                      {"name": "c", "image": "img",
                                       "resources": {"requests": {"cpu": "100m"}}}]}}},
            "status": {},
        })
        assert wait_until(
            lambda: client.resource("deployments").get("app")
            .get("status", {}).get("readyReplicas") == 3, timeout=30.0)
        bound = [p["spec"].get("nodeName") for p in client.pods().list()]
        assert all(b in ("w0", "w1") for b in bound)
        # service gets endpoints with real kubelet-assigned IPs
        client.services().create({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "app", "namespace": "default"},
            "spec": {"selector": {"app": "app"}, "ports": [{"port": 80}]}})

        def eps():
            try:
                ep = client.endpoints().get("app")
            except Exception:
                return []
            return [a["ip"] for s in ep.get("subsets") or [] for a in s.get("addresses", [])]
        assert wait_until(lambda: len(eps()) == 3, timeout=15.0)
        assert all(ip.startswith("10.") for ip in eps())
    finally:
        sched.stop()
        mgr.stop()
        for n in nodes:
            n.stop()


def test_full_cluster_job_completion(client):
    node = HollowNode(client, "jw0", exit_after=0.1).start()
    mgr = ControllerManager(client, resync_period=0.3).start()
    sched = SchedulerRunner(client).start()
    try:
        client.resource("jobs").create({
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": "batch", "namespace": "default"},
            "spec": {"parallelism": 2, "completions": 3,
                     "template": {"metadata": {"labels": {"job": "batch"}},
                                  "spec": {"restartPolicy": "Never",
                                           "containers": [{"name": "c"}]}}},
            "status": {},
        })

        def complete():
            j = client.resource("jobs").get("batch")
            return any(c.get("type") == "Complete" and c.get("status") == "True"
                       for c in j.get("status", {}).get("conditions", []))
        assert wait_until(complete, timeout=30.0)
        assert client.resource("jobs").get("batch")["status"]["succeeded"] == 3
    finally:
        sched.stop()
        mgr.stop()
        node.stop()
