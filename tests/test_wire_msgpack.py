"""Binary wire format (msgpack) negotiation + the informer's fast-confirm
path.

The reference negotiates JSON vs protobuf per request via Content-Type /
Accept (``apimachinery/pkg/runtime/serializer/negotiation``); here the binary
format is msgpack, the default for HTTPClient, with JSON interop preserved —
a JSON client and a msgpack client against the same server must observe
identical state. Watch streams negotiate the same way (msgpack frames with a
nil heartbeat vs newline-JSON lines).
"""

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture()
def api():
    server = APIServer().start()
    yield server
    server.stop()


def test_msgpack_crud_roundtrip(api):
    c = HTTPClient(api.url)  # msgpack by default
    assert c._mp is not None, "msgpack should be the default wire format"
    c.nodes().create(make_node("n0").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj().to_dict())
    c.pods("default").create(
        make_pod("a").req({"cpu": "100m"}).obj().to_dict())
    got = c.pods("default").get("a")
    assert got["metadata"]["name"] == "a"
    assert got["spec"]["containers"][0]["resources"]["requests"]["cpu"] \
        == "100m"
    # errors carry Status payloads in the negotiated format too
    with pytest.raises(ApiError) as ei:
        c.pods("default").get("missing")
    assert ei.value.code == 404 and ei.value.reason == "NotFound"


def test_json_and_msgpack_clients_interoperate(api):
    mp, js = HTTPClient(api.url), HTTPClient(api.url, wire="json")
    assert js._mp is None
    mp.pods("default").create(make_pod("frm-mp").obj().to_dict())
    js.pods("default").create(make_pod("frm-js").obj().to_dict())
    # each sees the other's writes, identical shape
    a = js.pods("default").get("frm-mp")
    b = mp.pods("default").get("frm-js")
    assert a["metadata"]["name"] == "frm-mp"
    assert b["metadata"]["name"] == "frm-js"
    assert mp.pods("default").get("frm-js") == js.pods("default").get("frm-js")


@pytest.mark.parametrize("wire", ["msgpack", "json"])
def test_watch_stream_formats(api, wire):
    c = HTTPClient(api.url, wire=wire)
    _, rv = c.pods("default").list_rv()
    w = c.pods("default").watch(since_rv=rv)
    seed = HTTPClient(api.url, wire="json" if wire == "msgpack" else "msgpack")
    seed.pods("default").create_many(
        [make_pod(f"w{i}").obj().to_dict() for i in range(5)])
    seen = []
    import time
    deadline = time.time() + 10.0
    while len(seen) < 5 and time.time() < deadline:
        ev = w.get(timeout=1.0)
        if ev is not None:
            seen.append(ev)
    assert [e.object["metadata"]["name"] for e in seen] == \
        [f"w{i}" for i in range(5)]
    assert all(e.type == "ADDED" for e in seen)
    w.stop()


def test_msgpack_bulk_create_and_bind(api):
    c = HTTPClient(api.url)
    c.nodes().create_many([make_node(f"n{i}").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj().to_dict()
        for i in range(2)])
    created = c.pods("default").create_many(
        [make_pod(f"b{i}").req({"cpu": "100m"}).obj().to_dict()
         for i in range(4)])
    assert all(o["metadata"].get("resourceVersion") for o in created)
    errs = c.pods("default").bind_many(
        [("default", f"b{i}", f"n{i % 2}") for i in range(4)])
    assert errs == [None] * 4
    assert c.pods("default").get("b3")["spec"]["nodeName"] == "n1"


def test_cache_confirm_fast_path():
    """confirm() promotes a matching assumed pod without a gen bump;
    mismatches fall back (return False) so add_pod handles them."""
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    pod = make_pod("x").req({"cpu": "100m"}).obj()
    cache.assume(pod, "n1")
    gen0 = cache.delta_info()[0]
    # wrong node: no promotion
    assert not cache.confirm(pod.key, "n2", dict(pod.metadata.labels))
    # right node + labels: promoted to bound, encoding-neutral
    assert cache.confirm(pod.key, "n1", dict(pod.metadata.labels))
    assert cache.is_bound(pod.key)
    assert cache.delta_info()[0] == gen0
    # nothing assumed anymore: second confirm is a no-op fallback
    assert not cache.confirm(pod.key, "n1", dict(pod.metadata.labels))


def test_cache_confirm_spec_guard():
    """The wire-shaped bind event (to_dict + nodeName, exactly what the
    server emits) passes the spec guard; a spec that changed since the
    assume (e.g. a tolerations PUT racing the bind) is refused so the
    fallback add_pod can install the fresh object."""
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    pod = make_pod("x").req({"cpu": "100m"}).obj()
    wire = pod.to_dict()
    wire["spec"]["nodeName"] = "n1"
    cache.assume(pod, "n1")
    changed = {**wire["spec"], "tolerations": [{"key": "k", "operator":
                                                "Exists"}]}
    assert not cache.confirm(pod.key, "n1", dict(pod.metadata.labels),
                             spec=changed)
    assert cache.confirm(pod.key, "n1", dict(pod.metadata.labels),
                         spec=wire["spec"])
    assert cache.is_bound(pod.key)


def test_runner_fast_confirm_via_watch(api):
    """End-to-end: the runner's informer confirms its own binding from the
    raw watch dict (no stale queue entry, pod bound in cache)."""
    import time
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner

    seed = HTTPClient(api.url)
    seed.nodes().create(make_node("n0").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj().to_dict())
    runner = SchedulerRunner(HTTPClient(api.url),
                             SchedulerConfiguration(batch_size=4))
    runner.start()
    try:
        seed.pods("default").create(
            make_pod("fc").req({"cpu": "100m"}).obj().to_dict())
        deadline = time.time() + 30.0
        while time.time() < deadline:
            obj = seed.pods("default").get("fc")
            if obj["spec"].get("nodeName"):
                break
            time.sleep(0.05)
        assert obj["spec"].get("nodeName") == "n0"
        deadline = time.time() + 10.0
        while time.time() < deadline and not runner.cache.is_bound("default/fc"):
            time.sleep(0.05)
        assert runner.cache.is_bound("default/fc")
    finally:
        runner.stop()


def test_msgpack_client_downgrades_against_json_only_server(monkeypatch):
    """A server without msgpack must answer a binary body with the 400 the
    client's downgrade probe keys on — then the client permanently falls
    back to the JSON wire and the request succeeds."""
    import kubernetes_tpu.store.apiserver as apiserver_mod
    monkeypatch.setattr(apiserver_mod, "_msgpack", None)
    server = APIServer().start()
    try:
        c = HTTPClient(server.url)  # msgpack default
        assert c._mp is not None
        c.pods("default").create(make_pod("dg").obj().to_dict())
        assert c._mp is None  # downgraded after the probe
        assert c.pods("default").get("dg")["metadata"]["name"] == "dg"
    finally:
        server.stop()
