"""Device-resident drain context under churn: delta-log patches.

Reference: the incremental half of ``Cache.UpdateSnapshot``
(``pkg/scheduler/internal/cache/cache.go`` — per-node generations so churn
moves only what changed) and scheduler_perf's churn op
(``test/integration/scheduler_perf/scheduler_perf.go`` churnOp): upstream
sustains its thresholds while nodes and pods recycle through the API.

Here the analog is sharper: the fused drain keeps the cluster encoding in
HBM, and node ADD/REMOVE, foreign pod deletes/rebinds, and preemption
nominee reservations must be applied as DEVICE-SIDE PATCHES
(encode/patch.py -> models/gang.apply_ctx_patch) without dropping the
context — the round-4 failure mode was context death on every foreign
delta, collapsing to a full re-encode + re-upload per pop.
"""

import numpy as np
import pytest

from kubernetes_tpu.config.types import Profile, SchedulerConfiguration
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _sched(nodes, batch_size=4, drain_batches=2):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    cfg = SchedulerConfiguration(batch_size=batch_size,
                                 max_drain_batches=drain_batches)
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, cache, queue, log


def _nodes(n, cpu="4", prefix="n"):
    return [make_node(f"{prefix}{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "32"})
            .obj() for i in range(n)]


def _drain(sched, queue, pods, rounds=6):
    """Push pods, run until the pipeline fully resolves."""
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if sched._pending_drain is None and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()  # binder log assertions must not race
    return bound


def _arm(sched, slot_headroom=64):
    """Arm the drain context the way the product does (warm_drain): compile
    the fused drain + patch program and stage the resident encoding with
    enough slot headroom for the test's pods."""
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(sched.cfg.batch_size)]
    assert sched.warm_drain(warm, slot_headroom=slot_headroom), \
        "drain context failed to arm"
    return sched._drain_ctx


def test_ctx_survives_pod_delete():
    """A foreign pod delete (e.g. churn teardown) patches the resident
    encoding — the context object survives and the freed capacity is
    immediately schedulable."""
    sched, cache, queue, log = _sched(_nodes(2, cpu="1"))
    ctx = _arm(sched)
    # fill the cluster: one 600m pod per 1-cpu node
    fillers = [make_pod(f"f{i}").req({"cpu": "600m"}).obj() for i in range(2)]
    assert _drain(sched, queue, fillers) == 2
    assert sched._drain_ctx is ctx
    # cluster full: one more 600m pod cannot fit
    assert _drain(sched, queue,
                  [make_pod("nofit").req({"cpu": "600m"}).obj()]) == 0
    # a foreign delete frees one filler's capacity
    cache.remove_pod("default/f0")
    got = _drain(sched, queue,
                 [make_pod("refit").req({"cpu": "600m"}).obj()])
    assert got == 1, "freed capacity not visible after device patch"
    assert sched._drain_ctx is ctx, "context died on a patchable delete"


def test_ctx_survives_node_add():
    """A node ADD patches into a free node row: pods land on the new node
    without a context rebuild."""
    sched, cache, queue, log = _sched(_nodes(2, cpu="1"))
    ctx = _arm(sched)
    # saturate the two original nodes
    assert _drain(sched, queue, [make_pod(f"s{i}").req({"cpu": "700m"}).obj()
                                 for i in range(2)]) == 2
    assert _drain(sched, queue,
                  [make_pod("wait").req({"cpu": "900m"}).obj()]) == 0
    # new node arrives (churn): only place with room
    cache.add_node(make_node("fresh")
                   .capacity({"cpu": "4", "memory": "8Gi", "pods": "32"})
                   .obj())
    got = _drain(sched, queue,
                 [make_pod("landed").req({"cpu": "900m"}).obj()])
    assert got == 1
    assert ("landed", "fresh") in log, log
    assert sched._drain_ctx is ctx, "context died on a node add"


def test_ctx_survives_node_delete():
    """A node REMOVE invalidates its row: nothing schedules there anymore,
    context intact."""
    sched, cache, queue, log = _sched(_nodes(3, cpu="2"))
    ctx = _arm(sched)
    cache.remove_node("n1")
    got = _drain(sched, queue, [make_pod(f"p{i}").req({"cpu": "100m"}).obj()
                                for i in range(6)])
    assert got == 6
    assert not any(node == "n1" for (_name, node) in log[-6:]), log[-6:]
    assert sched._drain_ctx is ctx, "context died on a node delete"


def test_ctx_survives_recreate_churn_cycle():
    """The scheduler_perf churn shape: node+pod create/delete every cycle.
    The context must survive the whole storm (zero rebuilds) and every
    measured pod still binds."""
    sched, cache, queue, log = _sched(_nodes(4))
    ctx = _arm(sched)
    for i in range(6):
        cache.add_node(make_node(f"churn-n{i}")
                       .capacity({"cpu": "2", "memory": "4Gi", "pods": "8"})
                       .obj())
        if i >= 2:
            cache.remove_node(f"churn-n{i-2}")
            cache.remove_pod(f"default/m{i-2}")
        got = _drain(sched, queue,
                     [make_pod(f"m{i}").req({"cpu": "100m"}).obj()])
        assert got == 1, f"cycle {i} lost its pod"
        assert sched._drain_ctx is ctx, f"context rebuilt at cycle {i}"


def test_ctx_patch_parity_with_rebuild():
    """After a patch storm, placements must equal a fresh scheduler built
    from the same cache state (the patched encoding is not an
    approximation)."""
    spec = [("a", "1"), ("b", "2"), ("c", "1")]
    sched, cache, queue, log = _sched(_nodes(4, cpu="4"))
    _arm(sched)
    # place some pods, then churn: drop one node, add three, delete a pod
    seed = [make_pod(f"s{i}").req({"cpu": "1"}).obj() for i in range(4)]
    assert _drain(sched, queue, seed) == 4
    cache.remove_node("n2")
    for name, cpu in spec:
        cache.add_node(make_node(f"x-{name}")
                       .capacity({"cpu": cpu, "memory": "4Gi", "pods": "8"})
                       .obj())
    cache.remove_pod("default/s1")
    # surviving bound pods BEFORE the probe, for the fresh reconstruction
    survivors = [(p, p.spec.node_name)
                 for p, _dl in list(cache._assumed.values())]
    probe = [make_pod(f"q{i}").req({"cpu": "1"}).obj() for i in range(5)]
    got = _drain(sched, queue, probe)

    # fresh scheduler over the identical surviving state
    nodes2 = [make_node(f"n{i}")
              .capacity({"cpu": "4", "memory": "8Gi", "pods": "32"}).obj()
              for i in (0, 1, 3)]
    nodes2 += [make_node(f"x-{name}")
               .capacity({"cpu": cpu, "memory": "4Gi", "pods": "8"}).obj()
               for name, cpu in spec]
    sched2, cache2, queue2, log2 = _sched(nodes2)
    for p, node in survivors:
        cache2.assume(p, node)
    probe2 = [make_pod(f"q{i}").req({"cpu": "1"}).obj() for i in range(5)]
    got2 = _drain(sched2, queue2, probe2)
    assert got == got2 == 5
    # score-equivalence, not placement-equality (SURVEY §7: tie-breaks hash
    # the node ROW index, which renumbers across a rebuild): the multiset of
    # chosen nodes — i.e. the resulting load distribution — must match.
    assert sorted(n for _p, n in log[-got:]) \
        == sorted(n for _p, n in log2[-got2:]), (log[-got:], log2[-got2:])


def test_ctx_resident_nominee_reservation():
    """Nominee reservations patch into the resident nom tensors: a LOWER
    priority pod cannot take the reserved capacity, a higher one can, and
    the context survives the whole exchange (round-4 weak #3: any live
    nominee dropped the drain context)."""
    import time
    sched, cache, queue, log = _sched(_nodes(1, cpu="2"))
    ctx = _arm(sched)
    # reserve 1.5 cpu for a priority-50 nominee on n0
    nominee = make_pod("nom").req({"cpu": "1500m"}).priority(50).obj()
    sched._nominated["preempt/nom"] = ("n0", 50, nominee, time.time())
    # lower-priority pod wanting 1 cpu: only ~1.6 free minus 1.5 reserved
    low = make_pod("low").req({"cpu": "1"}).priority(1).obj()
    assert _drain(sched, queue, [low]) == 0, \
        "low-priority pod stole a nominee's reservation"
    assert sched._drain_ctx is ctx, "context died on nominee overlay"
    # higher-priority pod ignores the reservation
    high = make_pod("high").req({"cpu": "1"}).priority(100).obj()
    assert _drain(sched, queue, [high]) == 1
    assert sched._drain_ctx is ctx


def test_ctx_rebuilds_on_unpatchable_delta():
    """A structural delta (new volume catalog state) must still fall back
    to a rebuild — patches are an optimization, not a semantics fork."""
    sched, cache, queue, log = _sched(_nodes(2))
    ctx = _arm(sched)
    cache.update_volume_object(
        "StorageClass", {"kind": "StorageClass",
                         "metadata": {"name": "fast"},
                         "provisioner": "x",
                         "volumeBindingMode": "WaitForFirstConsumer"})
    got = _drain(sched, queue, [make_pod("after").req({"cpu": "100m"}).obj()])
    assert got == 1
    assert sched._drain_ctx is not ctx, \
        "context survived a structural (full) delta it cannot patch"
