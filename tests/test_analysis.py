"""ktpu-lint unit tests — fixture snippets per rule.

Every rule gets: one fixture proving it FIRES, one proving the clean form
passes, and one proving the reasoned suppression comment works (the
acceptance contract of ISSUE 15). Plus the meta rule (reasonless disable =
KTL000 + no suppression), fingerprint stability under unrelated edits, and
the baseline round-trip.

Fixtures are written under a ``kubernetes_tpu/`` tmp directory so
path-scoped rules (KTL003's clock-disciplined trees, KTL005's whitelists,
KTL007's registry path) see the same repo-relative shapes they see in the
real package.
"""

from __future__ import annotations

import os
import textwrap

import pytest

from kubernetes_tpu.analysis import baseline as baseline_mod
from kubernetes_tpu.analysis.engine import run_analysis


def lint(tmp_path, files: dict[str, str]):
    """Write {relpath-under-kubernetes_tpu: source} fixtures and run the
    analyzer; -> list of Finding."""
    root = tmp_path / "kubernetes_tpu"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_analysis(str(root))


def rules_of(findings):
    return [f.rule for f in findings]


# ---- KTL001 guarded-by -----------------------------------------------------

GUARDED = """
    import threading

    class Batcher:
        def __init__(self):
            self._lock = threading.Lock()
            self.flushes = 0  # guarded by: self._lock

        def bump(self):
            self.flushes += 1{suffix}

        def bump_locked_ok(self):
            with self._lock:
                self.flushes += 1
"""


def test_ktl001_fires_on_unlocked_access(tmp_path):
    found = lint(tmp_path, {"sched/b.py": GUARDED.format(suffix="")})
    assert rules_of(found) == ["KTL001"]
    assert "self.flushes" in found[0].message


def test_ktl001_clean_under_lock(tmp_path):
    src = GUARDED.format(suffix="")
    src = src.replace("            self.flushes += 1\n\n",
                      "            with self._lock:\n"
                      "                self.flushes += 1\n\n", 1)
    assert lint(tmp_path, {"sched/b.py": src}) == []


def test_ktl001_suppression_with_reason(tmp_path):
    found = lint(tmp_path, {"sched/b.py": GUARDED.format(
        suffix="  # ktpu-lint: disable=KTL001 -- caller holds the lock")})
    assert found == []


def test_ktl001_locked_suffix_and_init_exempt(tmp_path):
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0  # guarded by: self._lock
            self.n += 1   # __init__ constructs before sharing

        def _bump_locked(self):
            self.n += 1   # *_locked convention: caller holds the lock
    """
    assert lint(tmp_path, {"sched/c.py": src}) == []


def test_ktl001_manual_acquire_counts(tmp_path):
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0  # guarded by: self._lock

        def try_bump(self):
            if not self._lock.acquire(blocking=False):
                return
            try:
                self.n += 1
            finally:
                self._lock.release()
    """
    assert lint(tmp_path, {"sched/c.py": src}) == []


def test_ktl001_per_shard_lock_array(tmp_path):
    src = """
    import threading

    class Sharded:
        def __init__(self, k):
            self._locks = [threading.Lock() for _ in range(k)]
            self._members = [dict() for _ in range(k)]  # guarded by: self._locks[i]

        def add(self, i, name, v):
            with self._locks[i]:
                self._members[i][name] = v

        def bad(self, i):
            return len(self._members[i])
    """
    found = lint(tmp_path, {"kubelet/s.py": src})
    assert rules_of(found) == ["KTL001"]
    assert found[0].line > 10  # the unlocked read, not the locked write


def test_ktl001_module_counter(tmp_path):
    src = """
    import threading

    TOTAL = 0
    _LOCK = threading.Lock()

    def bad():
        global TOTAL
        TOTAL += 1

    def good():
        global TOTAL
        with _LOCK:
            TOTAL += 1
    """
    found = lint(tmp_path, {"utils/m.py": src})
    assert rules_of(found) == ["KTL001"]
    assert "TOTAL" in found[0].message


# ---- KTL002 silent-swallow -------------------------------------------------

def test_ktl002_fires_on_silent_pass(tmp_path):
    src = """
    def f(x):
        try:
            return x()
        except Exception:
            pass
    """
    assert rules_of(lint(tmp_path, {"sched/e.py": src})) == ["KTL002"]


@pytest.mark.parametrize("body", [
    "_LOG.exception('boom')",
    "ERRS.inc({'site': 'f'})",
    "raise",
    "self._count_error()",
    "errors += 1",
])
def test_ktl002_trace_forms_pass(tmp_path, body):
    src = f"""
    def f(x, self=None, _LOG=None, ERRS=None):
        errors = 0
        try:
            return x()
        except Exception:
            {body}
    """
    assert lint(tmp_path, {"sched/e.py": src}) == []


def test_ktl002_narrow_except_out_of_scope(tmp_path):
    src = """
    def f(x):
        try:
            return x()
        except ValueError:
            pass
    """
    assert lint(tmp_path, {"sched/e.py": src}) == []


def test_ktl002_suppression_with_reason(tmp_path):
    src = """
    def f(x):
        try:
            return x()
        except Exception:  # ktpu-lint: disable=KTL002 -- teardown only
            pass
    """
    assert lint(tmp_path, {"sched/e.py": src}) == []


# ---- KTL003 clock discipline -----------------------------------------------

CLOCKY = """
    import time

    def loop():
        return time.time()
"""


def test_ktl003_fires_in_disciplined_tree(tmp_path):
    found = lint(tmp_path, {"controllers/t.py": CLOCKY})
    assert rules_of(found) == ["KTL003"]
    assert "time.time" in found[0].message


def test_ktl003_from_import_alias(tmp_path):
    src = """
    from time import monotonic as mono

    def loop():
        return mono()
    """
    found = lint(tmp_path, {"descheduler/t.py": src})
    assert rules_of(found) == ["KTL003"]


def test_ktl003_other_trees_exempt(tmp_path):
    assert lint(tmp_path, {"store/t.py": CLOCKY}) == []


def test_ktl003_suppression_with_reason(tmp_path):
    src = """
    import time

    def loop():
        return time.time()  # ktpu-lint: disable=KTL003 -- perf span needs the real wall
    """
    assert lint(tmp_path, {"autoscaler/t.py": src}) == []


# ---- KTL004 thread hygiene -------------------------------------------------

def test_ktl004_fires_without_daemon(tmp_path):
    src = """
    import threading

    def go(fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    """
    found = lint(tmp_path, {"utils/th.py": src})
    assert rules_of(found) == ["KTL004"]
    assert "daemon" in found[0].message


def test_ktl004_fires_without_lifecycle(tmp_path):
    src = """
    import threading

    def go(fn):
        threading.Thread(target=fn, daemon=True).start()
    """
    found = lint(tmp_path, {"utils/th.py": src})
    assert rules_of(found) == ["KTL004"]
    assert "join" in found[0].message


def test_ktl004_clean_with_daemon_and_join(tmp_path):
    src = """
    import threading

    def go(fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join(timeout=1.0)
    """
    assert lint(tmp_path, {"utils/th.py": src}) == []


def test_ktl004_watchdog_registration_counts(tmp_path):
    src = """
    import threading

    def go(fn, watchdog):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        watchdog.register("worker", t.is_alive, lambda: None)
    """
    assert lint(tmp_path, {"utils/th.py": src}) == []


def test_ktl004_suppression_with_reason(tmp_path):
    src = """
    import threading

    def go(fn):
        threading.Thread(target=fn).start()  # ktpu-lint: disable=KTL004 -- fixture thread, joined by the harness
    """
    assert lint(tmp_path, {"utils/th.py": src}) == []


# ---- KTL005 donation discipline ---------------------------------------------

def test_ktl005_donate_without_pin_fires(tmp_path):
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def step(ct):
        return ct
    """
    found = lint(tmp_path, {"models/g.py": src})
    assert rules_of(found) == ["KTL005"]
    assert "copy-on-donate" in found[0].message


def test_ktl005_out_shardings_pins(tmp_path):
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,), out_shardings=None)
    def step(ct):
        return ct
    """
    assert lint(tmp_path, {"models/g.py": src}) == []


def test_ktl005_constrain_cluster_pins(tmp_path):
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,), static_argnames=("mesh",))
    def step(ct, mesh=None):
        from kubernetes_tpu.parallel.mesh import constrain_cluster
        if mesh is not None:
            ct = constrain_cluster(mesh, ct)
        return ct
    """
    assert lint(tmp_path, {"models/g.py": src}) == []


def test_ktl005_device_get_outside_whitelist(tmp_path):
    src = """
    import jax

    def peek(x):
        return jax.device_get(x)
    """
    found = lint(tmp_path, {"ops/p.py": src})
    assert rules_of(found) == ["KTL005"]
    assert "zero-copy" in found[0].message


def test_ktl005_device_get_whitelisted_resolver(tmp_path):
    src = """
    import jax

    def resolve(x):
        return jax.device_get(x)
    """
    assert lint(tmp_path, {"sched/scheduler.py": src}) == []


def test_ktl005_suppression_with_reason(tmp_path):
    src = """
    import jax

    def peek(x):
        return jax.device_get(x)  # ktpu-lint: disable=KTL005 -- off-hot-path debug readback
    """
    assert lint(tmp_path, {"ops/p.py": src}) == []


# ---- KTL006 ConfigMap writes ------------------------------------------------

def test_ktl006_chained_write_fires(tmp_path):
    src = """
    def publish(client, ns, body):
        client.resource("configmaps", ns).update(body)
    """
    found = lint(tmp_path, {"sched/cmw.py": src})
    assert rules_of(found) == ["KTL006"]


def test_ktl006_var_assigned_write_fires(tmp_path):
    src = """
    def publish(client, ns, body):
        cms = client.resource("configmaps", ns)
        cms.create(body)
    """
    found = lint(tmp_path, {"sched/cmw.py": src})
    assert rules_of(found) == ["KTL006"]


def test_ktl006_reads_and_other_resources_pass(tmp_path):
    src = """
    def read(client, ns, name, body):
        cm = client.resource("configmaps", ns).get(name)
        client.resource("pods", ns).create(body)
        return cm
    """
    assert lint(tmp_path, {"sched/cmw.py": src}) == []


def test_ktl006_upsert_module_exempt(tmp_path):
    src = """
    def upsert_configmap(client, ns, body):
        client.resource("configmaps", ns).update(body)
    """
    assert lint(tmp_path, {"utils/configmap.py": src}) == []


def test_ktl006_suppression_with_reason(tmp_path):
    src = """
    def publish(client, ns, body):
        client.resource("configmaps", ns).update(body)  # ktpu-lint: disable=KTL006 -- reconcile must raise to requeue
    """
    assert lint(tmp_path, {"sched/cmw.py": src}) == []


# ---- KTL007 metrics registry ------------------------------------------------

REGISTRY_FIXTURE = """
    class _R:
        def counter(self, name, help_=""):
            return self

        def inc(self, labels=None, by=1.0):
            pass

    REGISTRY = _R()
    ERRS = REGISTRY.counter("loop_errors_total")
"""


def test_ktl007_construction_outside_registry_fires(tmp_path):
    src = """
    from kubernetes_tpu.metrics.registry import REGISTRY

    MINE = REGISTRY.counter("my_rogue_total")
    """
    found = lint(tmp_path, {"metrics/registry.py": REGISTRY_FIXTURE,
                            "sched/rogue.py": src})
    assert rules_of(found) == ["KTL007"]
    assert "outside metrics/registry.py" in found[0].message


def test_ktl007_inconsistent_labels_fire(tmp_path):
    use = """
    from kubernetes_tpu.metrics.registry import ERRS

    def a():
        ERRS.inc({"site": "a"})

    def b():
        ERRS.inc({"site": "b"})

    def c():
        ERRS.inc()   # the minority no-label series
    """
    found = lint(tmp_path, {"metrics/registry.py": REGISTRY_FIXTURE,
                            "sched/use.py": use})
    assert rules_of(found) == ["KTL007"]
    assert "loop_errors_total" in found[0].message
    assert found[0].path.endswith("sched/use.py")


def test_ktl007_consistent_labels_pass(tmp_path):
    use = """
    from kubernetes_tpu.metrics.registry import ERRS

    def a():
        ERRS.inc({"site": "a"})

    def b():
        ERRS.inc({"site": "b"})
    """
    assert lint(tmp_path, {"metrics/registry.py": REGISTRY_FIXTURE,
                           "sched/use.py": use}) == []


def test_ktl007_suppression_with_reason(tmp_path):
    use = """
    from kubernetes_tpu.metrics.registry import ERRS

    def a():
        ERRS.inc({"site": "a"})

    def b():
        ERRS.inc({"site": "b"})

    def c():
        ERRS.inc()  # ktpu-lint: disable=KTL007 -- aggregate tick, intentionally unlabeled
    """
    assert lint(tmp_path, {"metrics/registry.py": REGISTRY_FIXTURE,
                           "sched/use.py": use}) == []


# ---- KTL008 rename commits --------------------------------------------------

def test_ktl008_os_replace_fires(tmp_path):
    src = """
    import os

    def commit(tmp, path):
        os.replace(tmp, path)
    """
    found = lint(tmp_path, {"sched/persist.py": src})
    assert rules_of(found) == ["KTL008"]
    assert "atomicio" in found[0].message


def test_ktl008_rename_and_shutil_move_fire(tmp_path):
    src = """
    import os
    import shutil

    def commit(tmp, path):
        os.rename(tmp, path)
        shutil.move(tmp, path)
    """
    found = lint(tmp_path, {"store/persist.py": src})
    assert rules_of(found) == ["KTL008", "KTL008"]


def test_ktl008_helper_and_non_commit_io_pass(tmp_path):
    helper = """
    import os

    def atomic_write(path, data):
        os.replace(path + ".tmp", path)
    """
    clean = """
    import os

    from kubernetes_tpu.utils.atomicio import atomic_write

    def commit(path, data):
        atomic_write(path, data)
        os.unlink(path + ".bak")
        os.makedirs("x", exist_ok=True)
    """
    assert lint(tmp_path, {"utils/atomicio.py": helper,
                           "sched/persist.py": clean}) == []


def test_ktl008_suppression_with_reason(tmp_path):
    src = """
    import os

    def rotate(old, new):
        os.rename(old, new)  # ktpu-lint: disable=KTL008 -- log rotation of scratch output, not a durable commit
    """
    assert lint(tmp_path, {"sched/persist.py": src}) == []


# ---- KTL000 meta rule --------------------------------------------------------

def test_reasonless_disable_is_ktl000_and_suppresses_nothing(tmp_path):
    src = """
    def f(x):
        try:
            return x()
        except Exception:  # ktpu-lint: disable=KTL002
            pass
    """
    found = lint(tmp_path, {"sched/e.py": src})
    assert sorted(rules_of(found)) == ["KTL000", "KTL002"]


def test_own_line_suppression_covers_next_line(tmp_path):
    src = """
    import jax

    def peek(x):
        # ktpu-lint: disable=KTL005 -- debug readback, never on the cycle
        return jax.device_get(x)
    """
    assert lint(tmp_path, {"ops/p.py": src}) == []


# ---- fingerprints + baseline -------------------------------------------------

def test_fingerprint_stable_under_unrelated_edits(tmp_path):
    base = GUARDED.format(suffix="")
    f1 = lint(tmp_path, {"sched/b.py": base})
    edited = "'''a new module docstring'''\nX = 1\n" + textwrap.dedent(base)
    (tmp_path / "kubernetes_tpu" / "sched" / "b.py").write_text(edited)
    f2 = run_analysis(str(tmp_path / "kubernetes_tpu"))
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert f1[0].line != f2[0].line  # the line moved; the identity did not


def test_baseline_round_trip(tmp_path):
    findings = lint(tmp_path, {"sched/e.py": """
    def f(x):
        try:
            return x()
        except Exception:
            pass
    """})
    assert len(findings) == 1
    bl = tmp_path / "baseline.json"
    baseline_mod.write_baseline(findings, str(bl))
    fps = baseline_mod.load_baseline(str(bl))
    assert fps == {findings[0].fingerprint}
    # baselined finding is not NEW
    new, fixed = baseline_mod.diff(findings, fps)
    assert new == [] and fixed == 0
    # a fresh finding IS new; a fixed one is counted
    findings2 = lint(tmp_path, {"sched/e2.py": """
    def g(x):
        try:
            return x()
        except Exception:
            pass
    """})
    assert [f.path for f in findings2] == ["kubernetes_tpu/sched/e.py",
                                           "kubernetes_tpu/sched/e2.py"]
    new, fixed = baseline_mod.diff(findings2, fps)
    assert [f.path for f in new] == ["kubernetes_tpu/sched/e2.py"]
    assert fixed == 0
    # fixing the original (file gone) counts as baselined-and-fixed
    (tmp_path / "kubernetes_tpu" / "sched" / "e.py").unlink()
    findings3 = run_analysis(str(tmp_path / "kubernetes_tpu"))
    new, fixed = baseline_mod.diff(findings3, fps)
    assert len(new) == 1 and fixed == 1


def test_missing_baseline_means_everything_new(tmp_path):
    assert baseline_mod.load_baseline(str(tmp_path / "nope.json")) == set()


def test_ktl001_closure_in_with_is_not_lock_held(tmp_path):
    """A thread-target closure defined INSIDE `with self._lock:` runs
    after the lock is released — indentation must not exempt it."""
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0  # guarded by: self._lock

        def start(self):
            with self._lock:
                def worker():
                    self.n += 1
                threading.Thread(target=worker, daemon=True).start()
    """
    found = lint(tmp_path, {"sched/c.py": src})
    assert "KTL001" in [f.rule for f in found]


def test_ktl001_closure_in_init_is_not_exempt(tmp_path):
    src = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0  # guarded by: self._lock

            def worker():
                self.n += 1
            threading.Thread(target=worker, daemon=True).start()
    """
    found = lint(tmp_path, {"sched/c.py": src})
    assert "KTL001" in [f.rule for f in found]


def test_subtree_scan_keeps_package_relpaths(tmp_path):
    """Scanning a package SUBTREE anchors relpaths (and so fingerprints,
    path-scoped rules, baseline matches) exactly like a full-package
    scan — __init__.py chains mark the package top."""
    root = tmp_path / "kubernetes_tpu"
    (root / "controllers").mkdir(parents=True)
    (root / "__init__.py").write_text("")
    (root / "controllers" / "__init__.py").write_text("")
    (root / "controllers" / "t.py").write_text(textwrap.dedent(CLOCKY))
    full = run_analysis(str(root))
    sub = run_analysis(str(root / "controllers"))
    assert [f.rule for f in sub] == ["KTL003"]  # path scope still applies
    assert ([(f.path, f.fingerprint) for f in sub]
            == [(f.path, f.fingerprint) for f in full])


def test_dangling_suppression_is_ktl000(tmp_path):
    src = """
    def f(x):
        return x()  # ktpu-lint: disable=KTL002 -- excuse for nothing
    """
    found = lint(tmp_path, {"sched/d.py": src})
    assert [f.rule for f in found] == ["KTL000"]
    assert "stale exemption" in found[0].message


def test_dangling_scan_respects_rule_filter(tmp_path):
    """A --rule-filtered run must not condemn other rules' suppressions
    as dangling (their rules never ran)."""
    from kubernetes_tpu.analysis.rules import make_rules
    root = tmp_path / "kubernetes_tpu"
    (root / "sched").mkdir(parents=True)
    (root / "sched" / "d.py").write_text(textwrap.dedent("""
    import time

    def f(x):
        return time.time()  # ktpu-lint: disable=KTL003 -- real wall on purpose
    """))
    only_002 = [r for r in make_rules() if r.id == "KTL002"]
    assert run_analysis(str(root), rules=only_002) == []


def test_write_baseline_rejects_rule_filter(tmp_path, capsys):
    from kubernetes_tpu.analysis.cli import main
    root = tmp_path / "kubernetes_tpu"
    (root / "sched").mkdir(parents=True)
    (root / "sched" / "e.py").write_text("x = 1\n")
    rc = main([str(root), "--rule", "KTL003", "--write-baseline"])
    assert rc == 2
    assert "cannot be combined" in capsys.readouterr().out


def test_cli_json_summary(tmp_path, capsys):
    from kubernetes_tpu.analysis.cli import main
    root = tmp_path / "kubernetes_tpu"
    (root / "sched").mkdir(parents=True)
    (root / "sched" / "e.py").write_text(
        "def f(x):\n    try:\n        return x()\n"
        "    except Exception:\n        pass\n")
    rc = main([str(root), "--no-baseline", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    assert '"findings_new": 1' in out
    # --write-baseline then rerun: gate closes
    bl = tmp_path / "bl.json"
    assert main([str(root), "--baseline", str(bl), "--write-baseline"]) == 0
    assert main([str(root), "--baseline", str(bl), "--json"]) == 0
