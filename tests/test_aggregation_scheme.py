"""API aggregation (server chain) + multi-version conversion (scheme).

Reference: ``cmd/kube-apiserver/app/server.go`` CreateServerChain /
kube-aggregator, and ``apimachinery/pkg/runtime/scheme.go`` hub-and-spoke
conversion (``pkg/apis/autoscaling/v1/conversion.go`` for the HPA pair).
"""

import json

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.aggregator import AggregatedAPIServer
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_pod


# ------------------------------------------------------------- aggregation

@pytest.fixture()
def chain():
    backend = APIServer().start()       # the extension apiserver
    front = AggregatedAPIServer().start()
    yield front, backend
    front.stop()
    backend.stop()


def test_delegation_serves_core_resources(chain):
    front, _backend = chain
    c = HTTPClient(front.url)
    c.pods("default").create(make_pod("p").obj().to_dict())
    assert c.pods("default").get("p")["metadata"]["name"] == "p"


def test_apiservice_proxies_group_to_backend(chain):
    front, backend = chain
    # claim a group via an APIService object (stored like any resource)
    c = HTTPClient(front.url)
    c.resource("apiservices", None).create({
        "kind": "APIService", "metadata": {"name": "v1.metrics.example"},
        "spec": {"group": "metrics.example", "version": "v1",
                 "service": {"url": backend.url}}})
    # the backend serves /apis/... of its own resources; use a group the
    # backend ALSO routes: register the proxy for apps and create through
    # the front — the object must land in the BACKEND's store
    c.resource("apiservices", None).create({
        "kind": "APIService", "metadata": {"name": "v1.apps"},
        "spec": {"group": "apps", "version": "v1",
                 "service": {"url": backend.url}}})
    c.resource("deployments", "default").create(
        {"kind": "Deployment", "metadata": {"name": "via-proxy"},
         "spec": {"replicas": 1}})
    assert backend.store.get("Deployment", "default", "via-proxy")
    with pytest.raises(Exception):
        front.core.store.get("Deployment", "default", "via-proxy")
    # reads route through the proxy too
    got = c.resource("deployments", "default").get("via-proxy")
    assert got["metadata"]["name"] == "via-proxy"


def test_unavailable_backend_returns_503(chain):
    front, _backend = chain
    c = HTTPClient(front.url)
    c.resource("apiservices", None).create({
        "kind": "APIService", "metadata": {"name": "v1.dead.example"},
        "spec": {"group": "apps", "version": "v1",
                 "service": {"url": "http://127.0.0.1:1"}}})
    with pytest.raises(ApiError) as ei:
        c.resource("deployments", "default").get("nope")
    assert ei.value.code == 503


# ------------------------------------------------------------ multi-version

@pytest.fixture()
def api():
    server = APIServer().start()
    yield server
    server.stop()


def _v1_url(c, name=None):
    p = "/apis/autoscaling/v1/namespaces/default/horizontalpodautoscalers"
    if name:
        p += f"/{name}"
    return c.base + p


def test_hpa_v1_write_stored_as_v2(api):
    c = HTTPClient(api.url)
    c._req("POST", _v1_url(c), {
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web"},
        "spec": {"minReplicas": 1, "maxReplicas": 5,
                 "scaleTargetRef": {"kind": "Deployment", "name": "web"},
                 "targetCPUUtilizationPercentage": 70}})
    stored = api.store.get("HorizontalPodAutoscaler", "default", "web")
    # hub shape: metrics list, no v1 scalar
    assert "targetCPUUtilizationPercentage" not in stored["spec"]
    m = stored["spec"]["metrics"][0]
    assert m["resource"]["name"] == "cpu"
    assert m["resource"]["target"]["averageUtilization"] == 70


def test_hpa_v1_read_converts_back(api):
    c = HTTPClient(api.url)
    c.resource("horizontalpodautoscalers", "default").create({
        "kind": "HorizontalPodAutoscaler", "metadata": {"name": "v2native"},
        "spec": {"minReplicas": 1, "maxReplicas": 3,
                 "metrics": [{"type": "Resource", "resource": {
                     "name": "cpu", "target": {"type": "Utilization",
                                               "averageUtilization": 55}}}]}})
    got = c._req("GET", _v1_url(c, "v2native"))
    assert got["spec"]["targetCPUUtilizationPercentage"] == 55
    assert "metrics" not in got["spec"]
    # list endpoint converts every item
    lst = c._req("GET", _v1_url(c))
    assert lst["items"][0]["spec"]["targetCPUUtilizationPercentage"] == 55
    # the v2 endpoint still serves the hub shape
    v2 = c.resource("horizontalpodautoscalers", "default").get("v2native")
    assert v2["spec"]["metrics"][0]["resource"]["name"] == "cpu"


def test_hpa_v1_watch_converts(api):
    import urllib.request
    c = HTTPClient(api.url)
    url = _v1_url(c) + "?watch=true&resourceVersion=0"
    resp = urllib.request.urlopen(urllib.request.Request(url), timeout=5.0)
    c.resource("horizontalpodautoscalers", "default").create({
        "kind": "HorizontalPodAutoscaler", "metadata": {"name": "w"},
        "spec": {"maxReplicas": 2,
                 "metrics": [{"type": "Resource", "resource": {
                     "name": "cpu", "target": {"type": "Utilization",
                                               "averageUtilization": 80}}}]}})
    line = resp.readline()
    while line == b"\n":
        line = resp.readline()
    ev = json.loads(line)
    assert ev["type"] == "ADDED"
    assert ev["object"]["spec"]["targetCPUUtilizationPercentage"] == 80
    resp.close()


def test_aggregated_requests_pass_auth_chain(chain):
    """Registering an APIService must not open an unauthenticated path:
    the aggregator runs authn before proxying."""
    front, backend = chain
    from kubernetes_tpu.store.auth import TokenAuthenticator
    front.core.enable_auth(
        authenticator=TokenAuthenticator(tokens={"sekrit": "admin"},
                                         allow_anonymous=False))
    front.register_api_service("apps", "v1", backend.url, name="v1.apps2")
    with pytest.raises(ApiError) as ei:
        HTTPClient(front.url).resource("deployments", "default").get("x")
    assert ei.value.code == 401


def test_aggregated_watch_streams_through_proxy(chain):
    front, backend = chain
    c = HTTPClient(front.url)
    c.resource("apiservices", None).create({
        "kind": "APIService", "metadata": {"name": "v1.apps3"},
        "spec": {"group": "apps", "version": "v1",
                 "service": {"url": backend.url}}})
    w = c.resource("deployments", "default").watch(since_rv=0)
    HTTPClient(backend.url).resource("deployments", "default").create(
        {"kind": "Deployment", "metadata": {"name": "streamed"}})
    import time as _t
    deadline = _t.time() + 10.0
    ev = None
    while ev is None and _t.time() < deadline:
        ev = w.get(timeout=1.0)
    assert ev is not None and ev.object["metadata"]["name"] == "streamed"
    w.stop()


def test_hpa_ssa_and_status_convert(api):
    c = HTTPClient(api.url)
    # SSA at the v1 endpoint stores hub shape
    c._req("PATCH", _v1_url(c, "ssa") + "?fieldManager=t", {
        "kind": "HorizontalPodAutoscaler", "metadata": {"name": "ssa"},
        "spec": {"maxReplicas": 4, "targetCPUUtilizationPercentage": 60}})
    stored = api.store.get("HorizontalPodAutoscaler", "default", "ssa")
    assert "targetCPUUtilizationPercentage" not in stored["spec"]
    assert stored["spec"]["metrics"][0]["resource"]["target"][
        "averageUtilization"] == 60
    # v1 status PUT converts the fragment into hub currentMetrics
    c._req("PUT", _v1_url(c, "ssa") + "/status", {
        "status": {"currentCPUUtilizationPercentage": 42}})
    stored = api.store.get("HorizontalPodAutoscaler", "default", "ssa")
    assert "currentCPUUtilizationPercentage" not in stored["status"]
    assert stored["status"]["currentMetrics"][0]["resource"]["current"][
        "averageUtilization"] == 42


def test_hpa_v1_roundtrip_preserves_non_cpu_metrics(api):
    """A v1 GET-then-PUT must not destroy v2-only metrics (upstream stashes
    them in the autoscaling.alpha.kubernetes.io/metrics annotation)."""
    c = HTTPClient(api.url)
    c.resource("horizontalpodautoscalers", "default").create({
        "kind": "HorizontalPodAutoscaler", "metadata": {"name": "rt"},
        "spec": {"maxReplicas": 4, "metrics": [
            {"type": "Resource", "resource": {
                "name": "cpu", "target": {"type": "Utilization",
                                          "averageUtilization": 50}}},
            {"type": "Resource", "resource": {
                "name": "memory", "target": {"type": "Utilization",
                                             "averageUtilization": 70}}}]}})
    v1 = c._req("GET", _v1_url(c, "rt"))
    assert v1["spec"]["targetCPUUtilizationPercentage"] == 50
    ann = v1["metadata"]["annotations"][
        "autoscaling.alpha.kubernetes.io/metrics"]
    assert "memory" in ann
    # the classic v1 read-modify-write
    v1["spec"]["targetCPUUtilizationPercentage"] = 55
    c._req("PUT", _v1_url(c, "rt"), v1,
           headers={"If-Match": v1["metadata"]["resourceVersion"]})
    stored = api.store.get("HorizontalPodAutoscaler", "default", "rt")
    names = {m["resource"]["name"] for m in stored["spec"]["metrics"]}
    assert names == {"cpu", "memory"}
    cpu = next(m for m in stored["spec"]["metrics"]
               if m["resource"]["name"] == "cpu")
    assert cpu["resource"]["target"]["averageUtilization"] == 55
    # the stash annotation does not leak into storage
    assert "autoscaling.alpha.kubernetes.io/metrics" not in (
        stored["metadata"].get("annotations") or {})
