"""Live multi-chip scheduling: the CONNECTED drain/dispatch/preemption path
under a device mesh (sched/scheduler.py + parallel/mesh.py).

test_mesh.py proves the device programs are sharding-parity-safe in
isolation; this file proves the LIVE scheduler — cache, queue, resident
drain context, churn patches, resolve — produces identical placements with
``meshShape`` on, and that the mesh plumbing (epoch-checked rebuilds,
donation, row-pack encode) holds on any backend.

Mesh-executing tests carry the ``multichip`` marker and gate on the same
GSPMD canary as test_mesh.py: a jaxlib that miscompiles sharded programs on
the virtual-CPU platform skips them deterministically instead of failing
tier-1. Everything else here runs single-device and stays in tier-1.
"""

import io
import warnings

import numpy as np
import pytest

from kubernetes_tpu.config.types import (SchedulerConfiguration,
                                         ValidationError, validate)
from kubernetes_tpu.parallel.mesh import parse_mesh_shape
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n=64):
    return [make_node(f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("topology.kubernetes.io/zone", f"z{i // 3}")
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .obj() for i in range(n)]


def _pods(n=48, prefix="p"):
    out = []
    for i in range(n):
        b = (make_pod(f"{prefix}{i:03d}")
             .req({"cpu": "500m", "memory": "256Mi"})
             .label("app", f"g{i % 3}"))
        if i % 5 == 0:
            b = b.pod_anti_affinity("kubernetes.io/hostname", {"app": "g0"})
        out.append(b.obj())
    return out


def _scheduler(mesh_shape=None, nodes=None, batch_size=16, warm=True):
    cfg = SchedulerConfiguration(batch_size=batch_size, max_drain_batches=2,
                                 mesh_shape=mesh_shape)
    validate(cfg)
    cache = SchedulerCache()
    for n in (nodes or _nodes()):
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    if warm:
        warm_pods = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
                     for i in range(batch_size)]
        assert sched.warm_drain(warm_pods, slot_headroom=256)
    return sched, cache, queue, log


def _run_to_empty(sched, queue, pods, rounds=30):
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if not sched._pending and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()
    return bound


# ---- config surface ------------------------------------------------------

def test_parse_mesh_shape_forms():
    assert parse_mesh_shape(None) is None
    assert parse_mesh_shape("") is None
    assert parse_mesh_shape("off") is None
    assert parse_mesh_shape("1x2") == (1, 2)
    assert parse_mesh_shape("2,4") == (2, 4)
    assert parse_mesh_shape("4") == (1, 4)
    assert parse_mesh_shape([2, 2]) == (2, 2)
    assert parse_mesh_shape(1) is None


def test_mesh_shape_yaml_and_validation():
    cfg = SchedulerConfiguration.from_dict({"meshShape": [1, 2]})
    assert cfg.mesh_shape == (1, 2)
    validate(cfg)
    cfg = SchedulerConfiguration.from_dict({"meshShape": "2x4"})
    assert cfg.mesh_shape == (2, 4)
    with pytest.raises(ValidationError):
        validate(SchedulerConfiguration(mesh_shape=(3, 2)))  # not a pow2
    with pytest.raises(ValidationError):
        # pods axis must divide the batch bucket
        validate(SchedulerConfiguration(batch_size=6, mesh_shape=(4, 1)))


def test_unavailable_mesh_degrades_to_single_device():
    """A meshShape wider than the backend must degrade to single-device
    scheduling (the mesh is a throughput knob), not refuse to construct."""
    sched, _cache, queue, log = _scheduler(mesh_shape=(1, 1024), warm=False)
    assert sched._mesh is None
    bound = _run_to_empty(sched, queue, _pods(8))
    assert bound == 8
    sched.close()


# ---- mesh-epoch discipline (single-device: epoch logic only) -------------

def test_mesh_reshape_forces_ctx_rebuild():
    """A mesh reshape between drains must rebuild the resident context —
    patching arrays staged under the old layout would be silently wrong.
    set_mesh(None) still bumps the epoch, so this runs on any backend."""
    sched, cache, queue, log = _scheduler()
    bound = _run_to_empty(sched, queue, _pods(24))
    assert bound == 24
    assert sched._drain_ctx is not None
    rebuilds0 = sched.ctx_stats["rebuilds"]
    sched.set_mesh(None)  # reshape: epoch moves, layout semantics change
    bound += _run_to_empty(sched, queue, _pods(24, prefix="q"))
    assert bound == 48
    assert sched.ctx_stats["reasons"].get("mesh_reshape", 0) >= 1
    assert sched.ctx_stats["rebuilds"] > rebuilds0
    assert sched._drain_ctx is not None
    assert sched._drain_ctx["mesh_epoch"] == sched._mesh_epoch
    sched.close()


# ---- donation audit (satellite): steady-state drain/patch aliasing -------

def test_drain_patch_steady_state_no_copy_on_donate_warnings():
    """The resident ctx is donated through drain_step (both its plain and
    fused-fold variants) AND apply_ctx_patch; steady-state cycles must
    alias buffers in place. A 'donated buffers were not usable' warning
    means a layout mismatch re-copies the multi-MB encoding every drain —
    the exact regression the warmup double-execute exists to prevent. Runs
    the THREE-input drain (churn patch fused into the dispatch) and the
    legacy separate-apply path back to back."""
    sched, cache, queue, log = _scheduler()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        bound = _run_to_empty(sched, queue, _pods(24))
        # churn -> fold-into-dispatch -> drain again (three-input drain)
        cache.add_node(
            make_node("late-node")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", "late-node").obj())
        bound += _run_to_empty(sched, queue, _pods(16, prefix="late"))
        # legacy mode: churn -> separate apply_ctx_patch dispatch -> drain
        sched._fused_fold = False
        cache.add_node(
            make_node("late-node-2")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", "late-node-2").obj())
        bound += _run_to_empty(sched, queue, _pods(16, prefix="later"))
    assert bound == 56
    assert sched.ctx_stats["folds"] >= 1, \
        "churn did not take the fused-fold path"
    assert sched.ctx_stats["patches"] >= 1, \
        "churn did not take the legacy patch path"
    donate_warnings = [str(w.message) for w in caught
                       if "donated" in str(w.message).lower()]
    assert not donate_warnings, donate_warnings
    sched.close()


# ---- EK width regression (found arming the mesh path) --------------------

def test_ctx_patch_after_batch_widened_label_bucket():
    """Regression: a context armed before any labeled pod was seen (K=4
    bucket), then batches whose label keys crossed the bucket (extend_cluster
    widens the RESIDENT epod arrays to K=8), then a node-add churn patch.
    The patch must compile at the resident widths (CtxPatchState.EK) — it
    used to compile at the encoder's K and fail to broadcast at apply."""
    sched, cache, queue, log = _scheduler()  # warm pods carry no labels
    bound = _run_to_empty(sched, queue, _pods(24))  # labels cross the bucket
    assert bound == 24
    cache.add_node(
        make_node("late-node")
        .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
        .label("kubernetes.io/hostname", "late-node").obj())
    bound += _run_to_empty(sched, queue, _pods(16, prefix="late"))
    assert bound == 40
    assert sched.ctx_stats["patches"] + sched.ctx_stats["folds"] >= 1
    sched.close()


# ---- encode row packs (satellite): fill-only cycles ----------------------

def test_fill_only_cycles_do_no_per_pod_fill_work():
    """Once a pod's row pack exists (informer-time precompile or a prior
    encode), encode_pods must assemble it with bulk stacks only — the
    pod_rows_filled counter is the proof the bench reports."""
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    import jax
    nodes, pods = _nodes(16), _pods(12)
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    pb1 = enc.encode_pods(pods, meta)
    assert enc.pod_rows_filled == 12 and enc.pod_rows_stacked == 0
    pb2 = enc.encode_pods(pods, meta)  # fill-only cycle: pure stack
    assert enc.pod_rows_filled == 12 and enc.pod_rows_stacked == 12
    for a, b in zip(jax.tree_util.tree_leaves(pb1),
                    jax.tree_util.tree_leaves(pb2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # informer-time precompile: fresh pods arrive with rows prebuilt
    fresh = _pods(8, prefix="q")
    for p in fresh:
        enc.precompile_pod(p)
    filled0 = enc.pod_rows_filled
    enc.encode_pods(fresh, meta)
    assert enc.pod_rows_filled == filled0, \
        "precompiled pods paid per-pod fill work on the hot path"


def test_row_pack_invalidation_on_epoch_and_identity():
    """A catalog change (epoch bump) or a new watch object must invalidate
    the cached rows — stale packs would encode dead state."""
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    nodes, pods = _nodes(8), _pods(4)
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    enc.encode_pods(pods, meta)
    filled0 = enc.pod_rows_filled
    enc._pod_epoch += 1  # what set_volumes/set_namespaces/set_dra do
    enc.encode_pods(pods, meta)
    assert enc.pod_rows_filled == filled0 + len(pods)
    # fresh objects with the same keys (a new watch event) re-fill too
    pods2 = _pods(4)
    filled1 = enc.pod_rows_filled
    enc.encode_pods(pods2, meta)
    assert enc.pod_rows_filled == filled1 + len(pods2)


def test_sticky_widths_promote_monotonically():
    """A wide pod promotes the batch buckets; later narrow batches keep the
    promoted widths so their packs stay valid (stable compiled shapes)."""
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    nodes = _nodes(8)
    plain = [make_pod(f"a{i}").req({"cpu": "100m"}).obj() for i in range(4)]
    wide = [make_pod(f"w{i}").req({"cpu": "100m"})
            .toleration("k1", "v1").toleration("k2", "v2")
            .toleration("k3", "v3").obj() for i in range(2)]
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=plain + wide)
    pb_plain = enc.encode_pods(plain, meta)
    assert pb_plain.tol_valid.shape[1] == 0
    pb_wide = enc.encode_pods(wide, meta)
    TOL = pb_wide.tol_valid.shape[1]
    assert TOL >= 3
    pb_plain2 = enc.encode_pods(plain, meta)
    assert pb_plain2.tol_valid.shape[1] == TOL  # sticky: no shrink


# ---- status surface ------------------------------------------------------

def test_publish_status_and_ktpu_status():
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    try:
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "status"], out=out)
        assert rc == 1 and "no scheduler status" in out.getvalue()
        runner = SchedulerRunner(HTTPClient(server.url))
        runner.publish_status()
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "status"], out=out)
        assert rc == 0
        text = out.getvalue()
        assert "Mesh:" in text and "single-device" in text
        assert "default-scheduler" in text
        # resident-ctx fusion health is part of the status surface
        assert "Resident ctx:" in text and "fused fold on" in text
        assert "in flight" in text
        # zero-copy staging health (sched/staging.py arena)
        assert "Staging:" in text and "arena on" in text
        # no aotCacheDir configured -> the cache reports itself off
        assert "Compile cache: off" in text
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "status", "-o", "json"],
                       out=out)
        assert rc == 0
        import json
        st = json.loads(out.getvalue())
        assert st["mesh"] is None and st["batchSize"] == 256
        assert st["ctx"]["patches"] == 0 and st["ctx"]["folds"] == 0
        assert st["pipelineInflight"] == 0 and st["fusedFold"] is True
        assert st["staging"]["enabled"] is True
        assert st["staging"]["fallbacks"] == 0
        assert st["aotCache"] == {"enabled": False}
        runner.scheduler.close()
    finally:
        server.stop()


def test_ktpu_status_compile_cache_line(tmp_path):
    """With an aotCacheDir configured the status surface reports the
    durable compile cache: entry/byte counts and this boot's load, in
    both the text line and the -o json block."""
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.aotcache import AotExecutableCache
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    try:
        cfg = SchedulerConfiguration.from_dict(
            {"aotCacheDir": str(tmp_path / "aot")})
        runner = SchedulerRunner(HTTPClient(server.url), cfg)
        assert runner.aot_cache is not None
        runner.publish_status()
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "status"], out=out) == 0
        text = out.getvalue()
        assert "Compile cache: 0 entries" in text
        assert "boot loaded 0" in text
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "status", "-o", "json"],
                         out=out) == 0
        import json
        ac = json.loads(out.getvalue())["aotCache"]
        assert ac["enabled"] is True
        assert ac["entries"] == 0 and ac["bootEntries"] == 0
        assert ac["bootLoadMs"] is not None
        assert ac["errors"] == 0 and ac["invalidations"] == 0
        runner.scheduler.close()
    finally:
        AotExecutableCache.disarm()
        server.stop()


# ---- live parity under a real mesh (canary-gated, multichip tier) --------

def _mesh_backend_or_skip():
    # canary at THIS suite's mesh shape: GSPMD miscompiles are
    # shape-specific, so the 2x4 verdict must not over-skip the 1x2 path
    import test_mesh
    usable, why = test_mesh._sharded_backend_verdict((1, 2))
    if not usable:
        pytest.skip(why)


@pytest.mark.multichip
def test_live_path_parity_mesh_vs_single_device():
    """The SAME workload through the live scheduler with meshShape=(1,2)
    and single-device must bind every pod to identical nodes — the whole
    connected path (resident ctx staging, sharded dispatch, churn patch,
    replicated winners resolve), not just the isolated device programs."""
    _mesh_backend_or_skip()
    placements = {}
    for shape in (None, (1, 2)):
        sched, cache, queue, log = _scheduler(mesh_shape=shape)
        if shape is not None and sched._mesh is None:
            pytest.skip("mesh unavailable on this backend")
        bound = _run_to_empty(sched, queue, _pods(48))
        # churn against the resident (sharded) context mid-run
        cache.add_node(
            make_node("late-node")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", "late-node").obj())
        bound += _run_to_empty(sched, queue, _pods(16, prefix="late"))
        assert bound == 64, f"shape {shape} lost pods: {bound}"
        placements[shape] = dict(log)
        sched.close()
    assert placements[None] == placements[(1, 2)]


@pytest.mark.multichip
def test_preempt_masks_sharded_parity():
    """tensor_static_masks under the mesh == unsharded (the preempt/wave
    setup program the live failure path runs)."""
    _mesh_backend_or_skip()
    import jax
    from kubernetes_tpu.parallel.mesh import make_mesh
    from kubernetes_tpu.sched.preemption import tensor_static_masks
    nodes = _nodes(32)
    preemptors = [make_pod(f"hi{i}").req({"cpu": "6"}).priority(100).obj()
                  for i in range(8)]
    base = tensor_static_masks(nodes, preemptors, bound_pods=[])
    mesh = make_mesh(jax.devices()[:2], pods_axis=1)
    sharded = tensor_static_masks(nodes, preemptors, bound_pods=[],
                                  mesh=mesh)
    np.testing.assert_array_equal(base, sharded)
