"""The sidecar's process boundary exercised from OUTSIDE Python.

Reference: ``pkg/scheduler/extender.go`` (HTTPExtender) is the integration
precedent — a scheduler written in another language reaches the TPU engine
over the wire. ``native/sidecar_client.c`` speaks the actual protocol
(gRPC/HTTP2 via libcurl, 5-byte frames, hand-rolled msgpack codec): the
proof that sidecar/proto.py needs no Python on the consumer side.
"""

import os
import shutil
import subprocess

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


@pytest.fixture(scope="module")
def client_bin():
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    out = os.path.join(NATIVE_DIR, "sidecar_client")
    src = os.path.join(NATIVE_DIR, "sidecar_client.c")
    if (not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src)):
        subprocess.run([cc, "-O2", "-Wall", "-std=c11", "-o", out, src,
                        "-ldl"], check=True, capture_output=True)
    return out


def test_native_client_full_protocol(client_bin):
    """PushSnapshot -> Schedule (100x100) -> PushDelta bind -> STALE
    rejection -> second wave -> ordered node/pod deletes, all from C."""
    from kubernetes_tpu.sidecar import SidecarServer
    srv = SidecarServer().start()
    try:
        p = subprocess.run([client_bin, srv.address, "100", "100"],
                           capture_output=True, text=True, timeout=180)
        assert p.returncode == 0, (p.stdout, p.stderr)
        assert "ALL CHECKS PASSED" in p.stdout
        assert "STALE (server at 2)" in p.stdout
        assert "100/100 pods placed" in p.stdout
    finally:
        srv.stop()
