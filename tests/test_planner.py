"""Resident what-if planning: the three background planners (autoscaler,
descheduler, gang defrag) riding the scheduler's device-resident cluster
image through ``encode/overlay.ResidentPlanner`` overlay views.

What tier-1 proves here:
  * resident-overlay plans are BIT-EQUAL to today's cold-encode plans for
    all three planners (randomized parity fuzz),
  * every staleness condition (no ctx / in-flight drain / tainted context /
    mesh epoch / stale delta log / observation skew) declines into the cold
    path with the reason recorded,
  * per-tenant drain-slot quotas are enforced device-side in ONE dispatch —
    admission is a pure function of its verdicts,
  * the ``BackgroundPlanner`` cadence wires both planners to one shared
    ResidentPlanner, counts steady-window compiles, and publishes status
    the ``ktpu status`` "Planners:" line renders.
"""

import io
import json
import random

import numpy as np
import pytest

from kubernetes_tpu.autoscaler.nodegroup import NODE_GROUP_LABEL, NodeGroup
from kubernetes_tpu.autoscaler.simulator import (
    simulate_scale_down,
    simulate_scale_up,
)
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.descheduler import (
    CandidateSet,
    GANG_LABEL,
    plan_evictions,
    plan_gang_defrag,
)
from kubernetes_tpu.encode.overlay import ResidentPlanner, tenant_quota_mask
from kubernetes_tpu.encode.snapshot import SnapshotEncoder, TENANT_LABEL
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.planner


def _sched(nodes, bound=()):
    """A warm scheduler over ``nodes``+``bound`` whose resident drain
    context can hand out plan views (the product's warm_drain arming)."""
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in bound:
        cache.add_pod(p)
    queue = SchedulingQueue(backoff_initial=0.01, backoff_max=0.05)
    cfg = SchedulerConfiguration(batch_size=4, max_drain_batches=2,
                                 backoff_initial_s=0.01, backoff_max_s=0.05)
    sched = Scheduler(cfg, cache, queue, lambda pod, node: True)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    return sched, cache


def _norm_up(options):
    return [(o.group.name, sorted(o.pod_indices), o.nodes_needed,
             round(float(o.waste), 9)) for o in options]


def _norm_down(plan):
    return (sorted(plan.removable),
            {n: sorted(m) for n, m in plan.placements.items()},
            dict(plan.blocked))


def _norm_ev(plan):
    return ([(s.name, s.strategy, sorted(p.key for p in s.victims),
              sorted(s.moves)) for s in plan.accepted],
            dict(plan.blocked), plan.batch_victims, plan.batch_sets)


def _norm_gang(plan):
    acc = None
    if plan.accepted is not None:
        acc = (plan.accepted.name, sorted(p.key for p in plan.accepted.victims),
               sorted(plan.accepted.moves))
    return (plan.gang, acc, sorted(plan.gang_moves),
            plan.fits_without_evictions, dict(plan.blocked))


def _fuzz_cluster(seed):
    """8 nodes / random load; shapes stay in one jit bucket across seeds."""
    rng = random.Random(seed)
    nodes, bound = [], []
    for i in range(8):
        cpu, mem = rng.choice([("8", "16Gi"), ("4", "8Gi"), ("16", "32Gi")])
        # kubelet/autoscaler stamp hostname + group on every real node —
        # the templates' label keys must live in the node bucket
        nodes.append(make_node(f"f{i}")
                     .capacity({"cpu": cpu, "memory": mem, "pods": "32"})
                     .label("disk", rng.choice(["ssd", "hdd"]))
                     .label("kubernetes.io/hostname", f"f{i}")
                     .label(NODE_GROUP_LABEL, "fuzz-pool")
                     .obj())
    k = 0
    for i in range(8):
        for _ in range(rng.randint(0, 2)):
            bound.append(make_pod(f"fb{k}")
                         .req({"cpu": rng.choice(["500m", "1", "2"])})
                         .node(f"f{i}").obj())
            k += 1
    return nodes, bound


# ------------------------------------------------- resident/cold parity

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_resident_vs_cold_plan_parity_fuzz(seed):
    """All three planners, same observation, overlay view vs cold encode:
    plans must be bit-equal, and the overlay path must actually be taken
    (a decline would make the comparison vacuous cold-vs-cold)."""
    rng = random.Random(1000 + seed)
    nodes, bound = _fuzz_cluster(seed)
    sched, cache = _sched(nodes, bound)
    try:
        rp = ResidentPlanner(sched.resident_plan_view, cache)
        cold = SnapshotEncoder()

        # scale-up: pending pods only a template can absorb (plus a gang
        # label no NODE carries — pod-side keys past the node bucket must
        # not decline the template overlay)
        pending = [make_pod(f"pend{j}")
                   .req({"cpu": rng.choice(["20", "24"])})
                   .label(GANG_LABEL, "noise").obj()
                   for j in range(rng.randint(1, 3))]
        groups = [NodeGroup(name="ng-big", min_size=0, max_size=4,
                            template=make_node("ng-big-t").capacity(
                                {"cpu": "32", "memory": "64Gi",
                                 "pods": "32"}).obj())]
        up_r = simulate_scale_up(nodes, bound, pending, groups,
                                 encoder=cold, resident=rp)
        up_c = simulate_scale_up(nodes, bound, pending, groups,
                                 encoder=cold, resident=None)
        assert rp.stats()["hits"].get("autoscaler") == 1, rp.stats()
        assert _norm_up(up_r) == _norm_up(up_c)
        assert up_c, "scale-up parity must compare a real option"

        # scale-down: every node a candidate, shared-ledger drain proof
        cands = [n.metadata.name for n in nodes]
        down_r = simulate_scale_down(nodes, bound, cands,
                                     utilization_threshold=0.6,
                                     encoder=cold, resident=rp)
        down_c = simulate_scale_down(nodes, bound, cands,
                                     utilization_threshold=0.6,
                                     encoder=cold, resident=None)
        assert rp.stats()["hits"].get("autoscaler") == 2
        assert _norm_down(down_r) == _norm_down(down_c)

        # descheduler: drain the least-loaded occupied node
        per_node = {}
        for p in bound:
            per_node.setdefault(p.spec.node_name, []).append(p)
        if per_node:
            victim_node = min(per_node, key=lambda n: len(per_node[n]))
            sets = [CandidateSet(name=f"drain-{victim_node}",
                                 strategy="Fuzz",
                                 victims=per_node[victim_node],
                                 exclude_targets={victim_node})]
            ev_r = plan_evictions(nodes, bound, sets, encoder=cold,
                                  resident=rp)
            ev_c = plan_evictions(nodes, bound, sets, encoder=cold,
                                  resident=None)
            assert rp.stats()["hits"].get("descheduler") == 1
            assert _norm_ev(ev_r) == _norm_ev(ev_c)

        # gang defrag: a pending gang + the same consolidation candidates
        gang_pods = [make_pod(f"g{j}").req({"cpu": "2"})
                     .label(GANG_LABEL, "fuzz").obj() for j in range(2)]
        gsets = [CandidateSet(name=f"consolidate-{n}", strategy="GangFuzz",
                              victims=list(ps), exclude_targets={n})
                 for n, ps in sorted(per_node.items())]
        gp_r = plan_gang_defrag(nodes, bound, gang_pods, "fuzz", gsets,
                                encoder=cold, resident=rp)
        gp_c = plan_gang_defrag(nodes, bound, gang_pods, "fuzz", gsets,
                                encoder=cold, resident=None)
        assert rp.stats()["hits"].get("gangDefrag") == 1
        assert _norm_gang(gp_r) == _norm_gang(gp_c)
        assert not rp.stats()["declines"], rp.stats()
    finally:
        sched.close()


# --------------------------------------------------------- decline matrix

def test_decline_matrix_every_staleness_reason():
    """Each freshness violation must decline (cold fallback) with its
    reason recorded — never serve a view of a cluster the image doesn't
    hold."""
    nodes = [make_node(f"d{i}")
             .capacity({"cpu": "8", "memory": "16Gi", "pods": "32"}).obj()
             for i in range(4)]
    bound = [make_pod("db0").req({"cpu": "1"}).node("d0").obj()]
    sched, cache = _sched(nodes, bound)
    try:
        rp = ResidentPlanner(sched.resident_plan_view, cache)
        live = cache.list_nodes()
        assert rp.plan_view(live, bound, planner="t") is not None

        # planner observed fewer nodes than the image holds (and vice
        # versa the image must cover every observed node)
        assert rp.plan_view(live[:-1], bound, planner="t") is None
        # planner observed a bound pod the image doesn't hold
        ghost = make_pod("ghost").req({"cpu": "1"}).node("d1").obj()
        assert rp.plan_view(live, bound + [ghost], planner="t") is None

        # a drain in flight: winners folded device-side but not yet bound
        sched._pending.append({"sentinel": True})
        assert rp.plan_view(live, bound, planner="t") is None
        sched._pending.clear()

        # mesh epoch moved under the staged context
        sched._mesh_epoch += 1
        assert rp.plan_view(live, bound, planner="t") is None
        sched._mesh_epoch -= 1
        assert rp.plan_view(live, bound, planner="t") is not None

        # unconsumed delta-log entries the context never folded
        cache.add_node(make_node("d-new").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "32"}).obj())
        assert rp.plan_view(cache.list_nodes(), bound, planner="t") is None

        # context taint wins over everything downstream
        sched._drain_ctx["cs"].tainted = True
        assert rp.plan_view(live, bound, planner="t") is None

        assert rp.stats()["declines"]["t"] == {
            "node_set_skew": 1, "bound_set_skew": 1, "in_flight": 1,
            "mesh_epoch": 1, "stale_log": 1, "tainted": 1}
    finally:
        sched.close()


def test_no_ctx_declines_before_warmup():
    cache = SchedulerCache()
    for n in [make_node("c0").capacity({"cpu": "4", "memory": "8Gi",
                                        "pods": "16"}).obj()]:
        cache.add_node(n)
    sched = Scheduler(SchedulerConfiguration(batch_size=4), cache,
                      SchedulingQueue(), lambda pod, node: True)
    try:
        rp = ResidentPlanner(sched.resident_plan_view, cache)
        assert rp.plan_view(cache.list_nodes(), [], planner="t") is None
        assert rp.stats()["declines"]["t"] == {"no_ctx": 1}
    finally:
        sched.close()


# ------------------------------------------------- tenant drain quotas

def test_tenant_quota_mask_ranks_device_side():
    """One-hot x cumsum ranking on device: the (quota+1)-th victim of a
    tenant is refused, unlabeled (-1) victims are unlimited."""
    allowed = tenant_quota_mask([0, 0, 1, -1, 0], [2, 1])
    assert allowed.tolist() == [True, True, True, True, False]
    # -1 quota = unlimited
    assert tenant_quota_mask([0, 0, 0], [-1]).tolist() == [True] * 3


def test_tenant_drain_quota_blocks_whole_set_in_one_dispatch(monkeypatch):
    """End-to-end through Descheduler.plan: a set overdrawing a tenant's
    drain slots blocks WHOLE, a blocked set's victims STILL consume their
    tenants' slots (admission is a pure function of the single device
    verdict — no host-side re-ranking after a block), and exactly ONE
    quota dispatch serves the whole cycle."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.descheduler.descheduler import (
        Descheduler,
        DeschedulerConfiguration,
    )
    from kubernetes_tpu.store.store import ObjectStore
    import kubernetes_tpu.encode.overlay as overlay

    client = DirectClient(ObjectStore())

    # fleet isolation's validity gate hides other tenants' nodes from a
    # tenant-labeled pod, so every victim needs a same-tenant landing
    # zone; the busy-pool selector keeps victims off each other's drain
    # candidates (a cross-landing would block the later set for holding
    # simulated re-placements, before quota admission is even consulted)
    def node(name, tenant, pool=None, cpu="8"):
        b = make_node(name).capacity({"cpu": cpu, "memory": "16Gi",
                                      "pods": "32"})
        b = b.label(TENANT_LABEL, tenant)
        if pool:
            b = b.label("pool", pool)
        client.nodes().create(b.obj().to_dict())

    def pod(name, tenant, on, cpu="1", selector=None):
        b = make_pod(name).req({"cpu": cpu}).label(TENANT_LABEL, tenant)
        if selector:
            b = b.node_selector(selector)
        client.pods("default").create(b.node(on).obj().to_dict())

    node("q0", "acme")
    node("q1", "acme")
    node("q2", "acme", pool="busy")
    node("q3", "acme", pool="busy")
    node("q4", "zeta", pool="busy")
    busy = {"pool": "busy"}
    # drain/q0 (planned first): acme + zeta victims
    pod("acme-a", "acme", "q0", selector=busy)
    pod("zeta-a", "zeta", "q0", selector=busy)
    # drain/q1 (planned second): two acme victims
    pod("acme-b", "acme", "q1", selector=busy)
    pod("acme-c", "acme", "q1", selector=busy)
    # q2/q3 busy enough to dodge HighNodeUtilization, roomy enough to
    # absorb every acme victim in the re-placement proof
    for i, on in enumerate(["q2", "q2", "q3", "q3"]):
        pod(f"load-{i}", "acme", on, cpu="3")

    calls = []
    real = overlay.tenant_quota_mask
    monkeypatch.setattr(overlay, "tenant_quota_mask",
                        lambda ids, quotas: calls.append(len(ids))
                        or real(ids, quotas))
    d = Descheduler(client, DeschedulerConfiguration(
        strategies={"HighNodeUtilization": {"threshold": 0.3}},
        gang_defrag=False,
        tenant_drain_quotas={"acme": 2, "zeta": 0}))
    plan, gangs = d.plan()
    # drain/q0 overdraws zeta (quota 0) -> blocked whole, yet its acme
    # victim consumed acme slot #1; drain/q1's second acme victim then
    # lands at rank 2 >= quota 2 -> blocked too. Standalone, drain/q1
    # would have been admitted (2 victims against quota 2) — the block
    # proves blocked sets keep their device-assigned ranks.
    assert plan.blocked == {
        "drain/q0": "tenant drain quota exceeded",
        "drain/q1": "tenant drain quota exceeded",
    }, (plan.blocked, [s.name for s in plan.accepted])
    assert not plan.accepted
    assert calls == [4]  # ONE dispatch covered all four victims


# ------------------------------------------------- BackgroundPlanner

def test_background_planner_cycles_and_status():
    """The cadence: one shared ResidentPlanner wired to both planners,
    cycle summaries counting steady-window compiles, status published to
    the planner ConfigMap and rendered by ``ktpu status`` (text + json)."""
    from kubernetes_tpu.autoscaler.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.autoscaler.nodegroup import StaticNodeGroupProvider
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.descheduler.descheduler import (
        Descheduler,
        DeschedulerConfiguration,
    )
    from kubernetes_tpu.sched.bgplanner import (
        BackgroundPlanner,
        PLANNER_CONFIGMAP,
    )
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer

    server = APIServer().start()
    runner = None
    try:
        client = HTTPClient(server.url)
        # group-labeled = autoscaler-managed, so scale-down has candidates
        client.nodes().create_many(
            [make_node(f"bg{i}").capacity({"cpu": "8", "memory": "16Gi",
                                           "pods": "32"})
             .label(NODE_GROUP_LABEL, "bg-pool").obj().to_dict()
             for i in range(3)])
        client.pods("default").create_many(
            [make_pod(f"bgp{i}").req({"cpu": "2"}).node(f"bg{i}")
             .obj().to_dict() for i in range(2)])
        runner = SchedulerRunner(HTTPClient(server.url),
                                 SchedulerConfiguration(batch_size=4))
        runner.start(wait_sync=30.0, start_loop=False)
        assert runner.scheduler.warm_drain(
            [make_pod(f"bgw{i}").req({"cpu": "1"}).obj()
             for i in range(4)], slot_headroom=32)
        autoscaler = ClusterAutoscaler(
            HTTPClient(server.url),
            StaticNodeGroupProvider(HTTPClient(server.url), [NodeGroup(
                name="bg-pool", min_size=0, max_size=4,
                template=make_node("bg-t").capacity(
                    {"cpu": "2", "memory": "4Gi", "pods": "16"}).obj())]),
            scale_down_unneeded_s=10 ** 9)
        descheduler = Descheduler(HTTPClient(server.url),
                                  DeschedulerConfiguration())
        planner = BackgroundPlanner(client, runner.scheduler,
                                    autoscaler=autoscaler,
                                    descheduler=descheduler,
                                    descheduler_dry_run=True,
                                    warmup_cycles=1)
        # one planner, both consumers — the tentpole wiring
        assert autoscaler.resident is planner.resident
        assert descheduler.resident is planner.resident

        first = planner.run_once()
        assert "steadyCompiles" not in first      # warmup: gate unarmed
        second = planner.run_once()
        assert "steadyCompiles" in second          # steady: gate armed
        st = planner.status()
        assert st["cycles"] == 2
        assert set(st["planners"]) == {"autoscaler", "descheduler",
                                       "gangDefrag"}

        cm = client.resource("configmaps", "default").get(PLANNER_CONFIGMAP)
        published = json.loads(cm["data"]["status"])
        assert published["cycles"] == 2
        assert published["planners"]["autoscaler"]["hits"] + \
            published["planners"]["autoscaler"]["declines"] >= 1

        # ktpu status renders the Planners line, -o json carries the blob
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "status"], out=out) == 0
        text = out.getvalue()
        assert "Planners:      2 cycles" in text
        assert "steady compiles" in text
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "status",
                          "-o", "json"], out=out) == 0
        blob = json.loads(out.getvalue())
        assert blob["planner"]["cycles"] == 2
        assert blob["planner"]["planners"].keys() == \
            published["planners"].keys()
    finally:
        if runner is not None:
            runner.stop()
        server.stop()


# ------------------------------------------------- satellites

def test_auditor_emits_replayable_incident_trace(tmp_path):
    """A repro bundle converts to an ``incident-*.trace.jsonl`` sitting
    next to it — the ktpu scenario record --from-bundle conversion, run
    automatically on the fail-fast path."""
    from kubernetes_tpu.audit.auditor import InvariantAuditor
    from kubernetes_tpu.scenario.trace import Trace

    bundle = {"invariant": "capacity", "chaosSeed": 7,
              "podBatch": ["default/x1", "default/x2"]}
    path = tmp_path / "audit-20260807T000000Z-capacity.json"
    path.write_text(json.dumps(bundle))
    auditor = InvariantAuditor.__new__(InvariantAuditor)
    out = auditor._emit_trace(str(path))
    assert out and out.endswith(".trace.jsonl")
    assert "incident-20260807T000000Z-capacity" in out
    tr = Trace.load(out)
    assert {(e.ns, e.name) for e in tr.events if e.verb == "create"} >= \
        {("default", "x1"), ("default", "x2")}

    # a bundle without a pending batch has nothing to replay
    empty = tmp_path / "audit-20260807T000001Z-empty.json"
    empty.write_text(json.dumps({"invariant": "empty", "podBatch": []}))
    assert auditor._emit_trace(str(empty)) is None


@pytest.mark.scenario
def test_autoscaler_thrash_generator_deterministic():
    from kubernetes_tpu.scenario.generate import BUILTINS

    gen = BUILTINS["autoscaler-thrash"]
    for seed in (0, 1, 2):
        a = gen({}, seed=seed)
        b = gen({}, seed=seed)
        assert a.to_lines() == b.to_lines()
        assert len(a.events) > 0
    assert gen({}, seed=0).to_lines() != gen({}, seed=1).to_lines()
    # the shape: floor pods, per-swing bursts, survivor-trimming deletes
    tr = gen({"swings": 2, "burstPods": 6, "survivors": 1}, seed=3)
    verbs = {}
    for e in tr.events:
        verbs[e.verb] = verbs.get(e.verb, 0) + 1
    assert verbs["create"] > verbs["delete"] > 0


def test_fleet_rekey_maps_csi_topology_per_tenant():
    """Zone labels on nodes/PVs and PV nodeAffinity zone terms cross the
    fleet re-keying boundary with the tenant prefix, and unrekey restores
    the tenant's own view byte-for-byte."""
    from kubernetes_tpu.sched.fleet import rekey_for_tenant, unrekey_for_tenant

    pv = {"metadata": {"name": "pv1",
                       "labels": {"topology.kubernetes.io/zone": "us-a",
                                  "tier": "fast"}},
          "spec": {"capacity": {"storage": "10Gi"},
                   "nodeAffinity": {"required": {"nodeSelectorTerms": [
                       {"matchExpressions": [
                           {"key": "topology.kubernetes.io/zone",
                            "operator": "In", "values": ["us-a"]},
                           {"key": "tier", "operator": "In",
                            "values": ["fast"]}]}]}}}}
    out = rekey_for_tenant(2, "persistentvolumes", pv)
    assert out["metadata"]["name"] == "t2.pv1"
    assert out["metadata"]["labels"]["topology.kubernetes.io/zone"] == \
        "t2.us-a"
    assert out["metadata"]["labels"]["tier"] == "fast"  # not a zone key
    exprs = out["spec"]["nodeAffinity"]["required"][
        "nodeSelectorTerms"][0]["matchExpressions"]
    assert {e["key"]: e["values"] for e in exprs} == {
        "topology.kubernetes.io/zone": ["t2.us-a"], "tier": ["fast"]}
    back = unrekey_for_tenant(2, "persistentvolumes", out)
    assert back["metadata"]["labels"] == pv["metadata"]["labels"]
    assert back["spec"]["nodeAffinity"] == pv["spec"]["nodeAffinity"]

    node = {"metadata": {"name": "n1", "labels": {
        "topology.kubernetes.io/region": "us", "x": "y"}}}
    out_n = rekey_for_tenant(2, "nodes", node)
    assert out_n["metadata"]["labels"][
        "topology.kubernetes.io/region"] == "t2.us"
    assert unrekey_for_tenant(
        2, "nodes", out_n)["metadata"]["labels"] == node["metadata"]["labels"]


def test_preemption_static_mask_respects_dra_claim_state():
    """An unready claim holds the preemptor off every node; an allocated
    claim pins it to the allocation's node."""
    from kubernetes_tpu.ops.preemption import _static_mask
    from kubernetes_tpu.sched.dra import DraCatalog

    nodes = [make_node(f"p{i}").capacity({"cpu": "4", "memory": "8Gi",
                                          "pods": "16"}).obj()
             for i in range(3)]

    def pod_with(claim_name):
        p = make_pod("pre").req({"cpu": "1"}).priority(100).obj()
        p.spec.resource_claims = [{"name": "dev",
                                   "resourceClaimName": claim_name}]
        return p

    # referenced claim doesn't resolve -> unschedulable, all-False
    cat = DraCatalog.from_lists()
    assert not _static_mask(nodes, pod_with("missing"), dra=cat).any()

    # allocated claim pins to its node
    cat = DraCatalog.from_lists(claims=[{
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"devices": {"requests": []}},
        "status": {"allocation": {"nodeName": "p1"}, "reservedFor": []}}])
    mask = _static_mask(nodes, pod_with("c1"), dra=cat)
    assert mask.tolist() == [False, True, False]

    # no claims: unaffected
    free = make_pod("free").req({"cpu": "1"}).priority(100).obj()
    assert _static_mask(nodes, free, dra=cat).all()
