"""CSR flow: create -> approve -> signer controller issues a real X.509
certificate chained to the cluster CA.

Reference: ``pkg/controller/certificates/signer/signer.go`` +
``kubectl certificate approve``.
"""

import base64
import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.certificates import (
    HAVE_CRYPTOGRAPHY,
    CSRSigningController,
    approve_csr,
    deny_csr,
    make_csr_pem,
)

# X.509 issuance needs the optional ``cryptography`` package; without it the
# signer is disabled (controllers/manager.py drops it too) — clean skip, not
# a failure, exactly like any other optional-dependency suite
pytestmark = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY, reason="optional dependency 'cryptography' "
    "not installed (signer disabled)")
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def _run(client):
    ctrl = CSRSigningController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def _csr_obj(name, pem):
    return {"kind": "CertificateSigningRequest",
            "metadata": {"name": name},
            "spec": {"request": base64.b64encode(pem).decode(),
                     "signerName": "kubernetes.io/kube-apiserver-client",
                     "usages": ["digital signature", "key encipherment"]}}


def test_csr_signed_after_approval():
    from cryptography import x509
    client = DirectClient(ObjectStore())
    ctrl, factory = _run(client)
    try:
        pem, _key = make_csr_pem("alice", organizations=("dev",))
        res = client.resource("certificatesigningrequests", None)
        res.create(_csr_obj("alice-csr", pem))
        time.sleep(0.3)
        # pending CSRs are NOT signed
        assert "certificate" not in (res.get("alice-csr").get("status") or {})
        approve_csr(client, "alice-csr")
        assert wait_until(lambda: (res.get("alice-csr").get("status") or {})
                          .get("certificate"))
        cert_pem = base64.b64decode(res.get("alice-csr")["status"]
                                    ["certificate"])
        cert = x509.load_pem_x509_certificate(cert_pem)
        # subject preserved, issued by the cluster CA
        cn = cert.subject.get_attributes_for_oid(
            x509.oid.NameOID.COMMON_NAME)[0].value
        assert cn == "alice"
        assert cert.issuer == ctrl.ca_cert.subject
        # real chain: the CA's key verifies the signature
        cert.verify_directly_issued_by(ctrl.ca_cert)
    finally:
        ctrl.stop()
        factory.stop_all()


def test_denied_csr_never_signed():
    client = DirectClient(ObjectStore())
    ctrl, factory = _run(client)
    try:
        pem, _ = make_csr_pem("mallory")
        res = client.resource("certificatesigningrequests", None)
        res.create(_csr_obj("bad-csr", pem))
        deny_csr(client, "bad-csr")
        approve_csr(client, "bad-csr")  # denied wins even if later approved
        time.sleep(0.5)
        assert "certificate" not in (res.get("bad-csr").get("status") or {})
    finally:
        ctrl.stop()
        factory.stop_all()


def test_foreign_signer_left_alone():
    client = DirectClient(ObjectStore())
    ctrl, factory = _run(client)
    try:
        pem, _ = make_csr_pem("other")
        obj = _csr_obj("other-csr", pem)
        obj["spec"]["signerName"] = "example.com/custom-signer"
        res = client.resource("certificatesigningrequests", None)
        res.create(obj)
        approve_csr(client, "other-csr")
        time.sleep(0.5)
        assert "certificate" not in (res.get("other-csr").get("status") or {})
    finally:
        ctrl.stop()
        factory.stop_all()


def test_cli_certificate_approve():
    import io
    from kubernetes_tpu.cli.ktpu import main
    server = APIServer().start()
    try:
        pem, _ = make_csr_pem("cli-user")
        HTTPClient(server.url).resource(
            "certificatesigningrequests", None).create(
            _csr_obj("cli-csr", pem))
        out = io.StringIO()
        rc = main(["--server", server.url, "certificate", "approve",
                   "cli-csr"], out=out)
        assert rc == 0, out.getvalue()
        got = HTTPClient(server.url).resource(
            "certificatesigningrequests", None).get("cli-csr")
        assert any(c["type"] == "Approved"
                   for c in got["status"]["conditions"])
    finally:
        server.stop()


def test_unsignable_csr_fails_once_not_forever():
    """A malformed request records ONE terminal Failed condition — no
    hot loop of growing conditions."""
    client = DirectClient(ObjectStore())
    ctrl, factory = _run(client)
    try:
        res = client.resource("certificatesigningrequests", None)
        res.create({"kind": "CertificateSigningRequest",
                    "metadata": {"name": "mangled"},
                    "spec": {"request": "bm90LWEtY3Ny",  # not a CSR
                             "signerName":
                                 "kubernetes.io/kube-apiserver-client"}})
        approve_csr(client, "mangled")
        assert wait_until(lambda: any(
            c["type"] == "Failed"
            for c in (res.get("mangled").get("status") or {})
            .get("conditions") or []))
        time.sleep(0.6)  # would accumulate dozens of conditions if looping
        conds = [c for c in res.get("mangled")["status"]["conditions"]
                 if c["type"] == "Failed"]
        assert len(conds) == 1, conds
    finally:
        ctrl.stop()
        factory.stop_all()
