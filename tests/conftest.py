"""Test harness config: 8-device virtual CPU mesh, axon TPU tunnel disabled.

The image's sitecustomize (PYTHONPATH=/root/.axon_site) dials the single-chip
TPU tunnel at EVERY interpreter start when PALLAS_AXON_POOL_IPS is set;
concurrent clients contend for the chip claim and can hang for minutes. Tests
never need the real chip, so if the axon env leaks in we re-exec pytest once
with a scrubbed environment. Real-TPU benchmarking happens only in bench.py.
"""

import os
import sys

_SCRUBBED = "KUBERNETES_TPU_TEST_SCRUBBED"

if os.environ.get("PALLAS_AXON_POOL_IPS") and not os.environ.get(_SCRUBBED):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env[_SCRUBBED] = "1"
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
