"""Test harness config: 8-device virtual CPU mesh, axon TPU tunnel disabled.

The image's sitecustomize (PYTHONPATH=/root/.axon_site) registers an "axon"
PJRT plugin for the single-chip TPU tunnel when PALLAS_AXON_POOL_IPS is set.
Backend *initialization* (the dial) is lazy — it only happens when JAX first
resolves a platform — so forcing JAX_PLATFORMS=cpu before any backend use is
enough to keep tests off the chip. We previously re-exec'd pytest with a
scrubbed env, but pytest's capture plugin has already swapped fd 1/2 to a
temp file by the time conftest imports, so the exec'd run's output vanished.
Real-TPU benchmarking happens only in bench.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env setup, before any backend init)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
