"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax import.

Multi-chip shardings are validated on virtual CPU devices
(xla_force_host_platform_device_count); the driver's dryrun_multichip does the
same. Real-TPU benchmarking happens only in bench.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
