"""Test harness config: 8-device virtual CPU mesh, axon TPU tunnel disabled.

The image's sitecustomize (PYTHONPATH=/root/.axon_site) registers an "axon"
PJRT plugin for the single-chip TPU tunnel when PALLAS_AXON_POOL_IPS is set.
Backend *initialization* (the dial) is lazy — it only happens when JAX first
resolves a platform — so forcing JAX_PLATFORMS=cpu before any backend use is
enough to keep tests off the chip. We previously re-exec'd pytest with a
scrubbed env, but pytest's capture plugin has already swapped fd 1/2 to a
temp file by the time conftest imports, so the exec'd run's output vanished.
Real-TPU benchmarking happens only in bench.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after env setup, before any backend init)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ---- thread-leak detector -------------------------------------------------
# Watchdog/resolver/auditor restarts must never leak loops into later tests
# silently: product threads are daemons by contract (the process may exit
# under them), so any NON-daemon thread that outlives the test that started
# it is a harness bug — it would also hang the pytest process at exit.

import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

_THREAD_BASELINE: "set[int] | None" = None


def _leaked_nondaemon(baseline: "set[int]", grace_s: float = 2.0) -> list:
    """Live non-daemon threads not in ``baseline``, after letting
    shutdown-in-progress threads finish for up to ``grace_s``."""
    def live():
        return [t for t in threading.enumerate()
                if not t.daemon and t.is_alive()
                and t.ident not in baseline
                and t is not threading.main_thread()]
    leaked = live()
    deadline = time.time() + grace_s
    while leaked and time.time() < deadline:
        time.sleep(0.05)
        leaked = live()
    return leaked


@pytest.fixture(autouse=True)
def _no_thread_leaks():
    global _THREAD_BASELINE
    if _THREAD_BASELINE is None:  # session baseline: pytest's own threads
        _THREAD_BASELINE = {t.ident for t in threading.enumerate()
                            if not t.daemon}
    baseline = set(_THREAD_BASELINE)
    yield
    leaked = _leaked_nondaemon(baseline)
    if leaked:
        # absorb into the baseline so ONE leak fails ONE test, not every
        # test that follows it
        _THREAD_BASELINE.update(t.ident for t in leaked)
        pytest.fail(
            "non-daemon thread(s) leaked past the test: "
            + ", ".join(f"{t.name} ({t.ident})" for t in leaked),
            pytrace=False)
