"""Tracing hardening: ring-buffer truncation, id-based span linkage,
sampling, Chrome trace-event export, and the per-pod flight recorder."""

import json
import threading
import time

import pytest

from kubernetes_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    export_otlp_json,
    validate_chrome_trace,
)


# ------------------------------------------------------------------ ring

def test_ring_buffer_drops_oldest_and_counts():
    tr = Tracer(max_spans=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["s6", "s7", "s8", "s9"]
    assert tr.dropped == 6
    tr.reset()
    assert tr.spans() == [] and tr.dropped == 0


def test_max_spans_resize_preserves_newest():
    tr = Tracer(max_spans=8)
    for i in range(8):
        with tr.span(f"s{i}"):
            pass
    tr.max_spans = 3
    assert [s.name for s in tr.spans()] == ["s5", "s6", "s7"]
    # growing back keeps content and the new cap
    tr.max_spans = 100
    with tr.span("new"):
        pass
    assert [s.name for s in tr.spans()] == ["s5", "s6", "s7", "new"]


# ------------------------------------------------------------- id linkage

def test_span_ids_unique_and_parent_by_id():
    tr = Tracer()
    with tr.span("outer") as outer:
        with tr.span("mid") as mid:
            with tr.span("inner") as inner:
                pass
        with tr.span("sibling") as sib:
            pass
    ids = [s.span_id for s in tr.spans()]
    assert len(ids) == len(set(ids)) == 4
    assert inner.parent_id == mid.span_id
    assert mid.parent_id == outer.span_id
    assert sib.parent_id == outer.span_id
    assert outer.parent_id == 0
    # one trace: every span shares the root's trace id
    assert {s.trace_id for s in tr.spans()} == {outer.span_id}
    # display-name convenience still present
    assert inner.parent == "mid" and outer.parent is None


def test_same_name_spans_link_to_the_right_parent():
    """The old name-based linkage guessed; ids don't. Two same-named
    parents must each claim their own child."""
    tr = Tracer()
    parents = []
    for _ in range(2):
        with tr.span("cycle") as p:
            parents.append(p)
            with tr.span("child"):
                pass
    children = tr.spans("child")
    assert [c.parent_id for c in children] == [p.span_id for p in parents]
    assert children[0].trace_id != children[1].trace_id


def test_separate_roots_get_separate_traces():
    tr = Tracer()
    with tr.span("a") as a:
        pass
    with tr.span("b") as b:
        pass
    assert a.trace_id != b.trace_id


def test_nesting_is_per_thread():
    tr = Tracer()
    seen = {}

    def worker():
        with tr.span("worker-root") as sp:
            seen["worker"] = sp

    with tr.span("main-root") as main_sp:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # the worker's span must NOT have picked up main's stack as a parent
    assert seen["worker"].parent_id == 0
    assert seen["worker"].trace_id != main_sp.trace_id


# ---------------------------------------------------------------- sampling

def test_sampling_ratio_keeps_a_fraction():
    tr = Tracer(ratio=0.25)
    kept = 0
    for _ in range(100):
        with tr.span("s") as sp:
            kept += sp is not None
    assert kept == len(tr.spans()) == 25


# ------------------------------------------------------------- otlp export

def test_otlp_export_links_by_id_and_orphans_evicted_parents():
    tr = Tracer(max_spans=2)
    with tr.span("outer"):
        with tr.span("inner1"):
            pass
        with tr.span("inner2"):
            pass
    # ring holds [inner2, outer]; inner1 was evicted
    doc = export_otlp_json(tr)
    spans = {s["name"]: s for s in
             doc["resourceSpans"][0]["scopeSpans"][0]["spans"]}
    assert set(spans) == {"inner2", "outer"}
    assert spans["inner2"]["parentSpanId"] != ""
    assert spans["outer"]["parentSpanId"] == ""
    assert len(spans["outer"]["spanId"]) == 16
    assert len(spans["outer"]["traceId"]) == 32
    # a child exported while its parent is still OPEN (parent not yet in
    # the finished ring) must come out a root, not dangle a broken link
    tr2 = Tracer()
    with tr2.span("outer"):
        with tr2.span("inner"):
            pass
        doc2 = export_otlp_json(tr2)
    (only,) = doc2["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert only["name"] == "inner" and only["parentSpanId"] == ""


# ----------------------------------------------------------- chrome export

def test_export_chrome_schema_and_content(tmp_path):
    tr = Tracer()
    fl = FlightRecorder(enabled=True)
    with tr.span("scheduler/gang_dispatch", pods=3) as sp:
        fl.record("default/p0", "dispatch", span=sp)
    fl.record("default/p0", "bind", node="n0")
    doc = tr.export_chrome(path=str(tmp_path / "t.json"), flight=fl)
    assert validate_chrome_trace(doc) == []
    on_disk = json.loads((tmp_path / "t.json").read_text())
    assert validate_chrome_trace(on_disk) == []
    names = [e["name"] for e in doc["traceEvents"]]
    assert "scheduler/gang_dispatch" in names
    assert "dispatch" in names and "bind" in names
    # the pod's dispatch slice links back to the batch span by id
    ev = next(e for e in doc["traceEvents"] if e["name"] == "dispatch")
    assert ev["args"]["span_id"] == sp.span_id
    # per-pod track is named after the pod key
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(e["args"].get("name") == "default/p0" for e in meta)


def test_export_chrome_max_events_keeps_newest():
    tr = Tracer()
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    doc = tr.export_chrome(flight=FlightRecorder(enabled=False),
                           max_events=3)
    xs = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert xs == ["s7", "s8", "s9"]


def test_export_chrome_bounds_flight_tracks():
    tr = Tracer()
    fl = FlightRecorder(enabled=True)
    for i in range(10):
        fl.record(f"ns/p{i}", "informer")
    doc = tr.export_chrome(flight=fl, max_flight_pods=2)
    tracks = {e["args"]["name"] for e in doc["traceEvents"]
              if e["name"] == "thread_name" and e["pid"] == 2}
    assert tracks == {"ns/p8", "ns/p9"}  # newest-inserted kept


def test_validate_chrome_trace_rejects_garbage():
    assert validate_chrome_trace({}) == ["traceEvents missing or not a list"]
    bad = {"traceEvents": [{"ph": "X", "name": "x", "ts": 1.0, "pid": 1}]}
    assert any("dur" in p for p in validate_chrome_trace(bad))
    assert validate_chrome_trace({"traceEvents": [
        {"ph": "i", "name": "x", "ts": 0.0, "s": "t", "pid": 2}]}) == []


# --------------------------------------------------------- flight recorder

def test_flight_recorder_per_pod_ring_and_pod_eviction():
    fl = FlightRecorder(max_pods=2, max_events=3, enabled=True)
    for i in range(5):
        fl.record("ns/a", f"stage{i}")
    tl = fl.timeline("ns/a")
    assert [e["stage"] for e in tl] == ["stage2", "stage3", "stage4"]
    fl.record("ns/b", "informer")
    fl.record("ns/c", "informer")  # evicts ns/a (oldest inserted)
    assert fl.timeline("ns/a") == []
    assert fl.stats()["droppedPods"] == 1
    assert set(fl.keys()) == {"ns/b", "ns/c"}


def test_flight_recorder_disabled_is_noop():
    fl = FlightRecorder(enabled=False)
    fl.record("ns/a", "informer")
    assert fl.timeline("ns/a") == [] and fl.stats()["pods"] == 0


def test_flight_recorder_bind_observes_e2e_histograms():
    from kubernetes_tpu.metrics.registry import E2E_DURATION, E2E_SCHEDULING
    base_e2e = E2E_SCHEDULING.count()
    base_sli = E2E_DURATION.count()
    fl = FlightRecorder(enabled=True)
    fl.record("ns/p", "informer")
    fl.record("ns/p", "queue_add")
    fl.record("ns/p", "dispatch")
    fl.record("ns/p", "bind", node="n0")
    assert E2E_SCHEDULING.count() == base_e2e + 1
    assert E2E_DURATION.count() == base_sli + 1


def test_flight_recorder_new_incarnation_resets_closed_timeline():
    """A recreated pod under the same ns/name must not stitch onto the
    old incarnation's bound timeline (the gap between them would poison
    the derived e2e histogram)."""
    from kubernetes_tpu.metrics.registry import E2E_SCHEDULING
    fl = FlightRecorder(enabled=True)
    fl.record("ns/p", "informer")
    fl.record("ns/p", "bind", node="n0")
    t_gap = time.time()
    fl.record("ns/p", "informer")  # second incarnation
    tl = fl.timeline("ns/p")
    assert [e["stage"] for e in tl] == ["informer"]
    assert tl[0]["ts"] >= t_gap
    base = E2E_SCHEDULING.count()
    fl.record("ns/p", "bind", node="n1")
    assert E2E_SCHEDULING.count() == base + 1
    # the new observation spans only the second incarnation, and a
    # requeue mid-flight does NOT reset (same incarnation)
    fl.record("ns/p", "informer")
    fl.record("ns/p", "requeue")
    fl.record("ns/p", "dispatch")
    assert [e["stage"] for e in fl.timeline("ns/p")] == [
        "informer", "requeue", "dispatch"]


def test_flight_recorder_timeline_attrs_and_span_link():
    tr = Tracer()
    fl = FlightRecorder(enabled=True)
    with tr.span("batch") as sp:
        fl.record("ns/p", "dispatch", span=sp, depth=2)
    (ev,) = fl.timeline("ns/p")
    assert ev["span_id"] == sp.span_id
    assert ev["attrs"] == {"depth": 2}
