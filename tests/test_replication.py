"""Replicated store: quorum writes, elections, failover, resync.

Reference role: etcd's raft quorum behind the apiserver
(``apiserver/pkg/storage/etcd3``). These tests drive a 3-node group over
the real HTTP peer transport: writes commit only with a quorum, a dead
leader is replaced by the most up-to-date follower, minority partitions
cannot commit, and lagging/diverged replicas resync from the leader.
"""

import time

import pytest

from kubernetes_tpu.store.replication import (NotLeader, QuorumLost,
                                              RaftNode, ReplicatedStore)
from kubernetes_tpu.store.store import ObjectStore


def wait_until(fn, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def _cluster(n=3):
    """n RaftNodes wired into one group (ports chosen first)."""
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    nodes = []
    for i in range(n):
        peers = {f"n{j}": f"http://127.0.0.1:{ports[j]}"
                 for j in range(n) if j != i}
        nodes.append(RaftNode(f"n{i}", ObjectStore(), peers,
                              port=ports[i]))
    return nodes


def _load_budget(base: float) -> float:
    """Load-aware time budget: scale ``base`` by how crowded this
    interpreter actually is. The raft tickers share the GIL with every
    thread the rest of the suite leaked (hundreds under the full run —
    conftest only polices non-daemon leaks), so silence detection (1.2s)
    + prevote round trips (2s timeouts) stretch a single election attempt
    to multiple seconds and split votes retry from scratch. A fixed
    budget is either flaky under load or slow alone; this one is sized by
    the live thread count, so the quiet single-test run stays fast and
    the full-suite run gets the headroom it demonstrably needs (flaked
    since PR 1; PR 12 only widened the constants)."""
    import threading
    return base * min(4.0, max(1.0, len(threading.enumerate()) / 40.0))


def _leader(nodes, timeout=None):
    if timeout is None:
        timeout = _load_budget(30.0)
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [nd for nd in nodes
                   if not nd._stop.is_set() and nd.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no single leader elected: "
                         + str([nd.status() for nd in nodes]))


def _cm(name, v="1"):
    return {"kind": "ConfigMap", "metadata": {"name": name},
            "data": {"v": v}}


def test_quorum_write_replicates_to_followers():
    nodes = _cluster(3)
    try:
        leader = _leader(nodes)
        rs = ReplicatedStore(leader)
        rs.create("ConfigMap", _cm("a"))
        followers = [nd for nd in nodes if nd is not leader]
        for f in followers:
            assert wait_until(lambda f=f: any(
                (o.get("metadata") or {}).get("name") == "a"
                for o, in [(x,) for x in
                           f.store.list("ConfigMap")[0]])), f.status()
        # follower watchers observed the replicated event
        w = followers[0].store.watch("ConfigMap", since_rv=0)
        ev = w.get(timeout=2.0)
        assert ev is not None and ev.object["metadata"]["name"] == "a"
        w.stop()
    finally:
        for nd in nodes:
            nd.stop()


def test_mutation_on_follower_rejected():
    nodes = _cluster(3)
    try:
        leader = _leader(nodes)
        follower = next(nd for nd in nodes if nd is not leader)
        with pytest.raises(NotLeader):
            ReplicatedStore(follower).create("ConfigMap", _cm("x"))
    finally:
        for nd in nodes:
            nd.stop()


def test_leader_failover_and_continued_writes():
    nodes = _cluster(3)
    try:
        leader = _leader(nodes)
        ReplicatedStore(leader).create("ConfigMap", _cm("pre"))
        leader.stop()
        survivors = [nd for nd in nodes if nd is not leader]
        new_leader = _leader(survivors)
        assert new_leader is not leader
        # the committed pre-failover write survived the election
        objs, _ = new_leader.store.list("ConfigMap")
        assert any(o["metadata"]["name"] == "pre" for o in objs)
        # and the group still commits (2/3 alive = quorum); the commit
        # gate itself gets the suite-load budget too
        ReplicatedStore(new_leader,
                        commit_timeout=_load_budget(15.0)).create(
                            "ConfigMap", _cm("post"))
        other = next(nd for nd in survivors if nd is not new_leader)
        assert wait_until(lambda: any(
            o["metadata"]["name"] == "post"
            for o in other.store.list("ConfigMap")[0]),
            timeout=_load_budget(15.0))
    finally:
        for nd in nodes:
            nd.stop()


def test_minority_cannot_commit():
    nodes = _cluster(3)
    try:
        leader = _leader(nodes)
        followers = [nd for nd in nodes if nd is not leader]
        for f in followers:
            f.stop()  # majority gone
        rs = ReplicatedStore(leader, commit_timeout=1.0)
        with pytest.raises((QuorumLost, NotLeader)):
            rs.create("ConfigMap", _cm("lost"))
    finally:
        for nd in nodes:
            nd.stop()


def test_downed_replica_rejoins_via_snapshot():
    """A replica that died and lost its state rejoins empty on the same
    port: the leader detects the gap and installs a snapshot (raft's
    InstallSnapshot shape)."""
    nodes = _cluster(3)
    try:
        leader = _leader(nodes)
        lagger = next(nd for nd in nodes if nd is not leader)
        port, node_id, peers = lagger.port, lagger.node_id, lagger.peers
        lagger.stop()
        rs = ReplicatedStore(leader)
        for i in range(5):
            rs.create("ConfigMap", _cm(f"c{i}"))
        reborn = RaftNode(node_id, ObjectStore(), peers, port=port)
        nodes.append(reborn)
        assert wait_until(lambda: len(
            reborn.store.list("ConfigMap")[0]) == 5, timeout=15.0), \
            reborn.status()
    finally:
        for nd in nodes:
            nd.stop()


def test_apiserver_over_replicated_store():
    """The control plane on top: an APIServer backed by the leader's
    ReplicatedStore — HTTP writes quorum-commit and appear on followers."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.store.apiserver import APIServer
    nodes = _cluster(3)
    api = None
    try:
        leader = _leader(nodes)
        api = APIServer(store=ReplicatedStore(leader)).start()
        c = HTTPClient(api.url)
        c.resource("configmaps", "default").create(_cm("via-http"))
        follower = next(nd for nd in nodes if nd is not leader)
        assert wait_until(lambda: any(
            (o.get("metadata") or {}).get("name") == "via-http"
            for o in follower.store.list("ConfigMap")[0]))
    finally:
        if api is not None:
            try:
                api.stop()
            except Exception:
                pass
        for nd in nodes:
            nd.stop()


def test_replicated_writes_are_wal_durable_on_followers(tmp_path):
    """Quorum-acked entries must survive a follower restart from ITS OWN
    disk — replication without follower durability would lose acknowledged
    writes if the leader's disk died (the etcd contract is quorum of
    DISKS, not of memories)."""
    import socket as _socket
    socks, ports = [], []
    for _ in range(3):
        sk = _socket.socket()
        sk.bind(("127.0.0.1", 0))
        ports.append(sk.getsockname()[1])
        socks.append(sk)
    for sk in socks:
        sk.close()
    nodes = []
    dirs = [str(tmp_path / f"d{i}") for i in range(3)]
    for i in range(3):
        peers = {f"n{j}": f"http://127.0.0.1:{ports[j]}"
                 for j in range(3) if j != i}
        nodes.append(RaftNode(f"n{i}", ObjectStore(data_dir=dirs[i]),
                              peers, port=ports[i]))
    try:
        leader = _leader(nodes)
        ReplicatedStore(leader).create("ConfigMap", _cm("durable"))
        follower = next(nd for nd in nodes if nd is not leader)
        f_idx = nodes.index(follower)
        assert wait_until(lambda: any(
            o["metadata"]["name"] == "durable"
            for o in follower.store.list("ConfigMap")[0]))
        follower.store.close()
        # a fresh process restoring the follower's disk sees the write
        restored = ObjectStore(data_dir=dirs[f_idx])
        objs, _ = restored.list("ConfigMap")
        assert any(o["metadata"]["name"] == "durable" for o in objs), objs
        restored.close()
    finally:
        for nd in nodes:
            nd.stop()
