"""Pre-sharded double-buffered batch staging (sched/staging.py) + the
resident-totals host shadow.

The arena's contract: a redeemed swap is bit- and sharding-identical to the
legacy inline ``device_put``, and every invalidation path (mesh reshape,
upload failure, dead stager thread, buffer-full submit) DECLINES into the
inline fallback — placements never depend on which path staged the batch.
The invalidation matrix runs the live scheduler through mesh reshape,
catalog-epoch bumps, sticky row-width growth, ctx taint, and mid-stream
churn with the arena on vs off and diffs placements bit-for-bit.

Mesh-executing tests carry the ``multichip`` marker and gate on the
test_mesh GSPMD canary, like test_mesh_live.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.config.types import SchedulerConfiguration, validate
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.sched.staging import ResidentShadow, StagingArena
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n=32):
    return [make_node(f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .obj() for i in range(n)]


def _pods(n=24, prefix="p", cpu="500m"):
    return [make_pod(f"{prefix}{i:03d}")
            .req({"cpu": cpu, "memory": "256Mi"})
            .label("app", f"g{i % 3}").obj() for i in range(n)]


def _scheduler(mesh_shape=None, nodes=None, batch_size=16, warm=True,
               staging=True):
    cfg = SchedulerConfiguration(batch_size=batch_size, max_drain_batches=2,
                                 mesh_shape=mesh_shape,
                                 staging_arena=staging)
    validate(cfg)
    cache = SchedulerCache()
    for n in (nodes or _nodes()):
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    if warm:
        warm_pods = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
                     for i in range(batch_size)]
        assert sched.warm_drain(warm_pods, slot_headroom=256)
    return sched, cache, queue, log


def _run_to_empty(sched, queue, pods, rounds=30):
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if not sched._pending and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()
    return bound


def _mesh_or_skip():
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    import test_mesh
    usable, why = test_mesh._sharded_backend_verdict((1, 2))
    if not usable:
        pytest.skip(why)
    from kubernetes_tpu.parallel.mesh import mesh_from_shape
    return mesh_from_shape((1, 2))


def _stack(P=8, R=3):
    """A tiny stacked-batch-shaped pytree (plain dict works for the arena —
    it stages any pytree of numpy leaves)."""
    rng = np.random.default_rng(7)
    return {"requests": rng.integers(0, 100, (2, P, R)).astype(np.int32),
            "pod_valid": np.ones((2, P), bool),
            "labels": rng.integers(-1, 9, (2, P, 4)).astype(np.int32)}


# ---- presplit parity -----------------------------------------------------

@pytest.mark.multichip
def test_presplit_matches_device_put():
    mesh = _mesh_or_skip()
    import jax
    from kubernetes_tpu.parallel.mesh import presplit_stack, stack_shardings
    stack = _stack(P=8)
    a = presplit_stack(mesh, stack)
    b = jax.device_put(stack, stack_shardings(mesh, stack))
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert x.sharding == y.sharding


# ---- arena unit contract -------------------------------------------------

@pytest.mark.multichip
def test_arena_submit_redeem_swap():
    mesh = _mesh_or_skip()
    arena = StagingArena()
    stack = _stack()
    t = arena.submit(stack, mesh)
    assert t is not None
    staged = arena.redeem(t, mesh)
    assert staged is not None
    import jax
    for x, y in zip(jax.tree_util.tree_leaves(staged),
                    jax.tree_util.tree_leaves(stack)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    st = arena.stats()
    assert st["swaps"] == 1 and st["fallbacks"] == 0
    assert st["bytesStaged"] > 0 and st["inflight"] == 0
    arena.close()


@pytest.mark.multichip
def test_arena_invalidate_declines_redeem():
    """A mesh install/reshape between submit and redeem must decline the
    swap — the staged buffers carry the OLD layout."""
    mesh = _mesh_or_skip()
    arena = StagingArena()
    t = arena.submit(_stack(), mesh)
    arena.invalidate()
    assert arena.redeem(t, mesh) is None
    assert arena.stats()["fallbacks"] == 1
    # a redeem against a DIFFERENT active mesh declines too
    t2 = arena.submit(_stack(), mesh)
    assert arena.redeem(t2, None) is None
    arena.close()


@pytest.mark.multichip
def test_arena_double_buffer_bound_and_failure():
    mesh = _mesh_or_skip()
    arena = StagingArena(depth=2)
    t1 = arena.submit(_stack(), mesh)
    t2 = arena.submit(_stack(), mesh)
    assert t1 is not None and t2 is not None
    # full-buffer decline, made deterministic: let both uploads land
    # (queue quiet, no concurrent decrements), then pin the in-flight
    # counter at depth — a live three-submit assertion would race the
    # stager's slot release
    assert t1.done.wait(10.0) and t2.done.wait(10.0)
    with arena._lock:
        arena._inflight = arena.depth
    assert arena.submit(_stack(), mesh) is None
    with arena._lock:
        arena._inflight = 0
    assert arena.redeem(t1, mesh) is not None
    assert arena.redeem(t2, mesh) is not None
    # an upload that raises surfaces as a declined redeem, not a crash
    t3 = arena.submit({"bad": object()}, mesh)  # not an array: upload fails
    assert arena.redeem(t3, mesh) is None
    # and the stager thread survives to serve the next submit
    t4 = arena.submit(_stack(), mesh)
    assert arena.redeem(t4, mesh) is not None
    arena.close()


@pytest.mark.multichip
def test_arena_unredeemed_tickets_do_not_leak_slots():
    """A cycle that dies between submit and redeem must not pin a depth
    slot: the slot frees when the UPLOAD finishes, so abandoned tickets
    can never disable the arena for the process lifetime."""
    mesh = _mesh_or_skip()
    arena = StagingArena(depth=2)
    for _ in range(4):  # > 2x depth abandoned tickets
        t = arena.submit(_stack(), mesh)
        assert t is not None
        assert t.done.wait(10.0)
        # never redeemed — the exception-unwound-cycle case
    assert arena.stats()["inflight"] == 0
    t = arena.submit(_stack(), mesh)
    assert arena.redeem(t, mesh) is not None
    arena.close()


def test_single_device_submit_is_none_and_inline_counts_bytes():
    """Single-device: no arena tickets; stage_drain_batch is one EXPLICIT
    device_put whose bytes land on the inline counter."""
    from kubernetes_tpu.metrics.registry import STAGE_BYTES
    cache = SchedulerCache()
    assert cache.stage_submit(_stack()) is None
    before = STAGE_BYTES.get({"path": "inline"})
    staged = cache.stage_drain_batch(_stack())
    import jax
    assert all(hasattr(l, "sharding")
               for l in jax.tree_util.tree_leaves(staged))
    assert STAGE_BYTES.get({"path": "inline"}) > before


def test_config_and_env_disable_staging(monkeypatch):
    cfg = SchedulerConfiguration.from_dict({"stagingArena": False})
    assert cfg.staging_arena is False
    cache = SchedulerCache()
    cache.configure_staging(False)
    assert cache.staging_stats()["enabled"] is False
    monkeypatch.setenv("KTPU_STAGE_ARENA", "0")
    cache2 = SchedulerCache()
    cache2.configure_staging(True)  # env wins OFF for bench A/Bs
    assert cache2.staging_stats()["enabled"] is False


# ---- live invalidation matrix: arena on == arena off, bit-identical ------

def _matrix_scenario(sched, cache, queue, scenario):
    """One churny workload with a mid-run invalidation event; returns the
    placement map."""
    bound = _run_to_empty(sched, queue, _pods(24))
    if scenario == "mesh_reshape":
        sched.set_mesh(None)
    elif scenario == "catalog_epoch":
        # namespace-label churn bumps the encoder's pod epoch: cached row
        # packs invalidate, the staged copy of ALREADY-encoded stacks is
        # unaffected (it was cut after encode) — placements must not move
        cache.update_namespace({"metadata": {"name": "default",
                                             "labels": {"team": "a"}}})
    elif scenario == "row_width_growth":
        # wider pods promote the sticky bucket widths -> the next stack's
        # shapes exceed the ctx's compiled shapes -> rebuild + restage
        wide = [make_pod(f"w{i}").req({"cpu": "100m"})
                .toleration("k1", "v1").toleration("k2", "v2")
                .toleration("k3", "v3").obj() for i in range(4)]
        bound += _run_to_empty(sched, queue, wide)
    elif scenario == "ctx_taint":
        sched.taint_ctx()
    elif scenario == "churn_mid_stage":
        cache.add_node(
            make_node("late-node")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", "late-node").obj())
    bound += _run_to_empty(sched, queue, _pods(24, prefix="q"))
    return bound


@pytest.mark.multichip
@pytest.mark.parametrize("scenario", ["mesh_reshape", "catalog_epoch",
                                      "row_width_growth", "ctx_taint",
                                      "churn_mid_stage"])
def test_invalidation_matrix_parity_vs_legacy_staging(scenario):
    """Every invalidation event must fall back to the legacy device_put
    path with bit-identical placements (arena on vs stagingArena off)."""
    _mesh_or_skip()
    placements = {}
    for staging in (True, False):
        sched, cache, queue, log = _scheduler(mesh_shape=(1, 2),
                                              staging=staging)
        if sched._mesh is None:
            pytest.skip("mesh unavailable on this backend")
        bound = _matrix_scenario(sched, cache, queue, scenario)
        expected = 48 + (4 if scenario == "row_width_growth" else 0)
        assert bound == expected, f"{scenario} staging={staging}: {bound}"
        placements[staging] = dict(log)
        if staging:
            st = cache.staging_stats()
            assert st["enabled"] and st["submits"] >= 1
        sched.close()
    assert placements[True] == placements[False], scenario


@pytest.mark.multichip
def test_steady_state_swaps_track_dispatches():
    """A churn-free steady state serves (nearly) every dispatch from a
    buffer swap: fallbacks stay at zero once the context is warm."""
    _mesh_or_skip()
    sched, cache, queue, log = _scheduler(mesh_shape=(1, 2))
    if sched._mesh is None:
        pytest.skip("mesh unavailable on this backend")
    bound = _run_to_empty(sched, queue, _pods(32))
    bound += _run_to_empty(sched, queue, _pods(32, prefix="q"))
    assert bound == 64
    st = cache.staging_stats()
    assert st["swaps"] >= 2, st
    assert st["fallbacks"] == 0, st
    from kubernetes_tpu.metrics.registry import (STAGE_BUFFER_REUSE,
                                                 STAGE_BYTES)
    assert STAGE_BYTES.get({"path": "arena"}) > 0
    assert STAGE_BUFFER_REUSE.get() >= st["swaps"]
    sched.close()


# ---- resident-totals host shadow -----------------------------------------

def test_resident_shadow_unit():
    sh = ResidentShadow(np.full((4, 2), 100, np.int32),
                        np.zeros((4, 2), np.int32))
    pod = make_pod("x").req({"cpu": "1"}).obj()
    sh.fold_winners([(pod, 1), (pod, 1)])
    assert sh.arrays() is None  # pending winners: behind until catch_up
    sh.catch_up(lambda p: np.array([3, 1], np.int32))
    alloc, req = sh.arrays()
    assert req[1].tolist() == [6, 2]
    # patch mirror: reset row 2, rewrite row 0's allocatable, add a delta
    patch = {"node_row": np.array([0, 2, -1], np.int32),
             "n_alloc": np.array([[7, 7], [0, 0], [0, 0]], np.int32),
             "n_reset": np.array([False, True, False]),
             "req_delta": np.full((4, 2), 1, np.int32)}
    sh.req[2] = 50
    sh.apply_patch(patch)
    alloc, req = sh.arrays()
    assert alloc[0].tolist() == [7, 7]
    assert req[2].tolist() == [1, 1]      # reset then delta
    assert req[1].tolist() == [7, 3]
    # a failing catch_up poisons the shadow instead of lying
    sh.fold_winners([(pod, 0)])
    sh.catch_up(lambda p: (_ for _ in ()).throw(RuntimeError("boom")))
    assert sh.ok is False and sh.arrays() is None
    # order contract: a patch applied with winner folds still pending
    # poisons rather than mis-mirroring (on device the folds happened
    # BEFORE the patch — a reset row must zero them too)
    sh2 = ResidentShadow(np.full((4, 2), 100, np.int32),
                         np.zeros((4, 2), np.int32))
    sh2.fold_winners([(pod, 1)])
    sh2.apply_patch(patch)
    assert sh2.ok is False and sh2.arrays() is None


def test_shadow_matches_device_totals_through_churn():
    """After drains + churn patches + winner folds, the host shadow equals
    a device readback of the resident totals bit-for-bit (the wave's
    zero-round-trip source is exact, not approximate)."""
    import jax
    sched, cache, queue, log = _scheduler()
    bound = _run_to_empty(sched, queue, _pods(24))
    cache.add_node(
        make_node("late-node")
        .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
        .label("kubernetes.io/hostname", "late-node").obj())
    bound += _run_to_empty(sched, queue, _pods(16, prefix="late"))
    assert bound == 40
    assert not sched._pending
    ctx = sched._drain_ctx
    assert ctx is not None and not ctx["cs"].tainted
    shadow = ctx["shadow"]
    shadow.catch_up(
        lambda p: cache.request_vector(p, ctx["cs"].resources))
    got = shadow.arrays()
    assert got is not None and shadow.ok
    alloc_s, req_s = got
    alloc_d, req_d = jax.device_get((ctx["ct"].allocatable,
                                     ctx["ct"].requested))
    np.testing.assert_array_equal(alloc_s, np.asarray(alloc_d, np.int64))
    np.testing.assert_array_equal(req_s, np.asarray(req_d, np.int64))
    sched.close()


def test_wave_reads_shadow_not_device(monkeypatch):
    """A preemption wave riding the resident context serves cluster totals
    from the host shadow — and nominates identically to the snapshot
    path."""
    nodes = _nodes(4)
    outcomes = {}
    for use_shadow in (True, False):
        sched, cache, queue, log = _scheduler(nodes=nodes, batch_size=8)
        # saturate: 4 nodes x 8 cpu, low-prio pods eat all of it
        low = [make_pod(f"low{i}").req({"cpu": "4"}).priority(1).obj()
               for i in range(8)]
        assert _run_to_empty(sched, queue, low) == 8
        served = []
        if use_shadow:
            orig = ResidentShadow.arrays

            def spy(self):
                got = orig(self)
                if got is not None:
                    served.append(1)
                return got
            monkeypatch.setattr(ResidentShadow, "arrays", spy)
        else:
            sched._drain_ctx["shadow"] = None
        high = [make_pod(f"hi{i}").req({"cpu": "4"}).priority(100).obj()
                for i in range(2)]
        for p in high:
            queue.add(p)
        deadline = time.time() + 30
        while time.time() < deadline:
            sched.run_once(wait=0.01)
            sched._resolve_pending()
            if all(sched._nominated.get(p.key) or cache.is_bound(p.key)
                   for p in high):
                break
        noms = {p.metadata.name:
                (sched._nominated.get(p.key) or (None,))[0]
                for p in high}
        if use_shadow:
            assert served, "wave never read the shadow totals"
            monkeypatch.setattr(ResidentShadow, "arrays", orig)
        outcomes[use_shadow] = noms
        sched.close()
    assert outcomes[True] == outcomes[False]
