"""Golden cases for the relational-plugin semantic corners: explicit
``namespaces``/``namespaceSelector`` lists, ``matchLabelKeys``/
``mismatchLabelKeys``, spread ``minDomains`` and node-inclusion policies.

Reference: podtopologyspread/{common,filtering}.go, interpodaffinity/
filtering.go (namespace merging via mergeAffinityTermNamespacesIfNotEmpty).
Every case diffs the FULL tensor feasibility (filters + spread + inter-pod)
against the serial oracle bit-for-bit, then asserts the expected mask.
"""

import numpy as np

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.schedule_step import evaluate
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def both_masks(nodes, pods, bound=None, namespace_labels=None):
    enc = SnapshotEncoder()
    if namespace_labels:
        enc.set_namespaces(namespace_labels)
    ct, meta = enc.encode_cluster(nodes, bound or [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    res = evaluate(ct, pb, topo_keys=meta.topo_keys)
    tm = np.asarray(res.feasible)[:len(pods), :len(nodes)]
    orc = OracleScheduler(nodes, bound or [],
                          namespace_labels=namespace_labels)
    om = np.asarray([orc.feasible(p)[0] for p in pods])
    np.testing.assert_array_equal(
        tm, om, err_msg=f"pods={[p.key for p in pods]}")
    return tm


def zone_nodes(n=3):
    return [make_node(f"n{i}").capacity({"cpu": "8", "pods": "20"})
            .label("zone", f"z{i}").obj() for i in range(n)]


# ----------------------------------------------------------- namespaces list

def test_anti_affinity_explicit_namespaces():
    nodes = zone_nodes(2)
    bound = [make_pod("other", namespace="team-b").label("app", "db")
             .node("n0").obj()]
    # own-namespace term: does NOT see team-b's pod
    own = make_pod("own").pod_anti_affinity("zone", {"app": "db"}).obj()
    # explicit namespaces term: sees it
    explicit = make_pod("explicit").pod_anti_affinity(
        "zone", {"app": "db"}, namespaces=["team-b"]).obj()
    tm = both_masks(nodes, [own, explicit], bound)
    np.testing.assert_array_equal(tm, [[True, True], [False, True]])


def test_affinity_explicit_namespaces():
    nodes = zone_nodes(2)
    bound = [make_pod("web", namespace="prod").label("app", "web")
             .node("n1").obj()]
    pod = make_pod("follower").pod_affinity(
        "zone", {"app": "web"}, namespaces=["prod"]).obj()
    miss = make_pod("wrong-ns").pod_affinity(
        "zone", {"app": "web"}, namespaces=["staging"]).obj()
    tm = both_masks(nodes, [pod, miss], bound)
    np.testing.assert_array_equal(tm, [[False, True], [False, False]])


def test_namespace_selector():
    nodes = zone_nodes(2)
    ns_labels = {"default": {}, "team-a": {"tier": "gold"},
                 "team-b": {"tier": "bronze"}}
    bound = [make_pod("gold-db", namespace="team-a").label("app", "db")
             .node("n0").obj(),
             make_pod("bronze-db", namespace="team-b").label("app", "db")
             .node("n1").obj()]
    pod = make_pod("avoids-gold").pod_anti_affinity(
        "zone", {"app": "db"}, namespace_selector={"tier": "gold"}).obj()
    tm = both_masks(nodes, [pod], bound, namespace_labels=ns_labels)
    np.testing.assert_array_equal(tm, [[False, True]])


def test_namespace_selector_ors_with_list():
    nodes = zone_nodes(3)
    ns_labels = {"default": {}, "team-a": {"tier": "gold"}, "team-b": {}}
    bound = [make_pod("a", namespace="team-a").label("app", "db").node("n0").obj(),
             make_pod("b", namespace="team-b").label("app", "db").node("n1").obj(),
             make_pod("c", namespace="default").label("app", "db").node("n2").obj()]
    pod = make_pod("avoid-both").pod_anti_affinity(
        "zone", {"app": "db"}, namespaces=["team-b"],
        namespace_selector={"tier": "gold"}).obj()
    tm = both_masks(nodes, [pod], bound, namespace_labels=ns_labels)
    # list covers team-b (n1), selector covers team-a (n0); default (n2) ok
    np.testing.assert_array_equal(tm, [[False, False, True]])


def test_empty_namespace_selector_matches_all():
    nodes = zone_nodes(2)
    ns_labels = {"default": {}, "team-a": {"x": "y"}}
    bound = [make_pod("any", namespace="team-a").label("app", "db")
             .node("n0").obj()]
    pod = make_pod("avoid-everywhere").pod_anti_affinity(
        "zone", {"app": "db"}, namespace_selector={}).obj()
    tm = both_masks(nodes, [pod], bound, namespace_labels=ns_labels)
    np.testing.assert_array_equal(tm, [[False, True]])


def test_symmetry_with_explicit_namespaces():
    """An EXISTING pod's anti term with explicit namespaces vetoes incoming
    pods from those namespaces (and only those)."""
    nodes = zone_nodes(2)
    guard = make_pod("guard", namespace="infra").label("role", "guard") \
        .pod_anti_affinity("zone", {"app": "web"}, namespaces=["prod"]) \
        .node("n0").obj()
    hit = make_pod("victim", namespace="prod").label("app", "web").obj()
    safe = make_pod("bystander", namespace="staging").label("app", "web").obj()
    tm = both_masks(nodes, [hit, safe], [guard])
    np.testing.assert_array_equal(tm, [[False, True], [True, True]])


# ------------------------------------------------- matchLabelKeys (affinity)

def test_affinity_match_label_keys():
    """matchLabelKeys merges the incoming pod's own value: anti-affinity
    scoped to the same rollout generation."""
    nodes = zone_nodes(2)
    bound = [make_pod("old-gen").label("app", "web").label("gen", "1")
             .node("n0").obj()]
    same_gen = make_pod("same").label("app", "web").label("gen", "1") \
        .pod_anti_affinity("zone", {"app": "web"},
                           match_label_keys=["gen"]).obj()
    new_gen = make_pod("next").label("app", "web").label("gen", "2") \
        .pod_anti_affinity("zone", {"app": "web"},
                           match_label_keys=["gen"]).obj()
    tm = both_masks(nodes, [same_gen, new_gen], bound)
    np.testing.assert_array_equal(tm, [[False, True], [True, True]])


def test_affinity_mismatch_label_keys():
    """mismatchLabelKeys adds NotIn(own value): affinity to app peers of
    OTHER tenants."""
    nodes = zone_nodes(2)
    bound = [make_pod("tenant-a").label("app", "web").label("tenant", "a")
             .node("n0").obj()]
    pod = make_pod("tenant-b").label("app", "web").label("tenant", "b") \
        .pod_affinity("zone", {"app": "web"},
                      mismatch_label_keys=["tenant"]).obj()
    same = make_pod("tenant-a2").label("app", "web").label("tenant", "a") \
        .pod_affinity("zone", {"app": "web"},
                      mismatch_label_keys=["tenant"]).obj()
    tm = both_masks(nodes, [pod, same], bound)
    # tenant-b finds tenant-a's pod in z0; tenant-a2 excludes its own tenant
    # (no match anywhere -> only the bootstrap path could admit it, but the
    # pod doesn't match its own term either -> infeasible everywhere)
    np.testing.assert_array_equal(tm, [[True, False], [False, False]])


# ----------------------------------------------------------------- minDomains

def test_spread_min_domains():
    """3 pods across 2 zones, minDomains=3: global min treated as 0, so a
    node already at maxSkew rejects; without minDomains both zones accept."""
    nodes = zone_nodes(2)
    bound = [make_pod("b0").label("app", "web").node("n0").obj()]
    plain = make_pod("plain").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    strict = make_pod("strict").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"},
                min_domains=3).obj()
    tm = both_masks(nodes, [plain, strict], bound)
    # plain: min over {z0:1, z1:0} = 0 -> n0 skew 2 > 1 infeasible, n1 ok.
    # strict: min forced to 0 (only 2 domains < 3) -> same outcome here,
    # but on a node in z1 count 0 + self 1 - 0 = 1 <= 1 ok.
    np.testing.assert_array_equal(tm, [[False, True], [False, True]])


def test_spread_min_domains_blocks_when_met_domain_full():
    """minDomains with every domain populated behaves like plain spread."""
    nodes = zone_nodes(3)
    bound = [make_pod(f"b{i}").label("app", "web").node(f"n{i}").obj()
             for i in range(3)]
    pod = make_pod("p").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"},
                min_domains=3).obj()
    tm = both_masks(nodes, [pod], bound)
    np.testing.assert_array_equal(tm, [[True, True, True]])


# ------------------------------------------------------ node-inclusion policies

def test_spread_node_affinity_policy_honor_default():
    """Default Honor: nodes failing the pod's nodeSelector don't count.
    The pod selects zone in {z0,z1}; a matching pod on z2 is invisible, so
    min over {z0:1, z1:0} = 0 and z0 is rejected at maxSkew 1... but with
    Ignore policy z2's count keeps min at 0 identically — the DIFFERENCE
    shows in the domain the excluded node would have made minimal."""
    nodes = zone_nodes(3)
    bound = [make_pod("b0").label("app", "web").node("n0").obj(),
             make_pod("b2").label("app", "web").node("n2").obj()]
    # selector restricts to z0/z1: z2 (1 pod) excluded -> min = 0 (z1 empty)
    honor = make_pod("honor").label("app", "web") \
        .node_selector({"zone": "z0"}) \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    # Ignore: z2 still counted, min still 0 via z1 -> same mask on n0 here;
    # build a sharper case: selector to z0 only, z1+z2 hold 1 pod each ->
    # Honor: only z0 eligible, min = count(z0) = 1 -> skew 1+1-1 = 1 ok.
    # Ignore: min over all = 1 as well (z1=z2=1, z0=1)... use bound2 below.
    tm = both_masks(nodes, [honor], bound)
    # Honor (default): eligible domains = {z0}; min = 1 -> skew 1+1-1=1 ok!
    np.testing.assert_array_equal(tm[0], [True, False, False])


def test_spread_node_affinity_policy_ignore():
    nodes = zone_nodes(3)
    bound = [make_pod("b0").label("app", "web").node("n0").obj(),
             make_pod("b2").label("app", "web").node("n2").obj()]
    ignore = make_pod("ignore").label("app", "web") \
        .node_selector({"zone": "z0"}) \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"},
                node_affinity_policy="Ignore").obj()
    tm = both_masks(nodes, [ignore], bound)
    # Ignore: min over {z0:1, z1:0, z2:1} = 0 -> n0 skew 1+1-0=2 > 1: reject
    np.testing.assert_array_equal(tm[0], [False, False, False])


def test_spread_node_taints_policy():
    nodes = zone_nodes(2)
    nodes.append(make_node("n2").capacity({"cpu": "8", "pods": "20"})
                 .label("zone", "z2").taint("dedicated", "ml", "NoSchedule")
                 .obj())
    bound = [make_pod("b0").label("app", "web").node("n0").obj()]
    # default Ignore: tainted z2 counts as an (empty) eligible domain ->
    # min 0 -> n0 rejected at skew 2
    default = make_pod("default").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    # Honor: z2 excluded (untolerated taint) -> min over {z0:1, z1:0} = 0,
    # same rejection on n0; on z1: 0+1-0 <= 1 feasible either way. The
    # difference needs z1 absent: see honor2 with only z0+z2.
    tm = both_masks(nodes, [default], bound)
    np.testing.assert_array_equal(tm[0], [False, True, False])


def test_spread_node_taints_policy_honor_shrinks_min():
    nodes = [make_node("n0").capacity({"cpu": "8", "pods": "20"})
             .label("zone", "z0").obj(),
             make_node("n1").capacity({"cpu": "8", "pods": "20"})
             .label("zone", "z1").taint("dedicated", "ml", "NoSchedule").obj()]
    bound = [make_pod("b0").label("app", "web").node("n0").obj()]
    default = make_pod("default").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    honor = make_pod("honor").label("app", "web") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"},
                node_taints_policy="Honor").obj()
    tm = both_masks(nodes, [default, honor], bound)
    # default Ignore: z1 eligible + empty -> min 0 -> n0 skew 2: reject;
    #   n1 itself fails TaintToleration anyway -> [False, False]
    # Honor: z1 excluded -> only z0 -> min 1 -> n0 skew 1+1-1=1: ok
    np.testing.assert_array_equal(tm, [[False, False], [True, False]])


# ----------------------------------------------------- spread matchLabelKeys

def test_factored_domain_counts_bit_parity(monkeypatch):
    """The factored (scatter-per-value) domain path used above the node
    threshold must be bit-identical to the [N,N] matmul path — same masks,
    same scores, on a workload exercising spread (minDomains + policies),
    (anti-)affinity across namespaces, and the symmetry veto."""
    import random

    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    rng = random.Random(7)
    nodes = [make_node(f"n{i}").capacity({"cpu": "16", "pods": "40"})
             .label("zone", f"z{i % 5}").obj() for i in range(24)]
    bound = []
    for i in range(30):
        w = make_pod(f"b{i}", namespace=rng.choice(["default", "team-a"])) \
            .label("app", rng.choice(["web", "db"]))
        if rng.random() < 0.4:
            w.pod_anti_affinity("zone", {"app": "web"},
                                namespaces=["default", "team-a"])
        p = w.obj()
        p.spec.node_name = f"n{rng.randint(0, 23)}"
        bound.append(p)
    pods = []
    for i in range(6):
        w = make_pod(f"p{i}").label("app", rng.choice(["web", "db"]))
        w.spread(1, "zone", "DoNotSchedule", {"app": "web"},
                 min_domains=rng.choice([None, 3]))
        if rng.random() < 0.7:
            w.pod_affinity("zone", {"app": "db"},
                           anti=rng.random() < 0.5,
                           namespaces=["default", "team-a"])
        pods.append(w.obj())

    def full_eval():
        enc = SnapshotEncoder()
        ct, meta = enc.encode_cluster(nodes, bound, pending_pods=pods)
        pb = enc.encode_pods(pods, meta)
        res = evaluate(ct, pb, topo_keys=meta.topo_keys)
        return (np.asarray(res.feasible)[:len(pods), :len(nodes)],
                np.asarray(res.scores)[:len(pods), :len(nodes)])

    monkeypatch.setenv("KTPU_DOMAIN_FACTORED", "0")
    feas_mm, scores_mm = full_eval()
    monkeypatch.setenv("KTPU_DOMAIN_FACTORED", "1")
    feas_f, scores_f = full_eval()
    np.testing.assert_array_equal(feas_mm, feas_f)
    np.testing.assert_array_equal(scores_mm, scores_f)


def test_spread_match_label_keys():
    """matchLabelKeys scopes spread counting to the pod's own rollout: the
    old generation's pods don't count against the new one."""
    nodes = zone_nodes(2)
    bound = [make_pod("old0").label("app", "web").label("rev", "1")
             .node("n0").obj(),
             make_pod("old1").label("app", "web").label("rev", "1")
             .node("n0").obj()]
    new = make_pod("new").label("app", "web").label("rev", "2") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"},
                match_label_keys=["rev"]).obj()
    plain = make_pod("plain").label("app", "web").label("rev", "2") \
        .spread(1, "zone", "DoNotSchedule", {"app": "web"}).obj()
    tm = both_masks(nodes, [new, plain], bound)
    # new: rev=2 counts are 0 everywhere -> both zones fine
    # plain: z0 has 2 rev-agnostic matches, min 0 -> n0 rejected
    np.testing.assert_array_equal(tm, [[True, True], [False, True]])


def test_factored_boundary_parity_at_threshold_scale():
    """Factored vs matmul domain counting agree AT the switchover scale:
    one node past _FACTORED_THRESHOLD (8192), so the default path really is
    the factored O(N+V) formulation, diffed against the forced-matmul path
    on identical inputs (VERDICT r2: the boundary was only tested at toy N)."""
    import os
    N = 8192 + 8
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "16"})
             .label("zone", f"z{i % 16}").obj() for i in range(N)]
    bound = [make_pod(f"b{i}").label("app", "web").node(f"n{i * 37 % N}").obj()
             for i in range(24)]
    pods = [make_pod(f"p{i}").label("app", "web")
            .spread(1, "zone", "DoNotSchedule", {"app": "web"})
            .spread(2, "zone", "ScheduleAnyway", {"app": "web"})
            .obj() for i in range(4)]

    def full_eval():
        enc = SnapshotEncoder()
        ct, meta = enc.encode_cluster(nodes, bound, pending_pods=pods)
        pb = enc.encode_pods(pods, meta)
        res = evaluate(ct, pb, topo_keys=meta.topo_keys)
        return (np.asarray(res.feasible)[:len(pods), :N],
                np.asarray(res.scores)[:len(pods), :N])

    prev = os.environ.pop("KTPU_DOMAIN_FACTORED", None)
    try:
        feas_auto, scores_auto = full_eval()   # auto: factored (N > 8192)
        os.environ["KTPU_DOMAIN_FACTORED"] = "0"
        feas_mm, scores_mm = full_eval()       # forced matmul
    finally:
        if prev is None:
            os.environ.pop("KTPU_DOMAIN_FACTORED", None)
        else:
            os.environ["KTPU_DOMAIN_FACTORED"] = prev
    np.testing.assert_array_equal(feas_auto, feas_mm)
    np.testing.assert_array_equal(scores_auto, scores_mm)
