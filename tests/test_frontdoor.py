"""The front door: follower apiservers serving reads, leader routing,
client endpoint spreading, and cross-replica watch semantics.

Reference role: apiserver replicas over etcd — any replica serves
list/watch from its (replicated) cache with a bounded-staleness
contract; linearizable mutations go through the leader. The watch rv
vocabulary is IDENTICAL across replicas (rvs are minted once, under the
raft log), so a watcher can hop replicas without renumbering."""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.client.clientset import HTTPClient, TooOld
from kubernetes_tpu.client.informer import SharedInformer
from kubernetes_tpu.store.frontdoor import (FRONTDOOR_CONFIGMAP,
                                            FRONTDOOR_NAMESPACE,
                                            FrontDoorCluster,
                                            FrontDoorPublisher,
                                            aggregate_frontdoor)


pytestmark = pytest.mark.watchstorm


def wait_until(fn, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def _cm(name, v="1"):
    return {"kind": "ConfigMap", "metadata": {"name": name},
            "data": {"v": v}}


def _raw_get(url, path):
    """(status, headers, body) without client-side routing/retry."""
    try:
        with urllib.request.urlopen(url + path, timeout=5.0) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture(scope="module")
def cluster():
    fd = FrontDoorCluster(3).start()
    yield fd
    fd.stop()


def test_replica_serves_reads_with_lag_header(cluster):
    c = cluster.client()
    c.resource("configmaps", "default").create(_cm("fd-read"))
    replica_url = cluster.replica_apis[0].url
    path = "/api/v1/namespaces/default/configmaps/fd-read"
    assert wait_until(lambda: _raw_get(replica_url, path)[0] == 200)
    code, headers, body = _raw_get(replica_url, path)
    assert code == 200
    assert json.loads(body)["metadata"]["name"] == "fd-read"
    # staleness is part of the replica's response contract
    lag_ms = float(headers["X-KTPU-Replay-Lag"])
    assert 0.0 <= lag_ms < 5000.0
    # the leader never advertises a lag (it IS the truth)
    _, leader_headers, _ = _raw_get(cluster.leader_api.url, path)
    assert "X-KTPU-Replay-Lag" not in leader_headers


def test_write_on_replica_answers_421_with_leader_hint(cluster):
    replica_url = cluster.replica_apis[0].url
    req = urllib.request.Request(
        replica_url + "/api/v1/namespaces/default/configmaps",
        data=json.dumps(_cm("fd-reject")).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5.0)
    assert ei.value.code == 421
    assert ei.value.headers["X-KTPU-Leader"] == cluster.leader_api.url
    assert json.loads(ei.value.read())["reason"] == "NotLeader"


def test_spread_client_write_chases_leader(cluster):
    """A client whose FIRST endpoint is a replica still writes: the 421
    hint re-routes the request to the leader transparently."""
    leader_url = cluster.leader_api.url
    endpoints = [api.url for api in cluster.replica_apis] + [leader_url]
    c = HTTPClient(endpoints)
    assert c._leader == endpoints[0]  # starts pointed at a replica
    c.resource("configmaps", "default").create(_cm("fd-chase"))
    assert c._leader == leader_url  # learned the hint
    got = c.resource("configmaps", "default").get("fd-chase")
    assert got["metadata"]["name"] == "fd-chase"


def test_watch_rv_vocabulary_identical_across_replicas(cluster):
    """Events watched on a REPLICA carry the same resourceVersions a
    fresh LEADER list reports — rvs are minted once under the raft log,
    so streams are portable across the front door."""
    writer = cluster.client()
    replica = HTTPClient(cluster.replica_apis[0].url)
    cms = writer.resource("configmaps", "default")
    _, rv0 = cms.list_rv()
    w = replica.resource("configmaps", "default").watch(since_rv=rv0)
    names = [f"fd-rv-{i}" for i in range(5)]
    for n in names:
        cms.create(_cm(n))
    seen = {}
    deadline = time.monotonic() + 15.0
    while len(seen) < len(names) and time.monotonic() < deadline:
        ev = w.get(timeout=1.0)
        if ev is not None and ev.object["metadata"]["name"] in set(names):
            seen[ev.object["metadata"]["name"]] = ev.resource_version
    w.stop()
    leader_c = HTTPClient(cluster.leader_api.url)
    leader_rvs = {o["metadata"]["name"]:
                  int(o["metadata"]["resourceVersion"])
                  for o in leader_c.resource("configmaps", "default").list()
                  if o["metadata"]["name"] in set(names)}
    assert seen == leader_rvs


def test_replica_readyz_gates_on_replay_lag(cluster):
    replica_api = cluster.replica_apis[0]
    assert _raw_get(replica_api.url, "/readyz")[0] == 200
    try:
        # an impossible staleness budget: any positive lag exceeds it
        replica_api.max_replay_lag_s = -1.0
        assert wait_until(
            lambda: _raw_get(replica_api.url, "/readyz")[0] == 503,
            timeout=5.0)
        # liveness is NOT staleness: the process stays alive
        assert _raw_get(replica_api.url, "/livez")[0] == 200
        # the leader has no lag to gate on
        assert _raw_get(cluster.leader_api.url, "/readyz")[0] == 200
    finally:
        replica_api.max_replay_lag_s = 2.0


def test_frontdoor_status_and_read_role_accounting(cluster):
    from kubernetes_tpu.metrics.registry import READ_REQUESTS
    leader_reads = READ_REQUESTS.get({"role": "leader"})
    replica_reads = READ_REQUESTS.get({"role": "replica"})
    path = "/api/v1/namespaces/default/configmaps"
    assert _raw_get(cluster.leader_api.url, path)[0] == 200
    assert _raw_get(cluster.replica_apis[0].url, path)[0] == 200
    assert READ_REQUESTS.get({"role": "leader"}) > leader_reads
    assert READ_REQUESTS.get({"role": "replica"}) > replica_reads
    st = json.loads(_raw_get(cluster.replica_apis[0].url,
                             "/frontdoor/status")[2])
    assert st["role"] == "replica"
    assert st["replayLagMs"] is not None
    assert st["watch"]["shardsPerKind"] >= 1
    st = json.loads(_raw_get(cluster.leader_api.url,
                             "/frontdoor/status")[2])
    assert st["role"] == "leader" and st["replayLagMs"] is None


def test_publisher_writes_frontdoor_configmap(cluster):
    c = cluster.client()
    pub = FrontDoorPublisher(c, cluster.endpoints)
    assert pub.publish_once()

    def fetch():
        # the spread client may read a follower that hasn't replayed the
        # publish yet — the plane promises bounded staleness, not
        # read-your-writes, so poll until the write is visible
        try:
            return c.resource("configmaps", FRONTDOOR_NAMESPACE).get(
                FRONTDOOR_CONFIGMAP)
        except Exception:
            return None

    assert wait_until(lambda: fetch() is not None)
    data = fetch()["data"]
    assert data["leader"] == cluster.leader_api.url
    assert data["replicas"] == "2"
    nodes = json.loads(data["nodes"])
    assert len(nodes) == 3
    assert sum(1 for n in nodes if n["reachable"]) == 3
    assert {n["role"] for n in nodes} == {"leader", "replica"}


def test_ktpu_status_frontdoor_line(cluster):
    """``ktpu status`` renders the published front-door picture — and the
    ``--server`` flag takes the whole endpoint list (comma-separated), so
    the CLI itself rides the spread client."""
    import io

    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    c = cluster.client()
    assert FrontDoorPublisher(c, cluster.endpoints).publish_once()
    out = io.StringIO()
    rc = ktpu_main(["--server", ",".join(cluster.endpoints), "status"],
                   out=out)
    text = out.getvalue()
    assert rc == 0
    assert "Front door:" in text
    assert "2 read replicas" in text
    assert "3/3 reachable" in text
    out = io.StringIO()
    rc = ktpu_main(["--server", cluster.leader_api.url, "status",
                    "-o", "json"], out=out)
    assert rc == 0
    st = json.loads(out.getvalue())
    assert st["frontdoor"]["replicas"] == "2"
    assert st["frontdoor"]["leader"] == cluster.leader_api.url
    assert len(st["frontdoor"]["nodes"]) == 3


def test_aggregate_renders_unreachable_nodes():
    data = aggregate_frontdoor({
        "http://a": {"role": "leader", "node": "n0", "ready": True,
                     "replayLagMs": None,
                     "watch": {"watchersTotal": 3, "dropsTotal": 1,
                               "shardsPerKind": 8}},
        "http://b": None})
    assert data["leader"] == "http://a"
    assert data["replicas"] == "0"
    assert data["watchersTotal"] == "3" and data["dropsTotal"] == "1"
    nodes = {n["url"]: n for n in json.loads(data["nodes"])}
    assert nodes["http://b"] == {"url": "http://b", "reachable": False}


def test_spread_reads_survive_replica_loss_and_compaction():
    """Disruption leg (own cluster — it wounds a replica): reads keep
    succeeding through endpoint rotation after a replica apiserver dies,
    a compacted replica answers watches with 410 -> client TooOld, and
    an informer against that replica relists to the SAME state a fresh
    leader list reports."""
    fd = FrontDoorCluster(3).start()
    try:
        writer = fd.client()
        cms = writer.resource("configmaps", "default")
        for i in range(5):
            cms.create(_cm(f"dis-{i}"))
        victim, survivor = fd.replica_apis[0], fd.replica_apis[1]
        surv_store = survivor.store.inner
        assert wait_until(
            lambda: len(surv_store.list("ConfigMap")[0]) == 5)
        # ---- compaction: the survivor's history floor advances (the
        # post-snapshot-resync state) -> watch rv=1 is 410/TooOld, and
        # an informer heals by relisting
        surv_store.load_snapshot_blob(surv_store.snapshot_blob())
        replica_c = HTTPClient(survivor.url)
        with pytest.raises(TooOld):
            replica_c.resource("configmaps", "default").watch(since_rv=1)
        inf = SharedInformer(
            replica_c.resource("configmaps", "default")).start()
        assert inf.wait_for_cache_sync(15.0)
        leader_list = {o["metadata"]["name"]:
                       o["metadata"]["resourceVersion"]
                       for o in writer.resource("configmaps",
                                                "default").list()}
        informer_view = {o["metadata"]["name"]:
                         o["metadata"]["resourceVersion"]
                         for o in inf.store.list()}
        assert informer_view == leader_list
        inf.stop()
        # ---- replica loss: kill one replica's apiserver; every read on
        # the spread client still lands (sticky endpoints rotate away)
        victim.stop()
        reader = fd.client()
        for _ in range(8):
            got = reader.resource("configmaps", "default").get("dis-0")
            assert got["metadata"]["name"] == "dis-0"
    finally:
        fd.stop()
