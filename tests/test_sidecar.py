"""gRPC scheduling sidecar: generation-tokened filter/score/schedule.

The north-star integration story (SURVEY §7 phase 7): a reference-world
scheduler delegates its hot loop to the TPU sidecar over gRPC, tagging every
batch with its informer-cache generation; the sidecar rejects stale
generations and the client reconciles with delta pushes. Supersedes the
HTTP extender protocol (``pkg/scheduler/extender.go`` precedent).
"""

import pytest

from kubernetes_tpu.sidecar import SidecarClient, SidecarServer
from kubernetes_tpu.sidecar import proto
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture()
def sidecar():
    server = SidecarServer().start()
    client = SidecarClient(server.address)
    yield server, client
    client.close()
    server.stop()


def _seed(client, n_nodes=4):
    for i in range(n_nodes):
        client.upsert_node(make_node(f"n{i}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj().to_dict())


def test_schedule_roundtrip(sidecar):
    _, client = sidecar
    _seed(client)
    client.push_snapshot()
    pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj().to_dict()
            for i in range(6)]
    assigned = client.schedule(pods)
    assert len(assigned) == 6
    assert all(a.startswith("n") for a in assigned)


def test_filter_and_score_shapes(sidecar):
    _, client = sidecar
    _seed(client, n_nodes=3)
    # n2 is tainted: filter must exclude it for intolerant pods
    client.upsert_node(make_node("n2").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": "16"})
        .taint("dedicated", "gpu", "NoSchedule").obj().to_dict())
    client.push_snapshot()
    pods = [make_pod("a").req({"cpu": "1"}).obj().to_dict()]
    mask = client.filter(pods)
    assert mask.shape == (1, 3)
    assert mask[0].sum() == 2  # n2 excluded
    scores = client.score(pods)
    assert scores.shape == (1, 3)


def test_stale_reject_then_delta_push_then_reschedule(sidecar):
    """The staleness race, end to end: the client binds optimistically
    (generation advances locally), the sidecar rejects the next batch as
    stale, the client re-pushes exactly the missed deltas and the retry
    schedules against the updated state."""
    _, client = sidecar
    # two nodes, each fits exactly ONE 2-cpu pod: after the first binding
    # the only correct answer for the second pod is the OTHER node
    client.upsert_node(make_node("ta").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "8"}).obj().to_dict())
    client.upsert_node(make_node("tb").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "8"}).obj().to_dict())
    client.push_snapshot()
    gen0 = client.generation

    # schedule one pod; it lands somewhere
    [first] = client.schedule(
        [make_pod("filler").req({"cpu": "2"}).obj().to_dict()])
    assert first in ("ta", "tb")
    assert client.stale_retries == 0

    # assume-optimism: the binding is observed locally, gen moves on
    bound = make_pod("filler").req({"cpu": "2"}).node(first).obj().to_dict()
    client.observe_binding(bound)
    assert client.generation == gen0 + 1

    # next batch goes out tagged with the NEW generation -> sidecar is
    # behind -> STALE -> client delta-pushes -> retry succeeds and must
    # respect filler's binding (no double-booking of its node)
    [second] = client.schedule(
        [make_pod("next").req({"cpu": "2"}).obj().to_dict()])
    assert client.stale_retries == 1
    assert second == ("tb" if first == "ta" else "ta")


def test_journal_overflow_falls_back_to_full_push(sidecar):
    _, client = sidecar
    client._journal_limit = 4
    _seed(client, n_nodes=2)
    client.push_snapshot()
    for i in range(8):  # overflow the journal: compaction drops it
        client.observe_binding(
            make_pod(f"b{i}").req({"cpu": "1"}).node("n0").obj().to_dict())
    assert client._journal == [] or len(client._journal) <= 4
    # schedule still converges (full snapshot re-push under the hood)
    out = client.schedule([make_pod("x").req({"cpu": "1"}).obj().to_dict()])
    assert out[0] in ("n0", "n1")


def test_server_rejects_unknown_generation_delta(sidecar):
    server, client = sidecar
    _seed(client, n_nodes=1)
    client.push_snapshot()
    # a delta whose base doesn't match the server's applied generation
    out = client._call["PushDelta"]({
        "base_generation": 999, "generation": 1000, "ops": []})
    assert out.get("stale") is True
    assert out["server_generation"] == client._pushed_gen


def test_bidi_session_stream(sidecar):
    """The streaming transport: frames tagged {kind, seq} answered in
    order on one HTTP/2 stream."""
    import grpc
    server, client = sidecar
    _seed(client, n_nodes=2)
    snap = {"kind": "PushSnapshot", "seq": 1,
            "nodes": list(client._nodes.values()), "pods": [],
            "generation": client.generation}
    sched = {"kind": "Schedule", "seq": 2,
             "pods": [make_pod("s").req({"cpu": "1"}).obj().to_dict()],
             "generation": client.generation}
    chan = grpc.insecure_channel(server.address)
    call = chan.stream_stream(
        proto.method_path(proto.STREAM_METHOD),
        request_serializer=proto.pack, response_deserializer=proto.unpack)
    replies = list(call(iter([snap, sched])))
    chan.close()
    assert [r["seq"] for r in replies] == [1, 2]
    assert replies[0]["generation"] == client.generation
    assert replies[1]["assignments"][0] in ("n0", "n1")


def test_schedule_matches_oracle(sidecar):
    """Sidecar assignments agree with the serial oracle on a capacity
    scenario (same engine as the in-process scheduler)."""
    from kubernetes_tpu.sched.oracle import OracleScheduler
    from kubernetes_tpu.api.types import Node, Pod
    from collections import Counter
    _, client = sidecar
    _seed(client, n_nodes=3)
    client.push_snapshot()
    pods = [make_pod(f"p{i}").req({"cpu": "2"}).obj() for i in range(6)]
    assigned = client.schedule([p.to_dict() for p in pods])
    nodes = [Node.from_dict(d) for d in client._nodes.values()]
    orc = OracleScheduler(nodes, [])
    placed = orc.schedule_all([Pod.from_dict(p.to_dict()) for p in pods])
    oracle_names = [nodes[i].metadata.name if i is not None else ""
                    for i in placed]
    # gang vs serial tie-breaks may order differently; the LOAD SHAPE must
    # agree (6 x 2cpu over 3 x 4cpu nodes -> exactly 2 per node), with
    # bit-parity covered by the main oracle parity suites
    assert Counter(assigned) == Counter(oracle_names)
    assert all(a for a in assigned)


def test_delete_then_readd_survives_delta_push(sidecar):
    """Ordered delta replay: a pod evicted and re-bound (same key) between
    pushes must still be live in the sidecar after reconciliation — its
    capacity charged, so a same-size pod cannot double-book the node."""
    _, client = sidecar
    client.upsert_node(make_node("one").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "8"}).obj().to_dict())
    client.push_snapshot()
    bound = make_pod("x").req({"cpu": "2"}).node("one").obj().to_dict()
    client.observe_binding(bound)
    client.observe_delete("default/x")
    client.observe_binding(bound)  # re-add with the SAME key
    # stale reject -> ordered delta replay -> 'one' must be FULL
    assert client.schedule(
        [make_pod("y").req({"cpu": "2"}).obj().to_dict()]) == [""]
    assert client.stale_retries >= 1


def test_unknown_resource_widens_encoding(sidecar):
    """A batch demanding a resource outside the cached axis must force a
    re-encode (the cache's 'widen' check), not silently zero the demand."""
    _, client = sidecar
    client.upsert_node(make_node("plain").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": "16"}).obj().to_dict())
    client.push_snapshot()
    # prime the encoding cache with a cpu-only batch
    assert client.schedule(
        [make_pod("warm").req({"cpu": "1"}).obj().to_dict()])[0] == "plain"
    # now demand an extended resource no node has: must NOT be admitted
    fpga = make_pod("fpga").req({"cpu": "1"}).obj().to_dict()
    fpga["spec"]["containers"][0]["resources"]["requests"][
        "example.com/fpga"] = "1"
    assert client.schedule([fpga]) == [""]
