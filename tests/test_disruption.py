"""PodDisruptionBudgets: status controller, eviction gate, PDB-aware
preemption, and nominated-node capacity reservation.

Reference: pkg/controller/disruption/disruption.go,
registry/core/pod/storage/eviction.go,
framework/plugins/defaultpreemption/default_preemption.go, and
schedule_one.go's nominatedNodeName handling.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.api.policy import compute_pdb_status
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.sched import preemption
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _running(pod_dict, node="n1"):
    pod_dict["spec"]["nodeName"] = node
    pod_dict["status"] = {"phase": "Running",
                          "conditions": [{"type": "Ready", "status": "True"}]}
    return pod_dict


def _pdb(name, ns="default", min_available=None, max_unavailable=None,
         match=None):
    spec = {"selector": {"matchLabels": match or {"app": "web"}}}
    if min_available is not None:
        spec["minAvailable"] = min_available
    if max_unavailable is not None:
        spec["maxUnavailable"] = max_unavailable
    return {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


def test_compute_pdb_status_arithmetic():
    pods = [_running(make_pod(f"w{i}").label("app", "web").obj().to_dict())
            for i in range(4)]
    st = compute_pdb_status(_pdb("b", min_available=3), pods)
    assert st == {"expectedPods": 4, "currentHealthy": 4,
                  "desiredHealthy": 3, "disruptionsAllowed": 1}
    st = compute_pdb_status(_pdb("b", min_available="50%"), pods)
    assert st["desiredHealthy"] == 2 and st["disruptionsAllowed"] == 2
    st = compute_pdb_status(_pdb("b", max_unavailable=1), pods)
    assert st["desiredHealthy"] == 3 and st["disruptionsAllowed"] == 1
    # unhealthy pods don't count toward currentHealthy
    pods[0]["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
    st = compute_pdb_status(_pdb("b", min_available=3), pods)
    assert st["currentHealthy"] == 3 and st["disruptionsAllowed"] == 0


def test_eviction_respects_pdb():
    server = APIServer().start()
    try:
        c = HTTPClient(server.url)
        for i in range(2):
            c.pods().create(_running(
                make_pod(f"w{i}").label("app", "web").obj().to_dict()))
        c.resource("poddisruptionbudgets").create(_pdb("guard", min_available=2))
        with pytest.raises(ApiError) as ei:
            c.pods().evict("w0")
        assert ei.value.code == 429
        # raise capacity: one disruption allowed
        c.pods().create(_running(
            make_pod("w2").label("app", "web").obj().to_dict()))
        c.pods().evict("w0")  # now allowed
        with pytest.raises(ApiError) as ei:  # budget spent
            c.pods().evict("w1")
        assert ei.value.code == 429
    finally:
        server.stop()


def test_preemption_prefers_pdb_safe_victims():
    nodes = [make_node(f"n{i}").capacity({"cpu": "2", "pods": "10"}).obj()
             for i in range(2)]
    guarded = make_pod("guarded").label("app", "web").req({"cpu": "2"}) \
        .priority(0).node("n0").obj()
    free = make_pod("free").label("app", "other").req({"cpu": "2"}) \
        .priority(0).node("n1").obj()
    pdbs = [_pdb("guard", min_available=1)]
    pred = make_pod("pred").req({"cpu": "2"}).priority(100).obj()
    res = preemption.find_candidate(nodes, [guarded, free], pred, pdbs=pdbs)
    assert res is not None
    assert res.node_name == "n1", "should pick the PDB-unprotected victim"
    assert res.num_pdb_violations == 0
    # last resort: only guarded victims exist -> still preempts, violating
    res = preemption.find_candidate([nodes[0]], [guarded], pred, pdbs=pdbs)
    assert res is not None and res.num_pdb_violations == 1


def test_nominated_reservation_blocks_lower_priority():
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    from kubernetes_tpu.ops.filters import run_filters

    nodes = [make_node("n0").capacity({"cpu": "2", "pods": "10"}).obj()]
    hi = make_pod("hi").req({"cpu": "2"}).priority(100).obj()
    lo = make_pod("lo").req({"cpu": "1"}).priority(0).obj()
    higher = make_pod("higher").req({"cpu": "1"}).priority(200).obj()
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=[hi, lo, higher])
    ct = enc.with_nominated(ct, meta, [("n0", 100, hi)])
    pb = enc.encode_pods([lo, higher], meta)
    mask = np.asarray(run_filters(ct, pb))
    assert not mask[0, 0], "lower-priority pod must not take reserved capacity"
    assert mask[1, 0], "higher-priority pod ignores the reservation"


def test_scheduler_reserves_nominated_capacity():
    """End-to-end through the Scheduler: while a nominee (not in the batch)
    holds a nomination, a lower-priority batch pod must not squat on the
    freed node."""
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.cache import SchedulerCache
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler

    cache = SchedulerCache()
    cache.add_node(make_node("n0").capacity({"cpu": "2", "pods": "10"}).obj())
    cache.add_node(make_node("n1").capacity({"cpu": "1", "pods": "10"}).obj())
    bound = []
    sched = Scheduler(SchedulerConfiguration(), cache,
                      SchedulingQueue(), lambda p, n: bound.append((p, n)) or True)
    hi = make_pod("hi").req({"cpu": "2"}).priority(100).obj()
    sched._nominated[hi.key] = ("n0", 100, hi, time.time())
    lo = make_pod("lo").req({"cpu": "2"}).priority(0).obj()
    profile = SchedulerConfiguration().profile_for(lo.spec.scheduler_name)
    n = sched._schedule_group(profile, [(lo, 0)])
    sched.wait_for_bindings()
    assert n == 0 and not bound, \
        "lo (cpu 2) fits only on the reserved n0 and must wait"
    # the nominee itself schedules there (its own entry is excluded in-batch)
    n = sched._schedule_group(profile, [(hi, 0)])
    sched.wait_for_bindings()
    assert n == 1 and bound and bound[0][1] == "n0"
