"""PVC/PV protection finalizers + CSR approving/cleaning controllers.

Reference: ``pkg/controller/volume/{pvcprotection,pvprotection}`` (in-use
storage cannot be deleted out from under consumers — graceful deletion
via finalizers) and ``pkg/controller/certificates/{approver,cleaner}``.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.csrlifecycle import (CSRApprovingController,
                                                     CSRCleanerController,
                                                     SIGNER_KUBELET_CLIENT)
from kubernetes_tpu.controllers.volumeprotection import (
    PVC_FINALIZER, PVCProtectionController, PVProtectionController)
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_pod


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def run_controller(client, ctrl):
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def stop(ctrl, factory):
    ctrl.stop()
    factory.stop_all()


# -------------------------------------------------------------- finalizers

def test_store_graceful_deletion_with_finalizers(client):
    cms = client.resource("configmaps", "default")
    cms.create({"kind": "ConfigMap",
                "metadata": {"name": "held",
                             "finalizers": ["example.com/hold"]},
                "data": {}})
    cms.delete("held")
    got = cms.get("held")  # still present, terminating
    assert got["metadata"]["deletionTimestamp"]
    got["metadata"]["finalizers"] = []
    cms.update(got)  # last finalizer off -> delete completes
    with pytest.raises(ApiError):
        cms.get("held")


def test_pvc_protection_blocks_delete_while_pod_mounts(client):
    pvcs = client.resource("persistentvolumeclaims", "default")
    pvcs.create({"kind": "PersistentVolumeClaim",
                 "metadata": {"name": "data"},
                 "spec": {"resources": {"requests": {"storage": "1Gi"}}}})
    pod = make_pod("user").obj().to_dict()
    pod["spec"]["volumes"] = [
        {"name": "d", "persistentVolumeClaim": {"claimName": "data"}}]
    client.pods("default").create(pod)
    ctrl, factory = run_controller(client, PVCProtectionController(client))
    try:
        assert wait_until(lambda: PVC_FINALIZER in
                          (pvcs.get("data")["metadata"].get("finalizers")
                           or []))
        pvcs.delete("data")
        time.sleep(0.3)
        got = pvcs.get("data")  # still here: a pod mounts it
        assert got["metadata"]["deletionTimestamp"]
        # pod goes away -> finalizer comes off -> the delete completes
        client.pods("default").delete("user")

        def gone():
            try:
                pvcs.get("data")
                return False
            except ApiError:
                return True
        assert wait_until(gone)
    finally:
        stop(ctrl, factory)


def test_pv_protection_blocks_delete_while_bound(client):
    pvs = client.resource("persistentvolumes", None)
    pvcs = client.resource("persistentvolumeclaims", "default")
    pvs.create({"kind": "PersistentVolume", "metadata": {"name": "vol"},
                "spec": {"capacity": {"storage": "1Gi"}}})
    pvcs.create({"kind": "PersistentVolumeClaim",
                 "metadata": {"name": "claim"},
                 "spec": {"volumeName": "vol",
                          "resources": {"requests": {"storage": "1Gi"}}}})
    ctrl, factory = run_controller(client, PVProtectionController(client))
    try:
        assert wait_until(lambda: (pvs.get("vol")["metadata"]
                                   .get("finalizers")))
        pvs.delete("vol")
        time.sleep(0.3)
        assert pvs.get("vol")["metadata"]["deletionTimestamp"]
        pvcs.delete("claim")

        def gone():
            try:
                pvs.get("vol")
                return False
            except ApiError:
                return True
        assert wait_until(gone)
    finally:
        stop(ctrl, factory)


# --------------------------------------------------------------------- CSR

def _csr(name, signer, groups=(), username="", created=None):
    obj = {"kind": "CertificateSigningRequest",
           "metadata": {"name": name},
           "spec": {"signerName": signer, "groups": list(groups),
                    "username": username, "request": ""}}
    if created is not None:
        obj["metadata"]["creationTimestamp"] = created
    return obj


def test_kubelet_client_csrs_auto_approved(client):
    res = client.resource("certificatesigningrequests", None)
    res.create(_csr("node-boot", SIGNER_KUBELET_CLIENT,
                    groups=["system:bootstrappers"]))
    res.create(_csr("random-user", "kubernetes.io/kube-apiserver-client",
                    groups=["system:authenticated"]))
    ctrl, factory = run_controller(client, CSRApprovingController(client))
    try:
        def approved(name):
            conds = (res.get(name).get("status") or {}) \
                .get("conditions") or []
            return any(c.get("type") == "Approved" for c in conds)
        assert wait_until(lambda: approved("node-boot"))
        time.sleep(0.2)
        assert not approved("random-user")  # wrong signer: untouched
    finally:
        stop(ctrl, factory)


def test_stale_and_finished_csrs_cleaned(client):
    res = client.resource("certificatesigningrequests", None)
    old = time.time() - 7200
    issued = _csr("issued-old", SIGNER_KUBELET_CLIENT, created=old)
    issued["status"] = {"certificate": "UEVN"}
    res.create(issued)
    res.create(_csr("fresh", SIGNER_KUBELET_CLIENT))
    ancient = _csr("ancient-pending", SIGNER_KUBELET_CLIENT,
                   created=time.time() - 2 * 24 * 3600)
    res.create(ancient)
    ctrl = CSRCleanerController(client)
    ctrl.tick_interval = 0.2
    ctrl, factory = run_controller(client, ctrl)
    try:
        def names():
            return {(c.get("metadata") or {}).get("name")
                    for c in res.list()}
        assert wait_until(lambda: names() == {"fresh"}), names()
    finally:
        stop(ctrl, factory)
