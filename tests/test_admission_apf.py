"""Admission chain (built-ins + policies) and API Priority & Fairness."""

import threading
import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.admission import (
    AdmissionChain,
    ValidatingPolicy,
    default_chain,
    default_toleration_seconds,
)
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.flowcontrol import (
    FlowController,
    FlowSchema,
    PriorityLevel,
    RejectedError,
)
from kubernetes_tpu.testing.wrappers import make_pod


@pytest.fixture()
def server():
    s = APIServer()
    s.enable_admission()
    s.start()
    yield s
    s.stop()


def test_default_toleration_seconds(server):
    client = HTTPClient(server.url)
    out = client.pods().create(make_pod("p").obj().to_dict())
    tols = {t["key"]: t for t in out["spec"]["tolerations"]}
    assert tols["node.kubernetes.io/not-ready"]["tolerationSeconds"] == 300
    assert tols["node.kubernetes.io/unreachable"]["effect"] == "NoExecute"


def test_priority_class_resolution(server):
    client = HTTPClient(server.url)
    client.resource("priorityclasses", None).create({
        "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
        "metadata": {"name": "high"}, "value": 1000})
    p = make_pod("prio").obj().to_dict()
    p["spec"]["priorityClassName"] = "high"
    out = client.pods().create(p)
    assert out["spec"]["priority"] == 1000
    # unknown class rejected
    p2 = make_pod("bad").obj().to_dict()
    p2["spec"]["priorityClassName"] = "nope"
    with pytest.raises(ApiError) as ei:
        client.pods().create(p2)
    assert ei.value.code == 400


def test_limit_ranger_defaults(server):
    client = HTTPClient(server.url)
    client.resource("limitranges").create({
        "apiVersion": "v1", "kind": "LimitRange",
        "metadata": {"name": "defaults", "namespace": "default"},
        "spec": {"limits": [{"type": "Container",
                             "defaultRequest": {"cpu": "200m",
                                                "memory": "128Mi"}}]}})
    out = client.pods().create(make_pod("noreq").obj().to_dict())
    req = out["spec"]["containers"][0]["resources"]["requests"]
    assert req == {"cpu": "200m", "memory": "128Mi"}
    # explicit requests win over defaults
    out2 = client.pods().create(make_pod("hasreq").req({"cpu": "1"}).obj().to_dict())
    assert out2["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "1"


def test_resource_quota_enforced(server):
    client = HTTPClient(server.url)
    client.resource("resourcequotas").create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "team", "namespace": "default"},
        "spec": {"hard": {"pods": "2", "requests.cpu": "1"}}})
    client.pods().create(make_pod("a").req({"cpu": "500m"}).obj().to_dict())
    client.pods().create(make_pod("b").req({"cpu": "400m"}).obj().to_dict())
    with pytest.raises(ApiError) as ei:
        client.pods().create(make_pod("c").req({"cpu": "50m"}).obj().to_dict())
    assert ei.value.code == 400 and "quota" in str(ei.value).lower()
    # cpu quota also enforced below the pod-count limit
    client.pods().delete("b")
    with pytest.raises(ApiError):
        client.pods().create(make_pod("d").req({"cpu": "600m"}).obj().to_dict())


def test_validating_policy():
    chain = AdmissionChain()
    chain.validating.append(ValidatingPolicy(
        "max-replicas", ("Deployment",),
        [{"field": "spec.replicas", "op": "<=", "value": 10,
          "message": "replicas capped at 10"}]))
    server = APIServer()
    chain.install(server)
    server.start()
    try:
        client = HTTPClient(server.url)
        client.resource("deployments").create({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "ok", "namespace": "default"},
            "spec": {"replicas": 3, "selector": {"matchLabels": {"a": "b"}},
                     "template": {"spec": {"containers": [{"name": "c"}]}}}})
        with pytest.raises(ApiError) as ei:
            client.resource("deployments").create({
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "big", "namespace": "default"},
                "spec": {"replicas": 50,
                         "selector": {"matchLabels": {"a": "b"}},
                         "template": {"spec": {"containers": [{"name": "c"}]}}}})
        assert "replicas capped" in str(ei.value)
    finally:
        server.stop()


# ----------------------------------------------------------------- APF

def test_flow_classification():
    fc = FlowController()
    assert fc.classify("get", "/healthz").exempt
    assert fc.classify("get", "/apis/coordination.k8s.io/v1/namespaces/x/leases"
                       ).name == "leader-election"
    assert fc.classify("get", "/api/v1/pods", agent="kubelet/1.0").name == "system"
    assert fc.classify("get", "/api/v1/pods").name == "global-default"


def test_flow_rejects_on_overflow():
    lvl = PriorityLevel("tiny", concurrency=1, queue_length=0)
    fc = FlowController(levels=[lvl,
                                PriorityLevel("global-default", concurrency=1)],
                        schemas=[FlowSchema("all", "tiny")])
    level = fc.classify("get", "/api/v1/pods")
    fc.acquire(level)
    with pytest.raises(RejectedError):
        fc.acquire(level)  # seat taken, queue full
    fc.release(level)
    fc.acquire(level)  # seat free again
    fc.release(level)


def test_flow_queues_then_proceeds():
    lvl = PriorityLevel("q", concurrency=1, queue_length=5)
    fc = FlowController(levels=[lvl,
                                PriorityLevel("global-default", concurrency=1)],
                        schemas=[FlowSchema("all", "q")])
    level = fc.classify("get", "/x")
    fc.acquire(level)
    done = []

    def waiter():
        fc.acquire(level, timeout=5.0)
        done.append(1)
        fc.release(level)
    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    assert not done  # queued behind the held seat
    fc.release(level)
    t.join(2.0)
    assert done == [1]


def test_apf_429_over_http():
    server = APIServer()
    server.enable_flow_control(FlowController(
        levels=[PriorityLevel("global-default", concurrency=1, queue_length=0),
                PriorityLevel("exempt", concurrency=0, exempt=True)],
        schemas=[FlowSchema("health", "exempt",
                            paths=("/healthz", "/readyz", "/livez"))]))
    server.start()
    try:
        client = HTTPClient(server.url)
        # hold the only seat with a slow request: simulate by acquiring directly
        level = server.flow.levels["global-default"]
        server.flow.acquire(level)
        with pytest.raises(ApiError) as ei:
            client.pods().list()
        assert ei.value.code == 429
        server.flow.release(level)
        assert client.pods().list() == []
        assert server.flow.rejected_total >= 1
    finally:
        server.stop()


def test_resource_quota_generate_name_reservations(server):
    """generateName pods have no name at admission time; reservations must
    still be unique per request (no overwrite between racing creates) and
    released once the create commits (no 30s phantom double-count)."""
    client = HTTPClient(server.url)
    client.resource("resourcequotas").create({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "team", "namespace": "default"},
        "spec": {"hard": {"requests.cpu": "1"}}})

    def gen_pod(cpu):
        d = make_pod("x").req({"cpu": cpu}).obj().to_dict()
        d["metadata"].pop("name", None)
        d["metadata"]["generateName"] = "burst-"
        return d

    # two sequential generateName creates filling the quota exactly: the
    # second must NOT be blocked by a lingering reservation for the first
    # (released at commit), and a third must be denied.
    client.pods().create(gen_pod("500m"))
    client.pods().create(gen_pod("500m"))
    with pytest.raises(ApiError) as ei:
        client.pods().create(gen_pod("50m"))
    assert "quota" in str(ei.value).lower()

    # racing generateName creates cannot jointly exceed the quota
    client.resource("resourcequotas").update({
        "apiVersion": "v1", "kind": "ResourceQuota",
        "metadata": {"name": "team", "namespace": "default"},
        "spec": {"hard": {"requests.cpu": "4"}}})
    errs, oks = [], []

    def create_one():
        try:
            client.pods().create(gen_pod("1"))
            oks.append(1)
        except ApiError:
            errs.append(1)

    threads = [threading.Thread(target=create_one) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pods = client.pods().list()
    def millicpu(q):
        return int(q[:-1]) if q.endswith("m") else int(q) * 1000

    total_cpu = sum(
        millicpu(c["resources"]["requests"]["cpu"])
        for p in pods for c in p["spec"]["containers"]
        if (p["metadata"].get("generateName") or "").startswith("burst-")
        or p["metadata"]["name"].startswith("burst-"))
    assert total_cpu <= 4000, f"quota jointly exceeded: {total_cpu}m"


# --------------------------------------------------- fair queuing (queueset)

def test_shuffle_shard_deterministic_and_distinct():
    from kubernetes_tpu.store.flowcontrol import shuffle_shard
    h1 = shuffle_shard("alice", 64, 8)
    assert h1 == shuffle_shard("alice", 64, 8)
    assert len(h1) == len(set(h1)) == 8
    assert all(0 <= i < 64 for i in h1)
    assert shuffle_shard("bob", 64, 8) != h1  # overwhelmingly likely
    # a tiny deck degrades gracefully
    assert sorted(shuffle_shard("x", 4, 8)) == list(range(4))


def test_greedy_flow_cannot_starve_polite_flow():
    """The APF property upstream's queueset exists for: an elephant flow
    saturating a priority level must not starve a mouse flow sharing it.
    One seat, a greedy flow keeping 40 requests in flight, a polite flow
    issuing sequential requests — the polite flow must keep completing."""
    import threading
    import time
    from kubernetes_tpu.store.flowcontrol import (FlowController,
                                                  PriorityLevel)
    level = PriorityLevel("t", concurrency=1, queue_length=100,
                          n_queues=16, hand_size=4)
    fc = FlowController(levels=[level,
                                PriorityLevel("global-default",
                                              concurrency=20)])
    stop = threading.Event()
    done = {"greedy": 0, "polite": 0}

    def greedy(i):
        while not stop.is_set():
            try:
                fc.acquire(level, timeout=5.0, flow="greedy")
            except Exception:
                continue
            try:
                time.sleep(0.002)  # hold the seat
                done["greedy"] += 1
            finally:
                fc.release(level)

    threads = [threading.Thread(target=greedy, args=(i,), daemon=True)
               for i in range(40)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # the greedy flow is saturating the level

    t0 = time.time()
    polite_latencies = []
    for _ in range(10):
        s = time.time()
        fc.acquire(level, timeout=5.0, flow="polite")
        try:
            done["polite"] += 1
        finally:
            fc.release(level)
        polite_latencies.append(time.time() - s)
    elapsed = time.time() - t0
    stop.set()
    for t in threads:
        t.join(timeout=2.0)
    # under plain FIFO the polite flow waits behind ~40 greedy holders per
    # request (~0.08s each, plus continuous re-arrivals = starvation);
    # fair dispatch serves its queue every round
    assert done["polite"] == 10
    assert elapsed < 3.0, (elapsed, polite_latencies)
    assert max(polite_latencies) < 1.0, polite_latencies


def test_queueset_overflow_rejects_429():
    from kubernetes_tpu.store.flowcontrol import (FlowController,
                                                  PriorityLevel,
                                                  RejectedError)
    import threading
    level = PriorityLevel("t", concurrency=1, queue_length=1,
                          n_queues=1, hand_size=1)
    fc = FlowController(levels=[level,
                                PriorityLevel("global-default",
                                              concurrency=20)])
    fc.acquire(level, flow="a")       # seat taken
    waiter_admitted = threading.Event()

    def waiter():
        fc.acquire(level, timeout=5.0, flow="a")  # queued (len 1)
        waiter_admitted.set()
        fc.release(level)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time
    time.sleep(0.05)
    with pytest.raises(RejectedError):
        fc.acquire(level, timeout=0.1, flow="a")  # queue full -> 429
    assert fc.rejected_total >= 1
    fc.release(level)                 # frees the seat -> waiter admitted
    assert waiter_admitted.wait(2.0)
    t.join(timeout=2.0)


# ------------------------------------------------------- server-side dry run

def test_dry_run_create_runs_admission_but_persists_nothing():
    """?dryRun=All (endpoints/handlers/create.go): admission mutations and
    denials apply, the would-be object returns, and the store is
    untouched — including quota holds, which must release."""
    from kubernetes_tpu.testing.wrappers import make_pod
    server = APIServer()
    server.enable_admission()
    server.start()
    try:
        c = HTTPClient(server.url)
        pod = make_pod("ghost").req({"cpu": "100m"}).obj().to_dict()
        out = c.pods("default").create(pod, dry_run=True)
        # admission ran: the default tolerations were injected
        assert any(t.get("key") == "node.kubernetes.io/not-ready"
                   for t in out["spec"]["tolerations"])
        # nothing persisted
        with pytest.raises(ApiError) as ei:
            c.pods("default").get("ghost")
        assert ei.value.code == 404
        # a denying policy still denies in dry-run
        nss = c.resource("namespaces", None)
        ns = nss.get("default")
        ns.setdefault("metadata", {}).setdefault("labels", {})[
            "pod-security.kubernetes.io/enforce"] = "baseline"
        nss.update(ns)
        bad = make_pod("priv").obj().to_dict()
        bad["spec"]["hostNetwork"] = True
        with pytest.raises(ApiError) as ei:
            c.pods("default").create(bad, dry_run=True)
        assert "PodSecurity" in str(ei.value)
        # dry-run against an existing name reports the conflict
        c.pods("default").create(make_pod("real").obj().to_dict())
        with pytest.raises(ApiError) as ei:
            c.pods("default").create(make_pod("real").obj().to_dict(),
                                     dry_run=True)
        assert ei.value.code == 409
    finally:
        server.stop()
