"""One resident scheduling program: fused churn folds + resident preemption.

PR tentpole coverage: (a) churn deltas ride the drain dispatch as
``drain_step``'s third donated input (models/gang.py) instead of a separate
blocking ``apply_ctx_patch`` dispatch, and fold-SAFE churn no longer drains
the multi-deep dispatch pipeline first (encode/patch.py entries_fold_safe);
(b) the preemption wave shares the device-resident cluster image — static
masks on the resident encoding in place, per-node totals read back from it,
victim request vectors from its fold ledger — instead of re-encoding
``nodes``/``bound_pods`` per wave.

The parity tests are the contract: fused-vs-legacy placements must be
IDENTICAL on the same delta log, and the resident wave must return exactly
what the snapshot-path wave returns (PDB budgets, victim sets, dedup
included) — the fusion is an optimization, never a semantics fork.
"""

import threading

import numpy as np
import pytest

from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n, cpu="4", prefix="n"):
    return [make_node(f"{prefix}{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": "32"})
            .obj() for i in range(n)]


def _sched(nodes, batch_size=4, drain_batches=2, fused=True,
           pipeline_depth=2, parity_every=0):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    cfg = SchedulerConfiguration(batch_size=batch_size,
                                 max_drain_batches=drain_batches,
                                 pipeline_depth=pipeline_depth,
                                 fused_fold=fused,
                                 parity_sample_every=parity_every)
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, cache, queue, log


def _arm(sched, slot_headroom=128):
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(sched.cfg.batch_size)]
    assert sched.warm_drain(warm, slot_headroom=slot_headroom)
    return sched._drain_ctx


def _drain(sched, queue, pods, rounds=8):
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if not sched._pending and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    sched.wait_for_bindings()
    return bound


# ---- tentpole (a): fused fold vs apply-then-dispatch parity ---------------

def _churn_script(rng, cache, i):
    """One randomized churn op against the cache (the delta-log feed)."""
    op = rng.integers(0, 4)
    if op == 0:  # foreign bound pod lands
        cache.add_pod(make_pod(f"foreign{i}").req({"cpu": "300m"})
                      .node(f"n{int(rng.integers(0, 3))}").obj())
    elif op == 1:  # foreign pod leaves
        cache.remove_pod(f"default/foreign{max(0, i - 2)}")
    elif op == 2:  # node add
        cache.add_node(make_node(f"late{i}")
                       .capacity({"cpu": "2", "memory": "4Gi", "pods": "8"})
                       .obj())
    else:  # node relabel (upsert of an existing node)
        cache.add_node(make_node("n0")
                       .capacity({"cpu": "4", "memory": "8Gi", "pods": "32"})
                       .label("churn", f"v{i}").obj())


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_fold_matches_apply_then_dispatch(seed):
    """Randomized parity: the SAME delta log driven through a fused-fold
    scheduler and a legacy (separate apply_ctx_patch) scheduler must bind
    the same pods to the same nodes — the third drain input is the same
    scatter the standalone dispatch applied, so placements cannot drift."""
    rng = np.random.default_rng(seed)
    script = []  # (kind, payload) replayed identically against both
    for i in range(6):
        script.append(("churn", int(rng.integers(0, 4)), i))
        script.append(("drain", i))

    def run(fused):
        sched, cache, queue, log = _sched(_nodes(3), fused=fused)
        _arm(sched)
        for step in script:
            if step[0] == "churn":
                _churn_script(np.random.default_rng(step[1] * 7 + step[2]),
                              cache, step[2])
            else:
                i = step[1]
                got = _drain(sched, queue,
                             [make_pod(f"m{i}-{j}").req({"cpu": "200m"}).obj()
                              for j in range(4)])
                assert got == 4, f"step {i} lost pods (fused={fused})"
        stats = dict(sched.ctx_stats)
        sched.close()
        return sorted(log), stats  # bind workers race log order, not content

    log_fused, stats_fused = run(True)
    log_legacy, stats_legacy = run(False)
    assert log_fused == log_legacy, (log_fused, log_legacy)
    # both runs took their respective churn path at least once
    assert stats_fused["patches"] == 0
    assert stats_fused["folds"] >= 1
    assert stats_legacy["folds"] == 0
    assert stats_legacy["patches"] >= 1


def test_fold_safe_churn_does_not_drain_the_pipeline():
    """The serialize-on-churn fix: with a drain IN FLIGHT, fold-safe foreign
    churn (a bound pod landing) must compile into the next dispatch without
    resolving the pipeline; fold-UNSAFE churn (a node delete, whose retire
    accounting cannot see in-flight folds) must still resolve first."""
    sched, cache, queue, log = _sched(_nodes(4), pipeline_depth=2)
    _arm(sched)
    # park resolution: dispatched drains stay in flight until we say so
    parked = []

    def park(pend):
        pend["done"] = threading.Event()
        parked.append(pend)
    sched._submit_resolve = park
    resolves = {"n": 0}
    orig_rp = sched._resolve_pending

    def counting_rp():
        resolves["n"] += 1
        return orig_rp()
    sched._resolve_pending = counting_rp

    def release_and_resolve():
        for pend in parked:
            pend["done"].set()  # inline fetch takes over immediately
        n = orig_rp()
        parked.clear()
        return n

    # cycle 1: drain dispatches, stays in flight
    for p in [make_pod(f"a{j}").req({"cpu": "200m"}).obj() for j in range(4)]:
        queue.add(p)
    sched.run_once(wait=0.01)
    assert len(sched._pending) == 1

    # fold-safe foreign churn + cycle 2: NO pipeline drain, one fused fold
    cache.add_pod(make_pod("foreign").req({"cpu": "300m"}).node("n1").obj())
    for p in [make_pod(f"b{j}").req({"cpu": "200m"}).obj() for j in range(4)]:
        queue.add(p)
    sched.run_once(wait=0.01)
    assert resolves["n"] == 0, "fold-safe churn drained the pipeline"
    assert len(sched._pending) == 2
    assert sched.ctx_stats["folds"] == 1
    assert sched.ctx_stats["patches"] == 0

    # fold-UNSAFE churn (node delete) with drains in flight: resolve first
    # (the forced resolve's bounded wait degrades to an inline fetch — cut
    # the wait short so the test doesn't idle 30s against a parked Event)
    import kubernetes_tpu.sched.scheduler as sched_mod
    saved_wait = sched_mod.RESOLVE_WAIT_S
    sched_mod.RESOLVE_WAIT_S = 0.3
    try:
        cache.remove_node("n3")
        for p in [make_pod(f"c{j}").req({"cpu": "200m"}).obj()
                  for j in range(4)]:
            queue.add(p)
        sched.run_once(wait=0.01)
        assert resolves["n"] >= 1, "node delete must settle in-flight folds"
    finally:
        sched_mod.RESOLVE_WAIT_S = saved_wait

    release_and_resolve()
    sched.wait_for_bindings()
    assert len(log) == 12, (len(log), log)
    assert not any(node == "n3" for name, node in log
                   if name.startswith("c")), log
    sched.close()


def test_steady_state_churn_zero_separate_patch_dispatches():
    """Acceptance: a fused-mode churn storm (the scheduler_perf recreate
    shape) keeps ctx_stats['patches'] at 0 — every delta folds on-device
    inside a dispatch — and the context never rebuilds."""
    sched, cache, queue, log = _sched(_nodes(4))
    ctx = _arm(sched)
    for i in range(6):
        cache.add_node(make_node(f"churn-n{i}")
                       .capacity({"cpu": "2", "memory": "4Gi", "pods": "8"})
                       .obj())
        if i >= 2:
            cache.remove_node(f"churn-n{i-2}")
            cache.remove_pod(f"default/m{i-2}")
        assert _drain(sched, queue,
                      [make_pod(f"m{i}").req({"cpu": "100m"}).obj()]) == 1
        assert sched._drain_ctx is ctx, f"context rebuilt at cycle {i}"
    assert sched.ctx_stats["patches"] == 0, sched.ctx_stats
    assert sched.ctx_stats["folds"] >= 6, sched.ctx_stats
    assert sched.ctx_stats["rebuilds"] == 0, sched.ctx_stats
    sched.close()


def test_sentinel_judges_deltas_folded_inside_a_dispatch():
    """Parity sentinel vs the fused fold: deltas folded INSIDE a sampled
    dispatch are part of what the device saw (the scatter applies in front
    of the scan), and the capture's log cursor is taken after the advance —
    so a correct fused program must produce zero divergences even when the
    sampled dispatch itself carried churn."""
    sched, cache, queue, log = _sched(_nodes(3), parity_every=1)
    _arm(sched)
    assert sched.sentinel is not None
    for i in range(3):
        cache.add_pod(make_pod(f"f{i}").req({"cpu": "500m"})
                      .node(f"n{i}").obj())  # fold-safe churn, every cycle
        assert _drain(sched, queue,
                      [make_pod(f"m{i}-{j}").req({"cpu": "200m"}).obj()
                       for j in range(4)]) == 4
    sched.sentinel.drain()
    assert sched.ctx_stats["folds"] >= 1
    assert sched.sentinel.samples["drain"] >= 1
    assert sched.sentinel.divergences == 0, sched.sentinel.last_divergence
    sched.close()


# ---- tentpole (b): resident preemption wave -------------------------------

def _preempt_fixture(pdb=False):
    """A saturated little cluster scheduled THROUGH the drain (so the
    resident context's fold ledger owns the placements), plus high-priority
    preemptors."""
    sched, cache, queue, log = _sched(_nodes(6, cpu="2"))
    _arm(sched)
    low = [make_pod(f"low{i}").req({"cpu": "1500m"}).priority(1)
           .label("app", "victim").obj() for i in range(6)]
    assert _drain(sched, queue, low) == 6
    if pdb:
        sched.pdb_lister = lambda: [{
            "metadata": {"name": "pdb", "namespace": "default"},
            "spec": {"selector": {"matchLabels": {"app": "victim"}},
                     "minAvailable": 4},
            "status": {}}]
    views = [make_pod(f"hi{i}").req({"cpu": "1800m"}).priority(100).obj()
             for i in range(3)]
    return sched, cache, views


def _norm(results):
    return [(r.node_name, sorted(v.key for v in r.victims),
             r.num_pdb_violations) if r else None for r in results]


@pytest.mark.parametrize("pdb", [False, True])
def test_resident_wave_parity_with_snapshot_wave(pdb):
    """The wave riding the resident context must return EXACTLY what the
    snapshot-path wave returns — same winners, same victim sets (deduped
    across picks by the shared sequential commit), same PDB-violation
    counts charged against the same budgets."""
    import kubernetes_tpu.sched.preemption as pmod
    sched, cache, views_src = _preempt_fixture(pdb=pdb)
    view = sched._resident_wave_view()
    assert view is not None, "fixture should leave a current resident ctx"
    bound = cache.bound_pods(include_assumed=True)
    pdbs = sched.pdb_lister()
    masks = pmod.tensor_static_masks(
        view["nodes"], views_src, ct=view["ct"], meta=view["meta"],
        encode_pods=cache.encode_pods, min_p=4, pre_staged=True,
        node_rows=view["rows"])
    resident = pmod.preempt_wave(
        view["nodes"], bound, views_src, pdbs=pdbs, static_masks=masks,
        min_q=4, resident_arrays=sched._resident_cluster_arrays(view),
        req_lookup=sched._resident_req_lookup(view))
    plain = pmod.preempt_wave(view["nodes"], bound, views_src, pdbs=pdbs,
                              min_q=4)
    assert _norm(resident) == _norm(plain)
    # victim dedup holds across the wave's sequential commits
    evicted = [v.key for r in resident if r for v in r.victims]
    assert len(evicted) == len(set(evicted))
    sched.close()


def test_resident_cluster_arrays_match_host_encode():
    """The arrays the resident path feeds dry_run_wave — totals read back
    from the device-resident encoding, victim vectors from the fold
    ledger — must equal the host encode bit for bit (same scaled-integer
    arithmetic, same implicit 'pods' slot, same UNLIMITED caps)."""
    from kubernetes_tpu.ops.preemption import _encode_cluster_arrays
    sched, cache, views = _preempt_fixture()
    # foreign churn so the ledger holds PATCHED vectors too, not just folds
    cache.add_pod(make_pod("patched").req({"cpu": "250m"}).priority(1)
                  .node("n0").obj())
    # a probe drain consumes the delta (fused fold) so the ctx is current
    assert _drain(sched, sched.queue,
                  [make_pod("probe").req({"cpu": "100m"}).obj()]) == 1
    view = sched._resident_wave_view()
    assert view is not None
    bound = cache.bound_pods(include_assumed=True)
    resources = sorted({**dict(views[0].resource_requests())})
    host = _encode_cluster_arrays(view["nodes"], bound, resources, 100, [])
    res = _encode_cluster_arrays(
        view["nodes"], bound, resources, 100, [],
        resident_arrays=sched._resident_cluster_arrays(view),
        req_lookup=sched._resident_req_lookup(view))
    for a, b, name in zip(host, res, ("allocatable", "requested", "vic_req",
                                      "vic_valid", "vic_violating",
                                      "vic_prio", "vic_ref")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)
    sched.close()


def test_resident_wave_declines_when_stale_or_inflight():
    """Discipline: the resident view must refuse to stand in for a snapshot
    when the context is tainted, lags the delta log, was staged under an
    old mesh epoch, or when drains are still in flight (their folds are in
    the resident totals but not in the cache's bound view)."""
    sched, cache, _ = _preempt_fixture()
    assert sched._resident_wave_view() is not None
    # unconsumed foreign delta -> stale
    cache.add_pod(make_pod("fresh").req({"cpu": "100m"}).node("n0").obj())
    assert sched._resident_wave_view() is None
    # a probe drain folds the delta in: current again
    assert _drain(sched, sched.queue,
                  [make_pod("probe").req({"cpu": "100m"}).obj()]) == 1
    assert sched._resident_wave_view() is not None
    # in-flight drain -> decline
    sched._pending.append({"chunks": []})
    assert sched._resident_wave_view() is None
    sched._pending.clear()
    # mesh epoch moved -> decline (reshape semantics)
    sched._mesh_epoch += 1
    assert sched._resident_wave_view() is None
    sched._mesh_epoch -= 1
    # tainted -> decline
    sched._drain_ctx["cs"].tainted = True
    assert sched._resident_wave_view() is None
    sched.close()


def test_connected_failure_path_uses_resident_wave():
    """End to end through _handle_failures: a wave of preemptors failing at
    a drain resolve must ride the resident context (no snapshot span), and
    the nominations + evictions must match what the standalone wave
    computes on the same state."""
    import kubernetes_tpu.sched.preemption as pmod
    sched, cache, views = _preempt_fixture()
    bound_before = cache.bound_pods(include_assumed=True)
    expect = _norm(pmod.preempt_wave(
        sched._resident_wave_view()["nodes"], bound_before, views,
        min_q=pmod.WAVE_BUCKET))
    evicted = []
    sched._evict = lambda v: evicted.append(v.key) or \
        cache.remove_pod(v.key)
    noms = sched._default_preempt_wave(views)
    assert noms == [e[0] if e else None for e in expect]
    assert sorted(evicted) == sorted(
        v for e in expect if e for v in e[1])
    sched.close()
