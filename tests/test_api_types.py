"""API object model: quantities, selectors, wire round-trip.

Golden cases mirror the reference's table-driven tests
(apimachinery resource quantity tests; labels selector tests)."""

import pytest

from kubernetes_tpu.api import (
    LabelSelector,
    Pod,
    Requirement,
    Taint,
    Toleration,
    parse_quantity,
    to_bytes,
    to_milli,
)
from kubernetes_tpu.api.selectors import (
    label_selector_matches,
    node_selector_matches,
    requirement_matches,
)
from kubernetes_tpu.api.types import NodeSelectorTerm
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.mark.parametrize("s,expected", [
    ("100m", 0.1), ("1", 1.0), ("2.5", 2.5), ("1Gi", 2**30), ("512Mi", 512 * 2**20),
    ("1k", 1000.0), ("1M", 1e6), ("1e3", 1000.0), ("0", 0.0), ("16Ki", 16384.0),
])
def test_parse_quantity(s, expected):
    assert parse_quantity(s) == pytest.approx(expected)


def test_quantity_canonical_units():
    assert to_milli("250m") == 250
    assert to_milli("2") == 2000
    assert to_bytes("1Gi") == 2**30


def test_parse_quantity_invalid():
    with pytest.raises(ValueError):
        parse_quantity("abc")


def test_pod_resource_requests_max_of_init_and_sum():
    pod = (make_pod("p").req({"cpu": "100m", "memory": "1Gi"})
           .container_req({"cpu": "200m"})
           .init_req({"cpu": "500m"})
           .overhead({"cpu": "50m"}).obj())
    reqs = pod.resource_requests()
    # sum(containers)=300m; max(init)=500m -> effective 500m, + overhead 50m
    assert reqs["cpu"] == 550
    assert reqs["memory"] == 2**30
    assert reqs["pods"] == 1


@pytest.mark.parametrize("op,values,labels,want", [
    ("In", ["a", "b"], {"k": "a"}, True),
    ("In", ["a"], {"k": "z"}, False),
    ("In", ["a"], {}, False),
    ("NotIn", ["a"], {"k": "b"}, True),
    ("NotIn", ["a"], {"k": "a"}, False),
    ("NotIn", ["a"], {}, True),          # absent key matches NotIn
    ("Exists", [], {"k": "x"}, True),
    ("Exists", [], {}, False),
    ("DoesNotExist", [], {}, True),
    ("DoesNotExist", [], {"k": "x"}, False),
    ("Gt", ["5"], {"k": "7"}, True),
    ("Gt", ["5"], {"k": "5"}, False),
    ("Lt", ["5"], {"k": "3"}, True),
    ("Gt", ["5"], {}, False),
    ("Gt", ["5"], {"k": "notanum"}, False),
])
def test_requirement_matches(op, values, labels, want):
    assert requirement_matches(Requirement("k", op, values), labels) is want


def test_node_selector_or_of_terms():
    terms = [
        NodeSelectorTerm(match_expressions=[Requirement("zone", "In", ["a"])]),
        NodeSelectorTerm(match_expressions=[Requirement("zone", "In", ["b"])]),
    ]
    assert node_selector_matches(terms, {"zone": "b"})
    assert not node_selector_matches(terms, {"zone": "c"})
    assert not node_selector_matches([], {"zone": "a"})
    # empty term matches nothing
    assert not node_selector_matches([NodeSelectorTerm()], {"zone": "a"})


def test_label_selector_nil_vs_empty():
    assert not label_selector_matches(None, {"a": "b"})
    assert label_selector_matches(LabelSelector(), {"a": "b"})  # empty matches all
    sel = LabelSelector(match_labels={"app": "web"},
                        match_expressions=[Requirement("tier", "NotIn", ["db"])])
    assert label_selector_matches(sel, {"app": "web", "tier": "fe"})
    assert not label_selector_matches(sel, {"app": "web", "tier": "db"})
    assert not label_selector_matches(sel, {"tier": "fe"})


@pytest.mark.parametrize("tol,taint,want", [
    (Toleration(operator="Exists"), Taint("any", "v", "NoSchedule"), True),
    (Toleration(key="k", operator="Exists"), Taint("k", "v", "NoSchedule"), True),
    (Toleration(key="k", operator="Exists"), Taint("other", "v", "NoSchedule"), False),
    (Toleration(key="k", operator="Equal", value="v"), Taint("k", "v", "NoSchedule"), True),
    (Toleration(key="k", operator="Equal", value="w"), Taint("k", "v", "NoSchedule"), False),
    (Toleration(key="k", operator="Equal", value="v", effect="NoExecute"),
     Taint("k", "v", "NoSchedule"), False),
])
def test_toleration_tolerates(tol, taint, want):
    assert tol.tolerates(taint) is want


def test_wire_roundtrip():
    pod = (make_pod("web-1", "prod").label("app", "web")
           .req({"cpu": "500m", "memory": "256Mi"})
           .toleration(key="gpu", operator="Exists", effect="NoSchedule")
           .node_affinity_in("zone", ["us-a", "us-b"])
           .pod_anti_affinity("kubernetes.io/hostname", {"app": "web"})
           .spread(1, "zone", "DoNotSchedule", {"app": "web"})
           .host_port(8080).priority(100).obj())
    d = pod.to_dict()
    pod2 = Pod.from_dict(d)
    assert pod2.to_dict() == d
    assert pod2.spec.affinity.pod_anti_affinity.required[0].topology_key == "kubernetes.io/hostname"
    assert pod2.resource_requests() == pod.resource_requests()

    node = (make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": "110"})
            .taint("dedicated", "ml", "NoSchedule").label("zone", "us-a").obj())
    from kubernetes_tpu.api.types import Node
    assert Node.from_dict(node.to_dict()).to_dict() == node.to_dict()
    assert node.allocatable_canonical()["cpu"] == 4000
