"""Chaos harness + self-healing connected loop.

Five contracts pinned here:

1. DETERMINISM — a fault schedule generated from a seed replays exactly
   (the one logged seed reproduces any chaos failure), and the injectors
   fire/recover where the schedule says.
2. RELIST-AND-RESYNC — watch gaps (truncated streams, forced
   "resourceVersion too old") heal by relist: the rebuilt informer cache
   equals a fresh list, and ``watch_relists_total`` proves the healing ran.
3. DEGRADE-DON'T-DIE — consecutive device failures walk the circuit
   breaker mesh -> single-device -> pure-numpy oracle WITHOUT dropping a
   scheduling cycle, and half-open probes restore the tensor path.
4. WATCHDOG — a dead/stalled loop or resolver thread restarts (resident
   ctx tainted) instead of hanging the runner.
5. CRASH RECOVERY — a scheduler killed mid-flight reconciles
   assumed-but-unbound pods and stale nominations from apiserver state
   and converges to the same placements as an uninterrupted run.
"""

import threading
import time
from types import SimpleNamespace

import pytest

from kubernetes_tpu.chaos import (
    ChaosClient,
    DeviceChaos,
    Fault,
    FaultSchedule,
    ThreadChaos,
    hooks,
)
from kubernetes_tpu.client.clientset import ApiError, DirectClient
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.metrics.registry import (
    BIND_RETRIES,
    LOOP_ERRORS,
    WATCH_RELISTS,
)
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.resilience import DeviceCircuitBreaker
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.store.store import ObjectStore, TooOld
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.utils.clock import FakeClock

pytestmark = pytest.mark.chaos


def wait_for(pred, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def _nodes(n, cpu="4", pods="16"):
    return [make_node(f"n{i}")
            .capacity({"cpu": cpu, "memory": "8Gi", "pods": pods})
            .label("kubernetes.io/hostname", f"n{i}")
            .obj() for i in range(n)]


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    yield
    hooks.uninstall()


# ---- 1. determinism ------------------------------------------------------

def test_fault_schedule_deterministic_replay():
    a = FaultSchedule.generate(1234, profile="churn")
    b = FaultSchedule.generate(1234, profile="churn")
    assert [(f.site, f.kind, f.at, f.count, f.arg) for f in a.faults] \
        == [(f.site, f.kind, f.at, f.count, f.arg) for f in b.faults]
    c = FaultSchedule.generate(99, profile="churn")
    assert [(f.site, f.at) for f in a.faults] != [(f.site, f.at)
                                                 for f in c.faults]
    # replay: identical op streams make identical fire decisions (the
    # default profile's offsets land inside a few dozen ops)
    a = FaultSchedule.generate(1234)
    b = FaultSchedule.generate(1234)
    sites = (["api.bind"] * 20 + ["api.create"] * 20
             + ["device.gang"] * 8)
    fires_a = [bool(a.should_fire(s)) for s in sites]
    fires_b = [bool(b.should_fire(s)) for s in sites]
    assert fires_a == fires_b and any(fires_a)


def test_api_injector_fires_and_reports_recovery():
    store = ObjectStore()
    schedule = FaultSchedule([
        Fault("api.create", "error", 1, 1, 503),
        Fault("api.bind", "conflict", 0),
        Fault("api.update", "latency", 0, 1, 0.01),
    ], seed=7)
    client = ChaosClient(DirectClient(store), schedule)
    client.nodes().create(_nodes(1)[0].to_dict())        # op 0: clean
    with pytest.raises(ApiError) as ei:
        client.pods().create(make_pod("p0").obj().to_dict())  # op 1: 503
    assert ei.value.code == 503
    client.pods().create(make_pod("p0").obj().to_dict())  # op 2: heals
    with pytest.raises(ApiError) as ei:
        client.pods().bind("p0", "n0")
    assert ei.value.code == 409
    client.pods().bind("p0", "n0")                        # heals
    p = client.pods().get("p0")
    client.pods().update(p)                               # latency, then ok
    rep = schedule.report()
    assert rep["seed"] == 7
    assert rep["classes"]["api.create:error"]["fires"] == 1
    assert rep["classes"]["api.create:error"]["recovered"] == 1
    assert rep["classes"]["api.bind:conflict"]["recovered"] == 1
    assert not rep["unrecovered_sites"]


def test_chaos_watch_truncates_and_too_old():
    store = ObjectStore()
    schedule = FaultSchedule([
        Fault("watch.pods", "too_old", 0),
        Fault("watch.pods", "drop", 1, 1, 2),
    ])
    client = ChaosClient(DirectClient(store), schedule)
    with pytest.raises(TooOld):
        client.pods().watch(since_rv=0)
    w = client.pods().watch(since_rv=0)  # truncating stream: 2 events max
    for i in range(4):
        store.create("Pod", make_pod(f"p{i}").obj().to_dict())
    got = []
    deadline = time.time() + 5
    while not w.closed and time.time() < deadline:
        ev = w.get(timeout=0.1)
        if ev is not None:
            got.append(ev.object["metadata"]["name"])
    assert got == ["p0", "p1"] and w.closed


# ---- 2. informer relist-and-resync parity --------------------------------

def test_informer_relists_and_rebuilt_cache_equals_fresh_list():
    """Watch chaos (truncation + forced too-old) while the server mutates:
    once chaos quiesces, the informer store must equal a fresh list (the
    parity proof) and the relist counter must show the healing ran."""
    from kubernetes_tpu.client.informer import SharedInformer
    store = ObjectStore()
    direct = DirectClient(store)
    # the informer's FIRST watch call is op 0: truncate it, force a
    # too-old on the re-establish, truncate once more, then heal
    schedule = FaultSchedule([
        Fault("watch.pods", "drop", 0, 1, 3),
        Fault("watch.pods", "too_old", 1),
        Fault("watch.pods", "drop", 2, 1, 2),
    ])
    client = ChaosClient(direct, schedule)
    relists_before = WATCH_RELISTS.get({"resource": "pods"})
    inf = SharedInformer(client.pods("default"))
    seen = []
    inf.add_event_handler(lambda t, o, old: seen.append(t))
    inf.start()
    assert inf.wait_for_cache_sync(5)
    # continuous mutation through every gap the schedule forces
    for i in range(30):
        store.create("Pod", make_pod(f"p{i}").obj().to_dict())
        if i % 5 == 0:
            time.sleep(0.05)
    store.delete("Pod", "default", "p0")
    store.delete("Pod", "default", "p7")
    # keep trickling mutations until every scheduled gap has healed — a
    # truncating stream only closes once events flow through it
    extra = 0
    deadline = time.time() + 20
    while inf.relists < 3 and time.time() < deadline:
        store.create("Pod", make_pod(f"x{extra}").obj().to_dict())
        extra += 1
        time.sleep(0.05)
    assert inf.relists >= 3, f"relists={inf.relists}"
    fresh = {(p["metadata"]["namespace"], p["metadata"]["name"]):
             p["metadata"]["resourceVersion"]
             for p in direct.pods("default").list()}

    def parity():
        mine = {((o.get("metadata") or {}).get("namespace"),
                 o["metadata"]["name"]): o["metadata"]["resourceVersion"]
                for o in inf.store.list()}
        return mine == fresh
    assert wait_for(parity, timeout=10), (
        sorted(fresh), sorted((o["metadata"]["name"])
                              for o in inf.store.list()))
    assert inf.last_relist is not None
    assert WATCH_RELISTS.get({"resource": "pods"}) - relists_before >= 3
    inf.stop()


def test_no_silent_swallow_decode_and_handler_errors():
    from kubernetes_tpu.client.informer import SharedInformer
    store = ObjectStore()
    runner = SchedulerRunner(DirectClient(store))
    pod_before = LOOP_ERRORS.get({"site": "pod_decode"})
    node_before = LOOP_ERRORS.get({"site": "node_decode"})
    runner._on_pod("ADDED", {"metadata": {"name": "bad"},
                             "spec": {"containers": 42}}, None)
    runner._on_node("ADDED", {"metadata": {"name": "bad"},
                              "status": {"allocatable": 42}}, None)
    assert LOOP_ERRORS.get({"site": "pod_decode"}) - pod_before == 1
    assert LOOP_ERRORS.get({"site": "node_decode"}) - node_before == 1
    # a handler that throws is counted, and later handlers still run
    handler_before = LOOP_ERRORS.get({"site": "informer_handler"})
    inf = SharedInformer(DirectClient(store).pods("default"))
    ran = []
    inf.add_event_handler(lambda *a: (_ for _ in ()).throw(RuntimeError()))
    inf.add_event_handler(lambda *a: ran.append(1))
    inf._dispatch("ADDED", {"metadata": {"name": "x"}}, None)
    assert LOOP_ERRORS.get({"site": "informer_handler"}) \
        - handler_before == 1
    assert ran == [1]
    runner.scheduler.close()


# ---- 3. circuit breaker: degrade ladder + half-open ----------------------

def test_breaker_ladder_and_half_open_unit():
    clock = FakeClock(0.0)
    br = DeviceCircuitBreaker(levels=("mesh", "single", "oracle"),
                              threshold=3, cooldown_s=10.0, clock=clock)
    assert br.mode == "mesh" and br.attempt_level() == "mesh"
    for _ in range(3):
        br.fail("mesh")
    assert br.mode == "single" and br.trips == 1
    for _ in range(3):
        br.fail("single")
    assert br.mode == "oracle" and br.trips == 2
    # cooldown not elapsed: no probe
    assert br.attempt_level() == "oracle"
    clock.advance(11.0)
    assert br.attempt_level() == "single"   # half-open probe
    br.fail("single")                        # probe fails -> stay, re-arm
    assert br.mode == "oracle" and br.attempt_level() == "oracle"
    clock.advance(11.0)
    assert br.attempt_level() == "single"
    br.succeed("single")                     # probe passes -> restore
    assert br.mode == "single" and br.restores == 1
    clock.advance(11.0)
    assert br.attempt_level() == "mesh"
    br.succeed("mesh")
    assert br.mode == "mesh" and br.restores == 2
    # a success mid-count resets the CONSECUTIVE failure counter
    br.fail("mesh")
    br.fail("mesh")
    br.succeed("mesh")
    br.fail("mesh")
    br.fail("mesh")
    assert br.mode == "mesh"


def _direct_sched(nodes, batch_size=8, **cfg_kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.01, backoff_max=0.05)
    cfg = SchedulerConfiguration(batch_size=batch_size,
                                 backoff_initial_s=0.01,
                                 backoff_max_s=0.05, **cfg_kw)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, cache, queue, log


def test_scheduler_degrades_to_oracle_without_dropping_a_cycle():
    """The acceptance gate: a device-failure burst trips mesh(single) ->
    oracle, yet EVERY cycle still binds its batch; after the device heals
    and the cooldown elapses, the half-open probe restores the tensor
    path — also without dropping the probe cycle."""
    sched, cache, queue, log = _direct_sched(_nodes(4), batch_size=8,
                                             breaker_threshold=2)
    clock = FakeClock(0.0)
    sched.breaker = DeviceCircuitBreaker(levels=("single", "oracle"),
                                         threshold=2, cooldown_s=10.0,
                                         clock=clock)
    schedule = FaultSchedule([Fault("device.gang", "runtime", 0, 2)])
    chaos = DeviceChaos(schedule).install()
    try:
        # cycles 1..2: device fails -> same-cycle oracle fallback binds
        for cyc in range(2):
            for i in range(4):
                queue.add(make_pod(f"c{cyc}-p{i}")
                          .req({"cpu": "100m"}).obj())
            n = sched.run_once(wait=0.01)
            assert n == 4, f"cycle {cyc} dropped pods: bound {n}"
        assert sched.breaker.mode == "oracle"
        assert sched.breaker.trips == 1
        # cycle 3: fully degraded — oracle path, no device touch
        for i in range(4):
            queue.add(make_pod(f"c2-p{i}").req({"cpu": "100m"}).obj())
        assert sched.run_once(wait=0.01) == 4
        # device heals (schedule exhausted after 4 fires) + cooldown:
        # the half-open probe runs the tensor path and restores
        clock.advance(11.0)
        for i in range(4):
            queue.add(make_pod(f"c3-p{i}").req({"cpu": "100m"}).obj())
        assert sched.run_once(wait=0.01) == 4     # probe cycle binds too
        assert sched.breaker.mode == "single"
        assert sched.breaker.restores == 1
        sched.wait_for_bindings()
        assert len(log) == 16
    finally:
        chaos.uninstall()
        sched.close()


@pytest.mark.multichip
def test_breaker_mesh_degrade_and_restore():
    """With a configured (1,2) mesh, a device burst must drop the ACTIVE
    mesh to single-device (configured mesh remembered), and the half-open
    recovery must reinstall it."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    sched, cache, queue, log = _direct_sched(
        _nodes(4), batch_size=8, mesh_shape=(1, 2), breaker_threshold=2)
    if sched._mesh is None:
        pytest.skip("mesh unavailable on this backend")
    clock = FakeClock(0.0)
    sched.breaker = DeviceCircuitBreaker(
        levels=("mesh", "single", "oracle"), threshold=2, cooldown_s=10.0,
        clock=clock)
    configured = sched._configured_mesh
    schedule = FaultSchedule([Fault("device.gang", "runtime", 0, 2)])
    chaos = DeviceChaos(schedule).install()
    try:
        for cyc in range(2):
            for i in range(3):
                queue.add(make_pod(f"m{cyc}-p{i}")
                          .req({"cpu": "100m"}).obj())
            assert sched.run_once(wait=0.01) == 3
        assert sched.breaker.mode == "single"
        # next cycle actually runs single-device (mesh uninstalled)
        for i in range(3):
            queue.add(make_pod(f"m2-p{i}").req({"cpu": "100m"}).obj())
        assert sched.run_once(wait=0.01) == 3
        assert sched._mesh is None
        assert sched._configured_mesh is configured
        clock.advance(11.0)  # probe restores the mesh
        for i in range(3):
            queue.add(make_pod(f"m3-p{i}").req({"cpu": "100m"}).obj())
        assert sched.run_once(wait=0.01) == 3
        assert sched.breaker.mode == "mesh"
        assert sched._mesh is configured
    finally:
        chaos.uninstall()
        sched.close()


def test_drain_dispatch_failure_falls_back_same_cycle():
    """The fused-drain seam: a drain_step failure drops the resident ctx
    and the pop still schedules via the per-batch (or oracle) path."""
    nodes = _nodes(8)
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.01)
    cfg = SchedulerConfiguration(batch_size=4, max_drain_batches=2,
                                 breaker_threshold=2,
                                 backoff_initial_s=0.01, backoff_max_s=0.05)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(pod.metadata.name) or True)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    schedule = FaultSchedule([Fault("device.drain", "runtime", 0, 1)])
    chaos = DeviceChaos(schedule).install()
    try:
        pods = [make_pod(f"d{i}").req({"cpu": "100m"}).obj()
                for i in range(8)]
        for p in pods:
            queue.add(p)
        bound = 0
        for _ in range(20):
            bound += sched.run_once(wait=0.01)
            if bound >= 8:
                break
        bound += sched._resolve_pending()
        assert bound == 8
        sched.wait_for_bindings()
        assert len(log) == 8
    finally:
        chaos.uninstall()
        sched.close()


# ---- 4. thread watchdog + resolver stall ---------------------------------

def test_mid_cycle_failure_requeues_popped_batch():
    """A run_once that dies between pop and handling must not strand the
    popped pods (they are in no queue; no watch event re-delivers them):
    the rescue path requeues them and a later cycle binds them."""
    sched, cache, queue, log = _direct_sched(_nodes(2), batch_size=8)
    calls = []
    orig = sched._schedule_group

    def boom(profile, items, headroom=0):
        if not calls:
            calls.append(1)
            raise RuntimeError("mid-cycle boom")
        return orig(profile, items, headroom)
    sched._schedule_group = boom
    for i in range(4):
        queue.add(make_pod(f"rp{i}").req({"cpu": "100m"}).obj())
    with pytest.raises(RuntimeError):
        sched.run_once(wait=0.01)
    stats = queue.stats()
    assert sum(stats.values()) == 4, stats   # every popped pod requeued
    bound = 0
    for _ in range(30):
        bound += sched.run_once(wait=0.05)
        if bound == 4:
            break
    assert bound == 4
    sched.close()


def test_run_loop_self_heals_catchable_errors():
    """A catchable chaos error inside the loop is counted + absorbed; the
    loop keeps scheduling."""
    sched, cache, queue, log = _direct_sched(_nodes(2), batch_size=4)
    hooks.install(ThreadChaos(FaultSchedule(
        [Fault("thread.loop", "error", 1, 2)])))
    before = LOOP_ERRORS.get({"site": "run_once"})
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        for i in range(4):
            queue.add(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        assert wait_for(lambda: len(log) == 4, timeout=15), log
        assert LOOP_ERRORS.get({"site": "run_once"}) - before >= 1
        assert t.is_alive()
    finally:
        hooks.uninstall()
        stop.set()
        t.join(timeout=5)
        sched.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_watchdog_restarts_dead_loop_thread():
    """ChaosThreadDeath (a BaseException) kills the scheduling loop dead;
    the watchdog revives it, taints the ctx, and scheduling resumes."""
    store = ObjectStore()
    client = DirectClient(store)
    client.nodes().create(_nodes(2)[0].to_dict())
    runner = SchedulerRunner(client, SchedulerConfiguration(
        batch_size=4, backoff_initial_s=0.02, backoff_max_s=0.1,
        watchdog_interval_s=0.1, watchdog_stall_s=30.0))
    hooks.install(ThreadChaos(FaultSchedule(
        [Fault("thread.loop", "die", 2)])))
    try:
        runner.start()
        # the die fault fires within a few loop iterations; the watchdog
        # (100ms sweeps) must notice the dead thread and restart a term
        assert wait_for(lambda: runner._watchdog.restarts >= 1,
                        timeout=20), "watchdog never restarted the loop"
        hooks.uninstall()
        assert wait_for(
            lambda: runner._loop_thread is not None
            and runner._loop_thread.is_alive(), timeout=10)
        # the revived loop schedules
        for i in range(3):
            client.pods().create(
                make_pod(f"p{i}").req({"cpu": "100m"}).obj().to_dict())
        assert wait_for(
            lambda: all(p["spec"].get("nodeName")
                        for p in client.pods().list()), timeout=20), \
            [(p["metadata"]["name"], p["spec"].get("nodeName"))
             for p in client.pods().list()]
    finally:
        hooks.uninstall()
        runner.stop()


def test_resolver_stall_bounded_wait_falls_back_inline(monkeypatch):
    """A stalled resolver must not hang the loop: the bounded wait
    expires and the scheduling thread fetches inline."""
    import kubernetes_tpu.sched.scheduler as sched_mod
    monkeypatch.setattr(sched_mod, "RESOLVE_WAIT_S", 0.3)
    nodes = _nodes(8)
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.01)
    cfg = SchedulerConfiguration(batch_size=4, max_drain_batches=2,
                                 backoff_initial_s=0.01)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(pod.metadata.name) or True)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(4)]
    assert sched.warm_drain(warm, slot_headroom=64)
    hooks.install(ThreadChaos(FaultSchedule(
        [Fault("thread.resolver", "stall", 0, 1, 2.0)])))
    before = LOOP_ERRORS.get({"site": "resolver_wait"})
    try:
        pods = [make_pod(f"r{i}").req({"cpu": "100m"}).obj()
                for i in range(8)]
        for p in pods:
            queue.add(p)
        t0 = time.time()
        bound = 0
        for _ in range(20):
            bound += sched.run_once(wait=0.01)
            if bound >= 8:
                break
        bound += sched._resolve_pending()
        assert bound == 8
        assert LOOP_ERRORS.get({"site": "resolver_wait"}) - before >= 1
        sched.wait_for_bindings()
    finally:
        hooks.uninstall()
        sched.close()


# ---- 5. crash recovery + leader election + retries -----------------------

def _forced_workload(client, n_nodes=4, n_pods=8):
    """Placement-forced workload: pod i MUST land on n{i % n_nodes}
    (nodeSelector), so any correct run — interrupted or not — converges
    to the identical placement map."""
    for n in _nodes(n_nodes):
        client.nodes().create(n.to_dict())
    pods = []
    for i in range(n_pods):
        p = (make_pod(f"p{i}")
             .req({"cpu": "100m"})
             .node_selector({"kubernetes.io/hostname": f"n{i % n_nodes}"})
             .obj())
        client.pods().create(p.to_dict())
        pods.append(p)
    return pods


def _placements(client):
    return {p["metadata"]["name"]: p["spec"].get("nodeName")
            for p in client.pods().list()}


def _all_bound(client, n):
    pl = _placements(client)
    return len(pl) == n and all(pl.values())


def test_crash_recovery_mid_flight_reconverges():
    """Kill the scheduler while its bind layer is down (it has assumed /
    retried pods in flight, none bound), write a stale nomination the dead
    incarnation supposedly left, restart — the fresh runner reconciles
    everything from apiserver state and converges to the exact placements
    of an uninterrupted run."""
    cfg = lambda: SchedulerConfiguration(  # noqa: E731
        batch_size=4, backoff_initial_s=0.02, backoff_max_s=0.1,
        bind_retries=0)
    # ---- uninterrupted reference run
    store_ref = ObjectStore()
    ref_client = DirectClient(store_ref)
    _forced_workload(ref_client)
    r_ref = SchedulerRunner(DirectClient(store_ref), cfg())
    r_ref.start()
    assert wait_for(lambda: _all_bound(ref_client, 8), timeout=30)
    r_ref.stop()
    expected = _placements(ref_client)

    # ---- incarnation 1: bind layer down (assumes never become binds)
    store = ObjectStore()
    truth = DirectClient(store)
    pods = _forced_workload(truth)
    outage = FaultSchedule([Fault("api.bind", "error", 0, 10**6, 503)])
    r1 = SchedulerRunner(ChaosClient(DirectClient(store), outage), cfg())
    r1.start()
    # it must have TRIED (assumed + failed binds) before we kill it
    assert wait_for(lambda: outage.peek("api.bind") >= 1, timeout=20)
    r1.kill()  # crash: no graceful drain, in-memory assumed state dies
    assert not any(_placements(truth).values())  # nothing actually bound

    # stale nomination from the dead incarnation: must not wedge recovery
    p0 = truth.pods().get("p0")
    p0.setdefault("status", {})["nominatedNodeName"] = "n3"
    truth.pods().update_status(p0)

    # ---- incarnation 2: clean client, fresh state, same store
    r2 = SchedulerRunner(DirectClient(store), cfg())
    r2.start()
    try:
        assert wait_for(lambda: _all_bound(truth, 8),
                        timeout=30), _placements(truth)
        assert _placements(truth) == expected
        # capacity sanity: no node overcommitted past the forced mapping
        per_node = {}
        for name, node in _placements(truth).items():
            per_node[node] = per_node.get(node, 0) + 1
        assert all(v == 2 for v in per_node.values()), per_node
    finally:
        r2.stop()


@pytest.mark.disaster
def test_crash_recovery_warm_from_cache_restart_parity(tmp_path):
    """The cache-ON leg of crash recovery. Same scenario as above — bind
    outage, SIGKILL mid-flight, stale state — but every incarnation
    shares one durable AOT cache dir, and the restarted scheduler must
    deliver the SAME placements with ZERO genuine XLA compiles: after
    ``jax.clear_caches()`` wipes the in-process dispatch caches, every
    program it runs has to load from disk (``hits`` proves it did)."""
    import jax

    from kubernetes_tpu.sched.aotcache import AotExecutableCache
    cache_dir = str(tmp_path / "aot")
    cfg = lambda: SchedulerConfiguration(  # noqa: E731
        batch_size=4, backoff_initial_s=0.02, backoff_max_s=0.1,
        bind_retries=0, aot_cache_dir=cache_dir)
    try:
        # earlier tests may have warmed this process's jit dispatch
        # caches; drop them so the reference run genuinely compiles (and
        # therefore persists) every program
        jax.clear_caches()
        # ---- reference run: pins placements AND populates the cache
        store_ref = ObjectStore()
        ref_client = DirectClient(store_ref)
        _forced_workload(ref_client)
        r_ref = SchedulerRunner(DirectClient(store_ref), cfg())
        assert r_ref.aot_cache is not None
        r_ref.start()
        assert wait_for(lambda: _all_bound(ref_client, 8), timeout=30)
        r_ref.stop()
        expected = _placements(ref_client)
        assert r_ref.aot_cache.seal(force=True) >= 1  # durable entries

        # ---- incarnation 1: bind layer down, killed mid-flight
        store = ObjectStore()
        truth = DirectClient(store)
        _forced_workload(truth)
        outage = FaultSchedule([Fault("api.bind", "error", 0, 10**6, 503)])
        r1 = SchedulerRunner(ChaosClient(DirectClient(store), outage),
                             cfg())
        r1.start()
        assert wait_for(lambda: outage.peek("api.bind") >= 1, timeout=20)
        r1.kill()
        assert not any(_placements(truth).values())

        # ---- the restart: dispatch caches gone, only the disk survives
        jax.clear_caches()
        r2 = SchedulerRunner(DirectClient(store), cfg())
        assert r2.aot_cache is not None
        assert r2.aot_cache.boot["entries"] >= 1  # warm from birth
        r2.start()
        try:
            assert wait_for(lambda: _all_bound(truth, 8),
                            timeout=30), _placements(truth)
            assert _placements(truth) == expected
            stats = r2.aot_cache.stats()
            assert stats["realCompiles"] == 0, stats
            assert stats["hits"] >= 1, stats  # loaded, not re-derived
        finally:
            r2.stop()
    finally:
        AotExecutableCache.disarm()
        jax.clear_caches()


def test_leader_elector_survives_api_storm_and_callback_failure():
    """Satellite regression: the elector thread used to die silently when
    a callback raised or transport errors leaked; now it backs off,
    re-contends, and resumes leadership once the API (or the callback)
    heals."""
    from kubernetes_tpu.client.leaderelection import (LeaderElectionConfig,
                                                      LeaderElector)
    store = ObjectStore()
    # API storm: the first 4 lease reads fail hard (non-ApiError shape)
    schedule = FaultSchedule([Fault("api.get", "error", 0, 4, 503)])
    leases = ChaosClient(DirectClient(store), schedule).leases()
    calls = []
    started = threading.Event()

    def flaky_started():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("loop failed to start")
        started.set()
    el = LeaderElector(leases, LeaderElectionConfig(
        lock_name="sched", identity="me", lease_duration=0.6,
        renew_deadline=0.5, retry_period=0.05,
        on_started_leading=flaky_started))
    stop = threading.Event()
    before = LOOP_ERRORS.get({"site": "leader_elector"})
    t = threading.Thread(target=el.run, args=(stop,), daemon=True)
    t.start()
    try:
        # storm + one callback failure later, leadership resumes
        assert started.wait(15), f"calls={len(calls)}"
        assert el.is_leader
        assert LOOP_ERRORS.get({"site": "leader_elector"}) - before >= 1
        assert t.is_alive()
    finally:
        stop.set()
        t.join(timeout=5)


def test_bind_retries_absorb_transient_unavailability():
    store = ObjectStore()
    truth = DirectClient(store)
    truth.nodes().create(_nodes(1)[0].to_dict())
    truth.pods().create(make_pod("p0").req({"cpu": "100m"})
                        .obj().to_dict())
    schedule = FaultSchedule([Fault("api.bind", "error", 0, 2, 503)])
    runner = SchedulerRunner(ChaosClient(DirectClient(store), schedule),
                             SchedulerConfiguration(
                                 bind_retries=2,
                                 bind_retry_backoff_s=0.01))
    before = BIND_RETRIES.get()
    pod = __import__("kubernetes_tpu.api.types",
                     fromlist=["Pod"]).Pod.from_dict(truth.pods().get("p0"))
    assert runner._bind(pod, "n0") is True   # 2 x 503 absorbed in-request
    assert BIND_RETRIES.get() - before == 2
    assert truth.pods().get("p0")["spec"]["nodeName"] == "n0"
    # a 409 is semantic, never retried: second bind fails immediately
    assert runner._bind(pod, "n0") is False
    runner.scheduler.close()


# ---- status surface + chaos churn smoke ----------------------------------

def test_status_surfaces_resilience_state():
    import io
    import json
    from kubernetes_tpu.cli.ktpu import cmd_status
    store = ObjectStore()
    runner = SchedulerRunner(DirectClient(store))
    br = runner.scheduler.breaker
    for _ in range(br.threshold):
        br.fail(br.mode)          # trip one level -> degraded
    runner.publish_status()
    out = io.StringIO()
    rc = cmd_status(runner.client,
                    SimpleNamespace(namespace="default", output="json"),
                    out)
    assert rc == 0
    st = json.loads(out.getvalue())
    res = st["resilience"]
    assert res["degradedMode"] == "oracle"
    assert res["breakerTrips"] == 1
    assert res["watchdogRestarts"] == 0
    assert "watchRelists" in res and "lastRelist" in res
    out = io.StringIO()
    rc = cmd_status(runner.client,
                    SimpleNamespace(namespace="default", output=None), out)
    assert rc == 0
    text = out.getvalue()
    assert "Degraded:      oracle" in text
    assert "Watchdog:" in text and "Last relist:" in text
    runner.scheduler.close()


def test_chaos_churn_smoke_all_pods_bind_under_faults():
    """Mini ChaosChurn: API storms on bind/create, a watch truncation, a
    breaker-tripping device burst, and a resolver stall — 100% of pods
    must still bind, and the recovery ledger must close every span."""
    store = ObjectStore()
    truth = DirectClient(store)
    for n in _nodes(4, cpu="8", pods="32"):
        truth.nodes().create(n.to_dict())
    schedule = FaultSchedule([
        Fault("api.bind", "error", 0, 2, 503),
        Fault("watch.pods", "drop", 0, 1, 4),     # first watch truncated
        Fault("device.drain", "runtime", 0, 2),   # deep pop = drain path
        Fault("device.gang", "runtime", 0, 1),    # per-batch fallback too
        Fault("thread.resolver", "stall", 0, 1, 0.2),
    ], seed=42)
    runner = SchedulerRunner(
        ChaosClient(DirectClient(store), schedule),
        SchedulerConfiguration(batch_size=8, backoff_initial_s=0.02,
                               backoff_max_s=0.1, breaker_threshold=2,
                               breaker_cooldown_s=1.0, bind_retries=2,
                               bind_retry_backoff_s=0.01,
                               watchdog_interval_s=0.2))
    chaos = DeviceChaos(schedule).install()
    hooks.install(ThreadChaos(schedule))
    try:
        runner.start()
        for i in range(24):
            truth.pods().create(make_pod(f"cp{i}")
                                .req({"cpu": "200m"}).obj().to_dict())
        assert wait_for(
            lambda: sum(1 for p in truth.pods().list()
                        if p["spec"].get("nodeName")) == 24,
            timeout=45), [
                (p["metadata"]["name"], p["spec"].get("nodeName"))
                for p in truth.pods().list() if not p["spec"].get("nodeName")]
        rep = schedule.report()
        assert rep["total_fires"] >= 5, rep
        # every API outage must have closed its recovery span (the loop
        # keeps writing through them); device/watch sites may legitimately
        # see no further traffic once degraded paths or relists took over
        assert not any(s.startswith("api.")
                       for s in rep["unrecovered_sites"]), rep
        assert rep["classes"]["api.bind:error"]["recovered"] >= 1, rep
    finally:
        hooks.uninstall()
        chaos.uninstall()
        runner.stop()
