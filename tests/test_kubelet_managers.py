"""Kubelet node managers: probes (liveness/readiness/startup), QoS and
allocatable admission, CPU pinning, and the volume-manager reconcile.

Reference: pkg/kubelet/prober/, pkg/kubelet/cm/cpumanager/,
pkg/kubelet/lifecycle/predicate.go, pkg/kubelet/volumemanager/.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.kubelet import (
    AllocatableAdmitter,
    CPUManager,
    Kubelet,
    VolumeManager,
    pod_qos,
)
from kubernetes_tpu.kubelet.runtime import EXITED, RUNNING, FakeRuntime
from kubernetes_tpu.store.store import ObjectStore


def wait_until(fn, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


def mkpod(name, uid=None, containers=None, **spec_extra):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "uid": uid or f"uid-{name}"},
            "spec": {"containers": containers or [{"name": "c"}],
                     "nodeName": "n0", **spec_extra},
            "status": {}}


@pytest.fixture
def cluster():
    client = DirectClient(ObjectStore())
    kubelet = Kubelet(client, "n0", heartbeat_period=0.5,
                      allocatable={"cpu": "4", "memory": "8Gi", "pods": "10"})
    kubelet.prober.tick_s = 0.05
    kubelet.start()
    yield client, kubelet
    kubelet.stop()


# ------------------------------------------------------------------ probes

def test_readiness_probe_gates_ready_condition(cluster):
    client, kubelet = cluster
    client.pods().create(mkpod("web", containers=[
        {"name": "c", "readinessProbe": {"periodSeconds": 0.1,
                                         "failureThreshold": 2}}]))

    def ready():
        st = client.pods().get("web").get("status") or {}
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in st.get("conditions") or [])
    assert wait_until(ready)
    # probe starts failing -> Ready goes False without a restart
    uid = client.pods().get("web")["metadata"]["uid"]
    kubelet.runtime.set_health(uid, "c", False)
    assert wait_until(lambda: not ready())
    sb = kubelet.runtime.get_sandbox(uid)
    assert sb.containers["c"].state == RUNNING  # readiness never kills
    assert sb.containers["c"].restart_count == 0
    # recovers
    kubelet.runtime.set_health(uid, "c", True)
    assert wait_until(ready)


def test_liveness_probe_restarts_container(cluster):
    client, kubelet = cluster
    client.pods().create(mkpod("app", containers=[
        {"name": "c", "livenessProbe": {"periodSeconds": 0.1,
                                        "failureThreshold": 2}}]))
    uid = client.pods().get("app")["metadata"]["uid"]
    assert wait_until(lambda: kubelet.runtime.get_sandbox(uid)
                      and kubelet.runtime.get_sandbox(uid).containers
                      .get("c", None) is not None
                      and kubelet.runtime.get_sandbox(uid).containers["c"].state
                      == RUNNING)
    kubelet.runtime.set_health(uid, "c", False)
    # killed and restarted (restart_count grows); health stays bad so it
    # keeps getting restarted
    assert wait_until(lambda: kubelet.runtime.get_sandbox(uid)
                      .containers["c"].restart_count >= 1)
    kubelet.runtime.set_health(uid, "c", True)


def test_startup_probe_gates_and_kills(cluster):
    client, kubelet = cluster
    client.pods().create(mkpod("slow", containers=[
        {"name": "c",
         "startupProbe": {"periodSeconds": 0.1, "failureThreshold": 3},
         "readinessProbe": {"periodSeconds": 0.1}}]))
    uid = client.pods().get("slow")["metadata"]["uid"]
    assert wait_until(lambda: kubelet.runtime.get_sandbox(uid) is not None)

    def ready():
        st = client.pods().get("slow").get("status") or {}
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in st.get("conditions") or [])
    # healthy container: startup succeeds, readiness follows
    assert wait_until(ready)


# ------------------------------------------------------------- QoS classes

def test_pod_qos_classes():
    guaranteed = mkpod("g", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "1", "memory": "1Gi"},
        "limits": {"cpu": "1", "memory": "1Gi"}}}])
    burstable = mkpod("b", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "1"}}}])
    besteffort = mkpod("e")
    assert pod_qos(guaranteed) == "Guaranteed"
    assert pod_qos(burstable) == "Burstable"
    assert pod_qos(besteffort) == "BestEffort"


# ------------------------------------------------------ allocatable admission

def test_admitter_rejects_overcommit():
    adm = AllocatableAdmitter({"cpu": "2", "memory": "2Gi", "pods": "10"})
    big = mkpod("big", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "1500m"}}}])
    ok, _ = adm.admit(big)
    assert ok
    second = mkpod("second", uid="uid-2", containers=[{"name": "c",
        "resources": {"requests": {"cpu": "1"}}}])
    ok, reason = adm.admit(second)
    assert not ok and reason == "OutOfCpu"
    adm.release("uid-big")
    ok, _ = adm.admit(second)
    assert ok


def test_kubelet_fails_overcommitted_pod(cluster):
    client, kubelet = cluster
    client.pods().create(mkpod("huge", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "64"}}}]))
    assert wait_until(lambda: (client.pods().get("huge").get("status") or {})
                      .get("phase") == "Failed")
    st = client.pods().get("huge")["status"]
    assert st.get("reason", "").startswith("OutOf")


# ------------------------------------------------------------- cpu manager

def test_cpu_manager_exclusive_pinning():
    cm = CPUManager(4)
    g1 = mkpod("g1", uid="u1", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "2", "memory": "1Gi"},
        "limits": {"cpu": "2", "memory": "1Gi"}}}])
    g2 = mkpod("g2", uid="u2", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "2", "memory": "1Gi"},
        "limits": {"cpu": "2", "memory": "1Gi"}}}])
    s1 = cm.allocate(g1)
    s2 = cm.allocate(g2)
    assert len(s1) == 2 and len(s2) == 2 and not (s1 & s2)
    g3 = mkpod("g3", uid="u3", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "1", "memory": "1Gi"},
        "limits": {"cpu": "1", "memory": "1Gi"}}}])
    with pytest.raises(RuntimeError):
        cm.allocate(g3)
    cm.release("u1")
    assert len(cm.allocate(g3)) == 1
    # burstable / fractional pods share the pool
    frac = mkpod("f", uid="u4", containers=[{"name": "c", "resources": {
        "requests": {"cpu": "1500m", "memory": "1Gi"},
        "limits": {"cpu": "1500m", "memory": "1Gi"}}}])
    assert cm.allocate(frac) is None


# ----------------------------------------------------------- volume manager

def test_volume_manager_reconcile_and_gate():
    vm = VolumeManager(reconcile_s=0.02)
    pod = mkpod("v", volumes=[
        {"name": "data", "persistentVolumeClaim": {"claimName": "claim-a"}},
        {"name": "scratch", "emptyDir": {}}])
    vm.add_pod(pod)
    assert not vm.wait_for_attach_and_mount(pod, timeout=0.01)  # not reconciled
    vm.reconcile_once()
    assert vm.wait_for_attach_and_mount(pod, timeout=0.5)
    assert "pvc:claim-a" in vm.mounted_volumes()
    vm.remove_pod(pod)
    vm.reconcile_once()
    assert not vm.mounted_volumes()
    ops = [op for op, _ in vm.mount_ops]
    assert ops.count("mount") == 2 and ops.count("unmount") == 2


def test_kubelet_mounts_volumes_before_start(cluster):
    client, kubelet = cluster
    client.pods().create(mkpod("dbpod", volumes=[
        {"name": "data", "persistentVolumeClaim": {"claimName": "pvc-1"}}]))
    uid = client.pods().get("dbpod")["metadata"]["uid"]
    assert wait_until(lambda: kubelet.runtime.get_sandbox(uid) is not None)
    assert "pvc:pvc-1" in kubelet.volumes.mounted_volumes()
    client.pods().delete("dbpod")
    assert wait_until(lambda: "pvc:pvc-1" not in
                      kubelet.volumes.mounted_volumes())
