"""Fleet scheduling: K tenant clusters through one warm resident program.

The acceptance spine (sched/fleet.py + the tenant plane in the encoder/
model stack):

- mask soundness / BIT-PARITY: fleet-batched placements on randomized
  K-tenant clusters equal K independently-scheduled single-tenant runs
  (same seeds) — scoring, tie-breaks, spread minima and preemption waves
  included, with topology label values deliberately SHARED across tenants.
- isolation: a pod can never see (or preempt on) a sibling tenant's node;
  the ``cross_tenant`` audit invariant and the scheduler's victim guard
  are the hard walls behind the mask.
- fairness: FleetQueue fills the drain in single-tenant blocks, weighted
  round-robin, short block closes the pop.
- per-tenant status publishing does not collide (the parameterized
  ConfigMap-name regression).
"""

from __future__ import annotations

import json
import random
import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.encode.snapshot import (
    TENANT_KEY_ID,
    TENANT_LABEL,
    SnapshotEncoder,
)
from kubernetes_tpu.sched.fleet import (
    FleetQueue,
    FleetRunner,
    rekey_for_tenant,
    split_fleet_name,
    unrekey_for_tenant,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.fleet

ZONES = ("z0", "z1", "z2")  # SHARED across tenants on purpose


# ---------------------------------------------------------------------------
# rekey boundary
# ---------------------------------------------------------------------------

def test_rekey_pod_roundtrip_and_references():
    pod = (make_pod("p1", "teamA").req({"cpu": "1"})
           .pod_anti_affinity("zone", {"app": "x"})
           .obj().to_dict())
    pod["spec"]["nodeName"] = "node-3"
    pod["status"] = {"nominatedNodeName": "node-9"}
    pod["spec"]["affinity"]["podAntiAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"][0][
        "namespaces"] = ["teamB"]
    rk = rekey_for_tenant(3, "pods", pod)
    assert rk["metadata"]["namespace"] == "t3.teamA"
    assert rk["spec"]["nodeName"] == "t3.node-3"
    assert rk["status"]["nominatedNodeName"] == "t3.node-9"
    assert rk["metadata"]["labels"][TENANT_LABEL] == "3"
    assert rk["spec"]["affinity"]["podAntiAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"][0][
        "namespaces"] == ["t3.teamB"]
    # the ingested original is never mutated (informer stores share it)
    assert pod["spec"]["nodeName"] == "node-3"
    assert pod["metadata"].get("labels", {}).get(TENANT_LABEL) is None
    uk = unrekey_for_tenant(3, "pods", rk)
    assert uk["metadata"]["namespace"] == "teamA"
    assert uk["spec"]["nodeName"] == "node-3"
    assert uk["status"]["nominatedNodeName"] == "node-9"
    assert TENANT_LABEL not in (uk["metadata"]["labels"] or {})


def test_unrekey_volume_and_claim_writebacks():
    """The binder's write-backs must never leak fleet-internal names into
    a tenant apiserver: PVC selected-node annotation + volumeName, PV
    claimRef, and DRA claim allocation nodeName all strip."""
    pvc = {"metadata": {"name": "c1", "namespace": "t2.default",
                        "annotations": {
                            "volume.kubernetes.io/selected-node": "t2.n0"}},
           "spec": {"volumeName": "t2.pv1", "storageClassName": "t2.fast"}}
    uk = unrekey_for_tenant(2, "persistentvolumeclaims", pvc)
    assert uk["metadata"]["namespace"] == "default"
    assert uk["metadata"]["annotations"][
        "volume.kubernetes.io/selected-node"] == "n0"
    assert uk["spec"]["volumeName"] == "pv1"
    assert uk["spec"]["storageClassName"] == "fast"
    pv = {"metadata": {"name": "t2.pv1"},
          "spec": {"storageClassName": "t2.fast",
                   "claimRef": {"namespace": "t2.default", "name": "c1"}}}
    uk = unrekey_for_tenant(2, "persistentvolumes", pv)
    assert uk["metadata"]["name"] == "pv1"
    assert uk["spec"]["claimRef"]["namespace"] == "default"
    claim = {"metadata": {"name": "rc", "namespace": "t2.default"},
             "status": {"allocation": {"nodeName": "t2.n0"}}}
    uk = unrekey_for_tenant(2, "resourceclaims", claim)
    assert uk["status"]["allocation"]["nodeName"] == "n0"
    ev = {"metadata": {"name": "e", "namespace": "t2.default"},
          "involvedObject": {"kind": "Pod", "name": "p",
                             "namespace": "t2.default"}}
    uk = unrekey_for_tenant(2, "events", ev)
    assert uk["involvedObject"]["namespace"] == "default"


def test_rekey_node_and_split():
    node = make_node("n0").obj().to_dict()
    rk = rekey_for_tenant(12, "nodes", node)
    assert rk["metadata"]["name"] == "t12.n0"
    assert split_fleet_name("t12.n0") == (12, "n0")
    assert split_fleet_name("n0") == (None, "n0")


# ---------------------------------------------------------------------------
# tenant plane in the encoder + model stack
# ---------------------------------------------------------------------------

def _tenant_nodes(t, n, cpu="4"):
    return [Node.from_dict(rekey_for_tenant(t, "nodes", (
        make_node(f"n{i}")
        .capacity({"cpu": cpu, "memory": "8Gi", "pods": "32"})
        .label("kubernetes.io/hostname", f"n{i}")
        .label("topology.kubernetes.io/zone", ZONES[i % len(ZONES)])
        .obj().to_dict()))) for i in range(n)]


def _tenant_pod(t, wrapper):
    return Pod.from_dict(rekey_for_tenant(t, "pods",
                                          wrapper.obj().to_dict()))


def test_tenant_plane_rides_the_label_columns():
    nodes = _tenant_nodes(0, 2) + _tenant_nodes(1, 2)
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [])
    tv = np.asarray(ct.node_labels)[:, TENANT_KEY_ID]
    vals = [meta.values.lookup(int(v)) for v in tv[:4]]
    assert vals == ["0", "0", "1", "1"]
    pods = [_tenant_pod(1, make_pod("p0").req({"cpu": "1"}))]
    pb = enc.encode_pods(pods, meta)
    pv = np.asarray(pb.pod_labels)[:, TENANT_KEY_ID]
    assert meta.values.lookup(int(pv[0])) == "1"


def test_tenant_mask_gates_filters_and_oracle():
    from kubernetes_tpu.ops.filters import run_filters
    nodes = _tenant_nodes(0, 2) + _tenant_nodes(1, 2)
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [])
    pods = [_tenant_pod(0, make_pod("a").req({"cpu": "1"})),
            _tenant_pod(1, make_pod("b").req({"cpu": "1"}))]
    pb = enc.encode_pods(pods, meta)
    mask = np.asarray(run_filters(ct, pb))
    assert mask[0, :2].all() and not mask[0, 2:4].any()
    assert mask[1, 2:4].all() and not mask[1, :2].any()
    # oracle mirrors (first-fail vocabulary included)
    from kubernetes_tpu.sched.oracle import FailReason, OracleScheduler
    orc = OracleScheduler(nodes, [])
    m, reasons = orc.feasible(pods[0])
    assert m[:2] == [True, True] and m[2:] == [False, False]
    assert reasons[nodes[2].metadata.name] == FailReason.TENANT


def test_tenant_local_rank_degenerates_to_arange():
    from kubernetes_tpu.ops.filters import tenant_local_rank
    nodes = [make_node(f"n{i}").capacity({"cpu": "1"}).obj()
             for i in range(5)]
    enc = SnapshotEncoder()
    ct, _meta = enc.encode_cluster(nodes, [])
    rank = np.asarray(tenant_local_rank(ct))
    np.testing.assert_array_equal(rank, np.arange(ct.node_valid.shape[0]))


def test_tenant_local_rank_interleaved():
    from kubernetes_tpu.ops.filters import tenant_local_rank
    # interleave two tenants' nodes: ranks must count per tenant
    n0 = _tenant_nodes(0, 3)
    n1 = _tenant_nodes(1, 3)
    nodes = [n0[0], n1[0], n0[1], n1[1], n0[2], n1[2]]
    enc = SnapshotEncoder()
    ct, _meta = enc.encode_cluster(nodes, [])
    rank = np.asarray(tenant_local_rank(ct))[:6]
    np.testing.assert_array_equal(rank, [0, 0, 1, 1, 2, 2])


# ---------------------------------------------------------------------------
# THE parity gate: fleet-batched == K independent single-tenant runs
# ---------------------------------------------------------------------------

def _random_workload(rng, t, n_nodes, n_pods):
    """One tenant's randomized cluster: shared zone values, mixed
    capacities, pods with random requests, priorities, spread and
    anti-affinity terms."""
    nodes = [make_node(f"n{i}")
             .capacity({"cpu": rng.choice(["2", "4", "8"]),
                        "memory": "16Gi", "pods": "64"})
             .label("kubernetes.io/hostname", f"n{i}")
             .label("topology.kubernetes.io/zone", rng.choice(ZONES))
             .obj().to_dict() for i in range(n_nodes)]
    pods = []
    for i in range(n_pods):
        w = (make_pod(f"p{i}")
             .req({"cpu": rng.choice(["250m", "500m", "1"])})
             .label("app", rng.choice(["a", "b"]))
             .priority(rng.choice([0, 0, 10])))
        r = rng.random()
        if r < 0.3:
            w = w.spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                         {"app": "a"})
        elif r < 0.5:
            w = w.pod_anti_affinity("kubernetes.io/hostname",
                                    {"app": "b"})
        pods.append(w.obj().to_dict())
    return nodes, pods


def _drain_assignments(nodes, pod_chunks, batch, seed=7):
    """Schedule ``pod_chunks`` (ragged, possibly tenant-homogeneous — the
    live path's Scheduler._tenant_chunks shape) over ``nodes`` with the
    drain program, every chunk's bucket pinned to ``batch`` (min_p, the
    scheduler's own discipline). Returns {pod key: node name or None}."""
    from kubernetes_tpu.models.gang import gang_drain
    enc = SnapshotEncoder()
    typed_nodes = [Node.from_dict(n) for n in nodes]
    batches = [[Pod.from_dict(p) for p in c] for c in pod_chunks]
    all_pods = [p for c in batches for p in c]
    ct, meta = enc.encode_cluster(typed_nodes, [], pending_pods=all_pods)
    pbs = [enc.encode_pods(b, meta, min_p=batch) for b in batches]
    a, _rounds, _req = gang_drain(ct, pbs, seed=seed,
                                  topo_keys=meta.topo_keys)
    out = {}
    for b, chunk in enumerate(batches):
        for i, p in enumerate(chunk):
            ni = int(a[b][i])
            out[p.key] = meta.node_names[ni] if ni >= 0 else None
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fleet_parity_randomized(seed):
    """Randomized K-tenant clusters: the fleet-batched drain (tenants
    concatenated on the node axis, per-tenant batches, shared zone label
    values) places every tenant's pods EXACTLY where its standalone run
    does — scores, spread minima, tie-breaks and all."""
    rng = random.Random(seed)
    K, batch = 3, 8
    singles = {}
    fleet_nodes = []
    fleet_chunks = []
    per_tenant = {}
    for t in range(K):
        nodes, pods = _random_workload(rng, t, n_nodes=rng.randint(3, 6),
                                       n_pods=rng.randint(6, 12))
        per_tenant[t] = (nodes, pods)
        singles[t] = _drain_assignments(
            nodes, [pods[i:i + batch] for i in range(0, len(pods), batch)],
            batch)
    # fleet leg: INTERLEAVE tenants' nodes (worst case for index-based
    # tie-breaks), per-tenant ragged chunks in tenant order — exactly the
    # shape FleetQueue block fill + Scheduler._tenant_chunks produce
    maxn = max(len(n) for n, _p in per_tenant.values())
    for i in range(maxn):
        for t in range(K):
            nodes, _pods = per_tenant[t]
            if i < len(nodes):
                fleet_nodes.append(rekey_for_tenant(t, "nodes", nodes[i]))
    for t in range(K):
        _nodes, pods = per_tenant[t]
        rk = [rekey_for_tenant(t, "pods", p) for p in pods]
        fleet_chunks += [rk[i:i + batch] for i in range(0, len(rk), batch)]
    fleet = _drain_assignments(fleet_nodes, fleet_chunks, batch)
    mismatches = []
    for t in range(K):
        _nodes, pods = per_tenant[t]
        for p in pods:
            key = f"default/{p['metadata']['name']}"
            fkey = f"t{t}.default/{p['metadata']['name']}"
            want = singles[t][key]
            got = fleet.get(fkey)
            if got is not None:
                _tid, got = split_fleet_name(got)
                assert _tid == t, f"cross-tenant placement: {fkey} -> {got}"
            if want != got:
                mismatches.append((fkey, want, got))
    assert not mismatches, mismatches


def test_fleet_preempt_wave_parity():
    """Preemption-wave parity: per-tenant victims + chosen nodes in the
    fleet view equal each tenant's standalone wave (and never cross)."""
    from kubernetes_tpu.sched.preemption import preempt_wave

    def leg(t_ids):
        nodes, bound, views = [], [], []
        for t in t_ids:
            for i in range(2):
                nd = make_node(f"n{i}").capacity(
                    {"cpu": "2", "memory": "4Gi", "pods": "8"}) \
                    .label("kubernetes.io/hostname", f"n{i}").obj().to_dict()
                nodes.append(Node.from_dict(
                    rekey_for_tenant(t, "nodes", nd)) if t is not None
                    else Node.from_dict(nd))
            for i in range(2):
                pd = make_pod(f"victim{i}").req({"cpu": "2"}) \
                    .priority(0).obj().to_dict()
                pd["spec"]["nodeName"] = f"n{i}"
                bound.append(Pod.from_dict(
                    rekey_for_tenant(t, "pods", pd)) if t is not None
                    else Pod.from_dict(pd))
            hp = make_pod("vip").req({"cpu": "2"}).priority(100) \
                .obj().to_dict()
            views.append(Pod.from_dict(
                rekey_for_tenant(t, "pods", hp)) if t is not None
                else Pod.from_dict(hp))
        return preempt_wave(nodes, bound, views)

    fleet = leg([0, 1])
    single = leg([None])
    assert all(r is not None for r in fleet)
    for t, res in enumerate(fleet):
        tid, raw = split_fleet_name(res.node_name)
        assert tid == t
        assert raw == single[0].node_name
        assert [split_fleet_name(v.key.split("/")[0])[0]
                for v in res.victims] == [t] * len(res.victims)
        assert sorted(v.metadata.name for v in res.victims) == \
            sorted(v.metadata.name for v in single[0].victims)


def test_fleet_gang_atomicity_per_tenant():
    """Per-tenant gangs (anti-affine members needing distinct hosts) ride
    the fleet drain atomically: a gang that fits its OWN tenant binds
    whole; a gang that does NOT fit its tenant binds nobody — even though
    a sibling tenant has idle nodes that could host the overflow."""
    from kubernetes_tpu.audit.invariants import GANG_LABEL

    def gang(t, size):
        out = []
        for i in range(size):
            w = (make_pod(f"g{i}").req({"cpu": "1"})
                 .label(GANG_LABEL, f"gang-{t}")
                 .label("grp", f"g{t}")
                 .pod_anti_affinity("kubernetes.io/hostname",
                                    {"grp": f"g{t}"}))
            out.append(rekey_for_tenant(t, "pods", w.obj().to_dict()))
        return out

    # tenant 0: 3 nodes, gang of 3 (fits). tenant 1: 3 nodes, gang of 5
    # (cannot fit anti-affine members on 3 hosts).
    nodes = [rekey_for_tenant(t, "nodes", n.to_dict())
             for t in (0, 1)
             for n in (make_node(f"n{i}")
                       .capacity({"cpu": "4", "memory": "8Gi", "pods": "8"})
                       .label("kubernetes.io/hostname", f"n{i}").obj()
                       for i in range(3))]
    chunks = [gang(0, 3), gang(1, 5)]
    got = _drain_assignments(nodes, chunks, batch=8)
    t0_placed = [v for k, v in got.items() if k.startswith("t0.")]
    t1_placed = [v for k, v in got.items() if k.startswith("t1.")]
    assert all(v is not None for v in t0_placed)
    assert len({v for v in t0_placed}) == 3          # distinct hosts
    assert all(v is None or v.startswith("t1.") for v in t1_placed)
    # at most 3 of tenant 1's 5 anti-affine members can hold a host;
    # none may spill onto tenant 0's idle nodes
    assert sum(v is not None for v in t1_placed) <= 3


def test_cross_tenant_victim_guard():
    """Scheduler._evict_victims refuses a preemption result carrying a
    foreign tenant's victim (belt-and-braces behind the mask)."""
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.cache import SchedulerCache
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler
    sch = Scheduler(SchedulerConfiguration(), SchedulerCache(),
                    SchedulingQueue(), lambda p, n: True)
    evicted = []
    sch._evict = lambda v: evicted.append(v.key)
    preemptor = _tenant_pod(0, make_pod("vip").priority(100))
    own = _tenant_pod(0, make_pod("mine"))
    foreign = _tenant_pod(1, make_pod("theirs"))
    assert sch._evict_victims(preemptor, [own]) is True
    assert evicted == [own.key]
    evicted.clear()
    assert sch._evict_victims(preemptor, [own, foreign]) is False
    assert evicted == []  # nothing evicted when ANY victim is foreign


# ---------------------------------------------------------------------------
# audit invariant
# ---------------------------------------------------------------------------

def test_cross_tenant_invariant():
    from kubernetes_tpu.audit.invariants import (AuditSnapshot,
                                                 check_cross_tenant)
    node0 = rekey_for_tenant(0, "nodes", make_node("n0").obj().to_dict())
    node1 = rekey_for_tenant(1, "nodes", make_node("n0").obj().to_dict())
    ok_pod = rekey_for_tenant(0, "pods",
                              make_pod("good").obj().to_dict())
    ok_pod["spec"]["nodeName"] = "t0.n0"
    bad_pod = rekey_for_tenant(0, "pods", make_pod("bad").obj().to_dict())
    bad_pod["spec"]["nodeName"] = "t1.n0"
    nom_pod = rekey_for_tenant(1, "pods", make_pod("nom").obj().to_dict())
    nom_pod["status"] = {"nominatedNodeName": "t0.n0"}
    snap = AuditSnapshot(ts=time.time(), rv=None,
                         api_pods=[ok_pod, bad_pod, nom_pod],
                         api_nodes=[node0, node1])
    v = check_cross_tenant(snap)
    assert {x.fingerprint[1:3] for x in v} == {
        ("t0.default/bad", "nodeName"),
        ("t1.default/nom", "nominatedNodeName")}
    assert all(x.confirm == 1 for x in v)
    # untenanted cluster: check is a no-op
    plain_pod = make_pod("p").obj().to_dict()
    plain_pod["spec"]["nodeName"] = "x"
    snap2 = AuditSnapshot(ts=time.time(), rv=None, api_pods=[plain_pod],
                          api_nodes=[make_node("x").obj().to_dict()])
    assert check_cross_tenant(snap2) == []


# ---------------------------------------------------------------------------
# fairness plane
# ---------------------------------------------------------------------------

def _queued(t, name, prio=0):
    p = make_pod(name, f"t{t}.default").priority(prio).obj()
    p.metadata.labels[TENANT_LABEL] = str(t)
    return p


def test_fleet_queue_round_robin_blocks():
    q = FleetQueue(block=4)
    for t in range(3):
        for i in range(10):
            q.add(_queued(t, f"p{i}"))
    batch = [p.metadata.labels[TENANT_LABEL] for p, _ in
             q.pop_batch(12, wait=0.1)]
    assert len(batch) == 12
    for i in range(0, 12, 4):
        assert len(set(batch[i:i + 4])) == 1  # single-tenant blocks
    assert set(batch) == {"0", "1", "2"}      # nobody starved


def test_fleet_queue_weighted_and_rotating():
    q = FleetQueue(block=2, weights={"0": 2})
    for t in range(2):
        for i in range(8):
            q.add(_queued(t, f"p{i}"))
    batch = [p.metadata.labels[TENANT_LABEL] for p, _ in
             q.pop_batch(6, wait=0.1)]
    # tenant 0 carries weight 2: two blocks per rotation vs one
    assert batch.count("0") == 4 and batch.count("1") == 2
    # rotation cursor moved: the next pop starts from the other tenant
    batch2 = [p.metadata.labels[TENANT_LABEL] for p, _ in
              q.pop_batch(2, wait=0.1)]
    assert batch2 == ["1", "1"]


def test_fleet_queue_short_block_closes_pop():
    q = FleetQueue(block=4)
    q.add(_queued(0, "only"))       # tenant 0: 1 pod (short block)
    for i in range(8):
        q.add(_queued(1, f"p{i}"))
    batch = [(p.metadata.labels[TENANT_LABEL], p.metadata.name)
             for p, _ in q.pop_batch(8, wait=0.1)]
    # whoever comes first, a short block must be the LAST block popped
    tenants = [t for t, _ in batch]
    if tenants[0] == "0":
        assert batch == [("0", "only")]  # short block closed the pop
    else:
        assert tenants[:4] == ["1"] * 4 and batch[4][0] == "0"
    # leftovers stay queued (priority order intact)
    rest = q.pop_batch(16, wait=0.1)
    assert len(batch) + len(rest) == 9


def test_fleet_queue_single_tenant_degenerates():
    q = FleetQueue(block=4)
    for i in range(6):
        q.add(make_pod(f"p{i}").priority(i).obj())
    got = [p.metadata.name for p, _ in q.pop_batch(6, wait=0.1)]
    assert got == [f"p{i}" for i in range(5, -1, -1)]  # priority desc


def test_scheduler_tenant_chunks():
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.cache import SchedulerCache
    from kubernetes_tpu.sched.queue import SchedulingQueue
    from kubernetes_tpu.sched.scheduler import Scheduler
    sch = Scheduler(SchedulerConfiguration(batch_size=4,
                                           max_drain_batches=4),
                    SchedulerCache(), SchedulingQueue(), lambda p, n: True)
    items = [(_queued(t, f"p{i}"), 0) for t in (0, 1) for i in range(6)]
    # fleet off: plain slicing (mixed chunks)
    plain = sch._tenant_chunks(items, 4)
    assert [len(c) for c in plain] == [4, 4, 4]
    sch.fleet_mode = True
    chunks = sch._tenant_chunks(items, 4)
    for c in chunks:
        tenants = {(p.metadata.labels or {}).get(TENANT_LABEL)
                   for p, _ in c}
        assert len(tenants) == 1  # tenant-homogeneous
    assert sorted(len(c) for c in chunks) == [2, 2, 4, 4]


# ---------------------------------------------------------------------------
# per-tenant catalog epochs
# ---------------------------------------------------------------------------

def test_tenant_scoped_catalog_epochs():
    enc = SnapshotEncoder()
    p0 = _tenant_pod(0, make_pod("a").req({"cpu": "1"}))
    p1 = _tenant_pod(1, make_pod("b").req({"cpu": "1"}))
    enc.precompile_pod(p0)
    enc.precompile_pod(p1)
    nodes = _tenant_nodes(0, 1) + _tenant_nodes(1, 1)
    _ct, meta = enc.encode_cluster(nodes, [], pending_pods=[p0, p1])
    enc.pod_cache_hits = enc.pod_cache_misses = 0
    # tenant 1's namespace churns: ONLY tenant 1's record invalidates
    enc.set_namespaces({"t1.default": {TENANT_LABEL: "1", "x": "y"}},
                       changed_tenants={"1"})
    enc.encode_pods([p0, p1], meta)
    assert enc.pod_cache_hits == 1 and enc.pod_cache_misses == 1
    # a GLOBAL catalog change (volumes) still invalidates everyone
    enc.pod_cache_hits = enc.pod_cache_misses = 0
    enc.set_volumes(None)
    enc.encode_pods([p0, p1], meta)
    assert enc.pod_cache_hits == 0 and enc.pod_cache_misses == 2


# ---------------------------------------------------------------------------
# status publishing: parameterized ConfigMap names (regression)
# ---------------------------------------------------------------------------

def test_two_tenant_status_publishers_do_not_collide():
    """Two scheduler identities on ONE apiserver, publishing concurrently
    with per-instance ConfigMap names: both survive with their own
    identity (the old module-constant name made the last writer win)."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    runners = [
        SchedulerRunner(client, identity=f"sched-{i}",
                        status_name=f"scheduler-status-{i}",
                        explain_name=f"scheduler-explanations-{i}",
                        trace_name=f"scheduler-trace-{i}")
        for i in range(2)]
    try:
        threads = [threading.Thread(target=r.publish_status)
                   for r in runners for _ in range(3)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(10.0)
        for i in range(2):
            cm = client.resource("configmaps", "default").get(
                f"scheduler-status-{i}")
            st = json.loads(cm["data"]["status"])
            assert st["identity"] == f"sched-{i}"
    finally:
        for r in runners:
            r.stop()


# ---------------------------------------------------------------------------
# connected e2e: 2 tenant apiservers, one FleetRunner
# ---------------------------------------------------------------------------

def test_fleet_runner_e2e_two_tenants():
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.store.apiserver import APIServer

    servers = [APIServer().start() for _ in range(2)]
    clients = [HTTPClient(s.url) for s in servers]
    runner = None
    try:
        for c in clients:
            for i in range(3):
                c.nodes().create(make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": "32"})
                    .obj().to_dict())
        runner = FleetRunner(clients,
                             SchedulerConfiguration(batch_size=8))
        runner.start(wait_sync=20.0)
        for c in clients:
            for i in range(6):
                c.pods("default").create(
                    make_pod(f"p{i}", "default").req({"cpu": "100m"})
                    .obj().to_dict())
        deadline = time.time() + 90
        bound = [0, 0]
        while time.time() < deadline:
            bound = [sum(1 for p in c.pods("default").list()
                         if p["spec"].get("nodeName")) for c in clients]
            if all(b == 6 for b in bound):
                break
            time.sleep(0.3)
        assert bound == [6, 6], bound
        # node names on each tenant apiserver are RAW (prefix stripped)
        for t, c in enumerate(clients):
            for p in c.pods("default").list():
                assert not p["spec"]["nodeName"].startswith("t"), p
                assert p["spec"]["nodeName"] in {"n0", "n1", "n2"}
        # the continuous auditor (cross_tenant live) confirms nothing
        runner.auditor.run_once()
        runner.auditor.run_once()
        assert runner.auditor.total_violations == 0
        # per-tenant fairness CM lands on EVERY tenant's apiserver (the
        # background auditor republishes on its cadence; publish NOW for
        # a deterministic read)
        runner.publish_status()
        for c in clients:
            cm = c.resource("configmaps", "default").get(
                "kubernetes-tpu-fleet-sched-status")
            fs = json.loads(cm["data"]["fleetSched"])
            assert fs["tenants"] == 2
            assert all(d["bound"] == 6 for d in fs["tenant"].values())
        # ktpu status renders the Fleet sched line from any tenant
        import io
        from kubernetes_tpu.cli.ktpu import main as ktpu_main
        out = io.StringIO()
        rc = ktpu_main(["--server", servers[0].url, "status"], out=out)
        assert rc == 0
        assert "Fleet sched:   2 tenants, one warm program" in out.getvalue()
        rc = ktpu_main(["--server", servers[1].url, "status", "-o", "json"],
                       out=(out2 := io.StringIO()))
        assert rc == 0
        assert json.loads(out2.getvalue())["fleetSched"]["tenants"] == 2
    finally:
        if runner is not None:
            runner.kill()
        for s in servers:
            s.stop()
