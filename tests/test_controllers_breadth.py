"""Breadth controllers: CronJob, TTL-after-finished, HPA, Namespace purge,
EndpointSlice.

Reference: pkg/controller/{cronjob,ttlafterfinished,podautoscaler,namespace,
endpointslice}/.
"""

import calendar
import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import (
    CronJobController,
    EndpointSliceController,
    HorizontalPodAutoscalerController,
    NamespaceController,
    TTLAfterFinishedController,
)
from kubernetes_tpu.controllers.cronjob import cron_matches, most_recent_schedule
from kubernetes_tpu.controllers.hpa import USAGE_ANNOTATION
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_pod


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def run_controller(client, ctrl):
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def stop(ctrl, factory):
    ctrl.stop()
    factory.stop_all()


# ------------------------------------------------------------------ cronjob

def _utc(y, mo, d, h, mi):
    return float(calendar.timegm((y, mo, d, h, mi, 0, 0, 0, 0)))


def test_cron_expression_parsing():
    ts = _utc(2026, 7, 30, 12, 30)  # Thursday 12:30 UTC
    assert cron_matches("30 12 * * *", ts)
    assert cron_matches("*/15 * * * *", ts)
    assert cron_matches("* * * * 4", ts)          # Thursday
    assert not cron_matches("* * * * 0", ts)      # not Sunday
    assert cron_matches("30 12 30 7 *", ts)
    assert not cron_matches("31 12 * * *", ts)
    assert cron_matches("0-45/5 12 * * *", ts)
    # dow 7 == Sunday, including inside ranges and steps
    sun = _utc(2026, 8, 2, 12, 30)  # Sunday
    assert cron_matches("30 12 * * 7", sun)
    assert cron_matches("30 12 * * 5-7", sun)       # Fri-Sun range
    assert not cron_matches("30 12 * * 5-7", ts)    # Thursday outside it
    assert cron_matches("30 12 * * */7", sun)       # {0,7} -> Sunday
    # vixie OR-semantics when both dom and dow are restricted
    assert cron_matches("30 12 2 * 4", sun)         # dom matches, dow doesn't


def test_most_recent_schedule():
    now = _utc(2026, 7, 30, 12, 34)
    got = most_recent_schedule("30 12 * * *", now - 3600, now)
    assert got == _utc(2026, 7, 30, 12, 30)
    assert most_recent_schedule("0 1 * * *", now - 60, now) is None


def test_cronjob_creates_and_forbids(client):
    ctrl, factory = run_controller(client, CronJobController(client))
    try:
        client.resource("cronjobs").create({
            "apiVersion": "batch/v1", "kind": "CronJob",
            "metadata": {"name": "tick", "namespace": "default"},
            "spec": {"schedule": "* * * * *",   # every minute
                     "concurrencyPolicy": "Forbid",
                     "jobTemplate": {"spec": {"parallelism": 1,
                                              "template": {"spec": {
                                                  "containers": [{"name": "c"}]}}}}}})
        assert wait_until(lambda: client.resource("jobs").list())
        jobs = client.resource("jobs").list()
        assert len(jobs) == 1
        ref = jobs[0]["metadata"]["ownerReferences"][0]
        assert ref["kind"] == "CronJob" and ref["name"] == "tick"
        # Forbid: while the job is active, no second job appears
        time.sleep(1.5)
        assert len(client.resource("jobs").list()) == 1
        st = client.resource("cronjobs").get("tick").get("status") or {}
        assert st.get("lastScheduleTime") and st.get("active")
    finally:
        stop(ctrl, factory)


def test_cronjob_invalid_schedule_sets_condition(client):
    ctrl, factory = run_controller(client, CronJobController(client))
    try:
        client.resource("cronjobs").create({
            "apiVersion": "batch/v1", "kind": "CronJob",
            "metadata": {"name": "bad", "namespace": "default"},
            "spec": {"schedule": "@hourly",  # macros unsupported: 5 fields only
                     "jobTemplate": {"spec": {}}}})
        assert wait_until(lambda: (client.resource("cronjobs").get("bad")
                                   .get("status") or {}).get("conditions"))
        cond = client.resource("cronjobs").get("bad")["status"]["conditions"][0]
        assert cond["type"] == "InvalidSchedule"
        assert not client.resource("jobs").list()
    finally:
        stop(ctrl, factory)


def test_cronjob_suspend(client):
    ctrl, factory = run_controller(client, CronJobController(client))
    try:
        client.resource("cronjobs").create({
            "apiVersion": "batch/v1", "kind": "CronJob",
            "metadata": {"name": "paused", "namespace": "default"},
            "spec": {"schedule": "* * * * *", "suspend": True,
                     "jobTemplate": {"spec": {}}}})
        time.sleep(1.5)
        assert not client.resource("jobs").list()
    finally:
        stop(ctrl, factory)


# --------------------------------------------------------------------- ttl

def test_ttl_after_finished_deletes_job(client):
    ctrl, factory = run_controller(client, TTLAfterFinishedController(client))
    try:
        client.resource("jobs").create({
            "apiVersion": "apps/v1", "kind": "Job",
            "metadata": {"name": "done", "namespace": "default"},
            "spec": {"ttlSecondsAfterFinished": 0},
            "status": {"conditions": [{"type": "Complete", "status": "True",
                                       "lastTransitionTime": time.time() - 5}]}})
        client.resource("jobs").create({
            "apiVersion": "apps/v1", "kind": "Job",
            "metadata": {"name": "running", "namespace": "default"},
            "spec": {"ttlSecondsAfterFinished": 0}, "status": {}})
        client.resource("jobs").create({
            "apiVersion": "apps/v1", "kind": "Job",
            "metadata": {"name": "no-ttl", "namespace": "default"},
            "spec": {},
            "status": {"conditions": [{"type": "Complete", "status": "True"}]}})
        assert wait_until(lambda: {j["metadata"]["name"]
                                   for j in client.resource("jobs").list()}
                          == {"running", "no-ttl"})
    finally:
        stop(ctrl, factory)


# --------------------------------------------------------------------- hpa

def _deploy(replicas):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": replicas,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}}}


def _usage_pod(name, request, used):
    p = make_pod(name).label("app", "web").req({"cpu": request}).obj().to_dict()
    p["metadata"].setdefault("annotations", {})[USAGE_ANNOTATION] = used
    p["spec"]["nodeName"] = "n1"
    p["status"] = {"phase": "Running"}
    return p


def test_hpa_scales_up_and_respects_max(client):
    ctrl, factory = run_controller(
        client, HorizontalPodAutoscalerController(client))
    try:
        client.resource("deployments").create(_deploy(2))
        for i in range(2):
            client.pods().create(_usage_pod(f"w{i}", "1", "900m"))  # 90% used
        client.resource("horizontalpodautoscalers").create({
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "web"},
                     "minReplicas": 1, "maxReplicas": 3,
                     "metrics": [{"type": "Resource", "resource": {
                         "name": "cpu", "target": {
                             "type": "Utilization",
                             "averageUtilization": 50}}}]}})
        # 90% actual vs 50% target -> ceil(2*1.8)=4, clamped to max 3
        assert wait_until(lambda: client.resource("deployments")
                          .get("web")["spec"]["replicas"] == 3)
        st = client.resource("horizontalpodautoscalers").get("web")["status"]
        assert st["desiredReplicas"] == 3
        assert st["currentCPUUtilizationPercentage"] == 90.0
    finally:
        stop(ctrl, factory)


def test_hpa_scale_down_stabilized(client):
    ctrl, factory = run_controller(
        client, HorizontalPodAutoscalerController(
            client, downscale_stabilization_s=9999.0))
    try:
        client.resource("deployments").create(_deploy(3))
        for i in range(3):
            client.pods().create(_usage_pod(f"w{i}", "1", "100m"))  # 10% used
        client.resource("horizontalpodautoscalers").create({
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "web"},
                     "minReplicas": 1, "maxReplicas": 5,
                     "metrics": [{"type": "Resource", "resource": {
                         "name": "cpu", "target": {
                             "type": "Utilization",
                             "averageUtilization": 50}}}]}})
        time.sleep(2.0)  # within the stabilization window: no scale-down yet
        assert client.resource("deployments").get("web")["spec"]["replicas"] == 3
    finally:
        stop(ctrl, factory)


# --------------------------------------------------------------- namespace

def test_namespace_purge_on_delete(client):
    ctrl = NamespaceController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.resource("namespaces", None).create(
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "team-a"}})
        pod = make_pod("p1").obj().to_dict()
        pod["metadata"]["namespace"] = "team-a"
        client.pods("team-a").create(pod)
        client.resource("configmaps", "team-a").create(
            {"apiVersion": "v1", "kind": "ConfigMap",
             "metadata": {"name": "cm", "namespace": "team-a"}})
        keep = make_pod("keep").obj().to_dict()
        client.pods("default").create(keep)
        client.resource("namespaces", None).delete("team-a")
        assert wait_until(lambda: not client.pods("team-a").list()
                          and not client.resource("configmaps", "team-a").list())
        assert client.pods("default").list()
    finally:
        ctrl.stop()
        factory.stop_all()


# ---------------------------------------------------------------- podgc

def test_podgc_sweeps():
    from kubernetes_tpu.controllers import PodGCController
    client = DirectClient(ObjectStore())
    client.nodes().create({"apiVersion": "v1", "kind": "Node",
                           "metadata": {"name": "alive"}, "status": {}})
    ctrl = PodGCController(client, terminated_threshold=1, quarantine_s=0.2)
    ctrl.tick_interval = 0.1
    ctrl2, factory = run_controller(client, ctrl)
    try:
        # (a) two terminated pods, threshold 1 -> oldest goes
        for i, ts in ((0, 10.0), (1, 20.0)):
            p = make_pod(f"done{i}").obj().to_dict()
            p["metadata"]["creationTimestamp"] = ts
            p["spec"]["nodeName"] = "alive"
            p["status"] = {"phase": "Succeeded"}
            client.pods().create(p)
        # (b) orphan on a node that doesn't exist
        orphan = make_pod("orphan").obj().to_dict()
        orphan["spec"]["nodeName"] = "ghost-node"
        orphan["status"] = {"phase": "Running"}
        client.pods().create(orphan)
        # (c) terminating but never scheduled
        limbo = make_pod("limbo").obj().to_dict()
        limbo["metadata"]["deletionTimestamp"] = 123.0
        client.pods().create(limbo)
        # survivor
        keeper = make_pod("keeper").obj().to_dict()
        keeper["spec"]["nodeName"] = "alive"
        keeper["status"] = {"phase": "Running"}
        client.pods().create(keeper)
        # controller-owned terminated pod: NOT the threshold sweep's business
        owned = make_pod("job-done").obj().to_dict()
        owned["metadata"]["creationTimestamp"] = 1.0  # oldest of all
        owned["metadata"]["ownerReferences"] = [{
            "kind": "Job", "name": "j", "uid": "u1", "controller": True}]
        owned["spec"]["nodeName"] = "alive"
        owned["status"] = {"phase": "Succeeded"}
        client.pods().create(owned)
        assert wait_until(lambda: {p["metadata"]["name"]
                                   for p in client.pods().list()}
                          == {"done1", "keeper", "job-done"})
    finally:
        stop(ctrl2, factory)


# ------------------------------------------------- replicationcontroller

def test_replicationcontroller_map_selector():
    from kubernetes_tpu.controllers import ReplicationControllerController
    client = DirectClient(ObjectStore())
    ctrl, factory = run_controller(
        client, ReplicationControllerController(client))
    try:
        client.resource("replicationcontrollers").create({
            "apiVersion": "v1", "kind": "ReplicationController",
            "metadata": {"name": "legacy", "namespace": "default"},
            "spec": {"replicas": 2,
                     "selector": {"app": "legacy"},  # v1 MAP selector
                     "template": {"metadata": {"labels": {"app": "legacy"}},
                                  "spec": {"containers": [{"name": "c"}]}}}})
        assert wait_until(lambda: len(client.pods().list()) == 2)
        for p in client.pods().list():
            ref = p["metadata"]["ownerReferences"][0]
            assert ref["kind"] == "ReplicationController"
        assert wait_until(lambda: (client.resource("replicationcontrollers")
                                   .get("legacy").get("status") or {})
                          .get("replicas") == 2)
        # scale down
        rc = client.resource("replicationcontrollers").get("legacy")
        rc["spec"]["replicas"] = 1
        client.resource("replicationcontrollers").update(rc)
        assert wait_until(lambda: len([
            p for p in client.pods().list()
            if not (p["metadata"].get("deletionTimestamp"))]) == 1)
    finally:
        stop(ctrl, factory)


# ------------------------------------------------------- serviceaccount

def test_default_serviceaccount_and_token(client):
    from kubernetes_tpu.controllers import (
        ServiceAccountController,
        TokenController,
    )
    sa_ctrl, sa_factory = run_controller(client, ServiceAccountController(client))
    tok_ctrl, tok_factory = run_controller(client, TokenController(client))
    try:
        client.resource("namespaces", None).create(
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "team-a"}})
        assert wait_until(lambda: client.resource("serviceaccounts", "team-a")
                          .list())
        sa = client.resource("serviceaccounts", "team-a").list()[0]
        assert sa["metadata"]["name"] == "default"
        assert wait_until(lambda: client.resource("secrets", "team-a").list())
        secret = client.resource("secrets", "team-a").list()[0]
        assert secret["type"] == "kubernetes.io/service-account-token"
        assert secret["data"]["token"].startswith("ktpu-sa-")
        assert secret["metadata"]["ownerReferences"][0]["kind"] == "ServiceAccount"
        # the SA records its token secret
        assert wait_until(lambda: {"name": "default-token"} in
                          (client.resource("serviceaccounts", "team-a")
                           .get("default").get("secrets") or []))
    finally:
        stop(sa_ctrl, sa_factory)
        stop(tok_ctrl, tok_factory)


def test_sa_token_authenticates_against_apiserver():
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.store.apiserver import APIServer

    server = APIServer().enable_auth().start()
    try:
        # mint what the token controller would have (no controllers here:
        # exercise only the authn path over the minted secret)
        admin = HTTPClient(server.url)  # anonymous allowed by default authn?
        # (the "default" Namespace now always exists: the apiserver seeds
        # the system namespaces at start, like pkg/controlplane)
        server.store.create("Secret", {
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": "robot-token", "namespace": "default",
                         "annotations": {
                             "kubernetes.io/service-account.name": "robot"}},
            "type": "kubernetes.io/service-account-token",
            "data": {"token": "ktpu-sa-abc123"}})
        user = server.authenticator.authenticate("Bearer ktpu-sa-abc123")
        assert user.name == "system:serviceaccount:default:robot"
        assert "system:serviceaccounts" in user.groups
    finally:
        server.stop()


# ------------------------------------------------------------ endpointslice

def test_endpointslice_created_and_sliced(client):
    ctrl, factory = run_controller(client, EndpointSliceController(client))
    try:
        client.services().create({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"selector": {"app": "web"},
                     "ports": [{"port": 80, "targetPort": 8080}]}})
        for i in range(3):
            p = make_pod(f"w{i}").label("app", "web").obj().to_dict()
            p["status"] = {"phase": "Running", "podIP": f"10.0.0.{i+1}",
                           "conditions": [{"type": "Ready", "status": "True"}]}
            client.pods().create(p)

        def ok():
            slices = [s for s in client.resource("endpointslices").list()
                      if (s["metadata"].get("labels") or {})
                      .get("kubernetes.io/service-name") == "web"]
            if len(slices) != 1:
                return False
            eps = slices[0].get("endpoints") or []
            ips = sorted(e["addresses"][0] for e in eps)
            return (ips == ["10.0.0.1", "10.0.0.2", "10.0.0.3"]
                    and slices[0]["ports"][0]["port"] == 8080
                    and all(e["conditions"]["ready"] for e in eps))
        assert wait_until(ok)
        # service deleted -> slices cleaned up
        client.services().delete("web")
        assert wait_until(lambda: not [
            s for s in client.resource("endpointslices").list()
            if (s["metadata"].get("labels") or {})
            .get("kubernetes.io/service-name") == "web"])
    finally:
        stop(ctrl, factory)


# ------------------------------------------------------- root CA publisher

def test_root_ca_published_to_every_namespace(client):
    from kubernetes_tpu.controllers.rootca import (CONFIGMAP_NAME,
                                                   RootCAPublisher)
    nss = client.resource("namespaces", None)
    nss.create({"kind": "Namespace", "metadata": {"name": "team-a"}})
    ctrl, factory = run_controller(
        client, RootCAPublisher(client, ca_pem="PEM-BUNDLE"))
    try:
        def published(ns):
            try:
                cm = client.resource("configmaps", ns).get(CONFIGMAP_NAME)
            except ApiError:
                return False
            return cm.get("data", {}).get("ca.crt") == "PEM-BUNDLE"
        assert wait_until(lambda: published("team-a"))
        # a later namespace gets the bundle too
        nss.create({"kind": "Namespace", "metadata": {"name": "team-b"}})
        assert wait_until(lambda: published("team-b"))
        # drift heals
        cm = client.resource("configmaps", "team-a").get(CONFIGMAP_NAME)
        cm["data"] = {"ca.crt": "tampered"}
        client.resource("configmaps", "team-a").update(cm)
        assert wait_until(lambda: published("team-a"))
        # deletion heals
        client.resource("configmaps", "team-b").delete(CONFIGMAP_NAME)
        assert wait_until(lambda: published("team-b"))
    finally:
        stop(ctrl, factory)


# ----------------------------------------------- endpointslice mirroring

def test_mirrors_custom_endpoints_for_selectorless_service(client):
    from kubernetes_tpu.controllers.endpointslicemirroring import (
        EndpointSliceMirroringController)
    svcs = client.resource("services", "default")
    eps = client.resource("endpoints", "default")
    slices = client.resource("endpointslices", "default")
    # selector-LESS service with hand-maintained Endpoints (external DB)
    svcs.create({"kind": "Service", "metadata": {"name": "ext-db"},
                 "spec": {"clusterIP": "10.96.5.1",
                          "ports": [{"port": 5432}]}})
    eps.create({"kind": "Endpoints", "metadata": {"name": "ext-db"},
                "subsets": [{"addresses": [{"ip": "192.0.2.10"}],
                             "notReadyAddresses": [{"ip": "192.0.2.11"}],
                             "ports": [{"port": 5432,
                                        "protocol": "TCP"}]}]})
    # selector-DRIVEN service: its endpoints must NOT be mirrored
    svcs.create({"kind": "Service", "metadata": {"name": "managed"},
                 "spec": {"clusterIP": "10.96.5.2",
                          "selector": {"app": "x"},
                          "ports": [{"port": 80}]}})
    eps.create({"kind": "Endpoints", "metadata": {"name": "managed"},
                "subsets": [{"addresses": [{"ip": "10.88.0.1"}],
                             "ports": [{"port": 80}]}]})
    ctrl, factory = run_controller(
        client, EndpointSliceMirroringController(client))
    try:
        def mirrored():
            try:
                return slices.get("ext-db-mirror-0")
            except ApiError:
                return None
        assert wait_until(mirrored)
        sl = mirrored()
        ready = [e for e in sl["endpoints"] if e["conditions"]["ready"]]
        notready = [e for e in sl["endpoints"]
                    if not e["conditions"]["ready"]]
        assert [e["addresses"] for e in ready] == [["192.0.2.10"]]
        assert [e["addresses"] for e in notready] == [["192.0.2.11"]]
        assert sl["ports"] == [{"name": "", "port": 5432,
                                "protocol": "TCP"}]
        time.sleep(0.3)
        with pytest.raises(ApiError):
            slices.get("managed-mirror-0")  # selector-driven: not mirrored
        # endpoints update flows through; endpoints delete removes mirror
        ep = eps.get("ext-db")
        ep["subsets"][0]["addresses"].append({"ip": "192.0.2.12"})
        eps.update(ep)
        assert wait_until(lambda: len(mirrored()["endpoints"]) == 3)
        eps.delete("ext-db")
        assert wait_until(lambda: mirrored() is None)
    finally:
        stop(ctrl, factory)


def test_mirroring_and_endpointslice_controllers_coexist(client):
    """Both default controllers running: the endpointslice controller must
    NOT delete the mirroring controller's slices for selector-less
    Services (they carry a foreign managed-by label)."""
    from kubernetes_tpu.controllers.endpointslicemirroring import (
        EndpointSliceMirroringController)
    svcs = client.resource("services", "default")
    eps = client.resource("endpoints", "default")
    slices = client.resource("endpointslices", "default")
    svcs.create({"kind": "Service", "metadata": {"name": "xdb"},
                 "spec": {"clusterIP": "10.96.5.9",
                          "ports": [{"port": 5432}]}})
    eps.create({"kind": "Endpoints", "metadata": {"name": "xdb"},
                "subsets": [{"addresses": [{"ip": "192.0.2.20"}],
                             "ports": [{"port": 5432}]}]})
    c1, f1 = run_controller(client, EndpointSliceMirroringController(client))
    c2, f2 = run_controller(client, EndpointSliceController(client))
    try:
        def mirror():
            try:
                return slices.get("xdb-mirror-0")
            except ApiError:
                return None
        assert wait_until(mirror)
        # poke the endpointslice controller's sync for this service and
        # give it time to (wrongly) delete; the mirror must survive
        c2.queue.add("default/xdb")
        time.sleep(0.5)
        assert mirror() is not None, \
            "endpointslice controller deleted the mirror slice"
    finally:
        stop(c1, f1)
        stop(c2, f2)
