"""Incremental snapshot encoding (Cache.UpdateSnapshot analog).

Pod binds/unbinds between snapshots must patch the cached tensors in place
(no full re-encode — encoder.generation stays put); heartbeat-only node
updates must not invalidate the cache at all; structural changes fall back
to a full encode. Patched tensors must be semantically identical to a fresh
full encode (same requested state, same existing-pod set, same scheduling
decisions).
"""

import numpy as np

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.schedule_step import schedule_step
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n=8):
    return [make_node(f"n{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("topology.kubernetes.io/zone", f"z{i % 3}")
            .label("kubernetes.io/hostname", f"n{i}")
            .obj() for i in range(n)]


def _pod(i, labels=None, anti=False):
    b = make_pod(f"p{i}").req({"cpu": "500m", "memory": "256Mi"})
    for k, v in (labels or {"app": "a"}).items():
        b = b.label(k, v)
    if anti:
        b = b.pod_anti_affinity("kubernetes.io/hostname", {"app": "a"})
    return b.obj()


def test_pod_binds_patch_without_reencode():
    cache = SchedulerCache()
    for n in _nodes():
        cache.add_node(n)
    pending = [_pod(i, anti=(i % 2 == 0)) for i in range(6)]
    nodes, ct0, meta = cache.snapshot(pending_pods=pending)
    gen_after_full = cache._encoder.generation

    # bind three pods (assume path, like the scheduler does)
    for i, p in enumerate(pending[:3]):
        cache.assume(p, f"n{i}")
    nodes, ct1, meta1 = cache.snapshot(pending_pods=pending[3:])
    assert cache._encoder.generation == gen_after_full, "should have patched"
    assert meta1 is meta

    # semantics match a fresh full encode of the same state
    bound = cache.bound_pods()
    fresh_enc = SnapshotEncoder()
    ct_ref, meta_ref = fresh_enc.encode_cluster(
        [cache._nodes[n] for n in sorted(cache._nodes)], bound,
        pending_pods=pending[3:])
    # same node ordering? cache.snapshot uses dict order == insertion order
    assert meta.node_names == meta_ref.node_names
    np.testing.assert_array_equal(np.asarray(ct1.requested)[:8],
                                  np.asarray(ct_ref.requested)[:8])
    # valid epods occupy same (node, ns) multiset
    def epod_set(ct):
        v = np.asarray(ct.epod_valid)
        return sorted(np.asarray(ct.epod_node)[v].tolist())
    assert epod_set(ct1) == epod_set(ct_ref)

    # scheduling parity on the remaining pods
    pb1 = cache.encode_pods(pending[3:], meta1)
    r1 = schedule_step(ct1, pb1, topo_keys=meta1.topo_keys)
    pb_ref = fresh_enc.encode_pods(pending[3:], meta_ref)
    r_ref = schedule_step(ct_ref, pb_ref, topo_keys=meta_ref.topo_keys)
    np.testing.assert_array_equal(np.asarray(r1.choice)[:3],
                                  np.asarray(r_ref.choice)[:3])
    np.testing.assert_array_equal(np.asarray(r1.feasible)[:3, :8],
                                  np.asarray(r_ref.feasible)[:3, :8])


def test_unbind_and_rebind_patch():
    cache = SchedulerCache()
    for n in _nodes(4):
        cache.add_node(n)
    pods = [_pod(i) for i in range(4)]
    cache.snapshot(pending_pods=pods)
    for i, p in enumerate(pods):
        cache.assume(p, f"n{i}")
    _, ct, _ = cache.snapshot()
    gen = cache._encoder.generation
    before = np.asarray(ct.requested).copy()

    cache.remove_pod(pods[0].key)   # unbind
    _, ct2, _ = cache.snapshot()
    assert cache._encoder.generation == gen
    after = np.asarray(ct2.requested)
    assert (after[0] <= before[0]).all() and (after[0] < before[0]).any()
    assert int(np.asarray(ct2.epod_valid).sum()) == 3

    cache.assume(pods[0], "n3")      # rebind elsewhere
    _, ct3, _ = cache.snapshot()
    assert cache._encoder.generation == gen
    np.testing.assert_array_equal(np.asarray(ct3.requested)[3],
                                  before[3] + (before[0] - after[0]))


def test_heartbeat_does_not_invalidate():
    cache = SchedulerCache()
    for n in _nodes(4):
        cache.add_node(n)
    _, ct, _ = cache.snapshot()
    n0 = cache._nodes["n0"]
    import copy
    hb = copy.deepcopy(n0)
    hb.status.conditions = [{"type": "Ready", "status": "True",
                             "lastHeartbeatTime": "2026-07-29T00:00:00Z"}]
    cache.update_node(hb)
    _, ct2, _ = cache.snapshot()
    assert ct2 is ct  # same cached object — not even a patch


def test_bound_pod_status_update_does_not_invalidate():
    """The pod twin of the heartbeat check: a kubelet rewriting ``status``
    on an already-bound pod must not bump the generation or append a
    delta-log entry — at fleet scale these MODIFIEDs arrive per pod per
    sync and used to make every drain cycle compile a churn patch over
    hundreds of unchanged pods. A REAL change (labels, requests, node)
    still invalidates."""
    import copy
    cache = SchedulerCache()
    for n in _nodes(4):
        cache.add_node(n)
    bound = _pod(0)
    bound.spec.node_name = "n0"
    cache.add_pod(bound)
    _, ct, _ = cache.snapshot()
    gen0, seq0 = cache._generation, cache.log_seq()
    hb = copy.deepcopy(bound)
    hb.status.phase = "Running"
    cache.add_pod(hb)
    assert cache._generation == gen0 and cache.log_seq() == seq0
    _, ct2, _ = cache.snapshot()
    assert ct2 is ct  # same cached object — not even a patch
    assert cache._pods[bound.key] is hb  # the stored object still refreshes
    relabeled = copy.deepcopy(hb)
    relabeled.metadata.labels["app"] = "changed"
    cache.add_pod(relabeled)
    assert cache._generation > gen0 and cache.log_seq() == seq0 + 1


def test_structural_changes_force_full_encode():
    cache = SchedulerCache()
    for n in _nodes(4):
        cache.add_node(n)
    cache.snapshot()
    gen = cache._encoder.generation

    # node relabel → full
    import copy
    n0 = copy.deepcopy(cache._nodes["n0"])
    n0.metadata.labels["disk"] = "ssd"
    cache.update_node(n0)
    cache.snapshot()
    assert cache._encoder.generation == gen + 1

    # pod with an unseen label key → patch bails, full encode
    gen = cache._encoder.generation
    cache.assume(_pod(9, labels={"brand-new-key": "x"}), "n1")
    _, ct, meta = cache.snapshot()
    assert cache._encoder.generation == gen + 1
    assert int(np.asarray(ct.epod_valid).sum()) == 1


def test_delta_pod_with_volumes_falls_back():
    cache = SchedulerCache()
    for n in _nodes(2):
        cache.add_node(n)
    cache.snapshot()
    gen = cache._encoder.generation
    p = _pod(0)
    p.spec.volumes.append({"persistentVolumeClaim": {"claimName": "c1"}})
    cache.assume(p, "n0")
    cache.snapshot()
    assert cache._encoder.generation == gen + 1
