"""kube-proxy: rule table from Service+Endpoints, DNAT resolution, affinity."""

import random
import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.proxy import Proxier
from kubernetes_tpu.store.store import ObjectStore


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def make_service(client, name="web", port=80, target=8080, affinity=False):
    spec = {"selector": {"app": name},
            "ports": [{"port": port, "targetPort": target}]}
    if affinity:
        spec["sessionAffinity"] = "ClientIP"
    return client.services().create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": name, "namespace": "default"}, "spec": spec})


def put_endpoints(client, name, ips, port=8080, update=False):
    ep = {"apiVersion": "v1", "kind": "Endpoints",
          "metadata": {"name": name, "namespace": "default"},
          "subsets": [{"ports": [{"name": "", "port": port}],
                       "addresses": [{"ip": ip} for ip in ips]}] if ips else []}
    if update:
        cur = client.endpoints().get(name)
        ep["metadata"]["resourceVersion"] = cur["metadata"]["resourceVersion"]
        return client.endpoints().update(ep)
    return client.endpoints().create(ep)


def test_cluster_ip_allocated_on_create(client):
    svc = make_service(client)
    assert svc["spec"]["clusterIP"].startswith("10.96.")
    svc2 = make_service(client, name="other")
    assert svc2["spec"]["clusterIP"] != svc["spec"]["clusterIP"]


def test_proxier_resolves_vip_to_backends(client):
    svc = make_service(client)
    put_endpoints(client, "web", ["10.1.0.1", "10.1.0.2"])
    p = Proxier(client).start()
    try:
        vip = svc["spec"]["clusterIP"]
        rng = random.Random(0)
        picks = {p.resolve(vip, 80, rng=rng) for _ in range(50)}
        assert picks == {"10.1.0.1:8080", "10.1.0.2:8080"}
        # no endpoints -> REJECT (None)
        assert p.resolve("10.96.99.99", 80) is None
        # endpoints change propagates
        put_endpoints(client, "web", ["10.1.0.9"], update=True)
        assert wait_until(lambda: p.resolve(vip, 80) == "10.1.0.9:8080")
    finally:
        p.stop()


def test_proxier_session_affinity(client):
    svc = make_service(client, name="sticky", affinity=True)
    put_endpoints(client, "sticky", ["10.2.0.1", "10.2.0.2", "10.2.0.3"])
    p = Proxier(client).start()
    try:
        vip = svc["spec"]["clusterIP"]
        rng = random.Random(7)
        first = p.resolve(vip, 80, client_ip="172.16.0.5", rng=rng)
        assert all(p.resolve(vip, 80, client_ip="172.16.0.5", rng=rng) == first
                   for _ in range(20))
        # a different client may get a different backend (and keeps it)
        other = p.resolve(vip, 80, client_ip="172.16.0.6", rng=rng)
        assert all(p.resolve(vip, 80, client_ip="172.16.0.6", rng=rng) == other
                   for _ in range(5))
    finally:
        p.stop()


def test_proxier_rules_render(client):
    svc = make_service(client)
    put_endpoints(client, "web", ["10.1.0.1", "10.1.0.2"])
    empty = make_service(client, name="void")
    p = Proxier(client).start()
    try:
        rules = p.rules()
        vip = svc["spec"]["clusterIP"]
        assert any(f"-d {vip}/32" in r and "KUBE-SVC-default/web" in r for r in rules)
        assert any("DNAT --to-destination 10.1.0.1:8080" in r for r in rules)
        # probability ladder on the first of two endpoints
        assert any("--probability 0.50000" in r for r in rules)
        # service without endpoints renders a REJECT
        assert any(f"-d {empty['spec']['clusterIP']}/32" in r and "REJECT" in r
                   for r in rules)
    finally:
        p.stop()


def test_headless_service_has_no_rules(client):
    client.services().create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": "hl", "namespace": "default"},
        "spec": {"clusterIP": "None", "selector": {"app": "hl"},
                 "ports": [{"port": 80}]}})
    p = Proxier(client).start()
    try:
        assert all("hl" not in r for r in p.rules())
    finally:
        p.stop()
