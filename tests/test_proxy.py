"""kube-proxy: rule table from Service+Endpoints, DNAT resolution, affinity."""

import random
import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.proxy import Proxier
from kubernetes_tpu.store.store import ObjectStore


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def make_service(client, name="web", port=80, target=8080, affinity=False):
    spec = {"selector": {"app": name},
            "ports": [{"port": port, "targetPort": target}]}
    if affinity:
        spec["sessionAffinity"] = "ClientIP"
    return client.services().create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": name, "namespace": "default"}, "spec": spec})


def put_endpoints(client, name, ips, port=8080, update=False):
    ep = {"apiVersion": "v1", "kind": "Endpoints",
          "metadata": {"name": name, "namespace": "default"},
          "subsets": [{"ports": [{"name": "", "port": port}],
                       "addresses": [{"ip": ip} for ip in ips]}] if ips else []}
    if update:
        cur = client.endpoints().get(name)
        ep["metadata"]["resourceVersion"] = cur["metadata"]["resourceVersion"]
        return client.endpoints().update(ep)
    return client.endpoints().create(ep)


def test_cluster_ip_allocated_on_create(client):
    svc = make_service(client)
    assert svc["spec"]["clusterIP"].startswith("10.96.")
    svc2 = make_service(client, name="other")
    assert svc2["spec"]["clusterIP"] != svc["spec"]["clusterIP"]


def test_proxier_resolves_vip_to_backends(client):
    svc = make_service(client)
    put_endpoints(client, "web", ["10.1.0.1", "10.1.0.2"])
    p = Proxier(client).start()
    try:
        vip = svc["spec"]["clusterIP"]
        rng = random.Random(0)
        picks = {p.resolve(vip, 80, rng=rng) for _ in range(50)}
        assert picks == {"10.1.0.1:8080", "10.1.0.2:8080"}
        # no endpoints -> REJECT (None)
        assert p.resolve("10.96.99.99", 80) is None
        # endpoints change propagates
        put_endpoints(client, "web", ["10.1.0.9"], update=True)
        assert wait_until(lambda: p.resolve(vip, 80) == "10.1.0.9:8080")
    finally:
        p.stop()


def test_proxier_session_affinity(client):
    svc = make_service(client, name="sticky", affinity=True)
    put_endpoints(client, "sticky", ["10.2.0.1", "10.2.0.2", "10.2.0.3"])
    p = Proxier(client).start()
    try:
        vip = svc["spec"]["clusterIP"]
        rng = random.Random(7)
        first = p.resolve(vip, 80, client_ip="172.16.0.5", rng=rng)
        assert all(p.resolve(vip, 80, client_ip="172.16.0.5", rng=rng) == first
                   for _ in range(20))
        # a different client may get a different backend (and keeps it)
        other = p.resolve(vip, 80, client_ip="172.16.0.6", rng=rng)
        assert all(p.resolve(vip, 80, client_ip="172.16.0.6", rng=rng) == other
                   for _ in range(5))
    finally:
        p.stop()


def test_proxier_rules_render(client):
    svc = make_service(client)
    put_endpoints(client, "web", ["10.1.0.1", "10.1.0.2"])
    empty = make_service(client, name="void")
    p = Proxier(client).start()
    try:
        rules = p.rules()
        vip = svc["spec"]["clusterIP"]
        # VIP dispatch jumps to an upstream-shaped hashed chain, with the
        # readable service name carried in the -m comment
        assert any(f"-d {vip}/32" in r and "KUBE-SVC-" in r
                   and "default/web" in r for r in rules)
        assert any("DNAT --to-destination 10.1.0.1:8080" in r for r in rules)
        # probability ladder on the first of two endpoints
        assert any("--probability 0.50000" in r for r in rules)
        # service without endpoints renders a REJECT
        assert any(f"-d {empty['spec']['clusterIP']}/32" in r and "REJECT" in r
                   for r in rules)
    finally:
        p.stop()


def test_headless_service_has_no_rules(client):
    client.services().create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": "hl", "namespace": "default"},
        "spec": {"clusterIP": "None", "selector": {"app": "hl"},
                 "ports": [{"port": 80}]}})
    p = Proxier(client).start()
    try:
        assert all("hl" not in r for r in p.rules())
    finally:
        p.stop()


# ---------------------------------------------- iptables-restore rendering

def _mk_proxier_with(services, endpoints):
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    for s in services:
        client.resource("services", s["metadata"].get("namespace",
                                                      "default")).create(s)
    for e in endpoints:
        client.resource("endpoints", e["metadata"].get("namespace",
                                                       "default")).create(e)
    p = Proxier(client).start()
    return p


def test_restore_payload_structure_and_roundtrip():
    from kubernetes_tpu.proxy.proxier import RestoredRules
    p = _mk_proxier_with(
        [{"kind": "Service", "metadata": {"name": "web"},
          "spec": {"clusterIP": "10.96.0.10", "sessionAffinity": "None",
                   "ports": [{"port": 80, "protocol": "TCP",
                              "nodePort": 30080}]}},
         {"kind": "Service", "metadata": {"name": "empty"},
          "spec": {"clusterIP": "10.96.0.11",
                   "ports": [{"port": 443, "protocol": "TCP"}]}}],
        [{"kind": "Endpoints", "metadata": {"name": "web"},
          "subsets": [{"addresses": [{"ip": "10.88.0.5"},
                                     {"ip": "10.88.0.6"}],
                       "ports": [{"port": 8080}]}]}])
    try:
        text = p.sync_proxy_rules_text()
        # structural essentials of a syncProxyRules payload
        assert text.startswith("*nat")
        assert text.rstrip().endswith("COMMIT")
        assert ":KUBE-SERVICES - [0:0]" in text
        assert "-j MASQUERADE" in text and "0x4000/0x4000" in text
        assert "--mode random --probability 0.5" in text
        assert "-j REJECT" in text  # endpoint-less service
        # chain names are upstream-shaped hashes, not readable strings
        import re
        assert re.search(r"KUBE-SVC-[A-Z2-7]{16}", text)
        assert re.search(r"KUBE-SEP-[A-Z2-7]{16}", text)
        # ROUND TRIP: parsing the text yields the same DNAT decisions
        rr = RestoredRules(text)
        assert sorted(rr.backends("10.96.0.10", 80)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        assert rr.backends("10.96.0.11", 443) == []  # REJECT
        # nodePort dispatch reaches the same chain
        assert sorted(rr.backends("203.0.113.1", 30080)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        # and the live resolve() agrees with the parsed rules
        got = {p.resolve("10.96.0.10", 80) for _ in range(50)}
        assert got == set(rr.backends("10.96.0.10", 80))
    finally:
        p.stop()


def test_rejected_vip_does_not_fall_through_to_nodeport():
    """REJECT precedes the nodePort dispatch: a rejected clusterIP whose
    port collides with another service's nodePort must stay rejected."""
    from kubernetes_tpu.proxy.proxier import RestoredRules
    p = _mk_proxier_with(
        [{"kind": "Service", "metadata": {"name": "np"},
          "spec": {"clusterIP": "10.96.0.20",
                   "ports": [{"port": 80, "protocol": "TCP",
                              "nodePort": 30080}]}},
         {"kind": "Service", "metadata": {"name": "dead"},
          "spec": {"clusterIP": "10.96.0.21",
                   "ports": [{"port": 30080, "protocol": "TCP"}]}}],
        [{"kind": "Endpoints", "metadata": {"name": "np"},
          "subsets": [{"addresses": [{"ip": "10.88.0.9"}],
                       "ports": [{"port": 8080}]}]}])
    try:
        rr = RestoredRules(p.sync_proxy_rules_text())
        assert rr.backends("10.96.0.21", 30080) == []   # REJECT wins
        assert rr.backends("203.0.113.9", 30080) == ["10.88.0.9:8080"]
    finally:
        p.stop()


# -------------------------------------------------- nftables backend render

def _mk_nft_proxier_with(services, endpoints):
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.proxy.nftables import NftablesProxier
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    for s in services:
        client.resource("services", s["metadata"].get("namespace",
                                                      "default")).create(s)
    for e in endpoints:
        client.resource("endpoints", e["metadata"].get("namespace",
                                                       "default")).create(e)
    return NftablesProxier(client).start()


def test_nftables_payload_structure_and_roundtrip():
    from kubernetes_tpu.proxy.nftables import RestoredNftRules
    p = _mk_nft_proxier_with(
        [{"kind": "Service", "metadata": {"name": "web"},
          "spec": {"clusterIP": "10.96.0.10", "sessionAffinity": "None",
                   "ports": [{"port": 80, "protocol": "TCP",
                              "nodePort": 30080}]}},
         {"kind": "Service", "metadata": {"name": "empty"},
          "spec": {"clusterIP": "10.96.0.11",
                   "ports": [{"port": 443, "protocol": "TCP"}]}}],
        [{"kind": "Endpoints", "metadata": {"name": "web"},
          "subsets": [{"addresses": [{"ip": "10.88.0.5"},
                                     {"ip": "10.88.0.6"}],
                       "ports": [{"port": 8080}]}]}])
    try:
        text = p.sync_nft_text()
        # structural essentials of the nftables proxier's ruleset
        assert text.startswith("table ip kube-proxy {")
        assert "type nat hook prerouting priority dnat" in text
        assert "type nat hook postrouting priority srcnat" in text
        assert "vmap @service-ips" in text
        assert "vmap @service-nodeports" in text
        assert "numgen random mod 2 vmap" in text
        assert "mark-for-masquerade" in text and "0x4000" in text
        import re
        assert re.search(r"service-[A-Z2-7]{8}-default/web/tcp/80", text)
        assert re.search(r"endpoint-[A-Z2-7]{8}-10\.88\.0\.5/", text)
        # endpoint-less service rejects via the no-endpoint map
        assert "10.96.0.11 . tcp . 443 : goto reject-chain" in text
        # ROUND TRIP: parsing the ruleset yields the same DNAT decisions
        rr = RestoredNftRules(text)
        assert sorted(rr.backends("10.96.0.10", 80)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        assert rr.backends("10.96.0.11", 443) == []
        assert sorted(rr.backends("203.0.113.1", 30080)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        # and the live resolve() agrees with the parsed rules
        got = {p.resolve("10.96.0.10", 80) for _ in range(50)}
        assert got == set(rr.backends("10.96.0.10", 80))
    finally:
        p.stop()


def test_nftables_and_iptables_backends_agree():
    """Both renderers must encode the SAME decision table: parse each back
    and compare backend sets for every (vip, port) — drift between the two
    dataplanes (or between render and semantics) fails here."""
    from kubernetes_tpu.proxy.nftables import RestoredNftRules
    from kubernetes_tpu.proxy.proxier import RestoredRules
    svcs = [{"kind": "Service", "metadata": {"name": f"s{i}"},
             "spec": {"clusterIP": f"10.96.1.{i}",
                      "ports": [{"port": 80 + i, "protocol": "TCP"}]}}
            for i in range(4)]
    eps = [{"kind": "Endpoints", "metadata": {"name": f"s{i}"},
            "subsets": [{"addresses": [{"ip": f"10.88.1.{10*i + j}"}
                                       for j in range(i)],  # s0 has none
                         "ports": [{"port": 9000 + i}]}]}
           for i in range(4)]
    ipt = _mk_proxier_with(svcs, eps)
    nft = _mk_nft_proxier_with(svcs, eps)
    try:
        rr_ipt = RestoredRules(ipt.sync_proxy_rules_text())
        rr_nft = RestoredNftRules(nft.sync_nft_text())
        for i in range(4):
            a = sorted(rr_ipt.backends(f"10.96.1.{i}", 80 + i))
            b = sorted(rr_nft.backends(f"10.96.1.{i}", 80 + i))
            assert a == b, (i, a, b)
        assert rr_nft.backends("10.96.1.0", 80) == []  # no endpoints
    finally:
        ipt.stop()
        nft.stop()


# ------------------------------------------------------ ipvs backend render

def _mk_ipvs_proxier_with(services, endpoints):
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.proxy.ipvs import IpvsProxier
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    for s in services:
        client.resource("services", s["metadata"].get("namespace",
                                                      "default")).create(s)
    for e in endpoints:
        client.resource("endpoints", e["metadata"].get("namespace",
                                                       "default")).create(e)
    return IpvsProxier(client).start()


def test_ipvs_payload_structure_and_roundtrip():
    from kubernetes_tpu.proxy.ipvs import RestoredIpvsRules
    p = _mk_ipvs_proxier_with(
        [{"kind": "Service", "metadata": {"name": "web"},
          "spec": {"clusterIP": "10.96.0.10",
                   "sessionAffinity": "ClientIP",
                   "ports": [{"port": 80, "protocol": "TCP",
                              "nodePort": 30080}]}},
         {"kind": "Service", "metadata": {"name": "empty"},
          "spec": {"clusterIP": "10.96.0.11",
                   "ports": [{"port": 443, "protocol": "TCP"}]}}],
        [{"kind": "Endpoints", "metadata": {"name": "web"},
          "subsets": [{"addresses": [{"ip": "10.88.0.5"},
                                     {"ip": "10.88.0.6"}],
                       "ports": [{"port": 8080}]}]}])
    try:
        text = p.sync_ipvs_text()
        # VIPs on the dummy interface; virtual + real servers; source-hash
        # scheduler with persistence for ClientIP affinity
        assert "ip addr add 10.96.0.10/32 dev kube-ipvs0" in text
        assert "-A -t 10.96.0.10:80 -s sh -p 10800" in text
        assert "-a -t 10.96.0.10:80 -r 10.88.0.5:8080 -m -w 1" in text
        assert "-A -t 0.0.0.0:30080" in text          # nodePort vserver
        assert "-A -t 10.96.0.11:443" in text          # empty: vserver only
        assert "-a -t 10.96.0.11:443" not in text      # ...no real servers
        rr = RestoredIpvsRules(text)
        assert sorted(rr.backends("10.96.0.10", 80)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        assert rr.backends("10.96.0.11", 443) == []
        assert sorted(rr.backends("203.0.113.1", 30080)) == \
            ["10.88.0.5:8080", "10.88.0.6:8080"]
        got = {p.resolve("10.96.0.10", 80, client_ip="1.2.3.4")}
        assert got <= set(rr.backends("10.96.0.10", 80))
    finally:
        p.stop()


def test_all_three_backends_agree():
    """iptables, nftables, and ipvs renderers must encode the SAME decision
    table for identical cluster state."""
    from kubernetes_tpu.proxy.ipvs import RestoredIpvsRules
    from kubernetes_tpu.proxy.nftables import RestoredNftRules
    from kubernetes_tpu.proxy.proxier import RestoredRules
    svcs = [{"kind": "Service", "metadata": {"name": f"s{i}"},
             "spec": {"clusterIP": f"10.96.2.{i}",
                      "ports": [{"port": 80 + i, "protocol": "TCP"}]}}
            for i in range(3)]
    eps = [{"kind": "Endpoints", "metadata": {"name": f"s{i}"},
            "subsets": [{"addresses": [{"ip": f"10.88.2.{10*i + j}"}
                                       for j in range(i)],
                         "ports": [{"port": 9000 + i}]}]}
           for i in range(3)]
    ipt = _mk_proxier_with(svcs, eps)
    nft = _mk_nft_proxier_with(svcs, eps)
    ipv = _mk_ipvs_proxier_with(svcs, eps)
    try:
        a = RestoredRules(ipt.sync_proxy_rules_text())
        b = RestoredNftRules(nft.sync_nft_text())
        c = RestoredIpvsRules(ipv.sync_ipvs_text())
        for i in range(3):
            key = (f"10.96.2.{i}", 80 + i)
            assert sorted(a.backends(*key)) == sorted(b.backends(*key)) \
                == sorted(c.backends(*key)), key
    finally:
        ipt.stop()
        nft.stop()
        ipv.stop()
