"""Sharded watch fan-out with bounded per-watcher queues.

Reference role: the watch cache's per-watcher channel budget
(``cacher.go``) — a slow consumer is force-disconnected and relists,
instead of growing an unbounded queue that stalls every sibling. The
shard structure keeps watcher churn (register/drop at 10k-client scale)
off the store's write lock.
"""

import pytest

from kubernetes_tpu.metrics.registry import WATCH_DROPS
from kubernetes_tpu.store.store import (WATCH_QUEUE_MAX, WATCH_SHARDS,
                                        ObjectStore, TooOld)

pytestmark = pytest.mark.watchstorm


def _cm(name, v="1"):
    return {"kind": "ConfigMap", "metadata": {"name": name},
            "data": {"v": v}}


def _flood(store, n, start=0):
    for i in range(start, start + n):
        store.create("ConfigMap", _cm(f"c{i}"))


def test_events_flow_through_shards_in_order():
    store = ObjectStore()
    w = store.watch("ConfigMap", since_rv=0)
    _flood(store, 20)
    rvs = []
    for _ in range(20):
        ev = w.get(timeout=2.0)
        assert ev is not None
        rvs.append(ev.resource_version)
    assert rvs == sorted(rvs)
    w.stop()


def test_watchers_spread_across_shards():
    store = ObjectStore()
    watchers = [store.watch("ConfigMap", since_rv=0)
                for _ in range(WATCH_SHARDS * 4)]
    occupancy = [shard.stats()[0] for shard in store._shards["ConfigMap"]]
    assert sum(occupancy) == WATCH_SHARDS * 4
    # round-robin placement: every shard carries its equal share
    assert occupancy == [4] * WATCH_SHARDS
    for w in watchers:
        w.stop()
    assert store.watch_stats()["watchersTotal"] == 0


def test_slow_watcher_evicted_with_counted_drop():
    """The stalled-consumer regression gate: a watcher that never drains
    overflows its bounded queue, is evicted with a counted drop, and the
    stream closes — while a healthy sibling keeps receiving every event."""
    store = ObjectStore()
    stalled = store.watch("ConfigMap", since_rv=0)
    healthy = store.watch("ConfigMap", since_rv=0)
    drops_before = WATCH_DROPS.get({"kind": "ConfigMap"})
    # fill both queues to EXACTLY the budget (no overflow yet) ...
    _flood(store, WATCH_QUEUE_MAX)
    seen = 0
    while healthy.get(timeout=0.2) is not None:
        seen += 1
    assert seen == WATCH_QUEUE_MAX
    # ... then one more event: the healthy (drained) watcher receives it,
    # the stalled (still-full) watcher tips over and is evicted
    _flood(store, 1, start=WATCH_QUEUE_MAX)
    ev = healthy.get(timeout=2.0)
    assert ev is not None
    assert ev.object["metadata"]["name"] == f"c{WATCH_QUEUE_MAX}"
    # the stalled watcher's stream is severed: ERROR surfaces as a
    # closed watcher (get -> None, closed=True), the relist signal
    drained = 0
    while stalled.get(timeout=0.2) is not None:
        drained += 1
    assert stalled.closed
    assert drained < WATCH_QUEUE_MAX  # queue was truncated, not delivered
    stats = store.watch_stats()
    assert stats["drops"]["ConfigMap"] == 1
    assert stats["dropsTotal"] == 1
    assert WATCH_DROPS.get({"kind": "ConfigMap"}) == drops_before + 1
    # the evicted watcher no longer occupies a shard slot
    assert stats["watchers"]["ConfigMap"] == 1
    healthy.stop()


def test_replay_backlog_beyond_queue_budget_is_too_old():
    """A watch() whose replay alone would overflow the bounded queue gets
    TooOld up front — the relist hands it the same state cheaper than a
    replay that immediately evicts it."""
    store = ObjectStore()
    _flood(store, WATCH_QUEUE_MAX + 10)
    with pytest.raises(TooOld):
        store.watch("ConfigMap", since_rv=0)
    # a caught-up watcher is fine
    _, rv = store.list("ConfigMap")
    w = store.watch("ConfigMap", since_rv=rv)
    store.create("ConfigMap", _cm("after"))
    ev = w.get(timeout=2.0)
    assert ev is not None and ev.object["metadata"]["name"] == "after"
    w.stop()


def test_fanout_span_accounting():
    store = ObjectStore()
    w = store.watch("ConfigMap", since_rv=0)
    _flood(store, 10)
    stats = store.watch_stats()
    assert stats["fanoutEvents"] >= 10
    assert stats["fanoutNs"] > 0
    w.stop()


def test_snapshot_install_severs_every_shard():
    store = ObjectStore()
    watchers = [store.watch("ConfigMap", since_rv=0) for _ in range(12)]
    _flood(store, 3)
    blob = store.snapshot_blob()
    store.load_snapshot_blob(blob)
    for w in watchers:
        while w.get(timeout=0.5) is not None:
            pass
        assert w.closed  # ERROR delivered -> consumer must relist
    assert store.watch_stats()["watchersTotal"] == 0
