"""Cluster autoscaler: hypothetical-node overlay, batched scale-up
simulation, expanders, scale-down re-placement proof, and the kubemark
end-to-end (gang scale-up from zero + idle reclaim).

Reference: ``kubernetes/autoscaler`` ClusterAutoscaler (simulator/,
expander/, core ScaleUp/ScaleDown).
"""

import io
import json
import time

import numpy as np
import pytest

from kubernetes_tpu.autoscaler.expander import (
    least_waste,
    most_pods,
    priority,
    random_expander,
)
from kubernetes_tpu.autoscaler.nodegroup import (
    NODE_GROUP_LABEL,
    NodeGroup,
    StaticNodeGroupProvider,
    load_node_group,
)
from kubernetes_tpu.autoscaler import simulator
from kubernetes_tpu.autoscaler.simulator import (
    simulate_scale_down,
    simulate_scale_up,
)
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.ops.filters import run_filters
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.autoscaler


def _group(name, min_size, max_size, caps, taints=(), labels=(), **kw):
    tpl = make_node(f"{name}-tpl").capacity(dict(caps))
    for k, v in dict(labels).items():
        tpl = tpl.label(k, v)
    for key, value, effect in taints:
        tpl = tpl.taint(key, value, effect)
    return NodeGroup(name, min_size, max_size, tpl.obj(), **kw)


def wait_for(pred, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------- overlay

def test_hypothetical_overlay_feasibility():
    """Template rows behave like real nodes under the filter pipeline:
    labels satisfy selectors, taints repel intolerant pods, capacity
    gates fit — and the real rows are untouched."""
    enc = SnapshotEncoder()
    real = make_node("real").capacity({"cpu": "1", "pods": "10"}).obj()
    blocker = make_pod("blocker").req({"cpu": "900m"}).node("real").obj()
    want_ssd = (make_pod("want-ssd").req({"cpu": "500m"})
                .node_selector({"disk": "ssd"}).obj())
    plain = make_pod("plain").req({"cpu": "500m"}).obj()
    pending = [want_ssd, plain]
    ct, meta = enc.encode_cluster([real], [blocker], pending_pods=pending,
                                  pending_slots=False)
    ssd = make_node("hypo-ssd").capacity({"cpu": "4", "pods": "10"}) \
        .label("disk", "ssd").obj()
    tainted = make_node("hypo-taint").capacity({"cpu": "4", "pods": "10"}) \
        .taint("dedicated", "infra").obj()
    ct2, rows = enc.with_hypothetical(ct, meta, [ssd, tainted])
    assert len(rows) == 2
    mask = np.asarray(run_filters(ct2, enc.encode_pods(pending, meta)))
    # real node is full: neither pending pod fits there
    assert not mask[0, 0] and not mask[1, 0]
    # ssd template: selector satisfied for want-ssd, plain also fits
    assert mask[0, rows[0]] and mask[1, rows[0]]
    # tainted template repels both (no toleration); selector also unmet
    assert not mask[0, rows[1]] and not mask[1, rows[1]]


def test_overlay_empty_is_identity():
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(
        [make_node("n").capacity({"cpu": "1"}).obj()], [])
    ct2, rows = enc.with_hypothetical(ct, meta, [])
    assert rows == [] and ct2 is ct


# ----------------------------------------------------- batched simulation

def test_scale_up_is_one_batched_evaluation(monkeypatch):
    """Acceptance: K candidate groups evaluate via ONE run_filters call
    over the hypothetical-node overlay, not K sequential passes."""
    calls = {"n": 0}
    real_run_filters = simulator.run_filters

    def counting(ct, pb, enabled=None):
        calls["n"] += 1
        return real_run_filters(ct, pb, enabled)

    monkeypatch.setattr(simulator, "run_filters", counting)
    nodes = [make_node("full").capacity({"cpu": "1", "pods": "10"}).obj()]
    bound = [make_pod("b").req({"cpu": "900m"}).node("full").obj()]
    pending = [make_pod(f"p{i}").req({"cpu": "600m"}).obj()
               for i in range(6)]
    groups = [_group("g-small", 0, 10, {"cpu": "1", "pods": "10"}),
              _group("g-med", 0, 10, {"cpu": "2", "pods": "10"}),
              _group("g-big", 0, 10, {"cpu": "8", "pods": "10"})]
    options = simulate_scale_up(nodes, bound, pending, groups)
    assert calls["n"] == 1, "candidate evaluation must be one batched call"
    by_name = {o.group.name: o for o in options}
    assert by_name["g-small"].pods_placed == 6
    assert by_name["g-small"].nodes_needed == 6   # one 600m pod per 1-cpu
    assert by_name["g-med"].nodes_needed == 2     # three per 2-cpu node
    assert by_name["g-big"].nodes_needed == 1


def test_scale_up_skips_pods_that_fit_existing_nodes():
    nodes = [make_node("roomy").capacity({"cpu": "4", "pods": "10"}).obj()]
    pending = [make_pod("p").req({"cpu": "500m"}).obj()]
    options = simulate_scale_up(nodes, [], pending,
                                [_group("g", 0, 5, {"cpu": "8"})])
    assert options == []  # the scheduler just hasn't reached it yet


def test_scale_up_headroom_caps_expansion():
    nodes = [make_node("full").capacity({"cpu": "1", "pods": "10"}).obj()]
    bound = [make_pod("b").req({"cpu": "1"}).node("full").obj()]
    pending = [make_pod(f"p{i}").req({"cpu": "900m"}).obj()
               for i in range(5)]
    g = _group("g", 0, 5, {"cpu": "1", "pods": "10"})
    options = simulate_scale_up(nodes, bound, pending, [g],
                                headroom={"g": 2})
    assert len(options) == 1
    assert options[0].nodes_needed == 2 and options[0].pods_placed == 2


def test_scale_up_respects_template_taints_and_selectors():
    nodes = [make_node("full").capacity({"cpu": "1", "pods": "10"}).obj()]
    bound = [make_pod("b").req({"cpu": "1"}).node("full").obj()]
    tolerant = (make_pod("tol").req({"cpu": "500m"})
                .toleration("dedicated", "Equal", "infra", "NoSchedule")
                .obj())
    intolerant = make_pod("plain").req({"cpu": "500m"}).obj()
    g = _group("dedicated", 0, 5, {"cpu": "8", "pods": "10"},
               taints=[("dedicated", "infra", "NoSchedule")])
    options = simulate_scale_up(nodes, bound, [tolerant, intolerant], [g])
    assert len(options) == 1
    assert options[0].pod_indices == [0]  # only the tolerating pod


# -------------------------------------------------------------- expanders

def _opt(name, waste, placed, nodes_needed=1, prio=0):
    from kubernetes_tpu.autoscaler.simulator import ScaleUpOption
    return ScaleUpOption(
        group=_group(name, 0, 10, {"cpu": "1"}, priority=prio),
        pod_indices=list(range(placed)), nodes_needed=nodes_needed,
        waste=waste)


def test_expanders():
    a = _opt("a", waste=0.8, placed=4, nodes_needed=4)
    b = _opt("b", waste=0.2, placed=4, nodes_needed=1)
    c = _opt("c", waste=0.5, placed=6, nodes_needed=2, prio=7)
    assert least_waste([a, b, c]).group.name == "b"
    assert most_pods([a, b, c]).group.name == "c"
    assert priority([a, b, c]).group.name == "c"
    assert random_expander([a, b, c], seed=0) is not None
    assert least_waste([]) is None
    # deterministic tie-break from the seed
    tie1, tie2 = _opt("t1", 0.5, 3), _opt("t2", 0.5, 3)
    picks = {random_expander([tie1, tie2], seed=s).group.name
             for s in range(8)}
    assert picks == {"t1", "t2"}


# ------------------------------------------------------------- scale-down

def _three_nodes():
    caps = {"cpu": "4", "memory": "8Gi", "pods": "10"}
    return [make_node(n).capacity(caps).obj() for n in ("m0", "m1", "m2")]


def test_scale_down_replacement_proof():
    """An idle-ish node drains only when every resident provably fits
    elsewhere; a resident that fits nowhere else blocks its node."""
    nodes = _three_nodes()
    bound = [make_pod("r0").req({"cpu": "500m"}).node("m1").obj(),
             make_pod("r1").req({"cpu": "3500m"}).node("m2").obj(),
             make_pod("r2").req({"cpu": "3"}).node("m0").obj()]
    plan = simulate_scale_down(nodes, bound, ["m1", "m2"],
                               utilization_threshold=0.95)
    assert plan.removable == ["m1"]
    assert plan.placements["m1"] == [("default/r0", "m0")]
    assert "fits nowhere else" in plan.blocked["m2"]


def test_scale_down_utilization_gate():
    nodes = _three_nodes()
    bound = [make_pod("busy").req({"cpu": "3"}).node("m1").obj()]
    plan = simulate_scale_down(nodes, bound, ["m1"],
                               utilization_threshold=0.5)
    assert plan.removable == []
    assert "utilization" in plan.blocked["m1"]


def test_scale_down_shared_ledger_no_double_booking():
    """Two candidates' residents must not both claim the same free room."""
    caps = {"cpu": "4", "pods": "10"}
    nodes = [make_node(n).capacity(caps).obj() for n in ("a", "b", "t")]
    bound = [make_pod("pa").req({"cpu": "3"}).node("a").obj(),
             make_pod("pb").req({"cpu": "3"}).node("b").obj()]
    plan = simulate_scale_down(nodes, bound, ["a", "b"],
                               utilization_threshold=0.95)
    # target t has 4 cpu: holds one 3-cpu re-placement, not two
    assert plan.removable == ["a"]
    assert "pb" in plan.blocked["b"]


def test_scale_down_pdb_blocks_eviction():
    nodes = _three_nodes()
    pod = make_pod("guarded").req({"cpu": "100m"}).node("m1") \
        .label("app", "web").obj()
    pod_dict = pod.to_dict()
    pod_dict.setdefault("status", {})["phase"] = "Running"
    pod_dict["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
    pdb = {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
           "metadata": {"name": "web-pdb", "namespace": "default"},
           "spec": {"minAvailable": 1,
                    "selector": {"matchLabels": {"app": "web"}}}}
    plan = simulate_scale_down(nodes, [pod], ["m1"],
                               utilization_threshold=0.95,
                               pdbs=[pdb], all_pod_dicts=[pod_dict])
    assert plan.removable == []
    assert "PDB" in plan.blocked["m1"]


def test_scale_down_pdb_budget_charges_across_evictions():
    """A budget with ONE disruption left must not approve TWO evictions:
    the simulation charges the allowance per approved pod instead of
    re-reading the same static status."""
    nodes = _three_nodes()
    pods = [make_pod(f"web-{i}").req({"cpu": "100m"}).node("m1")
            .label("app", "web").obj() for i in range(2)]
    dicts = []
    for p in pods:
        d = p.to_dict()
        d.setdefault("status", {})["phase"] = "Running"
        d["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
        dicts.append(d)
    pdb = {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
           "metadata": {"name": "web-pdb", "namespace": "default"},
           "spec": {"minAvailable": 1,
                    "selector": {"matchLabels": {"app": "web"}}}}
    # 2 healthy, minAvailable 1 -> disruptionsAllowed = 1: first eviction
    # passes, the second must trip the charged budget
    plan = simulate_scale_down(nodes, pods, ["m1"],
                               utilization_threshold=0.95,
                               pdbs=[pdb], all_pod_dicts=dicts)
    assert plan.removable == []
    assert "PDB" in plan.blocked["m1"]


def test_scale_down_ignores_daemonset_and_mirror_pods():
    """Daemon/mirror pods need no re-placement proof — the drain skips
    them, so the simulation must too."""
    nodes = _three_nodes()
    ds_pod = make_pod("ds-x").req({"cpu": "100m"}).node("m1").obj()
    ds_pod.metadata.owner_references.append(
        {"kind": "DaemonSet", "name": "ds"})
    mirror = make_pod("mirror-x").req({"cpu": "100m"}).node("m1").obj()
    mirror.metadata.annotations["kubernetes.io/config.mirror"] = "abc"
    plan = simulate_scale_down(nodes, [ds_pod, mirror], ["m1"],
                               utilization_threshold=0.95)
    assert plan.removable == ["m1"]
    assert plan.placements["m1"] == []


def test_hollow_node_registers_template_taints():
    """Template fidelity: the node the provider registers must carry the
    taints the scale-up simulation evaluated."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.store.store import ObjectStore
    kl = Kubelet(DirectClient(ObjectStore()), "t0",
                 taints=[{"key": "dedicated", "value": "infra",
                          "effect": "NoSchedule"}],
                 register_node=False)
    obj = kl._node_object()
    assert obj["spec"]["taints"] == [{"key": "dedicated", "value": "infra",
                                      "effect": "NoSchedule"}]


def test_scale_down_reclaims_multiple_nodes_to_min_in_one_pass():
    """Live min-size accounting: with min_size=1 and three idle nodes, one
    reconcile reclaims two and stops exactly at the floor."""
    from kubernetes_tpu.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    provider = StaticNodeGroupProvider(
        client, [_group("idle-pool", 1, 5, {"cpu": "2", "pods": "10"})])
    provider.scale_up("idle-pool", 3)
    ca = ClusterAutoscaler(client, provider, scale_down_unneeded_s=0.0)
    summary = ca.run_once()
    assert len(summary["scaled_down"]) == 2
    assert provider.target_size("idle-pool") == 1
    assert len(client.nodes().list()) == 1


# ----------------------------------------------- config sanity + loading

def test_check_node_groups_fails_fast():
    from kubernetes_tpu.utils.sanity import check_node_groups
    bad = _group("bad", 5, 2, {"cpu": "1"})
    no_alloc = NodeGroup("empty", 0, 1, make_node("t").obj())
    dup = _group("bad", 0, 1, {"cpu": "1"})
    problems = check_node_groups([bad, no_alloc, dup])
    assert any("min_size 5 > max_size 2" in p for p in problems)
    assert any("no allocatable" in p for p in problems)
    assert any("duplicate" in p for p in problems)
    assert check_node_groups([_group("ok", 0, 3, {"cpu": "1"})]) == []


def test_autoscaler_rejects_bad_groups_at_construction():
    from kubernetes_tpu.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    provider = StaticNodeGroupProvider(
        client, [_group("bad", 9, 1, {"cpu": "1"})])
    with pytest.raises(ValueError, match="min_size 9 > max_size 1"):
        ClusterAutoscaler(client, provider)
    with pytest.raises(ValueError, match="unknown expander"):
        ClusterAutoscaler(
            client, StaticNodeGroupProvider(
                client, [_group("ok", 0, 1, {"cpu": "1"})]),
            expander="does-not-exist")


def test_load_node_group_yaml():
    import os
    import yaml
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "config", "templates",
        "node-group-default.yaml")
    with open(path) as f:
        g = load_node_group(yaml.safe_load(f))
    assert g.name == "perf-group" and g.min_size == 0 and g.max_size == 50
    assert g.template.status.allocatable["cpu"] == "32"
    stamped = g.template_node("perf-group-0")
    assert stamped.metadata.labels[NODE_GROUP_LABEL] == "perf-group"
    from kubernetes_tpu.utils.sanity import check_node_groups
    assert check_node_groups([g]) == []


# --------------------------------------------- queue + cache observability

def test_queue_unschedulable_pods_snapshot():
    from kubernetes_tpu.sched.queue import SchedulingQueue
    q = SchedulingQueue()
    q.park_unschedulable(make_pod("u1").obj(), attempts=1)
    q.park_unschedulable(make_pod("u2").obj(), attempts=2)
    names = {p.metadata.name for p in q.unschedulable_pods()}
    assert names == {"u1", "u2"}
    q.close()


def test_cache_exports_generation_and_full_encode_gauges():
    from kubernetes_tpu.metrics.registry import (
        CACHE_FULL_ENCODES,
        CACHE_GENERATION,
    )
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    cache.add_node(make_node("n1").capacity({"cpu": "1"}).obj())
    cache.snapshot()
    g1 = CACHE_GENERATION.get()
    e1 = CACHE_FULL_ENCODES.get()
    assert cache.stats()["full_encodes"] >= 1
    cache.add_node(make_node("n2").capacity({"cpu": "1"}).obj())
    cache.snapshot()
    assert CACHE_GENERATION.get() > g1
    assert CACHE_FULL_ENCODES.get() == e1 + 1
    # clean snapshot: generation gauge stays, no new full encode
    cache.snapshot()
    assert CACHE_FULL_ENCODES.get() == e1 + 1


# ----------------------------------------------------- HPA with FakeClock

def test_hpa_stabilization_with_fake_clock():
    """Satellite: the HPA scale-down stabilization window advances by
    FakeClock, not wall time — the HPA/autoscaler interplay is testable
    deterministically."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.controllers import HorizontalPodAutoscalerController
    from kubernetes_tpu.controllers.hpa import USAGE_ANNOTATION
    from kubernetes_tpu.store.store import ObjectStore
    from kubernetes_tpu.utils.clock import FakeClock

    client = DirectClient(ObjectStore())
    clock = FakeClock(1000.0)
    ctrl = HorizontalPodAutoscalerController(
        client, downscale_stabilization_s=300.0, clock=clock)
    ctrl.tick_interval = 0.1
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.resource("deployments").create({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [{"name": "c"}]}}}})
        for i in range(3):
            p = make_pod(f"w{i}").label("app", "web") \
                .req({"cpu": "1"}).obj().to_dict()
            p["metadata"].setdefault("annotations", {})[
                USAGE_ANNOTATION] = "100m"   # 10% used vs 50% target
            p["spec"]["nodeName"] = "n1"
            p["status"] = {"phase": "Running"}
            client.pods().create(p)
        client.resource("horizontalpodautoscalers").create({
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "web"},
                     "minReplicas": 1, "maxReplicas": 5,
                     "metrics": [{"type": "Resource", "resource": {
                         "name": "cpu", "target": {
                             "type": "Utilization",
                             "averageUtilization": 50}}}]}})
        time.sleep(1.0)  # several ticks inside the (frozen) window
        assert client.resource("deployments").get("web")["spec"][
            "replicas"] == 3
        clock.advance(301.0)  # window elapses instantly
        assert wait_for(lambda: client.resource("deployments")
                        .get("web")["spec"]["replicas"] == 1, 10.0)
    finally:
        ctrl.stop()
        factory.stop_all()


# ---------------------------------------------------------- CLI + status

def test_ktpu_autoscale_status():
    from kubernetes_tpu.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.store.apiserver import APIServer

    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        provider = StaticNodeGroupProvider(
            client, [_group("cli-group", 0, 4, {"cpu": "2"})])
        ca = ClusterAutoscaler(client, provider)
        ca.run_once()   # publishes the status ConfigMap
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "autoscale", "status"],
                       out=out)
        assert rc == 0
        text = out.getvalue()
        assert "cli-group" in text and "ready" in text
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "autoscale", "status",
                          "-o", "json"], out=out) == 0
        st = json.loads(out.getvalue())
        assert st["groups"]["cli-group"]["maxSize"] == 4
    finally:
        server.stop()


# ------------------------------------------------------------ end-to-end

def test_gang_scale_up_and_idle_scale_down_e2e():
    """Acceptance: an unschedulable gang on an empty cluster provisions a
    node group from zero (hollow kubelets through the apiserver), every
    member binds, the unschedulable set drains to zero; deleting most of
    the gang lets scale-down reclaim idle nodes — but never a node whose
    resident the tensor simulation cannot re-place."""
    from kubernetes_tpu.autoscaler import (
        ClusterAutoscaler,
        HollowNodeGroupProvider,
    )
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer

    server = APIServer().start()
    provider = runner = None
    try:
        client = HTTPClient(server.url)
        provider = HollowNodeGroupProvider(
            HTTPClient(server.url),
            [_group("gang-pool", 0, 4, {"cpu": "2", "memory": "4Gi",
                                        "pods": "110"})],
            heartbeat_period=1.0)
        runner = SchedulerRunner(
            HTTPClient(server.url),
            SchedulerConfiguration(backoff_initial_s=0.05,
                                   backoff_max_s=0.2))
        runner.start()
        ca = ClusterAutoscaler(HTTPClient(server.url), provider,
                               scale_down_unneeded_s=0.0)

        gang = [make_pod(f"gang-{i}").label("gang", "g1")
                .req({"cpu": "500m"}).obj().to_dict() for i in range(8)]
        client.pods("default").create_many(gang)
        pods = client.pods("default")

        def all_bound():
            ca.run_once()
            return all(p["spec"].get("nodeName") for p in pods.list())

        assert wait_for(all_bound, 60.0, interval=0.3), [
            (p["metadata"]["name"], p["spec"].get("nodeName"))
            for p in pods.list()]
        # 8 pods x 500m on 2-cpu nodes -> 2 nodes provisioned, gang bound
        assert provider.target_size("gang-pool") == 2
        assert wait_for(lambda: runner.queue.stats()["unschedulable"] == 0,
                        10.0)

        # pods actually run on the hollow kubelets
        assert wait_for(lambda: sum(
            1 for p in pods.list()
            if (p.get("status") or {}).get("phase") == "Running") == 8,
            30.0)

        # keep one PDB-guarded resident: its node can never drain (the
        # simulation must refuse the eviction), the now-idle node can
        survivor_node = pods.get("gang-0")["spec"]["nodeName"]
        client.resource("poddisruptionbudgets", "default").create({
            "apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
            "metadata": {"name": "gang-pdb", "namespace": "default"},
            "spec": {"minAvailable": 1,
                     "selector": {"matchLabels": {"gang": "g1"}}}})
        for i in range(1, 8):
            pods.delete(f"gang-{i}")

        def reclaimed():
            ca.run_once()
            return provider.target_size("gang-pool") == 1

        assert wait_for(reclaimed, 30.0, interval=0.3)
        assert pods.get("gang-0")["spec"]["nodeName"] == survivor_node
        node_names = {n["metadata"]["name"]
                      for n in client.nodes().list()}
        assert survivor_node in node_names and len(node_names) == 1
        st = ca.status()
        assert st["lastScaleUp"]["group"] == "gang-pool"
        assert st["lastScaleDown"]["group"] == "gang-pool"
    finally:
        if runner is not None:
            runner.stop()
        if provider is not None:
            provider.stop()
        server.stop()
