"""Churn under measurement — the scheduler_perf ``churn`` op through the
connected product path.

Reference: ``test/integration/scheduler_perf/scheduler_perf.go`` (churnOp,
Recreate mode): nodes and short-lived pods recycle through the API during
the measured window. The properties under test: every measured pod still
binds (no pod lost to a drain-context invalidation race), and the churn
actually exercised the API (node add/remove events hit the resident
encoding's invalidate-and-rebuild path, scheduler.py _schedule_drain).
"""

import pytest


def test_connected_churn_loses_no_pods():
    from benchmarks.connected import run_connected
    out = run_connected(n_pods=200, n_nodes=60, batch_size=64,
                        drain_batches=2, churn=True, timeout=240.0)
    assert out["case"] == "ConnectedChurn"
    # every measured pod bound despite node add/remove churn (a degraded
    # bench watcher is tolerated — it falls back to polling the store; the
    # scheduler's own informers are what's under test)
    assert out["bound"] == 200, out
    # the churn loop really ran API mutations — and met its fixed op
    # budget even though the measured drain finishes in ~a second (the
    # budget is decoupled from drain duration)
    assert out["churn_api_ops"] >= 500, out


def test_churn_opcode_routes_to_connected():
    """The YAML churn opcode must route through the connected harness and
    report in the scheduler_perf result shape."""
    from benchmarks.scheduler_perf import load_config, run_workload
    cases = load_config()
    churn_case = next(c for c in cases if c["name"] == "SchedulingChurn")
    assert any(op["opcode"] == "churn"
               for op in churn_case["workloadTemplate"])
    wl = dict(churn_case["workloads"][0])
    # shrink to test size: scale applies to counts and thresholds alike
    res = run_workload(churn_case, wl, scale=0.06, batch=64)
    assert res["connected"] is True
    assert res["scheduled"] == res["pods"]
    assert res["churn_api_ops"] > 0
    assert res["passed"], res
