"""Out-of-tree framework plugins: tensor Filter/Score extensions traced into
the gang program, and host-side Permit/PreBind/PostBind/Unreserve hooks on
the binding cycle (sched/framework.py — pkg/scheduler/framework Registry
analog)."""

import time

import jax.numpy as jnp
import pytest

from kubernetes_tpu.config.types import Profile, SchedulerConfiguration
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.framework import (
    ALLOW,
    DENY,
    WAIT,
    LifecyclePlugin,
    Registry,
    TensorPlugin,
)
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def build(cfg=None, registry=None, n_nodes=3, cpu="8"):
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i}")
                       .capacity({"cpu": cpu, "pods": "10"}).obj())
    queue = SchedulingQueue()
    bound = {}
    sched = Scheduler(cfg or SchedulerConfiguration(), cache, queue,
                      binder=lambda p, n: bound.setdefault(p.key, n) or True,
                      registry=registry)
    return sched, queue, bound


# ---------------------------------------------------------- tensor plugins

def test_custom_filter_plugin_vetoes_nodes():
    """A registered filter runs inside the jitted program: only node 1
    survives (everything else vetoed by index)."""
    reg = Registry().register(TensorPlugin(
        name="OnlyNodeOne",
        filter_fn=lambda ct, pb, tk: (
            jnp.arange(ct.node_valid.shape[0]) == 1)[None, :]
            | jnp.zeros((pb.pod_valid.shape[0], 1), bool)))
    sched, queue, bound = build(registry=reg)
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    queue.add(pod)
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert bound.get("default/p0") == "n1", bound


def test_custom_score_plugin_steers():
    reg = Registry().register(TensorPlugin(
        name="PreferNodeTwo", weight=1000.0,
        score_fn=lambda ct, pb, tk: (
            (jnp.arange(ct.node_valid.shape[0]) == 2).astype(jnp.float32)
            [None, :] * jnp.ones((pb.pod_valid.shape[0], 1), jnp.float32))))
    sched, queue, bound = build(registry=reg)
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    queue.add(pod)
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert bound.get("default/p0") == "n2", bound


def test_profile_opt_out_disables_plugin():
    reg = Registry().register(TensorPlugin(
        name="VetoEverything",
        filter_fn=lambda ct, pb, tk: jnp.zeros(
            (pb.pod_valid.shape[0], ct.node_valid.shape[0]), bool)))
    cfg = SchedulerConfiguration(profiles=[Profile(out_of_tree=[])])
    sched, queue, bound = build(cfg=cfg, registry=reg)
    pod = make_pod("p0").req({"cpu": "1"}).obj()
    queue.add(pod)
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert bound, "opted-out plugin must not veto"
    # and with the plugin enabled (default profile), nothing binds
    reg2 = Registry().register(TensorPlugin(
        name="VetoEverything",
        filter_fn=lambda ct, pb, tk: jnp.zeros(
            (pb.pod_valid.shape[0], ct.node_valid.shape[0]), bool)))
    sched2, queue2, bound2 = build(registry=reg2)
    queue2.add(make_pod("p1").req({"cpu": "1"}).obj())
    sched2.run_once(wait=0.1)
    sched2.wait_for_bindings()
    assert not bound2


def test_registry_rejects_duplicates():
    reg = Registry().register(TensorPlugin(name="X"))
    with pytest.raises(ValueError):
        reg.register(TensorPlugin(name="X"))
    reg.register(LifecyclePlugin(name="X"))  # separate namespace is fine


def test_registry_rejects_in_tree_names():
    with pytest.raises(ValueError):
        Registry().register(TensorPlugin(name="PodTopologySpread"))
    with pytest.raises(ValueError):
        Registry().register(TensorPlugin(name="TaintToleration"))


def test_unknown_profile_plugin_name_fails_fast():
    cfg = SchedulerConfiguration(profiles=[Profile(out_of_tree=["Typo"])])
    with pytest.raises(ValueError):
        build(cfg=cfg, registry=Registry())


def test_profile_score_weight_zero_disables_plugin():
    """A profile's scoreWeights override — including disable(0) — beats the
    plugin's own weight."""
    reg = Registry().register(TensorPlugin(
        name="PreferNodeTwo", weight=1000.0,
        score_fn=lambda ct, pb, tk: (
            (jnp.arange(ct.node_valid.shape[0]) == 2).astype(jnp.float32)
            [None, :] * jnp.ones((pb.pod_valid.shape[0], 1), jnp.float32))))
    cfg = SchedulerConfiguration(profiles=[
        Profile(score_weights={"PreferNodeTwo": 0.0})])
    sched, queue, bound = build(cfg=cfg, registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    # without the steering score all nodes tie; the tie-break picks n0 here
    assert bound.get("default/p0") != "n2", bound


# ------------------------------------------------------- lifecycle plugins

def test_permit_deny_blocks_binding():
    reg = Registry().register(LifecyclePlugin(
        name="Gatekeeper", permit=lambda pod, node: DENY))
    sched, queue, bound = build(registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert not bound  # denied at Permit; pod requeued, never bound


def test_permit_wait_then_allow():
    state = {"ready": False}

    def permit(pod, node):
        return ALLOW if state["ready"] else (WAIT, 0.05)
    reg = Registry().register(LifecyclePlugin(name="Warmup", permit=permit))
    sched, queue, bound = build(registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    time.sleep(0.2)
    state["ready"] = True
    sched.wait_for_bindings(timeout=10.0)
    assert bound.get("default/p0")


def test_pre_bind_failure_unreserves():
    calls = []
    reg = Registry().register(LifecyclePlugin(
        name="SideEffect",
        pre_bind=lambda pod, node: calls.append(("pre", pod.metadata.name))
        or True,
        unreserve=lambda pod, node: calls.append(("undo", pod.metadata.name)),
    )).register(LifecyclePlugin(
        name="ZFailer", pre_bind=lambda pod, node: False))  # sorts LAST
    sched, queue, bound = build(registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert not bound
    # ZFailer sorts after SideEffect: SideEffect's pre_bind ran, then rolled
    # back via its unreserve
    assert ("pre", "p0") in calls and ("undo", "p0") in calls


def test_permit_allowed_joins_unreserve_rollback():
    """A permit-only plugin that allowed gets unreserved when the BIND
    itself fails (the reservation-at-permit pattern)."""
    calls = []
    reg = Registry().register(LifecyclePlugin(
        name="Reserver",
        permit=lambda pod, node: calls.append("permit") or ALLOW,
        unreserve=lambda pod, node: calls.append("undo")))
    cache = SchedulerCache()
    cache.add_node(make_node("n0").capacity({"cpu": "8", "pods": "10"}).obj())
    queue = SchedulingQueue()
    sched = Scheduler(SchedulerConfiguration(), cache, queue,
                      binder=lambda p, n: False,  # bind always fails
                      registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert calls == ["permit", "undo"]


def test_profile_opt_out_disables_lifecycle_plugin():
    reg = Registry().register(LifecyclePlugin(
        name="Gatekeeper", permit=lambda pod, node: DENY))
    cfg = SchedulerConfiguration(profiles=[Profile(out_of_tree=[])])
    sched, queue, bound = build(cfg=cfg, registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert bound  # opted-out permit must not block binding


def test_post_bind_notified():
    seen = []
    reg = Registry().register(LifecyclePlugin(
        name="Notify",
        post_bind=lambda pod, node: seen.append((pod.metadata.name, node))))
    sched, queue, bound = build(registry=reg)
    queue.add(make_pod("p0").req({"cpu": "1"}).obj())
    sched.run_once(wait=0.1)
    sched.wait_for_bindings()
    assert bound and seen and seen[0][0] == "p0"
