"""Event recording (record.EventRecorder analog) + describe integration."""

import io
import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.utils.events import EventRecorder, events_for


def pod_obj(name="p0"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default", "uid": "u1"}}


def test_recorder_writes_and_aggregates():
    # FakeClock pins every timestamp and the aggregation window: whether the
    # two FailedScheduling events aggregate is decided by fake time the test
    # controls, never by how long the sink thread took on a loaded runner
    from kubernetes_tpu.utils.clock import FakeClock
    clock = FakeClock(1000.0)
    client = DirectClient(ObjectStore())
    rec = EventRecorder(client, "test-component", clock=clock)
    rec.event(pod_obj(), "Warning", "FailedScheduling", "no nodes")
    clock.advance(1.0)  # inside the window: must aggregate, not duplicate
    rec.event(pod_obj(), "Warning", "FailedScheduling", "no nodes")
    clock.advance(1.0)
    rec.event(pod_obj(), "Normal", "Scheduled", "assigned to n0")
    rec.flush()  # recording is async (broadcaster-style); settle before reading
    evs = events_for(client, "default", "p0")
    by_reason = {e["reason"]: e for e in evs}
    assert by_reason["FailedScheduling"]["count"] == 2  # aggregated
    assert by_reason["Scheduled"]["count"] == 1
    assert by_reason["Scheduled"]["source"]["component"] == "test-component"
    assert by_reason["FailedScheduling"]["involvedObject"]["name"] == "p0"
    assert by_reason["FailedScheduling"]["firstTimestamp"] == 1000.0
    assert by_reason["FailedScheduling"]["lastTimestamp"] == 1001.0


def test_scheduler_emits_scheduling_events():
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        # tight backoff: with the defaults, the stuck pod can sit out a
        # multi-second exponential backoff right when the node appears,
        # racing the Scheduled-event deadline on a loaded runner
        runner = SchedulerRunner(client, SchedulerConfiguration(
            backoff_initial_s=0.05, backoff_max_s=0.2))
        runner.start()
        # unschedulable pod (no nodes) -> FailedScheduling event
        client.pods().create({"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "stuck",
                                           "namespace": "default"},
                              "spec": {"containers": [{"name": "c"}]}})
        deadline = time.time() + 15
        while time.time() < deadline and not events_for(client, "default",
                                                        "stuck"):
            time.sleep(0.2)
        evs = events_for(client, "default", "stuck")
        # then a node appears -> Scheduled event
        client.nodes().create({"apiVersion": "v1", "kind": "Node",
                               "metadata": {"name": "n0"},
                               "status": {"allocatable": {"cpu": "4",
                                                          "pods": "10"}}})
        deadline = time.time() + 15
        ok = False
        while time.time() < deadline and not ok:
            ok = any(e["reason"] == "Scheduled"
                     for e in events_for(client, "default", "stuck"))
            time.sleep(0.2)
        assert ok, events_for(client, "default", "stuck")
        runner.stop()
    finally:
        server.stop()


def test_describe_shows_events():
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        client.pods().create({"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "p0",
                                           "namespace": "default"},
                              "spec": {"containers": [{"name": "c"}]}})
        # record against the LIVE object: describe filters events by the
        # pod's uid, so a stale incarnation's events don't show
        real = client.pods().get("p0")
        rec = EventRecorder(client, "tester")
        rec.event(real, "Warning", "Unhealthy", "probe failed")
        rec.flush()
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "describe", "pods", "p0"],
                       out=out)
        assert rc == 0
        assert "Unhealthy" in out.getvalue() and "probe failed" in out.getvalue()
    finally:
        server.stop()
