"""Event recording (record.EventRecorder analog) + describe integration."""

import io
import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.utils.events import EventRecorder, events_for


def pod_obj(name="p0"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default", "uid": "u1"}}


def test_recorder_writes_and_aggregates():
    # FakeClock pins every timestamp and the aggregation window: whether the
    # two FailedScheduling events aggregate is decided by fake time the test
    # controls, never by how long the sink thread took on a loaded runner
    from kubernetes_tpu.utils.clock import FakeClock
    clock = FakeClock(1000.0)
    client = DirectClient(ObjectStore())
    rec = EventRecorder(client, "test-component", clock=clock)
    rec.event(pod_obj(), "Warning", "FailedScheduling", "no nodes")
    clock.advance(1.0)  # inside the window: must aggregate, not duplicate
    rec.event(pod_obj(), "Warning", "FailedScheduling", "no nodes")
    clock.advance(1.0)
    rec.event(pod_obj(), "Normal", "Scheduled", "assigned to n0")
    rec.flush()  # recording is async (broadcaster-style); settle before reading
    evs = events_for(client, "default", "p0")
    by_reason = {e["reason"]: e for e in evs}
    assert by_reason["FailedScheduling"]["count"] == 2  # aggregated
    assert by_reason["Scheduled"]["count"] == 1
    assert by_reason["Scheduled"]["source"]["component"] == "test-component"
    assert by_reason["FailedScheduling"]["involvedObject"]["name"] == "p0"
    assert by_reason["FailedScheduling"]["firstTimestamp"] == 1000.0
    assert by_reason["FailedScheduling"]["lastTimestamp"] == 1001.0


class _FailingResource:
    def __init__(self, exc):
        self._exc = exc

    def create_many(self, objs):
        raise self._exc

    def get(self, name):
        raise self._exc

    def update(self, obj):
        raise self._exc


class _FailingClient:
    """Every API write fails — the recorder must neither raise nor spin."""

    def __init__(self, exc=None):
        self.exc = exc or ConnectionError("api down")

    def resource(self, plural, ns="default"):
        return _FailingResource(self.exc)


def test_recorder_survives_failing_client_and_counts_drops():
    """Satellite contract: under a failing client the drain thread must
    not raise or spin, flush(timeout=) must return on deadline, and every
    eaten event lands on the events_dropped_total counter."""
    from kubernetes_tpu.metrics.registry import EVENTS_DROPPED
    before = EVENTS_DROPPED.get({"reason": "write_failed"})
    rec = EventRecorder(_FailingClient(), "test-component")
    for i in range(5):
        rec.event(pod_obj(f"p{i}"), "Normal", "Scheduled", f"msg {i}")
    t0 = time.time()
    rec.flush(timeout=3.0)  # failed writes still drain the queue
    assert time.time() - t0 < 3.0, "flush spun out to its full deadline"
    assert rec._q.unfinished_tasks == 0
    deadline = time.time() + 3.0
    while (EVENTS_DROPPED.get({"reason": "write_failed"}) - before < 5
           and time.time() < deadline):
        time.sleep(0.01)
    assert EVENTS_DROPPED.get({"reason": "write_failed"}) - before == 5
    # recording after the failures must still be non-blocking and silent
    rec.event(pod_obj("late"), "Normal", "Scheduled", "still fine")
    rec.flush(timeout=2.0)


def test_recorder_flush_returns_on_deadline_with_stuck_sink():
    """A sink wedged mid-write must not wedge flush past its deadline."""
    import threading

    class _HangingResource:
        def __init__(self, release):
            self._release = release

        def create_many(self, objs):
            self._release.wait(10.0)
            raise ConnectionError("api down")

    class _HangingClient:
        def __init__(self):
            self.release = threading.Event()

        def resource(self, plural, ns="default"):
            return _HangingResource(self.release)

    client = _HangingClient()
    rec = EventRecorder(client, "test-component")
    rec.event(pod_obj(), "Normal", "Scheduled", "msg")
    t0 = time.time()
    rec.flush(timeout=0.3)
    assert time.time() - t0 < 2.0  # returned on deadline, not on drain
    client.release.set()  # unwedge the daemon sink


def test_recorder_queue_overflow_counts_drops():
    from kubernetes_tpu.metrics.registry import EVENTS_DROPPED
    import queue as queue_mod
    before = EVENTS_DROPPED.get({"reason": "queue_full"})
    rec = EventRecorder(_FailingClient(), "test-component")
    rec._q = queue_mod.Queue(maxsize=1)  # force overflow deterministically

    class _StuckSink:  # pretend a sink is alive but never draining
        @staticmethod
        def is_alive():
            return True
    rec._sink = _StuckSink()
    rec.event(pod_obj("of0"), "Normal", "Scheduled", "fills the queue")
    rec.event(pod_obj("of1"), "Normal", "Scheduled", "overflows")
    assert EVENTS_DROPPED.get({"reason": "queue_full"}) - before >= 1


def test_scheduler_emits_scheduling_events():
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        # tight backoff: with the defaults, the stuck pod can sit out a
        # multi-second exponential backoff right when the node appears,
        # racing the Scheduled-event deadline on a loaded runner
        runner = SchedulerRunner(client, SchedulerConfiguration(
            backoff_initial_s=0.05, backoff_max_s=0.2))
        runner.start()
        # unschedulable pod (no nodes) -> FailedScheduling event
        client.pods().create({"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "stuck",
                                           "namespace": "default"},
                              "spec": {"containers": [{"name": "c"}]}})
        deadline = time.time() + 15
        while time.time() < deadline and not events_for(client, "default",
                                                        "stuck"):
            time.sleep(0.2)
        evs = events_for(client, "default", "stuck")
        # then a node appears -> Scheduled event
        client.nodes().create({"apiVersion": "v1", "kind": "Node",
                               "metadata": {"name": "n0"},
                               "status": {"allocatable": {"cpu": "4",
                                                          "pods": "10"}}})
        deadline = time.time() + 15
        ok = False
        while time.time() < deadline and not ok:
            ok = any(e["reason"] == "Scheduled"
                     for e in events_for(client, "default", "stuck"))
            time.sleep(0.2)
        assert ok, events_for(client, "default", "stuck")
        runner.stop()
    finally:
        server.stop()


def test_describe_shows_events():
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    server = APIServer().start()
    try:
        client = HTTPClient(server.url)
        client.pods().create({"apiVersion": "v1", "kind": "Pod",
                              "metadata": {"name": "p0",
                                           "namespace": "default"},
                              "spec": {"containers": [{"name": "c"}]}})
        # record against the LIVE object: describe filters events by the
        # pod's uid, so a stale incarnation's events don't show
        real = client.pods().get("p0")
        rec = EventRecorder(client, "tester")
        rec.event(real, "Warning", "Unhealthy", "probe failed")
        rec.flush()
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "describe", "pods", "p0"],
                       out=out)
        assert rc == 0
        assert "Unhealthy" in out.getvalue() and "probe failed" in out.getvalue()
    finally:
        server.stop()
