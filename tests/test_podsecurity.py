"""Pod Security admission — baseline/restricted standard enforcement.

Reference: ``staging/src/k8s.io/pod-security-admission`` — namespaces
label ``pod-security.kubernetes.io/enforce`` with a level; pod writes are
checked against that level's controls with every violation named.
"""

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.podsecurity import check_pod
from kubernetes_tpu.testing.wrappers import make_pod


@pytest.fixture()
def api():
    server = APIServer()
    server.enable_admission()
    server.start()
    yield server
    server.stop()


def _label_ns(client, name, level):
    nss = client.resource("namespaces", None)
    try:
        ns = nss.get(name)
    except ApiError:
        ns = nss.create({"kind": "Namespace", "metadata": {"name": name}})
    ns.setdefault("metadata", {}).setdefault("labels", {})[
        "pod-security.kubernetes.io/enforce"] = level
    nss.update(ns)


def _restricted_ok_pod(name, ns):
    pod = make_pod(name, ns).req({"cpu": "100m"}).obj().to_dict()
    for c in pod["spec"]["containers"]:
        c["securityContext"] = {
            "allowPrivilegeEscalation": False,
            "runAsNonRoot": True,
            "capabilities": {"drop": ["ALL"]},
            "seccompProfile": {"type": "RuntimeDefault"},
        }
    return pod


def test_baseline_blocks_privileged_and_host_access(api):
    c = HTTPClient(api.url)
    _label_ns(c, "guarded", "baseline")
    pod = make_pod("bad", "guarded").obj().to_dict()
    pod["spec"]["hostNetwork"] = True
    pod["spec"]["containers"][0]["securityContext"] = {"privileged": True}
    with pytest.raises(ApiError) as ei:
        c.pods("guarded").create(pod)
    msg = str(ei.value)
    assert "PodSecurity" in msg and "privileged container" in msg \
        and "hostNetwork" in msg
    # an ordinary pod is fine at baseline
    c.pods("guarded").create(make_pod("ok", "guarded").obj().to_dict())


def test_baseline_blocks_hostpath_and_hostport(api):
    c = HTTPClient(api.url)
    _label_ns(c, "guarded", "baseline")
    pod = make_pod("vol", "guarded").obj().to_dict()
    pod["spec"]["volumes"] = [{"name": "h", "hostPath": {"path": "/etc"}}]
    with pytest.raises(ApiError) as ei:
        c.pods("guarded").create(pod)
    assert "hostPath" in str(ei.value)


def test_restricted_requires_hardening(api):
    c = HTTPClient(api.url)
    _label_ns(c, "locked", "restricted")
    # a plain pod violates multiple restricted controls
    with pytest.raises(ApiError) as ei:
        c.pods("locked").create(make_pod("plain", "locked").obj().to_dict())
    msg = str(ei.value)
    assert "runAsNonRoot" in msg and 'drop "ALL"' in msg \
        and "seccompProfile" in msg
    # the fully hardened pod is admitted
    c.pods("locked").create(_restricted_ok_pod("hard", "locked"))


def test_unlabeled_namespace_is_privileged(api):
    c = HTTPClient(api.url)
    pod = make_pod("wild").obj().to_dict()
    pod["spec"]["hostNetwork"] = True  # fine: default namespace unlabeled
    c.pods("default").create(pod)


def test_status_updates_exempt(api):
    """The kubelet's status heartbeats must not be policy-checked (the
    spec is unchanged; upstream exempts non-spec updates)."""
    c = HTTPClient(api.url)
    _label_ns(c, "guarded", "baseline")
    c.pods("guarded").create(make_pod("run", "guarded").obj().to_dict())
    # namespace tightened AFTER creation: status writes still flow
    got = c.pods("guarded").get("run")
    got.setdefault("status", {})["phase"] = "Running"
    c.pods("guarded").update_status(got)


def test_check_pod_level_logic():
    spec_ok = make_pod("x").obj().to_dict()
    assert check_pod("privileged", spec_ok) == []
    assert check_pod("baseline", spec_ok) == []
    assert check_pod("restricted", spec_ok) != []
    hard = _restricted_ok_pod("y", "default")
    assert check_pod("restricted", hard) == []


def test_namespace_from_request_url_not_body(api):
    """The policy must key off the REQUEST namespace: a manifest omitting
    metadata.namespace POSTed to a guarded namespace's collection URL is
    still checked against that namespace's level (the bypass would be a
    policy hole)."""
    c = HTTPClient(api.url)
    _label_ns(c, "guarded", "baseline")
    pod = make_pod("sneaky").obj().to_dict()
    pod["metadata"].pop("namespace", None)  # body carries no namespace
    pod["spec"]["hostNetwork"] = True
    with pytest.raises(ApiError) as ei:
        c.pods("guarded").create(pod)
    assert "PodSecurity" in str(ei.value)


def test_metadata_only_update_exempt_after_tightening(api):
    """An existing pod in a namespace that tightens its level afterwards
    can still take metadata-only updates (labels, finalizers) — only spec
    changes re-trigger enforcement."""
    c = HTTPClient(api.url)
    pod = make_pod("legacy", "guarded").obj().to_dict()
    pod["spec"]["hostNetwork"] = True
    _label_ns(c, "guarded", "privileged")
    c.pods("guarded").create(pod)
    _label_ns(c, "guarded", "baseline")  # tighten AFTER creation
    got = c.pods("guarded").get("legacy")
    got["metadata"].setdefault("labels", {})["touched"] = "yes"
    c.pods("guarded").update(got)  # metadata-only: allowed
    got = c.pods("guarded").get("legacy")
    got["spec"]["hostPID"] = True
    with pytest.raises(ApiError):  # spec change: enforced
        c.pods("guarded").update(got)
