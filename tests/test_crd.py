"""CustomResourceDefinitions: dynamic resource serving, discovery, watch,
informers over custom kinds, cascade on CRD delete, durable restore.

Reference: apiextensions-apiserver (``staging/src/k8s.io/apiextensions-
apiserver``): a stored CRD makes the server serve full CRUD+watch for the
named plural under /apis/<group>/<version>/.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.store.apiserver import APIServer

WIDGET_CRD = {
    "apiVersion": "apiextensions.k8s.io/v1", "kind": "CustomResourceDefinition",
    "metadata": {"name": "widgets.example.com"},
    "spec": {"group": "example.com",
             "scope": "Namespaced",
             "names": {"plural": "widgets", "kind": "Widget"},
             "versions": [{"name": "v1", "served": True, "storage": True}]}}


@pytest.fixture
def server():
    s = APIServer().start()
    yield s
    s.stop()


def widget(name, size=1):
    return {"apiVersion": "example.com/v1", "kind": "Widget",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"size": size}}


def test_crd_crud_and_watch(server):
    client = HTTPClient(server.url)
    client.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    assert client.discover_custom() == 1

    h = client.resource("widgets")
    h.create(widget("w1", 3))
    assert h.get("w1")["spec"]["size"] == 3
    got = h.get("w1")
    got["spec"]["size"] = 5
    h.update(got)
    assert h.get("w1")["spec"]["size"] == 5
    assert [w["metadata"]["name"] for w in h.list()] == ["w1"]

    events = []
    w = h.watch()
    h.create(widget("w2"))
    deadline = time.time() + 5
    while time.time() < deadline and not events:
        for ev in w:
            events.append(ev)
            break
    assert events and events[0].object["metadata"]["name"] in ("w1", "w2")

    h.delete("w1")
    with pytest.raises(ApiError):
        h.get("w1")


def test_unknown_plural_is_404_then_served(server):
    client = HTTPClient(server.url)
    client.register_custom("widgets", "Widget", group="example.com/v1")
    with pytest.raises(ApiError) as e:
        client.resource("widgets").create(widget("early"))
    assert e.value.code == 404  # no CRD yet
    client.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    client.resource("widgets").create(widget("now"))
    assert client.resource("widgets").get("now")


def test_crd_validation(server):
    client = HTTPClient(server.url)
    bad = {"apiVersion": "apiextensions.k8s.io/v1",
           "kind": "CustomResourceDefinition",
           "metadata": {"name": "bad"},
           "spec": {"names": {"plural": "pods", "kind": "Pod"},
                    "group": "example.com"}}
    with pytest.raises(ApiError) as e:
        client.resource("customresourcedefinitions", None).create(bad)
    assert e.value.code == 400  # shadows a built-in
    with pytest.raises(ApiError):
        client.resource("customresourcedefinitions", None).create({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": "nogroup"},
            "spec": {"names": {"plural": "things", "kind": "Thing"}}})
    # kind shadowing a built-in is rejected even with a fresh plural
    # (the store is keyed by kind: the delete cascade would wipe real Pods)
    with pytest.raises(ApiError) as e:
        client.resource("customresourcedefinitions", None).create({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": "sneaky"},
            "spec": {"group": "example.com",
                     "names": {"plural": "mypods", "kind": "Pod"}}})
    assert e.value.code == 400
    # two CRDs can't share a plural or a kind
    client.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    dup = {"apiVersion": "apiextensions.k8s.io/v1",
           "kind": "CustomResourceDefinition",
           "metadata": {"name": "widgets.other.com"},
           "spec": {"group": "other.com",
                    "names": {"plural": "widgets", "kind": "OtherWidget"}}}
    with pytest.raises(ApiError):
        client.resource("customresourcedefinitions", None).create(dup)
    # updating a CRD to shadow a built-in is rejected too
    crd = client.resource("customresourcedefinitions", None).get(
        "widgets.example.com")
    crd["spec"]["names"]["plural"] = "pods"
    with pytest.raises(ApiError) as e:
        client.resource("customresourcedefinitions", None).update(crd)
    assert e.value.code == 400


def test_crd_delete_cascades_instances(server):
    client = HTTPClient(server.url)
    client.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    client.discover_custom()
    client.resource("widgets").create(widget("doomed"))
    client.resource("customresourcedefinitions", None).delete(
        "widgets.example.com")
    with pytest.raises(ApiError) as e:
        client.resource("widgets").get("doomed")
    assert e.value.code == 404  # resource no longer served


def test_informer_over_custom_resource(server):
    client = HTTPClient(server.url)
    client.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    client.discover_custom()
    factory = InformerFactory(client)
    inf = factory.informer("widgets", None)
    seen = []
    inf.add_event_handler(lambda t, obj, old: seen.append(
        (t, obj["metadata"]["name"])))
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    try:
        client.resource("widgets").create(widget("w9"))
        deadline = time.time() + 5
        while time.time() < deadline and ("ADDED", "w9") not in seen:
            time.sleep(0.05)
        assert ("ADDED", "w9") in seen
        assert inf.store.get("default/w9")["spec"]["size"] == 1
    finally:
        factory.stop_all()


def test_crd_survives_durable_restart(tmp_path):
    data_dir = str(tmp_path / "state")
    s1 = APIServer(data_dir=data_dir).start()
    c1 = HTTPClient(s1.url)
    c1.resource("customresourcedefinitions", None).create(WIDGET_CRD)
    c1.discover_custom()
    c1.resource("widgets").create(widget("persistent", 7))
    s1.stop()

    s2 = APIServer(data_dir=data_dir).start()
    try:
        c2 = HTTPClient(s2.url)
        assert c2.discover_custom() == 1
        assert c2.resource("widgets").get("persistent")["spec"]["size"] == 7
    finally:
        s2.stop()
