"""Durable AOT executable cache (sched/aotcache.py) — the failure menu.

Every test here is a way the cache directory can betray a restarted
scheduler: torn bytes, flipped bits, a toolchain that moved underneath
it, a manifest that didn't survive, more entries than the bound allows.
The contract under test is single: damage degrades to a COUNTED
recompile — never a crash, never a silently wrong program — and an
intact cache makes the restart genuinely zero-compile (asserted through
the compile meter, not vibes).
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.sched.aotcache import (
    ENTRY_SUFFIX,
    FINGERPRINT_FILE,
    MANIFEST_FILE,
    AotExecutableCache,
    cache_knobs,
    resolve_cache_dir,
)

pytestmark = pytest.mark.disaster


@pytest.fixture
def cache_root(tmp_path):
    """A cache dir whose activation is always disarmed afterwards, so
    the process-global jax persistent-cache config never leaks into the
    next test."""
    yield str(tmp_path / "aot")
    AotExecutableCache.disarm()
    jax.clear_caches()


def _entries(cache) -> list:
    return [n for n in os.listdir(cache.entries_dir)
            if n.endswith(ENTRY_SUFFIX)]


def _populate(root: str, knobs=None, fns=1) -> int:
    """Activate a fresh cache at ``root``, run ``fns`` distinct jits so
    entries persist, seal, and return the sealed entry count."""
    cache = AotExecutableCache(root, knobs=knobs or {"k": 1})
    cache.activate()
    for i in range(fns):
        k = float(i + 2)
        jax.jit(lambda x, _k=k: x * _k + 1)(jnp.arange(8)).block_until_ready()
    n = cache.seal()
    assert n >= fns
    return n


# ---- warm restart ------------------------------------------------------------

def test_warm_restart_loads_instead_of_compiling(cache_root):
    n = _populate(cache_root)
    jax.clear_caches()  # the in-process restart: dispatch caches are gone
    cache = AotExecutableCache(cache_root, knobs={"k": 1})
    boot = cache.activate()
    assert boot["entries"] == n
    assert boot["fingerprintStale"] is False
    assert boot["corruptSwept"] == 0 and boot["rotated"] == 0
    jax.jit(lambda x: x * 2.0 + 1)(jnp.arange(8)).block_until_ready()
    stats = cache.stats()
    assert stats["realCompiles"] == 0, stats  # the headline property
    assert stats["hits"] >= 1
    assert stats["errors"] == 0 and stats["invalidations"] == 0


# ---- corruption --------------------------------------------------------------

def test_bitflip_swept_and_recompiled_not_crashed(cache_root):
    _populate(cache_root)
    jax.clear_caches()
    probe = AotExecutableCache(cache_root, knobs={"k": 1})
    victim = os.path.join(probe.entries_dir, _entries(probe)[0])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(blob))
    cache = AotExecutableCache(cache_root, knobs={"k": 1})
    boot = cache.activate()
    assert boot["corruptSwept"] == 1 and cache.errors == 1
    assert not os.path.exists(victim)  # deleted before jax could read it
    # the program behind the swept entry recompiles and answers correctly
    got = jax.jit(lambda x: x * 2.0 + 1)(jnp.arange(8))
    assert got.tolist() == [x * 2.0 + 1 for x in range(8)]
    assert cache.stats()["errors"] == 1


def test_truncation_swept(cache_root):
    _populate(cache_root)
    probe = AotExecutableCache(cache_root, knobs={"k": 1})
    victim = os.path.join(probe.entries_dir, _entries(probe)[0])
    with open(victim, "r+b") as f:
        f.truncate(max(1, os.path.getsize(victim) // 2))
    cache = AotExecutableCache(cache_root, knobs={"k": 1})
    boot = cache.activate()
    assert boot["corruptSwept"] == 1
    assert not os.path.exists(victim)


def test_corrupt_manifest_counted_entries_survive(cache_root):
    """A garbage manifest is a counted error, but the entries are NOT
    thrown away: jax wrote them via temp+rename and its framing
    self-checks, so unverifiable-but-present beats a cold ladder."""
    n = _populate(cache_root)
    with open(os.path.join(cache_root, MANIFEST_FILE), "w") as f:
        f.write("{torn")
    cache = AotExecutableCache(cache_root, knobs={"k": 1})
    boot = cache.activate()
    assert cache.errors == 1  # reason="manifest"
    assert boot["entries"] == n and boot["corruptSwept"] == 0
    # and the re-hash re-manifested them for the NEXT boot
    doc = json.load(open(os.path.join(cache_root, MANIFEST_FILE)))
    assert len(doc["entries"]) == n


# ---- fingerprint -------------------------------------------------------------

def test_stale_fingerprint_invalidates_wholesale(cache_root):
    _populate(cache_root, knobs={"k": 1})
    jax.clear_caches()
    cache = AotExecutableCache(cache_root, knobs={"k": 2})  # knob changed
    boot = cache.activate()
    assert boot["fingerprintStale"] is True
    assert boot["entries"] == 0 and cache.invalidations >= 1
    assert _entries(cache) == []  # nothing salvaged
    # the new fingerprint is committed: the NEXT same-knob boot trusts it
    doc = json.load(open(os.path.join(cache_root, FINGERPRINT_FILE)))
    assert doc["fingerprint"] == cache.fingerprint


def test_unreadable_fingerprint_treated_as_stale(cache_root):
    _populate(cache_root)
    with open(os.path.join(cache_root, FINGERPRINT_FILE), "w") as f:
        f.write("not json")
    cache = AotExecutableCache(cache_root, knobs={"k": 1})
    boot = cache.activate()
    assert boot["fingerprintStale"] is True and boot["entries"] == 0


# ---- size bound --------------------------------------------------------------

def test_gc_rotates_past_max_bytes(cache_root):
    n = _populate(cache_root, fns=2)
    cache = AotExecutableCache(cache_root, knobs={"k": 1}, max_bytes=1)
    boot = cache.activate()
    assert boot["rotated"] == n and boot["entries"] == 0
    assert cache.invalidations == n  # counted as reason="rotation"


# ---- wiring ------------------------------------------------------------------

def test_resolve_cache_dir_env_override(monkeypatch, tmp_path):
    from kubernetes_tpu.config.types import SchedulerConfiguration
    cfg = SchedulerConfiguration.from_dict(
        {"aotCacheDir": str(tmp_path / "cfg")})
    monkeypatch.delenv("KTPU_AOT_CACHE", raising=False)
    assert resolve_cache_dir(cfg) == str(tmp_path / "cfg")
    for off in ("", "0", "off", "none", "FALSE"):
        monkeypatch.setenv("KTPU_AOT_CACHE", off)
        assert resolve_cache_dir(cfg) is None
    monkeypatch.setenv("KTPU_AOT_CACHE", str(tmp_path / "env"))
    assert resolve_cache_dir(cfg) == str(tmp_path / "env")


def test_cache_knobs_cover_lowering_config():
    from kubernetes_tpu.config.types import SchedulerConfiguration
    cfg = SchedulerConfiguration()
    knobs = cache_knobs(cfg)
    assert set(knobs) == {"meshShape", "fusedFold", "batchSize",
                          "maxDrainBatches"}
    # any knob change must change the fingerprint (wholesale distrust)
    from kubernetes_tpu.parallel.aot import lowering_fingerprint
    flipped = dict(knobs, fusedFold=not knobs["fusedFold"])
    assert lowering_fingerprint(knobs) != lowering_fingerprint(flipped)
