"""Disaster recovery: the apiserver dies and restarts under live clients.

The contracts pinned here (the tier-1 face of the DisasterChurn bench):

1. READYZ GATE — an async-restore apiserver answers 503 on /readyz and
   every resource path until WAL replay completes, then serves the
   restored state.
2. OUTAGE BACKOFF — HTTPClient absorbs refused/reset storms with capped
   full-jitter retries; a request issued during a short outage succeeds
   once the server returns.
3. RELIST ACROSS RESTART — pre-restart resourceVersions get 410/TooOld
   over HTTP, informers relist (``watch_relists_total`` counts it), and
   the rebuilt cache equals a fresh list.
4. SCHEDULER SURVIVES — a connected SchedulerRunner rides the restart:
   pre-restart bindings persist, post-restart pods bind, and the
   scheduler cache converges to apiserver truth.
5. DISRUPTION MODES — mass unreadiness engages partial/full disruption
   (taints suppressed/removed, evictions halted), heal releases it, and
   the post-release grace window keeps outage-era staleness from
   tainting the laggards.
"""

import threading
import time

import pytest

from kubernetes_tpu.chaos.apiserver import InProcessApiServer, free_port
from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.client.informer import InformerFactory, SharedInformer
from kubernetes_tpu.controllers.nodelifecycle import (
    MODE_FULL,
    MODE_NORMAL,
    MODE_PARTIAL,
    TAINT_NOT_READY,
    TAINT_UNREACHABLE,
    NodeLifecycleController,
)
from kubernetes_tpu.metrics.registry import WATCH_RELISTS
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore, TooOld
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.disaster


def wait_for(pred, timeout=15.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---- 1. readyz gates until WAL replay completes ---------------------------

def test_readyz_503_until_replay_completes(tmp_path, monkeypatch):
    d = str(tmp_path / "data")
    s = APIServer(data_dir=d).start()
    HTTPClient(s.url).pods().create(
        make_pod("pre").obj().to_dict())
    s.stop()

    gate = threading.Event()
    orig = ObjectStore._restore_locked

    def gated_restore(self):
        gate.wait(10.0)
        return orig(self)
    monkeypatch.setattr(ObjectStore, "_restore_locked", gated_restore)
    s2 = APIServer(data_dir=d, async_restore=True).start()
    try:
        import urllib.error
        import urllib.request
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(s2.url + "/readyz", timeout=2.0)
        assert ei.value.code == 503
        # resource paths are gated too: an empty pre-restore store must
        # never be served as truth
        from kubernetes_tpu.client.clientset import ApiError
        with pytest.raises(ApiError) as ai:
            HTTPClient(s2.url, retry_attempts=0).pods().list()
        assert ai.value.code == 503
        # liveness stays green: the process is alive, just not ready
        assert urllib.request.urlopen(
            s2.url + "/healthz", timeout=2.0).status == 200
        gate.set()
        assert s2.wait_ready(10.0)
        assert urllib.request.urlopen(
            s2.url + "/readyz", timeout=2.0).status == 200
        pods = HTTPClient(s2.url).pods().list()
        assert [p["metadata"]["name"] for p in pods] == ["pre"]
    finally:
        gate.set()
        s2.stop()


# ---- 2. HTTPClient outage backoff -----------------------------------------

def test_http_client_retries_through_a_short_outage(tmp_path):
    box = InProcessApiServer(str(tmp_path / "data"))
    box.start()
    c = HTTPClient(box.url, retry_attempts=8, retry_base_s=0.1,
                   retry_cap_s=0.5)
    c.nodes().create(make_node("n1").obj().to_dict())
    box.stop(graceful=False)

    def restart_later():
        time.sleep(0.6)
        box.start()
    t = threading.Thread(target=restart_later, daemon=True)
    t.start()
    # issued while the server is DOWN: the capped full-jitter retry loop
    # must carry the request across the dead window
    nodes = c.nodes().list()
    assert [n["metadata"]["name"] for n in nodes] == ["n1"]
    t.join()
    box.stop()


def test_http_client_backoff_is_bounded_and_jittered(monkeypatch):
    sleeps = []
    import kubernetes_tpu.client.clientset as cs
    monkeypatch.setattr(cs.time, "sleep", lambda s: sleeps.append(s))
    c = HTTPClient(f"http://127.0.0.1:{free_port()}", retry_attempts=4,
                   retry_base_s=0.1, retry_cap_s=0.25)
    with pytest.raises(OSError):
        c.pods().list()
    # one sleep per retry, each within the capped full-jitter envelope
    assert len(sleeps) == 4
    for i, s in enumerate(sleeps):
        assert 0.0 < s <= min(0.25, 0.1 * (2 ** i)) + 1e-9
    # generateName creates must NOT burn retries (non-idempotent)
    sleeps.clear()
    pod = make_pod("x").obj().to_dict()
    pod["metadata"].pop("name")
    pod["metadata"]["generateName"] = "gen-"
    with pytest.raises(OSError):
        c.pods().create(pod)
    assert sleeps == []


# ---- 3. watch/informer resumption across a real HTTP restart --------------

def test_pre_restart_rv_gets_too_old_over_http(tmp_path):
    box = InProcessApiServer(str(tmp_path / "data"))
    box.start()
    c = HTTPClient(box.url)
    for i in range(3):
        c.pods().create(make_pod(f"p{i}").obj().to_dict())
    old_rv = int(c.pods().list_rv()[1])
    box.restart(graceful=False)
    c2 = HTTPClient(box.url)
    # the restore floor sits at the replayed rv: every pre-restart rv is
    # compacted away, and the HTTP 410 must surface as TooOld exactly
    # like DirectClient so informers relist immediately
    with pytest.raises(TooOld):
        c2.pods(None).watch(since_rv=old_rv - 1)
    w = c2.pods(None).watch(since_rv=int(c2.pods().list_rv()[1]))
    c2.pods().create(make_pod("after").obj().to_dict())
    ev = None
    deadline = time.time() + 5
    while ev is None and time.time() < deadline:
        ev = w.get(timeout=0.5)
    assert ev is not None and ev.object["metadata"]["name"] == "after"
    w.stop()
    box.stop()


def test_informer_relists_across_restart_and_matches_fresh_list(tmp_path):
    box = InProcessApiServer(str(tmp_path / "data"))
    box.start()
    c = HTTPClient(box.url, retry_attempts=4, retry_base_s=0.05,
                   retry_cap_s=0.3)
    for i in range(5):
        c.pods().create(make_pod(f"p{i}").obj().to_dict())
    relists_before = WATCH_RELISTS.get({"resource": "pods"})
    inf = SharedInformer(c.pods("default"))
    inf.start()
    assert inf.wait_for_cache_sync(10.0)
    assert len(inf.store) == 5

    # kill; mutate THROUGH the dead window is impossible, so mutate right
    # after restart instead — the informer must relist (its watch died,
    # its next list succeeds) and converge on the post-restart truth
    box.stop(graceful=False)
    time.sleep(1.0)  # a real dead window: list attempts fail + back off
    box.restart(graceful=False)  # second kill-restart exercises the WAL too
    c.pods().create(make_pod("post-restart").obj().to_dict())
    c.pods().delete("p0")

    fresh = lambda: {p["metadata"]["name"]: p["metadata"]["resourceVersion"]
                     for p in c.pods().list()}

    def parity():
        mine = {o["metadata"]["name"]: o["metadata"]["resourceVersion"]
                for o in inf.store.list()}
        return mine == fresh()
    assert wait_for(parity, timeout=20.0), (
        sorted(fresh()), sorted(o["metadata"]["name"]
                                for o in inf.store.list()))
    assert inf.relists >= 1
    assert WATCH_RELISTS.get({"resource": "pods"}) - relists_before >= 1
    inf.stop()
    box.stop()


def test_scheduler_survives_apiserver_restart(tmp_path):
    """End to end over HTTP: bindings persist the restart (WAL), the
    relisted scheduler cache equals apiserver truth, and a pod created
    after the restart is bound by the SAME runner."""
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    box = InProcessApiServer(str(tmp_path / "data"))
    box.start()
    c = HTTPClient(box.url)
    for i in range(2):
        c.nodes().create(make_node(f"n{i}").allocatable(
            {"cpu": "8", "memory": "16Gi", "pods": "110"}).obj().to_dict())
    runner = SchedulerRunner(
        HTTPClient(box.url, retry_attempts=4),
        SchedulerConfiguration(batch_size=16))
    runner.start(wait_sync=15.0)
    try:
        c.pods().create(make_pod("before").req({"cpu": "100m"})
                        .obj().to_dict())
        assert wait_for(lambda: (c.pods().get("before").get("spec") or {})
                        .get("nodeName"), 20.0)

        box.restart(graceful=False)
        c2 = HTTPClient(box.url)
        # pre-restart binding survived the WAL replay
        assert (c2.pods().get("before")["spec"]).get("nodeName")
        # post-restart pod binds through the healed informer/queue/drain
        c2.pods().create(make_pod("after").req({"cpu": "100m"})
                         .obj().to_dict())
        assert wait_for(lambda: (c2.pods().get("after").get("spec") or {})
                        .get("nodeName"), 30.0)
        # cache == fresh-list parity (the relist rebuilt, not patched)
        def cache_parity():
            view = runner.cache.audit_view()
            api_bound = {f"{p['metadata'].get('namespace', 'default')}/"
                         f"{p['metadata']['name']}":
                         (p.get("spec") or {}).get("nodeName")
                         for p in c2.pods().list()
                         if (p.get("spec") or {}).get("nodeName")}
            return (view["bound"] == api_bound
                    and view["nodes"] == {n["metadata"]["name"]
                                          for n in c2.nodes().list()})
        assert wait_for(cache_parity, 20.0), (
            runner.cache.audit_view(), c2.pods().list())
        # the heal was a real relist-and-resync, not luck: the runner's
        # informers relisted at least once across the dead window
        assert runner._total_relists() >= 1
    finally:
        runner.stop()
        box.stop()


# ---- 4. disruption-mode node lifecycle ------------------------------------

def _cluster(n, lease_age=0.0):
    """DirectClient cluster of ``n`` nodes with Ready=True conditions and
    kube-node-lease leases aged ``lease_age`` seconds."""
    store = ObjectStore()
    client = DirectClient(store)
    now = time.time()
    for i in range(n):
        node = make_node(f"d{i}").allocatable(
            {"cpu": "8", "pods": "110"}).obj().to_dict()
        node["status"]["conditions"] = [
            {"type": "Ready", "status": "True",
             "lastHeartbeatTime": now - lease_age}]
        client.nodes().create(node)
        client.leases("kube-node-lease").create(
            {"kind": "Lease",
             "metadata": {"name": f"d{i}", "namespace": "kube-node-lease"},
             "spec": {"holderIdentity": f"d{i}",
                      "renewTime": now - lease_age}})
    return store, client


def _start_ctrl(client, **kw):
    kw.setdefault("grace_period", 1.0)
    kw.setdefault("monitor_period", 0.1)
    ctrl = NodeLifecycleController(client, **kw)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def _renew_all(client, n):
    client.leases("kube-node-lease").renew_many(
        [(f"d{i}", time.time()) for i in range(n)])


def test_full_disruption_halts_taints_and_evictions_then_releases():
    """Every lease goes stale at once (the apiserver-outage signature):
    FullDisruption engages, no node is tainted, the bound pod survives;
    renewals resume -> mode releases -> still no taint storm (the
    post-release grace window covers the laggards)."""
    store, client = _cluster(8)
    pod = make_pod("w").node("d3").obj().to_dict()
    pod["status"] = {"phase": "Running"}
    client.pods().create(pod)
    ctrl, factory = _start_ctrl(client)
    try:
        # healthy steady state first (renewals flowing)
        for _ in range(5):
            _renew_all(client, 8)
            time.sleep(0.05)
        assert ctrl.mode == MODE_NORMAL
        # outage: ALL renewals stop
        assert wait_for(lambda: ctrl.mode == MODE_FULL, 10.0), ctrl.mode
        time.sleep(0.5)  # several sync sweeps under full disruption
        assert not any((n.get("spec") or {}).get("taints")
                       for n in client.nodes().list())
        assert [p["metadata"]["name"]
                for p in client.pods().list()] == ["w"]
        assert ctrl.evictions == 0 and ctrl.taints_suppressed > 0
        # heal: renewals resume for everyone
        stop = threading.Event()

        def renewer():
            while not stop.is_set():
                _renew_all(client, 8)
                time.sleep(0.05)
        threading.Thread(target=renewer, daemon=True).start()
        try:
            assert wait_for(lambda: ctrl.mode == MODE_NORMAL, 10.0)
            time.sleep(0.4)
            assert not any((n.get("spec") or {}).get("taints")
                           for n in client.nodes().list())
            assert ctrl.evictions == 0
            assert ctrl.engaged_count == 1
        finally:
            stop.set()
    finally:
        ctrl.stop()
        factory.stop_all()


def test_post_release_grace_shields_lease_laggards():
    """Half the fleet's renewals land late after an outage: the release
    back to Normal must NOT taint the still-stale half — their staleness
    is outage-era evidence (the exact cascade the first DisasterChurn
    run caught)."""
    store, client = _cluster(8)
    ctrl, factory = _start_ctrl(client, grace_period=1.0)
    try:
        assert wait_for(lambda: ctrl.mode == MODE_FULL, 10.0)
        # only 6/8 renew (fraction 0.25 < threshold -> Normal), d6/d7 stay
        # stale — laggards whose renewals are still in flight
        stop = threading.Event()

        def renewer():
            while not stop.is_set():
                client.leases("kube-node-lease").renew_many(
                    [(f"d{i}", time.time()) for i in range(6)])
                time.sleep(0.05)
        threading.Thread(target=renewer, daemon=True).start()
        try:
            assert wait_for(lambda: ctrl.mode == MODE_NORMAL, 10.0)
            time.sleep(0.3)  # sync sweeps run under the grace window
            taints = [t for n in client.nodes().list()
                      for t in (n.get("spec") or {}).get("taints") or []]
            assert taints == [], taints
            # after the FULL grace re-accrues, a genuinely dead node IS
            # tainted — the shield delays judgment, it does not blind it
            assert wait_for(lambda: any(
                t.get("key") == TAINT_UNREACHABLE
                for t in (client.nodes().get("d7").get("spec") or {})
                .get("taints") or []), 10.0)
        finally:
            stop.set()
    finally:
        ctrl.stop()
        factory.stop_all()


def test_partial_disruption_small_cluster_halts_large_rate_limits():
    """fraction >= 0.55 but < 1.0: small clusters halt evictions
    entirely; large clusters trickle NEW taints at the secondary rate
    (one immediate token, the rest deferred)."""
    # small (8 < largeClusterThreshold): halted
    store, client = _cluster(8)
    ctrl, factory = _start_ctrl(client)
    try:
        # 6/8 stale -> 0.75 >= 0.55, < 1.0 -> Partial
        stop = threading.Event()

        def renewer():
            while not stop.is_set():
                client.leases("kube-node-lease").renew_many(
                    [(f"d{i}", time.time()) for i in range(2)])
                time.sleep(0.05)
        threading.Thread(target=renewer, daemon=True).start()
        try:
            assert wait_for(lambda: ctrl.mode == MODE_PARTIAL, 10.0)
            time.sleep(0.4)
            assert not any((n.get("spec") or {}).get("taints")
                           for n in client.nodes().list())
            assert ctrl.taints_suppressed > 0
        finally:
            stop.set()
    finally:
        ctrl.stop()
        factory.stop_all()
    # large (>= threshold param set low): secondary-rate trickle
    store, client = _cluster(8)
    ctrl, factory = _start_ctrl(client, large_cluster_threshold=4,
                                secondary_eviction_rate_qps=0.001)
    try:
        stop = threading.Event()

        def renewer2():
            while not stop.is_set():
                client.leases("kube-node-lease").renew_many(
                    [(f"d{i}", time.time()) for i in range(2)])
                time.sleep(0.05)
        threading.Thread(target=renewer2, daemon=True).start()
        try:
            assert wait_for(lambda: ctrl.mode == MODE_PARTIAL, 10.0)
            # one token was banked: exactly one node may be tainted;
            # everything else defers at 0.001 qps (~never in this test)
            assert wait_for(lambda: ctrl.evictions_deferred > 0, 10.0)
            tainted = [n["metadata"]["name"] for n in client.nodes().list()
                       if (n.get("spec") or {}).get("taints")]
            assert len(tainted) <= 1, tainted
        finally:
            stop.set()
    finally:
        ctrl.stop()
        factory.stop_all()


def test_small_clusters_never_enter_disruption_mode():
    """min_disruption_nodes: a 2-node cluster's unready node is its own
    ground truth — taint + evict proceed exactly as before this PR."""
    store, client = _cluster(2)
    pod = make_pod("victim").node("d0").obj().to_dict()
    pod["status"] = {"phase": "Running"}
    client.pods().create(pod)
    ctrl, factory = _start_ctrl(client)
    try:
        # both nodes go stale (fraction 1.0) — but n < min_disruption_nodes
        assert wait_for(lambda: any(
            t.get("key") == TAINT_UNREACHABLE
            for t in (client.nodes().get("d0").get("spec") or {})
            .get("taints") or []), 10.0)
        assert ctrl.mode == MODE_NORMAL
        assert wait_for(
            lambda: not [p for p in client.pods().list()
                         if p["metadata"]["name"] == "victim"], 10.0)
        assert ctrl.evictions >= 1
    finally:
        ctrl.stop()
        factory.stop_all()


def test_disruption_status_configmap_published():
    store, client = _cluster(4)
    ctrl, factory = _start_ctrl(client)
    try:
        assert wait_for(lambda: ctrl.mode == MODE_FULL, 10.0)
        from kubernetes_tpu.controllers.nodelifecycle import (
            NODELIFECYCLE_CONFIGMAP)

        def published():
            import json as _json
            try:
                cm = client.resource("configmaps", "default").get(
                    NODELIFECYCLE_CONFIGMAP)
            except Exception:
                return False
            dis = _json.loads(cm["data"]["disruption"])
            return dis["mode"] == MODE_FULL and dis["engagedCount"] >= 1
        assert wait_for(published, 10.0)
    finally:
        ctrl.stop()
        factory.stop_all()


# ---- 5. ktpu status surfaces durability + disruption ----------------------

def test_ktpu_status_durability_and_disruption_lines(tmp_path):
    import io
    import json as _json

    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    box = InProcessApiServer(str(tmp_path / "data"))
    server = box.start()
    try:
        server.publish_durability()
        # a nodelifecycle controller publishing into the same namespace
        ctrl = NodeLifecycleController(HTTPClient(box.url))
        ctrl.publish_status()
        out = io.StringIO()
        rc = ktpu_main(["--server", box.url, "status"], out=out)
        text = out.getvalue()
        assert rc == 0
        assert "Durability:" in text and "readyz ok" in text
        assert "Disruption:    Normal" in text
        out = io.StringIO()
        rc = ktpu_main(["--server", box.url, "status", "-o", "json"],
                       out=out)
        st = _json.loads(out.getvalue())
        assert rc == 0
        assert st["durability"]["durable"] is True
        assert st["durability"]["ready"] is True
        assert st["disruption"]["mode"] == MODE_NORMAL
    finally:
        box.stop()
