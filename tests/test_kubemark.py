"""Kubemark e2e: hollow kubelet fleet + connected scheduler + apiserver.

Reference: ``pkg/kubemark/hollow_kubelet.go`` — real kubelet machinery over
a mocked CRI at node counts no test cluster provides. The full 500-node run
records its numbers in BENCH (benchmarks/kubemark.py); the slow-marked test
here runs the same loop at a CI-survivable fleet size.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import HTTPClient
from kubernetes_tpu.kubelet.kubemark import HollowCluster
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_pod


def test_hollow_cluster_registers_heartbeats_and_runs_pods():
    """Smoke: a small fleet registers in bulk, heartbeats via the driver
    pool, and drives scheduled pods to Running through the real kubelet
    sync machinery."""
    server = APIServer().start()
    cluster = None
    try:
        client = HTTPClient(server.url)
        cluster = HollowCluster(client, 12, prefix="hx",
                                heartbeat_period=0.5).start()
        assert len(client.nodes().list()) == 12
        # heartbeats: every node turns Ready
        deadline = time.time() + 10
        while time.time() < deadline:
            ready = sum(
                1 for n in client.nodes().list()
                if any(c.get("type") == "Ready"
                       and c.get("status") == "True"
                       for c in (n.get("status") or {})
                       .get("conditions") or []))
            if ready == 12:
                break
            time.sleep(0.1)
        assert ready == 12
        # a pod bound to a hollow node reaches Running via the shared watch
        pod = make_pod("hp").req({"cpu": "100m"}).obj().to_dict()
        pod["spec"]["nodeName"] = "hx-3"
        client.pods("default").create(pod)
        deadline = time.time() + 10
        phase = None
        while time.time() < deadline:
            phase = (client.pods("default").get("hp").get("status")
                     or {}).get("phase")
            if phase == "Running":
                break
            time.sleep(0.1)
        assert phase == "Running"
        assert cluster.running_pods() == 1
    finally:
        if cluster is not None:
            cluster.stop()
        server.stop()


@pytest.mark.slow
def test_kubemark_fleet_with_connected_scheduler():
    """The kubemark loop end to end at fleet scale: 150 hollow nodes, the
    connected scheduler binding 400 pods, kubelets driving them Running
    (the 500-node configuration runs in BENCH via benchmarks/kubemark.py)."""
    from benchmarks.kubemark import run_kubemark
    res = run_kubemark(n_hollow=150, n_pods=400, heartbeat_period=5.0,
                       timeout=180.0)
    assert res["bound"] == 400, res
    assert res["running"] == 400, res
    assert res["nodes_ready"] == 150, res
