"""Bulk write APIs + device-resident drain.

Covers the round-3 connected-path machinery: ``ObjectStore.bind_many`` /
``create_many``, the apiserver's bulk binding subresource (POST
``pods/-/binding``) and v1 List bulk create, the scheduler's bulk binding
cycle, and ``drain_step`` — the fused drain over a device-resident cluster
encoding — against the host ``gang_drain`` it replaces.

Reference anchors: ``pkg/registry/core/pod/storage/storage.go``
(BindingREST.Create — generalized to a batch) and
``pkg/scheduler/internal/cache/cache.go`` (UpdateSnapshot — generalized to
an HBM-resident snapshot updated by the device program itself).
"""

import numpy as np
import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture()
def api():
    server = APIServer().start()
    yield server
    server.stop()


def _seed(client, n_nodes=4, n_pods=6):
    for i in range(n_nodes):
        client.nodes().create(
            make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": "16"})
            .obj().to_dict())
    for i in range(n_pods):
        client.pods("default").create(
            make_pod(f"p{i}").req({"cpu": "100m"}).obj().to_dict())


def test_store_bind_many_per_item_results():
    s = ObjectStore()
    s.create("Pod", make_pod("a").obj().to_dict())
    s.create("Pod", make_pod("b").obj().to_dict())
    # pre-bind b so the second item conflicts
    pod_b = s.get("Pod", "default", "b")
    pod_b["spec"]["nodeName"] = "other"
    s.update("Pod", pod_b)
    errs = s.bind_many([("default", "a", "n1"),
                        ("default", "b", "n1"),
                        ("default", "missing", "n1")])
    assert errs[0] is None
    assert "bound" in errs[1]
    assert "not found" in errs[2]
    assert s.get("Pod", "default", "a")["spec"]["nodeName"] == "n1"
    assert s.get("Pod", "default", "b")["spec"]["nodeName"] == "other"


def test_store_bind_many_emits_watch_events():
    s = ObjectStore()
    s.create("Pod", make_pod("a").obj().to_dict())
    w = s.watch("Pod", since_rv=s.resource_version)
    s.bind_many([("default", "a", "n1")])
    ev = w.get(timeout=1.0)
    assert ev is not None and ev.type == "MODIFIED"
    assert ev.object["spec"]["nodeName"] == "n1"
    w.stop()


def test_http_bulk_binding(api):
    c = HTTPClient(api.url)
    _seed(c, n_nodes=2, n_pods=3)
    errs = c.pods("default").bind_many([
        ("default", "p0", "n0"), ("default", "p1", "n1"),
        ("default", "p0", "n1"),  # already bound above -> conflict
    ])
    assert errs[0] is None and errs[1] is None
    assert errs[2] is not None
    assert c.pods("default").get("p0")["spec"]["nodeName"] == "n0"


def test_store_create_many_single_pass_and_events():
    s = ObjectStore()
    w = s.watch("Pod", since_rv=0)
    out = s.create_many("Pod", [make_pod(f"x{i}").obj().to_dict()
                                for i in range(5)])
    assert len(out) == 5
    assert all(o["metadata"]["resourceVersion"] for o in out)
    seen = [w.get(timeout=1.0) for _ in range(5)]
    assert all(ev is not None and ev.type == "ADDED" for ev in seen)
    w.stop()


def test_http_list_bulk_create(api):
    c = HTTPClient(api.url)
    c.pods("default").create_many([make_pod(f"b{i}").obj().to_dict()
                                   for i in range(4)])
    assert len(c.pods("default").list()) == 4
    # per-item failure (duplicate) raises after siblings commit
    with pytest.raises(Exception):
        c.pods("default").create_many([
            make_pod("b0").obj().to_dict(),   # duplicate
            make_pod("fresh").obj().to_dict()])
    assert c.pods("default").get("fresh")["metadata"]["name"] == "fresh"


def test_event_payloads_share_but_clients_get_copies():
    """Store events share the authoritative dict (zero-copy fan-out); the
    DirectClient watch detaches copies so handlers can scribble safely."""
    s = ObjectStore()
    c = DirectClient(s)
    w = c.pods("default").watch(since_rv=0)
    c.pods("default").create(make_pod("z").obj().to_dict())
    ev = w.get(timeout=1.0)
    ev.object["spec"]["nodeName"] = "scribbled"
    assert s.get("Pod", "default", "z")["spec"].get("nodeName") is None \
        or s.get("Pod", "default", "z")["spec"].get("nodeName") != "scribbled"
    w.stop()


# ---- device-resident drain ------------------------------------------------


def _drain_fixture(n_pods=96, n_nodes=16, P=16, B=4):
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": "32"})
             .label("zone", f"z{i % 3}").obj() for i in range(n_nodes)]
    for n in nodes:
        cache.add_node(n)
    pods = [make_pod(f"d{i}").req({"cpu": "500m", "memory": "256Mi"}).obj()
            for i in range(n_pods)]
    _, ct, meta = cache.snapshot(pending_pods=pods[:P],
                                 slot_headroom=n_pods + B * P)
    chunks = [pods[i * P:(i + 1) * P] for i in range(B)]
    pbs = [cache.encode_pods(c, meta, min_p=P) for c in chunks]
    return cache, ct, meta, pods, pbs, P, B


def test_drain_step_matches_host_gang_drain():
    import jax
    from kubernetes_tpu.models.gang import (
        build_drain_context, drain_step, gang_drain, prepare_drain,
        unify_batches)
    cache, ct, meta, pods, pbs, P, B = _drain_fixture()
    host_asgn, _, _ = gang_drain(ct, pbs, topo_keys=meta.topo_keys)
    built = build_drain_context(ct, pbs)
    assert built is not None
    ct_dev, e0, fill = built
    pb_stack = jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *unify_batches(pbs))
    dev_asgn, rounds, _, new_fill = drain_step(
        ct_dev, pb_stack, fill, e0=e0, seed=0,
        fit_strategy="LeastAllocated", topo_keys=meta.topo_keys,
        weights=(), enabled_filters=(), max_rounds=64)
    dev_asgn, new_fill = jax.device_get((dev_asgn, new_fill))
    np.testing.assert_array_equal(np.asarray(host_asgn), dev_asgn)
    assert int(new_fill) == fill + int((dev_asgn >= 0).sum())


def test_drain_step_chains_capacity_across_calls():
    """Successive drain_steps over the resident encoding must see earlier
    placements: a saturating workload schedules exactly up to capacity."""
    import jax
    from kubernetes_tpu.models.gang import (
        build_drain_context, drain_step, unify_batches)
    from kubernetes_tpu.sched.cache import SchedulerCache
    cache = SchedulerCache()
    # 2 nodes x 4 cpu; each pod wants 1 cpu -> exactly 8 fit
    for i in range(2):
        cache.add_node(make_node(f"n{i}")
                       .capacity({"cpu": "4", "memory": "64Gi", "pods": "99"}).obj())
    pods = [make_pod(f"s{i}").req({"cpu": "1"}).obj() for i in range(16)]
    P, B = 4, 2
    _, ct, meta = cache.snapshot(pending_pods=pods[:P],
                                 slot_headroom=32)
    mk = lambda lo: [cache.encode_pods(pods[lo + i * P:lo + (i + 1) * P],
                                       meta, min_p=P) for i in range(B)]
    pbs = mk(0)
    built = build_drain_context(ct, pbs)
    ct_dev, e0, fill = built
    kw = dict(e0=e0, seed=0, fit_strategy="LeastAllocated",
              topo_keys=meta.topo_keys, weights=(), enabled_filters=(),
              max_rounds=64)
    stack = lambda ps: jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *unify_batches(ps))
    a1, _, ct_dev, fill = drain_step(ct_dev, stack(pbs), fill, **kw)
    a2, _, ct_dev, fill = drain_step(ct_dev, stack(mk(8)), fill, **kw)
    a1, a2, fill = jax.device_get((a1, a2, fill))
    assert int((a1 >= 0).sum()) == 8       # first 8 pods fill the cluster
    assert int((a2 >= 0).sum()) == 0       # second drain sees it full
    assert int(fill) == 8


def test_connected_scheduler_bulk_binds_end_to_end(api):
    """SchedulerRunner against the apiserver: everything binds through the
    bulk path (no per-pod binding POSTs for plain pods)."""
    import time
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    c = HTTPClient(api.url)
    _seed(c, n_nodes=4, n_pods=12)
    runner = SchedulerRunner(HTTPClient(api.url),
                             SchedulerConfiguration(batch_size=8))
    runner.start()
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            bound = sum(1 for p in c.pods("default").list()
                        if p["spec"].get("nodeName"))
            if bound == 12:
                break
            time.sleep(0.2)
        assert bound == 12
    finally:
        runner.stop()
