"""Integration: apiserver + connected scheduler, no kubelet — pods get bound.

Mirrors the reference's integration tier (test/integration/scheduler/): real
API server + real scheduler, node readiness faked, success = spec.nodeName set
by the watch-driven pipeline.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def wait_for(pred, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster():
    server = APIServer().start()
    client = HTTPClient(server.url)
    runner = SchedulerRunner(client, SchedulerConfiguration(
        backoff_initial_s=0.05, backoff_max_s=0.2))
    runner.start()
    yield server, client, runner
    runner.stop()
    server.stop()


def test_pods_scheduled_through_apiserver(cluster):
    server, client, runner = cluster
    nodes = client.nodes()
    for i in range(3):
        nodes.create(make_node(f"n{i}")
                     .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
                     .obj().to_dict())
    pods = client.pods("default")
    for i in range(6):
        pods.create(make_pod(f"p{i}").req({"cpu": "500m"}).obj().to_dict())

    def all_bound():
        return all(p["spec"].get("nodeName") for p in pods.list())

    assert wait_for(all_bound, 15), [
        (p["metadata"]["name"], p["spec"].get("nodeName")) for p in pods.list()]
    # spread across nodes by LeastAllocated
    assigned = {p["spec"]["nodeName"] for p in pods.list()}
    assert len(assigned) == 3


def test_unschedulable_pod_schedules_after_node_add(cluster):
    server, client, runner = cluster
    pods = client.pods("default")
    pods.create(make_pod("waiting").req({"cpu": "2"}).obj().to_dict())
    time.sleep(0.4)
    assert not pods.get("waiting")["spec"].get("nodeName")
    client.nodes().create(make_node("late").capacity(
        {"cpu": "4", "pods": "10"}).obj().to_dict())
    assert wait_for(lambda: pods.get("waiting")["spec"].get("nodeName") == "late", 15)


def test_preemption_through_api(cluster):
    server, client, runner = cluster
    client.nodes().create(make_node("only").capacity(
        {"cpu": "2", "pods": "5"}).obj().to_dict())
    pods = client.pods("default")
    pods.create(make_pod("victim").req({"cpu": "2"}).priority(1).obj().to_dict())
    assert wait_for(lambda: pods.get("victim")["spec"].get("nodeName"), 15)
    pods.create(make_pod("vip").req({"cpu": "2"}).priority(100).obj().to_dict())
    # victim evicted, vip bound
    assert wait_for(
        lambda: not any(p["metadata"]["name"] == "victim" for p in pods.list())
        and (pods.get("vip")["spec"].get("nodeName") or None), 20), \
        [(p["metadata"]["name"], p["spec"].get("nodeName")) for p in pods.list()]


def test_foreign_scheduler_pods_left_alone(cluster):
    server, client, runner = cluster
    client.nodes().create(make_node("n").capacity({"cpu": "4"}).obj().to_dict())
    pods = client.pods("default")
    pods.create(make_pod("foreign").scheduler_name("their-scheduler").obj().to_dict())
    time.sleep(0.6)
    assert not pods.get("foreign")["spec"].get("nodeName")


def test_externally_bound_pod_leaves_queue(cluster):
    """A pod bound by another party while queued must be dropped from the
    scheduling queue (regression: it was double-counted — pending in the
    batch AND bound in the cache — and retried in a 409 loop forever)."""
    _, client, runner = cluster
    client.nodes().create(make_node("n-ext").capacity({"cpu": "4"}).obj().to_dict())
    pod = make_pod("ext-bound").req({"cpu": "1"}).obj().to_dict()
    client.pods("default").create(pod)
    # bind it out from under the scheduler, as a split-brain peer would
    try:
        client.pods("default").bind("ext-bound", "n-ext")
    except Exception:
        pass  # scheduler may have bound it first — equally fine
    assert wait_for(
        lambda: client.pods("default").get("ext-bound")["spec"].get("nodeName"))
    # the queue must drain: no perpetual 409 retry loop for this pod
    assert wait_for(
        lambda: "default/ext-bound" not in runner.queue._keys_queued)


def test_start_loop_displaces_previous_term():
    """A re-elected leader's new loop must displace the old term's loop, not
    stack a concurrent one — and must actually start even if the old loop is
    still draining (regression: silent skip left a leader scheduling nothing)."""
    client = DirectClient(ObjectStore())
    runner = SchedulerRunner(client, SchedulerConfiguration(batch_size=4))
    runner._start_loop()
    first_t, first_s = runner._loop_thread, runner._loop_stop
    runner._start_loop()              # new term while the old loop still runs
    assert wait_for(lambda: first_s.is_set() and not first_t.is_alive())
    assert runner._loop_thread.is_alive()
    runner.stop()


def test_terminal_pods_release_capacity(cluster):
    """A node full of Succeeded pods must accept new ones (the reference's
    scheduler informer filters terminated pods; eventhandlers.go)."""
    server, client, runner = cluster
    client.nodes().create(make_node("solo")
                          .capacity({"cpu": "2", "pods": "10"})
                          .obj().to_dict())
    pods = client.pods("default")
    # two pods saturate the 2-cpu node
    for i in range(2):
        pods.create(make_pod(f"fill{i}").req({"cpu": "1"}).obj().to_dict())
    assert wait_for(lambda: all(p["spec"].get("nodeName")
                                for p in pods.list()), 15)
    # a third can't fit...
    pods.create(make_pod("next").req({"cpu": "1"}).obj().to_dict())
    time.sleep(0.5)
    assert not pods.get("next")["spec"].get("nodeName")
    # ...until the fillers terminate
    for i in range(2):
        p = pods.get(f"fill{i}")
        p["status"] = {"phase": "Succeeded"}
        pods.update_status(p)
    assert wait_for(lambda: pods.get("next")["spec"].get("nodeName"), 15)
