"""Volume scheduling: VolumeBinding/VolumeZone/VolumeRestrictions/
NodeVolumeLimits — tensor path vs oracle parity, plus the VolumeBinder."""

import numpy as np
import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.ops.filters import run_filters
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.sched.volumebinding import (
    SELECTED_NODE_ANNOTATION,
    VolumeBinder,
    VolumeCatalog,
    compile_pod_volumes,
)
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def pv(name, capacity="10Gi", zone=None, sc="", modes=("ReadWriteOnce",),
       node_affinity_values=None, claim=None):
    obj = {"apiVersion": "v1", "kind": "PersistentVolume",
           "metadata": {"name": name, "labels": {}},
           "spec": {"capacity": {"storage": capacity},
                    "accessModes": list(modes),
                    "storageClassName": sc},
           "status": {"phase": "Available"}}
    if zone:
        obj["metadata"]["labels"]["topology.kubernetes.io/zone"] = zone
    if node_affinity_values:
        obj["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [{"key": "topology.kubernetes.io/zone",
                                   "operator": "In",
                                   "values": list(node_affinity_values)}]}]}}
    if claim:
        ns, nm = claim
        obj["spec"]["claimRef"] = {"namespace": ns, "name": nm}
    return obj


def pvc(name, request="5Gi", sc="", modes=("ReadWriteOnce",), volume_name="",
        ns="default"):
    obj = {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
           "metadata": {"name": name, "namespace": ns},
           "spec": {"accessModes": list(modes),
                    "resources": {"requests": {"storage": request}},
                    "storageClassName": sc},
           "status": {}}
    if volume_name:
        obj["spec"]["volumeName"] = volume_name
    return obj


def storage_class(name, provisioner="csi.example.com"):
    return {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": {"name": name}, "provisioner": provisioner}


def pod_with_pvc(name, *claims):
    p = make_pod(name).req({"cpu": "100m"}).obj()
    p.spec.volumes = [{"name": f"v{i}", "persistentVolumeClaim":
                       {"claimName": c}} for i, c in enumerate(claims)]
    return p


def zone_nodes():
    return [make_node(f"n-{z}").label("topology.kubernetes.io/zone", z)
            .allocatable({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj()
            for z in ("a", "b", "c")]


def tensor_feasible(nodes, pods, bound=(), catalog=None):
    enc = SnapshotEncoder()
    if catalog is not None:
        enc.set_volumes(catalog)
    ct, meta = enc.encode_cluster(nodes, list(bound), pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    mask = np.asarray(run_filters(ct, pb))
    return mask[:len(pods), :len(nodes)]


def oracle_feasible(nodes, pods, bound=(), catalog=None):
    orc = OracleScheduler(nodes, list(bound), volumes=catalog)
    return np.array([[r is None for r in orc.feasible(p)[1]] for p in pods]) \
        if hasattr(orc, "feasible") else None


def assert_parity(nodes, pods, bound=(), catalog=None):
    got = tensor_feasible(nodes, pods, bound, catalog)
    orc = OracleScheduler(nodes, list(bound), volumes=catalog)
    for i, p in enumerate(pods):
        mask, _ = orc.feasible(p)
        assert list(got[i]) == list(mask), (
            p.metadata.name, list(got[i]), list(mask))
    return got


def test_bound_pv_node_affinity_constrains_pod():
    nodes = zone_nodes()
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("data", volume_name="pv-a")],
        pvs=[pv("pv-a", node_affinity_values=["a"], claim=("default", "data"))])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=catalog)
    assert list(got[0]) == [True, False, False]


def test_pv_zone_label_is_volumezone():
    nodes = zone_nodes()
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("data", volume_name="pv-z")],
        pvs=[pv("pv-z", zone="b", claim=("default", "data"))])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=catalog)
    assert list(got[0]) == [False, True, False]


def test_unbound_pvc_candidates_or_semantics():
    nodes = zone_nodes()
    # two candidate PVs in zones a and c -> pod can go to either
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("data")],
        pvs=[pv("pv1", node_affinity_values=["a"]),
             pv("pv2", node_affinity_values=["c"])])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=catalog)
    assert list(got[0]) == [True, False, True]


def test_two_pvcs_and_semantics():
    nodes = zone_nodes()
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("d1"), pvc("d2", volume_name="pv2")],
        pvs=[pv("pv1", node_affinity_values=["a", "b"]),
             pv("pv2", node_affinity_values=["b", "c"], claim=("default", "d2"))])
    got = assert_parity(nodes, [pod_with_pvc("p", "d1", "d2")], catalog=catalog)
    assert list(got[0]) == [False, True, False]


def test_missing_pvc_blocks_everywhere():
    nodes = zone_nodes()
    catalog = VolumeCatalog.from_lists(pvcs=[], pvs=[])
    got = assert_parity(nodes, [pod_with_pvc("p", "ghost")], catalog=catalog)
    assert not got[0].any()


def test_no_match_no_provisioner_blocks_but_storageclass_unblocks():
    nodes = zone_nodes()
    c1 = VolumeCatalog.from_lists(pvcs=[pvc("data", sc="fast")], pvs=[])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=c1)
    assert not got[0].any()
    c2 = VolumeCatalog.from_lists(pvcs=[pvc("data", sc="fast")], pvs=[],
                                  storage_classes=[storage_class("fast")])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=c2)
    assert got[0].all()  # provisionable anywhere (WaitForFirstConsumer shape)


def test_capacity_and_class_matching():
    nodes = zone_nodes()
    # pv too small + pv wrong class -> only pv-ok matches (zone b)
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("data", request="8Gi")],
        pvs=[pv("pv-small", capacity="1Gi"),
             pv("pv-class", sc="other"),
             pv("pv-ok", node_affinity_values=["b"])])
    got = assert_parity(nodes, [pod_with_pvc("p", "data")], catalog=catalog)
    assert list(got[0]) == [False, True, False]


def test_rwo_conflict_with_existing_pod():
    nodes = zone_nodes()
    bound = pod_with_pvc("existing", "data")
    bound.spec.node_name = "n-a"
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("data", volume_name="pv-x"), pvc("other", volume_name="pv-x2")],
        pvs=[pv("pv-x", claim=("default", "data")),
             pv("pv-x2", claim=("default", "other"))])
    # incoming pod mounts the SAME rwo pv -> n-a blocked; different pv -> free
    got = assert_parity(nodes, [pod_with_pvc("p1", "data"),
                                pod_with_pvc("p2", "other")],
                        bound=[bound], catalog=catalog)
    assert list(got[0]) == [False, True, True]
    assert list(got[1]) == [True, True, True]


def test_attach_limits():
    nodes = zone_nodes()
    for n in nodes:
        n.status.allocatable["attachable-volumes-csi-x"] = "1"
    bound = pod_with_pvc("existing", "d0")
    bound.spec.node_name = "n-a"
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("d0", volume_name="pv0"), pvc("d1", volume_name="pv1")],
        pvs=[pv("pv0", claim=("default", "d0")),
             pv("pv1", claim=("default", "d1"))],
    )
    got = assert_parity(nodes, [pod_with_pvc("p", "d1")],
                        bound=[bound], catalog=catalog)
    assert list(got[0]) == [False, True, True]  # n-a at its 1-volume limit


def test_rwop_in_use_blocks_everywhere():
    nodes = zone_nodes()
    bound = pod_with_pvc("holder", "excl")
    bound.spec.node_name = "n-b"
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("excl", volume_name="pv-e", modes=("ReadWriteOncePod",))],
        pvs=[pv("pv-e", modes=("ReadWriteOncePod",), claim=("default", "excl"))])
    got = assert_parity(nodes, [pod_with_pvc("p", "excl")],
                        bound=[bound], catalog=catalog)
    assert not got[0].any()


# ---------------------------------------------------------------- binder

def test_volume_binder_static_bind():
    client = DirectClient(ObjectStore())
    client.resource("persistentvolumeclaims").create(pvc("data"))
    client.resource("persistentvolumes", None).create(
        pv("pv-b", node_affinity_values=["b"]))
    catalog = VolumeCatalog.from_lists(
        pvcs=[client.resource("persistentvolumeclaims").get("data")],
        pvs=[client.resource("persistentvolumes", None).get("pv-b")])
    binder = VolumeBinder(client)
    p = pod_with_pvc("p", "data")
    ok = binder.bind_pod_volumes(
        p, None, catalog,
        node_labels={"topology.kubernetes.io/zone": "b"}, node_name="n-b")
    assert ok
    got_pvc = client.resource("persistentvolumeclaims").get("data")
    got_pv = client.resource("persistentvolumes", None).get("pv-b")
    assert got_pvc["spec"]["volumeName"] == "pv-b"
    assert got_pv["spec"]["claimRef"]["name"] == "data"
    assert got_pv["status"]["phase"] == "Bound"


def test_volume_binder_annotates_for_provisioner():
    client = DirectClient(ObjectStore())
    client.resource("persistentvolumeclaims").create(pvc("dyn", sc="fast"))
    catalog = VolumeCatalog.from_lists(
        pvcs=[client.resource("persistentvolumeclaims").get("dyn")],
        storage_classes=[storage_class("fast")])
    binder = VolumeBinder(client)
    ok = binder.bind_pod_volumes(pod_with_pvc("p", "dyn"), None, catalog,
                                 node_labels={}, node_name="n-a")
    assert ok
    got = client.resource("persistentvolumeclaims").get("dyn")
    assert got["metadata"]["annotations"][SELECTED_NODE_ANNOTATION] == "n-a"


def test_compile_groups_shape():
    catalog = VolumeCatalog.from_lists(
        pvcs=[pvc("a", volume_name="pv1"), pvc("b")],
        pvs=[pv("pv1", claim=("default", "a")), pv("pv2"), pv("pv3")])
    info = compile_pod_volumes(pod_with_pvc("p", "a", "b"), catalog)
    assert len(info.groups) == 2
    assert len(info.groups[0]) == 1      # bound: the PV's terms
    assert len(info.groups[1]) == 2      # candidates pv2, pv3
    assert info.claims_to_bind == ["b"]
    assert info.attach_count == 2


# ------------------------------------------------- connected end-to-end

def test_e2e_pod_with_waitforfirstconsumer_volume():
    """pod + unbound PVC (dynamic class) -> scheduler picks a node, annotates/
    binds via VolumeBinder, pvbinder provisions a node-pinned PV, pod binds."""
    import time as _t

    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.sched.runner import SchedulerRunner

    def wait_until(fn, timeout=20.0):
        dl = _t.time() + timeout
        while _t.time() < dl:
            if fn():
                return True
            _t.sleep(0.05)
        return fn()

    client = DirectClient(ObjectStore())
    for z in ("a", "b"):
        client.nodes().create(
            make_node(f"vn-{z}").label("topology.kubernetes.io/zone", z)
            .allocatable({"cpu": "4", "memory": "8Gi", "pods": "20"})
            .obj().to_dict())
    client.resource("storageclasses", None).create(
        {**storage_class("fast"), "volumeBindingMode": "WaitForFirstConsumer"})
    client.resource("persistentvolumeclaims").create(pvc("data", sc="fast"))

    mgr = ControllerManager(client, controllers=("pvbinder",),
                            resync_period=0.3, gc_enabled=False).start()
    sched = SchedulerRunner(client).start()
    try:
        p = pod_with_pvc("stateful", "data")
        client.pods().create(p.to_dict())
        assert wait_until(
            lambda: client.pods().get("stateful")["spec"].get("nodeName"))
        node = client.pods().get("stateful")["spec"]["nodeName"]
        # provisioner created a PV pinned to the chosen node and bound it
        assert wait_until(
            lambda: client.resource("persistentvolumeclaims").get("data")
            .get("spec", {}).get("volumeName"))
        pv_name = client.resource("persistentvolumeclaims").get("data") \
            ["spec"]["volumeName"]
        got_pv = client.resource("persistentvolumes", None).get(pv_name)
        pins = (got_pv["spec"]["nodeAffinity"]["required"]
                ["nodeSelectorTerms"][0]["matchFields"][0]["values"])
        assert pins == [node]
    finally:
        sched.stop()
        mgr.stop()


def test_e2e_static_pv_constrains_scheduling():
    """Immediate-mode PVC binds to a zone-a PV via pvbinder; the scheduler
    must then place the pod in zone a."""
    import time as _t

    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.sched.runner import SchedulerRunner

    def wait_until(fn, timeout=20.0):
        dl = _t.time() + timeout
        while _t.time() < dl:
            if fn():
                return True
            _t.sleep(0.05)
        return fn()

    client = DirectClient(ObjectStore())
    for z in ("a", "b"):
        client.nodes().create(
            make_node(f"sn-{z}").label("topology.kubernetes.io/zone", z)
            .allocatable({"cpu": "4", "memory": "8Gi", "pods": "20"})
            .obj().to_dict())
    client.resource("persistentvolumes", None).create(
        pv("static-a", node_affinity_values=["a"]))
    client.resource("persistentvolumeclaims").create(pvc("disk"))

    mgr = ControllerManager(client, controllers=("pvbinder",),
                            resync_period=0.3, gc_enabled=False).start()
    sched = SchedulerRunner(client).start()
    try:
        assert wait_until(
            lambda: client.resource("persistentvolumeclaims").get("disk")
            .get("spec", {}).get("volumeName") == "static-a")
        client.pods().create(pod_with_pvc("user", "disk").to_dict())
        assert wait_until(
            lambda: client.pods().get("user")["spec"].get("nodeName") == "sn-a")
    finally:
        sched.stop()
        mgr.stop()
