"""Durable store: WAL + snapshot restore (store.py data_dir mode).

Reference role: etcd's WAL + snapshot cycle under
``storage/etcd3/store.go`` — the apiserver process is restartable without
losing cluster state, and watchers relist across the restart boundary
(TooOld), exactly like clients of a compacted etcd.
"""

import json
import os

import pytest

from kubernetes_tpu.client.clientset import HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.store import ObjectStore, TooOld
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def test_wal_restore_roundtrip(tmp_path):
    d = str(tmp_path / "data")
    s = ObjectStore(data_dir=d)
    s.create("Pod", make_pod("a").obj().to_dict())
    s.create("Pod", make_pod("b").obj().to_dict())
    b = s.get("Pod", "default", "b")
    b["spec"]["nodeName"] = "n1"
    s.update("Pod", b)
    s.delete("Pod", "default", "a")
    rv = s.resource_version
    s.close()  # no explicit save: the WAL alone must reconstruct

    s2 = ObjectStore(data_dir=d)
    assert s2.resource_version == rv
    pods, _ = s2.list("Pod")
    assert [p["metadata"]["name"] for p in pods] == ["b"]
    assert pods[0]["spec"]["nodeName"] == "n1"


def test_wal_compaction_truncates_and_restores(tmp_path):
    d = str(tmp_path / "data")
    s = ObjectStore(data_dir=d, wal_compact_every=8)
    for i in range(30):
        s.create("ConfigMap", {"kind": "ConfigMap",
                               "metadata": {"name": f"cm-{i}",
                                            "namespace": "default"}})
    wal_lines = open(os.path.join(d, "wal.jsonl")).read().splitlines()
    assert len(wal_lines) < 30, "journal should have been folded into snapshot"
    assert os.path.exists(os.path.join(d, "snapshot.json"))
    s.close()
    s2 = ObjectStore(data_dir=d)
    cms, _ = s2.list("ConfigMap")
    assert len(cms) == 30


def test_torn_wal_tail_discarded(tmp_path):
    d = str(tmp_path / "data")
    s = ObjectStore(data_dir=d)
    s.create("Pod", make_pod("ok").obj().to_dict())
    s.close()
    with open(os.path.join(d, "wal.jsonl"), "a") as f:
        f.write('{"op": "set", "kind": "Pod", "ns": "default", "na')  # torn
    s2 = ObjectStore(data_dir=d)
    pods, _ = s2.list("Pod")
    assert [p["metadata"]["name"] for p in pods] == ["ok"]


def test_torn_wal_tail_truncated_at_every_byte_offset(tmp_path):
    """SIGKILL can land mid-append at ANY byte: truncate the WAL at every
    offset inside its final record and prove restore (a) never crashes,
    (b) keeps every committed record, (c) admits the final record ONLY
    when its commit marker — the trailing newline — survived, and (d)
    counts + truncates the torn bytes off disk so a post-restore append
    cannot merge into them."""
    import shutil

    from kubernetes_tpu.metrics.registry import WAL_TORN_TAIL
    base = str(tmp_path / "base")
    s = ObjectStore(data_dir=base)
    for i in range(3):
        s.create("Pod", make_pod(f"pre-{i}").obj().to_dict())
    s.create("Pod", make_pod("final").obj().to_dict())  # the last record
    s.close()
    wal = open(os.path.join(base, "wal.jsonl"), "rb").read()
    last_start = wal.rstrip(b"\n").rfind(b"\n") + 1
    assert 0 < last_start < len(wal)
    for cut in range(last_start, len(wal) + 1):
        d = str(tmp_path / f"cut-{cut}")
        shutil.copytree(base, d)
        with open(os.path.join(d, "wal.jsonl"), "wb") as f:
            f.write(wal[:cut])
        before = WAL_TORN_TAIL.get()
        s2 = ObjectStore(data_dir=d)
        names = sorted(p["metadata"]["name"] for p in s2.list("Pod")[0])
        if cut == len(wal):  # full record incl. newline: committed
            assert names == ["final", "pre-0", "pre-1", "pre-2"]
            assert WAL_TORN_TAIL.get() == before
        elif cut == last_start:  # cut exactly between records: no tear
            assert names == ["pre-0", "pre-1", "pre-2"]
            assert WAL_TORN_TAIL.get() == before
        else:
            # torn: the final record never committed — dropped, counted,
            # and the file truncated back to the last committed offset
            assert names == ["pre-0", "pre-1", "pre-2"], (cut, names)
            assert WAL_TORN_TAIL.get() == before + 1
            assert os.path.getsize(os.path.join(d, "wal.jsonl")) \
                == last_start
            assert s2.durability_stats()["tornTailsDropped"] == 1
        # appends after the (possibly truncated) restore stay clean: a
        # fresh write must survive the NEXT restore intact
        s2.create("Pod", make_pod("post").obj().to_dict())
        s2.close()
        s3 = ObjectStore(data_dir=d)
        assert "post" in {p["metadata"]["name"] for p in s3.list("Pod")[0]}
        s3.close()
        shutil.rmtree(d)


def test_generate_name_never_reissued_across_restart(tmp_path):
    d = str(tmp_path / "data")
    s = ObjectStore(data_dir=d)
    p = make_pod("x").obj().to_dict()
    p["metadata"].pop("name")
    p["metadata"]["generateName"] = "gen-"
    first = s.create("Pod", dict(p))["metadata"]["name"]
    s.close()
    s2 = ObjectStore(data_dir=d)
    second = s2.create("Pod", dict(p))["metadata"]["name"]
    assert first != second


def test_watch_relists_after_restore(tmp_path):
    d = str(tmp_path / "data")
    s = ObjectStore(data_dir=d)
    s.create("Pod", make_pod("a").obj().to_dict())
    old_rv = s.resource_version
    s.close()
    s2 = ObjectStore(data_dir=d)
    # pre-restart rv is below the restore floor -> TooOld -> client relists
    with pytest.raises(TooOld):
        s2.watch("Pod", since_rv=old_rv - 1)
    w = s2.watch("Pod", since_rv=s2.resource_version)
    s2.create("Pod", make_pod("b").obj().to_dict())
    ev = w.get(timeout=1.0)
    assert ev is not None and ev.object["metadata"]["name"] == "b"


def test_apiserver_restart_preserves_bindings(tmp_path):
    d = str(tmp_path / "data")
    server = APIServer(data_dir=d).start()
    c = HTTPClient(server.url)
    c.nodes().create(make_node("n1").capacity(
        {"cpu": "4", "pods": "10"}).obj().to_dict())
    c.pods().create(make_pod("w").req({"cpu": "1"}).obj().to_dict())
    c.pods().bind("w", "n1")
    server.stop()

    server2 = APIServer(data_dir=d).start()
    try:
        c2 = HTTPClient(server2.url)
        pod = c2.pods().get("w")
        assert pod["spec"]["nodeName"] == "n1"
        assert [n["metadata"]["name"] for n in c2.nodes().list()] == ["n1"]
    finally:
        server2.stop()
