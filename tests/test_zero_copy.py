"""Zero-copy steady state: the transfer-guard invariant (tier-1).

The steady-state drain cycle — stage -> fused dispatch (drain + churn
fold) -> resolve -> preemption wave — must perform ZERO implicit host
transfers: every upload is an explicit staged put (batch stack, churn
patch, wave inputs), every download is the explicit O(P) winners fetch or
a shadow read that never touches the device. ``jax.transfer_guard
("disallow")`` turns any regression into a loud XlaRuntimeError instead
of a silent throughput dip (the MULTICHIP_r06 failure mode: a 381->1641ms
transfer hiding inside a dispatch span for two rounds).

Runs single-device with no mesh so it guards every tier-1 run; the
mesh-sharded twin of the staging path is covered by test_staging.py's
parity matrix. Compiles and context rebuilds happen OUTSIDE the guard —
they are planned cold-path work; the guard brackets only the steady-state
cycles the ISSUE's zero-copy contract is about.
"""

import logging
import time
import warnings

import jax
import numpy as np
import pytest

from kubernetes_tpu.config.types import SchedulerConfiguration, validate
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _nodes(n=16, cpu="8"):
    return [make_node(f"n{i:03d}")
            .capacity({"cpu": cpu, "memory": "16Gi", "pods": "20"})
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .obj() for i in range(n)]


def _pods(n, prefix="p", cpu="500m", prio=0):
    return [make_pod(f"{prefix}{i:03d}")
            .req({"cpu": cpu, "memory": "256Mi"}).priority(prio).obj()
            for i in range(n)]


def _scheduler(nodes, batch_size=8):
    cfg = SchedulerConfiguration(batch_size=batch_size, max_drain_batches=2)
    validate(cfg)
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    warm = [make_pod(f"__warm{i}").req({"cpu": "100m"}).obj()
            for i in range(batch_size)]
    assert sched.warm_drain(warm, slot_headroom=256)
    return sched, cache, queue, log


def _drain(sched, queue, pods, rounds=30):
    for p in pods:
        queue.add(p)
    bound = 0
    for _ in range(rounds):
        bound += sched.run_once(wait=0.01)
        if not sched._pending and not queue.stats()["active"]:
            break
    bound += sched._resolve_pending()
    return bound


def _assert_no_absorbed_trips(caplog, errors_before):
    """A tripped guard never ESCAPES the scheduler — its self-healing
    absorbs the XlaRuntimeError and degrades (breaker, per-batch path,
    serial wave). Every such absorption logs with exc_info and most bump
    scheduler_loop_errors_total, so the invariant is pinned on BOTH: zero
    exception-carrying log records and an unchanged error counter."""
    from kubernetes_tpu.metrics.registry import LOOP_ERRORS
    absorbed = [r for r in caplog.records if r.exc_info]
    assert not absorbed, [r.getMessage() for r in absorbed]
    assert LOOP_ERRORS.items() == errors_before


def test_steady_state_cycle_zero_implicit_transfers(caplog):
    """One fused drain+fold cycle under transfer_guard("disallow"): the
    resident context, staged batch stack, device fill scalar, and staged
    churn patch make the dispatch all-device; the resolve is one explicit
    device_get. Any implicit transfer raises — and since the scheduler
    self-heals, the assertion is that NOTHING had to heal."""
    from kubernetes_tpu.metrics.registry import LOOP_ERRORS
    sched, cache, queue, log = _scheduler(_nodes())
    # cold path outside the guard: first pop rebuilds the resident ctx and
    # compiles the drain variants
    assert _drain(sched, queue, _pods(16)) == 16
    assert sched._drain_ctx is not None
    errors_before = LOOP_ERRORS.items()
    rebuilds_before = sched.ctx_stats["rebuilds"]
    with warnings.catch_warnings(record=True) as caught, \
            caplog.at_level(logging.WARNING, logger="kubernetes_tpu"):
        warnings.simplefilter("always")
        with jax.transfer_guard("disallow"):
            # steady state: plain drains
            assert _drain(sched, queue, _pods(16, prefix="q")) == 16
            # churn -> fused fold rides the next dispatch (three-input
            # drain, patch explicitly staged)
            cache.add_node(
                make_node("late-node")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
                .label("kubernetes.io/hostname", "late-node").obj())
            assert _drain(sched, queue, _pods(16, prefix="r")) == 16
    assert sched.ctx_stats["folds"] >= 1, "churn did not fuse"
    assert sched.ctx_stats["rebuilds"] == rebuilds_before, \
        "steady-state cycle dropped the resident context"
    _assert_no_absorbed_trips(caplog, errors_before)
    donate = [str(w.message) for w in caught
              if "donated" in str(w.message).lower()]
    assert not donate, donate
    sched.wait_for_bindings()
    sched.close()


def test_steady_state_wave_zero_implicit_transfers(caplog):
    """The preemption wave as a stage of the resident program: static
    masks on the device-resident encoding, cluster totals from the host
    shadow (zero device round-trips), wave-scan inputs explicitly staged.
    Guarded end to end."""
    from kubernetes_tpu.metrics.registry import LOOP_ERRORS
    nodes = _nodes(4, cpu="8")
    sched, cache, queue, log = _scheduler(nodes, batch_size=4)
    # saturate with low-prio so high-prio arrivals must preempt
    assert _drain(sched, queue, _pods(8, cpu="4", prio=1)) == 8
    # warm the wave programs outside the guard (planned compile work)
    warm_high = _pods(2, prefix="warmhi", cpu="4", prio=50)
    _drain(sched, queue, warm_high)
    sched._resolve_pending()
    for p in warm_high:  # clear nominations/evictions from the warm wave
        queue.delete(p)
        sched._nominated.pop(p.key, None)
    deadline = time.time() + 20
    while (sched._pending or queue.stats()["active"]) \
            and time.time() < deadline:
        sched.run_once(wait=0.01)
        sched._resolve_pending()
    assert sched._drain_ctx is not None
    high = _pods(2, prefix="hi", cpu="4", prio=100)
    errors_before = LOOP_ERRORS.items()
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu"), \
            jax.transfer_guard("disallow"):
        for p in high:
            queue.add(p)
        deadline = time.time() + 30
        while time.time() < deadline:
            sched.run_once(wait=0.01)
            sched._resolve_pending()
            if all(sched._nominated.get(p.key) or cache.is_bound(p.key)
                   for p in high):
                break
    assert all(sched._nominated.get(p.key) or cache.is_bound(p.key)
               for p in high), "wave never nominated the preemptors"
    _assert_no_absorbed_trips(caplog, errors_before)
    sched.wait_for_bindings()
    sched.close()


def test_stage_bytes_accounting():
    """Every staged batch lands on scheduler_stage_bytes_total — the
    counter a bench leg diffs to attribute h2d traffic."""
    from kubernetes_tpu.metrics.registry import STAGE_BYTES
    before = STAGE_BYTES.get({"path": "inline"})
    sched, cache, queue, log = _scheduler(_nodes(8))
    assert _drain(sched, queue, _pods(8)) == 8
    assert STAGE_BYTES.get({"path": "inline"}) > before
    sched.close()


def test_resolve_moves_only_compact_winners():
    """The resolver's device_get stays O(B*P) int32s: assignments+rounds,
    never a gathered encoding (RESOLVE_BYTES is the bench's proof)."""
    from kubernetes_tpu.metrics.registry import RESOLVE_BYTES
    sched, cache, queue, log = _scheduler(_nodes(8), batch_size=8)
    assert _drain(sched, queue, _pods(8)) == 8
    B, P = 2, 8
    assert 0 < RESOLVE_BYTES.get() <= B * P * 4 + B * 4 + 64
    sched.close()
