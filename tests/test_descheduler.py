"""Descheduler: batched eviction planning, strategies, PDB enforcement,
and the gang-defragmentation end-to-end.

Reference: ``kubernetes-sigs/descheduler`` (strategy plugins + the eviction
framework), with the per-candidate simulation replaced by one batched
``run_filters`` pass (descheduler/planner.py).
"""

import io
import json
import time

import pytest

from kubernetes_tpu.descheduler import planner as planner_mod
from kubernetes_tpu.descheduler import (
    CandidateSet,
    Descheduler,
    DeschedulerConfiguration,
    GANG_LABEL,
    gang_consolidation_candidates,
    plan_evictions,
    plan_evictions_naive,
    plan_gang_defrag,
)
from kubernetes_tpu.descheduler.strategies import (
    high_node_utilization,
    low_node_utilization,
    pods_violating_node_affinity,
    pods_violating_topology_spread,
    remove_duplicates,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod

pytestmark = pytest.mark.descheduler


def wait_for(pred, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _fragmented(n=4, node_cpu="2", filler_cpu="1"):
    """n nodes, each holding one filler pod — scattered load no single node
    can absorb a big pod through."""
    nodes = [make_node(f"n{i}")
             .capacity({"cpu": node_cpu, "memory": "4Gi", "pods": "10"})
             .obj() for i in range(n)]
    bound = [make_pod(f"filler-{i}").req({"cpu": filler_cpu})
             .node(f"n{i}").obj() for i in range(n)]
    return nodes, bound


# --------------------------------------------------------------- planner

def test_plan_is_one_batched_evaluation(monkeypatch):
    """Acceptance: K candidate sets validate via ONE run_filters call over
    the shared victim batch — no per-candidate loop on the hot path."""
    calls = {"n": 0}
    real = planner_mod.run_filters

    def counting(ct, pb, enabled=None):
        calls["n"] += 1
        return real(ct, pb, enabled)

    monkeypatch.setattr(planner_mod, "run_filters", counting)
    nodes, bound = _fragmented()
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    assert len(sets) == 4
    plan = plan_evictions(nodes, bound, sets)
    assert calls["n"] == 1, "candidate validation must be one batched call"
    assert plan.batch_sets == 4 and plan.batch_victims == 4


def test_batched_vs_naive_parity():
    """The one-call path and the per-candidate reference loop must agree on
    accepted sets, proof moves, and blocking reasons."""
    nodes, bound = _fragmented(n=6)
    # mixed candidates: drains + a single-pod set with no exclusions
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    sets.append(CandidateSet(name="solo", strategy="Test",
                             victims=[bound[5]], exclude_targets=set()))
    batched = plan_evictions(nodes, bound, sets)
    naive = plan_evictions_naive(nodes, bound, sets)
    assert [(s.name, s.moves) for s in batched.accepted] == \
        [(s.name, s.moves) for s in naive.accepted]
    assert batched.blocked == naive.blocked
    # and the batched path really did fold every victim into one batch
    assert batched.batch_victims == 6


def test_shared_ledger_no_double_booking():
    """Two drains approved in one cycle must not both park their victim in
    the same last slot of a survivor node."""
    nodes = [make_node(f"n{i}").capacity({"cpu": "2", "pods": "10"}).obj()
             for i in range(3)]
    # n2 has exactly one victim's worth of room; n0+n1 each hold a 1cpu pod
    bound = [make_pod("a").req({"cpu": "1"}).node("n0").obj(),
             make_pod("b").req({"cpu": "1"}).node("n1").obj(),
             make_pod("c").req({"cpu": "1"}).node("n2").obj()]
    sets = [CandidateSet("drain/n0", "T", [bound[0]], {"n0"}),
            CandidateSet("drain/n1", "T", [bound[1]], {"n1"})]
    plan = plan_evictions(nodes, bound, sets)
    # first drain's victim takes n2's room (n1 would be drained next);
    # whichever second set runs, the survivors can't absorb both
    assert len(plan.accepted) == 1
    assert len(plan.blocked) == 1


def test_pdb_blocks_eviction():
    nodes, bound = _fragmented()
    for p in bound:
        p.metadata.labels["app"] = "guarded"
    pdb = {"apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
           "metadata": {"name": "pdb", "namespace": "default"},
           "spec": {"minAvailable": 4,
                    "selector": {"matchLabels": {"app": "guarded"}}}}
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    plan = plan_evictions(nodes, bound, sets, pdbs=[pdb])
    assert not plan.accepted
    assert all("PDB" in why for why in plan.blocked.values())


def test_pdb_budget_charges_across_sets():
    """One disruption left in the budget: the first covered eviction takes
    it, every later covered eviction must see an empty budget."""
    nodes, bound = _fragmented()
    for p in bound:
        p.metadata.labels["app"] = "guarded"
    pdb = {"metadata": {"name": "pdb", "namespace": "default"},
           "spec": {"minAvailable": 3,
                    "selector": {"matchLabels": {"app": "guarded"}}}}
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    plan = plan_evictions(nodes, bound, sets, pdbs=[pdb])
    assert len(plan.accepted) == 1 and plan.evictions == 1
    # every other set blocks: the budget is spent (the receiver of the one
    # approved move additionally blocks as a drain target)
    assert len(plan.blocked) == 3
    assert sum("PDB" in why for why in plan.blocked.values()) >= 2


def test_max_evictions_budget():
    nodes, bound = _fragmented(n=4)
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    plan = plan_evictions(nodes, bound, sets, max_evictions=1)
    assert plan.evictions == 1
    assert sum("budget" in why for why in plan.blocked.values()) >= 1


def test_victims_fitting_nowhere_block_the_set():
    nodes = [make_node("n0").capacity({"cpu": "2", "pods": "10"}).obj(),
             make_node("n1").capacity({"cpu": "2", "pods": "10"}).obj()]
    bound = [make_pod("big-a").req({"cpu": "1500m"}).node("n0").obj(),
             make_pod("big-b").req({"cpu": "1500m"}).node("n1").obj()]
    plan = plan_evictions(nodes, bound, [
        CandidateSet("drain/n0", "T", [bound[0]], {"n0"})])
    assert not plan.accepted
    assert "fits nowhere else" in plan.blocked["drain/n0"]


def test_daemonset_and_mirror_pods_are_not_victims():
    nodes, bound = _fragmented()
    ds = make_pod("ds-0").req({"cpu": "100m"}).node("n0").obj()
    ds.metadata.owner_references = [{"kind": "DaemonSet", "name": "d",
                                     "controller": True}]
    mirror = make_pod("mirror-0").req({"cpu": "100m"}).node("n1").obj()
    mirror.metadata.annotations["kubernetes.io/config.mirror"] = "x"
    sets = high_node_utilization(nodes, bound + [ds, mirror], threshold=0.9)
    victims = {p.key for cs in sets for p in cs.victims}
    assert "default/ds-0" not in victims
    assert "default/mirror-0" not in victims


def test_without_pods_overlay_validates_the_ledger_proof():
    """Soundness: every move the host ledger approved must be feasible per
    the FULL filter set (resource fit included) against the device-side
    reverse overlay (``SnapshotEncoder.without_pods``) of the post-eviction
    cluster — the two formulations of "cluster minus victims" must agree."""
    import numpy as np
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    from kubernetes_tpu.ops.filters import run_filters

    nodes, bound = _fragmented()
    sets = high_node_utilization(nodes, bound, threshold=0.6)
    plan = plan_evictions(nodes, bound, sets)
    assert plan.accepted
    victims = [p for s in plan.accepted for p in s.victims]
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound, pending_pods=victims,
                                  pending_slots=False)
    masked = enc.without_pods(ct, meta, [p.key for p in victims])
    assert masked is not None
    # requested matches a from-scratch encode of the surviving pods
    survivors = [p for p in bound
                 if p.key not in {v.key for v in victims}]
    ct2, meta2 = SnapshotEncoder().encode_cluster(nodes, survivors,
                                                  pending_pods=victims,
                                                  pending_slots=False)
    real_n = len(meta.node_names)
    np.testing.assert_array_equal(
        np.asarray(masked.requested)[:real_n],
        np.asarray(ct2.requested)[:real_n])
    # every approved move is feasible on the masked cluster, fit included
    import dataclasses
    unpinned = [dataclasses.replace(
        p, spec=dataclasses.replace(p.spec, node_name="")) for p in victims]
    mask = np.asarray(run_filters(masked, enc.encode_pods(unpinned, meta)))
    key_to_row = {p.key: i for i, p in enumerate(victims)}
    for s in plan.accepted:
        for key, target in s.moves:
            assert mask[key_to_row[key], meta.node_index[target]], \
                f"ledger parked {key} on {target} but the overlay refuses"
    # unknown keys and port/volume pods refuse the overlay
    assert enc.without_pods(ct, meta, ["default/never-heard-of-it"]) is None


# ------------------------------------------------------------ strategies

def test_low_node_utilization_rebalances_hot_nodes():
    nodes = [make_node(f"n{i}").capacity({"cpu": "2", "pods": "10"}).obj()
             for i in range(3)]
    bound = ([make_pod(f"hot-{i}").req({"cpu": "600m"}).node("n0").obj()
              for i in range(3)]
             + [make_pod("lukewarm").req({"cpu": "1"}).node("n1").obj()])
    # n0 at 0.9, n1 at 0.5, n2 empty -> evict from n0 until it's at <= 0.8
    sets = low_node_utilization(nodes, bound, low=0.2, high=0.8)
    assert [cs.name for cs in sets] == ["rebalance/n0"]
    assert len(sets[0].victims) == 1 and sets[0].exclude_targets == {"n0"}
    plan = plan_evictions(nodes, bound, sets)
    assert plan.accepted and plan.accepted[0].moves[0][1] in ("n1", "n2")
    # no cold node -> nothing to rebalance toward -> no candidates
    warm = bound + [make_pod("warm").req({"cpu": "1"}).node("n2").obj()]
    assert low_node_utilization(nodes, warm, low=0.2, high=0.8) == []


def test_node_affinity_violation_detected_in_one_pass():
    nodes = [make_node("ssd").capacity({"cpu": "4", "pods": "10"})
             .label("disk", "ssd").obj(),
             make_node("hdd").capacity({"cpu": "4", "pods": "10"}).obj()]
    stale = (make_pod("wants-ssd").req({"cpu": "100m"})
             .node_selector({"disk": "ssd"}).node("hdd").obj())
    fine = (make_pod("placed-right").req({"cpu": "100m"})
            .node_selector({"disk": "ssd"}).node("ssd").obj())
    plain = make_pod("plain").req({"cpu": "100m"}).node("hdd").obj()
    sets = pods_violating_node_affinity(nodes, [stale, fine, plain])
    assert [cs.victims[0].key for cs in sets] == ["default/wants-ssd"]
    plan = plan_evictions(nodes, [stale, fine, plain], sets)
    # the proof must move it to the node its affinity demands
    assert plan.accepted and plan.accepted[0].moves == [
        ("default/wants-ssd", "ssd")]


def test_topology_spread_violation_sheds_excess():
    nodes = [make_node(f"n{i}")
             .capacity({"cpu": "8", "pods": "20"})
             .label("topology.kubernetes.io/zone", f"z{i % 2}")
             .obj() for i in range(4)]
    pods = []
    for i in range(5):  # 5 in z0 (n0), 1 in z1 (n1): skew 4, maxSkew 1
        pods.append(make_pod(f"s{i}").label("app", "web")
                    .req({"cpu": "100m"})
                    .spread(1, "topology.kubernetes.io/zone",
                            "DoNotSchedule", {"app": "web"})
                    .node("n0" if i < 5 else "n1").obj())
    pods.append(make_pod("s-z1").label("app", "web").req({"cpu": "100m"})
                .spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                        {"app": "web"})
                .node("n1").obj())
    sets = pods_violating_topology_spread(nodes, pods)
    assert len(sets) == 1
    cs = sets[0]
    assert cs.name == "spread/topology.kubernetes.io/zone=z0"
    assert len(cs.victims) == 3              # 5 - (1 floor + 1 maxSkew)
    assert cs.exclude_targets == {"n0", "n2"}  # the whole z0 domain
    plan = plan_evictions(nodes, pods, sets)
    assert plan.accepted
    assert all(t in ("n1", "n3") for _, t in plan.accepted[0].moves)


def test_remove_duplicates():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4", "pods": "10"}).obj()
             for i in range(2)]
    ref = [{"kind": "ReplicaSet", "name": "web-abc", "controller": True}]
    dup = []
    for i in range(3):
        p = make_pod(f"web-{i}").req({"cpu": "100m"}).node("n0").obj()
        p.metadata.owner_references = list(ref)
        dup.append(p)
    bare = make_pod("solo").req({"cpu": "100m"}).node("n0").obj()
    sets = remove_duplicates(nodes, dup + [bare])
    assert len(sets) == 1
    assert len(sets[0].victims) == 2         # keep one replica on n0
    assert sets[0].exclude_targets == {"n0"}
    plan = plan_evictions(nodes, dup + [bare], sets)
    assert plan.accepted and \
        all(t == "n1" for _, t in plan.accepted[0].moves)


# ----------------------------------------------------------- gang defrag

def test_gang_defrag_picks_fewest_evictions(monkeypatch):
    """A 2-member gang needing near-full nodes on a 4-node half-full
    cluster: the cheapest working consolidation drains exactly 2 nodes —
    and the whole search (empty probe + 4 prefixes + gang) is ONE
    run_filters call."""
    calls = {"n": 0}
    real = planner_mod.run_filters

    def counting(ct, pb, enabled=None):
        calls["n"] += 1
        return real(ct, pb, enabled)

    monkeypatch.setattr(planner_mod, "run_filters", counting)
    nodes, bound = _fragmented()
    gang = [make_pod(f"g{i}").label(GANG_LABEL, "train")
            .req({"cpu": "1500m"}).obj() for i in range(2)]
    cands = gang_consolidation_candidates(nodes, bound)
    gp = plan_gang_defrag(nodes, bound, gang, "train", cands)
    assert calls["n"] == 1
    assert not gp.fits_without_evictions
    assert gp.accepted is not None and len(gp.accepted.victims) == 2
    assert len(gp.gang_moves) == 2
    # the two cheapest-to-drain nodes, victims re-placed on survivors
    drained = {m[1] for m in gp.gang_moves}
    replaced = {t for _, t in gp.accepted.moves}
    assert drained.isdisjoint(replaced)
    # blocked prefixes explain themselves
    assert "no-evictions" in gp.blocked


def test_gang_that_fits_needs_no_evictions():
    nodes, bound = _fragmented()
    gang = [make_pod("g0").label(GANG_LABEL, "small")
            .req({"cpu": "500m"}).obj()]
    gp = plan_gang_defrag(nodes, bound, gang, "small",
                          gang_consolidation_candidates(nodes, bound))
    assert gp.fits_without_evictions and gp.accepted is None


def test_gang_defrag_respects_eviction_budget():
    nodes, bound = _fragmented()
    gang = [make_pod(f"g{i}").label(GANG_LABEL, "train")
            .req({"cpu": "1500m"}).obj() for i in range(2)]
    gp = plan_gang_defrag(nodes, bound, gang, "train",
                          gang_consolidation_candidates(nodes, bound),
                          max_evictions=1)
    assert gp.accepted is None
    assert any("over budget" in why for why in gp.blocked.values())


def test_two_gangs_share_one_cycle_ledger():
    """Two gangs whose only consolidation frees capacity for ONE of them:
    chaining the cycle's ledger through both plans must seat exactly one
    gang — a fresh ledger per gang would prove both onto the same vacated
    capacity (double-booking) and evict for a gang that cannot land."""
    nodes, bound = _fragmented()  # 4 nodes x 2cpu, one 1cpu filler each
    gang_a = [make_pod(f"a{i}").label(GANG_LABEL, "a")
              .req({"cpu": "1500m"}).obj() for i in range(2)]
    gang_b = [make_pod(f"b{i}").label(GANG_LABEL, "b")
              .req({"cpu": "1500m"}).obj() for i in range(2)]
    cands = gang_consolidation_candidates(nodes, bound)
    gp_a = plan_gang_defrag(nodes, bound, gang_a, "a", cands)
    assert gp_a.accepted is not None and len(gp_a.gang_moves) == 2
    # gang B plans against A's committed ledger: A drained 2 nodes and
    # parked its victims on the other 2 (now full) — nothing is left
    gp_b = plan_gang_defrag(nodes, bound, gang_b, "b", cands,
                            ledger=gp_a.ledger)
    assert gp_b.accepted is None and not gp_b.fits_without_evictions
    a_seats = {t for _, t in gp_a.gang_moves}
    assert len(a_seats) == 2
    # and WITHOUT the shared ledger the double-booking is real: B would
    # claim the same vacated nodes A already owns
    gp_b_alone = plan_gang_defrag(nodes, bound, gang_b, "b", cands)
    assert {t for _, t in gp_b_alone.gang_moves} & a_seats


def test_gang_consolidation_skips_protected_nodes():
    """A node holding a pod that OUTRANKS the gang can't fully drain, so
    it never enters the prefix ranking; a peer-priority resident is fair
    game (consolidation preserves victims, and the scheduler's nomination
    shield covers equal-priority replacements)."""
    nodes, bound = _fragmented()
    bound[0].spec.priority = 150           # outranks the gang: protected
    bound[1].spec.priority = 100           # peer: evictable
    cands = gang_consolidation_candidates(nodes, bound,
                                          max_victim_priority=100)
    assert all("n0" not in cs.exclude_targets for cs in cands)
    assert any("n1" in cs.exclude_targets for cs in cands)
    assert len(cands) == 3


def test_gang_consolidation_never_evicts_placed_gang_members():
    """A bound pod carrying the gang label is a seat of an already-placed
    gang: draining it fragments that gang, and for the planning gang's OWN
    members it is endless musical chairs (evict gang-0 to seat gang-1,
    repeat forever) — so such nodes never enter the prefix ranking, even
    at equal priority."""
    nodes, bound = _fragmented()
    bound[0].metadata.labels[GANG_LABEL] = "train"
    bound[0].spec.priority = 100     # a seated member of the same gang
    cands = gang_consolidation_candidates(nodes, bound,
                                          max_victim_priority=100)
    assert cands and all("n0" not in cs.exclude_targets for cs in cands)
    assert all(GANG_LABEL not in p.metadata.labels
               for cs in cands for p in cs.victims)


def test_cycle_threads_claimed_victims_through_gang_plans(monkeypatch):
    """One cycle, one claimed set: every gang plan must see the victim
    keys prior plans in the cycle already evict (the strategy plan's, then
    each earlier gang's) — otherwise a shared victim is planned, PDB-
    charged, and evicted twice."""
    from kubernetes_tpu.descheduler import descheduler as dmod
    from kubernetes_tpu.descheduler.planner import AcceptedSet

    client = _direct()
    nodes, bound = _fragmented()
    _populate(client, nodes, bound)
    for g, i in (("a", 0), ("a", 1), ("b", 0)):
        client.pods("default").create(
            make_pod(f"{g}{i}").label(GANG_LABEL, g)
            .req({"cpu": "1500m"}).obj().to_dict())
    calls = []

    def fake_gang_plan(nodes, bound, members, gang, cands, **kw):
        calls.append((gang, set(kw.get("claimed") or ())))
        gp = planner_mod.GangDefragPlan(gang=gang)
        if gang == "a":   # pretend gang a's plan evicts filler-1
            gp.accepted = AcceptedSet(name="consolidate/1-nodes",
                                      strategy="GangDefrag",
                                      victims=[bound[1]])
        return gp

    monkeypatch.setattr(dmod, "plan_gang_defrag", fake_gang_plan)
    d = Descheduler(client, DeschedulerConfiguration(
        strategies={"HighNodeUtilization": {"threshold": 0.6}}))
    plan, _gang_plans = d.plan()
    strategy_victims = {p.key for s in plan.accepted for p in s.victims}
    assert strategy_victims   # the fixture must exercise the handoff
    assert [g for g, _ in calls] == ["a", "b"]
    assert calls[0][1] == strategy_victims
    assert calls[1][1] == strategy_victims | {bound[1].key}


def test_gang_defrag_never_inverts_priority():
    """A default-priority (0) gang must not evict higher-priority pods:
    the Descheduler caps victims at the gang's own priority — unrestricted
    candidates would be the priority inversion upstream never allows, and
    the nomination shield (rp >= priority) couldn't even hold the seats
    against the outranking replacements."""
    client = _direct()
    nodes, bound = _fragmented()
    for p in bound:
        p.spec.priority = 1000
    _populate(client, nodes, bound)
    for i in range(2):     # gang at default priority 0
        client.pods("default").create(
            make_pod(f"g{i}").label(GANG_LABEL, "train")
            .req({"cpu": "1500m"}).obj().to_dict())
    d = Descheduler(client, DeschedulerConfiguration(strategies={}))
    _plan, gang_plans = d.plan()
    assert len(gang_plans) == 1
    gp = gang_plans[0]
    assert gp.accepted is None and not gp.fits_without_evictions


# ------------------------------------------------- control loop + CLI

def _direct():
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.store.store import ObjectStore
    return DirectClient(ObjectStore())


def _populate(client, nodes, pods):
    for n in nodes:
        client.nodes().create(n.to_dict())
    for p in pods:
        client.pods(p.metadata.namespace).create(p.to_dict())


def test_run_once_evicts_and_requeues_bare_pods():
    client = _direct()
    nodes, bound = _fragmented()
    gang = [make_pod(f"g{i}").label(GANG_LABEL, "train")
            .req({"cpu": "1500m"}).obj() for i in range(2)]
    _populate(client, nodes, bound + gang)
    d = Descheduler(client, DeschedulerConfiguration(strategies={}))
    summary = d.run_once()
    assert len(summary["evicted"]) == 2
    assert summary["gangs"][0]["evictions"] == 2
    # bare victims were re-created unbound -> back into the queue's world
    names = {(p["metadata"]["name"], (p["spec"] or {}).get("nodeName"))
             for p in client.pods("default").list()}
    evicted_names = {k.split("/", 1)[1] for k in summary["evicted"]}
    for name in evicted_names:
        assert (name, None) in names or (name, "") in names
    # metrics moved
    from kubernetes_tpu.metrics.registry import (
        DESCHEDULER_EVICTIONS,
        DESCHEDULER_PLAN_BATCH,
    )
    assert DESCHEDULER_EVICTIONS.get(
        {"strategy": "GangDefrag", "result": "evicted"}) >= 2
    assert DESCHEDULER_PLAN_BATCH.get({"phase": "gangDefrag"}) >= 2
    # status ConfigMap published
    cm = client.resource("configmaps", "default").get("descheduler-status")
    st = json.loads(cm["data"]["status"])
    assert st["lastLoop"]["gangs"][0]["gang"] == "train"


def test_gang_defrag_nominates_gang_pods():
    """Executing a gang plan reserves the drained capacity: every gang
    member's status.nominatedNodeName is written from the proof's
    placement (before the victims' replacements exist), so the scheduler
    shields those nodes from the re-created victims. A cleared plan
    (_unnominate_gang) removes the reservations again."""
    client = _direct()
    nodes, bound = _fragmented()
    gang = [make_pod(f"g{i}").label(GANG_LABEL, "train").priority(100)
            .req({"cpu": "1500m"}).obj() for i in range(2)]
    _populate(client, nodes, bound + gang)
    d = Descheduler(client, DeschedulerConfiguration(strategies={}))
    plan, gang_plans = d.plan()
    assert gang_plans and gang_plans[0].accepted is not None
    moves = dict(gang_plans[0].gang_moves)
    summary = d._execute(plan, gang_plans)
    assert len(summary["evicted"]) == 2
    by_name = {p["metadata"]["name"]: p
               for p in client.pods("default").list()}
    nominated = {f"default/{n}": (by_name[n].get("status") or {})
                 .get("nominatedNodeName")
                 for n in ("g0", "g1")}
    assert nominated == moves
    # distinct reservations: one drained node per gang member
    assert len(set(moves.values())) == 2
    # a cleared plan removes the reservations (abort path)
    d._unnominate_gang(gang_plans[0])
    for n in ("g0", "g1"):
        cur = client.pods("default").get(n)
        assert not (cur.get("status") or {}).get("nominatedNodeName")


def test_dry_run_plans_but_does_not_evict():
    client = _direct()
    nodes, bound = _fragmented()
    _populate(client, nodes, bound)
    cfg = DeschedulerConfiguration(
        strategies={"HighNodeUtilization": {"threshold": 0.6}},
        gang_defrag=False)
    summary = Descheduler(client, cfg).run_once(dry_run=True)
    assert summary["planned"] and "evicted" not in summary
    assert len(client.pods("default").list()) == 4


def test_autoscaler_handoff_seeds_unneeded_window():
    class FakeAutoscaler:
        def __init__(self):
            self.noted = []

        def note_drained(self, names):
            self.noted.extend(names)

    client = _direct()
    nodes, bound = _fragmented()
    gang = [make_pod(f"g{i}").label(GANG_LABEL, "t").req({"cpu": "1500m"})
            .obj() for i in range(2)]
    _populate(client, nodes, bound + gang)
    ca = FakeAutoscaler()
    d = Descheduler(client, DeschedulerConfiguration(strategies={}),
                    autoscaler=ca)
    d.run_once()
    # NOTE: gang members will claim these nodes, but at eviction time they
    # are empty — the handoff fires and the autoscaler's own re-check
    # decides whether reclaim survives the gang's arrival
    assert len(ca.noted) == 2


def test_cluster_autoscaler_note_drained():
    from kubernetes_tpu.autoscaler import ClusterAutoscaler, NodeGroup
    from kubernetes_tpu.autoscaler.nodegroup import StaticNodeGroupProvider
    from kubernetes_tpu.utils.clock import FakeClock
    client = _direct()
    tpl = make_node("tpl").capacity({"cpu": "2"})
    provider = StaticNodeGroupProvider(
        client, [NodeGroup("g", 0, 4, tpl.obj())])
    clock = FakeClock(500.0)
    ca = ClusterAutoscaler(client, provider, clock=clock)
    ca.note_drained(["n0", "n1"])
    assert ca._unneeded_since == {"n0": 500.0, "n1": 500.0}
    clock.advance(10.0)
    ca.note_drained(["n0"])          # idempotent: window start is kept
    assert ca._unneeded_since["n0"] == 500.0


def test_configuration_from_yaml(tmp_path):
    p = tmp_path / "policy.yaml"
    p.write_text("""
deschedulerInterval: 30
maxEvictionsPerCycle: 5
gangDefrag: true
profiles:
- name: defrag
  strategies:
    HighNodeUtilization: {threshold: 0.4}
    RemoveDuplicates: {}
""")
    cfg = DeschedulerConfiguration.from_yaml(str(p))
    assert cfg.interval_s == 30.0
    assert cfg.max_evictions_per_cycle == 5
    assert cfg.strategies == {"HighNodeUtilization": {"threshold": 0.4},
                              "RemoveDuplicates": {}}


def test_ktpu_deschedule_run_and_status():
    from kubernetes_tpu.cli.ktpu import main as ktpu_main
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    try:
        from kubernetes_tpu.client.clientset import HTTPClient
        client = HTTPClient(server.url)
        nodes, bound = _fragmented()
        _populate(client, nodes, bound)
        for i in range(2):
            client.pods("default").create(
                make_pod(f"g{i}").label(GANG_LABEL, "train")
                .req({"cpu": "1500m"}).obj().to_dict())
        out = io.StringIO()
        rc = ktpu_main(["--server", server.url, "deschedule", "run",
                        "--dry-run"], out=out)
        assert rc == 0
        assert "gang train: 2 eviction(s)" in out.getvalue()
        # dry-run totals must match what the wet run will report: gang
        # victims count, not just strategy-set moves
        assert "would evict 2 pod(s)" in out.getvalue()
        # a real run evicts and publishes status
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "deschedule", "run"],
                         out=out) == 0
        assert "evicted 2 pod(s)" in out.getvalue()
        out = io.StringIO()
        assert ktpu_main(["--server", server.url, "deschedule", "status"],
                         out=out) == 0
        text = out.getvalue()
        assert "Gang defrag:  on" in text and "evicted=2" in text
    finally:
        server.stop()


# ------------------------------------------------------------------ e2e

def test_gang_defrag_e2e_schedules_gang_without_scale_up():
    """Acceptance e2e: a fragmented cluster (every node half-full), a
    pending gang no node can host — descheduler evictions consolidate the
    fillers, the gang binds, the fillers re-bind, and the node set never
    grows (defragmentation instead of scale-up)."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.features import DEFAULT_FEATURE_GATE
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer

    server = APIServer().start()
    runner = None
    # preemption off: the gang outranks the fillers, and with preemption on
    # the scheduler would DELETE the fillers itself (bare pods: work lost).
    # The descheduler's whole value here is consolidation that preserves
    # the victims — so the e2e must prove evictions alone suffice.
    DEFAULT_FEATURE_GATE.set("PreemptionSimulation", False)
    try:
        client = HTTPClient(server.url)
        nodes, _ = _fragmented(n=4, node_cpu="2")
        for n in nodes:
            client.nodes().create(n.to_dict())
        # tight backoff/assume-ttl: evict-then-recreate races a bind RPC by
        # design here, and a lost bind otherwise parks phantom capacity in
        # the cache for the full default 30s TTL
        runner = SchedulerRunner(
            HTTPClient(server.url),
            SchedulerConfiguration(backoff_initial_s=0.05,
                                   backoff_max_s=0.2,
                                   assume_ttl_s=2.0))
        # a pod parked unschedulable right as an eviction lands would
        # otherwise wait out the full 60s timeout sweep mid-test
        runner.queue.unschedulable_timeout = 1.0
        runner.start()
        # fillers pre-bound one per node: the fragmentation is the fixture,
        # not a scheduler outcome the test races (a 2+2 scatter would leave
        # empty nodes and nothing for the descheduler to prove)
        pods = client.pods("default")
        for i in range(4):
            pods.create(make_pod(f"filler-{i}").req({"cpu": "1"})
                        .node(f"n{i}").obj().to_dict())
        gang = [make_pod(f"gang-{i}").label(GANG_LABEL, "train")
                .priority(100).req({"cpu": "2"}).obj().to_dict()
                for i in range(2)]
        pods.create_many(gang)
        # the gang is genuinely stuck: nothing binds without evictions
        time.sleep(1.0)
        assert not any(p["spec"].get("nodeName") for p in pods.list()
                       if p["metadata"]["name"].startswith("gang-"))

        d = Descheduler(HTTPClient(server.url),
                        DeschedulerConfiguration(strategies={}))

        # Throttle descheduler cycles: each run_once is a full plan pass
        # (encode + run_filters, JIT on first use) that fights the
        # scheduler loop for the GIL on small CI boxes — and a cycle that
        # lands while re-binds are in flight can evict a just-rebound
        # filler and restart convergence. Plan every ~2s; in between, poll
        # only the cheap list so the scheduler gets the CPU to converge.
        last_cycle = [0.0]

        def converged():
            live = pods.list()
            done = (sum(1 for p in live
                        if p["metadata"]["name"].startswith("gang-")
                        and p["spec"].get("nodeName")) == 2
                    and sum(1 for p in live
                            if p["metadata"]["name"].startswith("filler-")
                            and p["spec"].get("nodeName")) == 4)
            if not done and time.time() - last_cycle[0] > 2.0:
                last_cycle[0] = time.time()
                d.run_once()
            return done

        assert wait_for(converged, 150.0, interval=0.25), [
            (p["metadata"]["name"], p["spec"].get("nodeName"))
            for p in pods.list()]
        # defrag, not scale-up: same 4 nodes, gang members own whole nodes
        assert {n["metadata"]["name"] for n in client.nodes().list()} == \
            {f"n{i}" for i in range(4)}
        by_node = {}
        for p in pods.list():
            by_node.setdefault(p["spec"]["nodeName"], []).append(
                p["metadata"]["name"])
        for names in by_node.values():
            if any(n.startswith("gang-") for n in names):
                assert len(names) == 1   # a 2cpu gang pod fills its node
    finally:
        DEFAULT_FEATURE_GATE._overrides.pop("PreemptionSimulation", None)
        if runner is not None:
            runner.stop()
        server.stop()
