"""Sharded-vs-unsharded parity on the 8-device virtual CPU mesh.

The reference parallelizes filter/score with 16 goroutines chunked over nodes
(framework/parallelize/parallelism.go — see SURVEY §2.1 Parallelizer); our
analog shards the node axis of the cluster tensors and the pod axis of the
batch over a ("pods", "nodes") Mesh and lets GSPMD insert the collectives.
Parity requirement: the sharded program must be bit-identical to the
unsharded one, including when the node-axis split crosses a topology domain
(zones of 3 nodes vs shards of 4 — domain matmuls then reduce across shards).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.models.gang import GangState, extend_cluster, gang_round, gang_schedule
from kubernetes_tpu.models.schedule_step import schedule_step
from kubernetes_tpu.parallel.mesh import make_mesh, shard_batch, shard_cluster
from kubernetes_tpu.testing.wrappers import make_node, make_pod




def _cluster(n_nodes=16, n_pods=16):
    """Zones of 3 nodes so domain boundaries cross the 4-node shard boundary."""
    nodes = []
    for i in range(n_nodes):
        nodes.append(
            make_node(f"n{i:02d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "20"})
            .label("topology.kubernetes.io/zone", f"z{i // 3}")
            .label("kubernetes.io/hostname", f"n{i:02d}")
            .obj())
    pods = []
    for i in range(n_pods):
        b = make_pod(f"p{i:02d}").req({"cpu": "500m", "memory": "256Mi"})
        b = b.label("app", f"g{i % 3}")
        if i % 3 == 0:
            b = b.spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                         {"app": "g0"})
        if i % 3 == 1:
            b = b.pod_anti_affinity("kubernetes.io/hostname", {"app": "g1"})
        pods.append(b.obj())
    return nodes, pods


def _encode(nodes, pods):
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    return ct, pb, meta


def _mesh(pods_axis=2):
    return make_mesh(jax.devices()[:8], pods_axis=pods_axis)


def _sharded_backend_usable(mesh_shape=(2, 4)):
    """Gate the sharding suite on a backend that can actually run it.

    Two distinct reasons to skip, both environmental rather than product
    bugs: (a) fewer than 2 devices — GSPMD over a 1-device mesh exercises
    nothing and some jax versions refuse the axis sizes outright; (b) the
    virtual-CPU GSPMD lowering in the installed jaxlib miscompiles the
    sharded program (observed: hlo-verifier slice errors and wrong-result
    parity drift). The canary runs the real ``schedule_step`` on the same
    cluster shape the parity cases use, sharded and unsharded, and diffs
    the choice vector — a crash or drift means the cases below would fail
    for the same environmental reason, so the suite skips deterministically
    instead of failing tier-1 on a jaxlib regression.

    ``mesh_shape``: the (pods, nodes) mesh the caller will actually use —
    miscompiles are SHAPE-SPECIFIC (a jaxlib that breaks the 2x4 mesh can
    run 1x2 fine), so each suite canaries at its own shape. Verdicts are
    cached per shape via ``_sharded_backend_verdict``."""
    pods_axis, nodes_axis = mesh_shape
    want = pods_axis * nodes_axis
    if jax.device_count() < 2 or len(jax.devices()) < want:
        return False, (f"needs >=2 real devices ({want} virtual for the "
                       f"{pods_axis}x{nodes_axis} mesh)")
    # the UNSHARDED half runs outside the guard: encode/schedule_step
    # breakage is a product bug and must fail collection loudly — only the
    # sharded execution may be excused as environmental
    nodes, pods = _cluster()
    ct, pb, meta = _encode(nodes, pods)
    base = schedule_step(ct, pb, seed=0, topo_keys=meta.topo_keys)
    try:
        mesh = make_mesh(jax.devices()[:want], pods_axis=pods_axis)
        with mesh:
            out = schedule_step(shard_cluster(mesh, ct),
                                shard_batch(mesh, pb),
                                seed=0, topo_keys=meta.topo_keys)
        if not np.array_equal(np.asarray(base.choice),
                              np.asarray(out.choice)):
            return False, ("backend miscompiles sharded programs "
                           "(canary parity drift)")
        return True, ""
    except Exception as e:  # canary crash == every case would crash
        return False, ("backend cannot execute sharded programs: "
                       f"{type(e).__name__}")


@functools.lru_cache(maxsize=None)
def _sharded_backend_verdict(mesh_shape=(2, 4)):
    return _sharded_backend_usable(mesh_shape)


@pytest.fixture(scope="module", autouse=True)
def _require_sharded_backend():
    """Lazy gate: the canary runs a real sharded schedule_step (encode +
    GSPMD compile, seconds of work) — pay for it only when a mesh test is
    actually selected, not at every collection of this file."""
    usable, why = _sharded_backend_verdict()
    if not usable:
        pytest.skip(why)


def test_schedule_step_sharded_bit_equal():
    nodes, pods = _cluster()
    ct, pb, meta = _encode(nodes, pods)
    base = schedule_step(ct, pb, seed=3, topo_keys=meta.topo_keys)

    mesh = _mesh()
    with mesh:
        ct_s = shard_cluster(mesh, ct)
        pb_s = shard_batch(mesh, pb)
        out = schedule_step(ct_s, pb_s, seed=3, topo_keys=meta.topo_keys)

    np.testing.assert_array_equal(np.asarray(base.choice), np.asarray(out.choice))
    np.testing.assert_array_equal(np.asarray(base.assigned), np.asarray(out.assigned))
    np.testing.assert_array_equal(np.asarray(base.feasible), np.asarray(out.feasible))
    np.testing.assert_array_equal(np.asarray(base.scores), np.asarray(out.scores))


@pytest.mark.parametrize("pods_axis", [1, 2])
def test_gang_round_sharded_bit_equal(pods_axis):
    nodes, pods = _cluster()
    ct, pb, meta = _encode(nodes, pods)
    ct_ext = extend_cluster(ct, pb)
    P = int(pb.pod_valid.shape[0])

    def fresh_state(requested):
        return GangState(
            requested=jnp.asarray(requested),
            committed=jnp.zeros(P, bool),
            assignment=jnp.full(P, -1, jnp.int32),
            tried=jnp.zeros(P, bool),
            rounds=jnp.zeros((), jnp.int32),
        )

    base_state, base_n = gang_round(ct_ext, pb, fresh_state(ct.requested),
                                    seed=1, topo_keys=meta.topo_keys)

    mesh = _mesh(pods_axis=pods_axis)
    with mesh:
        ct_s = shard_cluster(mesh, ct_ext)
        pb_s = shard_batch(mesh, pb)
        st, n = gang_round(ct_s, pb_s, fresh_state(ct.requested),
                           seed=1, topo_keys=meta.topo_keys)

    assert int(base_n) == int(n)
    np.testing.assert_array_equal(np.asarray(base_state.assignment),
                                  np.asarray(st.assignment))
    np.testing.assert_array_equal(np.asarray(base_state.committed),
                                  np.asarray(st.committed))
    np.testing.assert_array_equal(np.asarray(base_state.requested),
                                  np.asarray(st.requested))


def test_gang_schedule_full_convergence_sharded():
    """Full multi-round gang convergence under the mesh == unsharded result."""
    nodes, pods = _cluster(n_nodes=16, n_pods=24)
    ct, pb, meta = _encode(nodes, pods)
    base_asg, base_rounds = gang_schedule(ct, pb, seed=0, topo_keys=meta.topo_keys)

    mesh = _mesh()
    with mesh:
        asg, rounds = gang_schedule(ct, pb, seed=0, topo_keys=meta.topo_keys,
                                    mesh=mesh)
    np.testing.assert_array_equal(base_asg, asg)
    assert base_rounds == rounds


def test_dryrun_entrypoint_in_process():
    """__graft_entry__.dryrun_multichip must succeed in-process on the
    virtual 8-device mesh (the driver's hard gate)."""
    import importlib.util, os
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_drain_step_sharded_parity():
    """The FULL fused drain (stacked batches, fold-back, fill arithmetic)
    over the mesh == unsharded — VERDICT r4 #2: the hot path itself must be
    mesh-parameterized, not only single-batch gang_schedule."""
    import jax
    from kubernetes_tpu.models.gang import (drain_step, extend_cluster_drain,
                                            unify_batches)
    from kubernetes_tpu.parallel.mesh import shard_drain
    P, B = 8, 2
    nodes, pods = _cluster(n_nodes=32, n_pods=P * B)
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    chunks = [pods[i:i + P] for i in range(0, P * B, P)]
    pbs = unify_batches([enc.encode_pods(c, meta, min_p=P) for c in chunks])
    ct_all, e0 = extend_cluster_drain(ct, pbs)
    pb_stack = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *pbs)
    kw = dict(e0=e0, seed=0, fit_strategy="LeastAllocated",
              topo_keys=meta.topo_keys, weights=(), enabled_filters=(),
              max_rounds=64)
    a_u, _, ct_u, fill_u = drain_step(ct_all, pb_stack, 0, **kw)
    mesh = _mesh()
    with mesh:
        ct_s, pb_s = shard_drain(mesh, ct_all, pb_stack)
        a_s, _, ct_s_out, fill_s = drain_step(ct_s, pb_s, 0, **kw)
    np.testing.assert_array_equal(np.asarray(a_u), np.asarray(a_s))
    assert int(fill_u) == int(fill_s)
    np.testing.assert_array_equal(np.asarray(ct_u.requested),
                                  np.asarray(ct_s_out.requested))
