"""Filter-mask parity: tensor path (ops/filters.py) vs oracle (sched/oracle.py).

Golden cases mirror the reference's plugin unit tests (fit_test.go,
taint_toleration_test.go, node_affinity_test.go, ...); the fuzzer sweeps random
clusters and diffs full [P,N] masks bit-for-bit.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import Requirement
from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.ops.filters import FILTERS, run_filters
from kubernetes_tpu.sched.oracle import OracleScheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def tensor_mask(nodes, pods, bound=None, enabled=None):
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, bound or [], pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    mask = np.asarray(run_filters(ct, pb, enabled=enabled))
    return mask[:len(pods), :len(nodes)]


def oracle_mask(nodes, pods, bound=None):
    orc = OracleScheduler(nodes, bound or [])
    return np.asarray([orc.feasible(p)[0] for p in pods])


def assert_parity(nodes, pods, bound=None):
    tm = tensor_mask(nodes, pods, bound)
    om = oracle_mask(nodes, pods, bound)
    np.testing.assert_array_equal(tm, om,
                                  err_msg=f"pods={[p.key for p in pods]} nodes={[n.name for n in nodes]}")
    return tm


# ---------------------------------------------------------------- golden cases

def test_resources_fit():
    nodes = [make_node(f"n{i}").capacity({"cpu": c, "memory": "4Gi", "pods": "10"}).obj()
             for i, c in enumerate(["1", "2", "4"])]
    pods = [make_pod(f"p{r}").req({"cpu": r}).obj() for r in ["500m", "1500m", "3", "8"]]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [
        [True, True, True], [False, True, True], [False, False, True], [False, False, False]])


def test_fit_counts_bound_pods():
    nodes = [make_node("n0").capacity({"cpu": "2", "pods": "10"}).obj()]
    bound = [make_pod("b0").req({"cpu": "1500m"}).node("n0").obj()]
    pods = [make_pod("p0").req({"cpu": "1"}).obj(),
            make_pod("p1").req({"cpu": "500m"}).obj()]
    tm = assert_parity(nodes, pods, bound)
    np.testing.assert_array_equal(tm, [[False], [True]])


def test_pods_capacity_resource():
    nodes = [make_node("n0").capacity({"cpu": "64", "pods": "2"}).obj()]
    bound = [make_pod(f"b{i}").node("n0").obj() for i in range(2)]
    pods = [make_pod("p0").obj()]
    tm = assert_parity(nodes, pods, bound)
    assert not tm[0, 0]


def test_extended_resource_absent_on_node():
    nodes = [make_node("n0").capacity({"cpu": "4", "pods": "10"}).obj(),
             make_node("n1").capacity({"cpu": "4", "pods": "10", "example.com/gpu": "2"}).obj()]
    pods = [make_pod("p0").req({"example.com/gpu": "1"}).obj()]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [[False, True]])


def test_unschedulable_and_toleration():
    nodes = [make_node("n0").unschedulable().obj(),
             make_node("n1").obj()]
    for n in nodes:
        n.status.allocatable = {"cpu": "4", "pods": "10"}
    pods = [make_pod("p0").obj(),
            make_pod("p1").toleration(key="node.kubernetes.io/unschedulable",
                                      operator="Exists", effect="NoSchedule").obj()]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [[False, True], [True, True]])


def test_node_name():
    nodes = [make_node("n0").capacity({"cpu": "1"}).obj(),
             make_node("n1").capacity({"cpu": "1"}).obj()]
    pods = [make_pod("p0").node("n1").obj(), make_pod("p1").node("missing").obj()]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [[False, True], [False, False]])


def test_taints_and_tolerations():
    nodes = [
        make_node("plain").capacity({"cpu": "4"}).obj(),
        make_node("tainted").capacity({"cpu": "4"}).taint("dedicated", "ml", "NoSchedule").obj(),
        make_node("prefer").capacity({"cpu": "4"}).taint("slow", "", "PreferNoSchedule").obj(),
        make_node("exec").capacity({"cpu": "4"}).taint("evict", "now", "NoExecute").obj(),
    ]
    pods = [
        make_pod("bare").obj(),
        make_pod("tol-eq").toleration(key="dedicated", operator="Equal", value="ml",
                                      effect="NoSchedule").obj(),
        make_pod("tol-exists").toleration(operator="Exists").obj(),  # tolerates everything
        make_pod("tol-wrongval").toleration(key="dedicated", operator="Equal", value="web").obj(),
    ]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [
        [True, False, True, False],
        [True, True, True, False],
        [True, True, True, True],
        [True, False, True, False]])


def test_node_selector_and_affinity():
    nodes = [
        make_node("a").capacity({"cpu": "4"}).label("zone", "us-a").label("disk", "ssd").obj(),
        make_node("b").capacity({"cpu": "4"}).label("zone", "us-b").obj(),
        make_node("c").capacity({"cpu": "4"}).label("zone", "us-c").label("gen", "7").obj(),
    ]
    pods = [
        make_pod("sel").node_selector({"zone": "us-b"}).obj(),
        make_pod("aff-in").node_affinity_in("zone", ["us-a", "us-c"]).obj(),
        make_pod("aff-notin").node_affinity_expr(Requirement("zone", "NotIn", ["us-a"])).obj(),
        make_pod("aff-exists").node_affinity_expr(Requirement("disk", "Exists")).obj(),
        make_pod("aff-dne").node_affinity_expr(Requirement("disk", "DoesNotExist")).obj(),
        make_pod("aff-gt").node_affinity_expr(Requirement("gen", "Gt", ["5"])).obj(),
        make_pod("aff-and").node_affinity_expr(
            Requirement("zone", "In", ["us-a", "us-b"]), Requirement("disk", "Exists")).obj(),
        # OR of two terms
        make_pod("aff-or").node_affinity_in("zone", ["us-a"]).node_affinity_in("zone", ["us-b"]).obj(),
        # selector AND affinity must both hold
        make_pod("sel+aff").node_selector({"disk": "ssd"}).node_affinity_in("zone", ["us-b"]).obj(),
    ]
    tm = assert_parity(nodes, pods)
    np.testing.assert_array_equal(tm, [
        [False, True, False],
        [True, False, True],
        [False, True, True],
        [True, False, False],
        [False, True, True],
        [False, False, True],
        [True, False, False],
        [True, True, False],
        [False, False, False]])


def test_match_fields():
    nodes = [make_node("n0").capacity({"cpu": "1"}).obj(),
             make_node("n1").capacity({"cpu": "1"}).obj()]
    from kubernetes_tpu.api.types import NodeSelectorTerm, Requirement as R, NodeAffinity, Affinity
    pod = make_pod("pin").obj()
    pod.spec.affinity = Affinity(node_affinity=NodeAffinity(required=[
        NodeSelectorTerm(match_fields=[R("metadata.name", "In", ["n1"])])]))
    tm = assert_parity(nodes, [pod])
    np.testing.assert_array_equal(tm, [[False, True]])


def test_host_ports():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj(),
             make_node("n1").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("b0").host_port(8080).node("n0").obj(),
             make_pod("b1").host_port(9090, host_ip="10.0.0.1").node("n1").obj()]
    pods = [
        make_pod("same-port").host_port(8080).obj(),
        make_pod("diff-port").host_port(8081).obj(),
        make_pod("udp-same").host_port(8080, protocol="UDP").obj(),
        make_pod("ip-overlap").host_port(9090, host_ip="10.0.0.1").obj(),
        make_pod("ip-disjoint").host_port(9090, host_ip="10.0.0.2").obj(),
        make_pod("ip-wild").host_port(9090).obj(),  # 0.0.0.0 clashes with 10.0.0.1
    ]
    tm = assert_parity(nodes, pods, bound)
    np.testing.assert_array_equal(tm, [
        [False, True], [True, True], [True, True],
        [True, False], [True, True], [True, False]])


# ------------------------------------------------------------------ fuzz sweep

ZONES = ["us-a", "us-b", "us-c"]
DISKS = ["ssd", "hdd"]


def random_node(rng: random.Random, i: int):
    w = make_node(f"n{i}").capacity({
        "cpu": str(rng.choice([1, 2, 4, 8])),
        "memory": f"{rng.choice([2, 4, 8])}Gi",
        "pods": str(rng.choice([3, 10]))})
    if rng.random() < 0.5:
        w.label("zone", rng.choice(ZONES))
    if rng.random() < 0.3:
        w.label("disk", rng.choice(DISKS))
    if rng.random() < 0.3:
        w.label("gen", str(rng.randint(1, 9)))
    if rng.random() < 0.2:
        w.taint("dedicated", rng.choice(["ml", "web"]),
                rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]))
    if rng.random() < 0.1:
        w.unschedulable()
    return w.obj()


def random_pod(rng: random.Random, i: int, node_names):
    w = make_pod(f"p{i}").req({
        "cpu": rng.choice(["100m", "500m", "1", "2"]),
        "memory": rng.choice(["64Mi", "512Mi", "2Gi"])})
    if rng.random() < 0.25:
        w.node_selector({"zone": rng.choice(ZONES)})
    if rng.random() < 0.3:
        op = rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"])
        key = rng.choice(["zone", "disk", "gen", "nope"])
        vals = ([str(rng.randint(0, 9))] if op in ("Gt", "Lt")
                else rng.sample(ZONES + DISKS, k=rng.randint(1, 2)))
        w.node_affinity_expr(Requirement(key, op, [] if op in ("Exists", "DoesNotExist") else vals))
    if rng.random() < 0.2:
        w.toleration(key="dedicated", operator=rng.choice(["Equal", "Exists"]),
                     value=rng.choice(["ml", "web", ""]),
                     effect=rng.choice(["NoSchedule", "", "NoExecute"]))
    if rng.random() < 0.1:
        w.toleration(operator="Exists")
    if rng.random() < 0.15:
        w.host_port(rng.choice([80, 8080]), protocol=rng.choice(["TCP", "UDP"]))
    if rng.random() < 0.1:
        w.node(rng.choice(node_names + ["ghost"]))
    return w.obj()


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_filter_parity(seed):
    rng = random.Random(seed)
    n_nodes, n_bound, n_pods = rng.randint(1, 12), rng.randint(0, 8), rng.randint(1, 10)
    nodes = [random_node(rng, i) for i in range(n_nodes)]
    names = [n.metadata.name for n in nodes]
    bound = []
    for i in range(n_bound):
        p = random_pod(rng, 100 + i, names)
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    pods = [random_pod(rng, i, names) for i in range(n_pods)]
    assert_parity(nodes, pods, bound)


# -------------------------------------------- relational fuzz (full evaluate)

NAMESPACES = ["default", "team-a", "team-b", "prod"]
NS_LABELS = {"default": {}, "team-a": {"tier": "gold"},
             "team-b": {"tier": "bronze"}, "prod": {"env": "prod"}}
APPS = ["web", "db", "cache"]
TOPO_KEYS = ["zone", "disk"]


def _random_volume_catalog(rng: random.Random):
    from kubernetes_tpu.sched.volumebinding import VolumeCatalog
    pvs, pvcs = [], []
    for i in range(rng.randint(2, 5)):
        zone = rng.choice(ZONES + [None])
        pv = {"apiVersion": "v1", "kind": "PersistentVolume",
              "metadata": {"name": f"pv{i}", "labels": {}},
              "spec": {"capacity": {"storage": "10Gi"},
                       "accessModes": [rng.choice(["ReadWriteOnce", "ReadWriteMany"])],
                       "storageClassName": ""},
              "status": {"phase": "Available"}}
        if zone:
            pv["metadata"]["labels"]["zone"] = zone
        pvs.append(pv)
    for i in range(rng.randint(1, 4)):
        claim = {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                 "metadata": {"name": f"c{i}", "namespace": "default"},
                 "spec": {"accessModes": ["ReadWriteOnce"],
                          "resources": {"requests": {"storage": "5Gi"}},
                          "storageClassName": ""},
                 "status": {}}
        if rng.random() < 0.5:
            claim["spec"]["volumeName"] = f"pv{rng.randint(0, len(pvs) - 1)}"
        pvcs.append(claim)
    return VolumeCatalog.from_lists(pvcs=pvcs, pvs=pvs, storage_classes=[]), \
        [c["metadata"]["name"] for c in pvcs]


def random_relational_pod(rng: random.Random, i: int, node_names, claim_names):
    w = make_pod(f"p{i}", namespace=rng.choice(NAMESPACES)).req({
        "cpu": rng.choice(["100m", "500m"])})
    w.label("app", rng.choice(APPS))
    if rng.random() < 0.6:
        w.label("rev", str(rng.randint(1, 3)))
    if rng.random() < 0.2:
        w.node_selector({"zone": rng.choice(ZONES)})
    if rng.random() < 0.15:
        w.node_affinity_in("zone", rng.sample(ZONES, k=rng.randint(1, 2)))
    if rng.random() < 0.15:
        w.toleration(key="dedicated", operator="Exists")
    if rng.random() < 0.4:
        w.spread(rng.randint(1, 2), rng.choice(TOPO_KEYS),
                 rng.choice(["DoNotSchedule", "DoNotSchedule", "ScheduleAnyway"]),
                 {"app": rng.choice(APPS)},
                 min_domains=rng.choice([None, None, 2, 3]),
                 node_affinity_policy=rng.choice(["Honor", "Honor", "Ignore"]),
                 node_taints_policy=rng.choice(["Ignore", "Ignore", "Honor"]),
                 match_label_keys=rng.choice([[], [], ["rev"]]))
    if rng.random() < 0.4:
        kw = {}
        r = rng.random()
        if r < 0.35:
            kw["namespaces"] = rng.sample(NAMESPACES, k=rng.randint(1, 2))
        elif r < 0.55:
            kw["namespace_selector"] = rng.choice(
                [{}, {"tier": "gold"}, {"env": "prod"}])
        mk = rng.random()
        if mk < 0.25:
            kw["match_label_keys"] = ["rev"]
        elif mk < 0.4:
            kw["mismatch_label_keys"] = ["rev"]
        w.pod_affinity(rng.choice(TOPO_KEYS), {"app": rng.choice(APPS)},
                       anti=rng.random() < 0.65, **kw)
    if claim_names and rng.random() < 0.2:
        p = w.obj()
        p.spec.volumes = [{"name": "v0", "persistentVolumeClaim":
                           {"claimName": rng.choice(claim_names)}}]
        return p
    return w.obj()


@pytest.mark.parametrize("seed", range(50))
def test_fuzz_relational_parity(seed):
    """Full feasibility (filters + spread + inter-pod + volumes) parity on
    clusters of >=64 nodes with multi-namespace relational workloads —
    namespaces/namespaceSelector, matchLabelKeys, minDomains, node-inclusion
    policies, and PVC references all in play."""
    from kubernetes_tpu.models.schedule_step import evaluate

    rng = random.Random(1000 + seed)
    n_nodes = rng.randint(64, 96)
    n_bound, n_pods = rng.randint(10, 30), rng.randint(4, 10)
    nodes = [random_node(rng, i) for i in range(n_nodes)]
    names = [n.metadata.name for n in nodes]
    catalog, claim_names = (_random_volume_catalog(rng)
                            if rng.random() < 0.4 else (None, []))
    bound = []
    for i in range(n_bound):
        p = random_relational_pod(rng, 100 + i, names, [])
        p.spec.node_name = rng.choice(names)
        bound.append(p)
    pods = [random_relational_pod(rng, i, names, claim_names)
            for i in range(n_pods)]

    enc = SnapshotEncoder()
    enc.set_namespaces(NS_LABELS)
    if catalog is not None:
        enc.set_volumes(catalog)
    ct, meta = enc.encode_cluster(nodes, bound, pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    res = evaluate(ct, pb, topo_keys=meta.topo_keys)
    tm = np.asarray(res.feasible)[:len(pods), :len(nodes)]

    orc = OracleScheduler(nodes, bound, volumes=catalog,
                          namespace_labels=NS_LABELS)
    om = np.asarray([orc.feasible(p)[0] for p in pods])
    np.testing.assert_array_equal(
        tm, om, err_msg=f"seed={seed} pods={[p.key for p in pods]}")
