"""CI-shaped e2e: apiserver + connected runner over HTTP, one pod made
unschedulable by an untolerated taint. ``ktpu why`` must name the taint
filter with per-filter node counts, the FailedScheduling event must carry
the same breakdown, and ``ktpu trace dump`` must emit a valid Chrome
trace-event document with the pod's flight-recorder track in it."""

import io
import json
import time

import pytest

from kubernetes_tpu.cli.ktpu import main
from kubernetes_tpu.client.clientset import HTTPClient
from kubernetes_tpu.config.types import SchedulerConfiguration
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_node, make_pod
from kubernetes_tpu.utils.tracing import validate_chrome_trace


def wait_for(pred, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return None


def ktpu(server, *argv):
    out = io.StringIO()
    rc = main(["--server", server.url, *argv], out=out)
    return rc, out.getvalue()


@pytest.fixture()
def cluster():
    server = APIServer().start()
    client = HTTPClient(server.url)
    runner = SchedulerRunner(client, SchedulerConfiguration(
        backoff_initial_s=0.05, backoff_max_s=0.2))
    runner.start()
    yield server, client, runner
    runner.stop()
    server.stop()


def test_why_trace_and_event_for_untolerated_taint(cluster):
    server, client, runner = cluster
    client.nodes().create(
        make_node("tainted-0")
        .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .taint("dedicated", "ml", "NoSchedule").obj().to_dict())
    client.nodes().create(
        make_node("tainted-1")
        .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"})
        .taint("dedicated", "ml", "NoSchedule").obj().to_dict())
    pods = client.pods("default")
    # a schedulable pod proves the pipeline works end to end...
    pods.create(make_pod("ok").req({"cpu": "100m"})
                .toleration(key="dedicated", operator="Exists")
                .obj().to_dict())
    # ...and the victim has no toleration: unschedulable everywhere
    pods.create(make_pod("stuck").req({"cpu": "100m"}).obj().to_dict())

    assert wait_for(
        lambda: pods.get("ok")["spec"].get("nodeName")), "ok pod never bound"

    # the explainer's verdict reaches the scheduler-explanations ConfigMap
    def explained():
        rc, out = ktpu(server, "why", "stuck", "-o", "json")
        return (rc, out) if rc == 0 else None
    got = wait_for(explained)
    assert got, "ktpu why never returned an explanation"
    _rc, out = got
    doc = json.loads(out)
    assert doc["scheduled"] is False
    # names the taint filter, with the per-filter node count
    assert doc["filters"] == {"TaintToleration": 2}
    assert doc["message"] == ("0/2 nodes are available: 2 node(s) had "
                              "untolerated taint.")

    # table output names the filter too
    rc, table = ktpu(server, "why", "stuck")
    assert rc == 0
    assert "TaintToleration: 2 node(s)" in table
    assert "0/2 nodes are available" in table

    # the FailedScheduling EVENT carries the same per-filter message
    runner.scheduler.recorder.flush()

    def event_msg():
        evs = client.resource("events", "default").list()
        for e in evs:
            if (e.get("reason") == "FailedScheduling"
                    and (e.get("involvedObject") or {}).get("name")
                    == "stuck"):
                return e.get("message")
        return None
    msg = wait_for(event_msg)
    assert msg == "0/2 nodes are available: 2 node(s) had untolerated taint."

    # a bound pod's why: names the node it landed on
    rc, out = ktpu(server, "why", "ok")
    assert rc == 0 and "scheduled to" in out

    # a pod OUTSIDE the runner's status namespace: the explanations
    # ConfigMap lives in "default", and ktpu why -n team-a must fall back
    # to it instead of 404ing on configmaps/team-a
    client.pods("team-a").create(
        make_pod("stuck2", "team-a").req({"cpu": "100m"}).obj().to_dict())

    def explained_cross_ns():
        rc, out = ktpu(server, "-n", "team-a", "why", "stuck2", "-o",
                       "json")
        return out if rc == 0 else None
    cross = wait_for(explained_cross_ns)
    assert cross, "cross-namespace ktpu why never resolved"
    assert json.loads(cross)["filters"] == {"TaintToleration": 2}

    # trace dump: publish now (the audit loop also does this on cadence),
    # then validate against the Chrome trace-event schema
    runner.publish_trace()
    rc, raw = ktpu(server, "trace", "dump")
    assert rc == 0, raw
    trace = json.loads(raw)
    assert validate_chrome_trace(trace) == []
    names = {e["name"] for e in trace["traceEvents"]}
    assert "scheduler/gang_schedule" in names or \
        "scheduler/gang_dispatch" in names
    # the flight recorder's per-pod track made it into the export
    tracks = {e["args"].get("name") for e in trace["traceEvents"]
              if e.get("ph") == "M"}
    assert "default/ok" in tracks
    stages = {e["name"] for e in trace["traceEvents"]
              if e.get("cat") == "pod"}
    assert {"informer", "queue_add", "dispatch", "bind"} <= stages

    # trace dump -o writes the same document to disk
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".json") as f:
        rc, out = ktpu(server, "trace", "dump", "-o", f.name)
        assert rc == 0 and "events to" in out
        on_disk = json.load(open(f.name))
        assert validate_chrome_trace(on_disk) == []

    # ktpu status surfaces the new observability blocks
    rc, st = ktpu(server, "status")
    assert rc == 0
    assert "Explainer:" in st and "Flight rec:" in st
    assert "Pending pods:" in st


def test_e2e_histogram_observed(cluster):
    """Binding through the product observes the flight-recorder-derived
    end-to-end histogram."""
    from kubernetes_tpu.metrics.registry import E2E_SCHEDULING
    server, client, _runner = cluster
    base = E2E_SCHEDULING.count()
    client.nodes().create(
        make_node("n0").capacity({"cpu": "4", "pods": "10"})
        .obj().to_dict())
    client.pods("default").create(
        make_pod("e2e-pod").req({"cpu": "100m"}).obj().to_dict())
    assert wait_for(lambda: client.pods("default")
                    .get("e2e-pod")["spec"].get("nodeName"))
    assert wait_for(lambda: E2E_SCHEDULING.count() > base), \
        "e2e scheduling histogram never observed"
