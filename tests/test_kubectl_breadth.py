"""kubectl breadth: logs / exec / port-forward / rollout + debug surface.

Reference: ``staging/src/k8s.io/kubectl/pkg/cmd/{logs,exec,portforward,
rollout}`` and the two-hop proxy path behind them — apiserver pod
subresource -> node.status.daemonEndpoints -> kubelet server
(``pkg/kubelet/server/server.go``). Port-forward is real TCP splicing end
to end (local socket -> apiserver upgrade -> kubelet -> container app).
"""

import io
import json
import socket
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.cli.ktpu import main
from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.kubelet.kubelet import HollowNode
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.testing.wrappers import make_pod


@pytest.fixture()
def cluster():
    server = APIServer().start()
    client = HTTPClient(server.url)
    node = HollowNode(client, "kn-1").start()
    pod = make_pod("app").req({"cpu": "100m"}).obj().to_dict()
    pod["spec"]["nodeName"] = "kn-1"
    client.pods("default").create(pod)
    deadline = time.time() + 10
    while time.time() < deadline:
        phase = (client.pods("default").get("app").get("status")
                 or {}).get("phase")
        if phase == "Running":
            break
        time.sleep(0.1)
    assert phase == "Running"
    yield server, client
    node.stop()
    server.stop()


def test_logs_via_apiserver_proxy(cluster):
    server, client = cluster
    text = client.pod_logs("default", "app")
    assert "container c0 started" in text or "started" in text, text
    out = io.StringIO()
    rc = main(["--server", server.url, "logs", "app"], out=out)
    assert rc == 0
    assert "started" in out.getvalue()


def test_exec_via_apiserver_proxy(cluster):
    server, client = cluster
    res = client.pod_exec("default", "app", ["echo", "hello", "world"])
    assert res["exit_code"] == 0
    assert res["output"] == "hello world\n"
    out = io.StringIO()
    rc = main(["--server", server.url, "exec", "app", "--",
               "echo", "hi"], out=out)
    assert rc == 0 and out.getvalue() == "hi\n"
    # a failing command's exit code propagates, like kubectl exec
    rc = main(["--server", server.url, "exec", "app", "--", "bogus"],
              out=io.StringIO())
    assert rc == 127


def test_port_forward_end_to_end(cluster):
    """Local socket -> CLI forwarder -> apiserver upgrade -> kubelet ->
    container app: bytes round-trip through all four legs."""
    server, client = cluster
    out = io.StringIO()
    done = threading.Event()

    def forwarder():
        main(["--server", server.url, "port-forward", "app", "0",
              "--one-shot"], out=out)
        done.set()

    t = threading.Thread(target=forwarder, daemon=True)
    t.start()
    deadline = time.time() + 10
    port = None
    while time.time() < deadline:
        m = out.getvalue()
        if "Forwarding from 127.0.0.1:" in m:
            port = int(m.split("127.0.0.1:")[1].split(" ")[0])
            break
        time.sleep(0.05)
    assert port, out.getvalue()
    with socket.create_connection(("127.0.0.1", port), timeout=10.0) as c:
        banner = c.recv(1024)
        assert banner.startswith(b"pod "), banner
        c.sendall(b"ping")
        assert c.recv(1024) == b"echo: ping"
    assert done.wait(10.0)


def _mk_rs(name, dep_name, dep_uid, revision, image):
    return {"kind": "ReplicaSet",
            "metadata": {"name": name,
                         "annotations": {
                             "deployment.kubernetes.io/revision":
                             str(revision)},
                         "ownerReferences": [{
                             "kind": "Deployment", "name": dep_name,
                             "uid": dep_uid, "controller": True}]},
            "spec": {"replicas": 1,
                     "template": {"spec": {"containers": [
                         {"name": "c", "image": image}]}}}}


def test_rollout_status_history_undo(cluster):
    server, client = cluster
    deps = client.resource("deployments", "default")
    dep = deps.create({
        "kind": "Deployment", "metadata": {"name": "web"},
        "spec": {"replicas": 2,
                 "template": {"spec": {"containers": [
                     {"name": "c", "image": "img:v2"}]}}},
        "status": {"updatedReplicas": 2, "availableReplicas": 2}})
    uid = dep["metadata"].get("uid", "")
    rs = client.resource("replicasets", "default")
    rs.create(_mk_rs("web-1", "web", uid, 1, "img:v1"))
    rs.create(_mk_rs("web-2", "web", uid, 2, "img:v2"))

    out = io.StringIO()
    assert main(["--server", server.url, "rollout", "status",
                 "deployment/web"], out=out) == 0
    assert "successfully rolled out" in out.getvalue()

    out = io.StringIO()
    assert main(["--server", server.url, "rollout", "history",
                 "deployment/web"], out=out) == 0
    assert "1\n" in out.getvalue() and "2\n" in out.getvalue()

    out = io.StringIO()
    assert main(["--server", server.url, "rollout", "undo",
                 "deployment/web"], out=out) == 0
    got = deps.get("web")
    assert got["spec"]["template"]["spec"]["containers"][0]["image"] \
        == "img:v1"

    out = io.StringIO()
    assert main(["--server", server.url, "rollout", "restart",
                 "deployment/web"], out=out) == 0
    ann = deps.get("web")["spec"]["template"]["metadata"]["annotations"]
    assert "kubectl.kubernetes.io/restartedAt" in ann


def test_debug_traces_and_stacks(cluster):
    server, _client = cluster
    from kubernetes_tpu.utils.tracing import TRACER
    with TRACER.span("test/export", n=1):
        pass
    with urllib.request.urlopen(server.url + "/debug/traces") as r:
        doc = json.loads(r.read())
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert any(s["name"] == "test/export" for s in spans)
    attrs = doc["resourceSpans"][0]["resource"]["attributes"]
    assert any(a["key"] == "service.name" for a in attrs)
    # ?format=chrome: the Perfetto-loadable trace-event export
    from kubernetes_tpu.utils.tracing import validate_chrome_trace
    with urllib.request.urlopen(server.url
                                + "/debug/traces?format=chrome") as r:
        chrome = json.loads(r.read())
    assert validate_chrome_trace(chrome) == []
    assert any(e["name"] == "test/export"
               for e in chrome["traceEvents"])
    with urllib.request.urlopen(server.url + "/debug/stacks") as r:
        text = r.read().decode()
    assert "thread " in text
    assert "dump_stacks" in text  # the serving frame itself is in the dump


def test_top_nodes_and_pods(cluster):
    server, client = cluster
    out = io.StringIO()
    assert main(["--server", server.url, "top", "nodes"], out=out) == 0
    text = out.getvalue()
    assert "kn-1" in text and "CPU(req)" in text
    out = io.StringIO()
    assert main(["--server", server.url, "top", "pods", "-A"], out=out) == 0
    assert "app" in out.getvalue()


def test_kubelet_metrics_endpoint(cluster):
    server, client = cluster
    node = client.nodes().get("kn-1")
    port = node["status"]["daemonEndpoints"]["kubeletEndpoint"]["Port"]
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics") as r:
        text = r.read().decode()
    assert "# TYPE" in text  # Prometheus exposition


def test_label_annotate_and_api_resources(cluster):
    server, client = cluster
    out = io.StringIO()
    assert main(["--server", server.url, "label", "pods", "app",
                 "tier=web", "color=blue"], out=out) == 0
    got = client.pods("default").get("app")["metadata"]["labels"]
    assert got["tier"] == "web" and got["color"] == "blue"
    # changing an existing value needs --overwrite, like kubectl
    out = io.StringIO()
    assert main(["--server", server.url, "label", "pods", "app",
                 "tier=db"], out=out) == 1
    assert "--overwrite" in out.getvalue()
    assert main(["--server", server.url, "label", "pods", "app",
                 "tier=db", "--overwrite"], out=io.StringIO()) == 0
    assert client.pods("default").get("app")["metadata"]["labels"][
        "tier"] == "db"
    # key- removes
    assert main(["--server", server.url, "label", "pods", "app",
                 "color-"], out=io.StringIO()) == 0
    assert "color" not in client.pods("default").get("app")["metadata"][
        "labels"]
    # annotate rides the same machinery
    assert main(["--server", server.url, "annotate", "pods", "app",
                 "note=hi"], out=io.StringIO()) == 0
    assert client.pods("default").get("app")["metadata"]["annotations"][
        "note"] == "hi"
    # api-resources lists the serving table
    out = io.StringIO()
    assert main(["--server", server.url, "api-resources"], out=out) == 0
    text = out.getvalue()
    assert "pods" in text and "volumeattachments" in text
    assert "NAMESPACED" in text


def test_attach_streams_container_output(cluster):
    server, client = cluster
    out = io.StringIO()
    assert main(["--server", server.url, "attach", "app"], out=out) == 0
    assert "started" in out.getvalue()


def test_static_pods_run_with_mirror(tmp_path):
    """The kubelet's FILE pod source (pkg/kubelet/config/file.go): a
    manifest dropped in --pod-manifest-path runs without the apiserver
    involved in scheduling, with a read-only mirror pod reflecting it to
    the API; removing the file stops the pod and the mirror."""
    server = APIServer().start()
    node = None
    try:
        client = HTTPClient(server.url)
        manifest_dir = tmp_path / "manifests"
        manifest_dir.mkdir()
        node = HollowNode(client, "sn-1")
        node.kubelet.start(static_pod_path=str(manifest_dir),
                           static_poll_s=0.1)
        (manifest_dir / "etcd.json").write_text(json.dumps({
            "kind": "Pod", "metadata": {"name": "etcd"},
            "spec": {"containers": [{"name": "etcd",
                                     "image": "etcd:3.5"}]}}))
        deadline = time.time() + 25
        mirror = None
        while time.time() < deadline:
            try:
                mirror = client.pods("default").get("etcd-sn-1")
                break
            except Exception:
                time.sleep(0.1)
        assert mirror is not None, "mirror pod never appeared"
        ann = mirror["metadata"]["annotations"]
        assert ann["kubernetes.io/config.source"] == "file"
        assert "kubernetes.io/config.mirror" in ann
        assert mirror["spec"]["nodeName"] == "sn-1"
        # the static pod actually RUNS in the node's runtime
        assert any(sb.pod_uid == "static-etcd-sn-1"
                   for sb in node.kubelet.runtime.list_sandboxes())
        # file removed -> pod stops, mirror deleted
        (manifest_dir / "etcd.json").unlink()
        deadline = time.time() + 25
        gone = False
        while time.time() < deadline:
            try:
                client.pods("default").get("etcd-sn-1")
                time.sleep(0.1)
            except Exception:
                gone = True
                break
        assert gone, "mirror pod not removed"
    finally:
        if node is not None:
            node.stop()
        server.stop()


def test_wait_for_condition_and_delete(cluster):
    server, client = cluster
    import threading
    out = io.StringIO()
    # pod Ready condition set by the hollow kubelet once Running
    rc = main(["--server", server.url, "wait", "pods", "app",
               "--for", "phase=Running", "--timeout", "10"], out=out)
    assert rc == 0, out.getvalue()
    # --for delete returns once the object is gone
    done = {}

    def waiter():
        done["rc"] = main(["--server", server.url, "wait", "pods", "app",
                           "--for", "delete", "--timeout", "15"],
                          out=io.StringIO())
    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.3)
    client.pods("default").delete("app")
    t.join(timeout=15)
    assert done.get("rc") == 0
    # timeout path
    out = io.StringIO()
    pod2 = make_pod("p2").obj().to_dict()
    pod2["spec"]["nodeName"] = "kn-1"
    client.pods("default").create(pod2)
    rc = main(["--server", server.url, "wait", "pods", "p2",
               "--for", "condition=NeverHappens", "--timeout", "1"],
              out=out)
    assert rc == 1 and "timed out" in out.getvalue()


def test_static_pod_survives_mirror_deletion_and_manifest_edit(tmp_path):
    """The file is the source of truth: deleting the MIRROR via the API
    neither stops the static pod nor sticks (the mirror is recreated);
    editing the manifest restarts the pod with the new spec."""
    server = APIServer().start()
    node = None
    try:
        client = HTTPClient(server.url)
        manifest_dir = tmp_path / "m"
        manifest_dir.mkdir()
        node = HollowNode(client, "sm-1")
        node.kubelet.start(static_pod_path=str(manifest_dir),
                           static_poll_s=0.1)
        mf = manifest_dir / "kapi.json"
        mf.write_text(json.dumps({
            "kind": "Pod", "metadata": {"name": "kapi"},
            "spec": {"containers": [{"name": "c", "image": "api:v1"}]}}))

        def mirror():
            try:
                return client.pods("default").get("kapi-sm-1")
            except Exception:
                return None
        deadline = time.time() + 25
        while time.time() < deadline and mirror() is None:
            time.sleep(0.1)
        assert mirror() is not None
        # API-side deletion: pod keeps running, mirror comes back
        client.pods("default").delete("kapi-sm-1")
        deadline = time.time() + 25
        while time.time() < deadline and mirror() is None:
            time.sleep(0.1)
        assert mirror() is not None, "mirror not recreated"
        assert any(sb.pod_uid == "static-kapi-sm-1"
                   for sb in node.kubelet.runtime.list_sandboxes()), \
            "static pod was stopped by a mirror deletion"
        # manifest edit: the new spec rolls out
        mf.write_text(json.dumps({
            "kind": "Pod", "metadata": {"name": "kapi"},
            "spec": {"containers": [{"name": "c", "image": "api:v2"}]}}))
        deadline = time.time() + 25
        img = run_img = None
        while time.time() < deadline:
            m = mirror()
            img = (m or {}).get("spec", {}).get(
                "containers", [{}])[0].get("image")
            with node.kubelet._pods_lock:
                run_img = (node.kubelet._pods.get("static-kapi-sm-1") or
                           {}).get("spec", {}).get(
                    "containers", [{}])[0].get("image")
            if run_img == "api:v2" and img == "api:v2":
                break
            time.sleep(0.1)
        assert run_img == "api:v2", run_img
        assert img == "api:v2", "mirror pod not refreshed after edit"
    finally:
        if node is not None:
            node.stop()
        server.stop()


def test_scale_subresource(cluster):
    """GET/PUT /scale (autoscaling/v1 Scale, ScaleREST): replicas move
    through the subresource without touching the rest of the spec; the
    Scale metadata rv is the optimistic precondition."""
    server, client = cluster
    deps = client.resource("deployments", "default")
    deps.create({"kind": "Deployment", "metadata": {"name": "web"},
                 "spec": {"replicas": 2,
                          "selector": {"matchLabels": {"app": "web"}},
                          "template": {"spec": {"containers": [
                              {"name": "c", "image": "i"}]}}},
                 "status": {"replicas": 2}})
    sc = deps.get_scale("web")
    assert sc["kind"] == "Scale"
    assert sc["spec"]["replicas"] == 2
    assert sc["status"]["selector"] == "app=web"
    out = deps.update_scale("web", 5)
    assert out["spec"]["replicas"] == 5
    got = deps.get("web")
    assert got["spec"]["replicas"] == 5
    assert got["spec"]["template"]["spec"]["containers"]  # spec intact
    # stale rv precondition -> 409
    with pytest.raises(ApiError) as ei:
        deps.update_scale("web", 9, expect_rv=sc["metadata"][
            "resourceVersion"])
    assert ei.value.code == 409
    # ktpu scale rides the subresource
    out_io = io.StringIO()
    assert main(["--server", server.url, "scale", "deployments", "web",
                 "--replicas", "3"], out=out_io) == 0
    assert deps.get("web")["spec"]["replicas"] == 3
