"""Controllers: replicaset, deployment, job, daemonset, statefulset,
endpoints, nodelifecycle, garbage collector, controller manager wiring."""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers import (
    ControllerManager,
    DaemonSetController,
    DeploymentController,
    GarbageCollector,
    JobController,
    NodeLifecycleController,
    ReplicaSetController,
    StatefulSetController,
)
from kubernetes_tpu.controllers.deployment import HASH_LABEL, template_hash
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.nodelifecycle import TAINT_NOT_READY, TAINT_UNREACHABLE
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def start_controller(client, ctrl):
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    yield_obj = (ctrl, factory)
    return yield_obj


def rs_manifest(name="web", replicas=3, labels=None, ns="default"):
    labels = labels or {"app": name}
    return {
        "apiVersion": "apps/v1", "kind": "ReplicaSet",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {"containers": [{"name": "c", "image": "img",
                                         "resources": {"requests": {"cpu": "100m"}}}]},
            },
        },
        "status": {},
    }


def deployment_manifest(name="dep", replicas=3, image="img:v1", ns="default"):
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "strategy": {"type": "RollingUpdate",
                         "rollingUpdate": {"maxSurge": 1, "maxUnavailable": 1}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {"containers": [{"name": "c", "image": image}]},
            },
        },
        "status": {},
    }


def mark_pods_running(client, selector_fn=None, ns="default"):
    """Simulate kubelet: phase=Running + Ready condition on scheduled pods."""
    n = 0
    for p in client.pods(ns).list():
        if selector_fn is not None and not selector_fn(p):
            continue
        p["status"] = {"phase": "Running", "podIP": f"10.0.0.{n + 1}",
                       "conditions": [{"type": "Ready", "status": "True"}]}
        client.pods(ns).update_status(p)
        n += 1
    return n


# --------------------------------------------------------------- replicaset

def test_replicaset_scales_up_and_down(client):
    ctrl = ReplicaSetController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.resource("replicasets").create(rs_manifest(replicas=3))
        assert wait_until(lambda: len(client.pods().list()) == 3)
        pods = client.pods().list()
        assert all(p["metadata"]["ownerReferences"][0]["kind"] == "ReplicaSet"
                   for p in pods)
        # scale down to 1
        rs = client.resource("replicasets").get("web")
        rs["spec"]["replicas"] = 1
        client.resource("replicasets").update(rs)
        assert wait_until(lambda: len(client.pods().list()) == 1)
        # kill the survivor -> replaced
        client.pods().delete(client.pods().list()[0]["metadata"]["name"])
        assert wait_until(lambda: len(client.pods().list()) == 1)
        # status reflects
        assert wait_until(lambda: client.resource("replicasets").get("web")
                          .get("status", {}).get("replicas") == 1)
    finally:
        ctrl.stop()
        factory.stop_all()


def test_replicaset_ignores_unowned_pods(client):
    ctrl = ReplicaSetController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        # a pod with matching labels but no owner ref is NOT counted
        loose = make_pod("loose").label("app", "web").obj().to_dict()
        client.pods().create(loose)
        client.resource("replicasets").create(rs_manifest(replicas=2))
        assert wait_until(
            lambda: len([p for p in client.pods().list()
                         if p["metadata"].get("ownerReferences")]) == 2)
    finally:
        ctrl.stop()
        factory.stop_all()


# --------------------------------------------------------------- deployment

def test_deployment_creates_rs_and_rolls(client):
    dep_ctrl = DeploymentController(client)
    rs_ctrl = ReplicaSetController(client)
    factory = InformerFactory(client)
    dep_ctrl.register(factory)
    rs_ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    dep_ctrl.start()
    rs_ctrl.start()
    try:
        dep = deployment_manifest(replicas=2, image="img:v1")
        client.resource("deployments").create(dep)
        h1 = template_hash(dep)
        assert wait_until(lambda: len(client.pods().list()) == 2)
        rses = client.resource("replicasets").list()
        assert len(rses) == 1 and rses[0]["metadata"]["name"] == f"dep-{h1}"
        assert rses[0]["spec"]["template"]["metadata"]["labels"][HASH_LABEL] == h1
        mark_pods_running(client)

        # rollout: new template hash -> second RS, old drains as new readies
        cur = client.resource("deployments").get("dep")
        cur["spec"]["template"]["spec"]["containers"][0]["image"] = "img:v2"
        client.resource("deployments").update(cur)
        h2 = template_hash(cur)
        assert h2 != h1
        assert wait_until(
            lambda: any(rs["metadata"]["name"] == f"dep-{h2}"
                        for rs in client.resource("replicasets").list()))

        # keep marking pods ready as they appear (kubelet stand-in) until
        # the old RS is fully scaled down
        def rolled():
            mark_pods_running(client)
            rss = {rs["metadata"]["name"]: rs
                   for rs in client.resource("replicasets").list()}
            old = rss.get(f"dep-{h1}", {})
            new = rss.get(f"dep-{h2}", {})
            return (old.get("spec", {}).get("replicas") == 0
                    and new.get("spec", {}).get("replicas") == 2)
        assert wait_until(rolled, timeout=10.0)
    finally:
        dep_ctrl.stop()
        rs_ctrl.stop()
        factory.stop_all()


# ---------------------------------------------------------------------- job

def test_job_runs_to_completion(client):
    ctrl = JobController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        job = {
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": "work", "namespace": "default"},
            "spec": {"parallelism": 2, "completions": 4, "backoffLimit": 6,
                     "template": {"metadata": {"labels": {"job": "work"}},
                                  "spec": {"containers": [{"name": "c"}],
                                           "restartPolicy": "Never"}}},
            "status": {},
        }
        client.resource("jobs").create(job)
        assert wait_until(lambda: len(client.pods().list()) == 2)

        def finish_active():
            for p in client.pods().list():
                if p.get("status", {}).get("phase") not in ("Succeeded", "Failed"):
                    p["status"] = {"phase": "Succeeded"}
                    client.pods().update_status(p)

        def complete():
            finish_active()
            j = client.resource("jobs").get("work")
            return any(c.get("type") == "Complete" and c.get("status") == "True"
                       for c in j.get("status", {}).get("conditions", []))
        assert wait_until(complete, timeout=10.0)
        j = client.resource("jobs").get("work")
        assert j["status"]["succeeded"] == 4
    finally:
        ctrl.stop()
        factory.stop_all()


def test_job_backoff_limit_fails_job(client):
    ctrl = JobController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        job = {
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": "bad", "namespace": "default"},
            "spec": {"parallelism": 1, "completions": 1, "backoffLimit": 1,
                     "template": {"spec": {"containers": [{"name": "c"}],
                                           "restartPolicy": "Never"}}},
            "status": {},
        }
        client.resource("jobs").create(job)

        def fail_active():
            for p in client.pods().list():
                if p.get("status", {}).get("phase") not in ("Succeeded", "Failed"):
                    p["status"] = {"phase": "Failed"}
                    client.pods().update_status(p)

        def failed():
            fail_active()
            j = client.resource("jobs").get("bad")
            return any(c.get("type") == "Failed" and c.get("status") == "True"
                       for c in j.get("status", {}).get("conditions", []))
        assert wait_until(failed, timeout=10.0)
    finally:
        ctrl.stop()
        factory.stop_all()


# ---------------------------------------------------------------- daemonset

def test_daemonset_one_pod_per_eligible_node(client):
    for i in range(3):
        client.nodes().create(make_node(f"n{i}").allocatable(
            {"cpu": "4", "memory": "8Gi", "pods": "110"}).obj().to_dict())
    # tainted node: not eligible without toleration
    client.nodes().create(make_node("tainted").taint("gpu", "true", "NoSchedule")
                          .allocatable({"cpu": "4", "pods": "110"}).obj().to_dict())
    ctrl = DaemonSetController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        ds = {
            "apiVersion": "apps/v1", "kind": "DaemonSet",
            "metadata": {"name": "agent", "namespace": "default"},
            "spec": {"selector": {"matchLabels": {"app": "agent"}},
                     "template": {"metadata": {"labels": {"app": "agent"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
            "status": {},
        }
        client.resource("daemonsets").create(ds)
        assert wait_until(lambda: len(client.pods().list()) == 3)
        pins = set()
        for p in client.pods().list():
            terms = (p["spec"]["affinity"]["nodeAffinity"]
                     ["requiredDuringSchedulingIgnoredDuringExecution"]
                     ["nodeSelectorTerms"])
            pins.add(terms[0]["matchFields"][0]["values"][0])
        assert pins == {"n0", "n1", "n2"}
        # new node joins -> gets a daemon pod
        client.nodes().create(make_node("n3").allocatable(
            {"cpu": "4", "pods": "110"}).obj().to_dict())
        assert wait_until(lambda: len(client.pods().list()) == 4)
        # node drained -> its pod removed
        client.nodes().delete("n3")
        assert wait_until(lambda: len(client.pods().list()) == 3)
    finally:
        ctrl.stop()
        factory.stop_all()


# -------------------------------------------------------------- statefulset

def test_statefulset_ordered_bringup_and_scaledown(client):
    ctrl = StatefulSetController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        ss = {
            "apiVersion": "apps/v1", "kind": "StatefulSet",
            "metadata": {"name": "db", "namespace": "default"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "db"}},
                     "template": {"metadata": {"labels": {"app": "db"}},
                                  "spec": {"containers": [{"name": "c"}]}}},
            "status": {},
        }
        client.resource("statefulsets").create(ss)
        # only db-0 exists until it is Running+Ready
        assert wait_until(lambda: [p["metadata"]["name"]
                                   for p in client.pods().list()] == ["db-0"])
        time.sleep(0.2)
        assert len(client.pods().list()) == 1

        def advance():
            mark_pods_running(client)
            return len(client.pods().list()) == 3
        assert wait_until(advance, timeout=10.0)
        names = sorted(p["metadata"]["name"] for p in client.pods().list())
        assert names == ["db-0", "db-1", "db-2"]
        mark_pods_running(client)
        # scale down to 1: highest ordinals go first
        cur = client.resource("statefulsets").get("db")
        cur["spec"]["replicas"] = 1
        client.resource("statefulsets").update(cur)
        assert wait_until(lambda: sorted(p["metadata"]["name"] for p in
                                         client.pods().list()) == ["db-0"],
                          timeout=10.0)
    finally:
        ctrl.stop()
        factory.stop_all()


# ---------------------------------------------------------------- endpoints

def test_endpoints_track_ready_pods(client):
    ctrl = EndpointsController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.services().create({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"selector": {"app": "web"},
                     "ports": [{"port": 80, "targetPort": 8080}]},
        })
        p = make_pod("w1").label("app", "web").obj().to_dict()
        p["status"] = {"phase": "Running", "podIP": "10.1.0.5",
                       "conditions": [{"type": "Ready", "status": "True"}]}
        client.pods().create(p)
        p2 = make_pod("w2").label("app", "web").obj().to_dict()
        p2["status"] = {"phase": "Running", "podIP": "10.1.0.6"}  # not ready
        client.pods().create(p2)

        def has_eps():
            try:
                ep = client.endpoints().get("web")
            except Exception:
                return False
            subs = ep.get("subsets") or []
            if not subs:
                return False
            ready = [a["ip"] for a in subs[0].get("addresses", [])]
            notready = [a["ip"] for a in subs[0].get("notReadyAddresses", [])]
            return ready == ["10.1.0.5"] and notready == ["10.1.0.6"]
        assert wait_until(has_eps)
        # pod deleted -> endpoints shrink
        client.pods().delete("w1")
        assert wait_until(lambda: not (client.endpoints().get("web")
                                       .get("subsets") or [{}])[0].get("addresses"))
    finally:
        ctrl.stop()
        factory.stop_all()


# ------------------------------------------------------------ nodelifecycle

def test_nodelifecycle_taints_and_evicts(client):
    node = make_node("sick").allocatable({"cpu": "4", "pods": "10"}).obj().to_dict()
    node["status"]["conditions"] = [{"type": "Ready", "status": "True",
                                     "lastHeartbeatTime": time.time()}]
    client.nodes().create(node)
    pod = make_pod("victim").node("sick").obj().to_dict()
    pod["status"] = {"phase": "Running"}
    client.pods().create(pod)
    tolerant = make_pod("survivor").node("sick") \
        .toleration(key=TAINT_NOT_READY, operator="Exists", effect="NoExecute") \
        .obj().to_dict()
    tolerant["status"] = {"phase": "Running"}
    client.pods().create(tolerant)

    ctrl = NodeLifecycleController(client, grace_period=0.5, monitor_period=0.1)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        # healthy -> no taint
        time.sleep(0.3)
        assert not (client.nodes().get("sick")["spec"].get("taints") or [])
        # report NotReady -> not-ready taint + eviction of intolerant pod
        n = client.nodes().get("sick")
        n["status"]["conditions"] = [{"type": "Ready", "status": "False",
                                      "lastHeartbeatTime": time.time()}]
        client.nodes().update_status(n)
        assert wait_until(lambda: any(
            t["key"] == TAINT_NOT_READY
            for t in client.nodes().get("sick")["spec"].get("taints") or []))
        assert wait_until(lambda: [p["metadata"]["name"]
                                   for p in client.pods().list()] == ["survivor"])
        # recovery -> taint removed
        n = client.nodes().get("sick")
        n["status"]["conditions"] = [{"type": "Ready", "status": "True",
                                      "lastHeartbeatTime": time.time()}]
        client.nodes().update_status(n)
        assert wait_until(lambda: not any(
            t["key"] in (TAINT_NOT_READY, TAINT_UNREACHABLE)
            for t in client.nodes().get("sick")["spec"].get("taints") or []))
    finally:
        ctrl.stop()
        factory.stop_all()


# --------------------------------------------------------- garbagecollector

def test_gc_deletes_orphans(client):
    factory = InformerFactory(client)
    gc = GarbageCollector(client)
    gc.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    rs = client.resource("replicasets").create(rs_manifest(replicas=0))
    pod = make_pod("child").obj().to_dict()
    pod["metadata"]["ownerReferences"] = [{
        "apiVersion": "apps/v1", "kind": "ReplicaSet", "name": "web",
        "uid": rs["metadata"]["uid"], "controller": True}]
    client.pods().create(pod)
    time.sleep(0.2)
    assert gc.sweep() == 0  # owner alive -> nothing deleted
    client.resource("replicasets").delete("web")
    assert wait_until(lambda: gc.sweep() >= 1)
    assert wait_until(lambda: len(client.pods().list()) == 0)
    factory.stop_all()


# ----------------------------------------------------------------- manager

def test_controller_manager_end_to_end(client):
    mgr = ControllerManager(client, resync_period=0.5)
    mgr.start()
    try:
        client.resource("deployments").create(deployment_manifest(replicas=2))
        assert wait_until(lambda: len(client.pods().list()) == 2, timeout=10.0)
        mark_pods_running(client)
        assert wait_until(
            lambda: client.resource("deployments").get("dep")
            .get("status", {}).get("readyReplicas") == 2, timeout=10.0)
        # deployment delete -> GC cascades RS + pods
        client.resource("deployments").delete("dep")
        assert wait_until(lambda: len(client.pods().list()) == 0, timeout=10.0)
        assert wait_until(
            lambda: len(client.resource("replicasets").list()) == 0, timeout=10.0)
    finally:
        mgr.stop()


def test_endpoints_named_targetport_resolved_per_pod(client):
    """A named targetPort must resolve against EACH pod's containers
    (FindPort): old and new pods mapping the name to different
    containerPorts land in separate subsets with their own ports."""
    ctrl = EndpointsController(client)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        client.services().create({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "api", "namespace": "default"},
            "spec": {"selector": {"app": "api"},
                     "ports": [{"port": 80, "targetPort": "http"}]},
        })
        for name, ip, cport in (("old", "10.2.0.1", 8080),
                                ("new", "10.2.0.2", 9090)):
            p = make_pod(name).label("app", "api").obj().to_dict()
            p["spec"]["containers"][0]["ports"] = [
                {"name": "http", "containerPort": cport}]
            p["status"] = {"phase": "Running", "podIP": ip,
                           "conditions": [{"type": "Ready", "status": "True"}]}
            client.pods().create(p)

        def split_by_port():
            try:
                ep = client.endpoints().get("api")
            except Exception:
                return False
            subs = ep.get("subsets") or []
            got = {(s["ports"][0]["port"],
                    tuple(a["ip"] for a in s.get("addresses", [])))
                   for s in subs}
            return got == {(8080, ("10.2.0.1",)), (9090, ("10.2.0.2",))}
        assert wait_until(split_by_port)
    finally:
        ctrl.stop()
        factory.stop_all()


def test_node_lease_renewal_counts_as_heartbeat():
    """pkg/kubelet/nodelease: a fresh Lease renewTime keeps a node healthy
    even when the status heartbeat is stale (upstream kubelets renew
    leases every 10s but touch node status only 5-minutely); a node with
    BOTH stale is tainted unreachable."""
    import time as _time
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.controllers.nodelifecycle import (
        TAINT_UNREACHABLE, NodeLifecycleController)
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    stale = _time.time() - 3600
    for name, lease_fresh in (("leasey", True), ("deady", False)):
        client.nodes().create({
            "kind": "Node", "metadata": {"name": name},
            "spec": {},
            "status": {"conditions": [{
                "type": "Ready", "status": "True",
                "lastHeartbeatTime": stale}]}})
        if lease_fresh:
            client.leases("kube-node-lease").create({
                "kind": "Lease",
                "metadata": {"name": name,
                             "namespace": "kube-node-lease"},
                "spec": {"holderIdentity": name,
                         "renewTime": _time.time()}})
    ctrl = NodeLifecycleController(client, grace_period=5.0,
                                   monitor_period=0.2)
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    try:
        def taints(name):
            return [t.get("key") for t in
                    (client.nodes().get(name).get("spec") or {})
                    .get("taints") or []]
        deadline = _time.time() + 8
        while _time.time() < deadline:
            if TAINT_UNREACHABLE in taints("deady"):
                break
            _time.sleep(0.1)
        assert TAINT_UNREACHABLE in taints("deady")
        assert TAINT_UNREACHABLE not in taints("leasey")  # lease saved it
    finally:
        ctrl.stop()
        factory.stop_all()


def test_gc_foreground_and_orphan_propagation():
    """DeleteOptions.propagationPolicy: Foreground holds the owner
    terminating until the GC deletes its dependents; Orphan strips
    ownerReferences so dependents survive the owner
    (pkg/controller/garbagecollector attemptToDeleteItem finalizers)."""
    from kubernetes_tpu.client.clientset import ApiError, DirectClient
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
    from kubernetes_tpu.store.store import ObjectStore
    from kubernetes_tpu.testing.wrappers import make_pod
    client = DirectClient(ObjectStore())
    rss = client.resource("replicasets", "default")
    rs = rss.create({"kind": "ReplicaSet", "metadata": {"name": "fg"},
                     "spec": {"replicas": 1}})
    uid = rs["metadata"]["uid"]
    pod = make_pod("fg-pod").obj().to_dict()
    pod["metadata"]["ownerReferences"] = [{
        "kind": "ReplicaSet", "name": "fg", "uid": uid,
        "controller": True, "blockOwnerDeletion": True}]
    client.pods("default").create(pod)
    gc = GarbageCollector(client)
    factory = InformerFactory(client)
    gc.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    try:
        # FOREGROUND: owner terminates, dependent deleted FIRST
        rss.delete("fg", propagation_policy="Foreground")
        held = rss.get("fg")
        assert held["metadata"]["deletionTimestamp"]
        assert "foregroundDeletion" in held["metadata"]["finalizers"]
        deadline = time.time() + 8
        while time.time() < deadline:
            gc.sweep()
            try:
                rss.get("fg")
            except ApiError:
                break
            time.sleep(0.1)
        with pytest.raises(ApiError):
            rss.get("fg")       # owner finally gone...
        with pytest.raises(ApiError):
            client.pods("default").get("fg-pod")  # ...after its dependent

        # ORPHAN: dependents lose the reference and SURVIVE
        rs2 = rss.create({"kind": "ReplicaSet", "metadata": {"name": "or"},
                          "spec": {"replicas": 1}})
        pod2 = make_pod("or-pod").obj().to_dict()
        pod2["metadata"]["ownerReferences"] = [{
            "kind": "ReplicaSet", "name": "or",
            "uid": rs2["metadata"]["uid"], "controller": True}]
        client.pods("default").create(pod2)
        rss.delete("or", propagation_policy="Orphan")
        deadline = time.time() + 8
        while time.time() < deadline:
            gc.sweep()
            try:
                rss.get("or")
            except ApiError:
                break
            time.sleep(0.1)
        with pytest.raises(ApiError):
            rss.get("or")
        survivor = client.pods("default").get("or-pod")
        assert not (survivor["metadata"].get("ownerReferences"))
        gc.sweep()  # background pass must NOT collect the orphan
        client.pods("default").get("or-pod")
    finally:
        factory.stop_all()
