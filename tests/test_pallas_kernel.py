"""Parity: the fused Pallas selector-match+count kernel vs the XLA
match+einsum pair it replaces (ops/pallas/domain_count.py vs
ops/topology.py _term_match_epods x onehot einsum).

Runs in interpreter mode so it validates on the CPU suite; the real-TPU
compile is covered by the kernel's own self-test at enablement and by
benchmarks/pallas_bench.py.
"""

import random

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.encode.snapshot import SnapshotEncoder
from kubernetes_tpu.ops.pallas.domain_count import match_count
from kubernetes_tpu.ops.topology import _term_match_epods
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONES = ["z0", "z1", "z2"]
APPS = ["web", "db", "cache"]
NAMESPACES = ["default", "team-a", "team-b"]


def xla_count(ct, sel, pod_ns, ns_explicit=None, ns_mask=None):
    N = ct.node_valid.shape[0]
    match = _term_match_epods(ct, sel, pod_ns, ns_explicit, ns_mask)
    onehot = (ct.epod_node[:, None] == jnp.arange(N)[None, :]).astype(jnp.float32)
    return jnp.einsum("ept,en->ptn", match, onehot)


def build_cluster(rng, n_nodes=12, n_bound=40, n_pods=6):
    nodes = [make_node(f"n{i}").capacity({"cpu": "64", "pods": "200"})
             .label("zone", rng.choice(ZONES)).obj() for i in range(n_nodes)]
    bound = []
    for i in range(n_bound):
        w = make_pod(f"b{i}", namespace=rng.choice(NAMESPACES)) \
            .label("app", rng.choice(APPS))
        if rng.random() < 0.5:
            w.label("rev", str(rng.randint(1, 3)))
        p = w.obj()
        p.spec.node_name = f"n{rng.randint(0, n_nodes - 1)}"
        bound.append(p)
    pods = []
    for i in range(n_pods):
        w = make_pod(f"p{i}", namespace=rng.choice(NAMESPACES)) \
            .label("app", rng.choice(APPS))
        kw = {}
        r = rng.random()
        if r < 0.3:
            kw["namespaces"] = rng.sample(NAMESPACES, k=rng.randint(1, 2))
        elif r < 0.45:
            kw["namespace_selector"] = {}
        if rng.random() < 0.3:
            kw["match_label_keys"] = ["rev"]
        w.pod_anti_affinity("zone", {"app": rng.choice(APPS)}, **kw)
        if rng.random() < 0.5:
            w.pod_affinity("zone", {"app": rng.choice(APPS)})
        pods.append(w.obj())
    enc = SnapshotEncoder()
    enc.set_namespaces({n: {} for n in NAMESPACES})
    ct, meta = enc.encode_cluster(nodes, bound, pending_pods=pods)
    pb = enc.encode_pods(pods, meta)
    return ct, pb, len(nodes)


@pytest.mark.parametrize("seed", range(8))
def test_fused_count_matches_xla(seed):
    rng = random.Random(seed)
    ct, pb, n_nodes = build_cluster(rng)
    for sel, topo_valid, nse, nsm in [
            (pb.anti_sel, pb.anti_valid, pb.anti_ns_explicit, pb.anti_ns_mask),
            (pb.aff_sel, pb.aff_valid, pb.aff_ns_explicit, pb.aff_ns_mask)]:
        if sel.key.shape[1] == 0 or sel.key.shape[2] == 0:
            continue
        want = np.asarray(xla_count(ct, sel, pb.pod_ns, nse, nsm))
        got = np.asarray(match_count(
            ct.epod_labels, ct.epod_node, ct.epod_ns, ct.epod_valid,
            sel.key, sel.op, sel.expr_valid, sel.vals, sel.valid, pb.pod_ns,
            ns_explicit=nse, ns_mask=nsm,
            n_nodes=int(ct.node_valid.shape[0]), interpret=True))
        np.testing.assert_allclose(got, want, atol=0, rtol=0,
                                   err_msg=f"seed={seed}")


def test_fused_count_empty_and_pad_cases():
    rng = random.Random(99)
    ct, pb, _ = build_cluster(rng, n_nodes=3, n_bound=2, n_pods=2)
    # invalid selector rows, pad terms, and ns ids beyond the mask bucket all
    # must contribute zero — compare against the XLA reference on the spread
    # selector set too (own-namespace only path)
    sel = pb.sc_sel
    if sel.key.shape[1] and sel.key.shape[2]:
        want = np.asarray(xla_count(ct, sel, pb.pod_ns))
        got = np.asarray(match_count(
            ct.epod_labels, ct.epod_node, ct.epod_ns, ct.epod_valid,
            sel.key, sel.op, sel.expr_valid, sel.vals, sel.valid, pb.pod_ns,
            n_nodes=int(ct.node_valid.shape[0]), interpret=True))
        np.testing.assert_allclose(got, want)
