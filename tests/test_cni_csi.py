"""CNI exec seam + CSI gRPC seam.

Reference: the CNI spec's exec/JSON protocol (env verbs, stdin conf,
stdout result) and the CSI Node service the kubelet mounts through
(``pkg/volume/csi``).
"""

import pytest

from kubernetes_tpu.kubelet.cni import CNI
from kubernetes_tpu.kubelet.csi import CSIDriverServer, CSIVolumePlugin
from kubernetes_tpu.kubelet.volumemanager import VolumeManager


# ------------------------------------------------------------------- CNI

def test_cni_add_del_via_exec(tmp_path):
    cni = CNI(data_dir=str(tmp_path))
    ip1 = cni.add("ctr-1")
    ip2 = cni.add("ctr-2")
    assert ip1 != ip2 and ip1.startswith("10.88.")
    # ADD is idempotent per container id (state lives in the PLUGIN's dir)
    assert cni.add("ctr-1") == ip1
    cni.delete("ctr-1")
    # released id gets a FRESH ip (sequential allocator)
    assert cni.add("ctr-1") not in (ip1,)


def test_cni_backs_sandbox_ips(tmp_path):
    from kubernetes_tpu.kubelet.runtime import FakeRuntime
    cni = CNI(data_dir=str(tmp_path))
    rt = FakeRuntime(ip_alloc=cni.ip_allocator())
    a = rt.run_pod_sandbox("u1", "a", "default")
    b = rt.run_pod_sandbox("u2", "b", "default")
    assert a.ip != b.ip and a.ip.startswith("10.88.")


# ------------------------------------------------------------------- CSI

@pytest.fixture()
def csi():
    driver = CSIDriverServer().start()
    plugin = CSIVolumePlugin(driver.address, node_name="n0")
    yield driver, plugin
    plugin.close()
    driver.stop()


def test_csi_stage_publish_lifecycle(csi):
    driver, plugin = csi
    assert plugin.plugin_info()["name"] == "hollow.csi.ktpu"
    plugin.mount("vol-1", "pod-a")
    plugin.mount("vol-1", "pod-b")  # second pod: publish only, one stage
    assert "vol-1" in driver.staged
    assert set(driver.published) == {"vol-1/pod-a", "vol-1/pod-b"}
    plugin.unmount("vol-1", "pod-a", last_pod=False)
    assert "vol-1" in driver.staged
    plugin.unmount("vol-1", "pod-b", last_pod=True)
    assert "vol-1" not in driver.staged
    assert not driver.published


def test_csi_publish_requires_stage(csi):
    driver, plugin = csi
    with pytest.raises(RuntimeError):
        plugin._req("NodePublishVolume", volume_id="ghost", pod_uid="p",
                    target_path="/t")


def test_volume_manager_drives_csi(csi):
    driver, plugin = csi
    vm = VolumeManager(csi_plugin=plugin)
    pod = {"metadata": {"uid": "u-csi"},
           "spec": {"volumes": [{"name": "data",
                                 "csi": {"driver": "hollow.csi.ktpu",
                                         "volumeHandle": "vol-9"}}]}}
    vm.add_pod(pod)
    vm.reconcile_once()
    assert "vol-9" in driver.staged
    assert "vol-9/u-csi" in driver.published
    assert vm.wait_for_attach_and_mount(pod, timeout=1.0)
    vm.remove_pod(pod)
    vm.reconcile_once()
    assert not driver.published
    assert "vol-9" not in driver.staged  # last pod gone -> unstaged


def test_shared_csi_volume_gates_per_pod(csi):
    """A second pod sharing a csi volume must wait for ITS OWN publish."""
    driver, plugin = csi
    vm = VolumeManager(csi_plugin=plugin)
    vol = {"name": "d", "csi": {"driver": "x", "volumeHandle": "shared"}}
    pod_a = {"metadata": {"uid": "pa"}, "spec": {"volumes": [vol]}}
    pod_b = {"metadata": {"uid": "pb"}, "spec": {"volumes": [vol]}}
    vm.add_pod(pod_a)
    vm.reconcile_once()
    assert vm.wait_for_attach_and_mount(pod_a, timeout=1.0)
    vm.add_pod(pod_b)
    # B not yet published: the gate must NOT open on A's mount
    assert not vm.wait_for_attach_and_mount(pod_b, timeout=0.2)
    vm.reconcile_once()
    assert vm.wait_for_attach_and_mount(pod_b, timeout=1.0)
    # both pods removed in ONE reconcile: unstage must come last (the
    # hollow driver would error a publish-after-unstage; survive = ordered)
    vm.remove_pod(pod_a)
    vm.remove_pod(pod_b)
    vm.reconcile_once()
    assert not driver.published and "shared" not in driver.staged
