"""Per-profile NodeAffinityArgs.addedAffinity.

Reference: ``pkg/scheduler/framework/plugins/nodeaffinity/node_affinity.go``
— a profile-level NodeAffinity applied to every pod the profile schedules,
IN ADDITION to the pod's own affinity (both must hold); preferred terms add
to scoring. Folded scheduler-side into the encoded terms
(Profile.apply_added_affinity) so tensor and oracle paths agree by
construction.
"""

import pytest

from kubernetes_tpu.api.types import Pod, with_added_node_affinity
from kubernetes_tpu.config.types import Profile, SchedulerConfiguration
from kubernetes_tpu.sched.cache import SchedulerCache
from kubernetes_tpu.sched.queue import SchedulingQueue
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"

ADDED = {"requiredDuringSchedulingIgnoredDuringExecution": {
    "nodeSelectorTerms": [{"matchExpressions": [
        {"key": ZONE, "operator": "In", "values": ["a"]}]}]}}


def _sched(nodes, added=None):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = SchedulingQueue(backoff_initial=0.05)
    log = []
    cfg = SchedulerConfiguration(
        profiles=[Profile(added_affinity=added)])
    sched = Scheduler(cfg, cache, queue,
                      lambda pod, node: log.append(
                          (pod.metadata.name, node)) or True)
    return sched, queue, log


def _zone_nodes():
    return [make_node(f"n-{z}{i}").capacity({"cpu": "4", "pods": "10"})
            .label(ZONE, z).obj() for z in ("a", "b") for i in range(2)]


def test_added_affinity_restricts_placement():
    sched, queue, log = _sched(_zone_nodes(), added=ADDED)
    for i in range(4):
        queue.add(make_pod(f"p{i}").req({"cpu": "1"}).obj())
    n = sched.run_once()
    sched.wait_for_bindings()
    assert n == 4
    assert all(node.startswith("n-a") for _name, node in log), log


def test_added_affinity_ands_with_pods_own():
    """A pod whose OWN affinity demands zone b cannot satisfy both — it
    must go unschedulable, exactly like two selectors ANDed upstream."""
    sched, queue, log = _sched(_zone_nodes(), added=ADDED)
    queue.add(make_pod("own-b").req({"cpu": "1"})
              .node_affinity_in(ZONE, ["b"]).obj())
    queue.add(make_pod("own-a").req({"cpu": "1"})
              .node_affinity_in(ZONE, ["a"]).obj())
    sched.run_once()
    sched.wait_for_bindings()
    bound = dict(log)
    assert "own-b" not in bound
    assert bound.get("own-a", "").startswith("n-a")


def test_no_added_affinity_is_noop():
    sched, queue, log = _sched(_zone_nodes(), added=None)
    queue.add(make_pod("p").req({"cpu": "1"}).obj())
    assert sched.run_once() == 1


def test_merge_cross_product():
    """AND of two OR-term selectors = cross product of merged terms."""
    pod = (make_pod("x")
           .node_affinity_in("disk", ["ssd", "hdd"]).obj())
    merged = with_added_node_affinity(pod, ADDED)
    terms = merged.spec.affinity.node_affinity.required
    # pod had 1 term, added has 1 -> 1x1 product with both expression sets
    assert len(terms) == 1
    keys = sorted(e.key for e in terms[0].match_expressions)
    assert keys == ["disk", ZONE]
    # original pod untouched
    own = pod.spec.affinity.node_affinity.required
    assert len(own[0].match_expressions) == 1


def test_merge_preferred_appends_and_scores():
    added = {"preferredDuringSchedulingIgnoredDuringExecution": [
        {"weight": 100, "preference": {"matchExpressions": [
            {"key": ZONE, "operator": "In", "values": ["b"]}]}}]}
    sched, queue, log = _sched(_zone_nodes(), added=added)
    for i in range(2):
        queue.add(make_pod(f"p{i}").req({"cpu": "1"}).obj())
    n = sched.run_once()
    sched.wait_for_bindings()
    assert n == 2
    # soft preference: everything still schedules, onto the preferred zone
    assert all(node.startswith("n-b") for _name, node in log), log


def test_oracle_parity_with_merged_pods():
    """Tensor placement with addedAffinity == oracle on manually-merged
    pods (feasibility sets identical)."""
    from kubernetes_tpu.sched.oracle import OracleScheduler
    nodes = _zone_nodes()
    pods = [make_pod(f"q{i}").req({"cpu": "1"}).obj() for i in range(4)]
    merged = [with_added_node_affinity(p, ADDED) for p in pods]
    orc = OracleScheduler(nodes, [])
    for p in merged:
        mask, _ = orc.feasible(p)
        feas = {nodes[i].metadata.name for i, ok in enumerate(mask) if ok}
        assert feas == {"n-a0", "n-a1"}
