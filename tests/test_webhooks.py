"""External admission webhooks: HTTP transport, failurePolicy, timeouts,
JSONPatch mutation.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/``
and the admission/v1 AdmissionReview wire shape.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.webhooks import WebhookTestServer, apply_json_patch
from kubernetes_tpu.testing.wrappers import make_pod


@pytest.fixture()
def api():
    server = APIServer()
    server.enable_admission()
    server.start()
    yield server
    server.stop()


def _register(client, kind_cfg, plural, name, url, *, resources=("pods",),
              operations=("CREATE",), failure_policy="Fail", timeout=10):
    client.resource(plural, None).create({
        "kind": kind_cfg, "metadata": {"name": name},
        "webhooks": [{
            "name": f"{name}.example.com",
            "clientConfig": {"url": url},
            "rules": [{"operations": list(operations),
                       "resources": list(resources)}],
            "failurePolicy": failure_policy,
            "timeoutSeconds": timeout,
        }]})


def test_mutating_webhook_patches_object(api):
    hook = WebhookTestServer(mutate=lambda review: [
        {"op": "add", "path": "/metadata/labels/injected", "value": "yes"},
        {"op": "add", "path": "/spec/priority", "value": 7},
    ]).start()
    try:
        c = HTTPClient(api.url)
        _register(c, "MutatingWebhookConfiguration",
                  "mutatingwebhookconfigurations", "mwh", hook.url)
        c.pods("default").create(make_pod("m").obj().to_dict())
        got = c.pods("default").get("m")
        assert got["metadata"]["labels"]["injected"] == "yes"
        assert got["spec"]["priority"] == 7
        assert hook.calls >= 1
    finally:
        hook.stop()


def test_validating_webhook_denies(api):
    def validate(review):
        name = review["request"]["object"]["metadata"]["name"]
        return (not name.startswith("bad"), f"{name} is forbidden")
    hook = WebhookTestServer(validate=validate).start()
    try:
        c = HTTPClient(api.url)
        _register(c, "ValidatingWebhookConfiguration",
                  "validatingwebhookconfigurations", "vwh", hook.url)
        c.pods("default").create(make_pod("good").obj().to_dict())
        with pytest.raises(ApiError) as ei:
            c.pods("default").create(make_pod("bad1").obj().to_dict())
        assert ei.value.code == 400
        assert "forbidden" in str(ei.value)
        # the denied object must not exist
        with pytest.raises(ApiError):
            c.pods("default").get("bad1")
    finally:
        hook.stop()


def test_failure_policy_fail_vs_ignore(api):
    c = HTTPClient(api.url)
    dead = "http://127.0.0.1:1/unreachable"
    _register(c, "ValidatingWebhookConfiguration",
              "validatingwebhookconfigurations", "dead-fail", dead,
              failure_policy="Fail")
    with pytest.raises(ApiError) as ei:
        c.pods("default").create(make_pod("x").obj().to_dict())
    assert "failurePolicy=Fail" in str(ei.value)
    # flip to Ignore: the unreachable webhook is skipped
    c.resource("validatingwebhookconfigurations", None).delete("dead-fail")
    _register(c, "ValidatingWebhookConfiguration",
              "validatingwebhookconfigurations", "dead-ignore", dead,
              failure_policy="Ignore")
    time.sleep(1.1)  # config poll window
    c.pods("default").create(make_pod("x").obj().to_dict())
    assert c.pods("default").get("x")["metadata"]["name"] == "x"


def test_webhook_timeout_respected(api):
    hook = WebhookTestServer(validate=lambda r: (True, ""),
                             latency_s=3.0).start()
    try:
        c = HTTPClient(api.url, timeout=30.0)
        _register(c, "ValidatingWebhookConfiguration",
                  "validatingwebhookconfigurations", "slow", hook.url,
                  failure_policy="Fail", timeout=1)
        t0 = time.time()
        with pytest.raises(ApiError):
            c.pods("default").create(make_pod("t").obj().to_dict())
        assert time.time() - t0 < 3.0  # timed out at ~1s, not 3s latency
    finally:
        hook.stop()


def test_rules_scope_webhook_to_kinds(api):
    hook = WebhookTestServer(validate=lambda r: (False, "no pods")).start()
    try:
        c = HTTPClient(api.url)
        _register(c, "ValidatingWebhookConfiguration",
                  "validatingwebhookconfigurations", "pods-only", hook.url,
                  resources=("pods",))
        # a configmap is outside the rules: admitted without calling out
        c.resource("configmaps", "default").create(
            {"kind": "ConfigMap", "metadata": {"name": "cm"}})
        assert hook.calls == 0
        with pytest.raises(ApiError):
            c.pods("default").create(make_pod("p").obj().to_dict())
    finally:
        hook.stop()


def test_apply_json_patch_ops():
    obj = {"spec": {"containers": [{"name": "a"}]}, "metadata": {}}
    out = apply_json_patch(obj, [
        {"op": "add", "path": "/metadata/labels", "value": {"k": "v"}},
        {"op": "add", "path": "/spec/containers/-", "value": {"name": "b"}},
        {"op": "replace", "path": "/spec/containers/0/name", "value": "a2"},
        {"op": "remove", "path": "/metadata/labels"},
    ])
    assert [c["name"] for c in out["spec"]["containers"]] == ["a2", "b"]
    assert "labels" not in out["metadata"]
    # original untouched
    assert obj["spec"]["containers"][0]["name"] == "a"


def test_webhook_subresource_scoping(api):
    """A rule for 'pods' must NOT fire on status PUTs; 'pods/status' opts
    in; 'pods/*' matches both (plugin/webhook/rules/rules.go Matcher)."""
    hook = WebhookTestServer(validate=lambda r: (True, "")).start()
    try:
        c = HTTPClient(api.url)
        _register(c, "ValidatingWebhookConfiguration",
                  "validatingwebhookconfigurations", "main-only", hook.url,
                  resources=("pods",), operations=("UPDATE",))
        pods = c.pods("default")
        pods.create(make_pod("w").obj().to_dict())
        pod = pods.get("w")
        pod.setdefault("status", {})["phase"] = "Running"
        pods.update_status(pod)  # status heartbeat: rule must not fire
        assert hook.calls == 0
        pod = pods.get("w")
        pod["metadata"].setdefault("labels", {})["x"] = "1"
        pods.update(pod)  # main-resource UPDATE: fires
        assert hook.calls == 1

        # a pods/status rule fires ONLY on the status fragment
        _register(c, "ValidatingWebhookConfiguration",
                  "validatingwebhookconfigurations", "status-only", hook.url,
                  resources=("pods/status",), operations=("UPDATE",))
        time.sleep(1.1)  # config poll window
        pod = pods.get("w")
        pod["status"]["phase"] = "Succeeded"
        pods.update_status(pod)
        assert hook.calls == 2  # status-only fired, main-only did not
    finally:
        hook.stop()
