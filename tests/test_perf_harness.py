"""scheduler_perf harness: config loading, op materialization, thresholds,
and the tracing aux subsystem."""

import time

from benchmarks.scheduler_perf import load_config, materialize, run_workload
from kubernetes_tpu.utils.tracing import Tracer


def test_config_covers_baseline_cases():
    cases = {c["name"] for c in load_config()}
    assert {"SchedulingBasic", "NodeResourcesFit", "SchedulingPodAntiAffinity",
            "PreferredTopologySpreading", "MixedHeterogeneous"} <= cases


def test_materialize_ops():
    cases = {c["name"]: c for c in load_config()}
    nodes, measured, warm = materialize(
        cases["SchedulingPodAntiAffinity"],
        {"initNodes": 8, "measurePods": 4})
    assert len(nodes) == 8 and len(measured) == 4 and warm == []
    # label strategy cycles zones
    zones = {n.metadata.labels["topology.kubernetes.io/zone"] for n in nodes}
    assert len(zones) == 4
    # template parsed into real API objects with anti-affinity
    assert measured[0].spec.affinity.pod_anti_affinity.required


def test_slo_gates_fail_on_missing_or_worse_numbers():
    """The hard SLO gate contract (BENCH_r05 lesson): a value that is
    missing, None, or unparsed fails exactly like a regressed one — it can
    never read as a pass. Unknown gate keys refuse to skip silently too."""
    from benchmarks.connected import check_slo_gates
    gates = {"SchedulingThroughput": 30, "p99AttemptLatencySeconds": 30}
    ok = {"SchedulingThroughput": 75.0, "p99_attempt_latency_s": 12.0}
    assert check_slo_gates(ok, gates) == []
    slow = {"SchedulingThroughput": 10.0, "p99_attempt_latency_s": 45.0}
    assert len(check_slo_gates(slow, gates)) == 2
    missing = {"SchedulingThroughput": None}  # p99 absent entirely
    fails = check_slo_gates(missing, gates)
    assert len(fails) == 2 and all("missing" in f for f in fails)
    assert check_slo_gates(ok, {"bogusGate": 1})  # unknown key = failure
    assert check_slo_gates(ok, None) == [] == check_slo_gates(ok, {})
    # the churn case config actually carries the gates the bench enforces
    cases = {c["name"]: c for c in load_config()}
    wl = cases["SchedulingChurn"]["workloads"][0]
    assert wl["sloGates"]["p99AttemptLatencySeconds"] > 0
    assert wl["sloGates"]["SchedulingThroughput"] > 0


def test_run_workload_small_passes_threshold():
    cases = {c["name"]: c for c in load_config()}
    res = run_workload(cases["SchedulingBasic"],
                       cases["SchedulingBasic"]["workloads"][0], scale=0.2)
    assert res["scheduled"] == res["pods"]
    assert res["passed"], res
    assert res["SchedulingThroughput"] > 0


def test_tracer_spans_nest_and_sample():
    tr = Tracer()
    with tr.span("outer", a=1):
        time.sleep(0.01)
        with tr.span("inner"):
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["inner", "outer"]
    outer = tr.spans("outer")[0]
    assert outer.parent is None and outer.duration_ms >= 10
    assert tr.spans("inner")[0].parent == "outer"
    assert outer.attributes == {"a": 1}


def test_scheduler_emits_spans():
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.store import ObjectStore
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    from kubernetes_tpu.utils.tracing import TRACER

    TRACER.reset()
    client = DirectClient(ObjectStore())
    client.nodes().create(make_node("t1").allocatable(
        {"cpu": "4", "pods": "10"}).obj().to_dict())
    runner = SchedulerRunner(client).start()
    try:
        client.pods().create(make_pod("traced").req({"cpu": "100m"}).obj().to_dict())
        deadline = time.time() + 15
        while time.time() < deadline:
            if client.pods().get("traced")["spec"].get("nodeName"):
                break
            time.sleep(0.05)
        assert client.pods().get("traced")["spec"].get("nodeName")
        names = {s.name for s in TRACER.spans()}
        assert "scheduler/gang_schedule" in names
        assert "scheduler/snapshot" in names
    finally:
        runner.stop()
