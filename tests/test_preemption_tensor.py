"""Tensorized preemption (ops/preemption.py) vs the exact serial scan.

The device dry-run must agree with ``find_candidate`` (the
SelectVictimsOnNode/pickOneNodeForPreemption parity reference,
``pkg/scheduler/framework/preemption/preemption.go``) on resource-driven
scenarios, and must fall back to the exact scan when the failure is
relational.
"""

import random

import pytest

from kubernetes_tpu.sched.preemption import (
    find_candidate,
    find_candidate_tensor,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _same(a, b):
    if a is None or b is None:
        return a is b
    return (a.node_name == b.node_name
            and [v.metadata.name for v in a.victims]
            == [v.metadata.name for v in b.victims]
            and a.num_pdb_violations == b.num_pdb_violations)


def test_tensor_matches_exact_basic():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(4)]
    bound = []
    for i in range(4):  # fill every node with low-priority pods
        for j in range(2):
            bound.append(make_pod(f"v{i}-{j}").req({"cpu": "2"})
                         .priority(j + 1).node(f"n{i}").obj())
    pod = make_pod("hi").req({"cpu": "2"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert exact is not None and _same(exact, tensor)


def test_tensor_no_candidate_when_priorities_equal():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("same").req({"cpu": "4"}).priority(10).node("n0").obj()]
    pod = make_pod("p").req({"cpu": "2"}).priority(10).obj()
    assert find_candidate_tensor(nodes, bound, pod) is None


def test_tensor_pdb_ordering_matches_exact():
    """PDB-protected victims are evicted last; both paths must agree on the
    victim set and violation count."""
    nodes = [make_node("n0").capacity({"cpu": "6"}).obj()]
    bound = [
        make_pod("guarded").req({"cpu": "2"}).priority(1).node("n0")
        .label("app", "db").obj(),
        make_pod("free").req({"cpu": "2"}).priority(1).node("n0").obj(),
        make_pod("high").req({"cpu": "2"}).priority(50).node("n0").obj(),
    ]
    pdbs = [{"metadata": {"name": "db-pdb", "namespace": "default"},
             "spec": {"minAvailable": 1,
                      "selector": {"matchLabels": {"app": "db"}}}}]
    pod = make_pod("pre").req({"cpu": "2"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod, pdbs=pdbs)
    tensor = find_candidate_tensor(nodes, bound, pod, pdbs=pdbs)
    assert exact is not None
    assert [v.metadata.name for v in exact.victims] == ["free"]
    assert _same(exact, tensor)


def test_tensor_relational_failure_falls_back_to_exact():
    """Pod blocked by anti-affinity, not resources: a node fits with zero
    evictions resource-wise, so the tensor path must defer to the exact
    scan (which knows evicting the anti-affine pod helps)."""
    nodes = [make_node("n0").capacity({"cpu": "8"})
             .label("zone", "z0").obj()]
    blocker = (make_pod("blocker").req({"cpu": "1"}).priority(1)
               .node("n0").label("app", "x").obj())
    pod = (make_pod("anti").req({"cpu": "1"}).priority(100)
           .pod_anti_affinity("zone", {"app": "x"}).obj())
    exact = find_candidate(nodes, [blocker], pod)
    tensor = find_candidate_tensor(nodes, [blocker], pod)
    assert _same(exact, tensor)
    if exact is not None:  # eviction of the blocker enables placement
        assert [v.metadata.name for v in exact.victims] == ["blocker"]


def test_tensor_prefers_fewest_and_lowest_priority_victims():
    """pickOneNode: node needing one low-priority victim beats a node
    needing two or a higher-priority one."""
    nodes = [make_node("a").capacity({"cpu": "4"}).obj(),
             make_node("b").capacity({"cpu": "4"}).obj()]
    bound = [
        # node a: one high-priority victim frees enough
        make_pod("a-big").req({"cpu": "4"}).priority(50).node("a").obj(),
        # node b: one LOW-priority victim frees enough -> preferred
        make_pod("b-small").req({"cpu": "4"}).priority(2).node("b").obj(),
    ]
    pod = make_pod("pre").req({"cpu": "3"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert exact is not None and exact.node_name == "b"
    assert _same(exact, tensor)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tensor_randomized_parity(seed):
    rng = random.Random(seed)
    nodes = [make_node(f"n{i}").capacity(
        {"cpu": str(rng.choice([2, 4, 8])),
         "memory": f"{rng.choice([4, 8])}Gi"}).obj() for i in range(8)]
    bound = []
    for i in range(8):
        for j in range(rng.randint(0, 4)):
            bound.append(
                make_pod(f"v{i}-{j}")
                .req({"cpu": str(rng.choice([1, 2])),
                      "memory": f"{rng.choice([1, 2])}Gi"})
                .priority(rng.randint(0, 20)).node(f"n{i}").obj())
    pod = (make_pod("pre")
           .req({"cpu": str(rng.choice([1, 2, 3])),
                 "memory": "2Gi"}).priority(15).obj())
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert _same(exact, tensor)
