"""Tensorized preemption (ops/preemption.py) vs the exact serial scan.

The device dry-run must agree with ``find_candidate`` (the
SelectVictimsOnNode/pickOneNodeForPreemption parity reference,
``pkg/scheduler/framework/preemption/preemption.go``) on resource-driven
scenarios, and must fall back to the exact scan when the failure is
relational.
"""

import random

import pytest

from kubernetes_tpu.sched.preemption import (
    find_candidate,
    find_candidate_tensor,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def _same(a, b):
    if a is None or b is None:
        return a is b
    return (a.node_name == b.node_name
            and [v.metadata.name for v in a.victims]
            == [v.metadata.name for v in b.victims]
            and a.num_pdb_violations == b.num_pdb_violations)


def test_tensor_matches_exact_basic():
    nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(4)]
    bound = []
    for i in range(4):  # fill every node with low-priority pods
        for j in range(2):
            bound.append(make_pod(f"v{i}-{j}").req({"cpu": "2"})
                         .priority(j + 1).node(f"n{i}").obj())
    pod = make_pod("hi").req({"cpu": "2"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert exact is not None and _same(exact, tensor)


def test_tensor_no_candidate_when_priorities_equal():
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("same").req({"cpu": "4"}).priority(10).node("n0").obj()]
    pod = make_pod("p").req({"cpu": "2"}).priority(10).obj()
    assert find_candidate_tensor(nodes, bound, pod) is None


def test_tensor_pdb_ordering_matches_exact():
    """PDB-protected victims are evicted last; both paths must agree on the
    victim set and violation count."""
    nodes = [make_node("n0").capacity({"cpu": "6"}).obj()]
    bound = [
        make_pod("guarded").req({"cpu": "2"}).priority(1).node("n0")
        .label("app", "db").obj(),
        make_pod("free").req({"cpu": "2"}).priority(1).node("n0").obj(),
        make_pod("high").req({"cpu": "2"}).priority(50).node("n0").obj(),
    ]
    pdbs = [{"metadata": {"name": "db-pdb", "namespace": "default"},
             "spec": {"minAvailable": 1,
                      "selector": {"matchLabels": {"app": "db"}}}}]
    pod = make_pod("pre").req({"cpu": "2"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod, pdbs=pdbs)
    tensor = find_candidate_tensor(nodes, bound, pod, pdbs=pdbs)
    assert exact is not None
    assert [v.metadata.name for v in exact.victims] == ["free"]
    assert _same(exact, tensor)


def test_tensor_relational_failure_falls_back_to_exact():
    """Pod blocked by anti-affinity, not resources: a node fits with zero
    evictions resource-wise, so the tensor path must defer to the exact
    scan (which knows evicting the anti-affine pod helps)."""
    nodes = [make_node("n0").capacity({"cpu": "8"})
             .label("zone", "z0").obj()]
    blocker = (make_pod("blocker").req({"cpu": "1"}).priority(1)
               .node("n0").label("app", "x").obj())
    pod = (make_pod("anti").req({"cpu": "1"}).priority(100)
           .pod_anti_affinity("zone", {"app": "x"}).obj())
    exact = find_candidate(nodes, [blocker], pod)
    tensor = find_candidate_tensor(nodes, [blocker], pod)
    assert _same(exact, tensor)
    if exact is not None:  # eviction of the blocker enables placement
        assert [v.metadata.name for v in exact.victims] == ["blocker"]


def test_tensor_prefers_fewest_and_lowest_priority_victims():
    """pickOneNode: node needing one low-priority victim beats a node
    needing two or a higher-priority one."""
    nodes = [make_node("a").capacity({"cpu": "4"}).obj(),
             make_node("b").capacity({"cpu": "4"}).obj()]
    bound = [
        # node a: one high-priority victim frees enough
        make_pod("a-big").req({"cpu": "4"}).priority(50).node("a").obj(),
        # node b: one LOW-priority victim frees enough -> preferred
        make_pod("b-small").req({"cpu": "4"}).priority(2).node("b").obj(),
    ]
    pod = make_pod("pre").req({"cpu": "3"}).priority(100).obj()
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert exact is not None and exact.node_name == "b"
    assert _same(exact, tensor)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tensor_randomized_parity(seed):
    rng = random.Random(seed)
    nodes = [make_node(f"n{i}").capacity(
        {"cpu": str(rng.choice([2, 4, 8])),
         "memory": f"{rng.choice([4, 8])}Gi"}).obj() for i in range(8)]
    bound = []
    for i in range(8):
        for j in range(rng.randint(0, 4)):
            bound.append(
                make_pod(f"v{i}-{j}")
                .req({"cpu": str(rng.choice([1, 2])),
                      "memory": f"{rng.choice([1, 2])}Gi"})
                .priority(rng.randint(0, 20)).node(f"n{i}").obj())
    pod = (make_pod("pre")
           .req({"cpu": str(rng.choice([1, 2, 3])),
                 "memory": "2Gi"}).priority(15).obj())
    exact = find_candidate(nodes, bound, pod)
    tensor = find_candidate_tensor(nodes, bound, pod)
    assert _same(exact, tensor)


# ---- wave batching (ops/preemption.py _wave_scan + preempt_wave) ---------

def _serial_wave(nodes, bound, preemptors, pdbs=None):
    """Ground truth: the serial failure path — one exact find_candidate per
    preemptor, committing evictions + the nominee's reservation between
    calls (schedule_one.go evict-then-retry semantics)."""
    import dataclasses
    from kubernetes_tpu.sched.preemption import find_candidate
    live = list(bound)
    out = []
    for pod in preemptors:
        res = find_candidate(nodes, live, pod, pdbs=pdbs)
        if res is not None:
            gone = {v.metadata.uid for v in res.victims}
            live = [p for p in live if p.metadata.uid not in gone]
            live.append(dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec,
                                              node_name=res.node_name)))
        out.append(res)
    return out


def _same_wave(a, b):
    return len(a) == len(b) and all(_same(x, y) for x, y in zip(a, b))


def test_wave_matches_serial_basic():
    from kubernetes_tpu.sched.preemption import preempt_wave
    nodes = [make_node(f"n{i}").capacity({"cpu": "8", "pods": "16"}).obj()
             for i in range(6)]
    bound = []
    for i in range(6):
        for j in range(2):
            bound.append(make_pod(f"v{i}-{j}").req({"cpu": "4"})
                         .priority(1 + (i + j) % 3).node(f"n{i}").obj())
    preemptors = [make_pod(f"hi{k}").req({"cpu": "6"}).priority(100).obj()
                  for k in range(4)]
    wave = preempt_wave(nodes, bound, preemptors)
    serial = _serial_wave(nodes, bound, preemptors)
    assert sum(r is not None for r in serial) == 4
    assert _same_wave(wave, serial)


def test_wave_sequential_commit_prevents_double_spend():
    """Two preemptors, one node with one evictable victim: only the first
    may win; the second must see the nominee's reservation and fail."""
    from kubernetes_tpu.sched.preemption import preempt_wave
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("low").req({"cpu": "4"}).priority(1).node("n0").obj()]
    preemptors = [make_pod("a").req({"cpu": "4"}).priority(100).obj(),
                  make_pod("b").req({"cpu": "4"}).priority(100).obj()]
    wave = preempt_wave(nodes, bound, preemptors)
    assert wave[0] is not None and wave[0].node_name == "n0"
    assert [v.metadata.name for v in wave[0].victims] == ["low"]
    assert wave[1] is None


def test_wave_mixed_priorities_respects_cutoff():
    """A preemptor only evicts strictly lower priorities; the wave's
    per-preemptor device masking must agree with serial."""
    from kubernetes_tpu.sched.preemption import preempt_wave
    nodes = [make_node("n0").capacity({"cpu": "4"}).obj(),
             make_node("n1").capacity({"cpu": "4"}).obj()]
    bound = [make_pod("p10").req({"cpu": "4"}).priority(10).node("n0").obj(),
             make_pod("p40").req({"cpu": "4"}).priority(40).node("n1").obj()]
    preemptors = [make_pod("mid").req({"cpu": "2"}).priority(20).obj(),
                  make_pod("top").req({"cpu": "2"}).priority(99).obj()]
    wave = preempt_wave(nodes, bound, preemptors)
    serial = _serial_wave(nodes, bound, preemptors)
    assert _same_wave(wave, serial)
    assert wave[0] is not None and wave[0].node_name == "n0"


def test_wave_pdb_budgets_thread_through():
    from kubernetes_tpu.sched.preemption import preempt_wave
    nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj()
             for i in range(3)]
    bound = [make_pod(f"db{i}").req({"cpu": "4"}).priority(1)
             .node(f"n{i}").label("app", "db").obj() for i in range(3)]
    # minAvailable 2 of 3: only ONE disruption allowed across the wave
    pdbs = [{"metadata": {"name": "db-pdb", "namespace": "default"},
             "spec": {"minAvailable": 2,
                      "selector": {"matchLabels": {"app": "db"}}}}]
    preemptors = [make_pod(f"hi{k}").req({"cpu": "4"}).priority(100).obj()
                  for k in range(2)]
    wave = preempt_wave(nodes, bound, preemptors, pdbs=pdbs)
    serial = _serial_wave(nodes, bound, preemptors, pdbs=pdbs)
    assert _same_wave(wave, serial)
    assert wave[0] is not None and wave[0].num_pdb_violations == 0
    # the second preemption must register as a budget violation
    assert wave[1] is not None and wave[1].num_pdb_violations == 1


@pytest.mark.parametrize("seed", [3, 4, 5, 6])
def test_wave_randomized_parity(seed):
    from kubernetes_tpu.sched.preemption import preempt_wave
    rng = random.Random(seed)
    nodes = [make_node(f"n{i}").capacity(
        {"cpu": str(rng.choice([2, 4, 8])),
         "memory": f"{rng.choice([4, 8])}Gi"}).obj() for i in range(8)]
    bound = []
    for i in range(8):
        for j in range(rng.randint(0, 4)):
            bound.append(
                make_pod(f"v{i}-{j}")
                .req({"cpu": str(rng.choice([1, 2])),
                      "memory": f"{rng.choice([1, 2])}Gi"})
                .priority(rng.randint(0, 20)).node(f"n{i}").obj())
    preemptors = [
        make_pod(f"pre{k}")
        .req({"cpu": str(rng.choice([1, 2, 3])), "memory": "2Gi"})
        .priority(rng.randint(10, 30)).obj() for k in range(5)]
    wave = preempt_wave(nodes, bound, preemptors)
    serial = _serial_wave(nodes, bound, preemptors)
    assert _same_wave(wave, serial)


def test_wave_phantom_commit_does_not_blind_later_preemptors():
    """Regression: preemptor A's device proposal commits victims in the
    scan carry, but host verification rejects it (anti-affinity) and finds
    nothing; preemptor B must NOT inherit the device's phantom eviction as
    a trusted 'no' — serial ground truth says B can preempt."""
    from kubernetes_tpu.sched.preemption import preempt_wave
    nodes = [make_node("n0").capacity({"cpu": "8"}).label("zone", "z").obj()]
    bound = [
        make_pod("v").req({"cpu": "6"}).priority(1).node("n0").obj(),
        make_pod("h").req({"cpu": "1"}).priority(200).node("n0")
        .label("team", "x").obj(),
    ]
    a = (make_pod("a").req({"cpu": "6"}).priority(100)
         .pod_anti_affinity("zone", {"team": "x"}).obj())
    b = make_pod("b").req({"cpu": "6"}).priority(100).obj()
    wave = preempt_wave(nodes, bound, [a, b])
    serial = _serial_wave(nodes, bound, [a, b])
    assert _same_wave(wave, serial)
    assert wave[0] is None
    assert wave[1] is not None and wave[1].node_name == "n0"
    assert [v.metadata.name for v in wave[1].victims] == ["v"]
