"""Authn/authz/audit chain (store/auth.py + apiserver filter order).

Reference: DefaultBuildHandlerChain (apiserver/pkg/server/config.go) —
authenticate (401) -> audit -> impersonation -> APF -> authorize (403);
RBAC semantics from plugin/pkg/auth/authorizer/rbac.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.auth import (
    AuditLog,
    RBACAuthorizer,
    TokenAuthenticator,
    UserInfo,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture()
def server():
    auth = (TokenAuthenticator(allow_anonymous=False)
            .add("admin-token", ("admin", ("system:masters",)))
            .add("sched-token", ("system:kube-scheduler", ()))
            .add("dev-token", ("dev", ("app-team",))))
    s = APIServer().enable_auth(authenticator=auth).start()
    yield s
    s.stop()


def admin(server):
    return HTTPClient(server.url, token="admin-token")


def test_unauthenticated_401(server):
    c = HTTPClient(server.url)
    with pytest.raises(ApiError) as ei:
        c.pods().list()
    assert ei.value.code == 401


def test_bad_token_401(server):
    c = HTTPClient(server.url, token="nope")
    with pytest.raises(ApiError) as ei:
        c.pods().list()
    assert ei.value.code == 401


def test_masters_group_bypasses_authz(server):
    c = admin(server)
    c.nodes().create(make_node("n1").capacity({"cpu": "4"}).obj().to_dict())
    assert [n["metadata"]["name"] for n in c.nodes().list()] == ["n1"]


def test_unbound_user_403(server):
    c = HTTPClient(server.url, token="dev-token")
    with pytest.raises(ApiError) as ei:
        c.pods().list()
    assert ei.value.code == 403


def test_role_binding_scopes_to_namespace(server):
    a = admin(server)
    a.resource("roles", "team-a").create({
        "apiVersion": "rbac/v1", "kind": "Role",
        "metadata": {"name": "pod-editor", "namespace": "team-a"},
        "rules": [{"verbs": ["get", "list", "create", "delete"],
                   "resources": ["pods"]}]})
    a.resource("rolebindings", "team-a").create({
        "apiVersion": "rbac/v1", "kind": "RoleBinding",
        "metadata": {"name": "dev-pods", "namespace": "team-a"},
        "subjects": [{"kind": "Group", "name": "app-team"}],
        "roleRef": {"kind": "Role", "name": "pod-editor"}})
    dev = HTTPClient(server.url, token="dev-token")
    pod = make_pod("p1").obj().to_dict()
    pod["metadata"]["namespace"] = "team-a"
    dev.pods("team-a").create(pod)
    assert dev.pods("team-a").list()
    # same verb in another namespace: denied
    with pytest.raises(ApiError) as ei:
        dev.pods("team-b").list()
    assert ei.value.code == 403
    # unlisted resource: denied
    with pytest.raises(ApiError) as ei:
        dev.nodes().list()
    assert ei.value.code == 403
    # unlisted verb (update): denied
    with pytest.raises(ApiError) as ei:
        dev.pods("team-a").update(pod)
    assert ei.value.code == 403


def test_scheduler_bootstrap_identity(server):
    """The seeded system:kube-scheduler ClusterRole admits exactly the
    scheduler's API surface: read pods/nodes, create bindings, not much
    else."""
    sched = HTTPClient(server.url, token="sched-token")
    a = admin(server)
    a.nodes().create(make_node("n1").capacity(
        {"cpu": "4", "pods": "10"}).obj().to_dict())
    a.pods().create(make_pod("w").req({"cpu": "1"}).obj().to_dict())
    assert sched.pods().list()
    assert sched.nodes().list()
    sched.pods().bind("w", "n1")  # pods/binding create allowed
    assert a.pods().get("w")["spec"]["nodeName"] == "n1"
    with pytest.raises(ApiError) as ei:  # cannot create plain pods
        sched.pods().create(make_pod("x").obj().to_dict())
    assert ei.value.code == 403
    with pytest.raises(ApiError) as ei:  # cannot delete nodes
        sched.nodes().delete("n1")
    assert ei.value.code == 403


def test_impersonation(server):
    a = HTTPClient(server.url, token="admin-token", impersonate="dev")
    # admin impersonating unbound dev -> dev's (empty) permissions apply
    with pytest.raises(ApiError) as ei:
        a.pods().list()
    assert ei.value.code == 403
    # non-privileged user may not impersonate
    d = HTTPClient(server.url, token="dev-token", impersonate="admin")
    with pytest.raises(ApiError) as ei:
        d.pods().list()
    assert ei.value.code == 403


def test_audit_log_records(server):
    c = admin(server)
    c.nodes().create(make_node("n1").obj().to_dict())
    with pytest.raises(ApiError):
        HTTPClient(server.url).pods().list()
    # audit logs at ResponseComplete (after the response bytes go out, like
    # upstream); give the handler thread a beat to finish the finally block
    deadline = time.time() + 2.0
    while time.time() < deadline:
        evs = list(server.audit.events)
        if (any(e["user"] == "admin" and e["verb"] == "POST"
                and e["code"] == 201 for e in evs)
                and any(e["code"] == 401 for e in evs)):
            break
        time.sleep(0.01)
    assert any(e["user"] == "admin" and e["verb"] == "POST"
               and e["code"] == 201 for e in evs)
    assert any(e["code"] == 401 for e in evs)


def test_anonymous_enabled_still_authorized():
    s = APIServer().enable_auth(
        authenticator=TokenAuthenticator(allow_anonymous=True)).start()
    try:
        c = HTTPClient(s.url)
        with pytest.raises(ApiError) as ei:  # anonymous has no bindings
            c.pods().list()
        assert ei.value.code == 403
    finally:
        s.stop()


def test_rbac_authorizer_unit():
    from kubernetes_tpu.store.store import ObjectStore
    st = ObjectStore()
    st.create("ClusterRole", {
        "kind": "ClusterRole", "metadata": {"name": "reader"},
        "rules": [{"verbs": ["get", "list", "watch"],
                   "resources": ["pods", "nodes"]}]})
    st.create("ClusterRoleBinding", {
        "kind": "ClusterRoleBinding", "metadata": {"name": "readers"},
        "subjects": [{"kind": "Group", "name": "view"}],
        "roleRef": {"kind": "ClusterRole", "name": "reader"}})
    az = RBACAuthorizer(st)
    viewer = UserInfo("alice", ("view",))
    assert az.authorize(viewer, "list", "pods", "any-ns", "")
    assert az.authorize(viewer, "get", "nodes", "", "n1")
    assert not az.authorize(viewer, "create", "pods", "ns", "")
    assert not az.authorize(viewer, "list", "secrets", "ns", "")
    nobody = UserInfo("bob", ())
    assert not az.authorize(nobody, "list", "pods", "ns", "")
