"""Attach/detach + node IPAM controllers.

Reference: ``pkg/controller/volume/attachdetach/attach_detach_controller.go``
(VolumeAttachment reconciliation from pods' volumes) and
``pkg/controller/nodeipam/ipam/range_allocator.go`` (per-node podCIDR
carving of the cluster CIDR).
"""

import time

import pytest

from kubernetes_tpu.client.clientset import DirectClient
from kubernetes_tpu.client.informer import InformerFactory
from kubernetes_tpu.controllers.attachdetach import (AttachDetachController,
                                                     attachment_name)
from kubernetes_tpu.controllers.nodeipam import CidrSet, NodeIpamController
from kubernetes_tpu.store.store import ObjectStore
from kubernetes_tpu.testing.wrappers import make_node, make_pod


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return fn()


@pytest.fixture
def client():
    return DirectClient(ObjectStore())


def run_controller(client, ctrl):
    factory = InformerFactory(client)
    ctrl.register(factory)
    factory.start_all()
    assert factory.wait_for_cache_sync(5.0)
    ctrl.start()
    return ctrl, factory


def stop(ctrl, factory):
    ctrl.stop()
    factory.stop_all()


def _seed_csi_volume(client, pv="pv-1", pvc="data", ns="default"):
    client.resource("persistentvolumes", None).create({
        "kind": "PersistentVolume", "metadata": {"name": pv},
        "spec": {"capacity": {"storage": "10Gi"},
                 "csi": {"driver": "ebs.csi.example.com",
                         "volumeHandle": "vol-123"}}})
    client.resource("persistentvolumeclaims", ns).create({
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": pvc, "namespace": ns},
        "spec": {"volumeName": pv,
                 "resources": {"requests": {"storage": "10Gi"}}}})


# ------------------------------------------------------------- attachdetach

def test_attach_created_for_scheduled_pod_and_detached_on_delete(client):
    client.nodes().create(make_node("n1").obj().to_dict())
    _seed_csi_volume(client)
    ctrl, factory = run_controller(client, AttachDetachController(client))
    try:
        pod = make_pod("app").obj().to_dict()
        pod["spec"]["nodeName"] = "n1"
        pod["spec"]["volumes"] = [
            {"name": "data", "persistentVolumeClaim": {"claimName": "data"}}]
        client.pods("default").create(pod)
        vas = client.resource("volumeattachments", None)

        def attached():
            items = vas.list()
            return [v for v in items
                    if (v.get("status") or {}).get("attached")]
        assert wait_until(lambda: len(attached()) == 1), vas.list()
        va = attached()[0]
        assert va["spec"]["nodeName"] == "n1"
        assert va["spec"]["attacher"] == "ebs.csi.example.com"
        assert va["spec"]["source"]["persistentVolumeName"] == "pv-1"
        assert va["metadata"]["name"] == attachment_name("pv-1", "n1")
        # node status mirrors the attachment
        assert wait_until(lambda: (client.nodes().get("n1").get("status") or
                                   {}).get("volumesAttached"))
        # pod deleted -> attachment detached
        client.pods("default").delete("app")
        assert wait_until(lambda: not attached()), vas.list()
    finally:
        stop(ctrl, factory)


def test_no_attachment_for_non_csi_pv(client):
    client.nodes().create(make_node("n1").obj().to_dict())
    client.resource("persistentvolumes", None).create({
        "kind": "PersistentVolume", "metadata": {"name": "local-pv"},
        "spec": {"capacity": {"storage": "10Gi"},
                 "hostPath": {"path": "/data"}}})
    client.resource("persistentvolumeclaims", "default").create({
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": "data", "namespace": "default"},
        "spec": {"volumeName": "local-pv",
                 "resources": {"requests": {"storage": "10Gi"}}}})
    ctrl, factory = run_controller(client, AttachDetachController(client))
    try:
        pod = make_pod("app").obj().to_dict()
        pod["spec"]["nodeName"] = "n1"
        pod["spec"]["volumes"] = [
            {"name": "data", "persistentVolumeClaim": {"claimName": "data"}}]
        client.pods("default").create(pod)
        time.sleep(0.3)
        assert client.resource("volumeattachments", None).list() == []
    finally:
        stop(ctrl, factory)


# ----------------------------------------------------------------- nodeipam

def test_cidrset_carves_and_recycles():
    cs = CidrSet("10.244.0.0/16", 24)
    assert cs.max == 256
    a = cs.allocate()
    b = cs.allocate()
    assert a == "10.244.0.0/24" and b == "10.244.1.0/24"
    cs.release(a)
    cs.occupy("10.244.5.0/24")
    # drain the whole space: the released subnet must come back, the
    # occupied one must not, and nothing is handed out twice
    got = [cs.allocate() for _ in range(cs.max - 2)]
    assert len(set(got)) == len(got)
    assert "10.244.5.0/24" not in got
    assert "10.244.0.0/24" in got  # released subnet recycled
    with pytest.raises(RuntimeError):
        cs.allocate()  # exhausted


def test_nodeipam_assigns_unique_cidrs(client):
    for i in range(3):
        client.nodes().create(make_node(f"n{i}").obj().to_dict())
    ctrl, factory = run_controller(
        client, NodeIpamController(client, cluster_cidr="10.244.0.0/16"))
    try:
        def cidrs():
            return [(n.get("spec") or {}).get("podCIDR")
                    for n in client.nodes().list()]
        assert wait_until(lambda: all(cidrs())), cidrs()
        got = cidrs()
        assert len(set(got)) == 3
        assert all(c.endswith("/24") and c.startswith("10.244.")
                   for c in got)
        # a late node gets a CIDR too, never a duplicate
        client.nodes().create(make_node("late").obj().to_dict())
        assert wait_until(
            lambda: (client.nodes().get("late").get("spec") or {})
            .get("podCIDR")), client.nodes().get("late")
        assert len(set(cidrs())) == 4
    finally:
        stop(ctrl, factory)


def test_nodeipam_reserves_existing_cidrs_on_restart(client):
    n = make_node("seeded").obj().to_dict()
    n["spec"]["podCIDR"] = "10.244.0.0/24"
    client.nodes().create(n)
    client.nodes().create(make_node("fresh").obj().to_dict())
    ctrl, factory = run_controller(
        client, NodeIpamController(client, cluster_cidr="10.244.0.0/16"))
    try:
        assert wait_until(
            lambda: (client.nodes().get("fresh").get("spec") or {})
            .get("podCIDR"))
        fresh = client.nodes().get("fresh")["spec"]["podCIDR"]
        assert fresh != "10.244.0.0/24"  # seeded subnet stayed reserved
        # seeded node keeps its original allocation
        assert client.nodes().get("seeded")["spec"]["podCIDR"] \
            == "10.244.0.0/24"
    finally:
        stop(ctrl, factory)


def test_nodeipam_releases_on_node_delete(client):
    cs_ctrl = NodeIpamController(client, cluster_cidr="10.0.0.0/30",
                                 node_mask_size=31)  # only 2 subnets
    client.nodes().create(make_node("a").obj().to_dict())
    client.nodes().create(make_node("b").obj().to_dict())
    ctrl, factory = run_controller(client, cs_ctrl)
    try:
        assert wait_until(lambda: all(
            (n.get("spec") or {}).get("podCIDR")
            for n in client.nodes().list()))
        client.nodes().delete("a")
        time.sleep(0.2)
        client.nodes().create(make_node("c").obj().to_dict())
        assert wait_until(
            lambda: (client.nodes().get("c").get("spec") or {})
            .get("podCIDR")), "released subnet was not reusable"
    finally:
        stop(ctrl, factory)


# ----------------------------------------- ephemeral / service-lb / route

def test_ephemeral_volume_creates_owned_pvc(client):
    from kubernetes_tpu.controllers.ephemeral import EphemeralVolumeController
    ctrl, factory = run_controller(client, EphemeralVolumeController(client))
    try:
        pod = make_pod("db").obj().to_dict()
        pod["spec"]["volumes"] = [{
            "name": "scratch",
            "ephemeral": {"volumeClaimTemplate": {
                "metadata": {"labels": {"app": "db"}},
                "spec": {"resources": {"requests": {"storage": "1Gi"}},
                         "storageClassName": "fast"}}}}]
        created = client.pods("default").create(pod)
        pvcs = client.resource("persistentvolumeclaims", "default")
        assert wait_until(lambda: any(
            (p.get("metadata") or {}).get("name") == "db-scratch"
            for p in pvcs.list()))
        claim = pvcs.get("db-scratch")
        refs = claim["metadata"]["ownerReferences"]
        assert refs[0]["kind"] == "Pod"
        assert refs[0]["uid"] == created["metadata"]["uid"]
        assert claim["spec"]["storageClassName"] == "fast"
        assert claim["metadata"]["labels"] == {"app": "db"}
    finally:
        stop(ctrl, factory)


def test_ephemeral_volume_refuses_foreign_claim(client):
    from kubernetes_tpu.controllers.ephemeral import EphemeralVolumeController
    pvcs = client.resource("persistentvolumeclaims", "default")
    pvcs.create({"kind": "PersistentVolumeClaim",
                 "metadata": {"name": "db-scratch"},
                 "spec": {"resources": {"requests": {"storage": "1Gi"}}}})
    ctrl, factory = run_controller(client, EphemeralVolumeController(client))
    try:
        pod = make_pod("db").obj().to_dict()
        pod["spec"]["volumes"] = [{
            "name": "scratch",
            "ephemeral": {"volumeClaimTemplate": {
                "spec": {"resources": {"requests": {"storage": "1Gi"}}}}}}]
        client.pods("default").create(pod)
        time.sleep(0.3)
        # the foreign claim is NOT adopted: no ownerReferences grafted
        assert "ownerReferences" not in pvcs.get("db-scratch")["metadata"]
    finally:
        stop(ctrl, factory)


def test_service_lb_assigns_and_releases_ingress(client):
    from kubernetes_tpu.controllers.servicelb import ServiceLBController
    ctrl, factory = run_controller(client, ServiceLBController(client))
    try:
        svcs = client.resource("services", "default")
        svcs.create({"kind": "Service", "metadata": {"name": "edge"},
                     "spec": {"type": "LoadBalancer",
                              "ports": [{"port": 443}]}})
        def ingress():
            return ((svcs.get("edge").get("status") or {})
                    .get("loadBalancer") or {}).get("ingress")
        assert wait_until(lambda: ingress()), svcs.get("edge")
        ip = ingress()[0]["ip"]
        assert ip.startswith("203.0.113.")
        # a second LB service gets a DIFFERENT address
        svcs.create({"kind": "Service", "metadata": {"name": "edge2"},
                     "spec": {"type": "LoadBalancer",
                              "ports": [{"port": 80}]}})
        assert wait_until(lambda: ((svcs.get("edge2").get("status") or {})
                                   .get("loadBalancer") or {}).get("ingress"))
        ip2 = svcs.get("edge2")["status"]["loadBalancer"]["ingress"][0]["ip"]
        assert ip2 != ip
        # type change away tears the LB down
        svc = svcs.get("edge")
        svc["spec"]["type"] = "ClusterIP"
        svcs.update(svc)
        assert wait_until(lambda: not ingress()), svcs.get("edge")
    finally:
        stop(ctrl, factory)


def test_route_controller_clears_network_unavailable(client):
    from kubernetes_tpu.controllers.route import RouteController
    n = make_node("rn-1").obj().to_dict()
    n["spec"]["podCIDR"] = "10.244.7.0/24"
    client.nodes().create(n)
    ctrl, factory = run_controller(client, RouteController(client))
    try:
        def net_ok():
            conds = (client.nodes().get("rn-1").get("status") or {}) \
                .get("conditions") or []
            return any(c.get("type") == "NetworkUnavailable"
                       and c.get("status") == "False" for c in conds)
        assert wait_until(net_ok)
        assert ctrl.routes == {"rn-1": "10.244.7.0/24"}
        client.nodes().delete("rn-1")
        assert wait_until(lambda: ctrl.routes == {})
    finally:
        stop(ctrl, factory)
