"""CRI gRPC process boundary + device/memory/topology managers.

Reference: ``staging/src/k8s.io/cri-api/.../api.proto`` (the kubelet <->
containerd seam) and ``pkg/kubelet/cm/{devicemanager,memorymanager,
topologymanager}``.
"""

import time

import pytest

from kubernetes_tpu.kubelet.cri import CRIServer, RemoteRuntime
from kubernetes_tpu.kubelet.managers import (
    Device,
    DeviceManager,
    MemoryManager,
    POLICY_BEST_EFFORT,
    POLICY_SINGLE_NUMA,
    TopologyManager,
)
from kubernetes_tpu.kubelet.runtime import EXITED, RUNNING, FakeRuntime


@pytest.fixture()
def cri():
    backend = FakeRuntime()
    server = CRIServer(backend).start()
    remote = RemoteRuntime(server.address)
    yield backend, server, remote
    remote.close()
    server.stop()


def _gpod(uid, name="p", cpu="1", memory="512Mi", extra=None):
    req = {"cpu": cpu, "memory": memory, **(extra or {})}
    return {"kind": "Pod", "metadata": {"uid": uid, "name": name,
                                        "namespace": "default"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": dict(req), "limits": dict(req)}}]}}


# ------------------------------------------------------------------ CRI seam

def test_cri_container_lifecycle_over_grpc(cri):
    backend, server, remote = cri
    sb = remote.run_pod_sandbox("u1", "p1", "default")
    assert sb.pod_uid == "u1" and sb.ip
    remote.create_container("u1", "c0", image="nginx:1")
    remote.start_container("u1", "c0")
    got = remote.get_sandbox("u1")
    assert got.containers["c0"].state == RUNNING
    # the image service recorded the kubelet's pull
    assert "nginx:1" in server.images
    remote.stop_container("u1", "c0", exit_code=3)
    assert remote.get_sandbox("u1").containers["c0"].state == EXITED
    assert remote.get_sandbox("u1").containers["c0"].exit_code == 3
    # state lives in the BACKEND process object, not the client
    assert backend.get_sandbox("u1").containers["c0"].exit_code == 3
    remote.stop_pod_sandbox("u1")
    assert remote.get_sandbox("u1") is None


def test_cri_probe_via_exec_sync(cri):
    backend, _server, remote = cri
    remote.run_pod_sandbox("u2", "p2", "default")
    remote.create_container("u2", "c", image="x")
    remote.start_container("u2", "c")
    assert remote.probe("u2", "c") is True
    backend.set_health("u2", "c", False)
    assert remote.probe("u2", "c") is False


def test_kubelet_runs_over_remote_runtime(cri):
    """The full kubelet sync loop drives containers across the gRPC seam."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.store.store import ObjectStore
    _backend, _server, remote = cri
    client = DirectClient(ObjectStore())
    kl = Kubelet(client, "node-cri", runtime=remote)
    kl.start()
    try:
        client.pods("default").create(
            {"kind": "Pod", "metadata": {"name": "remote-pod"},
             "spec": {"nodeName": "node-cri",
                      "containers": [{"name": "app", "image": "img:1"}]}})
        deadline = time.time() + 15.0
        phase = None
        while time.time() < deadline:
            p = client.pods("default").get("remote-pod")
            phase = (p.get("status") or {}).get("phase")
            if phase == "Running":
                break
            time.sleep(0.05)
        assert phase == "Running", phase
    finally:
        kl.stop()


# ------------------------------------------------------------ device manager

def test_device_manager_allocates_and_releases():
    dm = DeviceManager()
    dm.register_plugin("example.com/fpga",
                       [Device("f0", 0), Device("f1", 0), Device("f2", 1)])
    assert dm.capacity() == {"example.com/fpga": 3}
    pod = _gpod("u1", extra={"example.com/fpga": "2"})
    got = dm.allocate(pod)
    assert len(got["example.com/fpga"]) == 2
    with pytest.raises(RuntimeError):
        dm.allocate(_gpod("u2", extra={"example.com/fpga": "2"}))
    dm.release("u1")
    assert len(dm.allocate(_gpod("u2", extra={"example.com/fpga": "2"}))
               ["example.com/fpga"]) == 2


def test_device_hints_prefer_single_numa():
    dm = DeviceManager()
    dm.register_plugin("example.com/fpga",
                       [Device("a", 0), Device("b", 1), Device("c", 1)])
    h = dm.hints(_gpod("u", extra={"example.com/fpga": "2"}))
    assert h.numa_affinity == frozenset({1}) and h.preferred


# ------------------------------------------------------------ memory manager

def test_memory_manager_guaranteed_reservation():
    mm = MemoryManager([1024, 1024])
    plan = mm.allocate(_gpod("u1", memory="768Mi"))
    assert sum(plan.values()) == 768
    assert len(plan) == 1  # fits one NUMA node
    # second big pod must span nodes
    plan2 = mm.allocate(_gpod("u2", memory="1Gi"))
    assert sum(plan2.values()) == 1024
    with pytest.raises(RuntimeError):
        mm.allocate(_gpod("u3", memory="512Mi"))
    mm.release("u1")
    assert mm.allocate(_gpod("u3", memory="512Mi"))


def test_memory_manager_ignores_non_guaranteed():
    mm = MemoryManager([256])
    pod = _gpod("u", memory="10Gi")
    del pod["spec"]["containers"][0]["resources"]["limits"]  # burstable
    assert mm.allocate(pod) is None


# ---------------------------------------------------------- topology manager

def _managers(policy):
    dm = DeviceManager()
    dm.register_plugin("example.com/fpga",
                       [Device("a", 0), Device("b", 1)])
    mm = MemoryManager([512, 512])
    tm = TopologyManager(policy=policy, num_numa=2)
    tm.add_provider(dm)
    tm.add_provider(mm)
    return dm, mm, tm


def test_topology_single_numa_rejects_cross_node():
    dm, mm, tm = _managers(POLICY_SINGLE_NUMA)
    # demands 2 fpgas which live on DIFFERENT numa nodes: no single-NUMA fit
    ok, reason, _ = tm.admit(_gpod("u", memory="128Mi",
                                   extra={"example.com/fpga": "2"}))
    assert not ok and "TopologyAffinityError" in reason
    # one fpga + small memory aligns on one node
    ok, _, aff = tm.admit(_gpod("u2", memory="128Mi",
                                extra={"example.com/fpga": "1"}))
    assert ok and len(aff) == 1


def test_topology_best_effort_admits_cross_node():
    _dm, _mm, tm = _managers(POLICY_BEST_EFFORT)
    ok, _, _ = tm.admit(_gpod("u", memory="128Mi",
                              extra={"example.com/fpga": "2"}))
    assert ok


def test_topology_no_hints_always_admits():
    """A pod no provider has an opinion about carries no topology
    constraint — even single-numa-node must admit it (upstream admits
    hint-less pods)."""
    _dm, _mm, tm = _managers(POLICY_SINGLE_NUMA)
    pod = _gpod("u", memory="64Mi")
    del pod["spec"]["containers"][0]["resources"]["limits"]  # not Guaranteed
    ok, reason, _ = tm.admit(pod)
    assert ok, reason


def test_topology_rejection_releases_allocatable():
    """A topology rejection must not leak the admitter reservation."""
    from kubernetes_tpu.client.clientset import DirectClient
    from kubernetes_tpu.kubelet.kubelet import Kubelet
    from kubernetes_tpu.kubelet.managers import Device, POLICY_SINGLE_NUMA
    from kubernetes_tpu.store.store import ObjectStore
    client = DirectClient(ObjectStore())
    kl = Kubelet(client, "n-topo", allocatable={"cpu": "4", "memory": "4Gi",
                                                "pods": "10"})
    kl.topology_manager.policy = POLICY_SINGLE_NUMA
    kl.topology_manager.num_numa = 2
    kl.device_manager.register_plugin(
        "example.com/fpga", [Device("a", 0), Device("b", 1)])
    # spanning demand -> rejected; reservation must be released
    pod = _gpod("u-rej", cpu="1", memory="128Mi",
                extra={"example.com/fpga": "2"})
    pod["metadata"]["name"] = "rej"
    kl._sync_pod("u-rej", pod)
    assert "u-rej" in kl._rejected
    assert kl.admitter._used.get("u-rej") is None \
        or not kl.admitter._used.get("u-rej")
    # a normal pod still fits afterwards
    ok, _ = kl.admitter.admit(_gpod("u-ok", cpu="3", memory="3Gi"))
    assert ok
