"""Server-side apply: field ownership, reconcile-by-absence, conflicts.

Reference: ``staging/src/k8s.io/apimachinery/pkg/util/managedfields`` +
structured-merge-diff semantics behind ``kubectl apply --server-side``.
Lists are atomic here (documented simplification, store/apply.py).
"""

import pytest

from kubernetes_tpu.client.clientset import ApiError, DirectClient, HTTPClient
from kubernetes_tpu.store.apiserver import APIServer
from kubernetes_tpu.store.apply import (field_set, from_fields_v1,
                                        server_side_apply, to_fields_v1)
from kubernetes_tpu.store.store import ObjectStore


@pytest.fixture()
def api():
    server = APIServer().start()
    yield server
    server.stop()


def _cm(name, data):
    return {"kind": "ConfigMap", "metadata": {"name": name}, "data": data}


def test_fields_v1_roundtrip():
    paths = {("data", "a"), ("data", "b", "c"), ("metadata", "labels", "x")}
    assert from_fields_v1(to_fields_v1(paths)) == paths


def test_fields_v1_roundtrip_dotted_segments():
    """Map keys containing '.' (ConfigMap data file names, label keys like
    topology.kubernetes.io/zone) are single fieldsV1 segments — they must
    NOT split into nested path components."""
    paths = {("data", "config.yaml"),
             ("metadata", "labels", "topology.kubernetes.io/zone")}
    assert from_fields_v1(to_fields_v1(paths)) == paths
    trie = to_fields_v1(paths)
    assert "f:config.yaml" in trie["f:data"]


def test_apply_creates_with_ownership():
    out = server_side_apply(None, _cm("c", {"k": "v"}), "mgr-a")
    mf = out["metadata"]["managedFields"]
    assert len(mf) == 1
    assert mf[0]["manager"] == "mgr-a"
    assert mf[0]["operation"] == "Apply"
    assert "f:data" in mf[0]["fieldsV1"]


def test_reconcile_by_absence():
    """A field this manager applied before and dropped now is REMOVED —
    the property client-side apply cannot provide."""
    v1 = server_side_apply(None, _cm("c", {"keep": "1", "drop": "2"}), "a")
    v2 = server_side_apply(v1, _cm("c", {"keep": "1"}), "a")
    assert v2["data"] == {"keep": "1"}


def test_disjoint_managers_coexist():
    v1 = server_side_apply(None, _cm("c", {"a": "1"}), "mgr-a")
    v2 = server_side_apply(
        v1, {"kind": "ConfigMap", "metadata": {"name": "c",
                                               "labels": {"who": "b"}}},
        "mgr-b")
    # mgr-b owns labels; mgr-a's data survives untouched
    assert v2["data"] == {"a": "1"}
    assert v2["metadata"]["labels"] == {"who": "b"}
    managers = {e["manager"] for e in v2["metadata"]["managedFields"]}
    assert managers == {"mgr-a", "mgr-b"}
    # dropping mgr-b's label later removes it without touching data
    v3 = server_side_apply(v2, {"kind": "ConfigMap",
                                "metadata": {"name": "c"}}, "mgr-b")
    assert "labels" not in v3["metadata"]
    assert v3["data"] == {"a": "1"}


def test_conflict_and_force():
    from kubernetes_tpu.store.apply import ApplyConflict
    v1 = server_side_apply(None, _cm("c", {"k": "a-version"}), "mgr-a")
    with pytest.raises(ApplyConflict) as ei:
        server_side_apply(v1, _cm("c", {"k": "b-version"}), "mgr-b")
    assert ei.value.conflicts == [(("data", "k"), "mgr-a")]
    forced = server_side_apply(v1, _cm("c", {"k": "b-version"}), "mgr-b",
                               force=True)
    assert forced["data"]["k"] == "b-version"
    # ownership transferred: mgr-a's entry no longer claims data.k
    owners = {e["manager"]: from_fields_v1(e["fieldsV1"])
              for e in forced["metadata"]["managedFields"]}
    assert ("data", "k") in owners["mgr-b"]
    assert ("data", "k") not in owners.get("mgr-a", set())


def test_same_value_is_not_a_conflict():
    v1 = server_side_apply(None, _cm("c", {"k": "same"}), "mgr-a")
    v2 = server_side_apply(v1, _cm("c", {"k": "same"}), "mgr-b")
    owners = {e["manager"]: from_fields_v1(e["fieldsV1"])
              for e in v2["metadata"]["managedFields"]}
    # co-ownership: both managers hold the path
    assert ("data", "k") in owners["mgr-a"]
    assert ("data", "k") in owners["mgr-b"]
    # a co-owner dropping the field does NOT remove it (other owner remains)
    v3 = server_side_apply(v2, {"kind": "ConfigMap",
                                "metadata": {"name": "c"}}, "mgr-b")
    assert v3["data"]["k"] == "same"


@pytest.mark.parametrize("transport", ["http", "direct"])
def test_apply_via_transports(api, transport):
    if transport == "http":
        c = HTTPClient(api.url)
    else:
        c = DirectClient(ObjectStore())
    res = c.resource("configmaps", "default")
    res.apply(_cm("t", {"x": "1", "y": "2"}), field_manager="one")
    got = res.get("t")
    assert got["data"] == {"x": "1", "y": "2"}
    assert got["metadata"]["managedFields"][0]["manager"] == "one"
    # second manager conflicts without force
    with pytest.raises(ApiError) as ei:
        res.apply(_cm("t", {"x": "other"}), field_manager="two")
    assert ei.value.code == 409
    res.apply(_cm("t", {"x": "other"}), field_manager="two", force=True)
    assert res.get("t")["data"]["x"] == "other"
    # reconcile-by-absence over the wire
    res.apply(_cm("t", {"y": "2"}), field_manager="one")
    assert "x" in res.get("t")["data"]  # two's field survives
    left = res.get("t")["data"]
    assert left == {"x": "other", "y": "2"}


def test_cli_server_side_apply(api, tmp_path):
    import io
    from kubernetes_tpu.cli.ktpu import main
    f = tmp_path / "cm.yaml"
    f.write_text("kind: ConfigMap\nmetadata:\n  name: cli\ndata:\n  a: '1'\n")
    out = io.StringIO()
    rc = main(["--server", api.url, "apply", "-f", str(f), "--server-side",
               "--field-manager", "cli-mgr"], out=out)
    assert rc == 0, out.getvalue()
    got = HTTPClient(api.url).resource("configmaps", "default").get("cli")
    assert got["data"] == {"a": "1"}
    assert got["metadata"]["managedFields"][0]["manager"] == "cli-mgr"


def test_apply_crd_registers_routes(api):
    """SSA of a CRD must run the same validate + serving-table rebuild as
    POST/PUT — the custom kind is routable immediately after."""
    c = HTTPClient(api.url)
    c.resource("customresourcedefinitions", None).apply({
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "widgets.example.com"},
        "spec": {"group": "example.com",
                 "names": {"plural": "widgets", "kind": "Widget"}}},
        field_manager="ssa")
    c.discover_custom()
    c.resource("widgets", "default").create(
        {"kind": "Widget", "metadata": {"name": "w1"}})
    assert c.resource("widgets", "default").get("w1")["metadata"]["name"] \
        == "w1"
    # an invalid CRD is rejected, exactly like POST
    with pytest.raises(ApiError) as ei:
        c.resource("customresourcedefinitions", None).apply({
            "kind": "CustomResourceDefinition",
            "metadata": {"name": "bad"},
            "spec": {"names": {"plural": "pods", "kind": "Pod"}}},
            field_manager="ssa")
    assert ei.value.code == 400


def test_apply_field_manager_url_encoding(api):
    c = HTTPClient(api.url)
    res = c.resource("configmaps", "default")
    res.apply(_cm("enc", {"k": "v"}),
              field_manager="kubectl client-side & friends")
    mf = res.get("enc")["metadata"]["managedFields"]
    assert mf[0]["manager"] == "kubectl client-side & friends"


def test_apply_with_dotted_data_keys():
    """Regression: applying {'data': {'config.yaml': ...}} over a live
    object must replace the value (not silently keep the old one) and must
    not inject junk nested keys like {'config': {'yaml': None}}."""
    v1 = server_side_apply(None, _cm("c", {"config.yaml": "a: 1"}), "m")
    v2 = server_side_apply(v1, _cm("c", {"config.yaml": "a: 2"}), "m")
    assert v2["data"] == {"config.yaml": "a: 2"}
    assert "config" not in v2["data"]
    # reconcile-by-absence with a dotted sibling
    v3 = server_side_apply(
        v2, _cm("c", {"config.yaml": "a: 2", "extra.toml": "x"}), "m")
    v4 = server_side_apply(v3, _cm("c", {"config.yaml": "a: 2"}), "m")
    assert v4["data"] == {"config.yaml": "a: 2"}


def test_apply_with_dotted_label_keys():
    v1 = server_side_apply(None, {
        "kind": "Node", "metadata": {
            "name": "n",
            "labels": {"topology.kubernetes.io/zone": "us-east1-a"}}}, "m")
    v2 = server_side_apply(v1, {
        "kind": "Node", "metadata": {
            "name": "n",
            "labels": {"topology.kubernetes.io/zone": "us-east1-b"}}}, "m")
    assert v2["metadata"]["labels"] == {
        "topology.kubernetes.io/zone": "us-east1-b"}


def test_apply_to_subresource_rejected(api):
    c = HTTPClient(api.url)
    c.pods("default").create({"kind": "Pod", "metadata": {"name": "s"},
                              "spec": {"containers": []}})
    with pytest.raises(ApiError) as ei:
        c._req("PATCH", c._path("pods", "default", "s", "status",
                                query="fieldManager=x"),
               {"status": {"phase": "Running"}})
    assert ei.value.code == 405
