#!/usr/bin/env bash
# tools/lint.sh — the static-analysis gate, runnable anywhere tier-1 runs.
#
#   1. syntax pass: every file under kubernetes_tpu/ must byte-compile
#      (the pyflakes-equivalent floor; ktpu-lint skips unparseable files,
#      so this pass is what turns a syntax error into a hard failure);
#   2. ktpu-lint over the package with the committed baseline, failing on
#      any NEW finding and printing a machine-readable [ktpu-lint] JSON
#      summary line (the bench.py convention) for CI wrappers to parse.
#
# Exit: 0 clean, non-zero on syntax errors or new findings.
set -o pipefail

REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO_ROOT"

PY="${PYTHON:-python}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "[lint] syntax pass (compileall) ..."
if ! "$PY" -m compileall -q kubernetes_tpu; then
    echo '[ktpu-lint] {"tool": "ktpu-lint", "ok": false, "error": "syntax"}'
    exit 1
fi

echo "[lint] ktpu-lint (fail on new findings vs committed baseline) ..."
"$PY" -m kubernetes_tpu.analysis --json "$@"
rc=$?
if [ $rc -ne 0 ]; then
    echo "[lint] FAILED: new findings above (suppress with a reasoned"
    echo "       '# ktpu-lint: disable=KTL00N -- why', fix the code, or"
    echo "       deliberately accept via --write-baseline)"
fi
exit $rc
