"""Benchmark: scheduling throughput (pods/sec) on the real TPU chip.

Runs ALL FIVE BASELINE.json configs through the scheduler_perf harness
(benchmarks/scheduler_perf.py — the reference's
test/integration/scheduler_perf YAML workloads), then the CONNECTED path
(benchmarks/connected.py — informers + queue + incremental cache + gang step
+ async binding against the in-process apiserver).

Headline metric mirrors scheduler_perf's SchedulingThroughput on the
MixedHeterogeneous 10k pods x 5k nodes workload: scheduling *decisions* per
second through the filter/score/select cycle. p99 per-pod schedule latency
is reported per workload (north-star target: p99 < 1s).

vs_baseline: ratio against 300 pods/s — the mid-range of upstream
scheduler_perf thresholds for comparable workloads (BASELINE.md; the
reference publishes no in-repo numbers, "published": {}).

Env knobs: BENCH_CASE (only this case), BENCH_SCALE (default 1.0),
BENCH_BATCH (default 1024), BENCH_CONNECTED=0 to skip the connected run,
BENCH_CONNECTED_PODS/NODES (default 2000/1000), BENCH_CONNECTED_PIPELINE
(dispatch-pipeline depth for the connected run — sweep it to find the
knee; unset = SchedulerConfiguration.pipeline_depth default),
BENCH_CHAOS=0 to skip the ChaosChurn case (BENCH_CHAOS_PODS/NODES size
it; KTPU_CHAOS_SEED replays a failing fault schedule — the case exits
the bench non-zero if any pod is lost under faults),
BENCH_SCALEFLEET=0 to skip the ScaleFleet sweep (BENCH_SCALE_NODES
sizes the two-point fleet sweep, default "256 2048"; the 100k campaign
tier is "1250 10000"; BENCH_SCALE_MAX_GROWTH tunes the sublinear
control-plane gate), BENCH_FLEET=0 to skip the FleetChurn case (K
tenant apiservers through one FleetRunner + one warm resident program;
BENCH_FLEET_TENANTS default 4, campaign tier 16; BENCH_FLEET_NOISY
sets the noisy-neighbor churn multiple, BENCH_FLEET_P99 the per-tenant
bind-p99 ceiling — gates: 100% binds/tenant, 0 violations, 0 XLA
compiles in the steady window), BENCH_SLICECARVE=0 to skip the
SliceCarve case (contiguous ICI sub-slice churn over a labeled torus;
BENCH_SLICE_GRID/SHAPE/WINDOW_S/FRAG size it — gates: every gang lands
one contiguous box, 0 violations, 0 XLA compiles in the steady window,
0 carve-parity divergences at every=1),
BENCH_DISASTER=0 to skip the DisasterChurn case
(apiserver SIGKILL + WAL-replay restart mid-churn; BENCH_DISASTER_NODES/
PODS/OUTAGE_S size it, BENCH_DISASTER_BIND_SLO bounds time-to-first-
bind-after-restart), BENCH_WATCHSTORM=0 to skip the WatchStorm case
(>=10k watchers vs 1 leader + 2 read replicas;
BENCH_WATCHSTORM_WATCHERS/PODS size it, BENCH_WATCHSTORM_SPAN_GROWTH
gates leader fan-out span growth, BENCH_WATCHSTORM_HEAL_SLO bounds a
SIGKILLed replica's rebirth — every gate treats a missing number as
failure), BENCH_SCENARIO=0 to skip the ScenarioReplay case (cluster
time machine: BENCH_SCENARIO=builtin:<name> or a .trace.jsonl path
picks the trace, default builtin:smoke; BENCH_SCENARIO_SPEED warps
replay time, BENCH_SCENARIO_SEED seeds the generator — gates: 100% of
trace-resident pods bound, per-phase p99 attempt latency present,
deterministic dispatch order, the manifest's own sloGates),
BENCH_PLANNER=0 to skip the PlannerLoop case (three planners, one
cluster image: autoscaler + descheduler + gang defrag riding the
scheduler's device-resident encoding; BENCH_PLANNER_NODES/CYCLES size
it — gates: 0 XLA compiles and 0 cold full encodes in the steady
window, overlay hits advance for every planner, resident-vs-cold plan
parity bit-equal).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 300.0
HEADLINE = ("MixedHeterogeneous", "10000Pods5000Nodes")


def main():
    # single-core box: the tunnel client's Python layer competes for the
    # GIL with informer bursts; a finer switch interval shortens the stalls
    # a device_get suffers mid-burst. Set ONCE for the whole bench process
    # so every case runs under the same scheduling regime.
    sys.setswitchinterval(0.0005)
    from benchmarks.connected import run_connected
    from benchmarks.scheduler_perf import load_config, run_workload

    only_case = os.environ.get("BENCH_CASE")
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    batch = int(os.environ.get("BENCH_BATCH", "1024"))

    log = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
    results = []
    for case in load_config():
        if only_case and case["name"] != only_case:
            continue
        for wl in case["workloads"]:
            if "performance" not in (wl.get("labels") or []):
                continue
            t0 = time.time()
            log(f"[bench] {case['name']}/{wl['name']} ...")
            res = run_workload(case, wl, scale=scale, batch=batch, log=log)
            res["total_s"] = round(time.time() - t0, 1)
            results.append(res)
            log("[bench] " + json.dumps(res))

    # Perfetto traces of each connected-path case's measured window land
    # next to the result JSON, case-suffixed (BENCH_TRACE.<Case>.json;
    # BENCH_TRACE_PATH="" disables; load at ui.perfetto.dev). Set before
    # ALL cases — ChaosChurn/ExplainAB dump traces too.
    os.environ.setdefault(
        "BENCH_TRACE_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_TRACE.json"))

    connected = None
    if os.environ.get("BENCH_CONNECTED", "1") != "0" and not only_case:
        log("[bench] connected-path run ...")
        _pipe = os.environ.get("BENCH_CONNECTED_PIPELINE")
        connected = run_connected(
            n_pods=int(os.environ.get("BENCH_CONNECTED_PODS", "10000")),
            n_nodes=int(os.environ.get("BENCH_CONNECTED_NODES", "5000")),
            pipeline_depth=int(_pipe) if _pipe else None,
            log=log)
        log("[bench] " + json.dumps(connected))

    connected_mesh = None
    shapes = []
    if os.environ.get("BENCH_MESH", "1") != "0" and not only_case:
        # runs in a SUBPROCESS with a forced multi-device CPU host platform:
        # this process owns the single real TPU chip, and the mesh case
        # needs >= 2 devices to shard over (same trick as the driver's
        # multichip dry-run). The subprocess runs, PER MESH WIDTH, the
        # deterministic sharded-vs-unsharded drain parity gate and a live
        # hollow-kubelet leg against one shared unsharded baseline — and
        # gates sharded >= unsharded at every width that ran.
        import subprocess
        from kubernetes_tpu.parallel.mesh import parse_mesh_shape
        # BENCH_MESH_SHAPES: ";"/space-separated width list ("1x2;1x4");
        # falls back to the single-shape BENCH_MESH_SHAPE. "off"/"none"
        # (parse -> None) or an unparseable value disables the case —
        # never silently substitutes a default shape
        shape_s = os.environ.get(
            "BENCH_MESH_SHAPES", os.environ.get("BENCH_MESH_SHAPE", "1x2"))
        try:
            shapes = [s for s in
                      (parse_mesh_shape(tok)
                       for tok in shape_s.replace(";", " ").split())
                      if s is not None]
        except ValueError as e:
            log(f"[bench] bad BENCH_MESH_SHAPES={shape_s!r} ({e}); "
                "skipping mesh case")
            shapes = []
    if shapes:
        log(f"[bench] connected mesh run ({shape_s}) ...")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # append, don't clobber: the operator's own XLA flags (dump/tuning)
        # must survive in the subprocess. Device count covers the WIDEST
        # swept width; narrower meshes use a prefix of the devices.
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{max(p * n for p, n in shapes)}").strip()
        env["BENCH_MESH_SHAPES"] = shape_s
        # an exported KTPU_MESH would override BOTH legs' mesh_shape config
        # (including the unsharded leg's explicit None), silently turning
        # the A/B into sharded-vs-sharded
        env.pop("KTPU_MESH", None)
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "benchmarks",
                                              "connected.py"), "mesh"],
                env=env, capture_output=True, text=True, timeout=1800)
            sys.stderr.write(proc.stderr[-4000:])
            connected_mesh = json.loads(
                proc.stdout.strip().splitlines()[-1])
        except Exception as e:
            # NO parity verdict — the subprocess died/timed out before the
            # comparison ran. Distinct from parity ok=False (real
            # divergence): only the latter may fail the bench.
            connected_mesh = {"case": "ConnectedMesh", "error": str(e)}
        log("[bench] " + json.dumps(connected_mesh))
        _write_multichip(here, connected_mesh, log)

    chaos_churn = None
    if os.environ.get("BENCH_CHAOS", "1") != "0" and not only_case:
        # churn workload under the default fault schedule: API storms,
        # watch gaps, a breaker-tripping device burst, thread stalls. The
        # seed is logged and env-overridable (KTPU_CHAOS_SEED) so any
        # failure replays deterministically; the gate below exits non-zero
        # if a single pod was lost.
        from benchmarks.connected import run_chaos_churn
        log("[bench] chaos churn run ...")
        chaos_churn = run_chaos_churn(
            n_pods=int(os.environ.get("BENCH_CHAOS_PODS", "2000")),
            n_nodes=int(os.environ.get("BENCH_CHAOS_NODES", "1000")),
            log=log)
        log("[bench] " + json.dumps(chaos_churn))

    explain_ab = None
    if os.environ.get("BENCH_EXPLAIN_AB", "1") != "0" and not only_case:
        # explainer + flight recorder on/off A/B on the churn workload:
        # the observability layer must cost <= 5% throughput (hard gate,
        # like the other sloGates — a missing ratio fails too)
        from benchmarks.connected import run_explain_ab
        log("[bench] explain A/B run ...")
        explain_ab = run_explain_ab(
            n_pods=int(os.environ.get("BENCH_EXPLAIN_PODS", "2000")),
            n_nodes=int(os.environ.get("BENCH_EXPLAIN_NODES", "1000")),
            min_ratio=float(os.environ.get("BENCH_EXPLAIN_MIN_RATIO",
                                           "0.95")),
            log=log)
        log("[bench] " + json.dumps(explain_ab))

    preemption = None
    if os.environ.get("BENCH_PREEMPTION", "1") != "0" and not only_case:
        from benchmarks.preemption_bench import run_preemption
        log("[bench] preemption run ...")
        preemption = run_preemption(
            n_nodes=int(os.environ.get("BENCH_PREEMPT_NODES", "5000")),
            n_preemptors=int(os.environ.get("BENCH_PREEMPT_PODS", "128")),
            log=log)
        log("[bench] " + json.dumps(preemption))

    pallas = None
    if os.environ.get("BENCH_PALLAS", "1") != "0" and not only_case:
        # the domain-count hot op: live XLA number + the recorded round-4
        # measurement that retired the Pallas kernel (prove-or-delete);
        # in-process — the bench already owns the single TPU client
        from benchmarks.pallas_bench import run_domain_count
        log("[bench] domain-count hot-op run ...")
        try:
            pallas = run_domain_count()
        except Exception as e:
            pallas = {"error": str(e)}
        log("[bench] " + json.dumps(pallas))

    connected_preemption = None
    if os.environ.get("BENCH_CPREEMPT", "1") != "0" and not only_case:
        from benchmarks.connected import run_connected_preemption
        log("[bench] connected preemption run ...")
        connected_preemption = run_connected_preemption(
            n_nodes=int(os.environ.get("BENCH_CPREEMPT_NODES", "5000")),
            n_high=int(os.environ.get("BENCH_CPREEMPT_PODS", "128")),
            log=log)
        log("[bench] " + json.dumps(connected_preemption))

    scale_fleet = None
    if os.environ.get("BENCH_SCALEFLEET", "1") != "0" and not only_case:
        # two-point hollow-fleet sweep with the sublinear control-plane
        # gate (heartbeat + lease + status span growth <= 2x across an 8x
        # fleet; missing number = failure). BENCH_SCALE_NODES sizes the
        # sweep — default fits the box, the 100k campaign runs
        # "1250 10000". Runs before kubemark for the same
        # leftover-daemon-thread reason.
        from benchmarks.scalefleet import run_scale_fleet
        log("[bench] scale-fleet sweep ...")
        sizes = [int(t) for t in os.environ.get(
            "BENCH_SCALE_NODES", "256 2048").replace(",", " ").split()]
        scale_fleet = run_scale_fleet(
            fleet_sizes=sizes,
            n_pods=int(os.environ.get("BENCH_SCALE_PODS", "256")),
            window_s=float(os.environ.get("BENCH_SCALE_WINDOW_S", "12")),
            heartbeat_period=float(os.environ.get("BENCH_SCALE_HB_PERIOD",
                                                  "5.0")),
            max_growth=float(os.environ.get("BENCH_SCALE_MAX_GROWTH",
                                            "2.0")),
            log=log)
        log("[bench] " + json.dumps(scale_fleet))

    fleet_churn = None
    if os.environ.get("BENCH_FLEET", "1") != "0" and not only_case:
        # K tenant apiservers + hollow fleets through ONE FleetRunner and
        # one warm resident program: 100% binds per tenant, per-tenant SLO
        # gates with tenant 0 churning 4x (noisy neighbor), steady-state
        # resident-ctx rebuilds == 0, fail-fast auditor (cross_tenant
        # invariant live) — missing number = failure. Default K=4 fast;
        # campaign tier BENCH_FLEET_TENANTS=16.
        from benchmarks.fleetchurn import run_fleet_churn
        log("[bench] fleet churn run ...")
        fleet_churn = run_fleet_churn(
            n_tenants=int(os.environ.get("BENCH_FLEET_TENANTS", "4")),
            nodes_per_tenant=int(os.environ.get("BENCH_FLEET_NODES", "8")),
            upfront_pods=int(os.environ.get("BENCH_FLEET_PODS", "24")),
            window_s=float(os.environ.get("BENCH_FLEET_WINDOW_S", "12")),
            noisy_factor=int(os.environ.get("BENCH_FLEET_NOISY", "4")),
            p99_slo_s=float(os.environ.get("BENCH_FLEET_P99", "10")),
            log=log)
        log("[bench] " + json.dumps(fleet_churn))

    slice_carve = None
    if os.environ.get("BENCH_SLICECARVE", "1") != "0" and not only_case:
        # contiguous-slice churn over a labeled torus: every gang must
        # land one contiguous box, with 0 violations (slice_contiguity
        # armed), 0 XLA compiles in the steady window, and every device
        # carve parity-confirmed against the numpy oracle carver
        from benchmarks.slicecarve import run_slice_carve
        log("[bench] slice carve run ...")
        slice_carve = run_slice_carve(
            grid=os.environ.get("BENCH_SLICE_GRID", "4x4x2"),
            shape=os.environ.get("BENCH_SLICE_SHAPE", "2x2x2"),
            window_s=float(os.environ.get("BENCH_SLICE_WINDOW_S", "10")),
            n_fragment=int(os.environ.get("BENCH_SLICE_FRAG", "4")),
            log=log)
        log("[bench] " + json.dumps(slice_carve))

    disaster = None
    if os.environ.get("BENCH_DISASTER", "1") != "0" and not only_case:
        # apiserver SIGKILL + WAL-replay restart mid-churn: every pod
        # bound, 0 invariant violations, 0 outage-caused evictions/taints
        # (disruption mode engaged AND released), first-bind-after-restart
        # <= BENCH_DISASTER_BIND_SLO (10s) — missing number = failure
        from benchmarks.disaster import run_disaster_churn
        log("[bench] disaster churn run ...")
        disaster = run_disaster_churn(
            n_hollow=int(os.environ.get("BENCH_DISASTER_NODES", "48")),
            n_pods=int(os.environ.get("BENCH_DISASTER_PODS", "96")),
            outage_s=float(os.environ.get("BENCH_DISASTER_OUTAGE_S",
                                          "16")),
            bind_slo_s=float(os.environ.get("BENCH_DISASTER_BIND_SLO",
                                            "10")),
            log=log)
        log("[bench] " + json.dumps(disaster))

    watch_storm = None
    if os.environ.get("BENCH_WATCHSTORM", "1") != "0" and not only_case:
        # read-replica serving plane under a watch storm: >=10k watchers
        # against 1 leader + 2 replicas — leader fan-out span growth
        # <= 1.2x with >= 2/3 replica-served share, gap-free streams
        # (signature-identical per cohort), 0 drops, staleness bound
        # honored, replica SIGKILL mid-churn heals with zero loss.
        # BENCH_WATCHSTORM_WATCHERS/PODS size it; before kubemark for the
        # same daemon-thread-pollution reason as the others
        from benchmarks.watchstorm import run_watch_storm
        log("[bench] watch storm run ...")
        watch_storm = run_watch_storm(
            n_watchers=int(os.environ.get("BENCH_WATCHSTORM_WATCHERS",
                                          "10500")),
            churn_pods=int(os.environ.get("BENCH_WATCHSTORM_PODS", "600")),
            span_growth_max=float(os.environ.get(
                "BENCH_WATCHSTORM_SPAN_GROWTH", "1.2")),
            heal_slo_s=float(os.environ.get("BENCH_WATCHSTORM_HEAL_SLO",
                                            "90")),
            log=log)
        log("[bench] " + json.dumps(watch_storm))

    planner_loop = None
    if os.environ.get("BENCH_PLANNER", "1") != "0" and not only_case:
        # three planners, one cluster image: the BackgroundPlanner cadence
        # drives autoscaler + descheduler + gang defrag against the
        # scheduler's device-resident encoding — gates: 0 XLA compiles and
        # 0 cold full encodes across the measured window, every planner's
        # overlay hits advance, resident-vs-cold plans bit-equal, 0
        # invariant violations — missing number = failure
        from benchmarks.plannerloop import run_planner_loop
        log("[bench] planner loop run ...")
        planner_loop = run_planner_loop(
            n_nodes=int(os.environ.get("BENCH_PLANNER_NODES", "8")),
            window_cycles=int(os.environ.get("BENCH_PLANNER_CYCLES", "6")),
            log=log)
        log("[bench] " + json.dumps(planner_loop))

    scenario = None
    _scen = os.environ.get("BENCH_SCENARIO", "1")
    if _scen != "0" and not only_case:
        # cluster time machine: replay a production-shaped trace
        # (builtin:<name> or a .trace.jsonl path — committed fixture, WAL
        # capture, or audit-bundle conversion) through the connected
        # stack under the fail-fast auditor. Gates: 100% of trace-
        # resident pods bound, per-phase p99 attempt latency present,
        # deterministic dispatch order, the manifest's own sloGates —
        # missing numbers fail. BENCH_SCENARIO=1 runs builtin:smoke;
        # BENCH_SCENARIO_SPEED warps replay time (default 4x compressed).
        from benchmarks.scenario import run_scenario_replay
        log("[bench] scenario replay run ...")
        scenario = run_scenario_replay(
            spec="builtin:smoke" if _scen == "1" else _scen,
            speed=float(os.environ.get("BENCH_SCENARIO_SPEED", "4")),
            seed=int(os.environ.get("BENCH_SCENARIO_SEED", "0")),
            log=log)
        log("[bench] " + json.dumps(scenario))

    kubemark = None
    if os.environ.get("BENCH_KUBEMARK", "1") != "0" and not only_case:
        # LAST on purpose: the hollow fleet leaves hundreds of daemon
        # threads behind in this process, which measurably degrades any
        # device-path phase that runs after it on the single-core box
        from benchmarks.kubemark import run_kubemark
        log("[bench] kubemark run ...")
        kubemark = run_kubemark(
            n_hollow=int(os.environ.get("BENCH_KUBEMARK_NODES", "500")),
            n_pods=int(os.environ.get("BENCH_KUBEMARK_PODS", "1000")),
            log=log)
        log("[bench] " + json.dumps(kubemark))

    head = next((r for r in results
                 if (r["case"], r["workload"]) == HEADLINE), None)
    is_headline = head is not None
    if head is None:
        head = results[-1] if results else {"SchedulingThroughput": 0.0,
                                            "pods": 0, "nodes": 0,
                                            "case": "none", "workload": ""}
    throughput = head.get("SchedulingThroughput") or 0.0
    out = {
        "metric": (f"scheduling throughput ({head['case']} "
                   f"{head.get('pods', 0)}x{head.get('nodes', 0)})"),
        "value": round(throughput, 1),
        "unit": "pods/sec",
        # the 300 pods/s baseline is calibrated for the headline workload;
        # a filtered run (BENCH_CASE) has no comparable baseline
        "vs_baseline": (round(throughput / BASELINE_PODS_PER_SEC, 2)
                        if is_headline else None),
        "p99_schedule_latency_s": head.get("p99_schedule_latency_s"),
        "all_passed": all(r["passed"] for r in results) if results else False,
        "workloads": [
            # decision-latency cases (ClusterAutoscalerScaleUp,
            # DeschedulerDefrag) carry no SchedulingThroughput — a KeyError
            # here used to abort the whole summary (and the divergence gate
            # below) after every case had already passed
            {"case": r["case"], "workload": r["workload"],
             "pods_per_sec": r.get("SchedulingThroughput"),
             "p99_s": r.get("p99_schedule_latency_s"),
             "passed": r["passed"],
             **({"slo_failures": r["slo_failures"]}
                if r.get("slo_failures") else {}),
             **({"churn_api_ops": r["churn_api_ops"], "connected": True}
                if "churn_api_ops" in r else {})} for r in results],
        "connected": connected,
        "chaos_churn": chaos_churn,
        "connected_mesh": connected_mesh,
        "explain_ab": explain_ab,
        "preemption": preemption,
        "connected_preemption": connected_preemption,
        "scale_fleet": scale_fleet,
        "fleet_churn": fleet_churn,
        "slice_carve": slice_carve,
        "disaster_churn": disaster,
        "watch_storm": watch_storm,
        "planner_loop": planner_loop,
        "scenario_replay": scenario,
        "kubemark": kubemark,
        "pallas": pallas,
        # confirmed correctness-invariant violations across every audited
        # case (connected / chaos / mesh legs). _require_invariant_field
        # refuses to emit a summary without this key: BENCH_r05's
        # parsed-null crash taught that a silently missing figure reads
        # as "fine" for rounds
        "invariant_violations": _sum_violations(connected, chaos_churn,
                                                connected_mesh, explain_ab,
                                                scale_fleet, disaster,
                                                fleet_churn, slice_carve,
                                                watch_storm, planner_loop,
                                                scenario),
        # hard SLO verdicts from case-config gates (SchedulingChurn p99 +
        # throughput, ConnectedMesh legs). Missing numbers are failures —
        # the BENCH_r05 parsed-null lesson: a silently absent figure must
        # never read as a pass.
        "slo_failures": _collect_slo_failures(results, connected_mesh,
                                              explain_ab, scale_fleet,
                                              disaster, fleet_churn,
                                              slice_carve, watch_storm,
                                              scenario,
                                              planner_loop=planner_loop),
    }
    _require_invariant_field(out, "bench summary")
    print(json.dumps(out))
    if out["slo_failures"]:
        print(f"[bench] FATAL: {len(out['slo_failures'])} SLO gate "
              f"failure(s): {out['slo_failures']}", file=sys.stderr)
        sys.exit(1)
    if out["invariant_violations"]:
        audited = {name: c.get("invariant_violations") for name, c in
                   (("connected", connected), ("chaos_churn", chaos_churn),
                    ("connected_mesh", connected_mesh),
                    ("scale_fleet", scale_fleet),
                    ("fleet_churn", fleet_churn),
                    ("slice_carve", slice_carve),
                    ("disaster_churn", disaster),
                    ("watch_storm", watch_storm),
                    ("scenario_replay", scenario)) if c}
        print(f"[bench] FATAL: {out['invariant_violations']} correctness-"
              f"invariant violation(s) confirmed by the auditor "
              f"({audited}); repro bundles are on disk — replay with the "
              "logged chaos seed", file=sys.stderr)
        sys.exit(1)
    if chaos_churn is not None and (chaos_churn.get("chaos") or {}) \
            .get("lost"):
        # hard gate: pods lost under the fault schedule means self-healing
        # failed somewhere — replay with the logged seed to localize it
        print(f"[bench] FATAL: ChaosChurn lost "
              f"{chaos_churn['chaos']['lost']} pods "
              f"(seed {chaos_churn['chaos']['seed']})", file=sys.stderr)
        sys.exit(1)
    if (connected_mesh is not None
            and (connected_mesh.get("parity") or {}).get("ok") is False):
        # hard gate: a mesh whose placements diverge from single-device is
        # a miscompile or a sharding bug, never a tolerable perf variance.
        # (A subprocess error/timeout — or a width whose check crashed
        # environmentally — carries ok=None, reported above, not failed.)
        print("[bench] FATAL: ConnectedMesh sharded placements diverge "
              "from unsharded", file=sys.stderr)
        sys.exit(1)


def _collect_slo_failures(results, connected_mesh, explain_ab=None,
                          scale_fleet=None, disaster=None,
                          fleet_churn=None, slice_carve=None,
                          watch_storm=None, scenario=None,
                          planner_loop=None) -> list:
    """Flatten every case's hard-SLO failure strings, prefixed by case."""
    out = []
    for r in results or []:
        for msg in r.get("slo_failures") or []:
            out.append(f"{r['case']}/{r['workload']}: {msg}")
    if connected_mesh is not None:
        for msg in connected_mesh.get("slo_failures") or []:
            out.append(f"ConnectedMesh: {msg}")
    if explain_ab is not None:
        for msg in explain_ab.get("slo_failures") or []:
            out.append(f"ExplainAB: {msg}")
    if scale_fleet is not None:
        for msg in scale_fleet.get("slo_failures") or []:
            out.append(f"ScaleFleet: {msg}")
    if disaster is not None:
        for msg in disaster.get("slo_failures") or []:
            out.append(f"DisasterChurn: {msg}")
    if fleet_churn is not None:
        for msg in fleet_churn.get("slo_failures") or []:
            out.append(f"FleetChurn: {msg}")
    if slice_carve is not None:
        for msg in slice_carve.get("slo_failures") or []:
            out.append(f"SliceCarve: {msg}")
    if watch_storm is not None:
        for msg in watch_storm.get("slo_failures") or []:
            out.append(f"WatchStorm: {msg}")
    if scenario is not None:
        for msg in scenario.get("slo_failures") or []:
            out.append(f"ScenarioReplay: {msg}")
    if planner_loop is not None:
        for msg in planner_loop.get("slo_failures") or []:
            out.append(f"PlannerLoop: {msg}")
    return out


def _sum_violations(*cases) -> int:
    """Total invariant violations across audited case results (None cases
    — skipped via env knobs — contribute nothing)."""
    return sum(int(c.get("invariant_violations") or 0)
               for c in cases if c is not None)


def _require_invariant_field(summary: dict, label: str) -> None:
    """Refuse to emit a result JSON whose summary omits
    ``invariant_violations``: a missing correctness figure must fail the
    run loudly, not read as zero (the BENCH_r05 lesson, encoded)."""
    if "invariant_violations" not in summary:
        print(f"[bench] FATAL: {label} omits the invariant_violations "
              "field; refusing to emit it", file=sys.stderr)
        sys.exit(1)


def _write_multichip(here: str, result: dict, log) -> None:
    """Record the ConnectedMesh case in the next free MULTICHIP_r*.json
    (same series the driver's dry-run writes). Results that reached the
    audited legs must carry invariant_violations; pure error/skip records
    (the subprocess died before any leg ran) are exempt."""
    import re
    if not (result.get("error") or result.get("skipped")):
        _require_invariant_field(result, "MULTICHIP result")
    try:
        ns = [int(m.group(1)) for m in
              (re.match(r"MULTICHIP_r(\d+)\.json$", f)
               for f in os.listdir(here)) if m]
        path = os.path.join(here, f"MULTICHIP_r{max(ns, default=0) + 1:02d}.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1)
        log(f"[bench] wrote {os.path.basename(path)}")
    except Exception as e:
        log(f"[bench] MULTICHIP write failed: {e}")


if __name__ == "__main__":
    main()
