"""Benchmark: scheduling throughput (pods/sec) on the real TPU chip.

Headline config (BASELINE.json #5): 10k heterogeneous pods (spread + affinity
+ taints + selectors) onto 5k nodes, gang-batched. The metric mirrors
scheduler_perf's SchedulingThroughput: scheduling *decisions* per second —
the filter/score/select cycle — which is the part the reference measures and
the part lifted onto the TPU. Host-side snapshot encoding happens once per
cluster and is reported separately on stderr (it amortizes across cycles in
the live scheduler via incremental updates).

vs_baseline: ratio against 300 pods/s — the mid-range of upstream
scheduler_perf thresholds for comparable workloads (BASELINE.md; the
reference publishes no in-repo numbers, "published": {}).

Env knobs: BENCH_WORKLOAD (default MixedHeterogeneous), BENCH_PODS,
BENCH_NODES, BENCH_BATCH (default 1024).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 300.0


def main():
    import numpy as np

    from benchmarks.workloads import WORKLOADS
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    from kubernetes_tpu.models.gang import gang_schedule

    name = os.environ.get("BENCH_WORKLOAD", "MixedHeterogeneous")
    n_pods = int(os.environ.get("BENCH_PODS", "10000"))
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    batch = int(os.environ.get("BENCH_BATCH", "1024"))

    t0 = time.time()
    nodes, pods = WORKLOADS[name](pods=n_pods, nodes=n_nodes)
    print(f"[bench] workload {name}: {len(pods)} pods x {len(nodes)} nodes "
          f"(gen {time.time()-t0:.1f}s)", file=sys.stderr)

    t0 = time.time()
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    batches = [pods[i:i + batch] for i in range(0, len(pods), batch)]
    pbs = [enc.encode_pods(b, meta) for b in batches]
    topo_keys = meta.topo_keys
    print(f"[bench] encode: {time.time()-t0:.1f}s "
          f"({len(batches)} batches of {batch})", file=sys.stderr)

    # Warmup: compile the gang round on the first batch shape.
    t0 = time.time()
    gang_schedule(ct, pbs[0], topo_keys=topo_keys, max_rounds=2)
    print(f"[bench] warmup/compile: {time.time()-t0:.1f}s", file=sys.stderr)

    # Timed: schedule every batch, carrying committed capacity forward.
    t0 = time.time()
    scheduled = 0
    requested = np.asarray(ct.requested)
    total_rounds = 0
    for pb, chunk in zip(pbs, batches):
        ct_run = ct.replace(requested=requested)
        assignment, rounds = gang_schedule(ct_run, pb, topo_keys=topo_keys)
        total_rounds += rounds
        a = assignment[:len(chunk)]
        scheduled += int((a >= 0).sum())
        # fold accepted requests into the carried cluster state
        reqs = np.asarray(pb.requests)[:len(chunk)]
        valid = a >= 0
        np.add.at(requested, a[valid], reqs[valid])
    dt = time.time() - t0
    throughput = scheduled / dt if dt > 0 else 0.0
    print(f"[bench] scheduled {scheduled}/{len(pods)} pods in {dt:.2f}s "
          f"({total_rounds} gang rounds)", file=sys.stderr)

    print(json.dumps({
        "metric": f"scheduling throughput ({name} {len(pods)}x{len(nodes)})",
        "value": round(throughput, 1),
        "unit": "pods/sec",
        "vs_baseline": round(throughput / BASELINE_PODS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
