"""kube-proxy nftables backend — the successor dataplane renderer.

Reference: ``pkg/proxy/nftables/proxier.go`` (upstream's default-capable
backend since v1.31): one ``table ip kube-proxy`` owning verdict maps and
per-service chains —

- ``service-ips``        ipv4 . proto . port : verdict map dispatching
                         cluster IPs to ``goto service-<chain>``
- ``service-nodeports``  proto . port : verdict map for nodePorts
- ``no-endpoint-services`` REJECT verdicts for endpoint-less services
- ``service-<hash>``     ``numgen random mod N vmap`` spreading to
                         ``endpoint-<hash>`` chains
- ``endpoint-<hash>``    hairpin masquerade mark + ``dnat to ip:port``

Chain names carry upstream's readable suffix
(``service-<HASH8>-<ns>/<name>/<proto>/<port>``). The rendered document is
an ``nft -f`` payload; ``RestoredNftRules`` parses it back into a DNAT
decision table so render drift against the semantic table is caught the
same way the iptables backend's round-trip does.
"""

from __future__ import annotations

import base64
import hashlib

from kubernetes_tpu.proxy.proxier import Proxier, ServicePortInfo


def _hash8(*parts: str) -> str:
    digest = hashlib.sha256("".join(parts).encode()).digest()
    return base64.b32encode(digest).decode()[:8]


def _svc_chain(sp_name: str, proto: str, port: int) -> str:
    # service-<hash>-<ns>/<name>/<proto>/<port> (upstream servicePortChainName)
    return (f"service-{_hash8(sp_name, proto)}-"
            f"{sp_name.replace(':', '/')}/{proto}/{port}")


def _ep_chain(sp_name: str, proto: str, endpoint: str) -> str:
    ip = endpoint.rsplit(":", 1)[0]
    return (f"endpoint-{_hash8(sp_name, proto, endpoint)}-"
            f"{ip}/{sp_name.replace(':', '/')}")


class NftablesProxier(Proxier):
    """Same watch/sync machinery and resolve() dataplane as the iptables
    backend; only the kernel-facing render differs."""

    def sync_nft_text(self) -> str:
        """The full ``nft -f`` payload ``syncProxyRules`` would write."""
        with self._lock:
            services = sorted(self._services.items())
        svc_ip_elems: list[str] = []
        nodeport_elems: list[str] = []
        noep_elems: list[str] = []
        chains: list[str] = []
        for (ns, name, pname), spi in services:
            sp_name = f"{ns}/{name}" + (f":{pname}" if pname else "")
            proto = spi.protocol.lower()
            if not spi.endpoints:
                noep_elems.append(
                    f"{spi.cluster_ip} . {proto} . {spi.port} : "
                    f"goto reject-chain")
                continue
            chain = _svc_chain(sp_name, proto, spi.port)
            svc_ip_elems.append(
                f"{spi.cluster_ip} . {proto} . {spi.port} : goto {chain}")
            if spi.node_port:
                nodeport_elems.append(
                    f"{proto} . {spi.node_port} : goto {chain}")
            n = len(spi.endpoints)
            arms = ", ".join(
                f"{i} : goto {_ep_chain(sp_name, proto, ep)}"
                for i, ep in enumerate(spi.endpoints))
            chains.append(
                f"\tchain {chain} {{\n"
                f"\t\tnumgen random mod {n} vmap {{ {arms} }}\n"
                f"\t}}")
            for ep in spi.endpoints:
                ip = ep.rsplit(":", 1)[0]
                chains.append(
                    f"\tchain {_ep_chain(sp_name, proto, ep)} {{\n"
                    f"\t\tip saddr {ip} jump mark-for-masquerade\n"
                    f"\t\tmeta l4proto {proto} dnat to {ep}\n"
                    f"\t}}")

        def _map(name: str, keytype: str, elems: list[str]) -> str:
            body = (";\n\t\telements = { " + ",\n\t\t\t".join(elems) + " }"
                    if elems else "")
            return (f"\tmap {name} {{\n"
                    f"\t\ttype {keytype} : verdict{body}\n"
                    f"\t}}")

        parts = [
            "table ip kube-proxy {",
            '\tcomment "rules for kube-proxy"',
            "\tchain mark-for-masquerade {",
            "\t\tmeta mark set meta mark or 0x4000",
            "\t}",
            "\tchain masquerading {",
            "\t\ttype nat hook postrouting priority srcnat; policy accept;",
            "\t\tmeta mark & 0x4000 == 0 return",
            "\t\tmeta mark set meta mark xor 0x4000 masquerade",
            "\t}",
            "\tchain services {",
            "\t\ttype nat hook prerouting priority dnat; policy accept;",
            "\t\tip daddr . meta l4proto . th dport vmap "
            "@no-endpoint-services",
            "\t\tip daddr . meta l4proto . th dport vmap @service-ips",
            "\t\tfib daddr type local meta l4proto . th dport vmap "
            "@service-nodeports",
            "\t}",
            "\tchain reject-chain {",
            "\t\treject",
            "\t}",
            _map("service-ips", "ipv4_addr . inet_proto . inet_service",
                 svc_ip_elems),
            _map("service-nodeports", "inet_proto . inet_service",
                 nodeport_elems),
            _map("no-endpoint-services",
                 "ipv4_addr . inet_proto . inet_service", noep_elems),
        ] + chains + ["}"]
        return "\n".join(parts) + "\n"


class RestoredNftRules:
    """Parse an ``nft -f`` payload back into a DNAT decision table — the
    round-trip proof that the rendered ruleset is semantically complete
    (same contract as proxier.RestoredRules for iptables)."""

    def __init__(self, text: str):
        self.dispatch: dict[tuple, str] = {}   # (vip, port, proto) -> chain
        self.nodeports: dict[tuple, str] = {}  # (port, proto) -> chain
        self.rejects: set[tuple] = set()
        self.chains: dict[str, list[str]] = {}
        cur: str | None = None
        mode: str | None = None
        for raw in text.splitlines():
            line = raw.strip()
            if line.startswith("chain "):
                cur = line.split()[1]
                self.chains[cur] = []
                mode = "chain"
            elif line.startswith("map "):
                mode = line.split()[1]
            elif line == "}":
                cur = mode = None
            elif mode == "chain" and cur is not None and line:
                self.chains[cur].append(line)
            elif line.startswith("type "):
                continue  # the map's key-type declaration, not an element
            elif mode and mode != "chain" and ":" in line and "." in line:
                for elem in line.replace("elements = {", "").replace(
                        "}", "").split(","):
                    elem = elem.strip().rstrip(";")
                    if ":" not in elem or "." not in elem:
                        continue
                    key, _, verdict = elem.partition(" : ")
                    fields = [f.strip() for f in key.split(" . ")]
                    # removeprefix, not replace: chain names embed the
                    # user-controlled ns/name, which may contain "goto"
                    target = verdict.strip().removeprefix("goto").strip()
                    if mode == "service-ips":
                        vip, proto, port = fields
                        self.dispatch[(vip, int(port), proto)] = target
                    elif mode == "service-nodeports":
                        proto, port = fields
                        self.nodeports[(int(port), proto)] = target
                    elif mode == "no-endpoint-services":
                        vip, proto, port = fields
                        self.rejects.add((vip, int(port), proto))

    def backends(self, vip: str, port: int, proto: str = "tcp") -> list[str]:
        if (vip, port, proto) in self.rejects:
            return []
        chain = self.dispatch.get((vip, port, proto)) \
            or self.nodeports.get((port, proto))
        if chain is None:
            return []
        out: list[str] = []
        for rule in self.chains.get(chain, []):
            if "vmap" not in rule:
                continue
            arms = rule[rule.index("{") + 1:rule.rindex("}")]
            for arm in arms.split(","):
                target = arm.split(":", 1)[1].strip() \
                    .removeprefix("goto").strip()
                for ep_rule in self.chains.get(target, []):
                    if "dnat to" in ep_rule:
                        out.append(ep_rule.rsplit("dnat to", 1)[1].strip())
        return out
