"""kube-proxy — Service VIPs programmed from Service + Endpoints watches.

Reference: ``pkg/proxy/iptables/proxier.go`` (``Proxier.syncProxyRules``: a
full-state rule rebuild debounced behind change trackers), with
``pkg/proxy/servicechangetracker.go`` / ``endpointschangetracker.go``
feeding it. The kernel-rule surface here is a data-plane table: chains
KUBE-SERVICES -> KUBE-SVC-<id> -> KUBE-SEP-<id> represented as dicts, plus a
``resolve()`` that performs the DNAT a packet would take — tests and the CLI
exercise the same table the kernel would.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.client.informer import InformerFactory


@dataclass
class ServicePortInfo:
    namespace: str
    name: str
    port_name: str
    cluster_ip: str
    port: int
    protocol: str = "TCP"
    node_port: int = 0
    session_affinity: bool = False
    endpoints: list[str] = field(default_factory=list)  # "ip:port"


class Proxier:
    """Full-state sync on any Service/Endpoints change (rate-limited by the
    informer thread itself; upstream debounces with a bounded-frequency
    runner — sync here is cheap enough to run per event)."""

    def __init__(self, client, node_name: str = ""):
        self.client = client
        self.node_name = node_name
        self.factory = InformerFactory(client)
        self._lock = threading.Lock()
        # (ns, svc_name, port_name) -> ServicePortInfo
        self._services: dict[tuple, ServicePortInfo] = {}
        self._affinity: dict[tuple, str] = {}  # (client_ip, vip, port) -> endpoint
        self.sync_count = 0

    # ---- wiring ----------------------------------------------------------

    def start(self, wait_sync: float = 10.0):
        self.svc_informer = self.factory.informer("services", None)
        self.ep_informer = self.factory.informer("endpoints", None)
        self.svc_informer.add_event_handler(lambda *a: self.sync())
        self.ep_informer.add_event_handler(lambda *a: self.sync())
        self.factory.start_all()
        self.factory.wait_for_cache_sync(wait_sync)
        self.sync()
        return self

    def stop(self):
        self.factory.stop_all()

    # ---- syncProxyRules --------------------------------------------------

    def sync(self) -> None:
        eps_by_svc: dict[tuple, dict] = {}
        for ep in self.ep_informer.store.list():
            md = ep.get("metadata") or {}
            eps_by_svc[(md.get("namespace", ""), md.get("name", ""))] = ep
        table: dict[tuple, ServicePortInfo] = {}
        for svc in self.svc_informer.store.list():
            md = svc.get("metadata") or {}
            spec = svc.get("spec") or {}
            cluster_ip = spec.get("clusterIP", "")
            if not cluster_ip or cluster_ip == "None":
                continue  # headless: no VIP rules
            ns, name = md.get("namespace", ""), md.get("name", "")
            ep = eps_by_svc.get((ns, name), {})
            affinity = (spec.get("sessionAffinity") == "ClientIP")
            for sp in spec.get("ports") or []:
                pname = sp.get("name", "")
                backends: list[str] = []
                for subset in ep.get("subsets") or []:
                    # match the endpoints port to this service port by name
                    # (unnamed single-port services match everything)
                    target = None
                    for epp in subset.get("ports") or []:
                        if not pname or epp.get("name", "") == pname:
                            target = int(epp.get("port", 0))
                            break
                    if target is None:
                        continue
                    for a in subset.get("addresses") or []:
                        backends.append(f"{a['ip']}:{target}")
                table[(ns, name, pname)] = ServicePortInfo(
                    namespace=ns, name=name, port_name=pname,
                    cluster_ip=cluster_ip, port=int(sp.get("port", 0)),
                    protocol=sp.get("protocol", "TCP"),
                    node_port=int(sp.get("nodePort", 0) or 0),
                    session_affinity=affinity,
                    endpoints=sorted(backends))
        with self._lock:
            self._services = table
            live = {(spi.cluster_ip, spi.port) for spi in table.values()}
            self._affinity = {k: v for k, v in self._affinity.items()
                              if (k[1], k[2]) in live}
            self.sync_count += 1

    # ---- the data plane --------------------------------------------------

    def resolve(self, vip: str, port: int,
                client_ip: str = "",
                rng: Optional[random.Random] = None) -> Optional[str]:
        """DNAT decision for a packet to vip:port -> "backend_ip:port" or
        None (REJECT, the no-endpoints rule). Random pick mirrors the
        iptables statistic-mode-random chain; ClientIP affinity mirrors
        recent-module pinning."""
        rng = rng or random
        with self._lock:
            spi = next((s for s in self._services.values()
                        if (s.cluster_ip == vip or (s.node_port and s.node_port == port))
                        and (s.port == port or s.node_port == port)), None)
            if spi is None or not spi.endpoints:
                return None
            key = (client_ip, vip, port)
            if spi.session_affinity and client_ip:
                pinned = self._affinity.get(key)
                if pinned in spi.endpoints:
                    return pinned
            choice = spi.endpoints[rng.randrange(len(spi.endpoints))]
            if spi.session_affinity and client_ip:
                self._affinity[key] = choice
            return choice

    def rules(self) -> list[str]:
        """Back-compat view: the -A lines of sync_proxy_rules_text()."""
        return [ln for ln in self.sync_proxy_rules_text().splitlines()
                if ln.startswith("-A")]

    def sync_proxy_rules_text(self) -> str:
        """The FULL iptables-restore payload ``syncProxyRules`` writes: one
        ``*nat`` table with chain declarations, the masquerade plumbing
        (KUBE-POSTROUTING / KUBE-MARK-MASQ with the 0x4000 mark), per
        service-port KUBE-SVC-<hash> chains dispatched from KUBE-SERVICES,
        per-endpoint KUBE-SEP-<hash> chains (hairpin masquerade + DNAT),
        statistic-mode-random load spreading, REJECTs for endpoint-less
        services, a trailing KUBE-NODEPORTS dispatch, and COMMIT.

        Chain names hash exactly like upstream
        (``pkg/proxy/iptables/proxier.go`` ``portProtoHash``/
        ``servicePortEndpointChainName``: base32(sha256(...))[:16]), so the
        rendered document is byte-comparable with a real kube-proxy's
        iptables-save for the same cluster state."""
        with self._lock:
            services = sorted(self._services.items())
        decls = [":KUBE-SERVICES - [0:0]", ":KUBE-NODEPORTS - [0:0]",
                 ":KUBE-POSTROUTING - [0:0]", ":KUBE-MARK-MASQ - [0:0]"]
        rules: list[str] = [
            '-A KUBE-POSTROUTING -m mark ! --mark 0x4000/0x4000 -j RETURN',
            '-A KUBE-POSTROUTING -j MASQUERADE',
            '-A KUBE-MARK-MASQ -j MARK --set-xmark 0x4000/0x4000',
        ]
        nodeports: list[str] = []
        for (ns, name, pname), spi in services:
            sp_name = f"{ns}/{name}" + (f":{pname}" if pname else "")
            proto = spi.protocol.lower()
            comment = f'-m comment --comment "{sp_name} cluster IP"'
            if not spi.endpoints:
                rules.append(
                    f'-A KUBE-SERVICES {comment} -p {proto} -m {proto} '
                    f'-d {spi.cluster_ip}/32 --dport {spi.port} -j REJECT')
                continue
            svc_chain = _svc_chain(sp_name, spi.protocol)
            decls.append(f":{svc_chain} - [0:0]")
            rules.append(
                f'-A KUBE-SERVICES {comment} -p {proto} -m {proto} '
                f'-d {spi.cluster_ip}/32 --dport {spi.port} -j {svc_chain}')
            if spi.node_port:
                nodeports.append(
                    f'-A KUBE-NODEPORTS -p {proto} -m {proto} '
                    f'--dport {spi.node_port} -j {svc_chain}')
            n = len(spi.endpoints)
            for i, ep in enumerate(spi.endpoints):
                sep_chain = _sep_chain(sp_name, spi.protocol, ep)
                decls.append(f":{sep_chain} - [0:0]")
                prob = (f' -m statistic --mode random --probability '
                        f'{1 / (n - i):.10f}' if i < n - 1 else '')
                rules.append(
                    f'-A {svc_chain} -m comment --comment "{sp_name}"'
                    f'{prob} -j {sep_chain}')
                ip = ep.rsplit(":", 1)[0]
                rules.append(  # hairpin: a backend reaching its own VIP
                    f'-A {sep_chain} -m comment --comment "{sp_name}" '
                    f'-s {ip}/32 -j KUBE-MARK-MASQ')
                rules.append(
                    f'-A {sep_chain} -m comment --comment "{sp_name}" '
                    f'-p {proto} -m {proto} -j DNAT --to-destination {ep}')
        rules.append('-A KUBE-SERVICES -m addrtype --dst-type LOCAL '
                     '-j KUBE-NODEPORTS')
        rules += nodeports
        return "\n".join(["*nat"] + decls + rules + ["COMMIT", ""])

    def service_table(self) -> dict[tuple, ServicePortInfo]:
        with self._lock:
            return dict(self._services)


def _hash16(*parts: str) -> str:
    """base32(sha256(join))[:16] — upstream's portProtoHash shape."""
    import base64
    import hashlib
    digest = hashlib.sha256("".join(parts).encode()).digest()
    return base64.b32encode(digest).decode()[:16]


def _svc_chain(sp_name: str, protocol: str) -> str:
    return "KUBE-SVC-" + _hash16(sp_name, protocol.lower())


def _sep_chain(sp_name: str, protocol: str, endpoint: str) -> str:
    return "KUBE-SEP-" + _hash16(sp_name, protocol.lower(), endpoint)


class RestoredRules:
    """Parse an iptables-restore payload back into a DNAT decision table —
    the round-trip proof that the rendered text is semantically complete:
    a packet resolved through the PARSED rules must reach the same backend
    set as the live Proxier's resolve()."""

    def __init__(self, text: str):
        self.chains: dict[str, list[str]] = {}
        self.dispatch: dict[tuple, str] = {}   # (vip, port, proto) -> chain
        self.nodeports: dict[tuple, str] = {}  # (port, proto) -> chain
        self.rejects: set[tuple] = set()
        for line in text.splitlines():
            if line.startswith(":"):
                self.chains[line[1:].split()[0]] = []
            elif line.startswith("-A "):
                chain, rest = line[3:].split(" ", 1)
                self.chains.setdefault(chain, []).append(rest)
        for rule in self.chains.get("KUBE-SERVICES", []):
            toks = rule.split()
            if "-d" not in toks or "--dport" not in toks:
                continue
            vip = toks[toks.index("-d") + 1].split("/")[0]
            port = int(toks[toks.index("--dport") + 1])
            proto = toks[toks.index("-p") + 1]
            target = toks[toks.index("-j") + 1]
            if target == "REJECT":
                self.rejects.add((vip, port, proto))
            else:
                self.dispatch[(vip, port, proto)] = target
        for rule in self.chains.get("KUBE-NODEPORTS", []):
            toks = rule.split()
            if "--dport" in toks:
                self.nodeports[(int(toks[toks.index("--dport") + 1]),
                                toks[toks.index("-p") + 1])] = \
                    toks[toks.index("-j") + 1]

    def backends(self, vip: str, port: int, proto: str = "tcp") -> list[str]:
        """Every DNAT destination reachable for vip:port ([] = REJECT).
        REJECT rules sit in KUBE-SERVICES BEFORE the trailing nodePort
        dispatch, so a rejected clusterIP must not fall through to another
        service's nodePort chain."""
        if (vip, port, proto) in self.rejects:
            return []
        chain = self.dispatch.get((vip, port, proto)) \
            or self.nodeports.get((port, proto))
        if chain is None:
            return []
        out = []
        for rule in self.chains.get(chain, []):
            toks = rule.split()
            if "-j" not in toks:
                continue
            target = toks[toks.index("-j") + 1]
            if target.startswith("KUBE-SEP-"):
                for sep_rule in self.chains.get(target, []):
                    st = sep_rule.split()
                    if "--to-destination" in st:
                        out.append(st[st.index("--to-destination") + 1])
        return out
