"""kube-proxy — Service VIPs programmed from Service + Endpoints watches.

Reference: ``pkg/proxy/iptables/proxier.go`` (``Proxier.syncProxyRules``: a
full-state rule rebuild debounced behind change trackers), with
``pkg/proxy/servicechangetracker.go`` / ``endpointschangetracker.go``
feeding it. The kernel-rule surface here is a data-plane table: chains
KUBE-SERVICES -> KUBE-SVC-<id> -> KUBE-SEP-<id> represented as dicts, plus a
``resolve()`` that performs the DNAT a packet would take — tests and the CLI
exercise the same table the kernel would.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.client.informer import InformerFactory


@dataclass
class ServicePortInfo:
    namespace: str
    name: str
    port_name: str
    cluster_ip: str
    port: int
    protocol: str = "TCP"
    node_port: int = 0
    session_affinity: bool = False
    endpoints: list[str] = field(default_factory=list)  # "ip:port"


class Proxier:
    """Full-state sync on any Service/Endpoints change (rate-limited by the
    informer thread itself; upstream debounces with a bounded-frequency
    runner — sync here is cheap enough to run per event)."""

    def __init__(self, client, node_name: str = ""):
        self.client = client
        self.node_name = node_name
        self.factory = InformerFactory(client)
        self._lock = threading.Lock()
        # (ns, svc_name, port_name) -> ServicePortInfo
        self._services: dict[tuple, ServicePortInfo] = {}
        self._affinity: dict[tuple, str] = {}  # (client_ip, vip, port) -> endpoint
        self.sync_count = 0

    # ---- wiring ----------------------------------------------------------

    def start(self, wait_sync: float = 10.0):
        self.svc_informer = self.factory.informer("services", None)
        self.ep_informer = self.factory.informer("endpoints", None)
        self.svc_informer.add_event_handler(lambda *a: self.sync())
        self.ep_informer.add_event_handler(lambda *a: self.sync())
        self.factory.start_all()
        self.factory.wait_for_cache_sync(wait_sync)
        self.sync()
        return self

    def stop(self):
        self.factory.stop_all()

    # ---- syncProxyRules --------------------------------------------------

    def sync(self) -> None:
        eps_by_svc: dict[tuple, dict] = {}
        for ep in self.ep_informer.store.list():
            md = ep.get("metadata") or {}
            eps_by_svc[(md.get("namespace", ""), md.get("name", ""))] = ep
        table: dict[tuple, ServicePortInfo] = {}
        for svc in self.svc_informer.store.list():
            md = svc.get("metadata") or {}
            spec = svc.get("spec") or {}
            cluster_ip = spec.get("clusterIP", "")
            if not cluster_ip or cluster_ip == "None":
                continue  # headless: no VIP rules
            ns, name = md.get("namespace", ""), md.get("name", "")
            ep = eps_by_svc.get((ns, name), {})
            affinity = (spec.get("sessionAffinity") == "ClientIP")
            for sp in spec.get("ports") or []:
                pname = sp.get("name", "")
                backends: list[str] = []
                for subset in ep.get("subsets") or []:
                    # match the endpoints port to this service port by name
                    # (unnamed single-port services match everything)
                    target = None
                    for epp in subset.get("ports") or []:
                        if not pname or epp.get("name", "") == pname:
                            target = int(epp.get("port", 0))
                            break
                    if target is None:
                        continue
                    for a in subset.get("addresses") or []:
                        backends.append(f"{a['ip']}:{target}")
                table[(ns, name, pname)] = ServicePortInfo(
                    namespace=ns, name=name, port_name=pname,
                    cluster_ip=cluster_ip, port=int(sp.get("port", 0)),
                    protocol=sp.get("protocol", "TCP"),
                    node_port=int(sp.get("nodePort", 0) or 0),
                    session_affinity=affinity,
                    endpoints=sorted(backends))
        with self._lock:
            self._services = table
            live = {(spi.cluster_ip, spi.port) for spi in table.values()}
            self._affinity = {k: v for k, v in self._affinity.items()
                              if (k[1], k[2]) in live}
            self.sync_count += 1

    # ---- the data plane --------------------------------------------------

    def resolve(self, vip: str, port: int,
                client_ip: str = "",
                rng: Optional[random.Random] = None) -> Optional[str]:
        """DNAT decision for a packet to vip:port -> "backend_ip:port" or
        None (REJECT, the no-endpoints rule). Random pick mirrors the
        iptables statistic-mode-random chain; ClientIP affinity mirrors
        recent-module pinning."""
        rng = rng or random
        with self._lock:
            spi = next((s for s in self._services.values()
                        if (s.cluster_ip == vip or (s.node_port and s.node_port == port))
                        and (s.port == port or s.node_port == port)), None)
            if spi is None or not spi.endpoints:
                return None
            key = (client_ip, vip, port)
            if spi.session_affinity and client_ip:
                pinned = self._affinity.get(key)
                if pinned in spi.endpoints:
                    return pinned
            choice = spi.endpoints[rng.randrange(len(spi.endpoints))]
            if spi.session_affinity and client_ip:
                self._affinity[key] = choice
            return choice

    def rules(self) -> list[str]:
        """Render the table as iptables-ish chains (what ``iptables-save``
        of the reference's KUBE-* chains encodes)."""
        out = ["-N KUBE-SERVICES"]
        with self._lock:
            for (ns, name, pname), spi in sorted(self._services.items()):
                svc_chain = f"KUBE-SVC-{ns}/{name}:{pname or spi.port}"
                if not spi.endpoints:
                    out.append(f"-A KUBE-SERVICES -d {spi.cluster_ip}/32 "
                               f"-p {spi.protocol.lower()} --dport {spi.port} "
                               f"-j REJECT")
                    continue
                out.append(f"-A KUBE-SERVICES -d {spi.cluster_ip}/32 "
                           f"-p {spi.protocol.lower()} --dport {spi.port} "
                           f"-j {svc_chain}")
                n = len(spi.endpoints)
                for i, ep in enumerate(spi.endpoints):
                    sep = f"KUBE-SEP-{ep}"
                    prob = f" -m statistic --mode random --probability {1/(n-i):.5f}" \
                        if i < n - 1 else ""
                    out.append(f"-A {svc_chain}{prob} -j {sep}")
                    out.append(f"-A {sep} -j DNAT --to-destination {ep}")
        return out

    def service_table(self) -> dict[tuple, ServicePortInfo]:
        with self._lock:
            return dict(self._services)
