"""kube-proxy — Service VIP dataplane (SURVEY §2.4)."""

from kubernetes_tpu.proxy.proxier import Proxier, ServicePortInfo

__all__ = ["Proxier", "ServicePortInfo"]
