"""kube-proxy IPVS backend — virtual-server table renderer.

Reference: ``pkg/proxy/ipvs/proxier.go``: instead of per-service iptables
chains, every service port becomes an IPVS VIRTUAL SERVER (``ipvsadm -A -t
vip:port -s rr``) with one REAL SERVER per endpoint (``-a -t vip:port -r
ip:port -m``), all service VIPs bound to a ``kube-ipvs0`` dummy interface,
and a handful of ipset-driven iptables rules for masquerade — O(1) rule
count in services where iptables is O(n).

Rendered as the ``ipvsadm-restore`` / ``ipvsadm -Sn`` save format plus the
dummy-interface address list; ``RestoredIpvsRules`` parses it back into a
DNAT decision table so render drift is caught by the same cross-backend
round-trip tests the iptables and nftables renderers run under.
"""

from __future__ import annotations

from kubernetes_tpu.proxy.proxier import Proxier


class IpvsProxier(Proxier):
    """Same watch/sync machinery and resolve() dataplane; the kernel-facing
    render is the IPVS virtual/real-server table."""

    def sync_ipvs_text(self) -> str:
        """``ipvsadm -Sn`` save format: -A adds a virtual server (with the
        scheduler, rr unless sessionAffinity pins via source hashing),
        -a adds each real server in masquerade mode (-m). Endpoint-less
        services keep their virtual server with NO real servers — the
        kernel then refuses connections, the IPVS analog of the REJECT
        rule."""
        with self._lock:
            services = sorted(self._services.items())
        lines: list[str] = []
        vips: set[str] = set()
        for (_ns, _name, _pname), spi in services:
            proto_flag = "-t" if spi.protocol.upper() == "TCP" else "-u"
            sched = "sh" if spi.session_affinity else "rr"
            vs = f"{spi.cluster_ip}:{spi.port}"
            vips.add(spi.cluster_ip)
            persist = " -p 10800" if spi.session_affinity else ""
            lines.append(f"-A {proto_flag} {vs} -s {sched}{persist}")
            for ep in spi.endpoints:
                lines.append(f"-a {proto_flag} {vs} -r {ep} -m -w 1")
            if spi.node_port:
                nvs = f"0.0.0.0:{spi.node_port}"
                lines.append(f"-A {proto_flag} {nvs} -s {sched}{persist}")
                for ep in spi.endpoints:
                    lines.append(f"-a {proto_flag} {nvs} -r {ep} -m -w 1")
        # the dummy interface carrying every service VIP (ipvs proxier
        # binds them to kube-ipvs0 so local traffic hits IPVS)
        dev = [f"ip addr add {vip}/32 dev kube-ipvs0"
               for vip in sorted(vips)]
        return "\n".join(dev + lines) + "\n"


class RestoredIpvsRules:
    """Parse the save-format text back into a DNAT decision table (the
    round-trip contract shared with RestoredRules / RestoredNftRules)."""

    def __init__(self, text: str):
        # (vip, port, proto) -> [real servers]; "0.0.0.0" rows = nodePorts
        self.servers: dict[tuple, list[str]] = {}
        for raw in text.splitlines():
            toks = raw.split()
            if not toks or toks[0] == "ip":
                continue
            proto = "tcp" if "-t" in toks else "udp"
            vs = toks[toks.index("-t" if "-t" in toks else "-u") + 1]
            vip, _, port = vs.rpartition(":")
            key = (vip, int(port), proto)
            if toks[0] == "-A":
                self.servers.setdefault(key, [])
            elif toks[0] == "-a" and "-r" in toks:
                self.servers.setdefault(key, []).append(
                    toks[toks.index("-r") + 1])

    def backends(self, vip: str, port: int, proto: str = "tcp") -> list[str]:
        key = (vip, int(port), proto)
        if key in self.servers:
            return list(self.servers[key])
        # nodePort dispatch: any local address matches the 0.0.0.0 row
        return list(self.servers.get(("0.0.0.0", int(port), proto), []))
