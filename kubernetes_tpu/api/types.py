"""Typed API objects — the subset of core/v1 the scheduler consumes.

Reference semantics: ``staging/src/k8s.io/api/core/v1/types.go`` (``Pod``,
``Node``, ``Affinity``, ``Toleration``, ``Taint``, ``TopologySpreadConstraint``).
Objects are plain dataclasses with ``from_dict``/``to_dict`` against the
Kubernetes JSON wire shape (camelCase), so YAML manifests written for the
reference parse unchanged. The store layer (etcd analog) persists raw dicts;
typed objects materialize at the informer boundary.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from kubernetes_tpu.api.resource import canonical

_uid_counter = itertools.count(1)


def _gen_uid() -> str:
    return f"uid-{next(_uid_counter)}"


def _parse_time(v) -> Optional[float]:
    """Accept epoch numbers or RFC3339 strings ("2024-06-01T10:00:00Z")."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    import datetime
    return datetime.datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_gen_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    resource_version: str = ""
    creation_timestamp: float = field(default_factory=time.time)
    owner_references: list[dict[str, Any]] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    finalizers: list[str] = field(default_factory=list)
    generation: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid") or _gen_uid(),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            resource_version=str(d.get("resourceVersion", "")),
            creation_timestamp=(_parse_time(d["creationTimestamp"])
                                if "creationTimestamp" in d else time.time()),
            owner_references=list(d.get("ownerReferences") or []),
            deletion_timestamp=_parse_time(d.get("deletionTimestamp")),
            finalizers=list(d.get("finalizers") or []),
            generation=int(d.get("generation", 0)),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "resourceVersion": self.resource_version,
            "creationTimestamp": self.creation_timestamp,
        }
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.owner_references:
            d["ownerReferences"] = list(self.owner_references)
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        if self.generation:
            d["generation"] = self.generation
        return d


# --------------------------------------------------------------------------
# Selectors / affinity
# --------------------------------------------------------------------------

# Node selector operators — reference: core/v1 NodeSelectorOperator.
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class Requirement:
    """A single match expression (node selector or label selector flavor)."""

    key: str
    operator: str
    values: list[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "Requirement":
        return cls(key=d["key"], operator=d["operator"], values=list(d.get("values") or []))

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"key": self.key, "operator": self.operator}
        if self.values:
            d["values"] = list(self.values)
        return d


@dataclass
class NodeSelectorTerm:
    match_expressions: list[Requirement] = field(default_factory=list)
    # matchFields: selectors over node fields (in practice only metadata.name —
    # the daemonset pin-to-node shape).
    match_fields: list[Requirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeSelectorTerm":
        return cls(
            match_expressions=[Requirement.from_dict(e) for e in d.get("matchExpressions") or []],
            match_fields=[Requirement.from_dict(e) for e in d.get("matchFields") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.match_expressions:
            d["matchExpressions"] = [e.to_dict() for e in self.match_expressions]
        if self.match_fields:
            d["matchFields"] = [e.to_dict() for e in self.match_fields]
        return d


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm

    @classmethod
    def from_dict(cls, d: dict) -> "PreferredSchedulingTerm":
        return cls(weight=int(d["weight"]), preference=NodeSelectorTerm.from_dict(d.get("preference") or {}))

    def to_dict(self) -> dict:
        return {"weight": self.weight, "preference": self.preference.to_dict()}


@dataclass
class NodeAffinity:
    # requiredDuringSchedulingIgnoredDuringExecution: OR of terms, each an AND of exprs.
    required: list[NodeSelectorTerm] = field(default_factory=list)
    preferred: list[PreferredSchedulingTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeAffinity":
        req = d.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        return cls(
            required=[NodeSelectorTerm.from_dict(t) for t in req.get("nodeSelectorTerms") or []],
            preferred=[PreferredSchedulingTerm.from_dict(t)
                       for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.required:
            d["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [t.to_dict() for t in self.required]}
        if self.preferred:
            d["preferredDuringSchedulingIgnoredDuringExecution"] = [t.to_dict() for t in self.preferred]
        return d


@dataclass
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions. None = match nothing
    (k8s: a nil selector matches no objects; an empty selector matches all)."""

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[Requirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["LabelSelector"]:
        if d is None:
            return None
        return cls(
            match_labels=dict(d.get("matchLabels") or {}),
            match_expressions=[Requirement.from_dict(e) for e in d.get("matchExpressions") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.match_labels:
            d["matchLabels"] = dict(self.match_labels)
        if self.match_expressions:
            d["matchExpressions"] = [e.to_dict() for e in self.match_expressions]
        return d

    def requirements(self) -> list[Requirement]:
        """Fold matchLabels into In-requirements (k8s LabelSelectorAsSelector)."""
        reqs = [Requirement(k, OP_IN, [v]) for k, v in sorted(self.match_labels.items())]
        return reqs + list(self.match_expressions)


@dataclass
class PodAffinityTerm:
    """core/v1 PodAffinityTerm. ``namespaces`` + ``namespaceSelector`` pick the
    target namespaces (both empty/nil = the term-owning pod's own namespace; a
    set namespaceSelector ORs with the explicit list; an EMPTY selector {}
    matches all namespaces). ``matchLabelKeys``/``mismatchLabelKeys`` merge the
    owning pod's label values into the selector as In/NotIn requirements at
    scheduling time (MatchLabelKeysInPodAffinity)."""

    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: list[str] = field(default_factory=list)  # empty = pod's own namespace
    namespace_selector: Optional[LabelSelector] = None
    match_label_keys: list[str] = field(default_factory=list)
    mismatch_label_keys: list[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "PodAffinityTerm":
        return cls(
            topology_key=d.get("topologyKey", ""),
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
            namespaces=list(d.get("namespaces") or []),
            namespace_selector=LabelSelector.from_dict(d.get("namespaceSelector")),
            match_label_keys=list(d.get("matchLabelKeys") or []),
            mismatch_label_keys=list(d.get("mismatchLabelKeys") or []),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"topologyKey": self.topology_key}
        if self.label_selector is not None:
            d["labelSelector"] = self.label_selector.to_dict()
        if self.namespaces:
            d["namespaces"] = list(self.namespaces)
        if self.namespace_selector is not None:
            d["namespaceSelector"] = self.namespace_selector.to_dict()
        if self.match_label_keys:
            d["matchLabelKeys"] = list(self.match_label_keys)
        if self.mismatch_label_keys:
            d["mismatchLabelKeys"] = list(self.mismatch_label_keys)
        return d


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm

    @classmethod
    def from_dict(cls, d: dict) -> "WeightedPodAffinityTerm":
        return cls(weight=int(d["weight"]), term=PodAffinityTerm.from_dict(d.get("podAffinityTerm") or {}))

    def to_dict(self) -> dict:
        return {"weight": self.weight, "podAffinityTerm": self.term.to_dict()}


@dataclass
class PodAffinity:
    required: list[PodAffinityTerm] = field(default_factory=list)
    preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "PodAffinity":
        return cls(
            required=[PodAffinityTerm.from_dict(t)
                      for t in d.get("requiredDuringSchedulingIgnoredDuringExecution") or []],
            preferred=[WeightedPodAffinityTerm.from_dict(t)
                       for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.required:
            d["requiredDuringSchedulingIgnoredDuringExecution"] = [t.to_dict() for t in self.required]
        if self.preferred:
            d["preferredDuringSchedulingIgnoredDuringExecution"] = [t.to_dict() for t in self.preferred]
        return d


def with_added_node_affinity(pod: "Pod", added) -> "Pod":
    """Pod with a profile-level NodeAffinity folded in (NodeAffinityArgs.
    addedAffinity; reference ``pkg/scheduler/framework/plugins/nodeaffinity/
    node_affinity.go``): the pod must satisfy BOTH its own affinity and the
    added one. Required selectors are OR-of-terms, so AND of two selectors
    is the cross product of their term lists (each merged term carries both
    sides' expressions); preferred terms simply append. ``added``: a
    NodeAffinity or its wire dict. Returns a new Pod sharing every
    untouched subtree."""
    import dataclasses
    add = (added if isinstance(added, NodeAffinity)
           else NodeAffinity.from_dict(added))
    aff = pod.spec.affinity
    own = aff.node_affinity if aff else None
    if own is None or not own.required:
        req = list(add.required)
    elif not add.required:
        req = list(own.required)
    else:
        req = [NodeSelectorTerm(
            match_expressions=a.match_expressions + b.match_expressions,
            match_fields=a.match_fields + b.match_fields)
            for a in own.required for b in add.required]
    merged = NodeAffinity(
        required=req,
        preferred=(own.preferred if own else []) + list(add.preferred))
    new_aff = (dataclasses.replace(aff, node_affinity=merged) if aff
               else Affinity(node_affinity=merged))
    return dataclasses.replace(
        pod, spec=dataclasses.replace(pod.spec, affinity=new_aff))


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["Affinity"]:
        if not d:
            return None
        return cls(
            node_affinity=NodeAffinity.from_dict(d["nodeAffinity"]) if d.get("nodeAffinity") else None,
            pod_affinity=PodAffinity.from_dict(d["podAffinity"]) if d.get("podAffinity") else None,
            pod_anti_affinity=PodAffinity.from_dict(d["podAntiAffinity"]) if d.get("podAntiAffinity") else None,
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.node_affinity is not None:
            d["nodeAffinity"] = self.node_affinity.to_dict()
        if self.pod_affinity is not None:
            d["podAffinity"] = self.pod_affinity.to_dict()
        if self.pod_anti_affinity is not None:
            d["podAntiAffinity"] = self.pod_anti_affinity.to_dict()
        return d


# --------------------------------------------------------------------------
# Taints / tolerations
# --------------------------------------------------------------------------

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"

TOL_OP_EXISTS = "Exists"
TOL_OP_EQUAL = "Equal"


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE

    @classmethod
    def from_dict(cls, d: dict) -> "Taint":
        return cls(key=d["key"], value=d.get("value", ""), effect=d.get("effect", EFFECT_NO_SCHEDULE))

    def to_dict(self) -> dict:
        return {"key": self.key, "value": self.value, "effect": self.effect}


@dataclass
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = TOL_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty = all effects
    toleration_seconds: Optional[int] = None

    @classmethod
    def from_dict(cls, d: dict) -> "Toleration":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", TOL_OP_EQUAL),
            value=d.get("value", ""),
            effect=d.get("effect", ""),
            toleration_seconds=d.get("tolerationSeconds"),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.key:
            d["key"] = self.key
        d["operator"] = self.operator
        if self.value:
            d["value"] = self.value
        if self.effect:
            d["effect"] = self.effect
        if self.toleration_seconds is not None:
            d["tolerationSeconds"] = self.toleration_seconds
        return d

    def tolerates(self, taint: Taint) -> bool:
        """Reference: staging/src/k8s.io/api/core/v1/toleration.go (ToleratesTaint)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOL_OP_EXISTS:
            return True
        return self.value == taint.value


# --------------------------------------------------------------------------
# Topology spread
# --------------------------------------------------------------------------

UNSATISFIABLE_DO_NOT_SCHEDULE = "DoNotSchedule"
UNSATISFIABLE_SCHEDULE_ANYWAY = "ScheduleAnyway"


NODE_INCLUSION_HONOR = "Honor"
NODE_INCLUSION_IGNORE = "Ignore"


@dataclass
class TopologySpreadConstraint:
    """core/v1 TopologySpreadConstraint. ``min_domains`` (DoNotSchedule only):
    if fewer eligible domains exist, the global minimum is treated as 0.
    ``node_affinity_policy``/``node_taints_policy`` control whether nodes
    failing the pod's nodeSelector/nodeAffinity (default: Honor = excluded)
    or carrying untolerated taints (default: Ignore = included) count when
    computing skew. ``match_label_keys`` merge the pod's own label values
    into the selector as In requirements."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: str = UNSATISFIABLE_DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    node_affinity_policy: str = NODE_INCLUSION_HONOR
    node_taints_policy: str = NODE_INCLUSION_IGNORE
    match_label_keys: list[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "TopologySpreadConstraint":
        md = d.get("minDomains")
        return cls(
            max_skew=int(d.get("maxSkew", 1)),
            topology_key=d.get("topologyKey", ""),
            when_unsatisfiable=d.get("whenUnsatisfiable", UNSATISFIABLE_DO_NOT_SCHEDULE),
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
            min_domains=int(md) if md is not None else None,
            node_affinity_policy=d.get("nodeAffinityPolicy", NODE_INCLUSION_HONOR),
            node_taints_policy=d.get("nodeTaintsPolicy", NODE_INCLUSION_IGNORE),
            match_label_keys=list(d.get("matchLabelKeys") or []),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "maxSkew": self.max_skew,
            "topologyKey": self.topology_key,
            "whenUnsatisfiable": self.when_unsatisfiable,
        }
        if self.label_selector is not None:
            d["labelSelector"] = self.label_selector.to_dict()
        if self.min_domains is not None:
            d["minDomains"] = self.min_domains
        if self.node_affinity_policy != NODE_INCLUSION_HONOR:
            d["nodeAffinityPolicy"] = self.node_affinity_policy
        if self.node_taints_policy != NODE_INCLUSION_IGNORE:
            d["nodeTaintsPolicy"] = self.node_taints_policy
        if self.match_label_keys:
            d["matchLabelKeys"] = list(self.match_label_keys)
        return d


# --------------------------------------------------------------------------
# Pod
# --------------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "ContainerPort":
        return cls(
            container_port=int(d.get("containerPort", 0)),
            host_port=int(d.get("hostPort", 0)),
            protocol=d.get("protocol", "TCP"),
            host_ip=d.get("hostIP", ""),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"containerPort": self.container_port, "protocol": self.protocol}
        if self.host_port:
            d["hostPort"] = self.host_port
        if self.host_ip:
            d["hostIP"] = self.host_ip
        return d


@dataclass
class Container:
    name: str = "c"
    image: str = ""
    requests: dict[str, Any] = field(default_factory=dict)  # resource -> quantity
    limits: dict[str, Any] = field(default_factory=dict)
    ports: list[ContainerPort] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "Container":
        res = d.get("resources") or {}
        return cls(
            name=d.get("name", "c"),
            image=d.get("image", ""),
            requests=dict(res.get("requests") or {}),
            limits=dict(res.get("limits") or {}),
            ports=[ContainerPort.from_dict(p) for p in d.get("ports") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"name": self.name}
        if self.image:
            d["image"] = self.image
        res: dict[str, Any] = {}
        if self.requests:
            res["requests"] = dict(self.requests)
        if self.limits:
            res["limits"] = dict(self.limits)
        if res:
            d["resources"] = res
        if self.ports:
            d["ports"] = [p.to_dict() for p in self.ports]
        return d


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    topology_spread_constraints: list[TopologySpreadConstraint] = field(default_factory=list)
    scheduling_gates: list[str] = field(default_factory=list)
    overhead: dict[str, Any] = field(default_factory=dict)
    restart_policy: str = "Always"
    volumes: list[dict[str, Any]] = field(default_factory=list)
    # DRA (resource.k8s.io): [{"name", "resourceClaimName" |
    # "resourceClaimTemplateName"}] — pod-level device claims
    resource_claims: list[dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "PodSpec":
        return cls(
            node_name=d.get("nodeName", ""),
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            node_selector=dict(d.get("nodeSelector") or {}),
            affinity=Affinity.from_dict(d.get("affinity")),
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers") or []],
            priority=int(d.get("priority", 0) or 0),
            priority_class_name=d.get("priorityClassName", ""),
            topology_spread_constraints=[TopologySpreadConstraint.from_dict(t)
                                         for t in d.get("topologySpreadConstraints") or []],
            scheduling_gates=[g.get("name", "") if isinstance(g, dict) else str(g)
                              for g in d.get("schedulingGates") or []],
            overhead=dict(d.get("overhead") or {}),
            restart_policy=d.get("restartPolicy", "Always"),
            volumes=list(d.get("volumes") or []),
            resource_claims=list(d.get("resourceClaims") or []),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"schedulerName": self.scheduler_name,
                             "restartPolicy": self.restart_policy}
        if self.node_name:
            d["nodeName"] = self.node_name
        if self.node_selector:
            d["nodeSelector"] = dict(self.node_selector)
        if self.affinity is not None:
            d["affinity"] = self.affinity.to_dict()
        if self.tolerations:
            d["tolerations"] = [t.to_dict() for t in self.tolerations]
        d["containers"] = [c.to_dict() for c in self.containers]
        if self.init_containers:
            d["initContainers"] = [c.to_dict() for c in self.init_containers]
        if self.priority:
            d["priority"] = self.priority
        if self.priority_class_name:
            d["priorityClassName"] = self.priority_class_name
        if self.topology_spread_constraints:
            d["topologySpreadConstraints"] = [t.to_dict() for t in self.topology_spread_constraints]
        if self.scheduling_gates:
            d["schedulingGates"] = [{"name": g} for g in self.scheduling_gates]
        if self.overhead:
            d["overhead"] = dict(self.overhead)
        if self.volumes:
            d["volumes"] = list(self.volumes)
        if self.resource_claims:
            d["resourceClaims"] = list(self.resource_claims)
        return d


@dataclass
class PodStatus:
    phase: str = "Pending"
    nominated_node_name: str = ""
    conditions: list[dict[str, Any]] = field(default_factory=list)
    start_time: Optional[float] = None
    pod_ip: str = ""
    host_ip: str = ""

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodStatus":
        d = d or {}
        return cls(
            phase=d.get("phase", "Pending"),
            nominated_node_name=d.get("nominatedNodeName", ""),
            conditions=list(d.get("conditions") or []),
            start_time=d.get("startTime"),
            pod_ip=d.get("podIP", ""),
            host_ip=d.get("hostIP", ""),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"phase": self.phase}
        if self.nominated_node_name:
            d["nominatedNodeName"] = self.nominated_node_name
        if self.conditions:
            d["conditions"] = list(self.conditions)
        if self.start_time is not None:
            d["startTime"] = self.start_time
        if self.pod_ip:
            d["podIP"] = self.pod_ip
        if self.host_ip:
            d["hostIP"] = self.host_ip
        return d

    def is_ready(self) -> bool:
        """PodReady condition True (pkg/api/v1/pod/util.go IsPodReady)."""
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in self.conditions)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @classmethod
    def from_dict(cls, d: dict) -> "Pod":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
            status=PodStatus.from_dict(d.get("status")),
        )

    def to_dict(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def resource_requests(self) -> dict[str, int]:
        """Effective scheduling requests in canonical units.

        Reference: pkg/api/v1/resource/helpers.go (PodRequests) —
        max(sum(containers), max(initContainers)) + overhead, plus the
        implicit "pods" resource (each pod consumes 1 slot).

        Memoized per instance: quantity-string parsing dominated fleet-scale
        host paths (every encode/oracle/preemption pass re-parsed every
        pod). Requests are spec-immutable upstream, and every mutation path
        here builds a fresh Pod (informers, dataclasses.replace), so the
        cache lives exactly as long as it is valid. Callers treat the
        result as read-only.
        """
        cached = self.__dict__.get("_requests_cache")
        if cached is not None:
            return cached
        total: dict[str, int] = {}
        for c in self.containers_all(init=False):
            for r, q in c.requests.items():
                total[r] = total.get(r, 0) + canonical(r, q)
        for c in self.spec.init_containers:
            for r, q in c.requests.items():
                total[r] = max(total.get(r, 0), canonical(r, q))
        for r, q in self.spec.overhead.items():
            total[r] = total.get(r, 0) + canonical(r, q)
        total["pods"] = 1
        self.__dict__["_requests_cache"] = total
        return total

    def containers_all(self, init: bool = True) -> list[Container]:
        return (self.spec.init_containers if init else []) + self.spec.containers

    def pvc_names(self) -> list[str]:
        """claimNames of persistentVolumeClaim volumes, in spec order."""
        return [v["persistentVolumeClaim"]["claimName"]
                for v in self.spec.volumes
                if isinstance(v.get("persistentVolumeClaim"), dict)
                and v["persistentVolumeClaim"].get("claimName")]

    def host_ports(self) -> list[tuple[str, str, int]]:
        """(hostIP, protocol, hostPort) triples with hostPort != 0."""
        out = []
        for c in self.spec.containers:
            for p in c.ports:
                if p.host_port:
                    out.append((p.host_ip or "0.0.0.0", p.protocol or "TCP", p.host_port))
        return out


# --------------------------------------------------------------------------
# Node
# --------------------------------------------------------------------------


@dataclass
class ContainerImage:
    names: list[str] = field(default_factory=list)
    size_bytes: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "ContainerImage":
        return cls(names=list(d.get("names") or []), size_bytes=int(d.get("sizeBytes", 0)))

    def to_dict(self) -> dict:
        return {"names": list(self.names), "sizeBytes": self.size_bytes}


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NodeSpec":
        d = d or {}
        return cls(
            unschedulable=bool(d.get("unschedulable", False)),
            taints=[Taint.from_dict(t) for t in d.get("taints") or []],
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.unschedulable:
            d["unschedulable"] = True
        if self.taints:
            d["taints"] = [t.to_dict() for t in self.taints]
        return d


@dataclass
class NodeStatus:
    allocatable: dict[str, Any] = field(default_factory=dict)
    capacity: dict[str, Any] = field(default_factory=dict)
    images: list[ContainerImage] = field(default_factory=list)
    conditions: list[dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NodeStatus":
        d = d or {}
        return cls(
            allocatable=dict(d.get("allocatable") or {}),
            capacity=dict(d.get("capacity") or {}),
            images=[ContainerImage.from_dict(i) for i in d.get("images") or []],
            conditions=list(d.get("conditions") or []),
        )

    def to_dict(self) -> dict:
        d: dict[str, Any] = {}
        if self.allocatable:
            d["allocatable"] = dict(self.allocatable)
        if self.capacity:
            d["capacity"] = dict(self.capacity)
        if self.images:
            d["images"] = [i.to_dict() for i in self.images]
        if self.conditions:
            d["conditions"] = list(self.conditions)
        return d


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=NodeSpec.from_dict(d.get("spec")),
            status=NodeStatus.from_dict(d.get("status")),
        )

    def to_dict(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @property
    def name(self) -> str:
        return self.metadata.name

    def allocatable_canonical(self) -> dict[str, int]:
        return {r: canonical(r, q) for r, q in self.status.allocatable.items()}


def deep_copy(obj):
    """Structural copy of a dataclass tree (runtime.Object.DeepCopyObject analog)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return type(obj)(**{f.name: deep_copy(getattr(obj, f.name)) for f in dataclasses.fields(obj)})
    if isinstance(obj, dict):
        return {k: deep_copy(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [deep_copy(v) for v in obj]
    return obj
