"""Resource quantity parsing and arithmetic.

Reference semantics: ``staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go``
(type ``Quantity``) — decimal SI suffixes (k, M, G, T, P, E), binary suffixes
(Ki, Mi, Gi, Ti, Pi, Ei), the milli suffix (m), and scientific notation.

We canonicalize eagerly to integers at parse time (the tensor path wants flat
numerics, not lazy-formatted decimals): cpu-like resources are held in
millivalue units, byte-like resources in bytes. ``parse_quantity`` returns a
float of the *base* value; callers scale cpu by 1000 via ``to_milli``.
"""

from __future__ import annotations

import re
from decimal import Decimal

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {
    "n": Decimal("1e-9"), "u": Decimal("1e-6"), "m": Decimal("1e-3"), "": Decimal(1),
    "k": Decimal(10) ** 3, "M": Decimal(10) ** 6, "G": Decimal(10) ** 9,
    "T": Decimal(10) ** 12, "P": Decimal(10) ** 15, "E": Decimal(10) ** 18,
}

_QUANTITY_RE = re.compile(
    r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$"
)


def parse_decimal(value) -> Decimal:
    """Parse a Kubernetes-style quantity string (or number) to an exact Decimal.

    Exactness matters: the reference holds quantities int64-scaled; a float
    round-trip loses precision above 2^53 (e.g. "8Ei"), which would make
    distinct allocatable values compare equal.
    """
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity: {value!r}")
    if isinstance(value, (int, float)):
        return Decimal(str(value))
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num, suffix = m.group(1), m.group(2) or ""
    if suffix in _BINARY:
        return Decimal(num) * _BINARY[suffix]
    return Decimal(num) * _DECIMAL[suffix]


def parse_quantity(value) -> float:
    """Parse a Kubernetes-style quantity string (or number) to a float base value.

    >>> parse_quantity("100m")
    0.1
    >>> parse_quantity("1Gi")
    1073741824.0
    >>> parse_quantity("2")
    2.0
    """
    return float(parse_decimal(value))


def to_milli(value) -> int:
    """Quantity -> integer millivalue (cpu canonical unit). Exact for integers."""
    return int((parse_decimal(value) * 1000).to_integral_value(rounding="ROUND_HALF_EVEN"))


def to_bytes(value) -> int:
    """Quantity -> integer bytes (memory/storage canonical unit). Exact for integers."""
    return int(parse_decimal(value).to_integral_value(rounding="ROUND_HALF_EVEN"))


# Resource names treated as cpu-like (milli-canonical); everything else is
# taken at face value (bytes for memory/storage, counts for pods/extended).
MILLI_RESOURCES = frozenset({"cpu"})


def canonical(resource: str, value) -> int:
    """Canonical integer amount for ``resource`` (milli for cpu, base otherwise)."""
    if resource in MILLI_RESOURCES:
        return to_milli(value)
    return to_bytes(value)
