"""Multi-version API machinery — the runtime.Scheme analog.

Reference: ``staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go``: every
kind converts through a HUB version (upstream's __internal); each served
version registers to_hub/from_hub functions, and the apiserver converts
request bodies in and response objects out, so one stored shape serves many
wire shapes. CRDs get the same via their conversion webhooks — here the
scheme is the single registry for both.

Built-in registration mirrors the reference's best-known conversion pair:
``autoscaling/v1`` HorizontalPodAutoscaler (targetCPUUtilizationPercentage)
<-> the stored ``autoscaling/v2`` shape (metrics list) —
``pkg/apis/autoscaling/v1/conversion.go``.
"""

from __future__ import annotations

from typing import Callable, Optional


class Scheme:
    """(kind, version) -> (to_hub, from_hub). The hub version needs no
    registration: it is what the store holds."""

    def __init__(self):
        self._conv: dict[tuple[str, str], tuple[Callable, Callable]] = {}
        self._hub: dict[str, str] = {}

    def register(self, kind: str, version: str, to_hub: Callable,
                 from_hub: Callable, hub_version: str = "v1") -> None:
        self._conv[(kind, version)] = (to_hub, from_hub)
        self._hub[kind] = hub_version

    def converter(self, kind: str, version: str
                  ) -> Optional[tuple[Callable, Callable]]:
        return self._conv.get((kind, version))

    def served_versions(self, kind: str) -> list[str]:
        out = [self._hub.get(kind, "v1")]
        out += [v for (k, v) in self._conv if k == kind]
        return out


# --------------------------------------------- autoscaling/v1 <-> v2 (hub)

# non-cpu metrics ride this annotation through the lossy v1 view, exactly
# like upstream's conversion (pkg/apis/autoscaling/v1/conversion.go) — a
# v1 read-modify-write must not silently delete a v2 memory metric
METRICS_ANNOTATION = "autoscaling.alpha.kubernetes.io/metrics"


def _hpa_v1_to_v2(obj: dict) -> dict:
    """autoscaling/v1 wire shape -> the stored v2 shape: the single
    targetCPUUtilizationPercentage becomes a cpu Utilization metric, and
    metrics preserved through the round-trip annotation are restored."""
    import json as _json
    out = dict(obj)
    spec = dict(out.get("spec") or {})
    pct = spec.pop("targetCPUUtilizationPercentage", None)
    metrics = list(spec.get("metrics") or [])
    md = dict(out.get("metadata") or {})
    ann = dict(md.get("annotations") or {})
    stashed = ann.pop(METRICS_ANNOTATION, None)
    if stashed:
        try:
            metrics += [m for m in _json.loads(stashed)
                        if isinstance(m, dict)]
        except ValueError:
            pass
        md["annotations"] = ann
        if not ann:
            md.pop("annotations", None)
        out["metadata"] = md
    if pct is not None:
        metrics.insert(0, {
            "type": "Resource",
            "resource": {"name": "cpu",
                         "target": {"type": "Utilization",
                                    "averageUtilization": pct}},
        })
    if metrics:
        spec["metrics"] = metrics
    out["spec"] = spec
    out["apiVersion"] = "autoscaling/v2"
    if "status" in out:
        status = dict(out["status"])
        pct_s = status.pop("currentCPUUtilizationPercentage", None)
        if pct_s is not None and not status.get("currentMetrics"):
            status["currentMetrics"] = [{
                "type": "Resource",
                "resource": {"name": "cpu",
                             "current": {"averageUtilization": pct_s}},
            }]
        # installed unconditionally: the v1-only scalar must never reach
        # the stored hub shape
        out["status"] = status
    return out


def _hpa_v2_to_v1(obj: dict) -> dict:
    """Stored v2 -> the v1 wire shape; the cpu Utilization metric narrows
    to targetCPUUtilizationPercentage, every OTHER metric is stashed in the
    round-trip annotation so a v1 PUT of this object can't destroy it."""
    import json as _json
    out = dict(obj)
    spec = dict(out.get("spec") or {})
    metrics = spec.pop("metrics", None) or []
    others = []
    cpu_seen = False
    for m in metrics:
        res = m.get("resource") or {}
        if (not cpu_seen and m.get("type") == "Resource"
                and res.get("name") == "cpu"):
            cpu_seen = True
            pct = (res.get("target") or {}).get("averageUtilization")
            if pct is not None:
                spec["targetCPUUtilizationPercentage"] = pct
                continue
        others.append(m)
    if others:
        md = dict(out.get("metadata") or {})
        ann = dict(md.get("annotations") or {})
        ann[METRICS_ANNOTATION] = _json.dumps(others)
        md["annotations"] = ann
        out["metadata"] = md
    out["spec"] = spec
    out["apiVersion"] = "autoscaling/v1"
    if "status" in out:
        status = dict(out["status"])
        for m in status.pop("currentMetrics", None) or []:
            res = m.get("resource") or {}
            if m.get("type") == "Resource" and res.get("name") == "cpu":
                pct = (res.get("current") or {}).get("averageUtilization")
                if pct is not None:
                    status["currentCPUUtilizationPercentage"] = pct
                break
        # the narrowed status is installed even when no cpu metric exists:
        # currentMetrics is a v2-only field and must never leak into v1
        out["status"] = status
    return out


def default_scheme() -> Scheme:
    s = Scheme()
    s.register("HorizontalPodAutoscaler", "v1",
               to_hub=_hpa_v1_to_v2, from_hub=_hpa_v2_to_v1,
               hub_version="v2")
    return s
